"""Transformer language model — the long-context flagship family.

No reference counterpart (SURVEY.md §5.7: the reference predates
transformers; its sequence story ends at ``Recurrent``). This family is the
showcase for the framework's TPU-native extensions working together:

* :class:`MultiHeadAttention` — Pallas flash kernels locally, ring/Ulysses
  sequence parallelism across chips (``sequence_parallel=``, ``sp_axis=``);
* :class:`Remat` — gradient checkpointing per block for deep stacks;
* mixed precision (``Optimizer.set_compute_dtype``) and the full
  DP/TP/PP/EP planes of ``bigdl_tpu.parallel`` for scale-out.

Built entirely from existing framework modules — the point is that a
transformer is just another ``Sequential`` here.
"""

from __future__ import annotations

from typing import Optional

from bigdl_tpu.nn.attention import MultiHeadAttention
from bigdl_tpu.nn.containers import Container, Remat, Sequential
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import AbstractModule, TensorModule


class LayerNorm(TensorModule):
    """Per-token layer normalization (transformer-standard; the reference's
    BatchNormalization normalizes over the batch instead)."""

    def __init__(self, hidden_size: int, eps: float = 1e-5) -> None:
        super().__init__()
        self.hidden_size = hidden_size
        self.eps = eps

    def init_params(self, rng):
        import jax.numpy as jnp

        return {"weight": jnp.ones((self.hidden_size,), jnp.float32),
                "bias": jnp.zeros((self.hidden_size,), jnp.float32)}

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax.numpy as jnp

        xf = input.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean((xf - mean) ** 2, axis=-1, keepdims=True)
        out = (xf - mean) / jnp.sqrt(var + self.eps)
        out = out * params["weight"] + params["bias"]
        return out.astype(input.dtype), state


class PositionEmbedding(TensorModule):
    """Learned absolute positions added to token embeddings (module-level so
    the structured serializer can resolve it on load). ``sp_axis`` makes it
    shard-aware: inside a shard_map over that axis each chip holds a
    T_local sequence slice, and positions offset by ``axis_index * T_local``
    so they stay GLOBAL (matching ring attention's causal offsets)."""

    def __init__(self, max_len: int, hidden_size: int,
                 sp_axis: Optional[str] = None) -> None:
        super().__init__()
        self.max_len = max_len
        self.hidden_size = hidden_size
        self.sp_axis = sp_axis

    def init_params(self, rng):
        import jax

        return {"pos": 0.02 * jax.random.normal(
            rng, (self.max_len, self.hidden_size))}

    def apply(self, params, input, state=None, training=False, rng=None):
        T = input.shape[1]
        if self.sp_axis is None:
            return input + params["pos"][:T], state
        import jax.lax as lax

        n_shards = lax.psum(1, self.sp_axis)  # static axis size
        if n_shards * T > self.max_len:
            raise ValueError(
                f"global sequence {n_shards * T} exceeds max_len "
                f"{self.max_len} (dynamic_slice would silently clamp)")
        start = lax.axis_index(self.sp_axis) * T
        pos = lax.dynamic_slice_in_dim(params["pos"], start, T)
        return input + pos, state


class TransformerBlock(Container):
    """Pre-norm block: x + MHA(LN(x)); x + MLP(LN(x)). A ``Container`` so
    the child-key/init plumbing is the tested shared scheme."""

    def __init__(self, hidden_size: int, n_heads: int, mlp_ratio: int = 4,
                 causal: bool = True, sequence_parallel: Optional[str] = None,
                 sp_axis: str = "seq", use_flash: str = "auto",
                 flash_block: Optional[int] = None) -> None:
        super().__init__()
        self.ln1 = LayerNorm(hidden_size)
        self.attn = MultiHeadAttention(
            hidden_size, n_heads, causal=causal,
            sequence_parallel=sequence_parallel, sp_axis=sp_axis,
            use_flash=use_flash, flash_block=flash_block)
        self.ln2 = LayerNorm(hidden_size)
        self.fc1 = Linear(hidden_size, mlp_ratio * hidden_size)
        self.fc2 = Linear(mlp_ratio * hidden_size, hidden_size)
        for m in (self.ln1, self.attn, self.ln2, self.fc1, self.fc2):
            self.add(m)

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax

        def run(i, x, r=None):
            m = self.modules[i]
            out, _ = m.apply(params[self._child_key(i)], x, {},
                             training=training, rng=r)
            return out

        x = input + run(1, run(0, input), rng)        # attn(ln1(x))
        h = jax.nn.gelu(run(3, run(2, x)))            # fc1(ln2(x))
        return x + run(4, h), state


class ScanBlocks(Container):
    """``n_layers`` copies of one :class:`TransformerBlock` applied via
    ``lax.scan`` over a stacked-params pytree (every leaf gains a leading
    ``(n_layers,)`` axis).

    The alternative lowering to ``n_layers`` unrolled blocks: ONE compiled
    block program is iterated instead of ``n_layers`` inlined copies, so
    compile time is O(1) in depth and the weight working set cycles
    through the same HBM region each iteration. Step-time impact at LM
    scale is measured in benchmarks/llm_mfu_bench.py (``--layer_scan``) —
    scan forbids cross-layer fusion, so this trades peak step time for
    compile time; see PERF_ANALYSIS_r5.md for the numbers.

    Holds exactly one child (the template block); ``init_params`` stacks
    per-layer inits so each layer starts at a DIFFERENT draw, exactly like
    the unrolled construction."""

    def __init__(self, block: TransformerBlock, n_layers: int) -> None:
        super().__init__()
        if n_layers <= 0:
            raise ValueError(f"n_layers must be positive, got {n_layers}")
        self.n_layers = int(n_layers)
        self.add(block)

    def init_params(self, rng):
        import jax

        block = self.modules[0]
        keys = jax.random.split(rng, self.n_layers)
        per_layer = [block.init_params(k) for k in keys]
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jax.numpy.stack(leaves), *per_layer)
        return {self._child_key(0): stacked}

    def unstacked_params(self, params):
        """Per-layer list view of the stacked params (decode-step /
        export interop — the inverse of init_params' stacking)."""
        import jax

        stacked = params[self._child_key(0)]
        return [jax.tree_util.tree_map(lambda a: a[i], stacked)
                for i in range(self.n_layers)]

    def apply(self, params, input, state=None, training=False, rng=None):
        from jax import lax

        block = self.modules[0]
        stacked = params[self._child_key(0)]

        def body(x, layer_params):
            out, _ = block.apply(layer_params, x, {}, training=training,
                                 rng=None)
            return out, None

        out, _ = lax.scan(body, input, stacked)
        return out, state


def TransformerLM(vocab_size: int, hidden_size: int = 256, n_heads: int = 8,
                  n_layers: int = 4, max_len: int = 1024,
                  mlp_ratio: int = 4, causal: bool = True,
                  remat: bool = False,
                  sequence_parallel: Optional[str] = None,
                  sp_axis: str = "seq",
                  output: str = "logprobs",
                  embed_grad_matmul: bool = False,
                  use_flash: str = "auto",
                  flash_block: Optional[int] = None,
                  layer_scan: bool = False) -> Sequential:
    """GPT-style decoder LM over 1-based token ids ``(B, T)`` →
    per-position log-probs ``(B, T, vocab)``.

    ``remat=True`` checkpoints each block (long-context memory);
    ``sequence_parallel="ring"|"ulysses"`` shards the sequence axis across
    the ``sp_axis`` mesh dimension inside a ``shard_map``.

    ``output="logits"`` drops the final LogSoftMax — pair it with
    :class:`bigdl_tpu.nn.criterion_more.MaskedSoftmaxCECriterion`, which
    fuses the softmax into the loss instead of materializing the
    ``(B, T, vocab)`` log-prob tensor (identical math, gigabytes less HBM
    traffic at LM scale — see benchmarks/llm_mfu_bench.py).

    ``embed_grad_matmul`` routes the token-embedding gradient through a
    one-hot MXU matmul instead of the scatter-add lowering — measured
    slightly SLOWER at GPT-2-small scale on v5e (llm_mfu_bench), so off
    by default; kept as a knob for scatter-bound profiles.

    ``use_flash`` routes through every block to the attention layers'
    constructors (so their own validation applies — e.g. striped_ring
    refuses ``"never"``). ``"auto"`` (default) = flash on TPU at every
    length: IN-MODEL, flash wins even at T=2048 (152.4 vs 261.7 ms/step
    on the 137M config — the dense path's T×T score/softmax
    materialization is pure HBM traffic the rest of the step is already
    starved by), although the STANDALONE kernel microbench
    (flash_bench.py) only breaks even near 8k. Measured in
    llm_mfu_bench.py; ``"never"`` forces the dense path.

    ``flash_block`` overrides the flash kernel's VMEM tile length
    (multiple of 128; None = auto, measured optimal — the in-model sweep
    lives in llm_mfu_bench.py ``--sweep_block``).

    ``layer_scan=True`` lowers the block stack as ONE ``lax.scan`` over
    stacked per-layer params (:class:`ScanBlocks`) instead of
    ``n_layers`` unrolled copies — O(1) compile time in depth; step-time
    tradeoff measured in PERF_ANALYSIS_r5.md.
    """
    if output not in ("logprobs", "logits"):
        raise ValueError(f"unknown output {output!r}")
    if use_flash not in ("auto", "always", "never"):
        raise ValueError(f"unknown use_flash {use_flash!r}")
    from bigdl_tpu.nn.activations import LogSoftMax
    from bigdl_tpu.nn.misc import LookupTable

    model = Sequential()
    model.add(LookupTable(vocab_size, hidden_size,
                          grad_via_matmul=embed_grad_matmul))
    model.add(PositionEmbedding(
        max_len, hidden_size,
        sp_axis=sp_axis if sequence_parallel else None))
    def make_block():
        return TransformerBlock(hidden_size, n_heads, mlp_ratio, causal,
                                sequence_parallel, sp_axis,
                                use_flash=use_flash,
                                flash_block=flash_block)

    if layer_scan:
        block = make_block()
        model.add(ScanBlocks(Remat(block) if remat else block, n_layers))
    else:
        for _ in range(n_layers):
            block = make_block()
            model.add(Remat(block) if remat else block)
    model.add(LayerNorm(hidden_size))
    model.add(Linear(hidden_size, vocab_size))
    if output == "logprobs":
        model.add(LogSoftMax())
    return model


def train_main(argv=None):
    """Train a small TransformerLM on a synthetic (or ``-f`` text) corpus —
    mirrors the rnn/PTB main but on the transformer family."""
    import numpy as np

    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import ClassNLLCriterion, TimeDistributedCriterion
    from bigdl_tpu.optim.optim_method import Adam

    p = train_parser("Transformer language model", batch_size=16,
                     learning_rate=3e-3, max_epoch=2)
    p.add_argument("--vocab", type=int, default=100)
    p.add_argument("--seqLen", type=int, default=32)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--remat", action="store_true")
    args = p.parse_args(argv)

    rng = np.random.default_rng(0)
    samples = []
    if args.folder:
        from bigdl_tpu.dataset.text import (
            Dictionary, SequenceWindower, simple_tokenize,
        )

        with open(args.folder) as f:
            tokens = simple_tokenize(f.read())
        d = Dictionary([tokens])
        vocab = d.vocab_size()
        ids = [d.get_index(t) + 1 for t in tokens]
        for ls in SequenceWindower(args.seqLen)(iter([ids])):
            samples.append(Sample(np.asarray(ls.data, np.float32),
                                  np.asarray(ls.labels, np.float32)))
        if not samples:
            raise ValueError(f"{args.folder}: corpus shorter than --seqLen")
    else:
        vocab = args.vocab
        for _ in range(args.synthetic):
            toks = [int(rng.integers(1, vocab + 1))]
            for _ in range(args.seqLen):
                toks.append(1 + (toks[-1] + int(rng.integers(0, 3))) % vocab)
            arr = np.asarray(toks, np.float32)
            samples.append(Sample(arr[:-1], arr[1:]))

    model = TransformerLM(vocab, hidden_size=args.hidden, n_heads=args.heads,
                          n_layers=args.layers, max_len=args.seqLen,
                          remat=args.remat)
    crit = TimeDistributedCriterion(ClassNLLCriterion())
    return run_training(model, samples, crit, args,
                        optim_method=Adam(learning_rate=args.learningRate))


if __name__ == "__main__":
    train_main()


def _cast_keep_scales(tree, compute_dtype):
    """Cast float leaves to the serving dtype; quantized ``w_scale``
    leaves stay fp32 so the dequant multiply keeps full scale precision
    (int8 ``weight_q`` is not floating and passes through untouched).
    THE one copy of the serving-cast rule — used by both
    :func:`serving_params` and :func:`make_decode_step`."""
    if compute_dtype is None:
        return tree
    from bigdl_tpu.optim.train_step import cast_floats

    if isinstance(tree, dict):
        return {k: (v if k == "w_scale"
                    else _cast_keep_scales(v, compute_dtype))
                for k, v in tree.items()}
    return cast_floats(tree, compute_dtype)


def serving_params(model: Sequential, compute_dtype=None):
    """The model's params pre-cast for serving (floats to
    ``compute_dtype``, quantized ``w_scale`` leaves kept fp32) — put this
    on device once and pass it to the decode step as the runtime params
    argument, so weights are resident buffers in the serving dtype rather
    than program constants."""
    model._ensure_params()
    return _cast_keep_scales(model.params, compute_dtype)


def _decode_head_offset(model: Sequential) -> int:
    """1 when the model carries a trailing LogSoftMax (the decode/prefill
    steps apply log_softmax themselves either way), else 0."""
    from bigdl_tpu.nn.activations import LogSoftMax

    return 1 if isinstance(model.modules[-1], LogSoftMax) else 0


def _resolve_decode_views(model: Sequential, off: int, Pt):
    """Navigate a params tree into the views the decode/prefill steps
    read — runs at build time on the captured weights AND in-trace on a
    runtime params argument (same key navigation either way). Returns
    ``(lookup_w, pos_w, [(block_module, block_params)], lnf_p, lin_p)``;
    layer_scan (ScanBlocks) stacks unstack into per-layer views
    (tree_map slices, valid in-trace too)."""
    mods = model.modules
    blocks = []
    for i, m in enumerate(mods):
        inner, bp = m, Pt[model._child_key(i)]
        if isinstance(m, Remat):
            inner, bp = m.modules[0], bp[m._child_key(0)]
        if isinstance(inner, ScanBlocks):
            tmpl = inner.modules[0]
            for lp in inner.unstacked_params(bp):
                t2, p2 = tmpl, lp
                if isinstance(t2, Remat):
                    t2, p2 = t2.modules[0], p2[t2._child_key(0)]
                blocks.append((t2, p2))
            continue
        if isinstance(inner, TransformerBlock):
            blocks.append((inner, bp))
    return (Pt[model._child_key(0)]["weight"],
            Pt[model._child_key(1)]["pos"],
            blocks,
            Pt[model._child_key(len(mods) - 2 - off)],
            Pt[model._child_key(len(mods) - 1 - off)])


def _tree_has_key(tree, key: str) -> bool:
    """True if any nested dict in ``tree`` carries ``key`` (used to
    refuse quantized weight layouts on paths that cannot shard them)."""
    if isinstance(tree, dict):
        return key in tree or any(_tree_has_key(v, key)
                                  for v in tree.values())
    return False


def tp_param_specs(model: Sequential, model_axis: str = "model"):
    """``PartitionSpec`` tree mirroring ``model.params`` for the
    Megatron layout the serving steps shard over ``model_axis``:
    attention QKV + MLP fc1 column-parallel (output rows — head-major
    for QKV, so ``n_heads % tp == 0`` splits whole heads), attention
    output + MLP fc2 row-parallel (input columns, bias replicated and
    added once post-psum), everything else (embeddings, LayerNorms, LM
    head) replicated. Feed it to ``shard_map`` ``in_specs`` or
    ``jax.device_put`` — shard_map hands each chip exactly the slice
    :mod:`bigdl_tpu.parallel.tensor_parallel` expects."""
    import jax
    from jax.sharding import PartitionSpec as P

    model._ensure_params()
    if _tree_has_key(model.params, "weight_q"):
        raise NotImplementedError(
            "tensor-parallel serving does not shard quantized "
            "(weight_q/w_scale) layouts yet — serve the float model or "
            "drop the model-axis sharding")
    specs = jax.tree_util.tree_map(lambda _: P(), model.params)
    for i, m in enumerate(model.modules):
        inner, bp = m, specs[model._child_key(i)]
        if isinstance(inner, Remat):
            inner, bp = inner.modules[0], bp[inner._child_key(0)]
        if isinstance(inner, ScanBlocks):
            raise NotImplementedError(
                "tensor-parallel serving over layer_scan stacks is not "
                "wired up (stacked leaves need a leading layer dim in "
                "every spec) — build the model with layer_scan=False")
        if not isinstance(inner, TransformerBlock):
            continue
        def put(p, weight_spec, bias_spec):
            # spec trees must mirror the params STRUCTURE exactly — a
            # bias spec for a bias-free Linear would desync shard_map's
            # in_specs tree
            p["weight"] = weight_spec
            if "bias" in p:
                p["bias"] = bias_spec
        ap = bp[inner._child_key(1)]
        for wname in ("wq", "wk", "wv"):
            put(ap[wname], P(model_axis, None), P(model_axis))
        put(ap["wo"], P(None, model_axis), P())
        put(bp[inner._child_key(3)], P(model_axis, None), P(model_axis))
        put(bp[inner._child_key(4)], P(None, model_axis), P())
    return specs


def serving_carry_specs(model: Sequential, sampling: bool = False,
                        data_axis: str = "data",
                        model_axis: Optional[str] = None,
                        kv_quant: bool = False):
    """``PartitionSpec`` tree for a :func:`make_batch_decode_step` carry:
    every leaf's slot axis over ``data_axis``, and (when ``model_axis``
    is given) the per-layer K/V head axis over ``model_axis``. Specs
    deliberately carry NO trailing ``None`` dims — ``P("data")`` and
    ``P("data", None, ...)`` hash differently on some jax generations,
    and mixing the two spellings between placement and step output would
    double-compile the one serving program. ``kv_quant`` adds the int8
    path's ``(N, heads)`` dequant-scale leaves — their head axis shards
    over ``model_axis`` alongside the heads they scale."""
    from jax.sharding import PartitionSpec as P

    model._ensure_params()
    off = _decode_head_offset(model)
    _, _, blocks, _, _ = _resolve_decode_views(model, off, model.params)
    specs = {"pos": P(data_axis)}
    kv = P(data_axis) if model_axis is None \
        else P(data_axis, None, model_axis)
    ks = P(data_axis) if model_axis is None \
        else P(data_axis, model_axis)
    for i in range(len(blocks)):
        specs[f"k{i}"] = kv
        specs[f"v{i}"] = kv
        if kv_quant:
            specs[f"k{i}_scale"] = ks
            specs[f"v{i}_scale"] = ks
    if sampling:
        specs["rng"] = P(data_axis)
        specs["tok_counts"] = P(data_axis)
        specs["prompt_mask"] = P(data_axis)
    return specs


#: The six adapted projections of one transformer block, in block order —
#: the layout contract between a model and a serving
#: :class:`~bigdl_tpu.serving.lora.AdapterBank` (bank keys are
#: ``f"{site}{layer}_a"`` / ``f"{site}{layer}_b"``).
ADAPTER_SITES = ("wq", "wk", "wv", "wo", "fc1", "fc2")


def adapter_site_shapes(model: Sequential):
    """Per-layer ``{site: (out_dim, in_dim)}`` weight shapes for the six
    adapted projections — what a serving AdapterBank sizes its pooled
    low-rank factors against. Quantized (``weight_q``) layouts are
    refused: the adapter delta maths against the float weight shapes,
    and the serving TP plane cannot shard quantized weights anyway."""
    model._ensure_params()
    if _tree_has_key(model.params, "weight_q"):
        raise NotImplementedError(
            "LoRA adapter serving over quantized (weight_q/w_scale) "
            "layouts is not wired up — serve the float model")
    off = _decode_head_offset(model)
    _, _, blocks, _, _ = _resolve_decode_views(model, off, model.params)
    shapes = []
    for blk, bp in blocks:
        ap = bp[blk._child_key(1)]
        layer = {name: tuple(ap[name]["weight"].shape)
                 for name in ("wq", "wk", "wv", "wo")}
        layer["fc1"] = tuple(bp[blk._child_key(3)]["weight"].shape)
        layer["fc2"] = tuple(bp[blk._child_key(4)]["weight"].shape)
        shapes.append(layer)
    return shapes


def adapter_bank_specs(model: Sequential, model_axis: str = "model"):
    """``PartitionSpec`` dict mirroring an AdapterBank's device arrays
    for the Megatron serving layout (:func:`tp_param_specs`'s sibling):
    column-parallel sites (wq/wk/wv/fc1) shard B's OUT axis over
    ``model_axis`` with A replicated — the delta lands directly on the
    chip's head/hidden slice, zero communication; row-parallel sites
    (wo/fc2) shard A's IN axis with B replicated — each chip's partial
    delta folds into the block's one closing psum
    (``row_parallel_linear(partial_add=...)``). The adapter-slot axis is
    always replicated: the bank is tiny next to the weights and every
    chip must gather any row's factors."""
    from jax.sharding import PartitionSpec as P

    model._ensure_params()
    off = _decode_head_offset(model)
    _, _, blocks, _, _ = _resolve_decode_views(model, off, model.params)
    specs = {}
    for i in range(len(blocks)):
        for name in ("wq", "wk", "wv", "fc1"):
            specs[f"{name}{i}_a"] = P()
            specs[f"{name}{i}_b"] = P(None, model_axis)
        for name in ("wo", "fc2"):
            specs[f"{name}{i}_a"] = P(None, None, model_axis)
            specs[f"{name}{i}_b"] = P()
    return specs


def _adapter_delta(bank, site: str, ids, h, scale):
    """Per-row pooled-LoRA delta for one adapted projection: gather the
    rows' (A, B) factor pairs from the bank by adapter id and compute
    ``scale * (h @ A_r^T) @ B_r^T`` with fp32 accumulation. Bank slot 0
    is the permanently all-zeros NULL adapter, so base-model rows
    contribute an exact 0.0 and mixed base/tenant traffic stays one
    compiled program (adding 0.0 is the fp identity up to -0.0 → +0.0).
    Returns the raw fp32 accumulator — call sites round once."""
    import jax.numpy as jnp

    a = jnp.take(bank[site + "_a"], ids, axis=0)   # (N, r, in[/tp])
    b = jnp.take(bank[site + "_b"], ids, axis=0)   # (N, out[/tp], r)
    if h.ndim == 2:                                # decode: (N, in)
        z = jnp.einsum("ni,nri->nr", h, a,
                       preferred_element_type=jnp.float32)
        d = jnp.einsum("nr,nor->no", z, b,
                       preferred_element_type=jnp.float32)
    else:                                          # chunk: (N, S, in)
        z = jnp.einsum("nsi,nri->nsr", h, a,
                       preferred_element_type=jnp.float32)
        d = jnp.einsum("nsr,nor->nso", z, b,
                       preferred_element_type=jnp.float32)
    return d * jnp.float32(scale)


def _adapter_proj_fns(adapter, adapter_ids, bank):
    """``(proj, rp_delta)`` for one step invocation: ``proj(p, h, site)``
    is the serving projection plus the rows' LoRA delta (plain
    ``_serving_proj``, site ignored, when no adapter is configured);
    ``rp_delta(h, site)`` is the fp32 partial delta the row-parallel
    mesh sites fold into their closing psum via ``_tp_row_proj`` (None
    without an adapter — the projection then runs unchanged)."""
    if adapter is None:
        return (lambda p, h, site: _serving_proj(p, h),
                lambda h, site: None)
    ascale = adapter.scale

    def proj(p, h, site):
        y = _serving_proj(p, h)
        return y + _adapter_delta(bank, site, adapter_ids, h,
                                  ascale).astype(y.dtype)

    def rp_delta(h, site):
        return _adapter_delta(bank, site, adapter_ids, h, ascale)

    return proj, rp_delta


# Over-provision a growing scale by this factor. A requantization
# (round(q * s_old / s_new) over the whole stored row) costs up to half
# a quantum of FRESH rounding error each time it runs, and without
# headroom a stationary K/V stream grows its running max ~log(n) times
# over a rollout — stored values accumulate several quanta of drift.
# With headroom, one growth jumps PAST the running max, so follow-up
# maxima land inside the provisioned range and requants become rare
# (~1 per 1.25x growth of the true max); the price is that values use
# 127/1.25 ~ 101 int8 levels instead of 127 (error 0.39% -> 0.49% of
# amax). Net on the serving parity scan: flipped-argmax rollouts drop,
# and decode steps skip most requant work.
_KV_SCALE_HEADROOM = 1.25


def _kv_quant_merge(qc, s_old, amax_new):
    """Grow-only per-(row, head) scale merge for the int8 KV cache —
    THE one copy of the quantized-write rule (decode step, batched
    prefill, and per-request prefill all route through here).

    ``qc``: stored int8 cache ``(R, L, H, D)``; ``s_old``: current
    ``(R, H)`` fp32 scales; ``amax_new``: ``(R, H)`` max |new values|
    about to be written (0 for rows that write nothing — their scale
    and stored values pass through BITWISE: their scale does not grow,
    so the ratio is exactly 1.0 and ``round(q * 1.0)`` is the identity
    on int8 values).

    Returns ``(requantized qc, s_new, s_safe)``: when ``amax_new / 127``
    exceeds the stored scale, the scale jumps to ``_KV_SCALE_HEADROOM``
    times that (see the constant's comment — headroom makes growth
    rare), and already-stored values are requantized to it
    (``round(q * s_old / s_new)`` — one extra rounding, bounded by half
    a quantum of the NEW scale; scales only ever grow, so the ratio is
    ≤ 1 and the result stays in int8 range). ``s_safe`` substitutes 1.0
    for still-zero scales so dividing by it is always defined."""
    import jax.numpy as jnp

    s_cand = amax_new / 127.0
    s_new = jnp.where(s_cand > s_old, s_cand * _KV_SCALE_HEADROOM, s_old)
    s_safe = jnp.where(s_new > 0, s_new, 1.0)
    ratio = jnp.where(s_new > 0, s_old / s_safe, 1.0)
    qc2 = jnp.round(qc.astype(jnp.float32) * ratio[:, None, :, None]
                    ).astype(jnp.int8)
    return qc2, s_new, s_safe


def _kv_quant_merge_step(kc, vc, ks_old, vs_old, k_amax, v_amax):
    """Decode-step spelling of the grow-only merge: the full-cache
    requantization is a read-modify-write over every stored K/V byte,
    which would triple the decode step's HBM traffic if it ran
    unconditionally — the exact traffic the int8 cache exists to halve.
    So it runs under ONE ``lax.cond`` per layer: on the common
    no-growth step (headroom makes growth rare — see
    ``_KV_SCALE_HEADROOM``) the cond's identity branch passes the
    caches through and the step touches no cache bytes beyond the
    attention read and the one written column. Numerics are identical
    to the unconditional merge: non-growing (row, head) entries have
    ratio exactly 1.0 and requantize bitwise, so skipping them is
    exact."""
    import jax.numpy as jnp
    from jax import lax

    grew = (jnp.any(k_amax / 127.0 > ks_old) |
            jnp.any(v_amax / 127.0 > vs_old))

    def _grow(args):
        kc, vc, ks_old, vs_old = args
        kc2, ks, _ = _kv_quant_merge(kc, ks_old, k_amax)
        vc2, vs, _ = _kv_quant_merge(vc, vs_old, v_amax)
        return kc2, vc2, ks, vs

    kc, vc, ks, vs = lax.cond(grew, _grow, lambda args: args,
                              (kc, vc, ks_old, vs_old))
    ks_safe = jnp.where(ks > 0, ks, 1.0)
    vs_safe = jnp.where(vs > 0, vs, 1.0)
    return kc, vc, ks, vs, ks_safe, vs_safe


def _kv_quantize(x32, s_safe):
    """fp32 values → int8 at the given (broadcastable) safe scale."""
    import jax.numpy as jnp

    return jnp.clip(jnp.round(x32 / s_safe), -127, 127).astype(jnp.int8)


def _serving_proj(p, x):
    """Linear projection for the serving steps: plain {weight,bias}
    params or a QuantizedLinear weight-only layout (int8 weights convert
    inside the dot's fusion, fp32 accumulate, per-channel scale)."""
    import jax.numpy as jnp
    from jax import lax

    if "weight_q" in p:
        acc = lax.dot_general(
            x.astype(jnp.bfloat16),
            p["weight_q"].astype(jnp.bfloat16),
            (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = (acc * p["w_scale"][:, 0]).astype(x.dtype)
        return out + p["bias"].astype(x.dtype) if "bias" in p else out
    return jnp.matmul(x, p["weight"].T) + p["bias"]


def make_prefill_step(model: Sequential, compute_dtype=None,
                      kv_quant: bool = False):
    """ONE-pass prompt ingestion for the KV-cached decoder (the serving
    "prefill" phase). Returns ``prefill(params, tokens, carry) ->
    (logprobs_last, carry)``:

    * ``tokens``: (B, P) 0-based prompt ids, P ≤ max_len (static shape —
      re-jit per length bucket). EQUAL-LENGTH prompts only: there is no
      per-row length mask, so right-padding a shorter prompt would write
      pad tokens into its cache and score the pad position (batch rows
      must share one true length; ragged batches go through
      :func:`make_batch_prefill_step`, which masks per row);
    * ``carry`` must be FRESH (``carry['pos'] == 0`` everywhere, straight
      from ``init_carry``): prefill writes K/V at positions 0..P-1 and
      forces ``pos = P`` unconditionally, so a partially-filled carry
      would be silently corrupted. The returned wrapper raises on a
      non-zero concrete ``pos`` before entering jit (skipped under an
      outer trace, where the value is abstract);
    * the whole prompt runs as ONE causal forward (parallel over P, full
      MXU tiles) and the per-layer K/V land in the carry at positions
      0..P-1 with ``pos`` set to P — decoding continues with the
      :func:`make_decode_step` step.

    Replaces priming the cache with P sequential single-token decode
    steps, each of which re-reads every weight: at 137M/P=128 that is
    ~74 ms of weight traffic vs one ~6 ms forward (measured in
    benchmarks/decode_bench.py). ``params`` follows the same runtime-
    argument convention as the decode step (``serving_params``).

    ``kv_quant=True`` writes the cache int8 with (row, head) scales —
    the fresh-carry contract makes this the degenerate one-shot case of
    the grow-only merge (old scale is 0, so the written chunk's amax IS
    the scale) — and runs the prompt's own attention over the
    dequantized values, mirroring :func:`make_batch_prefill_step`."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.nn.misc import LookupTable

    model._ensure_params()
    mods = model.modules
    assert isinstance(mods[0], LookupTable), "TransformerLM-shaped model"
    max_len = mods[1].max_len
    off = _decode_head_offset(model)
    lnf = mods[-2 - off]
    _, _, blocks0, _, _ = _resolve_decode_views(model, off, model.params)
    attn0 = blocks0[0][0].attn
    heads, hd = attn0.n_heads, attn0.head_dim
    scale = hd ** -0.5
    cache_dtype = compute_dtype or jnp.float32
    _p0_cache: list = []

    def get_p0():
        if not _p0_cache:
            _p0_cache.append(_cast_keep_scales(model.params, compute_dtype))
        return _p0_cache[0]

    # NOTE: the per-block body below intentionally parallels (not shares)
    # make_decode_step's loop — a length-generic unification would make
    # prefill attend over the full max_len cache instead of the P-sized
    # prompt (3x the attention work at P=127/max_len=384). The drift risk
    # is pinned by test_prefill_matches_sequential_decode, which asserts
    # cache/logit equality against the decode step for plain, bf16 and
    # int8 models.
    def prefill(params, tokens, carry):
        Pt = get_p0() if params is None else \
            _cast_keep_scales(params, compute_dtype)
        lookup_w, pos_w, blocks, lnf_p, lin_p = \
            _resolve_decode_views(model, off, Pt)
        B, P = tokens.shape
        if P > max_len:
            raise ValueError(f"prompt length {P} exceeds max_len {max_len}")
        x = jnp.take(lookup_w, jnp.clip(tokens, 0, lookup_w.shape[0] - 1),
                     axis=0)                          # (B, P, Hid)
        x = x + pos_w[:P]
        causal = jnp.tril(jnp.ones((P, P), bool))
        new_carry = dict(carry)
        for i, (blk, bp) in enumerate(blocks):
            h, _ = blk.ln1.apply(bp[blk._child_key(0)], x)
            ap = bp[blk._child_key(1)]
            q = _serving_proj(ap["wq"], h).reshape(B, P, heads, hd)
            k = _serving_proj(ap["wk"], h).reshape(B, P, heads, hd)
            v = _serving_proj(ap["wv"], h).reshape(B, P, heads, hd)
            if kv_quant:
                # fresh carry (pos 0, scale 0): the degenerate one-shot
                # case of the grow-only merge — s_old is 0, so the
                # chunk's amax sets the scale (headroom included) and
                # the "requantized" zero cache passes through as zeros.
                # Routing through _kv_quant_merge keeps THE one copy of
                # the write rule honest.
                k32, v32 = k.astype(jnp.float32), v.astype(jnp.float32)
                kc_rq, ks, ks_safe = _kv_quant_merge(
                    new_carry[f"k{i}"], new_carry[f"k{i}_scale"],
                    jnp.max(jnp.abs(k32), axis=(1, 3)))
                vc_rq, vs, vs_safe = _kv_quant_merge(
                    new_carry[f"v{i}"], new_carry[f"v{i}_scale"],
                    jnp.max(jnp.abs(v32), axis=(1, 3)))
                kq = _kv_quantize(k32, ks_safe[:, None, :, None])
                vq = _kv_quantize(v32, vs_safe[:, None, :, None])
                # write into the REQUANTIZED cache (zeros requantize to
                # zeros on the fresh-carry contract, so this is free
                # here — but discarding kc_rq would silently corrupt any
                # future warm-carry caller the pos guard can't see,
                # e.g. under an outer trace)
                new_carry[f"k{i}"] = lax.dynamic_update_slice_in_dim(
                    kc_rq, kq, 0, 1)
                new_carry[f"v{i}"] = lax.dynamic_update_slice_in_dim(
                    vc_rq, vq, 0, 1)
                new_carry[f"k{i}_scale"] = ks
                new_carry[f"v{i}_scale"] = vs
                # attend over the dequantized values decode will read
                k = kq.astype(jnp.float32) * ks_safe[:, None, :, None]
                v = vq.astype(jnp.float32) * vs_safe[:, None, :, None]
                q = q.astype(jnp.float32)
            else:
                new_carry[f"k{i}"] = lax.dynamic_update_slice_in_dim(
                    new_carry[f"k{i}"], k.astype(cache_dtype), 0, 1)
                new_carry[f"v{i}"] = lax.dynamic_update_slice_in_dim(
                    new_carry[f"v{i}"], v.astype(cache_dtype), 0, 1)
            # dense causal attention over the prompt (P is prompt-sized;
            # scores accumulate fp32 like the decode step)
            s = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k,
                           preferred_element_type=jnp.float32)
            s = jnp.where(causal[None, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype).reshape(B, P, heads * hd)
            x = x + _serving_proj(ap["wo"], ctx)
            h2, _ = blk.ln2.apply(bp[blk._child_key(2)], x)
            mlp = _serving_proj(bp[blk._child_key(4)], jax.nn.gelu(
                _serving_proj(bp[blk._child_key(3)], h2)))
            x = x + mlp
        xf, _ = lnf.apply(lnf_p, x[:, -1:])           # last position only
        logits = _serving_proj(lin_p, xf[:, 0])
        new_carry["pos"] = jnp.full_like(carry["pos"], P)
        return jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1), new_carry

    jitted = jax.jit(prefill)

    def prefill_checked(params, tokens, carry):
        import numpy as np

        pos = carry["pos"]
        # fresh-carry contract (see docstring): cheap concrete-value check
        # outside jit; under an outer trace pos is abstract and the check
        # is skipped (the (B,) int32 host readback costs microseconds)
        if not isinstance(pos, jax.core.Tracer) and np.asarray(pos).any():
            raise ValueError(
                "make_prefill_step requires a fresh carry (carry['pos'] "
                "must be all zeros): prefill writes K/V at positions "
                "0..P-1 and resets pos, which would corrupt a partially-"
                f"filled cache (got pos={np.asarray(pos).tolist()})")
        return jitted(params, tokens, carry)

    # exposed so benchmarks/tests can count compiled (B, P) buckets
    prefill_checked._jitted = jitted
    return prefill_checked


def make_batch_prefill_step(model: Sequential, compute_dtype=None,
                            mesh=None, data_axis: str = "data",
                            model_axis: str = "model",
                            carry_sampling: bool = False,
                            kv_quant: bool = False,
                            adapter=None):
    """MASKED multi-row prompt ingestion: one compiled program prefills a
    whole RAGGED batch of prompts (the admission path of
    ``bigdl_tpu.serving`` — see ``serving/admission.py``). Returns
    ``prefill(params, tokens, lengths, carry) -> (logprobs_last, carry)``:

    * ``tokens``: (B, L) 0-based ids, each row RIGHT-PADDED to the
      length bucket L (pad values are ignored — clip to vocab range is
      applied, any filler works);
    * ``lengths``: (B,) int32 — row r's true token count (0 ≤ lengths[r]
      ≤ L). Rows with ``lengths[r] == 0`` are pure ballast: their cache
      and ``pos`` are bitwise untouched and their logprob row is garbage
      the caller must ignore (exactly the batch-decode ``active``
      convention, so one (B, L) program serves every occupancy);
    * ``carry``: a B-row :func:`make_batch_decode_step` carry.
      ``carry['pos'][r]`` is row r's START offset: 0 for a fresh prompt,
      ``p0 > 0`` to CONTINUE over ``p0`` already-cached positions (the
      shared-prefix path: a prefix-cache hit clones the cached carry and
      prefills only the suffix). Row r writes K/V at absolute positions
      ``pos[r]..pos[r]+lengths[r]-1`` and its ``pos`` advances by
      ``lengths[r]``;
    * returns per-row log-probs of each row's LAST VALID position (the
      next-token distribution after the prompt) and the updated carry.

    Masking: pad columns never reach the cache (their scatter indices
    are routed out of bounds and DROPPED), queries use absolute
    positions ``pos[r] + i`` for both the position embedding and the
    causal mask, and attention runs over the row's full cache window so
    cached-prefix keys participate — one program shape per (B, L)
    regardless of per-row lengths or start offsets. That bounds the
    compiled-program set by the bucket count where per-row
    :func:`make_prefill_step` calls compile per DISTINCT LENGTH (the
    PR-1 admission stall — see docs/serving.md). The tradeoff: scores
    span ``(L, max_len)`` instead of ``(P, P)``, so for one lone short
    prompt the per-row step does less work; the win is batching ragged
    admissions into one call (and it is what keeps a sharded prefill
    program reusable — shape-stable admission).

    The wrapper raises (on concrete values) if a row would write past
    ``max_len`` (``pos[r] + lengths[r] > max_len``) or ``lengths``
    exceeds L. Numerics follow the serving conventions (fp32 score
    accumulation, ``compute_dtype`` cache, int8 weight-only
    projections); per-row results equal :func:`make_prefill_step` to
    float round-off — the wider masked reduction can reorder XLA sums —
    pinned by tests/test_serving_admission.py.

    ``mesh`` lowers the program through ``utils.compat.shard_map`` with
    the same Megatron layout as :func:`make_batch_decode_step`: heads +
    MLP hidden shard over ``model_axis`` (two psums per block), while
    tokens/lengths/carry rows stay REPLICATED over ``data_axis`` —
    prefill rows are few and short-lived, so sharding them would buy
    little and break the B=1 prefix-cache path. The returned carry's
    K/V are head-sharded, matching the sharded pool's decode layout.

    ``kv_quant=True`` matches the int8 decode carry
    (:func:`make_batch_decode_step` with the same knob): written K/V
    quantize through the grow-only (row, head) scale merge — a suffix
    continuation over a quantized cached prefix requantizes the prefix
    when the suffix raises the scale — and the prompt's own attention
    reads the DEQUANTIZED cache, so prefill scores see exactly the
    values decode will (ballast rows still pass through bitwise:
    zero-length rows have amax 0 and their scatter drops).

    ``adapter`` (a :class:`~bigdl_tpu.serving.lora.AdapterSpec`) makes
    the returned step the multi-tenant variant: ``prefill(params,
    tokens, lengths, carry, adapter_ids, bank)``, where ``adapter_ids``
    (B,) int32 selects each row's pooled low-rank factor pair and
    ``bank`` is the AdapterBank's device-array dict — both runtime
    VALUES of the same one program (bank row 0 is the all-zeros null
    adapter, so mixed base/tenant batches never recompile). The six
    per-block projections add the rows' gathered delta; under a mesh
    the row-parallel sites fold their fp32 partial delta into the
    block's existing closing psum (collective count unchanged)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.misc import LookupTable

    model._ensure_params()
    mods = model.modules
    assert isinstance(mods[0], LookupTable), "TransformerLM-shaped model"
    max_len = mods[1].max_len
    off = _decode_head_offset(model)
    lnf = mods[-2 - off]
    _, _, blocks0, _, _ = _resolve_decode_views(model, off, model.params)
    attn0 = blocks0[0][0].attn
    heads, hd = attn0.n_heads, attn0.head_dim
    scale = hd ** -0.5
    cache_dtype = compute_dtype or jnp.float32
    _proj = _serving_proj
    tp = 1 if mesh is None else int(mesh.shape[model_axis])
    if mesh is not None:
        _check_tp_divisibility(model, heads, tp)
    heads_l = heads // tp

    def prefill(params, tokens, lengths, carry, adapter_ids=None,
                bank=None):
        Pt = _cast_keep_scales(params, compute_dtype)
        lookup_w, pos_w, blocks, lnf_p, lin_p = \
            _resolve_decode_views(model, off, Pt)
        aproj, rp_delta = _adapter_proj_fns(adapter, adapter_ids, bank)
        B, L = tokens.shape
        start = carry["pos"]                           # (B,) per-row offset
        rows = jnp.arange(B)
        qpos = start[:, None] + jnp.arange(L)[None]    # (B, L) absolute
        inb = jnp.arange(L)[None] < lengths[:, None]   # (B, L) valid mask
        # pad/overflow columns scatter to index max_len → dropped; valid
        # columns are in range (checked wrapper) and strictly increasing
        # per row, so writes never collide
        widx = jnp.where(inb, qpos, max_len)
        x = jnp.take(lookup_w, jnp.clip(tokens, 0, lookup_w.shape[0] - 1),
                     axis=0)                           # (B, L, Hid)
        x = x + jnp.take(pos_w, jnp.clip(qpos, 0, max_len - 1), axis=0)
        new_carry = dict(carry)
        for i, (blk, bp) in enumerate(blocks):
            h, _ = blk.ln1.apply(bp[blk._child_key(0)], x)
            ap = bp[blk._child_key(1)]
            q = aproj(ap["wq"], h, f"wq{i}").reshape(B, L, heads_l, hd)
            k = aproj(ap["wk"], h, f"wk{i}").reshape(B, L, heads_l, hd)
            v = aproj(ap["wv"], h, f"wv{i}").reshape(B, L, heads_l, hd)
            if kv_quant:
                # int8 storage: per-(row, head) amax over the VALID
                # columns only (pad columns must not inflate the scale),
                # grow-only merge with the cached prefix's scale, then
                # the same dropped-index masked scatter
                k32 = k.astype(jnp.float32)
                v32 = v.astype(jnp.float32)
                inbf = inb[:, :, None, None]
                k_amax = jnp.max(jnp.abs(k32) * inbf, axis=(1, 3))
                v_amax = jnp.max(jnp.abs(v32) * inbf, axis=(1, 3))
                kc_rq, ks_new, ks_safe = _kv_quant_merge(
                    new_carry[f"k{i}"], new_carry[f"k{i}_scale"], k_amax)
                vc_rq, vs_new, vs_safe = _kv_quant_merge(
                    new_carry[f"v{i}"], new_carry[f"v{i}_scale"], v_amax)
                kc = kc_rq.at[rows[:, None], widx].set(
                    _kv_quantize(k32, ks_safe[:, None, :, None]),
                    mode="drop")
                vc = vc_rq.at[rows[:, None], widx].set(
                    _kv_quantize(v32, vs_safe[:, None, :, None]),
                    mode="drop")
                new_carry[f"k{i}_scale"] = ks_new
                new_carry[f"v{i}_scale"] = vs_new
                # the prompt attends over the DEQUANTIZED cache — the
                # values decode-time reads will see, so prefill and
                # decode stay one consistent numerics story
                katt = kc.astype(jnp.float32) * ks_new[:, None, :, None]
                vatt = vc.astype(jnp.float32) * vs_new[:, None, :, None]
                qatt = (q * scale).astype(jnp.float32)
                p_dt = jnp.float32
            else:
                kc = new_carry[f"k{i}"].at[rows[:, None], widx].set(
                    k.astype(cache_dtype), mode="drop")
                vc = new_carry[f"v{i}"].at[rows[:, None], widx].set(
                    v.astype(cache_dtype), mode="drop")
                katt, vatt = kc, vc
                qatt = (q * scale).astype(cache_dtype)
                p_dt = cache_dtype
            new_carry[f"k{i}"], new_carry[f"v{i}"] = kc, vc
            # queries attend over the row's FULL cache window (cached
            # prefix + this chunk) under an absolute causal mask; scores
            # accumulate fp32 regardless of the serving dtype
            s = jnp.einsum("blhd,bmhd->bhlm", qatt, katt,
                           preferred_element_type=jnp.float32)
            valid = (jnp.arange(max_len)[None, None, None, :]
                     <= qpos[:, None, :, None])
            s = jnp.where(valid, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhlm,bmhd->blhd", p.astype(p_dt), vatt,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype).reshape(B, L, heads_l * hd)
            if mesh is None:
                x = x + aproj(ap["wo"], ctx, f"wo{i}")
            else:
                x = x + _tp_row_proj(ap["wo"], ctx, model_axis,
                                     delta32=rp_delta(ctx, f"wo{i}"))
            h2, _ = blk.ln2.apply(bp[blk._child_key(2)], x)
            hmid = jax.nn.gelu(aproj(bp[blk._child_key(3)], h2, f"fc1{i}"))
            if mesh is None:
                mlp = aproj(bp[blk._child_key(4)], hmid, f"fc2{i}")
            else:
                mlp = _tp_row_proj(bp[blk._child_key(4)], hmid, model_axis,
                                   delta32=rp_delta(hmid, f"fc2{i}"))
            x = x + mlp
        # each row's next-token logits come from its LAST VALID position
        last = jnp.clip(lengths - 1, 0, L - 1)
        xf, _ = lnf.apply(lnf_p, x[rows, last][:, None])
        logits = _proj(lin_p, xf[:, 0])
        new_carry["pos"] = start + lengths.astype(start.dtype)
        return jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1), new_carry

    if adapter is None:
        run = prefill
    else:
        # pin the adapter arity (shard_map's in_specs tree must match
        # the call positionally — no defaulted tail)
        def run(params, tokens, lengths, carry, adapter_ids, bank):
            return prefill(params, tokens, lengths, carry, adapter_ids,
                           bank)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.utils.compat import shard_map as _shard_map

        kv = P(None, None, model_axis)
        cspecs = {"pos": P()}
        for i in range(len(blocks0)):
            cspecs[f"k{i}"] = kv
            cspecs[f"v{i}"] = kv
            if kv_quant:
                # (B, heads) dequant scales shard with their heads
                cspecs[f"k{i}_scale"] = P(None, model_axis)
                cspecs[f"v{i}_scale"] = P(None, model_axis)
        if carry_sampling:
            # a sampling-enabled pool's zero carry rides through prefill
            # untouched — but shard_map's spec tree must still name
            # every leaf (replicated: prefill never reads them)
            cspecs["rng"] = P()
            cspecs["tok_counts"] = P()
            cspecs["prompt_mask"] = P()
        in_specs = [tp_param_specs(model, model_axis), P(), P(), cspecs]
        if adapter is not None:
            # adapter ids replicate like tokens/lengths (prefill rows
            # are few); the bank shards Megatron-style with the weights
            in_specs += [P(), adapter_bank_specs(model, model_axis)]
        jitted = jax.jit(_shard_map(
            run, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=(P(), cspecs), check_vma=False))
    else:
        jitted = jax.jit(run)

    def prefill_checked(params, tokens, lengths, carry, *adapter_args):
        import numpy as np

        lengths = jnp.asarray(lengths, jnp.int32)
        if tokens.ndim != 2 or lengths.shape != tokens.shape[:1]:
            raise ValueError(
                f"tokens must be (B, L) with lengths (B,): got "
                f"{tokens.shape} / {lengths.shape}")
        if carry["pos"].shape[0] != tokens.shape[0]:
            raise ValueError(
                f"carry has {carry['pos'].shape[0]} rows but tokens has "
                f"{tokens.shape[0]} — the carry must come from "
                "make_batch_decode_step's init_carry(B)")
        pos = carry["pos"]
        # cheap concrete-value guards outside jit (abstract under an
        # outer trace, where they are skipped): a row writing past the
        # cache would be silently DROPPED by the masked scatter
        if not isinstance(lengths, jax.core.Tracer) \
                and not isinstance(pos, jax.core.Tracer):
            ln, ps = np.asarray(lengths), np.asarray(pos)
            if (ln < 0).any() or (ln > tokens.shape[1]).any():
                raise ValueError(
                    f"lengths must lie in 0..L={tokens.shape[1]} "
                    f"(got {ln.tolist()})")
            if (ps + ln > max_len).any():
                raise ValueError(
                    f"rows would write past max_len {max_len}: "
                    f"pos={ps.tolist()} + lengths={ln.tolist()}")
        if adapter is not None and len(adapter_args) != 2:
            raise ValueError(
                "this prefill step was built with an adapter spec — "
                "call it as prefill(params, tokens, lengths, carry, "
                "adapter_ids, bank)")
        return jitted(params, tokens, lengths, carry, *adapter_args)

    # exposed so benchmarks/tests can count compiled (B, L) buckets
    prefill_checked._jitted = jitted
    return prefill_checked


def _serving_init_carry(n_layers: int, max_len: int, heads: int, hd: int,
                        cache_dtype, kv_quant: bool, sampling: bool,
                        vocab: int):
    """THE one pooled-carry layout: per-layer K/V rows + per-row ``pos``,
    int8 dequant scales on the quantized layout, and the per-row
    sampling state (RNG lanes + penalty counters — the engine seeds rows
    at admission via ``KVPool.write_sampling``). Shared by
    :func:`make_batch_decode_step` and :func:`make_batch_verify_step` so
    a pool built by either hands its carry to the other unchanged (the
    speculative engine's verify step IS its decode step)."""
    import jax.numpy as jnp

    def init_carry(n_slots: int):
        carry = {"pos": jnp.zeros((n_slots,), jnp.int32)}
        kv_dt = jnp.int8 if kv_quant else cache_dtype
        for i in range(n_layers):
            carry[f"k{i}"] = jnp.zeros((n_slots, max_len, heads, hd),
                                       kv_dt)
            carry[f"v{i}"] = jnp.zeros((n_slots, max_len, heads, hd),
                                       kv_dt)
            if kv_quant:
                # per-(slot, head) dequant scales; 0 = "no scale yet"
                # (fresh rows — the first write establishes it)
                carry[f"k{i}_scale"] = jnp.zeros((n_slots, heads),
                                                 jnp.float32)
                carry[f"v{i}_scale"] = jnp.zeros((n_slots, heads),
                                                 jnp.float32)
        if sampling:
            carry["rng"] = jnp.zeros((n_slots, 2), jnp.uint32)
            carry["tok_counts"] = jnp.zeros((n_slots, vocab), jnp.int32)
            carry["prompt_mask"] = jnp.zeros((n_slots, vocab), bool)
        return carry

    return init_carry


def make_decode_step(model: Sequential, compute_dtype=None):
    """KV-cached incremental decoding for a trained :func:`TransformerLM`.

    Returns ``(step_fn, init_carry)``:

    * ``init_carry(batch) -> carry`` — per-layer K/V caches
      ``(batch, max_len, heads, head_dim)`` plus a position counter;
    * ``step_fn(params, tokens, carry) -> (logprobs, carry)`` —
      one token per call, attention reads the cache (O(1) new compute per
      step instead of re-running the full prefix). ``params`` may be
      ``None`` (use the weights captured at build time — convenient, but
      jit bakes them into the program as CONSTANTS, so the compiled
      executable carries the full weight payload; measured as an HTTP 413
      on the axon remote-compile tunnel at 137M params) or the model's
      params pytree passed as a RUNTIME argument — the serving mode:
      weights live in device buffers, update without recompiling, and the
      program stays small (benchmarks/decode_bench.py uses this). The
      signature matches ``SequenceBeamSearch``/
      :func:`bigdl_tpu.nn.beam_search.beam_search`; beam
      parent-gathering permutes whole cache rows, and the position
      counter is uniform across rows, so lockstep decoding stays exact.

    Tokens are 0-based class indices (logit column c ↔ 1-based word id
    c+1), matching the LM's LogSoftMax output columns.

    ``compute_dtype`` (e.g. ``jnp.bfloat16``) is the serving-precision
    knob: captured weights and K/V caches store/compute in that dtype
    (decode is weight-read-bound, so halving weight bytes is the
    first-order lever — measured in benchmarks/decode_bench.py); score
    accumulation and the final log-softmax stay fp32. Quantized models
    (``Quantizer.quantize(lm, scheme="weight_only")``) decode through the
    same step — projections whose params carry ``weight_q`` run the int8
    dequant-into-matmul path, compounding with ``compute_dtype``.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bigdl_tpu.nn.misc import LookupTable

    model._ensure_params()
    mods = model.modules
    assert isinstance(mods[0], LookupTable), "TransformerLM-shaped model"
    posemb = mods[1]
    max_len = posemb.max_len
    off = _decode_head_offset(model)
    lnf = mods[-2 - off]

    def resolve(Pt):
        return _resolve_decode_views(model, off, Pt)

    # structural metadata from the UNCAST params (no weight copy); the
    # converted P0 copy is materialized lazily, only if a caller uses the
    # params=None (baked-constants) mode
    _, _, blocks0, _, _ = resolve(model.params)
    attn0 = blocks0[0][0].attn
    _p0_cache: list = []

    def get_p0():
        if not _p0_cache:
            _p0_cache.append(_cast_keep_scales(model.params, compute_dtype))
        return _p0_cache[0]
    heads, hd = attn0.n_heads, attn0.head_dim
    scale = hd ** -0.5

    cache_dtype = compute_dtype or jnp.float32

    def init_carry(batch: int):
        carry = {"pos": jnp.zeros((batch,), jnp.int32)}
        for i in range(len(blocks0)):
            carry[f"k{i}"] = jnp.zeros((batch, max_len, heads, hd),
                                       cache_dtype)
            carry[f"v{i}"] = jnp.zeros((batch, max_len, heads, hd),
                                       cache_dtype)
        return carry

    _proj = _serving_proj

    def step(params, tokens, carry):
        if params is None:
            Pt = get_p0()    # captured weights, baked in as jit constants
        else:
            Pt = _cast_keep_scales(params, compute_dtype)
        lookup_w, pos_w, blocks, lnf_p, lin_p = resolve(Pt)
        n = tokens.shape[0]
        t = carry["pos"][0]                      # uniform across rows
        x = jnp.take(lookup_w, jnp.clip(tokens, 0, lookup_w.shape[0] - 1),
                     axis=0)                     # (N, Hid)
        x = x + lax.dynamic_index_in_dim(pos_w, t, keepdims=False)
        new_carry = dict(carry)
        for i, (blk, bp) in enumerate(blocks):
            h, _ = blk.ln1.apply(bp[blk._child_key(0)], x[:, None])
            h = h[:, 0]
            ap = bp[blk._child_key(1)]
            q = _proj(ap["wq"], h).reshape(n, heads, hd)
            k_new = _proj(ap["wk"], h).reshape(n, heads, hd)
            v_new = _proj(ap["wv"], h).reshape(n, heads, hd)
            kc = lax.dynamic_update_slice_in_dim(
                new_carry[f"k{i}"], k_new[:, None].astype(cache_dtype), t, 1)
            vc = lax.dynamic_update_slice_in_dim(
                new_carry[f"v{i}"], v_new[:, None].astype(cache_dtype), t, 1)
            new_carry[f"k{i}"], new_carry[f"v{i}"] = kc, vc
            # scores accumulate fp32 regardless of the serving dtype
            s = jnp.einsum("nhd,nlhd->nhl",
                           (q * scale).astype(cache_dtype), kc,
                           preferred_element_type=jnp.float32)
            valid = jnp.arange(max_len)[None, None, :] <= t
            s = jnp.where(valid, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("nhl,nlhd->nhd", p.astype(cache_dtype), vc,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype).reshape(n, heads * hd)
            x = x + _proj(ap["wo"], ctx)
            h2, _ = blk.ln2.apply(bp[blk._child_key(2)], x[:, None])
            h2 = h2[:, 0]
            mlp = _proj(bp[blk._child_key(4)],
                        jax.nn.gelu(_proj(bp[blk._child_key(3)], h2)))
            x = x + mlp
        xf, _ = lnf.apply(lnf_p, x[:, None])
        logits = _proj(lin_p, xf[:, 0])
        new_carry["pos"] = carry["pos"] + 1
        return jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1), new_carry

    # shapes are static across steps: compile once, reuse every token
    # (composes with beam_search's lax.scan — jit-of-jit inlines)
    return jax.jit(step), init_carry


def _tp_row_proj(p, x, axis_name: str, delta32=None):
    """Row-parallel serving projection: this chip's partial product is
    completed by the block's one closing psum; the bias (replicated)
    is added once, post-psum (``parallel.tensor_parallel``'s layout).
    Partials and the psum accumulate fp32 and round to the serving
    dtype ONCE — matching the unsharded matmul's single rounding, so
    bf16 TP serving stays token-aligned with the single-device engine
    instead of drifting an ulp per psum addend. ``delta32``: an fp32
    per-chip LoRA partial delta folded into the SAME psum (the adapter
    path keeps the two-collectives-per-block budget; None = no-op)."""
    import jax.numpy as jnp

    from bigdl_tpu.parallel.tensor_parallel import row_parallel_linear

    return row_parallel_linear(x, p["weight"], p.get("bias"), axis_name,
                               accum_dtype=jnp.float32,
                               partial_add=delta32)


def _check_tp_divisibility(model: Sequential, heads: int, tp: int) -> None:
    """Fail fast (with the fix in the message) when a model cannot split
    over a ``tp``-way model axis: whole heads and whole MLP hidden rows
    must land on each chip."""
    if tp <= 0:
        raise ValueError(f"model-axis size must be positive, got {tp}")
    hidden = model.modules[1].hidden_size
    mlp_hidden = None
    for m in model.modules:
        inner = m.modules[0] if isinstance(m, Remat) else m
        if isinstance(inner, TransformerBlock):
            mlp_hidden = inner.fc1.output_size
            break
    if heads % tp:
        raise ValueError(
            f"n_heads {heads} not divisible by the model-axis size {tp} "
            "— tensor-parallel serving shards whole heads")
    if mlp_hidden is not None and mlp_hidden % tp:
        raise ValueError(
            f"MLP hidden {mlp_hidden} not divisible by the model-axis "
            f"size {tp}")
    if hidden % tp:
        raise ValueError(
            f"hidden {hidden} not divisible by the model-axis size {tp}")


def make_batch_decode_step(model: Sequential, compute_dtype=None,
                           sampling: bool = False, mesh=None,
                           data_axis: str = "data",
                           model_axis: str = "model",
                           kv_quant: bool = False,
                           adapter=None):
    """Per-ROW-position decode step for continuous batching
    (``bigdl_tpu.serving``): every cache row advances independently, so
    one pooled carry can hold many requests at different depths and rows
    can be recycled mid-flight.

    Returns ``(step_fn, init_carry)``:

    * ``init_carry(n_slots) -> carry`` — identical layout to
      :func:`make_decode_step` (per-layer ``(N, max_len, heads, hd)``
      K/V + ``pos``), but ``pos`` is PER-ROW state, not uniform;
    * ``step_fn(params, tokens, active, carry) -> (logprobs, carry)`` —
      ``tokens`` (N,) 0-based ids, ``active`` (N,) bool. Active rows
      write K/V at their own ``pos[r]``, attend over ``0..pos[r]`` of
      their own cache row, and advance ``pos[r]`` by one; inactive rows
      are pure ballast — their cache and ``pos`` are bitwise untouched
      (the write scatters the OLD value back) and their logprob rows are
      garbage the caller must ignore. Rows never interact (attention is
      per-row over the row's own cache), so each active row computes the
      same math as the single-request :func:`make_decode_step` (equal to
      float round-off — batch shape changes XLA reduction order).

    ``sampling=True`` fuses a per-row SAMPLE-FROM-LOGITS epilogue
    (:func:`bigdl_tpu.serving.sampling.sample_rows`) into the step:

    * the carry grows per-row sampling state — ``rng`` (N, 2) uint32
      RNG lanes, ``tok_counts`` (N, vocab) int32 generated-token
      counts, ``prompt_mask`` (N, vocab) bool prompt membership (the
      engine seeds these per admission via ``KVPool.write_sampling``);
    * the signature becomes ``step_fn(params, tokens, active, carry,
      knobs) -> (token, chosen_logp, carry)`` — ``knobs`` is the
      per-row array dict of :func:`~bigdl_tpu.serving.sampling.
      make_knob_rows` (temperature/top-k/top-p/penalties/ban rows, all
      runtime VALUES: one compiled program covers every knob mix, and
      ``temperature == 0`` rows reduce to exact argmax);
    * the ``(N, vocab)`` distribution never crosses to host — only the
      chosen token ids and their raw model log-probs do, preserving the
      one-small-readback-per-step property the greedy step had;
    * inactive rows stay bitwise untouched (rng/counts included); their
      token/log-prob outputs are garbage the caller must ignore.

    NOTE: the per-layer body below intentionally parallels (not shares)
    make_decode_step's loop — unifying them would put per-row gathers and
    masked scatters on the lockstep path that beam_search scans over.
    The drift risk is pinned by test_batch_decode_step_matches_single_row
    and the engine-vs-generate parity tests (plain + bf16): any fix to
    the decode math (mask constant, cache-dtype casts, _serving_proj)
    must land in BOTH loops or those tests fail.

    ``params``/``compute_dtype`` follow the :func:`make_decode_step`
    conventions (runtime params tree via :func:`serving_params`, fp32
    score accumulation, int8 weight-only projections supported).
    The caller owns slot assignment and must keep ``pos[r] < max_len``
    for active rows (writes clamp to the last cache index rather than
    silently wrapping).

    ``mesh`` (a ``jax.sharding.Mesh`` with ``data_axis`` and
    ``model_axis``) lowers the step through ``utils.compat.shard_map``
    instead of a bare jit — the tensor-parallel serving plane
    (``bigdl_tpu.serving.sharded``): slot rows shard over ``data_axis``,
    attention heads + MLP hidden shard over ``model_axis`` with the
    Megatron two-collectives-per-block layout (one psum closing the
    attention output projection, one closing the MLP — the column-
    parallel QKV/fc1 halves communicate nothing; see
    ``parallel/tensor_parallel.py``). Callers place params with
    :func:`tp_param_specs` and the carry with
    :func:`serving_carry_specs`; requires ``n_heads`` and
    ``mlp_ratio*hidden`` divisible by the model-axis size, float (non-
    quantized) weights, and no layer_scan. Per-row math is unchanged —
    only the two closing psums reorder float sums, so outputs match the
    unsharded step to round-off (slot-data-parallel-only meshes skip
    shard_map entirely and stay bitwise identical; pinned by
    tests/test_serving_sharded.py).

    ``kv_quant=True`` stores the per-layer K/V caches as INT8 with one
    fp32 scale per (slot, head) (carry keys ``k{i}_scale``/
    ``v{i}_scale``, shape ``(N, heads)`` — ~overhead-free next to the
    halved cache payload). Writes quantize through the grow-only scale
    merge (:func:`_kv_quant_merge`: a slot's scale only ever grows;
    stored values are requantized on growth, and rows that write
    nothing — inactive rows — pass through bitwise, preserving the
    ballast contract above). The attention read routes through
    :func:`bigdl_tpu.ops.decode_attention.decode_attention` with the
    dequantization FUSED into the K/V load (the Pallas pooled decode
    kernel on TPU, its jnp reference elsewhere — scales factor out of
    both contractions exactly, so int8 bytes are what cross HBM).
    Quantization is an engine-level storage choice, not per-row state:
    a ``kv_quant`` step is still ONE compiled program for every
    traffic mix, same as the float step (pinned by
    tests/test_serving_kv_quant.py).

    ``adapter`` (a :class:`~bigdl_tpu.serving.lora.AdapterSpec`) selects
    the multi-tenant variant: the signature grows a trailing
    ``(adapter_ids, bank)`` pair — ``adapter_ids`` (N,) int32 per-row
    bank-slot ids, ``bank`` the AdapterBank's device-array dict — and
    every block's six projections add the rows' gathered low-rank delta
    (``_adapter_delta``; bank row 0 is the all-zeros NULL adapter, so
    base rows add an exact 0.0 and mixed base/tenant traffic is the
    same ONE compiled program). Under a mesh the column-parallel sites
    compute their delta chip-locally (A replicated, B's out axis
    sharded) and the row-parallel sites fold an fp32 partial delta into
    the block's existing closing psum — the two-collectives-per-block
    budget is unchanged (see :func:`adapter_bank_specs`).
    """
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.misc import LookupTable

    model._ensure_params()
    mods = model.modules
    assert isinstance(mods[0], LookupTable), "TransformerLM-shaped model"
    max_len = mods[1].max_len
    vocab = mods[0].n_index
    off = _decode_head_offset(model)
    lnf = mods[-2 - off]
    _, _, blocks0, _, _ = _resolve_decode_views(model, off, model.params)
    attn0 = blocks0[0][0].attn
    heads, hd = attn0.n_heads, attn0.head_dim
    scale = hd ** -0.5
    cache_dtype = compute_dtype or jnp.float32
    tp = 1 if mesh is None else int(mesh.shape[model_axis])
    if mesh is not None:
        _check_tp_divisibility(model, heads, tp)
    # per-device head count: under shard_map each chip sees its own
    # head slice of the (already column-parallel) QKV projections
    heads_l = heads // tp

    init_carry = _serving_init_carry(len(blocks0), max_len, heads, hd,
                                     cache_dtype, kv_quant, sampling,
                                     vocab)

    _proj = _serving_proj

    def forward(params, tokens, active, carry, adapter_ids=None,
                bank=None):
        Pt = _cast_keep_scales(params, compute_dtype)
        lookup_w, pos_w, blocks, lnf_p, lin_p = \
            _resolve_decode_views(model, off, Pt)
        aproj, rp_delta = _adapter_proj_fns(adapter, adapter_ids, bank)
        n = tokens.shape[0]
        pos = carry["pos"]                        # (N,) per-row
        rows = jnp.arange(n)
        wpos = jnp.clip(pos, 0, max_len - 1)      # write index per row
        x = jnp.take(lookup_w, jnp.clip(tokens, 0, lookup_w.shape[0] - 1),
                     axis=0)                      # (N, Hid)
        x = x + jnp.take(pos_w, wpos, axis=0)
        new_carry = dict(carry)
        for i, (blk, bp) in enumerate(blocks):
            h, _ = blk.ln1.apply(bp[blk._child_key(0)], x[:, None])
            h = h[:, 0]
            ap = bp[blk._child_key(1)]
            # under a mesh these params are per-chip column-parallel
            # slices (head-major rows), so the same _proj IS the
            # column-parallel half — zero communication
            q = aproj(ap["wq"], h, f"wq{i}").reshape(n, heads_l, hd)
            k_new = aproj(ap["wk"], h, f"wk{i}").reshape(n, heads_l, hd)
            v_new = aproj(ap["wv"], h, f"wv{i}").reshape(n, heads_l, hd)
            kc_prev, vc_prev = new_carry[f"k{i}"], new_carry[f"v{i}"]
            if kv_quant:
                # int8 storage: grow-only (slot, head) scale merge, then
                # the same masked scatter contract — inactive rows have
                # amax 0, so their scale, stored values, and the
                # written-back old value are all bitwise untouched
                k32 = k_new.astype(jnp.float32)
                v32 = v_new.astype(jnp.float32)
                k_amax = jnp.where(active[:, None],
                                   jnp.max(jnp.abs(k32), axis=-1), 0.0)
                v_amax = jnp.where(active[:, None],
                                   jnp.max(jnp.abs(v32), axis=-1), 0.0)
                (kc_prev, vc_prev, ks_new, vs_new, ks_safe,
                 vs_safe) = _kv_quant_merge_step(
                    kc_prev, vc_prev, new_carry[f"k{i}_scale"],
                    new_carry[f"v{i}_scale"], k_amax, v_amax)
                k_wr0 = _kv_quantize(k32, ks_safe[..., None])
                v_wr0 = _kv_quantize(v32, vs_safe[..., None])
                new_carry[f"k{i}_scale"] = ks_new
                new_carry[f"v{i}_scale"] = vs_new
            else:
                k_wr0 = k_new.astype(cache_dtype)
                v_wr0 = v_new.astype(cache_dtype)
            # masked per-row scatter: inactive rows write their OLD value
            # back, so their cache stays bitwise identical
            k_old, v_old = kc_prev[rows, wpos], vc_prev[rows, wpos]
            k_wr = jnp.where(active[:, None, None], k_wr0, k_old)
            v_wr = jnp.where(active[:, None, None], v_wr0, v_old)
            kc = kc_prev.at[rows, wpos].set(k_wr)
            vc = vc_prev.at[rows, wpos].set(v_wr)
            new_carry[f"k{i}"], new_carry[f"v{i}"] = kc, vc
            if kv_quant:
                # attention via the pooled decode op: Pallas kernel on
                # TPU (int8 K/V loads, dequant fused as two scalar
                # factors), jnp reference elsewhere — per-row masked
                # single-query attention over cols 0..wpos[r]
                from bigdl_tpu.ops.decode_attention import decode_attention

                ctx = decode_attention(
                    q, kc, vc, wpos, k_scale=ks_new, v_scale=vs_new,
                    scale=scale, out_dtype=x.dtype
                ).reshape(n, heads_l * hd)
            else:
                # per-row causal mask over the row's own cache prefix;
                # scores accumulate fp32 regardless of the serving dtype
                s = jnp.einsum("nhd,nlhd->nhl",
                               (q * scale).astype(cache_dtype), kc,
                               preferred_element_type=jnp.float32)
                valid = jnp.arange(max_len)[None, None, :] \
                    <= wpos[:, None, None]
                s = jnp.where(valid, s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                ctx = jnp.einsum("nhl,nlhd->nhd", p.astype(cache_dtype),
                                 vc, preferred_element_type=jnp.float32
                                 ).astype(x.dtype).reshape(n, heads_l * hd)
            if mesh is None:
                x = x + aproj(ap["wo"], ctx, f"wo{i}")
            else:
                # row-parallel output projection — collective 1 of 2
                # (the adapter's partial delta rides the same psum)
                x = x + _tp_row_proj(ap["wo"], ctx, model_axis,
                                     delta32=rp_delta(ctx, f"wo{i}"))
            h2, _ = blk.ln2.apply(bp[blk._child_key(2)], x[:, None])
            h2 = h2[:, 0]
            hmid = jax.nn.gelu(aproj(bp[blk._child_key(3)], h2, f"fc1{i}"))
            if mesh is None:
                mlp = aproj(bp[blk._child_key(4)], hmid, f"fc2{i}")
            else:
                # row-parallel MLP projection — collective 2 of 2
                mlp = _tp_row_proj(bp[blk._child_key(4)], hmid, model_axis,
                                   delta32=rp_delta(hmid, f"fc2{i}"))
            x = x + mlp
        xf, _ = lnf.apply(lnf_p, x[:, None])
        logits = _proj(lin_p, xf[:, 0])
        new_carry["pos"] = pos + active.astype(jnp.int32)
        return jax.nn.log_softmax(logits.astype(jnp.float32),
                                  axis=-1), new_carry

    def step(params, tokens, active, carry):
        return forward(params, tokens, active, carry)

    def sample_step(params, tokens, active, carry, knobs,
                    adapter_ids=None, bank=None):
        # fused sampling epilogue: (N, vocab) log-probs reduce to a
        # per-row token + raw-model log-prob on device (sampling.py is
        # imported lazily — serving imports models, not vice versa)
        from bigdl_tpu.serving.sampling import sample_rows

        logp, new_carry = forward(params, tokens, active, carry,
                                  adapter_ids, bank)
        tok, chosen, new_keys, new_counts = sample_rows(
            logp, carry["rng"], knobs, carry["tok_counts"],
            carry["prompt_mask"])
        # inactive rows: rng/counts bitwise untouched, same contract as
        # the K/V scatter above
        new_carry["rng"] = jnp.where(active[:, None], new_keys,
                                     carry["rng"])
        new_carry["tok_counts"] = jnp.where(active[:, None], new_counts,
                                            carry["tok_counts"])
        return tok, chosen, new_carry

    # the carry is DONATED: the engine replaces its pooled carry with the
    # step's output every token, and without donation XLA materializes a
    # complete second copy of the whole KV pool per generated token
    # (~300 MB/step at 137M/8 slots). Callers must not touch the input
    # carry after a step — read it (np.asarray) before stepping.
    if adapter is None:
        fn = sample_step if sampling else step
    elif sampling:
        # pinned adapter arity (shard_map in_specs match positionally)
        def fn(params, tokens, active, carry, knobs, adapter_ids, bank):
            return sample_step(params, tokens, active, carry, knobs,
                               adapter_ids, bank)
    else:
        def fn(params, tokens, active, carry, adapter_ids, bank):
            return forward(params, tokens, active, carry, adapter_ids,
                           bank)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.serving.sampling import knob_partition_specs
        from bigdl_tpu.utils.compat import shard_map as _shard_map

        pspecs = tp_param_specs(model, model_axis)
        cspecs = serving_carry_specs(model, sampling=sampling,
                                     data_axis=data_axis,
                                     model_axis=model_axis,
                                     kv_quant=kv_quant)
        row = P(data_axis)
        if sampling:
            in_specs = (pspecs, row, row, cspecs,
                        knob_partition_specs(data_axis))
            out_specs = (row, row, cspecs)
        else:
            in_specs = (pspecs, row, row, cspecs)
            out_specs = (row, cspecs)
        if adapter is not None:
            # per-row adapter ids shard with their rows; the bank
            # shards Megatron-style with the weights it adapts
            in_specs = in_specs + (row,
                                   adapter_bank_specs(model, model_axis))
        # check_vma/check_rep off: sampled tokens and non-head state are
        # REPLICATED over the model axis (every model chip computes the
        # identical post-psum value deterministically), which the static
        # replication checker cannot prove through the sampler's vmapped
        # random.split
        fn = _shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_vma=False)
    jitted = jax.jit(fn, donate_argnums=(3,))
    return jitted, init_carry


def make_batch_verify_step(model: Sequential, compute_dtype=None,
                           width: int = 4, mesh=None,
                           data_axis: str = "data",
                           model_axis: str = "model",
                           kv_quant: bool = False,
                           adapter=None):
    """Speculative DRAFT-AND-VERIFY step for the serving engine
    (``bigdl_tpu.serving.speculative``): one compiled program scores a
    per-row CHUNK of candidate tokens against the target model and
    advances each row by however many the target confirms — the
    multi-token generalization of :func:`make_batch_decode_step`.
    Structurally this is the masked multi-row prefill
    (:func:`make_batch_prefill_step`'s per-row start offsets already
    express "continue this row's suffix"); what is new is that EVERY
    chunk position's next-token distribution is kept and fed through
    the per-row sampler, not just the last one.

    Returns ``(verify_fn, init_carry)``; ``init_carry`` builds exactly
    the :func:`make_batch_decode_step` ``sampling=True`` carry (shared
    layout — a pool built by either hands its carry to the other).

    ``verify_fn(params, tokens, lengths, carry, knobs) ->
    (tokens_out, logps_out, n_emit, carry)``:

    * ``tokens``: (N, ``width``) 0-based ids — row r's column 0 is its
      current decode input (the engine's ``next_token``), columns
      ``1..lengths[r]-1`` are DRAFT proposals for the following
      positions; columns at and beyond ``lengths[r]`` are pad the
      program never uses;
    * ``lengths``: (N,) int32, ``0 <= lengths[r] <= width`` — how many
      chunk positions row r runs this step (``k_r`` drafts + 1).
      ``lengths[r] == 1`` is EXACTLY the plain sampled decode step
      (one input, one draw, one emission — a normal row in a mixed
      speculative/normal batch costs nothing extra), and
      ``lengths[r] == 0`` rows are pure ballast: carry bitwise
      untouched, outputs garbage (the ``active`` convention). Per-row
      lengths are runtime VALUES of one compiled (N, width) program —
      traffic mix never recompiles;
    * ``knobs``: the per-row sampling knob dict
      (:func:`~bigdl_tpu.serving.sampling.make_knob_rows`);
    * ``tokens_out``/``logps_out``: (N, width) — position j's token is
      drawn by THE one per-row sampler
      (:func:`~bigdl_tpu.serving.sampling.sample_rows`) from the
      target's next-token distribution after chunk inputs ``0..j``,
      with the row's RNG lane split once per position IN ORDER and
      penalty counts updated per draw — each position computes the
      same math the plain decode step would had the accepted prefix
      been fed token by token. (Numerics caveat, the kv_quant
      accuracy contract's sibling: the chunked path rounds reduced-
      precision activations in a different order than the single-
      token step, so at bf16 an argmax sitting on a sub-rounding
      near-tie — untrained near-uniform logits — can flip vs the
      baseline; fp32 parity is exact on the dev box, and the parity
      tests pin configs with real gaps);
    * ``n_emit``: (N,) int32 — ``1 + (leading positions whose drawn
      token equals the NEXT chunk input)``. Acceptance is
      sampled-token agreement: position j's draw is a valid emission
      iff drafts ``1..j`` all matched the draws before them (so its
      conditioning context is the true emitted stream); the first
      mismatch position still emits — its draw came from the correct
      conditional — and everything after it is discarded. For
      temperature-0 rows this is standard greedy speculative
      verification (argmax agreement), token-identical to the baseline
      engine; for sampled rows the EMITTED stream equals the baseline
      engine's stream draw for draw (same lane splits, same
      conditionals — the draft only controls how many of those draws
      land per step, never their values), which is what makes fixed
      seeds replay across speculative/normal engines and
      eviction/readmission. (This deliberately trades Leviathan-style
      distribution-matching rejection sampling — which consumes
      randomness in a draft-dependent pattern and so cannot replay the
      baseline stream — for exact stream equality; acceptance rate is
      then ``P(draft == the sampler's draw)``.)

    The carry rollback contract: K/V for ALL ``lengths[r]`` inputs are
    written at ``pos[r]..pos[r]+lengths[r]-1`` (the masked dropped-index
    scatter of the batch prefill), but ``pos`` advances by only
    ``n_emit[r]`` — positions past the accepted prefix are stale bytes
    BEHIND ``pos``, invisible to every later step (the same masking
    that makes recycled slots safe) and overwritten as decoding
    proceeds. Rollback is pointer arithmetic, not a cache rewrite.
    The RNG lane and penalty counts advance by exactly ``n_emit[r]``
    draws for the same reason.

    ``mesh``/``kv_quant`` follow :func:`make_batch_decode_step`: the
    tensor-parallel lowering shards heads/MLP hidden over
    ``model_axis`` with slot rows over ``data_axis`` (chunk outputs
    replicate over the model axis like the sampled step's). The int8
    cache path merges ACCEPTED COLUMNS ONLY: the chunk's own attention
    reads the stored cache dequantized at its CURRENT (pre-merge)
    scales with the chunk's float K/V overlaid in place, and the
    grow-only (slot, head) scale merge + quantized scatter are
    DEFERRED until ``n_emit`` is known — the amax covers emitted
    positions alone and only they are written, so a REJECTED draft can
    never touch a row's scales or stored bytes: two steps from the same
    state whose accepted outcome agrees return BITWISE-identical
    carries no matter what their rejected columns held (unit-pinned in
    tests/test_serving_kv_quant.py::test_int8_draft_independence_exact,
    with end-to-end stream equality across good/garbage drafts pinned
    beside it). The trade, tiny and documented: in-step attention sees
    the chunk's own K/V unrounded (the plain decode step reads the
    current token int8-roundtripped), so int8 spec-vs-baseline parity
    stays the pinned-config contract it always was.

    Caller contract (the engine enforces it): ``pos[r] + lengths[r] <=
    max_len`` — out-of-range columns would be silently dropped by the
    masked scatter, exactly like :func:`make_batch_prefill_step`.

    NOTE: the per-block body parallels (not shares)
    make_batch_prefill_step's loop for the same reason the decode/
    prefill pair documents — drift is pinned by the speculative parity
    tests (tests/test_serving_speculative.py: greedy outputs equal the
    baseline engine and generate()).

    ``adapter`` follows :func:`make_batch_decode_step`: the signature
    grows a trailing ``(adapter_ids, bank)`` pair and every chunk
    position's six projections add the rows' gathered low-rank delta —
    the TARGET model's verification scores each row under that ROW'S
    adapter, so accept-rate accounting can never mix an adapted target
    with the wrong factors (the engine pins drafts to the null
    adapter; see serving/speculative.py).
    """
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.misc import LookupTable

    model._ensure_params()
    mods = model.modules
    assert isinstance(mods[0], LookupTable), "TransformerLM-shaped model"
    if width < 1:
        raise ValueError(f"width must be >= 1, got {width}")
    max_len = mods[1].max_len
    vocab = mods[0].n_index
    off = _decode_head_offset(model)
    lnf = mods[-2 - off]
    _, _, blocks0, _, _ = _resolve_decode_views(model, off, model.params)
    attn0 = blocks0[0][0].attn
    heads, hd = attn0.n_heads, attn0.head_dim
    scale = hd ** -0.5
    cache_dtype = compute_dtype or jnp.float32
    tp = 1 if mesh is None else int(mesh.shape[model_axis])
    if mesh is not None:
        _check_tp_divisibility(model, heads, tp)
    heads_l = heads // tp
    S = int(width)

    init_carry = _serving_init_carry(len(blocks0), max_len, heads, hd,
                                     cache_dtype, kv_quant, True, vocab)
    _proj = _serving_proj

    def verify(params, tokens, lengths, carry, knobs, adapter_ids=None,
               bank=None):
        from bigdl_tpu.serving.sampling import sample_rows

        Pt = _cast_keep_scales(params, compute_dtype)
        lookup_w, pos_w, blocks, lnf_p, lin_p = \
            _resolve_decode_views(model, off, Pt)
        aproj, rp_delta = _adapter_proj_fns(adapter, adapter_ids, bank)
        N = tokens.shape[0]
        start = carry["pos"]                          # (N,) per-row
        rows = jnp.arange(N)
        qpos = start[:, None] + jnp.arange(S)[None]   # (N, S) absolute
        inb = jnp.arange(S)[None] < lengths[:, None]  # (N, S) valid cols
        # pad/overflow columns scatter to index max_len -> dropped
        widx = jnp.where(inb, qpos, max_len)
        x = jnp.take(lookup_w, jnp.clip(tokens, 0, lookup_w.shape[0] - 1),
                     axis=0)                          # (N, S, Hid)
        x = x + jnp.take(pos_w, jnp.clip(qpos, 0, max_len - 1), axis=0)
        new_carry = dict(carry)
        chunk_kv = []            # per-layer float chunk K/V (int8 path)
        for i, (blk, bp) in enumerate(blocks):
            h, _ = blk.ln1.apply(bp[blk._child_key(0)], x)
            ap = bp[blk._child_key(1)]
            q = aproj(ap["wq"], h, f"wq{i}").reshape(N, S, heads_l, hd)
            k = aproj(ap["wk"], h, f"wk{i}").reshape(N, S, heads_l, hd)
            v = aproj(ap["wv"], h, f"wv{i}").reshape(N, S, heads_l, hd)
            if kv_quant:
                # int8 storage, ACCEPTED-ONLY merge: the chunk attention
                # reads the stored cache dequantized at the CURRENT
                # scales with the chunk's own FLOAT K/V overlaid (cast
                # to fp32, the quantized path's attention dtype); the
                # scale merge + quantized scatter are deferred past
                # acceptance (below), so nothing a rejected draft
                # produced can reach the carry
                k32 = k.astype(jnp.float32)
                v32 = v.astype(jnp.float32)
                ks_old = new_carry[f"k{i}_scale"]
                vs_old = new_carry[f"v{i}_scale"]
                katt = (new_carry[f"k{i}"].astype(jnp.float32)
                        * ks_old[:, None, :, None]).at[
                            rows[:, None], widx].set(k32, mode="drop")
                vatt = (new_carry[f"v{i}"].astype(jnp.float32)
                        * vs_old[:, None, :, None]).at[
                            rows[:, None], widx].set(v32, mode="drop")
                qatt = (q * scale).astype(jnp.float32)
                p_dt = jnp.float32
                chunk_kv.append((k32, v32))
            else:
                kc = new_carry[f"k{i}"].at[rows[:, None], widx].set(
                    k.astype(cache_dtype), mode="drop")
                vc = new_carry[f"v{i}"].at[rows[:, None], widx].set(
                    v.astype(cache_dtype), mode="drop")
                katt, vatt = kc, vc
                qatt = (q * scale).astype(cache_dtype)
                p_dt = cache_dtype
                new_carry[f"k{i}"], new_carry[f"v{i}"] = kc, vc
            # each chunk position attends over the row's full cache
            # window under the absolute causal mask; fp32 accumulation
            s = jnp.einsum("blhd,bmhd->bhlm", qatt, katt,
                           preferred_element_type=jnp.float32)
            valid = (jnp.arange(max_len)[None, None, None, :]
                     <= qpos[:, None, :, None])
            s = jnp.where(valid, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            ctx = jnp.einsum("bhlm,bmhd->blhd", p.astype(p_dt), vatt,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype).reshape(N, S, heads_l * hd)
            if mesh is None:
                x = x + aproj(ap["wo"], ctx, f"wo{i}")
            else:
                x = x + _tp_row_proj(ap["wo"], ctx, model_axis,
                                     delta32=rp_delta(ctx, f"wo{i}"))
            h2, _ = blk.ln2.apply(bp[blk._child_key(2)], x)
            hmid = jax.nn.gelu(aproj(bp[blk._child_key(3)], h2, f"fc1{i}"))
            if mesh is None:
                mlp = aproj(bp[blk._child_key(4)], hmid, f"fc2{i}")
            else:
                mlp = _tp_row_proj(bp[blk._child_key(4)], hmid, model_axis,
                                   delta32=rp_delta(hmid, f"fc2{i}"))
            x = x + mlp
        # EVERY position's next-token distribution (the whole point —
        # prefill keeps only the last valid one)
        xf, _ = lnf.apply(lnf_p, x)
        logits = _proj(lin_p, xf)                     # (N, S, V)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # sequential per-position sampling through THE one sampler: the
        # lane splits once per position in order, penalty counts grow
        # per draw — position j computes exactly the baseline step's
        # draw for emission j. S is small and static, so the unrolled
        # chain stays one compiled program.
        keys, counts = carry["rng"], carry["tok_counts"]
        pmask = carry["prompt_mask"]
        toks_out, lps_out, key_hist = [], [], []
        for j in range(S):
            t_j, lp_j, keys, counts = sample_rows(
                logp[:, j], keys, knobs, counts, pmask)
            toks_out.append(t_j)
            lps_out.append(lp_j)
            key_hist.append(keys)
        s_tok = jnp.stack(toks_out, axis=1)           # (N, S)
        s_lp = jnp.stack(lps_out, axis=1)
        # acceptance chain: position j's draw is emitted iff every draft
        # before it matched its preceding draw (cumulative product of
        # leading matches); the first mismatch still emits — its draw
        # conditioned on the true accepted context
        if S > 1:
            match = s_tok[:, :-1] == tokens[:, 1:]
            has_draft = jnp.arange(1, S)[None] < lengths[:, None]
            acc = jnp.cumprod((match & has_draft).astype(jnp.int32),
                              axis=1)
            n_acc = jnp.sum(acc, axis=1)
        else:
            n_acc = jnp.zeros((N,), jnp.int32)
        active = lengths > 0
        n_emit = jnp.where(active, n_acc + 1, 0).astype(jnp.int32)
        if kv_quant:
            # the DEFERRED accepted-only int8 commit: amax over emitted
            # columns alone, grow-only merge, quantized scatter of
            # exactly those columns (rejected drafts leave scales AND
            # stored bytes bitwise untouched — inactive rows write
            # nothing, amax 0, so their scales pass through bitwise
            # like every other write path's inactive rows). One
            # unconditional full-row requant per layer — the same cost
            # the in-loop merge paid before the restructure.
            emit = jnp.arange(S)[None] < n_emit[:, None]      # (N, S)
            emitf = emit[:, :, None, None]
            widx_e = jnp.where(emit, qpos, max_len)
            for i, (k32, v32) in enumerate(chunk_kv):
                k_amax = jnp.max(jnp.abs(k32) * emitf, axis=(1, 3))
                v_amax = jnp.max(jnp.abs(v32) * emitf, axis=(1, 3))
                kc_rq, ks_new, ks_safe = _kv_quant_merge(
                    new_carry[f"k{i}"], new_carry[f"k{i}_scale"], k_amax)
                vc_rq, vs_new, vs_safe = _kv_quant_merge(
                    new_carry[f"v{i}"], new_carry[f"v{i}_scale"], v_amax)
                new_carry[f"k{i}"] = kc_rq.at[rows[:, None], widx_e].set(
                    _kv_quantize(k32, ks_safe[:, None, :, None]),
                    mode="drop")
                new_carry[f"v{i}"] = vc_rq.at[rows[:, None], widx_e].set(
                    _kv_quantize(v32, vs_safe[:, None, :, None]),
                    mode="drop")
                new_carry[f"k{i}_scale"] = ks_new
                new_carry[f"v{i}_scale"] = vs_new
        # lane/counts advance by EXACTLY n_emit draws. The lane: select
        # the key after the last emitted draw from the (S, N, 2) split
        # history (inactive rows stay bitwise untouched). The counts:
        # sample_rows adds exactly one_hot(draw) per call, so the state
        # after n_emit draws is counts0 + the emitted draws' one-hots —
        # S small scatters instead of materializing an (S, N, vocab)
        # history stack on the decode hot path (unemitted/inactive rows
        # add 0, staying bitwise untouched)
        kh = jnp.stack(key_hist)                      # (S, N, 2)
        idx = jnp.clip(n_emit - 1, 0, S - 1)
        new_carry["rng"] = jnp.where(active[:, None], kh[idx, rows],
                                     carry["rng"])
        new_counts = carry["tok_counts"]
        for j in range(S):
            new_counts = new_counts.at[rows, s_tok[:, j]].add(
                (j < n_emit).astype(jnp.int32))
        new_carry["tok_counts"] = new_counts
        # accepted-prefix rollback: pos advances by the emitted count
        # only — chunk writes past it are stale bytes behind the mask
        new_carry["pos"] = start + n_emit
        return s_tok, s_lp, n_emit, new_carry

    if adapter is None:
        fn = verify
    else:
        # pinned adapter arity (shard_map in_specs match positionally)
        def fn(params, tokens, lengths, carry, knobs, adapter_ids, bank):
            return verify(params, tokens, lengths, carry, knobs,
                          adapter_ids, bank)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.serving.sampling import knob_partition_specs
        from bigdl_tpu.utils.compat import shard_map as _shard_map

        cspecs = serving_carry_specs(model, sampling=True,
                                     data_axis=data_axis,
                                     model_axis=model_axis,
                                     kv_quant=kv_quant)
        row = P(data_axis)
        in_specs = (tp_param_specs(model, model_axis), row, row, cspecs,
                    knob_partition_specs(data_axis))
        if adapter is not None:
            in_specs = in_specs + (row,
                                   adapter_bank_specs(model, model_axis))
        # check_vma off for the decode step's reason: chunk draws and
        # non-head state replicate over the model axis deterministically,
        # which the static checker cannot prove through the sampler
        fn = _shard_map(fn, mesh=mesh, in_specs=in_specs,
                        out_specs=(row, row, row, cspecs),
                        check_vma=False)
    # carry donated like the decode step's: the engine swaps its pooled
    # carry for the output every super-step
    return jax.jit(fn, donate_argnums=(3,)), init_carry


# -- jitted-step cache (ADVICE r5: generate()/beam_generate() paid two
# full XLA compiles per call; the serving engine shares the same cache) --

import weakref as _weakref

_SERVING_STEPS: dict = {}          # id(model) -> {(kind, dtype): step}


def _step_cache(model: Sequential, kind: str, compute_dtype, builder,
                extra=None):
    """Per-(model, kind, compute_dtype[, extra]) cache of built serving
    steps. ``extra`` extends the key for mesh-lowered variants (a
    ``jax.sharding.Mesh`` hashes by device assignment + axis names, so
    two engines over the same mesh share one compiled program while
    different mesh shapes stay distinct).

    Keyed by ``id(model)`` with a ``weakref.finalize`` that drops the
    entry when the model is collected (a dropped model frees its
    compiled steps; a WeakKeyDictionary could NOT — the cached step
    closures strongly reference the model, so weak keys would never
    die). Dtype is keyed by name. Prompt-length buckets need no
    explicit key: the cached prefill wrapper is ONE ``jax.jit`` whose
    internal trace cache is keyed on argument shapes, so each (B, P)
    bucket compiles once and is reused across calls. The cache assumes
    the model's ARCHITECTURE is frozen after first use (the steps bake
    structure, not weights — weights ride as runtime arguments)."""
    import numpy as np

    mid = id(model)
    per_model = _SERVING_STEPS.get(mid)
    if per_model is None:
        per_model = _SERVING_STEPS[mid] = {}
        # pops the entry at gc, so a recycled id() starts fresh
        _weakref.finalize(model, _SERVING_STEPS.pop, mid, None)
    key = (kind,
           None if compute_dtype is None else np.dtype(compute_dtype).name,
           extra)
    if key not in per_model:
        per_model[key] = builder()
    return per_model[key]


def get_decode_step(model: Sequential, compute_dtype=None):
    """Cached :func:`make_decode_step` — same ``(step, init_carry)``
    tuple for repeated calls with the same (model, compute_dtype)."""
    return _step_cache(model, "decode", compute_dtype,
                       lambda: make_decode_step(model, compute_dtype))


def get_prefill_step(model: Sequential, compute_dtype=None,
                     kv_quant: bool = False):
    """Cached :func:`make_prefill_step` (one wrapper; jit re-traces per
    prompt-length bucket internally and caches each compilation).
    ``kv_quant`` selects the int8-KV-writing variant (own cache
    entry — the carries have different structures)."""
    return _step_cache(model, "prefill", compute_dtype,
                       lambda: make_prefill_step(model, compute_dtype,
                                                 kv_quant=kv_quant),
                       extra="int8" if kv_quant else None)


def get_batch_decode_step(model: Sequential, compute_dtype=None,
                          sampling: bool = False, mesh=None,
                          data_axis: str = "data",
                          model_axis: str = "model",
                          kv_quant: bool = False, adapter=None):
    """Cached :func:`make_batch_decode_step` (the serving engine's step).
    ``sampling=True`` selects the sampled-epilogue variant (its own
    cache entry — the two steps have different signatures/carries);
    ``mesh`` selects the shard_map-lowered tensor-parallel variant
    (cached per mesh); ``kv_quant`` the int8-KV variant (own entry —
    different carry structure); ``adapter`` (a hashable
    :class:`~bigdl_tpu.serving.lora.AdapterSpec`) the multi-tenant
    variant — engines sharing a (model, dtype, adapter-config) share
    one compiled program. See :func:`make_batch_decode_step`."""
    kind = "batch_decode_sample" if sampling else "batch_decode"
    extra = ("int8" if kv_quant else None,
             None if mesh is None else (mesh, data_axis, model_axis),
             adapter)
    return _step_cache(model, kind, compute_dtype,
                       lambda: make_batch_decode_step(
                           model, compute_dtype, sampling=sampling,
                           mesh=mesh, data_axis=data_axis,
                           model_axis=model_axis, kv_quant=kv_quant,
                           adapter=adapter),
                       extra=extra)


def get_batch_verify_step(model: Sequential, compute_dtype=None,
                          width: int = 4, mesh=None,
                          data_axis: str = "data",
                          model_axis: str = "model",
                          kv_quant: bool = False, adapter=None):
    """Cached :func:`make_batch_verify_step` (the speculative engine's
    one target-side program). ``width`` (the chunk width = max drafts
    + 1) keys the cache alongside the mesh/kv_quant/adapter variants —
    engines sharing a (model, dtype, width) share one compiled verify
    program, exactly like the decode step cache."""
    extra = (int(width), "int8" if kv_quant else None,
             None if mesh is None else (mesh, data_axis, model_axis),
             adapter)
    return _step_cache(model, "batch_verify", compute_dtype,
                       lambda: make_batch_verify_step(
                           model, compute_dtype, width=width, mesh=mesh,
                           data_axis=data_axis, model_axis=model_axis,
                           kv_quant=kv_quant, adapter=adapter),
                       extra=extra)


def get_batch_prefill_step(model: Sequential, compute_dtype=None,
                           mesh=None, data_axis: str = "data",
                           model_axis: str = "model",
                           carry_sampling: bool = False,
                           kv_quant: bool = False, adapter=None):
    """Cached :func:`make_batch_prefill_step` (the batched-admission
    prefill; one wrapper whose jit re-traces per (B, L) bucket).
    ``mesh``/``carry_sampling`` select the shard_map-lowered tensor-
    parallel variant (cached per mesh + carry layout); ``kv_quant``
    the int8-KV-writing variant; ``adapter`` the multi-tenant variant
    (prefill signature grows ``(adapter_ids, bank)``)."""
    extra = ("int8" if kv_quant else None,
             None if mesh is None else (mesh, data_axis, model_axis,
                                        carry_sampling),
             adapter)
    return _step_cache(model, "batch_prefill", compute_dtype,
                       lambda: make_batch_prefill_step(
                           model, compute_dtype, mesh=mesh,
                           data_axis=data_axis, model_axis=model_axis,
                           carry_sampling=carry_sampling,
                           kv_quant=kv_quant, adapter=adapter),
                       extra=extra)


def beam_generate(model: Sequential, prompt_ids, beam_size: int = 4,
                  decode_length: int = 32, eos_id: int = -1,
                  alpha: float = 0.6, compute_dtype=None):
    """Beam-search continuation of a prompt with the KV-cached decoder.

    ``prompt_ids``: (P,) 1-based word ids for ONE prompt (decode several
    prompts with separate calls — beam_search's sos is scalar). Returns
    ``(sequences (beam, decode_length) of 1-based ids, scores (beam,))``.
    ``eos_id`` is a 1-based id, or -1 for none. ``compute_dtype``
    (e.g. bf16) selects the serving precision; weights ride as runtime
    arguments either way (large models cannot bake them into the
    program — see :func:`make_decode_step`).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.nn.beam_search import beam_search

    # cached per (model, dtype) — repeated calls stop paying XLA compiles
    step, init_carry = get_decode_step(model, compute_dtype=compute_dtype)
    P = jax.device_put(serving_params(model, compute_dtype))
    prompt = [int(t) for t in prompt_ids]
    assert prompt, "need a non-empty prompt"
    max_len = model.modules[1].max_len
    if len(prompt) - 1 + decode_length > max_len:
        raise ValueError(
            f"prompt ({len(prompt)}) + decode_length ({decode_length}) "
            f"exceeds the model's max_len {max_len} — the cache position "
            "would silently clamp (same guard as PositionEmbedding)")
    K = beam_size
    carry = init_carry(K)
    # prime the cache with the prompt in ONE prefill pass (every beam
    # identical; sequential single-token priming re-reads all weights
    # per prompt token)
    if len(prompt) > 1:
        prefill = get_prefill_step(model, compute_dtype=compute_dtype)
        ptoks = jnp.tile(jnp.asarray([t - 1 for t in prompt[:-1]],
                                     jnp.int32)[None], (K, 1))
        _, carry = prefill(P, ptoks, carry)
    vocab = model.modules[0].n_index
    seqs, scores = beam_search(
        step, P, carry, 1, K, vocab, decode_length,
        sos_id=prompt[-1] - 1,
        eos_id=(eos_id - 1) if eos_id > 0 else vocab + 7,
        alpha=alpha, padding_value=-1)
    out = np.asarray(seqs)[0] + 1            # back to 1-based ids
    return out, np.asarray(scores)[0]


def generate(model: Sequential, prompt_ids, length: int = 32,
             temperature: float = 1.0, top_k: int = 0, seed: int = 0,
             compute_dtype=None, sampling=None, return_logprobs=False):
    """Sampled (or greedy) continuation with the KV-cached decoder.

    ``temperature=0`` is greedy argmax; ``top_k > 0`` restricts sampling
    to the k most likely tokens. Returns (n,) 1-based word ids (n ==
    ``length`` unless a stop set ends the run early);
    ``return_logprobs=True`` returns ``(ids, logprobs)`` with the chosen
    tokens' raw model log-probs. ``compute_dtype`` selects the serving
    precision; weights ride as runtime arguments
    (see :func:`make_decode_step`).

    ``sampling`` takes a full
    :class:`bigdl_tpu.serving.sampling.SamplingParams` (top-p,
    penalties, min/max tokens, stop sets — it overrides the
    ``temperature``/``top_k``/``seed`` scalars). The draw runs through
    the SAME per-row sampler as the serving engine
    (:func:`~bigdl_tpu.serving.sampling.sample_rows` with one row), with
    the lane seeded by the same seed → key rule — so a fixed seed yields
    the engine's token stream for the same request (to the usual float
    round-off caveat on near-tied logits).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.serving.sampling import (
        SamplingParams, get_sampler, knob_row_values, lane_key,
        match_stop_sequences,
    )

    sp = sampling if sampling is not None else SamplingParams(
        temperature=temperature, top_k=top_k, seed=seed)
    if sp.max_tokens is not None:
        length = sp.max_tokens
    # cached per (model, dtype) — repeated calls stop paying XLA compiles
    step, init_carry = get_decode_step(model, compute_dtype=compute_dtype)
    P = jax.device_put(serving_params(model, compute_dtype))
    prompt = [int(t) for t in prompt_ids]
    assert prompt, "need a non-empty prompt"
    max_len = model.modules[1].max_len
    if len(prompt) - 1 + length > max_len:
        raise ValueError(
            f"prompt ({len(prompt)}) + length ({length}) exceeds the "
            f"model's max_len {max_len} — the cache position would "
            "silently clamp (same guard as PositionEmbedding)")
    carry = init_carry(1)
    if len(prompt) > 1:
        prefill = get_prefill_step(model, compute_dtype=compute_dtype)
        ptoks = jnp.asarray([[t - 1 for t in prompt[:-1]]], jnp.int32)
        _, carry = prefill(P, ptoks, carry)

    # one-row sampler state: the engine's per-slot layout with N=1
    vocab = model.modules[0].n_index
    scal, ban_row = knob_row_values(sp, -1)
    ban_base = bool(scal["ban"])
    knobs = {k: jnp.asarray([v]) for k, v in scal.items()}
    knobs["ban_ids"] = jnp.asarray(ban_row[None])
    counts = jnp.zeros((1, vocab), jnp.int32)
    pmask = np.zeros((vocab,), bool)
    pmask[np.clip(np.asarray(prompt) - 1, 0, vocab - 1)] = True
    pmask = jnp.asarray(pmask[None])
    keys = lane_key(sp.seed if sp.seed is not None else seed)[None]
    sampler = get_sampler()

    tok = jnp.asarray([prompt[-1] - 1], jnp.int32)
    out, lps = [], []
    # min-tokens ban rides as a runtime VALUE (no retrace); with no ban
    # configured it is the constant False — upload it once, not per token
    knobs["ban"] = jnp.asarray([False])
    for i in range(length):
        logp, carry = step(P, tok, carry)
        if ban_base:
            knobs["ban"] = jnp.asarray([i < sp.min_tokens])
        tok, chosen, keys, counts = sampler(logp, keys, knobs, counts,
                                            pmask)
        t1 = int(tok[0]) + 1                 # back to 1-based ids
        out.append(t1)
        lps.append(float(chosen[0]))
        if len(out) >= sp.min_tokens and (
                t1 in sp.stop_token_ids
                or match_stop_sequences(out, sp.stop_sequences)):
            break
    ids = np.asarray(out, np.int32)
    if return_logprobs:
        return ids, np.asarray(lps, np.float32)
    return ids
