"""LeNet-5 — BASELINE config #1 (MNIST via LocalOptimizer).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/lenet/LeNet5.scala``
— Reshape → Conv(1,6,5,5) → Tanh → MaxPool → Conv(6,12,5,5) → Tanh →
MaxPool → Reshape → Linear(12*4*4,100) → Tanh → Linear(100,classNum) →
LogSoftMax. Signature kept source-compatible: ``LeNet5(class_num)``.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Linear, LogSoftMax, Reshape, Sequential, SpatialConvolution,
    SpatialMaxPooling, Tanh,
)


def LeNet5(class_num: int = 10) -> Sequential:
    model = (
        Sequential()
        .add(Reshape([1, 28, 28]))
        .add(SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5"))
        .add(Tanh())
        .add(SpatialMaxPooling(2, 2, 2, 2))
        .add(SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5"))
        .add(Tanh())
        .add(SpatialMaxPooling(2, 2, 2, 2))
        .add(Reshape([12 * 4 * 4]))
        .add(Linear(12 * 4 * 4, 100).set_name("fc1"))
        .add(Tanh())
        .add(Linear(100, class_num).set_name("fc2"))
        .add(LogSoftMax())
    )
    return model


def train_main(argv=None):
    """Reference ``models/lenet/Train.scala`` main (``--env local`` config:
    BASELINE target #1 — LeNet-5/MNIST via LocalOptimizer)."""
    from bigdl_tpu.dataset.mnist import load_samples
    from bigdl_tpu.models.utils import run_training, train_parser
    from bigdl_tpu.nn.criterion import ClassNLLCriterion

    args = train_parser("LeNet-5 on MNIST", batch_size=128,
                        learning_rate=0.05, max_epoch=5).parse_args(argv)
    samples = load_samples(args.folder or "/nonexistent", "train",
                           synthetic_count=args.synthetic)
    return run_training(LeNet5(10), samples, ClassNLLCriterion(), args)


def test_main(argv=None):
    """Reference ``models/lenet/Test.scala`` main."""
    from bigdl_tpu.dataset.mnist import load_samples
    from bigdl_tpu.models.utils import run_test, test_parser

    args = test_parser("LeNet-5 MNIST evaluation").parse_args(argv)
    samples = load_samples(args.folder or "/nonexistent", "test",
                           synthetic_count=args.synthetic)
    return run_test(args.model, samples, args.batchSize)


if __name__ == "__main__":
    train_main()
