"""AlexNet (OWT single-tower variant).

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/models/alexnet/AlexNet.scala``
— ``AlexNet(classNum)`` is the "one weird trick" single-tower layout;
``AlexNet_OWT`` drops the LRN layers. Xavier init.
"""

from __future__ import annotations

from bigdl_tpu.nn import (
    Dropout, Linear, LogSoftMax, ReLU, Reshape, Sequential,
    SpatialConvolution, SpatialCrossMapLRN, SpatialMaxPooling, Xavier, Zeros,
)


def AlexNet_OWT(class_num: int = 1000, has_dropout: bool = True,
                first_layer_propagate_back: bool = False) -> Sequential:
    model = Sequential()
    model.add(
        SpatialConvolution(
            3, 64, 11, 11, 4, 4, 2, 2,
            propagate_back=first_layer_propagate_back,
            init_weight=Xavier(), init_bias=Zeros(),
        ).set_name("conv1")
    )
    model.add(ReLU(True).set_name("relu1"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    model.add(
        SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv2")
    )
    model.add(ReLU(True).set_name("relu2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    model.add(
        SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv3")
    )
    model.add(ReLU(True).set_name("relu3"))
    model.add(
        SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv4")
    )
    model.add(ReLU(True).set_name("relu4"))
    model.add(
        SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv5")
    )
    model.add(ReLU(True).set_name("relu5"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    model.add(Reshape([256 * 6 * 6], batch_mode=True))
    model.add(Linear(256 * 6 * 6, 4096,
                     init_weight=Xavier(), init_bias=Zeros()).set_name("fc6"))
    model.add(ReLU(True).set_name("relu6"))
    if has_dropout:
        model.add(Dropout(0.5).set_name("drop6"))
    model.add(Linear(4096, 4096,
                     init_weight=Xavier(), init_bias=Zeros()).set_name("fc7"))
    model.add(ReLU(True).set_name("relu7"))
    if has_dropout:
        model.add(Dropout(0.5).set_name("drop7"))
    model.add(Linear(4096, class_num,
                     init_weight=Xavier(), init_bias=Zeros()).set_name("fc8"))
    model.add(LogSoftMax().set_name("logsoftmax"))
    return model


def AlexNet(class_num: int = 1000, has_dropout: bool = True) -> Sequential:
    """Caffe-style AlexNet (with cross-map LRN after pool1/pool2)."""
    model = Sequential()
    model.add(
        SpatialConvolution(3, 96, 11, 11, 4, 4,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv1")
    )
    model.add(ReLU(True).set_name("relu1"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm1"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    model.add(
        SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv2")
    )
    model.add(ReLU(True).set_name("relu2"))
    model.add(SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm2"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    model.add(
        SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv3")
    )
    model.add(ReLU(True).set_name("relu3"))
    model.add(
        SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv4")
    )
    model.add(ReLU(True).set_name("relu4"))
    model.add(
        SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2,
                           init_weight=Xavier(), init_bias=Zeros()).set_name("conv5")
    )
    model.add(ReLU(True).set_name("relu5"))
    model.add(SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    model.add(Reshape([256 * 6 * 6], batch_mode=True))
    model.add(Linear(256 * 6 * 6, 4096,
                     init_weight=Xavier(), init_bias=Zeros()).set_name("fc6"))
    model.add(ReLU(True).set_name("relu6"))
    if has_dropout:
        model.add(Dropout(0.5).set_name("drop6"))
    model.add(Linear(4096, 4096,
                     init_weight=Xavier(), init_bias=Zeros()).set_name("fc7"))
    model.add(ReLU(True).set_name("relu7"))
    if has_dropout:
        model.add(Dropout(0.5).set_name("drop7"))
    model.add(Linear(4096, class_num,
                     init_weight=Xavier(), init_bias=Zeros()).set_name("fc8"))
    model.add(LogSoftMax().set_name("logsoftmax"))
    return model


def train_main(argv=None):
    """Reference ``models/alexnet`` Train main (OWT variant; synthetic
    ImageNet unless ``-f`` is an image folder)."""
    from bigdl_tpu.models.utils import (
        run_training, synthetic_imagenet_samples, train_parser,
    )
    from bigdl_tpu.nn.criterion import ClassNLLCriterion

    args = train_parser("AlexNet-OWT on ImageNet", batch_size=64,
                        learning_rate=0.01, max_epoch=2).parse_args(argv)
    if args.folder:
        from bigdl_tpu.dataset.image import image_folder_samples

        samples = image_folder_samples(args.folder, image_size=224)
    else:
        samples = synthetic_imagenet_samples(args.synthetic)
    return run_training(AlexNet_OWT(1000), samples, ClassNLLCriterion(), args)


if __name__ == "__main__":
    train_main()
