"""Binary TreeLSTM — the ``treeLSTMSentiment`` example's model family.

Reference (UNVERIFIED, SURVEY.md §0): ``.../bigdl/example/treeLSTMSentiment``
+ ``nn/BinaryTreeLSTM.scala`` — constituency-tree sentiment with a binary
TreeLSTM (Tai et al. 2015), scored per-node with ``TreeNNAccuracy``.

TPU-native redesign: the reference recursively walks each tree on the JVM —
data-dependent recursion that XLA cannot trace. Here a tree is a PADDED
ARRAY ENCODING in children-before-parent topological order:

    word   (N,) int32   — 1-based token id for leaves, 0 for internal
    left   (N,) int32   — 1-based node index of left child (0 for leaves)
    right  (N,) int32   — 1-based node index of right child
    mask   (N,) f32     — 1 for real nodes, 0 for padding

One ``lax.scan`` walks the node axis, gathering child (h, c) from a state
buffer — so EVERY tree shape compiles to the same static program, batches
vmap cleanly, and the whole forest runs as one XLA computation.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from bigdl_tpu.nn.init_methods import InitializationMethod, RandomUniform
from bigdl_tpu.nn.module import AbstractModule


class BinaryTreeLSTM(AbstractModule):
    """Input Table ``[word, left, right]`` each ``(B, N)`` (mask derived
    from word/left: a node is real if it has a word or children); output
    ``(B, N, hidden)`` node hidden states in the same node order."""

    def __init__(self, vocab_size: int, embedding_dim: int, hidden_size: int,
                 init_weight: Optional[InitializationMethod] = None) -> None:
        super().__init__()
        self.vocab_size = vocab_size
        self.embedding_dim = embedding_dim
        self.hidden_size = hidden_size
        self.weight_init = init_weight or RandomUniform(-0.1, 0.1)

    def init_params(self, rng):
        import jax

        E, D, H = self.vocab_size, self.embedding_dim, self.hidden_size
        ks = jax.random.split(rng, 6)
        init = self.weight_init.init
        return {
            "embedding": init(ks[0], (E, D)),
            # leaf transform: word embedding → (i, o, u) gates
            "w_leaf": init(ks[1], (D, 3 * H)),
            "b_leaf": init(ks[2], (3 * H,)) * 0,
            # composition: [h_l, h_r] → (i, o, u, f_l, f_r) gates
            "w_comp": init(ks[3], (2 * H, 5 * H)),
            "b_comp": init(ks[4], (5 * H,)) * 0,
        }

    def apply(self, params, input, state=None, training=False, rng=None):
        import jax
        import jax.numpy as jnp

        word, left, right = (jnp.asarray(t) for t in input)
        word = word.astype(jnp.int32)
        left = left.astype(jnp.int32)
        right = right.astype(jnp.int32)
        H = self.hidden_size

        def one_tree(word, left, right):
            N = word.shape[0]
            is_leaf = (word > 0)
            # embeddings for leaves (0 row for padding/internal)
            emb_table = jnp.concatenate(
                [jnp.zeros((1, params["embedding"].shape[1]),
                           params["embedding"].dtype), params["embedding"]])
            emb = emb_table[word]                              # (N, D)

            def step(carry, idx):
                h_buf, c_buf = carry                           # (N+1, H) each
                # leaf path
                iou = emb[idx] @ params["w_leaf"] + params["b_leaf"]
                i_l = jax.nn.sigmoid(iou[:H])
                o_l = jax.nn.sigmoid(iou[H:2 * H])
                u_l = jnp.tanh(iou[2 * H:])
                c_leaf = i_l * u_l
                h_leaf = o_l * jnp.tanh(c_leaf)
                # composition path (children live BEFORE idx in node order;
                # index 0 of the buffer is the zero state)
                hl, hr = h_buf[left[idx]], h_buf[right[idx]]
                cl, cr = c_buf[left[idx]], c_buf[right[idx]]
                g = jnp.concatenate([hl, hr]) @ params["w_comp"] + params["b_comp"]
                i_c = jax.nn.sigmoid(g[:H])
                o_c = jax.nn.sigmoid(g[H:2 * H])
                u_c = jnp.tanh(g[2 * H:3 * H])
                f_l = jax.nn.sigmoid(g[3 * H:4 * H])
                f_r = jax.nn.sigmoid(g[4 * H:])
                c_comp = i_c * u_c + f_l * cl + f_r * cr
                h_comp = o_c * jnp.tanh(c_comp)

                leaf = is_leaf[idx]
                h = jnp.where(leaf, h_leaf, h_comp)
                c = jnp.where(leaf, c_leaf, c_comp)
                real = leaf | (left[idx] > 0)
                h = jnp.where(real, h, 0.0)
                c = jnp.where(real, c, 0.0)
                h_buf = h_buf.at[idx + 1].set(h)
                c_buf = c_buf.at[idx + 1].set(c)
                return (h_buf, c_buf), h

            zeros = jnp.zeros((N + 1, H))
            (_, _), hs = jax.lax.scan(step, (zeros, zeros), jnp.arange(N))
            return hs                                          # (N, H)

        out = jax.vmap(one_tree)(word, left, right)
        return out, state

    def __repr__(self) -> str:
        return (f"BinaryTreeLSTM(vocab={self.vocab_size}, "
                f"emb={self.embedding_dim}, hidden={self.hidden_size})")


def TreeLSTMSentiment(vocab_size: int, embedding_dim: int = 128,
                      hidden_size: int = 128, class_num: int = 5):
    """The treeLSTMSentiment example net: BinaryTreeLSTM → per-node
    TimeDistributed(Linear) → LogSoftMax, scored per node."""
    from bigdl_tpu.nn import LogSoftMax, Sequential, TimeDistributed
    from bigdl_tpu.nn.linear import Linear

    return (Sequential()
            .add(BinaryTreeLSTM(vocab_size, embedding_dim, hidden_size))
            .add(TimeDistributed(Linear(hidden_size, class_num)))
            .add(LogSoftMax()))
