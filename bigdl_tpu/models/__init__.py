"""bigdl_tpu.models — the model zoo (reference layer L6, SURVEY.md §2.8)."""

from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.models.vgg import VggForCifar10, Vgg_16, Vgg_19
from bigdl_tpu.models.resnet import ResNet
from bigdl_tpu.models.inception import (
    Inception_v1, Inception_v1_NoAuxClassifier, Inception_Layer_v1,
    Inception_v2, Inception_Layer_v2,
)
from bigdl_tpu.models.alexnet import AlexNet, AlexNet_OWT
from bigdl_tpu.models.autoencoder import Autoencoder
from bigdl_tpu.models.textclassifier import TextClassifier
from bigdl_tpu.models.rnn import PTBModel, SimpleRNN
from bigdl_tpu.models.transformer import (
    LayerNorm, PositionEmbedding, TransformerBlock, TransformerLM,
    beam_generate, generate, get_batch_decode_step, get_batch_prefill_step,
    get_decode_step, get_prefill_step, make_batch_decode_step,
    make_batch_prefill_step, make_decode_step, make_prefill_step,
    serving_params,
)
from bigdl_tpu.models.treelstm import BinaryTreeLSTM, TreeLSTMSentiment

__all__ = [
    "LeNet5", "VggForCifar10", "Vgg_16", "Vgg_19", "ResNet",
    "Inception_v1", "Inception_v1_NoAuxClassifier", "Inception_Layer_v1",
    "Inception_v2", "Inception_Layer_v2",
    "AlexNet", "AlexNet_OWT", "Autoencoder",
    "TextClassifier", "PTBModel", "SimpleRNN",
    "TransformerLM", "TransformerBlock", "LayerNorm", "PositionEmbedding",
    "beam_generate", "generate", "make_decode_step", "make_prefill_step",
    "make_batch_decode_step", "make_batch_prefill_step",
    "get_decode_step", "get_prefill_step",
    "get_batch_decode_step", "get_batch_prefill_step", "serving_params",
    "BinaryTreeLSTM", "TreeLSTMSentiment",
]
