from bigdl_tpu.models.lenet import LeNet5

__all__ = ["LeNet5"]
