"""Shared Train/Test option parsing for the model zoo mains.

Reference (UNVERIFIED, SURVEY.md §0): each ``models/*/Utils.scala`` — a
scopt ``OptionParser`` with the canonical knobs (``-f`` data dir, ``-b``
batchSize, ``--learningRate``, ``--maxEpoch``, ``--cache`` checkpoint dir,
``--overWrite``, model snapshot/state resume paths).

Same knob names here (argparse), plus the TPU-native additions
(``--computeDtype`` mixed precision). Every main falls back to synthetic
data when ``-f`` is absent/missing, so the zoo is runnable anywhere.
"""

from __future__ import annotations

import argparse
import logging
from typing import Optional


def train_parser(description: str, batch_size: int = 128,
                 learning_rate: float = 0.01, max_epoch: int = 10) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--folder", default=None,
                   help="data dir (synthetic data when absent)")
    p.add_argument("-b", "--batchSize", type=int, default=batch_size)
    p.add_argument("--learningRate", type=float, default=learning_rate)
    p.add_argument("--learningRateDecay", type=float, default=0.0)
    p.add_argument("--weightDecay", type=float, default=0.0)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--maxEpoch", type=int, default=max_epoch)
    p.add_argument("--maxIteration", type=int, default=None,
                   help="overrides --maxEpoch when set")
    p.add_argument("--cache", default=None, help="checkpoint directory")
    p.add_argument("--overWrite", action="store_true",
                   help="overwrite checkpoint files")
    p.add_argument("--model", default=None, help="model snapshot to resume")
    p.add_argument("--state", default=None, help="optim state snapshot")
    p.add_argument("--summary", default=None, help="TensorBoard log dir")
    p.add_argument("--computeDtype", default=None,
                   choices=["bf16", "fp16", "fp32"],
                   help="mixed-precision compute dtype")
    p.add_argument("--synthetic", type=int, default=512,
                   help="synthetic sample count when no data dir")
    return p


def test_parser(description: str, batch_size: int = 128) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--folder", default=None)
    p.add_argument("-b", "--batchSize", type=int, default=batch_size)
    p.add_argument("--model", required=True, help="model snapshot to evaluate")
    p.add_argument("--synthetic", type=int, default=512)
    return p


def configure_optimizer(opt, args):
    """Apply the shared CLI knobs onto an Optimizer (trigger, checkpoint,
    summary, dtype). Returns the optimizer."""
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.visualization import TrainSummary

    if args.maxIteration:
        opt.set_end_when(Trigger.max_iteration(args.maxIteration))
    else:
        opt.set_end_when(Trigger.max_epoch(args.maxEpoch))
    if args.cache:
        opt.set_checkpoint(args.cache, Trigger.every_epoch())
        if args.overWrite:
            opt.over_write_checkpoint()
    if args.summary:
        opt.set_train_summary(TrainSummary(args.summary, "train"))
    if args.computeDtype and args.computeDtype != "fp32":
        opt.set_compute_dtype(args.computeDtype)
    return opt


def setup_logging() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s - %(message)s")


def synthetic_imagenet_samples(count: int, seed: int = 0):
    """Random (3, 224, 224) images with 1-based 1..1000 labels — the shared
    no-data fallback for the ImageNet-scale zoo mains."""
    import numpy as np

    from bigdl_tpu.dataset.sample import Sample

    rng = np.random.default_rng(seed)
    return [Sample(rng.standard_normal((3, 224, 224)).astype(np.float32),
                   np.int32(rng.integers(1, 1001)))
            for _ in range(count)]


def run_training(model, samples, criterion, args,
                 optim_method: Optional[object] = None):
    """The shared Train.scala body: dataset → Optimizer → optimize."""
    from bigdl_tpu.nn.module import AbstractModule
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.optimizer import Optimizer

    setup_logging()
    if args.model:  # resume from snapshot
        model = AbstractModule.load(args.model)
    opt = Optimizer(model=model, dataset=samples, criterion=criterion,
                    batch_size=args.batchSize)
    method = optim_method or SGD(
        learning_rate=args.learningRate,
        learning_rate_decay=args.learningRateDecay,
        weight_decay=args.weightDecay, momentum=args.momentum)
    if args.state:
        from bigdl_tpu.optim.optim_method import OptimMethod

        method = OptimMethod.load(args.state)
    opt.set_optim_method(method)
    configure_optimizer(opt, args)
    return opt.optimize()


def run_test(model_path: str, samples, batch_size: int):
    """The shared Test.scala body: load snapshot → Top-1 evaluate."""
    from bigdl_tpu.nn.module import AbstractModule
    from bigdl_tpu.optim.validation import Top1Accuracy

    setup_logging()
    model = AbstractModule.load(model_path)
    results = model.evaluate(samples, [Top1Accuracy()], batch_size=batch_size)
    for r in results:
        logging.getLogger("bigdl_tpu").info("test result: %s", r)
    return results
