"""TensorflowLoader: frozen-GraphDef import vs live TF execution
(SURVEY.md §2.7 TF import; §4 differential-testing pattern)."""

import numpy as np
import pytest

from tests.oracle import assert_close

tf = pytest.importorskip("tensorflow")


def _freeze(fn, example):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    conc = tf.function(fn).get_concrete_function(
        tf.TensorSpec(example.shape, tf.float32))
    frozen = convert_variables_to_constants_v2(conc)
    return frozen.graph.as_graph_def(), frozen


def test_import_mlp(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    w1 = tf.Variable(rng.randn(10, 16).astype(np.float32))
    b1 = tf.Variable(rng.randn(16).astype(np.float32))
    w2 = tf.Variable(rng.randn(16, 4).astype(np.float32))
    b2 = tf.Variable(rng.randn(4).astype(np.float32))

    def mlp(x):
        h = tf.nn.relu(tf.matmul(x, w1) + b1)
        return tf.nn.softmax(tf.matmul(h, w2) + b2)

    x = rng.randn(5, 10).astype(np.float32)
    gd, frozen = _freeze(mlp, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()

    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = gd.node[-1].name
    g = load_tf(gd, [in_name], [out_name])
    got = np.asarray(g.forward(x))
    assert_close(got, want, atol=1e-4)


def test_import_convnet(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    k = tf.Variable(rng.randn(3, 3, 3, 8).astype(np.float32) * 0.2)
    b = tf.Variable(rng.randn(8).astype(np.float32) * 0.1)
    w = tf.Variable(rng.randn(8, 5).astype(np.float32) * 0.2)

    def net(x):
        h = tf.nn.conv2d(x, k, strides=[1, 2, 2, 1], padding="SAME")
        h = tf.nn.relu(tf.nn.bias_add(h, b))
        h = tf.nn.max_pool2d(h, 2, 2, "VALID")
        h = tf.reduce_mean(h, axis=[1, 2])
        return tf.matmul(h, w)

    x = rng.randn(2, 12, 12, 3).astype(np.float32)
    gd, frozen = _freeze(net, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()

    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    got = np.asarray(g.forward(x))
    assert_close(got, want, atol=1e-4)


def test_imported_graph_is_trainable(rng):
    """Imported weights are params: gradients flow and SGD moves them."""
    import jax

    from bigdl_tpu.utils.tf_loader import load_tf

    w1 = tf.Variable(rng.randn(6, 8).astype(np.float32))
    b1 = tf.Variable(rng.randn(8).astype(np.float32))

    def net(x):
        return tf.nn.tanh(tf.matmul(x, w1) + b1)

    x = rng.randn(4, 6).astype(np.float32)
    gd, _ = _freeze(net, tf.constant(x))
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    g._ensure_params()

    def loss(p):
        out, _ = g.apply(p, x, g.state)
        return (out ** 2).sum()

    grads = jax.grad(loss)(g.params)
    leaves = jax.tree_util.tree_leaves(grads)
    # ParameterOp leaves (w1, b1) must receive nonzero gradients
    nonzero = [l for l in leaves if np.abs(np.asarray(l)).sum() > 0]
    assert len(nonzero) >= 2


def test_import_depthwise_and_pad(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    k = tf.Variable(rng.randn(3, 3, 4, 1).astype(np.float32) * 0.3)

    def net(x):
        h = tf.pad(x, [[0, 0], [1, 1], [1, 1], [0, 0]])
        return tf.nn.depthwise_conv2d(h, k, strides=[1, 1, 1, 1],
                                      padding="VALID")

    x = rng.randn(2, 6, 6, 4).astype(np.float32)
    gd, frozen = _freeze(net, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_import_addn_and_unary_ops(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    def net(x):
        a = tf.nn.softplus(x)
        b = tf.nn.leaky_relu(x, alpha=0.1)
        c = tf.exp(-tf.square(x))
        return tf.add_n([a, b, c])

    x = rng.randn(3, 5).astype(np.float32)
    gd, frozen = _freeze(net, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_import_extended_op_soup(rng):
    """Differential test over the extended op set: transpose, strided
    slicing, reductions, comparisons/select, clip, pow, cast."""
    from bigdl_tpu.utils.tf_loader import load_tf

    def soup(x):
        t = tf.transpose(x, [0, 2, 1])                    # Transpose
        s = t[:, 1:4:2, :]                                # StridedSlice
        r = tf.reduce_sum(s, axis=2, keepdims=True)       # Sum
        m = tf.reduce_max(s, axis=1)                      # Max
        c = tf.where(m > 0.0, m, -m)                      # Greater + Select
        p = tf.pow(tf.abs(c) + 1.0, 2.0)                  # Abs/Pow
        q = tf.clip_by_value(p, 0.5, 4.0)                 # ClipByValue
        f = tf.floor(q) + tf.math.ceil(q) - tf.round(q)   # Floor/Ceil/Round
        cast = tf.cast(tf.cast(f, tf.int32), tf.float32)  # Cast chain
        return cast + tf.reduce_min(r, axis=[1, 2])[:, None]  # Min

    x = rng.randn(2, 5, 6).astype(np.float32)
    gd, frozen = _freeze(soup, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_import_split_multi_output(rng):
    """Multi-output Split: consumers address ports via SelectTable."""
    from bigdl_tpu.utils.tf_loader import load_tf

    def f(x):
        a, b, c = tf.split(x, 3, axis=1)                  # Split, 3 ports
        return a * 1.0 + b * 2.0 + c * 3.0

    x = rng.randn(4, 9).astype(np.float32)
    gd, frozen = _freeze(f, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-5)


def test_import_stack_unstack_tile(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    def f(x):
        rows = tf.unstack(x, axis=1)                      # Unpack, ports
        s = tf.stack([rows[0], rows[2]], axis=1)          # Pack
        return tf.tile(s, [1, 2, 1])                      # Tile

    x = rng.randn(3, 4, 5).astype(np.float32)
    gd, frozen = _freeze(f, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-5)


def test_import_elu_selu_erf_minimum(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    def f(x):
        return tf.minimum(tf.nn.elu(x), tf.nn.selu(x)) + tf.math.erf(x)

    x = rng.randn(3, 7).astype(np.float32)
    gd, frozen = _freeze(f, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_import_gather_onehot_bmm_cumsum_topk(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    table = rng.randn(10, 4).astype(np.float32)

    def f(x):
        idx = tf.argmax(x, axis=1)                       # ArgMax
        g = tf.gather(tf.constant(table), idx)           # GatherV2
        oh = tf.one_hot(idx, 6, on_value=2.0, off_value=-1.0)  # OneHot
        bm = tf.matmul(x[:, None, :], x[:, :, None])     # BatchMatMulV2
        cs = tf.cumsum(x, axis=1, exclusive=True)        # Cumsum
        vals, _ = tf.math.top_k(x, k=3)                  # TopKV2 port 0
        z = tf.zeros_like(x) + tf.ones_like(x)           # Zeros/OnesLike
        return (tf.reduce_sum(g, 1) + tf.reduce_sum(oh, 1)
                + bm[:, 0, 0] + tf.reduce_sum(cs, 1)
                + tf.reduce_sum(vals, 1) + tf.reduce_sum(z, 1)
                + tf.reduce_sum(tf.nn.log_softmax(x), 1))

    x = rng.randn(5, 6).astype(np.float32)
    gd, frozen = _freeze(f, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_identity_and_output_preserve_ports(rng):
    """Code-review regression: port suffixes survive Identity chains and can
    name graph outputs directly."""
    from bigdl_tpu.utils.tf_loader import load_tf

    def f(x):
        parts = tf.unstack(x, axis=1)
        mid = tf.identity(parts[1])          # Identity over port 1
        return tf.nn.relu(mid)

    x = rng.randn(3, 4, 5).astype(np.float32)
    gd, frozen = _freeze(f, tf.constant(x))
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    g = load_tf(gd, [in_name], [gd.node[-1].name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-6)

    # a ported OUTPUT name: ask for split's second part directly
    def f2(x):
        a, b = tf.split(x, 2, axis=1)
        return a + 0.0, b + 0.0  # keep both alive

    gd2, _ = _freeze(f2, tf.constant(x))
    in2 = [n.name for n in gd2.node if n.op == "Placeholder"][0]
    split = [n.name for n in gd2.node if n.op == "SplitV" or n.op == "Split"][0]
    g2 = load_tf(gd2, [in2], [split + ":1"])
    assert_close(np.asarray(g2.forward(x)), x[:, 2:], atol=1e-6)


def test_session_finetunes_imported_graph(rng):
    """§2.7 'limited training-graph support': an imported frozen graph keeps
    trainable weights — fine-tuning through TFSession reduces the loss."""
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.utils.tf_loader import TFSession

    w1 = tf.Variable(rng.randn(6, 16).astype(np.float32) * 0.3)
    b1 = tf.Variable(np.zeros(16, np.float32))
    w2 = tf.Variable(rng.randn(16, 3).astype(np.float32) * 0.3)
    b2 = tf.Variable(np.zeros(3, np.float32))

    def mlp(x):
        h = tf.nn.relu(tf.matmul(x, w1) + b1)
        return tf.nn.log_softmax(tf.matmul(h, w2) + b2)

    x0 = rng.randn(4, 6).astype(np.float32)
    gd, _ = _freeze(mlp, tf.constant(x0))
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    sess = TFSession(gd, [in_name], [gd.node[-1].name])

    # separable synthetic task
    centers = rng.randn(3, 6).astype(np.float32) * 2
    labels = rng.randint(1, 4, size=96)
    X = centers[labels - 1] + 0.3 * rng.randn(96, 6).astype(np.float32)
    samples = [Sample(X[i], np.int32(labels[i])) for i in range(96)]

    crit = ClassNLLCriterion()
    before = crit.forward(sess.model.forward(X[:32]), labels[:32].astype(np.float32))
    sess.train(samples, ClassNLLCriterion(), batch_size=32,
               end_trigger=Trigger.max_epoch(8),
               optim_method=SGD(learning_rate=0.2))
    after = crit.forward(sess.model.forward(X[:32]), labels[:32].astype(np.float32))
    assert after < before * 0.7, (before, after)


def test_import_resize_pad_ops(rng):
    """Round-2 op widening: ResizeBilinear/NearestNeighbor, MirrorPad,
    PadV2 — differential vs live TF."""
    from bigdl_tpu.utils.tf_loader import load_tf

    x = rng.rand(2, 5, 7, 3).astype(np.float32)

    def fn(t):
        t = tf.raw_ops.MirrorPad(input=t,
                                 paddings=[[0, 0], [1, 1], [2, 2], [0, 0]],
                                 mode="REFLECT")
        t = tf.raw_ops.PadV2(input=t,
                             paddings=[[0, 0], [1, 0], [0, 1], [0, 0]],
                             constant_values=0.5)
        t = tf.raw_ops.ResizeBilinear(images=t, size=[10, 14])
        return tf.raw_ops.ResizeNearestNeighbor(images=t, size=[5, 7])

    gd, frozen = _freeze(fn, x)
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = [n.name for n in gd.node if n.name == "Identity"
                or n.name.endswith("/Identity")][-1]
    g = load_tf(gd, [in_name], [out_name])
    got = np.asarray(g.forward(x))
    assert_close(got, want, atol=1e-5)


def test_import_space_to_batch_dilated_conv(rng):
    """SpaceToBatchND/BatchToSpaceND — the pattern TF emits for dilated
    convolutions — round-trips through a real atrous conv graph."""
    from bigdl_tpu.utils.tf_loader import load_tf

    x = rng.rand(1, 12, 12, 2).astype(np.float32)
    k = tf.constant(rng.randn(3, 3, 2, 4).astype(np.float32) * 0.3)

    def fn(t):
        # TF lowers dilation>1 conv into SpaceToBatchND/conv/BatchToSpaceND
        return tf.nn.atrous_conv2d(t, k, rate=2, padding="SAME")

    gd, frozen = _freeze(fn, x)
    ops = {n.op for n in gd.node}
    assert "SpaceToBatchND" in ops and "BatchToSpaceND" in ops, ops
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = [n.name for n in gd.node if n.name == "Identity"
                or n.name.endswith("/Identity")][-1]
    g = load_tf(gd, [in_name], [out_name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_import_rank_size(rng):
    from bigdl_tpu.utils.tf_loader import load_tf

    x = rng.rand(2, 3, 4).astype(np.float32)

    def fn(t):
        r = tf.cast(tf.raw_ops.Rank(input=t), tf.float32)
        s = tf.cast(tf.raw_ops.Size(input=t), tf.float32)
        return tf.reduce_sum(t) + r * 100.0 + s

    gd, frozen = _freeze(fn, x)
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = [n.name for n in gd.node if n.name == "Identity"
                or n.name.endswith("/Identity")][-1]
    g = load_tf(gd, [in_name], [out_name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_resize_bilinear_uint8_returns_float(rng):
    """TF semantics: bilinear resize interpolates integer images and
    returns float32."""
    from bigdl_tpu.nn.ops import ResizeBilinear

    img = (rng.rand(1, 2, 2, 1) * 255).astype(np.uint8)
    out, _ = ResizeBilinear().apply({}, [img, np.array([4, 4])])
    out = np.asarray(out)
    assert out.dtype == np.float32
    want = tf.raw_ops.ResizeBilinear(images=tf.constant(img),
                                     size=[4, 4]).numpy()
    assert_close(out, want, atol=1e-4)


def test_import_elementwise_family_and_lrn(rng):
    """Trig/log1p/isfinite family + TF LRN — differential vs live TF."""
    from bigdl_tpu.utils.tf_loader import load_tf

    x = (rng.rand(2, 4, 5, 6).astype(np.float32) - 0.5) * 0.9

    def fn(t):
        t = tf.sin(t) + tf.cos(t) * tf.atan(t) + tf.math.log1p(tf.abs(t))
        t = t + tf.asin(tf.clip_by_value(t * 0.1, -0.9, 0.9))
        t = t + tf.math.expm1(t * 0.1) + tf.sinh(t * 0.1) * tf.cosh(t * 0.1)
        t = tf.nn.local_response_normalization(
            t, depth_radius=2, bias=1.5, alpha=0.3, beta=0.6)
        return tf.where(tf.math.is_finite(t), t, tf.zeros_like(t))

    gd, frozen = _freeze(fn, x)
    assert any(n.op == "LRN" for n in gd.node)
    want = frozen(tf.constant(x))[0].numpy()
    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = [n.name for n in gd.node if n.name == "Identity"
                or n.name.endswith("/Identity")][-1]
    g = load_tf(gd, [in_name], [out_name])
    assert_close(np.asarray(g.forward(x)), want, atol=1e-4)


def test_resize_nearest_flag_conventions(rng):
    """TF NN-resize scalers differ per flag — check all three conventions
    against live TF."""
    from bigdl_tpu.nn.ops import ResizeNearestNeighbor

    img = rng.rand(1, 4, 6, 2).astype(np.float32)
    for ac, hp in ((False, False), (True, False), (False, True)):
        want = tf.raw_ops.ResizeNearestNeighbor(
            images=tf.constant(img), size=[2, 3], align_corners=ac,
            half_pixel_centers=hp).numpy()
        got, _ = ResizeNearestNeighbor(ac, hp).apply(
            {}, [img, np.array([2, 3])])
        assert_close(np.asarray(got), want, atol=0), (ac, hp)
    up = tf.raw_ops.ResizeNearestNeighbor(
        images=tf.constant(img), size=[6, 9], align_corners=True).numpy()
    got, _ = ResizeNearestNeighbor(True, False).apply(
        {}, [img, np.array([6, 9])])
    assert_close(np.asarray(got), up, atol=0)
