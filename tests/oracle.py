"""Torch-cpu oracle helpers — the analog of the reference's Torch7 `TH`
differential-test harness (SURVEY.md §4: `torch/TH.scala` pattern)."""

from __future__ import annotations

import numpy as np


def assert_close(a, b, atol=1e-4, rtol=1e-4, msg=""):
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    assert a.shape == b.shape, f"shape mismatch {a.shape} vs {b.shape} {msg}"
    np.testing.assert_allclose(a, b, atol=atol, rtol=rtol, err_msg=msg)


def torch_forward_backward(torch_module, x_np, grad_out_np=None):
    """Run a torch module fwd (+ optional bwd); returns (out, grad_in, grads)."""
    import torch

    x = torch.from_numpy(np.asarray(x_np, dtype=np.float32)).requires_grad_(True)
    out = torch_module(x)
    grad_in = None
    if grad_out_np is not None:
        g = torch.from_numpy(np.asarray(grad_out_np, dtype=np.float32))
        out.backward(g)
        grad_in = x.grad.detach().numpy()
    grads = {n: p.grad.detach().numpy() if p.grad is not None else None
             for n, p in torch_module.named_parameters()}
    return out.detach().numpy(), grad_in, grads


def finite_diff_grad(f, x, eps=1e-3):
    """Central-difference gradient of scalar f at numpy array x — the analog
    of the reference's nn/GradientChecker.scala."""
    x = np.asarray(x, dtype=np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g
