import numpy as np

from bigdl_tpu.tensor import Tensor
from tests.oracle import assert_close


def test_construct_and_meta():
    t = Tensor(2, 3)
    assert t.size() == (2, 3)
    assert t.size(1) == 2 and t.size(2) == 3
    assert t.dim() == 2
    assert t.n_element() == 6

    a = Tensor(np.arange(6.0).reshape(2, 3))
    assert a.value_at(1, 1) == 0.0
    assert a.value_at(2, 3) == 5.0


def test_fill_zero_copy_clone():
    t = Tensor(2, 2).fill(3.0)
    assert_close(t.to_numpy(), np.full((2, 2), 3.0))
    c = t.clone()
    t.zero()
    assert_close(t.to_numpy(), np.zeros((2, 2)))
    assert_close(c.to_numpy(), np.full((2, 2), 3.0))


def test_views():
    a = Tensor(np.arange(24.0).reshape(2, 3, 4))
    assert a.view(6, 4).size() == (6, 4)
    assert a.select(1, 2).size() == (3, 4)
    assert_close(a.select(1, 2).to_numpy(), np.arange(24.0).reshape(2, 3, 4)[1])
    n = a.narrow(2, 2, 2)
    assert n.size() == (2, 2, 4)
    assert_close(n.to_numpy(), np.arange(24.0).reshape(2, 3, 4)[:, 1:3])
    assert a.transpose(1, 3).size() == (4, 3, 2)
    assert a.unsqueeze(1).size() == (1, 2, 3, 4)


def test_elementwise_and_reductions():
    a = Tensor(np.array([[1.0, -2.0], [3.0, 4.0]]))
    b = Tensor(np.ones((2, 2)))
    s = a + b
    assert_close(s.to_numpy(), np.array([[2.0, -1.0], [4.0, 5.0]]))
    assert a.clone().add(2.0, b).almost_equal(
        Tensor(np.array([[3.0, 0.0], [5.0, 6.0]])), 1e-6
    )
    assert abs(a.sum() - 6.0) < 1e-6
    assert abs(a.mean() - 1.5) < 1e-6
    assert a.max() == 4.0
    vals, idx = a.max(2)
    assert_close(vals.to_numpy(), np.array([[1.0], [4.0]]))
    assert_close(idx.to_numpy(), np.array([[1], [2]]))  # 1-based


def test_matmul_paths():
    rs = np.random.RandomState(0)
    a, b = rs.randn(3, 4).astype(np.float32), rs.randn(4, 5).astype(np.float32)
    out = Tensor(3, 5).mm(Tensor(a), Tensor(b))
    assert_close(out.to_numpy(), a @ b, atol=1e-5)
    t = rs.randn(3, 5).astype(np.float32)
    out2 = Tensor(3, 5).addmm(0.5, Tensor(t), 2.0, Tensor(a), Tensor(b))
    assert_close(out2.to_numpy(), 0.5 * t + 2.0 * (a @ b), atol=1e-5)
    assert_close((Tensor(a) @ Tensor(b)).to_numpy(), a @ b, atol=1e-5)


def test_pytree_registration():
    import jax

    t = Tensor(np.ones((2, 2)))
    out = jax.jit(lambda x: x + 1.0)(t)
    assert isinstance(out, Tensor)
    assert_close(out.to_numpy(), np.full((2, 2), 2.0))


def test_virtual_device_count():
    import jax

    assert jax.device_count() == 8, "tests must see 8 virtual CPU devices"


def test_tensor_batched_and_indexing_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    a = rng.randn(3, 4, 5).astype(np.float32)
    b = rng.randn(3, 5, 2).astype(np.float32)
    assert_close(np.asarray(Tensor(a).bmm(Tensor(b))),
                 torch.bmm(torch.from_numpy(a), torch.from_numpy(b)).numpy(),
                 atol=1e-5)

    x = rng.randn(4, 6).astype(np.float32)
    idx = np.array([3, 1], np.float32)
    assert_close(np.asarray(Tensor(x).index_select(2, idx)), x[:, [2, 0]])

    gi = np.array([[1, 3], [2, 4], [1, 1], [6, 5]], np.float32)
    got = np.asarray(Tensor(x).gather(2, gi))
    want = torch.gather(torch.from_numpy(x), 1,
                        torch.from_numpy(gi).long() - 1).numpy()
    assert_close(got, want)


def test_tensor_topk_sort_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    x = rng.randn(3, 8).astype(np.float32)
    vals, idx = Tensor(x).top_k(3)
    tv, ti = torch.topk(torch.from_numpy(x), 3, dim=1)
    assert_close(np.asarray(vals), tv.numpy(), atol=1e-6)
    assert_close(np.asarray(idx), ti.numpy() + 1)

    sv, si = Tensor(x).sort(2, descending=True)
    tv2, ti2 = torch.sort(torch.from_numpy(x), dim=1, descending=True)
    assert_close(np.asarray(sv), tv2.numpy(), atol=1e-6)


def test_tensor_shape_utils(rng):
    from bigdl_tpu.tensor import Tensor

    x = rng.randn(2, 3).astype(np.float32)
    assert np.asarray(Tensor(x[0:1]).expand(4, 3)).shape == (4, 3)
    assert np.asarray(Tensor(x).repeat_tensor(2, 2)).shape == (4, 6)
    chunks = Tensor(x).split(2, dim=2)
    assert len(chunks) == 2
    assert np.asarray(chunks[1]).shape == (2, 1)
    cat = Tensor.cat([Tensor(x), Tensor(x)], dim=1)
    assert np.asarray(cat).shape == (4, 3)


def test_tensor_elementwise_extras_vs_numpy(rng):
    from bigdl_tpu.tensor import Tensor

    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    assert_close(np.asarray(Tensor(x).cmax(Tensor(y))), np.maximum(x, y))
    assert_close(np.asarray(Tensor(x).sign()), np.sign(x))
    assert_close(np.asarray(Tensor(x).addcmul(0.5, Tensor(y), Tensor(y))),
                 x + 0.5 * y * y, atol=1e-6)
    assert_close(np.asarray(Tensor(x).ge(0.0)), (x >= 0).astype(np.float32))
    assert abs(Tensor(x).std() - x.std(ddof=1)) < 1e-5
    assert_close(np.asarray(Tensor(x).cumsum(2)), np.cumsum(x, 1), atol=1e-5)

    m = (x > 0)
    assert_close(np.asarray(Tensor(x).masked_fill(m, 0.0)),
                 np.where(m, 0.0, x))
    assert_close(Tensor(x).masked_select(m), x[m])

    sc = np.asarray(Tensor(x).scatter(
        2, np.ones((3, 1), np.float32), np.full((3, 1), 9.0, np.float32)))
    want = x.copy()
    want[:, 0] = 9.0
    assert_close(sc, want)


def test_tensor_random_fills():
    from bigdl_tpu.tensor import Tensor
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    t = Tensor(500, 4)
    t.uniform(2.0, 3.0)
    arr = np.asarray(t)
    assert arr.min() >= 2.0 and arr.max() <= 3.0
    t.normal(1.0, 0.5)
    arr = np.asarray(t)
    assert abs(arr.mean() - 1.0) < 0.1 and abs(arr.std() - 0.5) < 0.1
    t.bernoulli(0.3)
    arr = np.asarray(t)
    assert set(np.unique(arr)) <= {0.0, 1.0}
    assert abs(arr.mean() - 0.3) < 0.1


# ---------------------------------------------------------------------------
# breadth batch 2 (trig/scan/linalg/index families) vs torch oracles
# ---------------------------------------------------------------------------

def test_elementwise_trig_exp_family_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    x = (rng.randn(3, 4) * 0.5).astype(np.float32)
    tx = torch.from_numpy(x)
    for name, tfn in [("sin", torch.sin), ("cos", torch.cos),
                      ("tan", torch.tan), ("asin", torch.asin),
                      ("acos", torch.acos), ("atan", torch.atan),
                      ("sinh", torch.sinh), ("cosh", torch.cosh),
                      ("expm1", torch.expm1), ("erf", torch.erf),
                      ("erfc", torch.erfc), ("rsqrt", None),
                      ("log1p", None), ("square", None),
                      ("reciprocal", torch.reciprocal)]:
        arg = np.abs(x) + 0.1 if name in ("rsqrt", "log1p", "reciprocal") else x
        t = Tensor(arg.copy())
        got = np.asarray(getattr(t, name)().data)
        if tfn is not None and name not in ("rsqrt", "log1p"):
            want = tfn(torch.from_numpy(arg)).numpy()
        elif name == "rsqrt":
            want = 1.0 / np.sqrt(arg)
        elif name == "log1p":
            want = np.log1p(arg)
        elif name == "square":
            want = arg * arg
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5,
                                   err_msg=name)


def test_lerp_fmod_atan2_dist(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    a = rng.randn(4, 3).astype(np.float32)
    b = rng.randn(4, 3).astype(np.float32) + 2.0
    got = np.asarray(Tensor(a.copy()).lerp(b, 0.3).data)
    np.testing.assert_allclose(
        got, torch.lerp(torch.from_numpy(a), torch.from_numpy(b), 0.3),
        atol=1e-6)
    got = np.asarray(Tensor(a.copy()).fmod(2.0).data)
    np.testing.assert_allclose(got, np.fmod(a, 2.0), atol=1e-6)
    got = np.asarray(Tensor(a.copy()).atan2(b).data)
    np.testing.assert_allclose(got, np.arctan2(a, b), atol=1e-6)
    d = Tensor(a).dist(b, 2.0)
    assert abs(d - np.linalg.norm((a - b).ravel())) < 1e-4


def test_reductions_scans_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    x = rng.randn(4, 5).astype(np.float32)
    tx = torch.from_numpy(x)
    np.testing.assert_allclose(np.asarray(Tensor(x.copy()).cumprod(2).data),
                               torch.cumprod(tx, 1).numpy(), atol=1e-5)
    # median along dim 2 (1-based) — torch returns lower median
    vals, idx = Tensor(x).median(2)
    tv, ti = torch.median(tx, dim=1)
    np.testing.assert_allclose(np.asarray(vals.data), tv.numpy(), atol=1e-6)
    np.testing.assert_array_equal(np.asarray(idx.data) - 1, ti.numpy())
    vals, idx = Tensor(x).kthvalue(2, 2)
    tv, ti = torch.kthvalue(tx, 2, dim=1)
    np.testing.assert_allclose(np.asarray(vals.data), tv.numpy(), atol=1e-6)
    m = Tensor(x).median()
    assert abs(float(np.asarray(m.data)) - torch.median(tx).item()) < 1e-6
    assert abs(Tensor(x).sum_all() - x.sum()) < 1e-4
    assert Tensor(x).max_all() == x.max()


def test_linalg_batch_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    m = rng.randn(4, 4).astype(np.float32)
    tm = torch.from_numpy(m)
    assert abs(Tensor(m).trace() - np.trace(m)) < 1e-5
    np.testing.assert_allclose(np.asarray(Tensor(m).diag().data), np.diag(m))
    np.testing.assert_allclose(np.asarray(Tensor(m).tril(0).data),
                               torch.tril(tm).numpy())
    np.testing.assert_allclose(np.asarray(Tensor(m).triu(1).data),
                               torch.triu(tm, 1).numpy())

    v1 = rng.randn(4).astype(np.float32)
    v2 = rng.randn(5).astype(np.float32)
    acc = Tensor(np.zeros((4, 5), np.float32)).ger(v1, v2)
    np.testing.assert_allclose(np.asarray(acc.data), np.outer(v1, v2),
                               atol=1e-6)

    b1 = rng.randn(3, 4, 5).astype(np.float32)
    b2 = rng.randn(3, 5, 6).astype(np.float32)
    out = Tensor(np.zeros((4, 6), np.float32)).addbmm(1.0, b1, b2)
    np.testing.assert_allclose(
        np.asarray(out.data),
        torch.addbmm(torch.zeros(4, 6), torch.from_numpy(b1),
                     torch.from_numpy(b2)).numpy(), atol=1e-4)

    r = Tensor((rng.randn(3, 8) * 5).astype(np.float32))
    before = np.asarray(r.data).copy()
    r.renorm(2.0, 1, 1.0)
    norms = np.linalg.norm(np.asarray(r.data), axis=1)
    assert (norms <= 1.0 + 1e-4).all()
    want = torch.renorm(torch.from_numpy(before), 2, 0, 1.0).numpy()
    np.testing.assert_allclose(np.asarray(r.data), want, atol=1e-4)


def test_conv2_xcorr2_vs_scipy_style(rng):
    from bigdl_tpu.tensor import Tensor

    img = rng.randn(6, 7).astype(np.float32)
    ker = rng.randn(3, 3).astype(np.float32)
    got = np.asarray(Tensor(img).xcorr2(ker).data)
    want = np.zeros((4, 5), np.float32)
    for i in range(4):
        for j in range(5):
            want[i, j] = (img[i:i + 3, j:j + 3] * ker).sum()
    np.testing.assert_allclose(got, want, atol=1e-4)
    got = np.asarray(Tensor(img).conv2(ker).data)
    want = np.zeros((4, 5), np.float32)
    fk = ker[::-1, ::-1]
    for i in range(4):
        for j in range(5):
            want[i, j] = (img[i:i + 3, j:j + 3] * fk).sum()
    np.testing.assert_allclose(got, want, atol=1e-4)
    assert Tensor(img).conv2(ker, "F").data.shape == (8, 9)


def test_index_family(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    x = rng.randn(5, 3).astype(np.float32)
    src = rng.randn(2, 3).astype(np.float32)
    idx = np.array([2, 4], np.int64)  # 1-based

    got = np.asarray(Tensor(x.copy()).index_add(1, idx.astype(np.float32),
                                                src).data)
    want = torch.from_numpy(x.copy()).index_add(
        0, torch.from_numpy(idx - 1), torch.from_numpy(src)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-6)

    got = np.asarray(Tensor(x.copy()).index_copy(1, idx.astype(np.float32),
                                                 src).data)
    want = x.copy()
    want[idx - 1] = src
    np.testing.assert_allclose(got, want)

    got = np.asarray(Tensor(x.copy()).index_fill(1, idx.astype(np.float32),
                                                 7.0).data)
    want = x.copy()
    want[idx - 1] = 7.0
    np.testing.assert_allclose(got, want)

    nz = np.asarray(Tensor(np.float32([[0, 1], [2, 0]])).nonzero().data)
    np.testing.assert_array_equal(nz, [[1, 2], [2, 1]])  # 1-based coords

    mc = Tensor(np.zeros((2, 2), np.float32)).masked_copy(
        np.float32([[1, 0], [0, 1]]), np.float32([5, 6]))
    np.testing.assert_allclose(np.asarray(mc.data), [[5, 0], [0, 6]])


def test_unfold_permute_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    x = rng.randn(2, 8).astype(np.float32)
    got = np.asarray(Tensor(x).unfold(2, 3, 2).data)
    want = torch.from_numpy(x).unfold(1, 3, 2).numpy()
    np.testing.assert_allclose(got, want)

    y = rng.randn(2, 3, 4).astype(np.float32)
    got = np.asarray(Tensor(y).permute(3, 1, 2).data)
    np.testing.assert_allclose(got, y.transpose(2, 0, 1))


def test_constructors_and_meta(rng):
    from bigdl_tpu.tensor import Tensor

    np.testing.assert_allclose(np.asarray(Tensor.linspace(0, 1, 5).data),
                               np.linspace(0, 1, 5), atol=1e-6)
    np.testing.assert_allclose(np.asarray(Tensor.range(1, 5, 2).data),
                               [1, 3, 5])
    a, b = Tensor(np.zeros((2, 3))), Tensor(np.ones((2, 3)))
    assert a.is_same_size_as(b)
    c = Tensor(np.ones((4,), np.float32)).resize_as(b)
    assert c.data.shape == (2, 3)
    assert Tensor(np.float32([1, 0])).any_true()
    assert not Tensor(np.float32([1, 0])).all_true()
    ne = Tensor(np.float32([1, 2])).ne(np.float32([1, 3]))
    np.testing.assert_array_equal(np.asarray(ne.data), [False, True])
