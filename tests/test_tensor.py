import numpy as np

from bigdl_tpu.tensor import Tensor
from tests.oracle import assert_close


def test_construct_and_meta():
    t = Tensor(2, 3)
    assert t.size() == (2, 3)
    assert t.size(1) == 2 and t.size(2) == 3
    assert t.dim() == 2
    assert t.n_element() == 6

    a = Tensor(np.arange(6.0).reshape(2, 3))
    assert a.value_at(1, 1) == 0.0
    assert a.value_at(2, 3) == 5.0


def test_fill_zero_copy_clone():
    t = Tensor(2, 2).fill(3.0)
    assert_close(t.to_numpy(), np.full((2, 2), 3.0))
    c = t.clone()
    t.zero()
    assert_close(t.to_numpy(), np.zeros((2, 2)))
    assert_close(c.to_numpy(), np.full((2, 2), 3.0))


def test_views():
    a = Tensor(np.arange(24.0).reshape(2, 3, 4))
    assert a.view(6, 4).size() == (6, 4)
    assert a.select(1, 2).size() == (3, 4)
    assert_close(a.select(1, 2).to_numpy(), np.arange(24.0).reshape(2, 3, 4)[1])
    n = a.narrow(2, 2, 2)
    assert n.size() == (2, 2, 4)
    assert_close(n.to_numpy(), np.arange(24.0).reshape(2, 3, 4)[:, 1:3])
    assert a.transpose(1, 3).size() == (4, 3, 2)
    assert a.unsqueeze(1).size() == (1, 2, 3, 4)


def test_elementwise_and_reductions():
    a = Tensor(np.array([[1.0, -2.0], [3.0, 4.0]]))
    b = Tensor(np.ones((2, 2)))
    s = a + b
    assert_close(s.to_numpy(), np.array([[2.0, -1.0], [4.0, 5.0]]))
    assert a.clone().add(2.0, b).almost_equal(
        Tensor(np.array([[3.0, 0.0], [5.0, 6.0]])), 1e-6
    )
    assert abs(a.sum() - 6.0) < 1e-6
    assert abs(a.mean() - 1.5) < 1e-6
    assert a.max() == 4.0
    vals, idx = a.max(2)
    assert_close(vals.to_numpy(), np.array([[1.0], [4.0]]))
    assert_close(idx.to_numpy(), np.array([[1], [2]]))  # 1-based


def test_matmul_paths():
    rs = np.random.RandomState(0)
    a, b = rs.randn(3, 4).astype(np.float32), rs.randn(4, 5).astype(np.float32)
    out = Tensor(3, 5).mm(Tensor(a), Tensor(b))
    assert_close(out.to_numpy(), a @ b, atol=1e-5)
    t = rs.randn(3, 5).astype(np.float32)
    out2 = Tensor(3, 5).addmm(0.5, Tensor(t), 2.0, Tensor(a), Tensor(b))
    assert_close(out2.to_numpy(), 0.5 * t + 2.0 * (a @ b), atol=1e-5)
    assert_close((Tensor(a) @ Tensor(b)).to_numpy(), a @ b, atol=1e-5)


def test_pytree_registration():
    import jax

    t = Tensor(np.ones((2, 2)))
    out = jax.jit(lambda x: x + 1.0)(t)
    assert isinstance(out, Tensor)
    assert_close(out.to_numpy(), np.full((2, 2), 2.0))


def test_virtual_device_count():
    import jax

    assert jax.device_count() == 8, "tests must see 8 virtual CPU devices"


def test_tensor_batched_and_indexing_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    a = rng.randn(3, 4, 5).astype(np.float32)
    b = rng.randn(3, 5, 2).astype(np.float32)
    assert_close(np.asarray(Tensor(a).bmm(Tensor(b))),
                 torch.bmm(torch.from_numpy(a), torch.from_numpy(b)).numpy(),
                 atol=1e-5)

    x = rng.randn(4, 6).astype(np.float32)
    idx = np.array([3, 1], np.float32)
    assert_close(np.asarray(Tensor(x).index_select(2, idx)), x[:, [2, 0]])

    gi = np.array([[1, 3], [2, 4], [1, 1], [6, 5]], np.float32)
    got = np.asarray(Tensor(x).gather(2, gi))
    want = torch.gather(torch.from_numpy(x), 1,
                        torch.from_numpy(gi).long() - 1).numpy()
    assert_close(got, want)


def test_tensor_topk_sort_vs_torch(rng):
    import torch

    from bigdl_tpu.tensor import Tensor

    x = rng.randn(3, 8).astype(np.float32)
    vals, idx = Tensor(x).top_k(3)
    tv, ti = torch.topk(torch.from_numpy(x), 3, dim=1)
    assert_close(np.asarray(vals), tv.numpy(), atol=1e-6)
    assert_close(np.asarray(idx), ti.numpy() + 1)

    sv, si = Tensor(x).sort(2, descending=True)
    tv2, ti2 = torch.sort(torch.from_numpy(x), dim=1, descending=True)
    assert_close(np.asarray(sv), tv2.numpy(), atol=1e-6)


def test_tensor_shape_utils(rng):
    from bigdl_tpu.tensor import Tensor

    x = rng.randn(2, 3).astype(np.float32)
    assert np.asarray(Tensor(x[0:1]).expand(4, 3)).shape == (4, 3)
    assert np.asarray(Tensor(x).repeat_tensor(2, 2)).shape == (4, 6)
    chunks = Tensor(x).split(2, dim=2)
    assert len(chunks) == 2
    assert np.asarray(chunks[1]).shape == (2, 1)
    cat = Tensor.cat([Tensor(x), Tensor(x)], dim=1)
    assert np.asarray(cat).shape == (4, 3)


def test_tensor_elementwise_extras_vs_numpy(rng):
    from bigdl_tpu.tensor import Tensor

    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    assert_close(np.asarray(Tensor(x).cmax(Tensor(y))), np.maximum(x, y))
    assert_close(np.asarray(Tensor(x).sign()), np.sign(x))
    assert_close(np.asarray(Tensor(x).addcmul(0.5, Tensor(y), Tensor(y))),
                 x + 0.5 * y * y, atol=1e-6)
    assert_close(np.asarray(Tensor(x).ge(0.0)), (x >= 0).astype(np.float32))
    assert abs(Tensor(x).std() - x.std(ddof=1)) < 1e-5
    assert_close(np.asarray(Tensor(x).cumsum(2)), np.cumsum(x, 1), atol=1e-5)

    m = (x > 0)
    assert_close(np.asarray(Tensor(x).masked_fill(m, 0.0)),
                 np.where(m, 0.0, x))
    assert_close(Tensor(x).masked_select(m), x[m])

    sc = np.asarray(Tensor(x).scatter(
        2, np.ones((3, 1), np.float32), np.full((3, 1), 9.0, np.float32)))
    want = x.copy()
    want[:, 0] = 9.0
    assert_close(sc, want)


def test_tensor_random_fills():
    from bigdl_tpu.tensor import Tensor
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    t = Tensor(500, 4)
    t.uniform(2.0, 3.0)
    arr = np.asarray(t)
    assert arr.min() >= 2.0 and arr.max() <= 3.0
    t.normal(1.0, 0.5)
    arr = np.asarray(t)
    assert abs(arr.mean() - 1.0) < 0.1 and abs(arr.std() - 0.5) < 0.1
    t.bernoulli(0.3)
    arr = np.asarray(t)
    assert set(np.unique(arr)) <= {0.0, 1.0}
    assert abs(arr.mean() - 0.3) < 0.1
