import numpy as np

from bigdl_tpu.tensor import Tensor
from tests.oracle import assert_close


def test_construct_and_meta():
    t = Tensor(2, 3)
    assert t.size() == (2, 3)
    assert t.size(1) == 2 and t.size(2) == 3
    assert t.dim() == 2
    assert t.n_element() == 6

    a = Tensor(np.arange(6.0).reshape(2, 3))
    assert a.value_at(1, 1) == 0.0
    assert a.value_at(2, 3) == 5.0


def test_fill_zero_copy_clone():
    t = Tensor(2, 2).fill(3.0)
    assert_close(t.to_numpy(), np.full((2, 2), 3.0))
    c = t.clone()
    t.zero()
    assert_close(t.to_numpy(), np.zeros((2, 2)))
    assert_close(c.to_numpy(), np.full((2, 2), 3.0))


def test_views():
    a = Tensor(np.arange(24.0).reshape(2, 3, 4))
    assert a.view(6, 4).size() == (6, 4)
    assert a.select(1, 2).size() == (3, 4)
    assert_close(a.select(1, 2).to_numpy(), np.arange(24.0).reshape(2, 3, 4)[1])
    n = a.narrow(2, 2, 2)
    assert n.size() == (2, 2, 4)
    assert_close(n.to_numpy(), np.arange(24.0).reshape(2, 3, 4)[:, 1:3])
    assert a.transpose(1, 3).size() == (4, 3, 2)
    assert a.unsqueeze(1).size() == (1, 2, 3, 4)


def test_elementwise_and_reductions():
    a = Tensor(np.array([[1.0, -2.0], [3.0, 4.0]]))
    b = Tensor(np.ones((2, 2)))
    s = a + b
    assert_close(s.to_numpy(), np.array([[2.0, -1.0], [4.0, 5.0]]))
    assert a.clone().add(2.0, b).almost_equal(
        Tensor(np.array([[3.0, 0.0], [5.0, 6.0]])), 1e-6
    )
    assert abs(a.sum() - 6.0) < 1e-6
    assert abs(a.mean() - 1.5) < 1e-6
    assert a.max() == 4.0
    vals, idx = a.max(2)
    assert_close(vals.to_numpy(), np.array([[1.0], [4.0]]))
    assert_close(idx.to_numpy(), np.array([[1], [2]]))  # 1-based


def test_matmul_paths():
    rs = np.random.RandomState(0)
    a, b = rs.randn(3, 4).astype(np.float32), rs.randn(4, 5).astype(np.float32)
    out = Tensor(3, 5).mm(Tensor(a), Tensor(b))
    assert_close(out.to_numpy(), a @ b, atol=1e-5)
    t = rs.randn(3, 5).astype(np.float32)
    out2 = Tensor(3, 5).addmm(0.5, Tensor(t), 2.0, Tensor(a), Tensor(b))
    assert_close(out2.to_numpy(), 0.5 * t + 2.0 * (a @ b), atol=1e-5)
    assert_close((Tensor(a) @ Tensor(b)).to_numpy(), a @ b, atol=1e-5)


def test_pytree_registration():
    import jax

    t = Tensor(np.ones((2, 2)))
    out = jax.jit(lambda x: x + 1.0)(t)
    assert isinstance(out, Tensor)
    assert_close(out.to_numpy(), np.full((2, 2), 2.0))


def test_virtual_device_count():
    import jax

    assert jax.device_count() == 8, "tests must see 8 virtual CPU devices"
