"""CaffeLoader: prototxt text parsing + caffemodel wire decoding + graph
building, against a hand-built module oracle (SURVEY.md §2.7 Caffe import)."""

import struct

import numpy as np

from tests.oracle import assert_close


# -- minimal protobuf ENCODER (test fixture builder) ------------------------

def _varint(x: int) -> bytes:
    out = b""
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(fnum: int, wtype: int) -> bytes:
    return _varint((fnum << 3) | wtype)


def _ld(fnum: int, payload: bytes) -> bytes:
    return _tag(fnum, 2) + _varint(len(payload)) + payload


def _blob(arr: np.ndarray) -> bytes:
    shape = b"".join(_tag(1, 0) + _varint(d) for d in arr.shape)
    data = _tag(5, 2) + _varint(arr.size * 4) + struct.pack(
        f"<{arr.size}f", *arr.reshape(-1).astype(np.float32))
    return _ld(7, shape) + data


def _layer(name: str, blobs) -> bytes:
    body = _ld(1, name.encode())
    for b in blobs:
        body += _ld(7, _blob(b))
    return _ld(100, body)


def test_prototxt_parser():
    from bigdl_tpu.utils.caffe_loader import parse_prototxt

    net = parse_prototxt("""
    name: "tiny"  # comment
    input: "data"
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
            convolution_param { num_output: 4 kernel_size: 3 stride: 2
                                pad: 1 bias_term: true } }
    layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
    """)
    assert net["name"] == ["tiny"]
    assert len(net["layer"]) == 2
    cp = net["layer"][0]["convolution_param"][0]
    assert cp["num_output"] == [4] and cp["pad"] == [1]


def test_wire_decoder_roundtrip(rng):
    from bigdl_tpu.utils.caffe_loader import parse_caffemodel

    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    b = rng.randn(4).astype(np.float32)
    buf = _layer("conv1", [w, b]) + _layer("fc", [rng.randn(2, 8).astype(np.float32)])
    blobs = parse_caffemodel(buf)
    assert set(blobs) == {"conv1", "fc"}
    assert_close(blobs["conv1"][0], w)
    assert_close(blobs["conv1"][1], b)
    assert blobs["fc"][0].shape == (2, 8)


def test_load_caffe_lenet_like(rng):
    from bigdl_tpu.nn import (
        Linear, ReLU, Sequential, SoftMax, SpatialConvolution, SpatialMaxPooling,
    )
    from bigdl_tpu.utils.caffe_loader import load_caffe

    cw = (rng.randn(4, 1, 5, 5) * 0.2).astype(np.float32)
    cb = rng.randn(4).astype(np.float32) * 0.1
    fw = (rng.randn(3, 4 * 4 * 4) * 0.2).astype(np.float32)
    fb = rng.randn(3).astype(np.float32) * 0.1

    prototxt = """
    name: "lenet-ish"
    input: "data"
    layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
            convolution_param { num_output: 4 kernel_size: 5 } }
    layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
    layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
            pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
    layer { name: "flat" type: "Reshape" bottom: "pool1" top: "flat" }
    """
    # Reshape is unsupported on purpose here — drop it and flatten manually
    prototxt = prototxt.replace(
        'layer { name: "flat" type: "Reshape" bottom: "pool1" top: "flat" }\n', "")
    model_bytes = _layer("conv1", [cw, cb]) + _layer("ip1", [fw, fb])

    g = load_caffe(prototxt, model_bytes, match_all=False)
    x = rng.rand(2, 1, 12, 12).astype(np.float32)
    got = np.asarray(g.forward(x))

    oracle = Sequential()
    conv = SpatialConvolution(1, 4, 5, 5)
    pool = SpatialMaxPooling(2, 2, 2, 2).ceil()
    oracle.add(conv).add(ReLU()).add(pool)
    oracle._ensure_params()
    key0 = oracle._child_key(0)
    oracle.params[key0] = {"weight": cw, "bias": cb}
    want = np.asarray(oracle.forward(x))
    assert_close(got, want, atol=1e-5)


def test_load_caffe_full_mlp_with_softmax(rng):
    from bigdl_tpu.utils.caffe_loader import load_caffe

    fw1 = (rng.randn(8, 6) * 0.3).astype(np.float32)
    fb1 = rng.randn(8).astype(np.float32) * 0.1
    fw2 = (rng.randn(3, 8) * 0.3).astype(np.float32)
    fb2 = rng.randn(3).astype(np.float32) * 0.1

    prototxt = """
    input: "data"
    layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
            inner_product_param { num_output: 8 } }
    layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
    layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
            inner_product_param { num_output: 3 } }
    layer { name: "prob" type: "Softmax" bottom: "ip2" top: "prob" }
    """
    model = _layer("ip1", [fw1, fb1]) + _layer("ip2", [fw2, fb2])
    g = load_caffe(prototxt, model)

    x = rng.randn(4, 6).astype(np.float32)
    got = np.asarray(g.forward(x))
    h = np.maximum(x @ fw1.T + fb1, 0)
    logits = h @ fw2.T + fb2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    want = e / e.sum(-1, keepdims=True)
    assert_close(got, want, atol=1e-5)


def test_load_caffe_batchnorm_scale(rng):
    from bigdl_tpu.utils.caffe_loader import load_caffe

    C = 3
    mean = rng.randn(C).astype(np.float32)
    var = np.abs(rng.randn(C)).astype(np.float32) + 0.5
    sf = np.array([2.0], np.float32)  # caffe scale_factor
    sw = rng.randn(C).astype(np.float32)
    sb = rng.randn(C).astype(np.float32)

    prototxt = """
    input: "data"
    layer { name: "bn" type: "BatchNorm" bottom: "data" top: "bn"
            batch_norm_param { eps: 0.001 } }
    layer { name: "sc" type: "Scale" bottom: "bn" top: "sc"
            scale_param { bias_term: true } }
    """
    model = _layer("bn", [mean * 2.0, var * 2.0, sf]) + _layer("sc", [sw, sb])
    g = load_caffe(prototxt, model)
    g.evaluate()

    x = rng.randn(2, C, 4, 4).astype(np.float32)
    got = np.asarray(g.forward(x))
    norm = (x - mean[None, :, None, None]) / np.sqrt(
        var[None, :, None, None] + 1e-3)
    want = norm * sw[None, :, None, None] + sb[None, :, None, None]
    assert_close(got, want, atol=1e-4)


def test_caffe_export_import_roundtrip(rng, tmp_path):
    """CaffePersister → CaffeLoader round-trip preserves the forward."""
    from bigdl_tpu.nn import (
        Dropout, Linear, ReLU, Sequential, SoftMax, SpatialConvolution,
        SpatialMaxPooling,
    )
    from bigdl_tpu.nn.shape_ops import Reshape
    from bigdl_tpu.utils.caffe_loader import load_caffe, save_caffe

    m = (Sequential()
         .add(SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1))
         .add(ReLU())
         .add(SpatialMaxPooling(2, 2, 2, 2)))
    m._ensure_params()
    m.evaluate()
    x = rng.rand(2, 1, 8, 8).astype(np.float32)
    want = np.asarray(m.forward(x))

    proto = str(tmp_path / "net.prototxt")
    weights = str(tmp_path / "net.caffemodel")
    save_caffe(m, proto, weights)
    g = load_caffe(proto, weights)
    g.evaluate()
    got = np.asarray(g.forward(x))
    assert_close(got, want, atol=1e-5)


def test_caffe_export_mlp_roundtrip(rng, tmp_path):
    from bigdl_tpu.nn import Linear, ReLU, Sequential, SoftMax
    from bigdl_tpu.utils.caffe_loader import load_caffe, save_caffe

    m = (Sequential().add(Linear(6, 10)).add(ReLU())
         .add(Linear(10, 3)).add(SoftMax()))
    m._ensure_params()
    m.evaluate()
    x = rng.randn(4, 6).astype(np.float32)
    want = np.asarray(m.forward(x))

    proto = str(tmp_path / "mlp.prototxt")
    weights = str(tmp_path / "mlp.caffemodel")
    save_caffe(m, proto, weights)
    g = load_caffe(proto, weights)
    got = np.asarray(g.forward(x))
    assert_close(got, want, atol=1e-5)


def test_caffe_pooling_round_mode_fidelity(rng, tmp_path):
    """Floor-mode pooling must round-trip with identical geometry."""
    from bigdl_tpu.nn import Sequential, SpatialConvolution, SpatialMaxPooling
    from bigdl_tpu.utils.caffe_loader import load_caffe, save_caffe

    m = (Sequential()
         .add(SpatialConvolution(1, 2, 3, 3))
         .add(SpatialMaxPooling(3, 3, 2, 2)))  # floor mode
    m._ensure_params()
    m.evaluate()
    x = rng.rand(1, 1, 12, 12).astype(np.float32)
    want = np.asarray(m.forward(x))

    proto = str(tmp_path / "p.prototxt")
    weights = str(tmp_path / "p.caffemodel")
    save_caffe(m, proto, weights)
    g = load_caffe(proto, weights)
    got = np.asarray(g.forward(x))
    assert got.shape == want.shape
    assert_close(got, want, atol=1e-5)


def test_caffe_flatten_power_absval(rng):
    from bigdl_tpu.utils.caffe_loader import load_caffe

    fw = (rng.randn(3, 8) * 0.3).astype(np.float32)
    prototxt = """
    input: "data"
    layer { name: "abs1" type: "AbsVal" bottom: "data" top: "abs1" }
    layer { name: "pw" type: "Power" bottom: "abs1" top: "pw"
            power_param { power: 2.0 scale: 0.5 shift: 1.0 } }
    layer { name: "flat" type: "Flatten" bottom: "pw" top: "flat" }
    layer { name: "ip" type: "InnerProduct" bottom: "flat" top: "ip"
            inner_product_param { num_output: 3 bias_term: false } }
    """
    model = _layer("ip", [fw])
    g = load_caffe(prototxt, model)
    x = rng.randn(4, 2, 2, 2).astype(np.float32)
    got = np.asarray(g.forward(x))
    want = ((np.abs(x) * 0.5 + 1.0) ** 2).reshape(4, 8) @ fw.T
    assert_close(got, want, atol=1e-4)


def test_module_level_interop_entrypoints(tmp_path, rng):
    """Reference entry points Module.loadCaffeModel / Module.loadTF exist on
    the Module base (snake_case)."""
    from bigdl_tpu.nn import Module

    assert callable(Module.load_caffe_model)
    assert callable(Module.load_tf)


def test_load_caffe_deconv_prelu_elu(rng):
    """Round-2 widening: Deconvolution (FCN-style), PReLU, ELU, BNLL,
    Exp/Log — against a torch oracle for the weighted layers."""
    import torch

    from bigdl_tpu.utils.caffe_loader import load_caffe

    dw = (rng.randn(3, 2, 4, 4) * 0.2).astype(np.float32)  # (in, out, kh, kw)
    db = rng.randn(2).astype(np.float32) * 0.1
    pw = np.abs(rng.randn(2)).astype(np.float32) * 0.3

    prototxt = """
    name: "fcn-ish"
    input: "data"
    layer { name: "up" type: "Deconvolution" bottom: "data" top: "up"
            convolution_param { num_output: 2 kernel_size: 4 stride: 2
                                pad: 1 } }
    layer { name: "prelu" type: "PReLU" bottom: "up" top: "up" }
    layer { name: "elu" type: "ELU" bottom: "up" top: "elu"
            elu_param { alpha: 0.7 } }
    layer { name: "bnll" type: "BNLL" bottom: "elu" top: "out" }
    """
    model_bytes = _layer("up", [dw, db]) + _layer("prelu", [pw])
    g = load_caffe(prototxt, model_bytes, match_all=False)

    x = rng.rand(2, 3, 6, 6).astype(np.float32)
    got = np.asarray(g.forward(x))

    t = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(dw), torch.from_numpy(db),
        stride=2, padding=1)
    t = torch.nn.functional.prelu(t, torch.from_numpy(pw))
    t = torch.nn.functional.elu(t, alpha=0.7)
    want = torch.nn.functional.softplus(t).numpy()
    assert_close(got, want, atol=1e-4)
