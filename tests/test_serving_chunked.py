"""Chunked-prefill streaming admission (bigdl_tpu/serving/chunked.py):
token-identical parity with batched admission and generate() (greedy
fp32+bf16 and fixed-seed sampled streams, across eviction/readmission),
mid-prefill fault replay / cancellation / preemption, prefix-cache
chunk skipping, the zero-extra-decode-compiles + bounded-chunk-shapes
guards, KV-pool chunk-progress lifecycle, feasibility admission
control, sharded DP parity, and the bench smoke."""

import numpy as np
import pytest

from tests.test_serving import _make_lm


def _ragged_reqs(rng, n=9, vocab=29, max_plen=30):
    """Mixed prompt lengths including a 1-token prompt and prompts much
    longer than any test chunk budget, so plans span 1..several
    chunks."""
    reqs = [([int(rng.randint(1, vocab + 1))], 4)]      # 1-token prompt
    for i in range(n - 1):
        plen = int(rng.randint(2, max_plen + 1))
        reqs.append((rng.randint(1, vocab + 1, size=(plen,)).tolist(),
                     int(rng.randint(3, 9))))
    return reqs


def _run_mode(lm, reqs, mode, dtype=None, n_slots=3, stagger=True, **kw):
    """One trace through an engine: optionally staggered submits so
    later requests land mid-flight (the readmission path), drain to
    empty, assert the free list healed."""
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=n_slots, compute_dtype=dtype,
                        admission=mode, **kw)
    if stagger:
        ids = [eng.submit(*r) for r in reqs[:n_slots]]
        eng.step(); eng.step()
        ids += [eng.submit(*r) for r in reqs[n_slots:]]
    else:
        ids = [eng.submit(*r) for r in reqs]
    res = eng.drain()
    assert eng.pool.free_slots == eng.pool.n_slots
    assert not eng.scheduler.partial
    return eng, [res[rid] for rid in ids]


# -- parity (THE acceptance contract) --------------------------------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_chunked_parity_with_batched_and_generate(dtype_name, rng):
    """Ragged staggered trace (1-token prompts through prompts many
    chunks long, fewer slots than requests so rows recycle mid-flight):
    chunked admission must be token-for-token identical to batched
    admission AND sequential generate() — streaming changes WHEN
    prompts are ingested, never what any row computes."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import generate

    dtype = None if dtype_name == "fp32" else jnp.bfloat16
    lm = _make_lm()
    reqs = _ragged_reqs(rng)
    _, outs_b = _run_mode(lm, reqs, "batched", dtype)
    _, outs_c = _run_mode(lm, reqs, "chunked", dtype, chunk_budget=7)
    for j, (prompt, n_new) in enumerate(reqs):
        want = generate(lm, prompt, length=n_new, temperature=0.0,
                        compute_dtype=dtype)
        np.testing.assert_array_equal(
            outs_c[j], want,
            err_msg=f"req {j} prompt={prompt} dtype={dtype_name}")
        np.testing.assert_array_equal(outs_c[j], outs_b[j])


def test_chunked_sampled_seed_replay(rng):
    """Fixed-seed sampled requests replay draw-for-draw across
    admission modes — including rows evicted and readmitted mid-stream
    (more requests than slots) whose chunk plans replay prompt +
    emitted tokens."""
    from bigdl_tpu.serving import SamplingParams

    lm = _make_lm()
    reqs = []
    for i in range(8):
        plen = [1, 6, 19][i % 3]
        prompt = rng.randint(1, 30, size=(plen,)).tolist()
        sp = SamplingParams(temperature=0.9, top_k=12, seed=300 + i) \
            if i % 2 else None
        reqs.append((prompt, 6, -1, sp))
    _, outs_b = _run_mode(lm, reqs, "batched", n_slots=2)
    _, outs_c = _run_mode(lm, reqs, "chunked", n_slots=2, chunk_budget=5)
    for a, b in zip(outs_b, outs_c):
        np.testing.assert_array_equal(a, b)


def test_chunked_speculative_parity(rng):
    """Chunked admission composes with draft-and-verify: the draft
    cache ingests at activation like any admission, and greedy output
    stays identical to the batched baseline engine."""
    from bigdl_tpu.serving import SpeculativeConfig

    lm = _make_lm()
    draft = _make_lm()                    # same seed -> weight-tied
    reqs = _ragged_reqs(rng, n=6)
    _, outs_b = _run_mode(lm, reqs, "batched")
    _, outs_s = _run_mode(lm, reqs, "chunked", chunk_budget=6,
                          speculative=SpeculativeConfig(draft, k=3))
    for a, b in zip(outs_b, outs_s):
        np.testing.assert_array_equal(a, b)


# -- mid-prefill teardown paths --------------------------------------------

def test_chunked_fault_replay_byte_identical(rng):
    """Injected dispatch faults (step failures + admission faults that
    land mid-chunk-plan) must leave every stream byte-identical to the
    fault-free run: a faulted chunk evicts exactly its row, which
    replays its chunks at readmission."""
    from bigdl_tpu.serving import (
        FaultInjector, ServingEngine, WatchdogConfig,
    )

    lm = _make_lm()
    reqs = _ragged_reqs(rng, n=8)

    def run(faults=None):
        eng = ServingEngine(
            lm, n_slots=3, admission="chunked", chunk_budget=8,
            watchdog=WatchdogConfig(max_retries=None), faults=faults)
        ids = [eng.submit(*r) for r in reqs]
        res = eng.drain()
        assert eng.pool.free_slots == eng.pool.n_slots
        return eng, [res[r] for r in ids]

    _, clean = run()
    for seed in (1, 2, 3):
        inj = FaultInjector(seed=seed, p_fail=0.15, p_admit_fail=0.25)
        eng, faulty = run(inj)
        assert inj.counts["admit_fail"] > 0, (
            f"seed {seed} injected no admission faults — the mid-chunk "
            "replay path went unexercised")
        for a, b in zip(clean, faulty):
            np.testing.assert_array_equal(a, b)
        assert eng.metrics.summary()["serving/retries"] > 0


def test_chunked_cancel_partial_row_frees_everything(rng):
    """Cancelling a mid-prefill PARTIAL row drops its chunk plan, frees
    its slot, resets the pool's chunk-progress fields, and never emits
    a token for it — while other rows keep serving."""
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2, admission="chunked",
                        chunk_budget=4)
    r0 = eng.submit(rng.randint(1, 30, size=(3,)).tolist(),
                    max_new_tokens=12)
    r1 = eng.submit(rng.randint(1, 30, size=(30,)).tolist(),
                    max_new_tokens=5)
    eng.step()                  # r1 admitted PARTIAL (budget went to r0)
    eng.step()                  # r1's first chunk fed
    assert eng.scheduler.partial, "expected a mid-prefill row"
    slot = next(iter(eng.scheduler.partial))
    assert eng.pool.chunk_target[slot] == 29
    assert 0 < eng.pool.chunk_done[slot] < 29
    assert eng.cancel(r1)
    assert eng.pool.chunk_done[slot] == 0
    assert eng.pool.chunk_target[slot] == 0
    # the pump-order entry goes with the plan: a recycled slot must not
    # inherit the cancelled row's queue position (it would stream ahead
    # of earlier-admitted rows)
    assert slot not in eng.admitter._plans
    assert slot not in eng.admitter._order
    eng.drain()
    assert eng.request(r1).state == "cancelled"
    assert eng.request(r1).output == []
    assert len(eng.result(r0)) == 12
    assert eng.pool.free_slots == 2


def test_chunked_preemption_composes(rng):
    """Priority preemption under chunked admission: a high-priority
    arrival evicts a RUNNING victim loss-free while other rows are
    mid-prefill; every stream still matches generate()."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2, admission="chunked",
                        chunk_budget=8, policy="priority")
    reqs = [(rng.randint(1, 30, size=(n,)).tolist(), g)
            for n, g in ((5, 8), (17, 8), (9, 4))]
    ids = [eng.submit(p, max_new_tokens=g, priority=0)
           for p, g in reqs[:2]]
    for _ in range(4):
        eng.step()
    ids.append(eng.submit(reqs[2][0], max_new_tokens=reqs[2][1],
                          priority=10))
    res = eng.drain()
    assert eng.metrics.summary().get("serving/preempted", 0) >= 1
    for rid, (p, g) in zip(ids, reqs):
        np.testing.assert_array_equal(
            res[rid], generate(lm, p, length=g, temperature=0.0),
            err_msg=f"prompt={p}")


# -- prefix cache: cached prefixes skip whole chunks -----------------------

def test_chunked_prefix_cache_skips_chunks(rng):
    """A cached prefix writes into the slot in one scatter and its
    tokens never enter the chunk plan: the second wave of a shared
    long-prefix trace streams strictly fewer chunk tokens, and outputs
    still match generate()."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2, admission="chunked",
                        chunk_budget=6, prefix_cache=True)
    sys_p = rng.randint(1, 30, size=(18,)).tolist()
    reqs = [(sys_p + rng.randint(1, 30, size=(3,)).tolist(), 5)
            for _ in range(4)]
    reqs.append((reqs[0][0], 5))                  # identical: full hit
    ids = [eng.submit(*r) for r in reqs]
    outs = eng.drain()
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            outs[rid], generate(lm, p, length=n, temperature=0.0),
            err_msg=f"prompt={p}")
    s = eng.metrics.summary()
    assert s["serving/prefix_hit_rate"] > 0
    # the first wave (2 slots, cold cache) streams two full 20-token
    # plans; every later admission hits the 18-token cached prefix and
    # chunks at most its few-token suffix — far below the no-cache
    # total of ~20 tokens per request
    assert s["serving/chunk_tokens"] < 20 * len(reqs) - 18


# -- compile guards ---------------------------------------------------------

def test_chunked_zero_extra_decode_compiles_and_bounded_chunks(rng):
    """Chunked admission adds ZERO decode programs (PARTIAL rows are
    host bookkeeping) and its chunk-prefill shapes are (1, L) buckets
    capped by the budget's bucket — bounded no matter how many distinct
    prompt lengths traffic brings."""
    from bigdl_tpu.serving import ServingEngine, bucket_len
    from tests.compile_guards import assert_compile_count

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=4, admission="chunked",
                        chunk_budget=8)
    plens = list(range(2, 26))
    rng.shuffle(plens)
    for n in plens:
        eng.submit(rng.randint(1, 30, size=(n,)).tolist(),
                   max_new_tokens=3)
    eng.drain()
    assert_compile_count(eng._step_fn, 1, what="chunked decode")
    cap = bucket_len(eng.admitter.chunk_budget, eng.max_len)
    shapes = eng.admitter.traced_shapes
    assert all(B == 1 and L <= cap for B, L in shapes), shapes
    # bucketed: far fewer shapes than distinct chunk lengths
    assert len(shapes) <= 4
    n_before = len(shapes)
    # a second wave of the same lengths re-traces NOTHING
    for n in plens:
        eng.submit(rng.randint(1, 30, size=(n,)).tolist(),
                   max_new_tokens=3)
    eng.drain()
    assert len(eng.admitter.traced_shapes) == n_before
    assert_compile_count(eng._step_fn, 1, what="repeat lengths")


# -- KV-pool chunk-progress lifecycle (the recycled-slot pin) ---------------

def test_chunk_progress_resets_with_slot():
    """``chunk_done``/``chunk_target`` follow the recycled-slot
    contract the int8 scales set: ``free()`` resets both, so a new
    occupant never inherits its predecessor's progress; ``write_prefill``
    and ``set_pos`` keep ``chunk_done`` in lockstep with the device
    ``pos``."""
    from bigdl_tpu.models.transformer import get_batch_decode_step
    from bigdl_tpu.serving import KVPool

    lm = _make_lm()
    _, init = get_batch_decode_step(lm, sampling=True)
    pool = KVPool(init, 2)
    slot = pool.alloc()
    pool.begin_chunks(slot, 0, 20)
    assert pool.chunk_remaining(slot) == 20
    prefill_like = init(1)
    pool.write_prefill(slot, prefill_like, 7)
    assert pool.chunk_done[slot] == 7 and pool.chunk_remaining(slot) == 13
    pool.free(slot)
    assert pool.chunk_done[slot] == 0 and pool.chunk_target[slot] == 0
    assert pool.chunk_remaining(slot) == 0
    # a recycled slot starts from clean progress state
    slot2 = pool.alloc()
    assert pool.chunk_done[slot2] == 0 and pool.chunk_target[slot2] == 0
    pool.set_pos(slot2, 3)
    assert pool.chunk_done[slot2] == 3
    pool.free(slot2)
    with pytest.raises(ValueError, match="not allocated"):
        pool.begin_chunks(slot2, 0, 4)
    slot3 = pool.alloc()
    with pytest.raises(ValueError, match="chunk plan"):
        pool.begin_chunks(slot3, 5, 4)            # done > target
    with pytest.raises(ValueError, match="chunk plan"):
        pool.begin_chunks(slot3, 0, pool.max_len + 1)


# -- validation -------------------------------------------------------------

def test_chunked_knob_validation():
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    with pytest.raises(ValueError, match="chunk_budget"):
        ServingEngine(lm, admission="chunked", chunk_budget=0)
    with pytest.raises(ValueError, match="chunk_budget"):
        ServingEngine(lm, admission="batched", chunk_budget=8)
    with pytest.raises(ValueError, match="admission mode"):
        ServingEngine(lm, admission="streamed")
    # chunked + prefix cache is legal; per_request + prefix cache stays
    # rejected
    ServingEngine(lm, admission="chunked", prefix_cache=True)
    with pytest.raises(ValueError, match="prefix_cache"):
        ServingEngine(lm, admission="per_request", prefix_cache=True)


# -- feasibility admission control ------------------------------------------

def test_deadline_feasibility_drops_infeasible(rng):
    """With a measured decode-step estimate, a waiting request whose
    remaining tokens cannot fit inside its deadline is dropped at
    admission (finish_reason='infeasible', counted shed + deadline-
    missed) — while a feasible same-deadline request is served. Before
    any estimate exists nothing is dropped (feasibility never
    guesses)."""
    from bigdl_tpu.serving import ServingEngine, VirtualClock

    lm = _make_lm()
    clk = VirtualClock()
    eng = ServingEngine(lm, n_slots=2, admission="chunked",
                        deadline_feasibility=True, clock=clk)
    prompt = rng.randint(1, 30, size=(4,)).tolist()
    # no estimate yet: even an absurd deadline is admitted, not dropped
    r_warm = eng.submit(prompt, max_new_tokens=3, deadline_s=1e-9)
    eng.step()
    assert eng.request(r_warm) is None or \
        eng.request(r_warm).finish_reason != "infeasible"
    eng.drain()
    # seed a deterministic estimate: 0.1 s per decode step
    for _ in range(5):
        eng.metrics.add_phase("decode_step", 0.1)
    est = eng.metrics.decode_step_estimate()
    assert est is not None and est > 0.05
    r_bad = eng.submit(prompt, max_new_tokens=30, deadline_s=1.0)
    r_ok = eng.submit(prompt, max_new_tokens=30, deadline_s=1e6)
    eng.drain()
    bad = eng.request(r_bad)
    assert bad.finish_reason == "infeasible" and bad.output == []
    assert len(eng.result(r_ok)) == 30
    s = eng.metrics.summary()
    assert s["serving/infeasible"] == 1
    assert s["serving/shed"] >= 1 and s["serving/deadline_missed"] >= 1


def test_shed_preempted_request_drops_kv_stash(rng):
    """A PREEMPTED request carries its stashed KV row back to the
    queue; shedding it there (deadline/feasibility drop) must release
    the stash — the finished ledger must never pin per-row K/V device
    arrays (the cancel() teardown contract)."""
    from bigdl_tpu.serving import ServingEngine, VirtualClock

    lm = _make_lm()
    clk = VirtualClock()
    eng = ServingEngine(lm, n_slots=1, admission="chunked",
                        policy="priority", clock=clk)
    lo = eng.submit(rng.randint(1, 30, size=(5,)).tolist(),
                    max_new_tokens=8, priority=0, deadline_s=100.0)
    eng.step(); eng.step()
    hi = eng.submit(rng.randint(1, 30, size=(4,)).tolist(),
                    max_new_tokens=4, priority=10)
    eng.step()                              # preempts lo (stash taken)
    req_lo = eng.scheduler.waiting[0]
    assert req_lo.req_id == lo and req_lo.resume_carry is not None
    clk.advance(200.0)                      # lo's deadline expires
    eng.drain()
    assert eng.request(lo).finish_reason == "deadline"
    assert eng.request(lo).resume_carry is None
    assert len(eng.result(hi)) == 4


# -- decode-stall metric -----------------------------------------------------

def test_decode_gap_metric_records_stalls(rng):
    """The decode-gap samples exist exactly when rows stayed in flight
    across consecutive decode dispatches, and the summary reports a
    p99."""
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2, admission="chunked",
                        chunk_budget=4)
    eng.submit(rng.randint(1, 30, size=(3,)).tolist(), max_new_tokens=8)
    eng.step()
    eng.submit(rng.randint(1, 30, size=(20,)).tolist(), max_new_tokens=3)
    eng.drain()
    s = eng.metrics.summary()
    assert s.get("serving/decode_gap_p99_s", 0.0) > 0.0
    gaps = eng.metrics.decode_gap_percentiles()
    assert gaps["p99"] >= gaps["p50"] >= 0.0


# -- sharded plane -----------------------------------------------------------

@pytest.mark.mesh
def test_chunked_sharded_dp_parity(rng):
    """Chunked admission on a slot-data-parallel mesh: chunks route to
    the owning shard through the pool's mesh-pinned scatter, outputs
    token-identical to the unsharded chunked engine."""
    from bigdl_tpu.serving import ServingEngine
    from bigdl_tpu.serving.sharded import emulate_cpu_devices

    emulate_cpu_devices(8)
    lm = _make_lm()
    reqs = _ragged_reqs(rng, n=9)

    def run(**kw):
        eng = ServingEngine(lm, n_slots=4, admission="chunked",
                            chunk_budget=6, **kw)
        ids = [eng.submit(*r) for r in reqs]
        res = eng.drain()
        assert eng.pool.free_slots == 4
        return [res[r] for r in ids]

    plain = run()
    sharded = run(parallelism={"data": 4})
    for a, b in zip(plain, sharded):
        np.testing.assert_array_equal(a, b)


# -- bench smoke -------------------------------------------------------------

def test_chunked_bench_smoke():
    """The chunked scenario's contracts hold at smoke size: outputs
    match, equal compile counts, stall p99 shrinks (the in-bench
    asserts), and the report carries the chunk/stall metrics."""
    import benchmarks.serving_bench as sb

    out = sb.run_chunked(n_steady=2, n_burst=4, steady_gen=24,
                         burst_gen=4, burst_plen=64, n_slots=8,
                         chunk_budget=16)
    assert out["outputs_match"]
    assert out["chunked"]["decode_programs"] == \
        out["batched"]["decode_programs"]
    assert out["chunked"]["programs_total"] == \
        out["batched"]["programs_total"]
    assert out["stall_p99_improvement"] > 1.0
    assert out["chunked"]["chunks"] > 0
    assert out["chunked"]["decode_gap_p99_ms"] > 0
