"""Accuracy-parity harness — the north star's second clause (BASELINE.json
"top-1 parity"; round-1 verdict missing #2; reference
``models/lenet/Train.scala`` + ``optim/Top1Accuracy``).

A deterministic learnable digit dataset is written as REAL idx files on
disk (exercising the real MNIST reader, not the synthetic fallback),
LeNet-5 trains end-to-end through the real Optimizer harness to a fixed
Top-1 bar, and an architecturally identical torch model — same initial
weights, same batch stream, same SGD — must land within a documented
tolerance of the same final accuracy."""

import numpy as np
import pytest

pytestmark = pytest.mark.integration

BATCH = 128
STEPS = 160          # 5 epochs of 4096 samples
LR = 0.1
ACCURACY_BAR = 0.97  # convergence contract
PARITY_TOL = 0.02    # |jax - torch| final Top-1, documented tolerance


@pytest.fixture(scope="module")
def idx_dir(tmp_path_factory):
    from bigdl_tpu.dataset.mnist import generate_idx_dataset

    d = tmp_path_factory.mktemp("mnist_idx")
    # noise 220 lands LeNet at ~97.8% — comfortably above the 0.97 bar
    # but BELOW 100%, so the torch-parity comparison is a sharp signal
    # (at the old noise both models scored 1.0 and parity was vacuous)
    generate_idx_dataset(str(d), n_train=4096, n_test=1024, seed=7,
                         noise=220.0)
    return str(d)


def _train_stream(idx_dir, n_batches):
    """The deterministic batch stream both frameworks train on."""
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.image import GreyImgNormalizer
    from bigdl_tpu.dataset.mnist import TRAIN_MEAN, TRAIN_STD, load_samples

    samples = load_samples(idx_dir, "train", synthetic_fallback=False)
    assert len(samples) == 4096  # real files were read
    ds = (DataSet.array(samples, seed=11)
          .transform(GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD))
          .transform(SampleToMiniBatch(BATCH)))
    it = ds.data(train=True)
    return [next(it) for _ in range(n_batches)]


def _val_arrays(idx_dir):
    from bigdl_tpu.dataset.image import GreyImgNormalizer
    from bigdl_tpu.dataset.mnist import TRAIN_MEAN, TRAIN_STD, load_samples

    samples = load_samples(idx_dir, "test", synthetic_fallback=False)
    norm = GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD)
    xs = np.stack([np.asarray(s.feature()) for s in norm(iter(samples))])
    ys = np.array([int(s.label()) for s in samples], np.int64)  # 1-based
    return xs.astype(np.float32), ys


def _named_params(model):
    """name → param dict for the four weighted LeNet layers."""
    out = {}

    def walk(mods, params):
        for i, m in enumerate(mods):
            key = next((k for k in params if k.split(":")[0] == str(i)), None)
            if key is None:
                continue
            sub = params[key]
            if m.sub_modules():
                walk(m.sub_modules(), sub)
            elif isinstance(sub, dict) and sub:
                out[m.name or key] = sub

    walk(model.sub_modules(), model.params)
    return out


def test_lenet_convergence_and_torch_parity(idx_dir):
    import torch
    import torch.nn as tnn

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import Evaluator
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(42)
    model = LeNet5(10)
    model._ensure_params()
    init = _named_params(model)
    assert set(init) == {"conv1_5x5", "conv2_5x5", "fc1", "fc2"}
    init_np = {k: {kk: np.array(vv) for kk, vv in v.items()}
               for k, v in init.items()}

    batches = _train_stream(idx_dir, STEPS)

    # --- bigdl_tpu: the real Optimizer harness over the same stream ------
    opt = Optimizer(model=model, dataset=DataSet.array(batches),
                    criterion=ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(STEPS))
    opt.set_optim_method(SGD(learning_rate=LR))
    trained = opt.optimize()

    # the exact order the optimizer consumed (deterministic seed-0 stream)
    it = DataSet.array(batches).data(train=True)
    torch_order = [next(it) for _ in range(STEPS)]

    xs, ys = _val_arrays(idx_dir)
    res = Evaluator(trained).test(
        [b for b in _as_minibatches(xs, ys)], [Top1Accuracy()], BATCH)[0]
    jax_acc, n_scored = res.result()
    assert n_scored == len(ys)
    assert jax_acc >= ACCURACY_BAR, f"Top-1 {jax_acc:.4f} < {ACCURACY_BAR}"

    # --- torch: identical arch, identical init, identical batches --------
    tmodel = tnn.Sequential(
        tnn.Conv2d(1, 6, 5), tnn.Tanh(), tnn.MaxPool2d(2, 2),
        tnn.Conv2d(6, 12, 5), tnn.Tanh(), tnn.MaxPool2d(2, 2),
        tnn.Flatten(),
        tnn.Linear(12 * 4 * 4, 100), tnn.Tanh(),
        tnn.Linear(100, 10), tnn.LogSoftmax(dim=1),
    ).double()
    with torch.no_grad():
        pairs = [(0, "conv1_5x5"), (3, "conv2_5x5"), (7, "fc1"), (9, "fc2")]
        for ti, name in pairs:
            tmodel[ti].weight.copy_(
                torch.from_numpy(init_np[name]["weight"]).double())
            tmodel[ti].bias.copy_(
                torch.from_numpy(init_np[name]["bias"]).double())

    topt = torch.optim.SGD(tmodel.parameters(), lr=LR)
    lossf = tnn.NLLLoss()
    for b in torch_order:
        x = torch.from_numpy(np.asarray(b.get_input())).double()
        y = torch.from_numpy(
            np.asarray(b.get_target()).astype(np.int64) - 1)  # 0-based
        topt.zero_grad()
        loss = lossf(tmodel(x), y)
        loss.backward()
        topt.step()

    with torch.no_grad():
        pred = tmodel(torch.from_numpy(xs).double()).argmax(1).numpy()
    torch_acc = float((pred == ys - 1).mean())

    assert abs(jax_acc - torch_acc) <= PARITY_TOL, (
        f"final Top-1 parity broken: jax {jax_acc:.4f} vs "
        f"torch {torch_acc:.4f} (tol {PARITY_TOL})")


def _as_minibatches(xs, ys):
    from bigdl_tpu.dataset.sample import MiniBatch

    for i in range(0, len(xs), BATCH):
        yield MiniBatch(xs[i:i + BATCH], ys[i:i + BATCH].astype(np.float32))


def test_real_reader_roundtrip(idx_dir):
    """The files on disk parse back bit-identically through the real
    reader (writer/reader contract)."""
    from bigdl_tpu.dataset.mnist import (
        _synthetic_digits, read_data_sets,
    )

    imgs, labels = read_data_sets(idx_dir, "train", synthetic_fallback=False)
    want_imgs, want_labels = _synthetic_digits(4096, 7, noise=220.0)
    assert imgs.shape == (4096, 28, 28) and imgs.dtype == np.uint8
    np.testing.assert_array_equal(imgs, want_imgs)
    np.testing.assert_array_equal(labels, want_labels)
