"""transform.vision.image pipeline (SURVEY.md §2.5 later-0.x vision path)."""

import numpy as np
import pytest

from tests.oracle import assert_close


def _img(rng, h=12, w=10):
    return (rng.rand(h, w, 3) * 255).astype(np.float32)


@pytest.fixture
def rng():
    return np.random.RandomState(7)


def test_geometry_chain(rng):
    from bigdl_tpu.transform.vision.image import (
        CenterCrop, ImageFrame, Resize,
    )

    frame = ImageFrame.array([_img(rng), _img(rng)], labels=[1, 2])
    out = frame.transform(Resize(16, 16) >> CenterCrop(8, 8))
    mats = out.get_image()
    assert all(m.shape == (8, 8, 3) for m in mats)
    assert out.get_label() == [1, 2]


def test_random_crop_and_flip_deterministic(rng):
    from bigdl_tpu.transform.vision.image import HFlip, ImageFrame, RandomCrop

    img = _img(rng)
    frame = ImageFrame.array([img], seed=3)
    a = frame.transform(RandomCrop(6, 6)).get_image()[0]
    b = frame.transform(RandomCrop(6, 6)).get_image()[0]
    assert_close(a, b)  # same seed, same crop

    flipped = ImageFrame.array([img]).transform(HFlip()).get_image()[0]
    assert_close(flipped, img[:, ::-1])


def test_photometric_ops(rng):
    from bigdl_tpu.transform.vision.image import (
        Brightness, ChannelNormalize, ChannelOrder, Contrast, ImageFeature,
        PixelNormalizer, Saturation,
    )

    img = _img(rng)
    r = np.random.RandomState(0)
    out = Brightness(10, 10).apply_feature(ImageFeature(img), r).mat()
    assert_close(out, img + 10.0, atol=1e-4)
    out = Contrast(2.0, 2.0).apply_feature(ImageFeature(img), r).mat()
    assert_close(out, img * 2.0, atol=1e-3)
    out = Saturation(0.0, 0.0).apply_feature(ImageFeature(img), r).mat()
    assert_close(out, np.broadcast_to(img.mean(2, keepdims=True), img.shape),
                 atol=1e-3)
    out = ChannelOrder().apply_feature(ImageFeature(img), r).mat()
    assert_close(out, img[:, :, ::-1])
    out = ChannelNormalize(1.0, 2.0, 3.0, 2.0, 2.0, 2.0).apply_feature(
        ImageFeature(img), r).mat()
    assert_close(out, (img - [1, 2, 3]) / 2.0, atol=1e-4)
    out = PixelNormalizer(img).apply_feature(ImageFeature(img), r).mat()
    assert_close(out, np.zeros_like(img))


def test_expand_and_random_transformer(rng):
    from bigdl_tpu.transform.vision.image import (
        Expand, HFlip, ImageFeature, RandomTransformer,
    )

    img = _img(rng)
    r = np.random.RandomState(1)
    out = Expand(2.0).apply_feature(ImageFeature(img), r).mat()
    assert out.shape[0] >= img.shape[0] and out.shape[1] >= img.shape[1]

    # p=0 never applies, p=1 always applies
    same = RandomTransformer(HFlip(), 0.0).apply_feature(
        ImageFeature(img), np.random.RandomState(0)).mat()
    assert_close(same, img)
    flip = RandomTransformer(HFlip(), 1.0).apply_feature(
        ImageFeature(img), np.random.RandomState(0)).mat()
    assert_close(flip, img[:, ::-1])


def test_to_sample_pipeline_end_to_end(rng, tmp_path):
    from PIL import Image

    from bigdl_tpu.transform.vision.image import (
        CenterCrop, ChannelNormalize, ImageFrame, ImageFrameToSample,
        MatToTensor, Resize,
    )

    # write a tiny image directory and run the read→aug→sample pipeline
    for i in range(3):
        arr = (np.random.RandomState(i).rand(20, 24, 3) * 255).astype(np.uint8)
        Image.fromarray(arr).save(tmp_path / f"im{i}.png")
    frame = ImageFrame.read(str(tmp_path))
    assert len(frame) == 3
    pipeline = (Resize(16, 16) >> CenterCrop(8, 8)
                >> ChannelNormalize(120.0, 120.0, 120.0, 60.0, 60.0, 60.0)
                >> MatToTensor() >> ImageFrameToSample(target_keys=None))
    out = frame.transform(pipeline)
    samples = out.get_sample()
    assert len(samples) == 3
    feat = np.asarray(samples[0].features[0] if isinstance(
        samples[0].features, list) else samples[0].features)
    assert feat.shape == (3, 8, 8)


def test_aspect_scale(rng):
    from bigdl_tpu.transform.vision.image import AspectScale, ImageFeature

    img = _img(rng, h=10, w=20)
    out = AspectScale(5).apply_feature(
        ImageFeature(img), np.random.RandomState(0)).mat()
    assert out.shape[0] == 5 and out.shape[1] == 10  # short side → 5


def test_predict_image_attaches_predictions():
    """Reference ``model.predict_image(image_frame)``: every ImageFeature
    gets its forward output under 'predict'; batched outputs must equal
    one-shot prediction."""
    import numpy as np

    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.transform.vision.image import ImageFrame, MatToTensor
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(3)
    rs = np.random.RandomState(0)
    mats = [rs.rand(28, 28, 1).astype(np.float32) for _ in range(5)]
    frame = ImageFrame.array(mats).transform(MatToTensor())

    model = LeNet5(10)
    out_frame = model.predict_image(frame, batch_per_partition=2)
    assert out_frame is frame
    preds = [f["predict"] for f in frame.features]
    assert all(p.shape == (10,) for p in preds)

    batch = np.stack([f["floats"] for f in frame.features])
    want = np.asarray(model.predict(batch))
    np.testing.assert_allclose(np.stack(preds), want, rtol=1e-5,
                               atol=1e-6)

    # share_buffer accepted; output_layer refused; missing tensors refused
    model.predict_image(frame, share_buffer=True)
    import pytest

    with pytest.raises(NotImplementedError):
        model.predict_image(frame, output_layer="conv1")
    with pytest.raises(ValueError, match="MatToTensor"):
        model.predict_image(ImageFrame.array(mats))


def test_predict_image_multi_output_graph():
    """Multi-output Graph models attach a list of outputs per feature."""
    import numpy as np

    from bigdl_tpu.nn import Graph, Input, Linear, Reshape
    from bigdl_tpu.transform.vision.image import ImageFrame, MatToTensor
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    inp = Input()
    flat = Reshape([12], batch_mode=True).inputs(inp)
    h1 = Linear(12, 3).inputs(flat)
    h2 = Linear(12, 2).inputs(flat)
    model = Graph(inp, [h1, h2])

    rs = np.random.RandomState(1)
    mats = [rs.rand(2, 2, 3).astype(np.float32) for _ in range(5)]
    frame = ImageFrame.array(mats).transform(MatToTensor())
    model.predict_image(frame, batch_per_partition=2)
    for f in frame.features:
        preds = f["predict"]
        assert isinstance(preds, list) and len(preds) == 2
        assert preds[0].shape == (3,) and preds[1].shape == (2,)
