"""Engine-layer utilities: Table/T(), File, RandomGenerator, LoggerFilter
(SURVEY.md §2.6 rows)."""

import logging
import os

import numpy as np


def test_table_reference_semantics():
    from bigdl_tpu.utils.table import T, Table

    t = T(10, 20, 30)                 # 1-based integer keys
    assert t[1] == 10 and t[2] == 20 and t[3] == 30
    assert len(t) == 3 and t.length() == 3
    assert 2 in t and 7 not in t

    t["epoch"] = 4                    # string keys (optimMethod state style)
    assert t("epoch") == 4            # call-style access
    assert t.get("missing", -1) == -1
    assert t.get_or_update("neval", 0) == 0
    assert t["neval"] == 0

    t.insert(40)                      # appends at next free int index
    assert t[4] == 40
    t.remove(4)
    assert 4 not in t
    t2 = Table().update({"a": 1})
    assert t2["a"] == 1
    assert list(T(1, 2)) == [1, 2]    # iterates values


def test_file_save_load_roundtrip(tmp_path):
    import jax.numpy as jnp

    from bigdl_tpu.utils.file_io import File

    obj = {"weights": jnp.arange(6.0).reshape(2, 3), "epoch": 3,
           "nested": {"lr": 0.1}}
    path = str(tmp_path / "snap.bigdl")
    File.save(obj, path)
    back = File.load(path)
    np.testing.assert_allclose(np.asarray(back["weights"]),
                               np.arange(6.0).reshape(2, 3))
    assert back["epoch"] == 3 and back["nested"]["lr"] == 0.1

    # over_write guard (reference File.save(obj, path, overWrite))
    import pytest

    with pytest.raises(Exception):
        File.save(obj, path, over_write=False)
    File.save(obj, path, over_write=True)


def test_random_generator_determinism():
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(123)
    a = RNG.next_key()
    RNG.set_seed(123)
    b = RNG.next_key()
    assert np.array_equal(np.asarray(a), np.asarray(b))
    c = RNG.next_key()
    assert not np.array_equal(np.asarray(b), np.asarray(c))


def test_logger_filter_redirects(tmp_path):
    from bigdl_tpu.utils.logger_filter import LoggerFilter

    LoggerFilter.redirect_spark_info_logs(log_dir=str(tmp_path))
    noisy = logging.getLogger("jax._src.dispatch")
    noisy.info("very verbose backend chatter")
    logging.getLogger("bigdl_tpu").info("stays on console")
    logfile = os.path.join(str(tmp_path), "bigdl.log")
    assert os.path.exists(logfile)
