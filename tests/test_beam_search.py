"""SequenceBeamSearch: exactness vs brute force, greedy parity, eos pooling
(SURVEY.md §2.2 inventory; the reference tests its beam search against fixed
transformer fixtures — here the oracle is exhaustive enumeration)."""

import numpy as np

from tests.oracle import assert_close


def _markov_step(table):
    """Step whose logits depend only on the previous token (carry = dummy)."""
    import jax.numpy as jnp

    def step(params, tokens, carry):
        return jnp.asarray(table)[tokens], carry

    return step


def test_beam_search_exhaustive_matches_brute_force(rng):
    """With beam = V^(L-1) and no reachable eos, the search is exhaustive —
    its best score must equal the brute-force max over all V^L sequences."""
    import jax

    from bigdl_tpu.nn.beam_search import beam_search

    V, L = 4, 4
    K = V ** (L - 1)
    table = rng.randn(V, V).astype(np.float32)
    logp = np.log(np.exp(table) / np.exp(table).sum(-1, keepdims=True))
    sos = 0

    seqs, scores = jax.jit(
        lambda c: beam_search(
            _markov_step(table), None, c, 1, K, V, L,
            sos_id=sos, eos_id=V + 7, alpha=0.0),
    )(np.zeros((K, 1), np.float32))

    # brute force over all V^L sequences
    best = -np.inf
    best_seq = None
    for idx in np.ndindex(*([V] * L)):
        s, prev = 0.0, sos
        for t in idx:
            s += logp[prev, t]
            prev = t
        if s > best:
            best, best_seq = s, idx
    assert_close(float(scores[0, 0]), best, atol=1e-4)
    assert tuple(np.asarray(seqs)[0, 0]) == best_seq


def test_greedy_beam_matches_argmax_rollout(rng):
    import jax

    from bigdl_tpu.nn.beam_search import beam_search

    V, L = 6, 5
    table = rng.randn(V, V).astype(np.float32)
    seqs, _ = jax.jit(
        lambda c: beam_search(
            _markov_step(table), None, c, 2, 1, V, L,
            sos_id=1, eos_id=V + 7),
    )(np.zeros((2, 1), np.float32))

    tok, want = 1, []
    for _ in range(L):
        tok = int(np.argmax(table[tok]))
        want.append(tok)
    assert list(np.asarray(seqs)[0, 0]) == want
    assert list(np.asarray(seqs)[1, 0]) == want  # batch rows independent


def test_wider_beam_never_worse(rng):
    from bigdl_tpu.nn.beam_search import beam_search

    V, L = 5, 6
    table = rng.randn(V, V).astype(np.float32)
    scores = {}
    for K in (1, 2, 4):
        _, sc = beam_search(
            _markov_step(table), None, np.zeros((K, 1), np.float32),
            1, K, V, L, sos_id=0, eos_id=V + 7)
        scores[K] = float(np.asarray(sc)[0, 0])
    assert scores[2] >= scores[1] - 1e-6
    assert scores[4] >= scores[2] - 1e-6


def test_eos_finishes_and_outranks(rng):
    """A sequence that hits eos early with high probability must land in the
    finished pool and beat unfinished alternatives."""
    from bigdl_tpu.nn.beam_search import beam_search

    V = 4
    eos = 3
    # from sos(=1): token 2 is great; from 2: eos is overwhelming
    table = np.full((V, V), -5.0, np.float32)
    table[1, 2] = 5.0
    table[2, eos] = 8.0
    seqs, scores = beam_search(
        _markov_step(table), None, np.zeros((2, 1), np.float32),
        1, 2, V, 6, sos_id=1, eos_id=eos, alpha=0.6)
    top = list(np.asarray(seqs)[0, 0])
    assert top[0] == 2 and top[1] == eos
    assert np.isfinite(float(np.asarray(scores)[0, 0]))


def test_module_facade_tiles_carry(rng):
    from bigdl_tpu.nn.beam_search import SequenceBeamSearch

    V = 5
    table = rng.randn(V, V).astype(np.float32)
    m = SequenceBeamSearch(_markov_step(table), vocab_size=V, beam_size=3,
                           decode_length=4, sos_id=0, eos_id=V + 7)
    seqs, scores = m.forward(np.zeros((3, 2), np.float32))  # batch of 3
    assert np.asarray(seqs).shape == (3, 3, 4)
    assert np.asarray(scores).shape == (3, 3)
    # rows identical (same table, same start)
    assert np.array_equal(np.asarray(seqs)[0], np.asarray(seqs)[1])


def test_beam_search_carry_follows_parent(rng):
    """Carry gathering: a counting carry must equal the number of steps for
    every surviving beam (parents propagate their state)."""
    import jax.numpy as jnp

    from bigdl_tpu.nn.beam_search import beam_search

    V, L, K = 4, 3, 2

    def step(params, tokens, carry):
        logits = jnp.asarray(rng.randn(V, V).astype(np.float32))[tokens]
        return logits, carry + 1.0

    seqs, scores = beam_search(step, None, np.zeros((K,), np.float32),
                               1, K, V, L, sos_id=0, eos_id=V + 7)
    assert np.asarray(seqs).shape == (1, K, L)


def test_padding_value_blanks_after_eos():
    from bigdl_tpu.nn.beam_search import beam_search

    V, eos = 4, 3
    table = np.full((V, V), -5.0, np.float32)
    table[1, 2] = 5.0
    table[2, eos] = 8.0
    seqs, _ = beam_search(_markov_step(table), None,
                          np.zeros((2, 1), np.float32), 1, 2, V, 6,
                          sos_id=1, eos_id=eos, alpha=0.6, padding_value=0)
    top = list(np.asarray(seqs)[0, 0])
    assert top[:2] == [2, eos]
    assert top[2:] == [0, 0, 0, 0]  # padded, not sos-filled
