import numpy as np

from tests.oracle import assert_close


def test_sequential_forward_backward(rng):
    import torch

    from bigdl_tpu.nn import Linear, ReLU, Sequential

    model = Sequential().add(Linear(4, 8)).add(ReLU()).add(Linear(8, 3))
    model._ensure_params()
    keys = sorted(model.params.keys())
    l1, l3 = model.params[keys[0]], model.params[keys[2]]

    tm = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 3)
    )
    with torch.no_grad():
        tm[0].weight.copy_(torch.from_numpy(np.asarray(l1["weight"])))
        tm[0].bias.copy_(torch.from_numpy(np.asarray(l1["bias"])))
        tm[2].weight.copy_(torch.from_numpy(np.asarray(l3["weight"])))
        tm[2].bias.copy_(torch.from_numpy(np.asarray(l3["bias"])))

    x = rng.randn(5, 4).astype(np.float32)
    g = rng.randn(5, 3).astype(np.float32)
    out = model.forward(x)
    xt = torch.from_numpy(x).requires_grad_(True)
    t_out = tm(xt)
    t_out.backward(torch.from_numpy(g))
    assert_close(out, t_out.detach().numpy(), atol=1e-5)
    gin = model.backward(x, g)
    assert_close(gin, xt.grad.numpy(), atol=1e-5)


def test_concat(rng):
    from bigdl_tpu.nn import Concat, Identity, MulConstant

    c = Concat(2).add(Identity()).add(MulConstant(2.0))
    x = rng.randn(3, 4).astype(np.float32)
    out = np.asarray(c.forward(x))
    assert out.shape == (3, 8)
    assert_close(out[:, :4], x)
    assert_close(out[:, 4:], 2 * x)


def test_concat_table_and_caddtable(rng):
    from bigdl_tpu.nn import CAddTable, ConcatTable, Identity, MulConstant, Sequential

    # y = x + 3x = 4x — the residual-block shape
    m = (
        Sequential()
        .add(ConcatTable().add(Identity()).add(MulConstant(3.0)))
        .add(CAddTable())
    )
    x = rng.randn(2, 5).astype(np.float32)
    assert_close(np.asarray(m.forward(x)), 4 * x, atol=1e-6)


def test_parallel_table(rng):
    from bigdl_tpu.nn import MulConstant, ParallelTable

    m = ParallelTable().add(MulConstant(2.0)).add(MulConstant(3.0))
    a, b = rng.randn(2, 2).astype(np.float32), rng.randn(2, 2).astype(np.float32)
    out = m.forward([a, b])
    assert_close(np.asarray(out[0]), 2 * a)
    assert_close(np.asarray(out[1]), 3 * b)


def test_graph_diamond(rng):
    """input -> (id, 2x) -> add  == 3x, via the functional Graph API."""
    from bigdl_tpu.nn import CAddTable, Graph, Identity, Input, MulConstant

    inp = Input()
    a = Identity().inputs(inp)
    b = MulConstant(2.0).inputs(inp)
    out = CAddTable().inputs(a, b)
    g = Graph(inp, out)
    x = rng.randn(4, 3).astype(np.float32)
    assert_close(np.asarray(g.forward(x)), 3 * x, atol=1e-6)


def test_graph_multi_io(rng):
    from bigdl_tpu.nn import CAddTable, Graph, Input, MulConstant

    i1, i2 = Input(), Input()
    s = CAddTable().inputs(i1, i2)
    d = MulConstant(2.0).inputs(s)
    g = Graph([i1, i2], [s, d])
    a, b = rng.randn(2, 2).astype(np.float32), rng.randn(2, 2).astype(np.float32)
    out = g.forward([a, b])
    assert_close(np.asarray(out[0]), a + b, atol=1e-6)
    assert_close(np.asarray(out[1]), 2 * (a + b), atol=1e-6)


def test_graph_weight_sharing(rng):
    """The same Linear instance at two nodes must share one params subtree."""
    from bigdl_tpu.nn import CAddTable, Graph, Input, Linear

    shared = Linear(4, 4)
    inp = Input()
    a = shared.inputs(inp)
    b = shared.inputs(a)
    out = CAddTable().inputs(a, b)
    g = Graph(inp, out)
    g._ensure_params()
    # only one params subtree for the shared module
    assert len([k for k in g.params if "Linear" in k]) == 1
    x = rng.randn(2, 4).astype(np.float32)
    w = np.asarray(g.params[[k for k in g.params if "Linear" in k][0]]["weight"])
    bias = np.asarray(g.params[[k for k in g.params if "Linear" in k][0]]["bias"])
    h = x @ w.T + bias
    expect = h + (h @ w.T + bias)
    assert_close(np.asarray(g.forward(x)), expect, atol=1e-5)


def test_jit_whole_model(rng):
    """A container model's pure apply must trace into one jitted function."""
    import jax

    from bigdl_tpu.nn import Linear, ReLU, Sequential

    model = Sequential().add(Linear(4, 8)).add(ReLU()).add(Linear(8, 2))
    model._ensure_params()

    @jax.jit
    def f(params, x):
        out, _ = model.apply(params, x, model.init_state(), training=False)
        return out

    x = rng.randn(3, 4).astype(np.float32)
    out1 = f(model.params, x)
    out2 = model.forward(x)
    assert_close(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_remat_matches_plain(rng):
    """Remat: identical forward/backward, activations recomputed."""
    import jax
    import numpy as np

    from bigdl_tpu.nn import Linear, Remat, Sequential, Tanh
    from tests.oracle import assert_close

    inner = Sequential().add(Linear(6, 12)).add(Tanh()).add(Linear(12, 6))
    plain = Sequential().add(inner)
    plain._ensure_params()
    x = rng.randn(4, 6).astype(np.float32)

    rm = Remat(inner)
    rem = Sequential().add(rm)
    rem.params = {rem._child_key(0): {
        rm._child_key(0): plain.params[plain._child_key(0)]}}
    rem.state = {rem._child_key(0): {rm._child_key(0): {}}}
    rem._ensure_params()

    assert_close(np.asarray(plain.forward(x)), np.asarray(rem.forward(x)),
                 atol=1e-6)

    def loss(m, p, xx):
        out, _ = m.apply(p, xx, m.state)
        return (out ** 2).sum()

    g1 = jax.grad(lambda p: loss(plain, p, x))(plain.params)
    g2 = jax.grad(lambda p: loss(rem, p, x))(rem.params)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        assert_close(np.asarray(a), np.asarray(b), atol=1e-5)


def test_gradient_checker_utility(rng):
    import numpy as np

    from bigdl_tpu.nn import Linear, Sequential, Tanh
    from bigdl_tpu.utils.gradient_checker import GradientChecker

    m = Sequential().add(Linear(5, 8)).add(Tanh())
    m._ensure_params()
    x = rng.randn(3, 5).astype(np.float32)
    assert GradientChecker(perturbation=1e-2, precision=2e-2).check_layer(m, x)


def test_to_ir_jaxpr_dump(rng):
    """to_ir: the IRGraph-analog lowering inspector returns a jaxpr."""
    from bigdl_tpu.nn import Linear, ReLU, Sequential

    m = Sequential().add(Linear(4, 8)).add(ReLU())
    jaxpr = m.to_ir((2, 4))
    text = str(jaxpr)
    assert "dot_general" in text  # the Linear gemm is visible in the IR
    assert "max" in text or "relu" in text.lower()
