"""Keras-style API: shape inference + parity with hand-built core models
(SURVEY.md §2.2 keras row)."""

import pytest
import numpy as np

from tests.oracle import assert_close


def test_sequential_shape_inference(rng):
    from bigdl_tpu.nn import keras as K

    m = (K.Sequential()
         .add(K.Convolution2D(6, 5, 5, input_shape=(1, 28, 28),
                              activation="tanh"))
         .add(K.MaxPooling2D((2, 2)))
         .add(K.Convolution2D(12, 5, 5, activation="tanh"))
         .add(K.MaxPooling2D((2, 2)))
         .add(K.Flatten())
         .add(K.Dense(100, activation="tanh"))
         .add(K.Dense(10, activation="log_softmax")))
    assert m.get_output_shape() == (10,)

    x = rng.rand(4, 1, 28, 28).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (4, 10)
    # log_softmax rows sum to 1 in prob space
    assert_close(np.exp(out).sum(-1), np.ones(4), atol=1e-4)


def test_dense_matches_core_linear(rng):
    from bigdl_tpu.nn import Linear
    from bigdl_tpu.nn import keras as K

    d = K.Dense(7, input_shape=(12,))
    d.build((12,))
    d._ensure_params()
    x = rng.randn(3, 12).astype(np.float32)
    out = np.asarray(d.forward(x))

    assert isinstance(d._core, Linear)  # no activation wraps Linear directly
    lin = Linear(12, 7)
    lin.params = d.params  # KerasLayer materializes the core's params
    lin.state = {}
    lin._ensure_params()
    want = np.asarray(lin.forward(x))
    assert_close(out, want)


def test_same_padding_shapes(rng):
    from bigdl_tpu.nn import keras as K

    m = (K.Sequential()
         .add(K.Convolution2D(4, 3, 3, input_shape=(3, 9, 9),
                              border_mode="same", subsample=(2, 2)))
         .add(K.AveragePooling2D((2, 2), border_mode="same")))
    assert m.get_output_shape() == (4, 3, 3)
    out = m.forward(rng.rand(2, 3, 9, 9).astype(np.float32))
    assert np.asarray(out).shape == (2, 4, 3, 3)


def test_batchnorm_dropout_reshape(rng):
    from bigdl_tpu.nn import keras as K

    m = (K.Sequential()
         .add(K.Dense(24, input_shape=(8,)))
         .add(K.BatchNormalization())
         .add(K.Dropout(0.5))
         .add(K.Reshape((4, 6)))
         .add(K.Flatten()))
    assert m.get_output_shape() == (24,)
    m.evaluate()
    out = m.forward(rng.randn(5, 8).astype(np.float32))
    assert np.asarray(out).shape == (5, 24)


def test_lstm_return_sequences(rng):
    from bigdl_tpu.nn import keras as K

    x = rng.randn(2, 7, 5).astype(np.float32)
    seq = K.Sequential().add(K.LSTM(9, return_sequences=True,
                                    input_shape=(7, 5)))
    assert seq.get_output_shape() == (7, 9)
    assert np.asarray(seq.forward(x)).shape == (2, 7, 9)

    last = K.Sequential().add(K.LSTM(9, input_shape=(7, 5)))
    assert last.get_output_shape() == (9,)
    assert np.asarray(last.forward(x)).shape == (2, 9)


def test_functional_model(rng):
    from bigdl_tpu.nn import keras as K

    inp = K.Input(shape=(16,))
    h = K.Dense(32, activation="relu")(inp)
    out = K.Dense(4, activation="softmax")(h)
    m = K.Model(input=inp, output=out)
    assert m.output_shape == (4,)

    x = rng.randn(6, 16).astype(np.float32)
    y = np.asarray(m.forward(x))
    assert y.shape == (6, 4)
    assert_close(y.sum(-1), np.ones(6), atol=1e-5)


def test_keras_model_trains(rng):
    """End-to-end: a keras Sequential trains through the Optimizer plane."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.nn import keras as K
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    m = (K.Sequential()
         .add(K.Dense(16, activation="relu", input_shape=(6,)))
         .add(K.Dense(3, activation="log_softmax")))
    # separable blobs
    xs, ys = [], []
    for i in range(60):
        c = i % 3
        xs.append((rng.randn(6) * 0.3 + np.eye(3)[c].repeat(2) * 2
                   ).astype(np.float32))
        ys.append(np.int32(c + 1))
    samples = [Sample(x, y) for x, y in zip(xs, ys)]
    opt = Optimizer(model=m, dataset=DataSet.array(samples),
                    criterion=ClassNLLCriterion(), batch_size=20)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(Trigger.max_epoch(20))
    trained = opt.optimize()

    logits = np.asarray(trained.evaluate().forward(np.stack(xs)))
    acc = (logits.argmax(-1) + 1 == np.asarray(ys)).mean()
    assert acc > 0.8, f"keras model failed to train, acc={acc}"


def test_embedding_zero_based_ids(rng):
    """Keras ids are 0-based; row i of the table must embed token i."""
    from bigdl_tpu.nn import keras as K

    emb = K.Embedding(10, 4, input_shape=(3,))
    emb.build((3,))
    emb._ensure_params()
    ids = np.array([[0, 1, 9]], np.int32)
    out = np.asarray(emb.forward(ids))
    # find the LookupTable weight leaf
    import jax

    table = [w for w in jax.tree_util.tree_leaves(emb.params)
             if np.asarray(w).shape == (10, 4)][0]
    table = np.asarray(table)
    assert_close(out[0, 0], table[0], atol=1e-6)
    assert_close(out[0, 1], table[1], atol=1e-6)
    assert_close(out[0, 2], table[9], atol=1e-6)
    # token 0 must receive gradient (not a silently zeroed row)
    assert np.abs(out[0, 0]).sum() > 0


def test_keras_extras(rng):
    from bigdl_tpu.nn import keras as K

    m = (K.Sequential()
         .add(K.ZeroPadding2D((1, 2), input_shape=(3, 5, 5)))
         .add(K.UpSampling2D((2, 2)))
         .add(K.GlobalAveragePooling2D()))
    assert m.get_output_shape() == (3,)
    out = m.forward(rng.rand(2, 3, 5, 5).astype(np.float32))
    assert np.asarray(out).shape == (2, 3)


def test_keras_merge_functional(rng):
    from bigdl_tpu.nn import keras as K

    a = K.Input(shape=(6,))
    h1 = K.Dense(4)(a)
    h2 = K.Dense(4)(a)
    merged = K.Merge("sum")([h1, h2])
    out = K.Dense(2)(merged)
    model = K.Model(input=a, output=out)
    y = model.forward(rng.randn(3, 6).astype(np.float32))
    assert np.asarray(y).shape == (3, 2)


def test_keras_highway(rng):
    from bigdl_tpu.nn import keras as K

    hw = K.Highway(input_shape=(8,))
    hw.build((8,))
    hw._ensure_params()
    x = rng.randn(4, 8).astype(np.float32)
    out = np.asarray(hw.forward(x))
    assert out.shape == (4, 8)
    assert np.all(np.isfinite(out))


def test_merge_concat_axis_semantics(rng):
    """concat_axis indexes the BATCHED tensor (Keras semantics); axis 1 on
    (B, D) concatenates features, never the batch."""
    from bigdl_tpu.nn import keras as K

    a = K.Input(shape=(3,))
    h1 = K.Dense(3)(a)
    h2 = K.Dense(3)(a)
    merged = K.Merge("concat", concat_axis=1)([h1, h2])
    assert merged.shape == (6,)
    m = K.Model(input=a, output=merged)
    out = np.asarray(m.forward(rng.randn(4, 3).astype(np.float32)))
    assert out.shape == (4, 6)

    # three-way max merge (CMaxTable handles N inputs)
    mx = K.Merge("max")([h1, h2, K.Dense(3)(a)])
    m2 = K.Model(input=a, output=mx)
    out2 = np.asarray(m2.forward(rng.randn(4, 3).astype(np.float32)))
    assert out2.shape == (4, 3)

    import pytest

    with pytest.raises(ValueError):
        K.Merge("concat", concat_axis=0)


def test_keras_model_serialization_roundtrip(rng, tmp_path):
    """Keras layers ride the structured serializer like core modules."""
    from bigdl_tpu.nn import keras as K
    from bigdl_tpu.nn.module import AbstractModule

    m = (K.Sequential()
         .add(K.Dense(8, activation="relu", input_shape=(5,)))
         .add(K.Dense(3, activation="softmax")))
    m.evaluate()
    x = rng.randn(4, 5).astype(np.float32)
    want = np.asarray(m.forward(x))
    path = str(tmp_path / "keras.bigdl")
    m.save_module(path)
    m2 = AbstractModule.load_module(path)
    m2.evaluate()
    assert_close(np.asarray(m2.forward(x)), want, atol=1e-6)


def test_keras_breadth_batch2_shapes_and_numerics(rng):
    from bigdl_tpu.nn import keras as K

    # Convolution1D valid + same
    m = (K.Sequential()
         .add(K.Convolution1D(8, 3, activation="relu", input_shape=(10, 4)))
         .add(K.Convolution1D(6, 3, border_mode="same")))
    x = rng.randn(2, 10, 4).astype(np.float32)
    out = m.forward(x)
    assert out.shape == (2, 8, 6)
    assert m.get_output_shape() == (8, 6)

    # SeparableConvolution2D same-mode
    s = K.Sequential().add(K.SeparableConvolution2D(
        5, 3, 3, depth_multiplier=2, border_mode="same",
        input_shape=(3, 8, 8)))
    assert s.forward(rng.randn(2, 3, 8, 8).astype(np.float32)).shape == (2, 5, 8, 8)

    # LocallyConnected1D/2D
    l1 = K.Sequential().add(K.LocallyConnected1D(4, 3, input_shape=(7, 5)))
    assert l1.forward(rng.randn(2, 7, 5).astype(np.float32)).shape == (2, 5, 4)
    l2 = K.Sequential().add(K.LocallyConnected2D(3, 2, 2, input_shape=(2, 5, 6)))
    assert l2.forward(rng.randn(1, 2, 5, 6).astype(np.float32)).shape == (1, 3, 4, 5)


def test_keras_bidirectional_and_timedistributed(rng):
    from bigdl_tpu.nn import keras as K

    b = K.Sequential().add(K.Bidirectional(
        K.LSTM(6, return_sequences=True), merge_mode="concat",
        input_shape=(5, 3)))
    x = rng.randn(2, 5, 3).astype(np.float32)
    assert b.forward(x).shape == (2, 5, 12)

    bsum = K.Sequential().add(K.Bidirectional(
        K.GRU(6, return_sequences=True), merge_mode="sum",
        input_shape=(5, 3)))
    assert bsum.forward(x).shape == (2, 5, 6)

    td = K.Sequential().add(K.TimeDistributed(
        K.Dense(4), input_shape=(5, 3)))
    assert td.forward(x).shape == (2, 5, 4)


def test_keras_shape_utils_and_activations(rng):
    from bigdl_tpu.nn import keras as K

    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    p = K.Sequential().add(K.Permute((2, 3, 1), input_shape=(3, 4, 5)))
    out = np.asarray(p.forward(x))
    assert_close(out, x.transpose(0, 2, 3, 1))

    rv = K.Sequential().add(K.RepeatVector(4, input_shape=(6,)))
    v = rng.randn(2, 6).astype(np.float32)
    out = np.asarray(rv.forward(v))
    assert out.shape == (2, 4, 6)
    assert_close(out[:, 0], v)

    c1 = K.Sequential().add(K.Cropping1D((1, 2), input_shape=(8, 3)))
    assert c1.forward(rng.randn(2, 8, 3).astype(np.float32)).shape == (2, 5, 3)
    c2 = K.Sequential().add(K.Cropping2D((1, 1), (2, 0), input_shape=(3, 6, 7)))
    assert c2.forward(rng.randn(2, 3, 6, 7).astype(np.float32)).shape == (2, 3, 4, 5)
    c3 = K.Sequential().add(K.Cropping3D((1, 0), (0, 1), (1, 1),
                                         input_shape=(2, 4, 4, 5)))
    assert c3.forward(
        rng.randn(1, 2, 4, 4, 5).astype(np.float32)).shape == (1, 2, 3, 3, 3)

    t = K.Sequential().add(K.ThresholdedReLU(1.0, input_shape=(4,)))
    got = np.asarray(t.forward(np.float32([[0.5, 1.5, -2.0, 3.0]])))
    assert_close(got, [[0.0, 1.5, 0.0, 3.0]])

    md = K.Sequential().add(K.MaxoutDense(3, 4, input_shape=(5,)))
    assert md.forward(rng.randn(2, 5).astype(np.float32)).shape == (2, 3)

    sr = K.Sequential().add(K.SReLU(input_shape=(4,)))
    assert sr.forward(rng.randn(2, 4).astype(np.float32)).shape == (2, 4)

    for cls in (K.GaussianNoise, K.GaussianDropout, K.SpatialDropout1D):
        layer = cls(0.5, input_shape=(6, 3)) if cls is not K.GaussianNoise \
            else cls(0.3, input_shape=(6, 3))
        m = K.Sequential().add(layer)
        m.evaluate()
        xx = rng.randn(2, 6, 3).astype(np.float32)
        assert_close(np.asarray(m.forward(xx)), xx)  # eval mode = identity

    lr = K.Sequential().add(K.LeakyReLU(0.1, input_shape=(3,)))
    got = np.asarray(lr.forward(np.float32([[-1.0, 0.0, 2.0]])))
    assert_close(got, [[-0.1, 0.0, 2.0]], atol=1e-6)


def test_cropping1d_values(rng):
    """Code-review regression: crop VALUES, not just shape (1-based Narrow)."""
    from bigdl_tpu.nn import keras as K

    x = rng.randn(2, 8, 3).astype(np.float32)
    out = np.asarray(K.Sequential()
                     .add(K.Cropping1D((1, 2), input_shape=(8, 3)))
                     .forward(x))
    assert_close(out, x[:, 1:6])
    out0 = np.asarray(K.Sequential()
                      .add(K.Cropping1D((0, 3), input_shape=(8, 3)))
                      .forward(x))
    assert_close(out0, x[:, 0:5])


def test_keras_batch2_serialization_roundtrip(rng, tmp_path):
    """The new wrappers ride the structured serializer like core modules."""
    from bigdl_tpu.nn import keras as K
    from bigdl_tpu.nn.module import AbstractModule

    m = (K.Sequential()
         .add(K.Convolution1D(6, 3, activation="relu", input_shape=(10, 4)))
         .add(K.MaxoutDense(5, 3))
         .add(K.SReLU())
         .add(K.Dense(4)))
    m.evaluate()
    x = rng.randn(2, 10, 4).astype(np.float32)
    want = np.asarray(m.forward(x))
    path = str(tmp_path / "keras2.bigdl")
    m.save_module(path)
    m2 = AbstractModule.load_module(path)
    m2.evaluate()
    assert_close(np.asarray(m2.forward(x)), want, atol=1e-6)

    b = (K.Sequential()
         .add(K.Bidirectional(K.LSTM(5, return_sequences=True),
                              input_shape=(6, 3)))
         .add(K.TimeDistributed(K.Dense(2))))
    b.evaluate()
    xb = rng.randn(2, 6, 3).astype(np.float32)
    wantb = np.asarray(b.forward(xb))
    pathb = str(tmp_path / "keras2b.bigdl")
    b.save_module(pathb)
    b2 = AbstractModule.load_module(pathb)
    b2.evaluate()
    assert_close(np.asarray(b2.forward(xb)), wantb, atol=1e-6)


def test_keras_pooling_padding_family_round2(rng):
    """Round-2 widening: 1D/3D pooling (+ global variants), ZeroPadding1D/3D,
    UpSampling1D/3D, SpatialDropout3D, Convolution3D, Deconvolution2D —
    shapes AND numerics against numpy oracles."""
    from bigdl_tpu.nn import keras as K

    x1 = rng.randn(2, 10, 4).astype(np.float32)          # (B, steps, dim)

    mp = K.Sequential().add(K.MaxPooling1D(2, input_shape=(10, 4)))
    out = np.asarray(mp.forward(x1))
    assert out.shape == (2, 5, 4) and mp.get_output_shape() == (5, 4)
    np.testing.assert_allclose(out, x1.reshape(2, 5, 2, 4).max(2), atol=1e-6)

    ap = K.Sequential().add(K.AveragePooling1D(2, input_shape=(10, 4)))
    np.testing.assert_allclose(np.asarray(ap.forward(x1)),
                               x1.reshape(2, 5, 2, 4).mean(2), atol=1e-6)

    mps = K.Sequential().add(K.MaxPooling1D(3, 2, border_mode="same",
                                            input_shape=(10, 4)))
    assert np.asarray(mps.forward(x1)).shape == (2, 5, 4)

    gm = K.Sequential().add(K.GlobalMaxPooling1D(input_shape=(10, 4)))
    np.testing.assert_allclose(np.asarray(gm.forward(x1)), x1.max(1),
                               atol=1e-6)
    ga = K.Sequential().add(K.GlobalAveragePooling1D(input_shape=(10, 4)))
    np.testing.assert_allclose(np.asarray(ga.forward(x1)), x1.mean(1),
                               atol=1e-6)

    zp1 = K.Sequential().add(K.ZeroPadding1D(2, input_shape=(10, 4)))
    out = np.asarray(zp1.forward(x1))
    assert out.shape == (2, 14, 4)
    np.testing.assert_allclose(out[:, 2:12], x1, atol=1e-6)
    assert np.all(out[:, :2] == 0) and np.all(out[:, 12:] == 0)

    up1 = K.Sequential().add(K.UpSampling1D(3, input_shape=(10, 4)))
    np.testing.assert_allclose(np.asarray(up1.forward(x1)),
                               np.repeat(x1, 3, axis=1), atol=1e-6)

    x2 = rng.randn(2, 3, 6, 8).astype(np.float32)        # (B, C, H, W)
    g2 = K.Sequential().add(K.GlobalMaxPooling2D(input_shape=(3, 6, 8)))
    np.testing.assert_allclose(np.asarray(g2.forward(x2)), x2.max((2, 3)),
                               atol=1e-6)

    x3 = rng.randn(2, 3, 4, 6, 8).astype(np.float32)     # (B, C, D, H, W)
    mp3 = K.Sequential().add(K.MaxPooling3D((2, 2, 2),
                                            input_shape=(3, 4, 6, 8)))
    out = np.asarray(mp3.forward(x3))
    assert out.shape == (2, 3, 2, 3, 4)
    np.testing.assert_allclose(
        out, x3.reshape(2, 3, 2, 2, 3, 2, 4, 2).max((3, 5, 7)), atol=1e-6)
    ap3 = K.Sequential().add(K.AveragePooling3D((2, 2, 2),
                                                input_shape=(3, 4, 6, 8)))
    np.testing.assert_allclose(
        np.asarray(ap3.forward(x3)),
        x3.reshape(2, 3, 2, 2, 3, 2, 4, 2).mean((3, 5, 7)), atol=1e-6)
    gm3 = K.Sequential().add(K.GlobalMaxPooling3D(input_shape=(3, 4, 6, 8)))
    np.testing.assert_allclose(np.asarray(gm3.forward(x3)),
                               x3.max((2, 3, 4)), atol=1e-6)
    ga3 = K.Sequential().add(
        K.GlobalAveragePooling3D(input_shape=(3, 4, 6, 8)))
    np.testing.assert_allclose(np.asarray(ga3.forward(x3)),
                               x3.mean((2, 3, 4)), atol=1e-5)

    zp3 = K.Sequential().add(K.ZeroPadding3D((1, 2, 1),
                                             input_shape=(3, 4, 6, 8)))
    out = np.asarray(zp3.forward(x3))
    assert out.shape == (2, 3, 6, 10, 10)
    np.testing.assert_allclose(out[:, :, 1:5, 2:8, 1:9], x3, atol=1e-6)

    up3 = K.Sequential().add(K.UpSampling3D((2, 1, 2),
                                            input_shape=(3, 4, 6, 8)))
    assert np.asarray(up3.forward(x3)).shape == (2, 3, 8, 6, 16)

    sd3 = K.Sequential().add(K.SpatialDropout3D(0.5, input_shape=(3, 4, 6, 8)))
    sd3.evaluate()                      # inference: identity
    np.testing.assert_allclose(np.asarray(sd3.forward(x3)), x3, atol=1e-6)

    c3 = K.Sequential().add(K.Convolution3D(5, 2, 3, 3, activation="relu",
                                            input_shape=(3, 4, 6, 8)))
    out = np.asarray(c3.forward(x3))
    assert out.shape == (2, 5, 3, 4, 6) and (out >= 0).all()
    assert c3.get_output_shape() == (5, 3, 4, 6)

    d2 = K.Sequential().add(K.Deconvolution2D(4, 3, 3, subsample=(2, 2),
                                              input_shape=(3, 5, 5)))
    out = np.asarray(d2.forward(x2[:, :, :5, :5]))
    assert out.shape == (2, 4, 11, 11)
    assert d2.get_output_shape() == (4, 11, 11)

    with pytest.raises(ValueError, match="valid"):
        K.MaxPooling3D(border_mode="same", input_shape=(3, 4, 6, 8))
    with pytest.raises(ValueError, match="valid"):
        K.Convolution3D(4, 2, 2, 2, border_mode="same")


def test_average_pooling1d_same_excludes_padding(rng):
    """SAME-mode edge windows divide by the ACTUAL element count
    (Keras/TF semantics), not the full window size."""
    from bigdl_tpu.nn import keras as K

    x = np.arange(10, dtype=np.float32).reshape(1, 10, 1)
    ap = K.Sequential().add(K.AveragePooling1D(3, 2, border_mode="same",
                                               input_shape=(10, 1)))
    out = np.asarray(ap.forward(x)).reshape(-1)
    # windows (TF SAME, k=3 s=2): [0,1,2],[2,3,4],[4,5,6],[6,7,8],[8,9]
    np.testing.assert_allclose(out, [1.0, 3.0, 5.0, 7.0, 8.5], atol=1e-6)

    with pytest.raises(ValueError, match="border_mode"):
        K.MaxPooling1D(2, border_mode="SAME")


def test_keras_conv_lstm_2d(rng):
    from bigdl_tpu.nn import keras as K
    from bigdl_tpu.utils.random_gen import RNG

    x = rng.randn(2, 3, 2, 5, 6).astype(np.float32)
    RNG.set_seed(21)
    m = K.Sequential().add(K.ConvLSTM2D(4, 3, 3, return_sequences=True,
                                        input_shape=(3, 2, 5, 6)))
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 3, 4, 5, 6)
    assert m.get_output_shape() == (3, 4, 5, 6)

    last = K.Sequential().add(K.ConvLSTM2D(4, 3, 3, input_shape=(3, 2, 5, 6)))
    last._ensure_params()
    last.set_weights(m.get_weights())  # identical params, any key tree
    out2 = np.asarray(last.forward(x))
    assert out2.shape == (2, 4, 5, 6)
    np.testing.assert_allclose(out2, out[:, -1], atol=1e-6)


def test_conv_lstm_2d_rejects_rect_kernel():
    from bigdl_tpu.nn import keras as K
    with pytest.raises(ValueError, match="square"):
        K.ConvLSTM2D(4, 3, 5)


def test_atrous_convolutions(rng):
    import torch

    from bigdl_tpu.nn import keras as K

    x = rng.randn(2, 3, 10, 12).astype(np.float32)
    m = K.Sequential().add(K.AtrousConvolution2D(
        5, 3, 3, atrous_rate=(2, 2), input_shape=(3, 10, 12)))
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 5, 6, 8)
    assert m.get_output_shape() == (5, 6, 8)

    # numeric parity vs torch dilated conv with the SAME weights
    import jax

    leaves = jax.tree_util.tree_leaves(m.params)
    w = next(np.asarray(l) for l in leaves if np.ndim(l) == 4)
    b = next(np.asarray(l) for l in leaves if np.ndim(l) == 1)
    want = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(w),
        torch.from_numpy(b), dilation=2).numpy()
    np.testing.assert_allclose(out, want, atol=2e-4)

    x1 = rng.randn(2, 11, 4).astype(np.float32)
    m1 = K.Sequential().add(K.AtrousConvolution1D(
        6, 3, atrous_rate=2, input_shape=(11, 4)))
    out1 = np.asarray(m1.forward(x1))
    assert out1.shape == (2, 7, 6)
    assert m1.get_output_shape() == (7, 6)

    with pytest.raises(ValueError, match="valid"):
        K.AtrousConvolution2D(4, 3, 3, border_mode="same")


def test_keras_round2_layers_serialization_roundtrip(rng, tmp_path):
    """The round-2 wrappers (pooling family, ConvLSTM2D, Convolution3D,
    atrous) ride the structured serializer."""
    from bigdl_tpu.nn import keras as K
    from bigdl_tpu.nn.module import AbstractModule

    m = (K.Sequential()
         .add(K.ConvLSTM2D(3, 3, 3, return_sequences=True,
                           input_shape=(3, 2, 6, 6)))
         .add(K.TimeDistributed(K.Flatten()))
         .add(K.MaxPooling1D(3))
         .add(K.GlobalAveragePooling1D()))
    m.evaluate()
    x = rng.randn(2, 3, 2, 6, 6).astype(np.float32)
    want = np.asarray(m.forward(x))
    path = str(tmp_path / "keras_r2.bigdl")
    m.save_module(path)
    m2 = AbstractModule.load_module(path)
    m2.evaluate()
    assert_close(np.asarray(m2.forward(x)), want, atol=1e-6)

    c3 = (K.Sequential()
          .add(K.Convolution3D(4, 2, 2, 2, input_shape=(2, 4, 6, 6)))
          .add(K.GlobalMaxPooling3D())
          .add(K.Dense(3)))
    c3.evaluate()
    x3 = rng.randn(2, 2, 4, 6, 6).astype(np.float32)
    want3 = np.asarray(c3.forward(x3))
    c3.save_module(str(tmp_path / "keras_c3.bigdl"))
    c3b = AbstractModule.load_module(str(tmp_path / "keras_c3.bigdl"))
    c3b.evaluate()
    assert_close(np.asarray(c3b.forward(x3)), want3, atol=1e-6)

    a2 = K.Sequential().add(K.AtrousConvolution2D(
        4, 3, 3, atrous_rate=(2, 2), input_shape=(3, 10, 10)))
    a2.evaluate()
    xa = rng.randn(1, 3, 10, 10).astype(np.float32)
    wanta = np.asarray(a2.forward(xa))
    a2.save_module(str(tmp_path / "keras_a2.bigdl"))
    a2b = AbstractModule.load_module(str(tmp_path / "keras_a2.bigdl"))
    a2b.evaluate()
    assert_close(np.asarray(a2b.forward(xa)), wanta, atol=1e-6)
