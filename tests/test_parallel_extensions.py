"""Tensor/pipeline/expert parallelism vs single-device oracles, on the
8-virtual-device CPU mesh (the distributed-in-one-process pattern of
SURVEY.md §4).

Uses ``utils.compat.shard_map`` (not ``jax.shard_map``) so the suite
runs on every jax generation this repo supports — 0.4.x spells it
``jax.experimental.shard_map`` and calls the replication check
``check_rep``; the shim resolves both."""

import numpy as np
import pytest

from bigdl_tpu.utils.compat import shard_map
from tests.oracle import assert_close


def _mesh(n=8, name="model"):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]).reshape(n), (name,))


# ---------------------------------------------------------------------------
# tensor parallel
# ---------------------------------------------------------------------------

def test_column_parallel_linear(rng):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import column_parallel_linear

    B, IN, OUT = 4, 12, 16
    x = rng.randn(B, IN).astype(np.float32)
    w = rng.randn(OUT, IN).astype(np.float32)
    b = rng.randn(OUT).astype(np.float32)
    mesh = _mesh()

    # unsplit weights; in_specs P("model", None) shards the output rows
    f = jax.jit(shard_map(
        lambda x, ws, bs: column_parallel_linear(x, ws, bs, "model"),
        mesh=mesh, in_specs=(P(), P("model", None), P("model")),
        out_specs=P(None, "model"),
    ))
    out = np.asarray(f(x, w, b))
    assert_close(out, x @ w.T + b, atol=1e-5)


def test_row_parallel_linear(rng):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import row_parallel_linear

    B, IN, OUT = 4, 16, 12
    x = rng.randn(B, IN).astype(np.float32)
    w = rng.randn(OUT, IN).astype(np.float32)
    b = rng.randn(OUT).astype(np.float32)
    mesh = _mesh()

    f = jax.jit(shard_map(
        lambda xs, ws, b: row_parallel_linear(xs, ws, b, "model"),
        mesh=mesh,
        # x sharded on features; w sharded on input columns (dim 1)
        in_specs=(P(None, "model"), P(None, "model"), P()),
        out_specs=P(),
    ))
    out = np.asarray(f(x, w, b))
    assert_close(out, x @ w.T + b, atol=1e-5)


def test_tp_mlp_matches_dense(rng):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import tp_mlp

    B, D, H = 4, 12, 32
    x = rng.randn(B, D).astype(np.float32)
    w1 = rng.randn(H, D).astype(np.float32)
    b1 = rng.randn(H).astype(np.float32)
    w2 = rng.randn(D, H).astype(np.float32)
    b2 = rng.randn(D).astype(np.float32)
    mesh = _mesh()

    f = jax.jit(shard_map(
        lambda x, w1, b1, w2, b2: tp_mlp(x, w1, b1, w2, b2, "model"),
        mesh=mesh,
        # w1 column-parallel (rows), w2 row-parallel (input columns)
        in_specs=(P(), P("model", None), P("model"), P(None, "model"), P()),
        out_specs=P(),
    ))
    out = np.asarray(f(x, w1, b1, w2, b2))
    want = np.asarray(jax.nn.gelu(x @ w1.T + b1)) @ w2.T + b2
    assert_close(out, want, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_tp_attention_matches_dense(rng, causal):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import tp_attention
    from bigdl_tpu.parallel.ring_attention import attention

    B, T, D, HEADS = 2, 6, 32, 8   # 8 heads over 8 chips -> 1 head/chip
    HD = D // HEADS
    x = rng.randn(B, T, D).astype(np.float32)
    wq, wk, wv = (rng.randn(D, D).astype(np.float32) for _ in range(3))
    wo = rng.randn(D, D).astype(np.float32)
    bo = rng.randn(D).astype(np.float32)
    mesh = _mesh()

    f = jax.jit(shard_map(
        lambda x, wq, wk, wv, wo, bo: tp_attention(
            x, wq, wk, wv, wo, "model", n_heads_local=1, causal=causal, bo=bo),
        mesh=mesh,
        in_specs=(P(), P("model", None), P("model", None), P("model", None),
                  P(None, "model"), P()),
        out_specs=P(),
    ))
    out = np.asarray(f(x, wq, wk, wv, wo, bo))

    # dense oracle
    q = (x @ wq.T).reshape(B, T, HEADS, HD)
    k = (x @ wk.T).reshape(B, T, HEADS, HD)
    v = (x @ wv.T).reshape(B, T, HEADS, HD)
    o = np.asarray(attention(q, k, v, causal=causal)).reshape(B, T, D)
    want = o @ wo.T + bo
    assert_close(out, want, atol=1e-4)


# ---------------------------------------------------------------------------
# pipeline parallel
# ---------------------------------------------------------------------------

def _stage_fn(p, x):
    import jax.numpy as jnp

    return jnp.tanh(jnp.matmul(x, p["w"]) + p["b"])


def test_gpipe_matches_sequential(rng):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import gpipe, microbatch, stack_stage_params

    n_stages, M, mb, D = 8, 4, 2, 6
    stages = [{"w": rng.randn(D, D).astype(np.float32) * 0.5,
               "b": rng.randn(D).astype(np.float32) * 0.1}
              for _ in range(n_stages)]
    x = rng.randn(M * mb, D).astype(np.float32)
    mesh = _mesh(name="pipe")

    stacked = stack_stage_params(stages)
    f = jax.jit(shard_map(
        lambda p, xm: gpipe(_stage_fn, p, xm, "pipe"),
        mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
    ))
    out = np.asarray(f(stacked, microbatch(x, M))).reshape(M * mb, D)

    want = x
    for p in stages:
        want = np.tanh(want @ p["w"] + p["b"])
    assert_close(out, want, atol=1e-5)


def test_gpipe_gradients_match(rng):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import gpipe, microbatch, stack_stage_params

    n_stages, M, mb, D = 8, 2, 2, 4
    stages = [{"w": rng.randn(D, D).astype(np.float32) * 0.5,
               "b": rng.randn(D).astype(np.float32) * 0.1}
              for _ in range(n_stages)]
    x = rng.randn(M * mb, D).astype(np.float32)
    mesh = _mesh(name="pipe")
    stacked = stack_stage_params(stages)

    def piped_loss(p, xm):
        inner = shard_map(
            lambda p, xm: gpipe(_stage_fn, p, xm, "pipe"),
            mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P(),
        )
        return jnp.sum(inner(p, xm) ** 2)

    g_pipe = jax.jit(jax.grad(piped_loss))(stacked, microbatch(x, M))

    def seq_loss(p, x):
        out = x
        for i in range(n_stages):
            out = _stage_fn(jax.tree_util.tree_map(lambda l: l[i], p), out)
        return jnp.sum(out ** 2)

    g_seq = jax.jit(jax.grad(seq_loss))(stacked, x)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        assert_close(np.asarray(a), np.asarray(b), atol=1e-4)


# ---------------------------------------------------------------------------
# expert parallel
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_dense_oracle(rng, top_k):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel import mlp_expert, moe_layer

    n_dev, E, T_loc, D, H = 8, 8, 6, 4, 8
    T = n_dev * T_loc
    x = rng.randn(T, D).astype(np.float32)
    router = rng.randn(D, E).astype(np.float32)
    ep = {
        "w1": rng.randn(E, D, H).astype(np.float32) * 0.5,
        "b1": rng.randn(E, H).astype(np.float32) * 0.1,
        "w2": rng.randn(E, H, D).astype(np.float32) * 0.5,
        "b2": rng.randn(E, D).astype(np.float32) * 0.1,
    }
    mesh = _mesh(name="expert")

    f = jax.jit(shard_map(
        lambda x, r, ep: moe_layer(x, r, ep, mlp_expert, "expert",
                                   top_k=top_k, capacity=T_loc),
        mesh=mesh,
        in_specs=(P("expert"), P(), P("expert")),
        out_specs=P("expert"),
    ))
    out = np.asarray(f(x, router, ep))

    # dense oracle: per-token top-k routing, no capacity drops
    logits = x @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(x)
    for t in range(T):
        top = np.argsort(-probs[t])[:top_k]
        gates = probs[t][top] / probs[t][top].sum()
        for g, e in zip(gates, top):
            hh = np.asarray(jax.nn.gelu(x[t] @ ep["w1"][e] + ep["b1"][e]))
            want[t] += g * (hh @ ep["w2"][e] + ep["b2"][e])
    assert_close(out, want, atol=1e-4)


def test_hybrid_dcn_ici_mesh_step():
    """Engine.hybrid_mesh: 2 slices x (2 data x 2 model) on 8 virtual
    devices; model-parallel psum stays intra-slice (ICI axes), gradient
    pmean crosses dcn+data — one full step must match the single-device
    computation."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.utils.engine import Engine

    mesh = Engine.hybrid_mesh(
        ici_axis_names=("data", "model"), ici_axis_sizes=(2, 2),
        num_slices=2)
    assert mesh.shape == {"dcn": 2, "data": 2, "model": 2}

    rng = np.random.default_rng(0)
    w = rng.standard_normal((8, 8)).astype(np.float32)   # rows sharded: model
    x = rng.standard_normal((8, 8)).astype(np.float32)   # batch: dcn*data

    def spmd(w, x):
        # row-parallel matmul: the contraction dim is sharded over 'model',
        # so local products are PARTIAL sums completed by an intra-slice
        # (ICI) psum
        part = jnp.matmul(x, w)
        y = lax.psum(part, "model")
        loss = jnp.mean(y ** 2)
        # gradient-style reduction over the data axes (dcn is one of them)
        return lax.pmean(lax.pmean(loss, "data"), "dcn")

    step = jax.jit(shard_map(
        spmd, mesh=mesh,
        in_specs=(P("model", None), P(("dcn", "data"), "model")),
        out_specs=P()))
    got = float(step(w, x))
    want = float(np.mean((x @ w) ** 2))
    assert abs(got - want) < 1e-4
