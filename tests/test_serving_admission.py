"""Batched admission subsystem (bigdl_tpu/serving/admission.py +
make_batch_prefill_step + PrefixCache): masked multi-row prefill parity
with the per-row prefill, token-for-token engine parity between batched
and per-request admission across ragged prompt lengths, the bounded
prefill-compile guarantee, and prefix-cache hit/refcount/eviction
invariants."""

import numpy as np
import pytest

from tests.oracle import assert_close
from tests.test_serving import _make_lm


# -- make_batch_prefill_step (the model-layer factor) ----------------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_batch_prefill_matches_per_row_prefill(dtype_name, rng):
    """One masked (B, L) prefill over RAGGED rows must reproduce each
    row's private make_prefill_step result: identical cache K/V in the
    valid region, matching last-position logprobs, and advanced pos."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_batch_decode_step, make_batch_prefill_step, make_decode_step,
        make_prefill_step, serving_params,
    )

    dtype = None if dtype_name == "fp32" else jnp.bfloat16
    lm = _make_lm()
    P = serving_params(lm, dtype)
    prefill1 = make_prefill_step(lm, dtype)
    prefillB = make_batch_prefill_step(lm, dtype)
    _, init1 = make_decode_step(lm, dtype)
    _, initN = make_batch_decode_step(lm, dtype)

    lens = [4, 7, 1, 6]
    L = 8
    toks = np.zeros((len(lens), L), np.int32)
    prompts = [rng.randint(0, 29, size=(n,)) for n in lens]
    for j, p in enumerate(prompts):
        toks[j, :len(p)] = p
    lpB, cB = prefillB(P, jnp.asarray(toks),
                       np.asarray(lens, np.int32), initN(len(lens)))
    assert np.asarray(cB["pos"]).tolist() == lens
    atol, rtol = (1e-5, 1e-4) if dtype is None else (5e-2, 5e-2)
    for j, p in enumerate(prompts):
        lp1, c1 = prefill1(P, jnp.asarray(p[None]), init1(1))
        assert_close(np.asarray(lpB)[j], np.asarray(lp1)[0],
                     atol=atol, rtol=rtol)
        for i in range(2):
            assert_close(np.asarray(cB[f"k{i}"])[j, :len(p)],
                         np.asarray(c1[f"k{i}"])[0, :len(p)],
                         atol=atol, rtol=rtol)
            assert_close(np.asarray(cB[f"v{i}"])[j, :len(p)],
                         np.asarray(c1[f"v{i}"])[0, :len(p)],
                         atol=atol, rtol=rtol)


def test_batch_prefill_ballast_rows_untouched(rng):
    """lengths == 0 rows are pure ballast (the batch-decode ``active``
    convention): cache and pos bitwise identical after the call."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_batch_decode_step, make_batch_prefill_step, serving_params,
    )

    lm = _make_lm()
    P = serving_params(lm, None)
    prefillB = make_batch_prefill_step(lm)
    _, initN = make_batch_decode_step(lm)
    carry = initN(3)
    toks = np.zeros((3, 4), np.int32)
    toks[0, :3] = rng.randint(0, 29, size=(3,))
    before = {k: np.asarray(v).copy() for k, v in carry.items()}
    _, out = prefillB(P, jnp.asarray(toks), np.asarray([3, 0, 0], np.int32),
                      carry)
    assert np.asarray(out["pos"]).tolist() == [3, 0, 0]
    for key in before:
        if key == "pos":
            continue
        np.testing.assert_array_equal(np.asarray(out[key])[1:],
                                      before[key][1:])


def test_batch_prefill_suffix_continuation_matches_full(rng):
    """A nonzero start offset (the prefix-cache suffix path) must land
    on the same state as one full prefill: prefix-chunk + suffix-chunk
    == whole prompt, K/V and logits alike."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_batch_decode_step, make_batch_prefill_step, serving_params,
    )

    lm = _make_lm()
    P = serving_params(lm, None)
    prefillB = make_batch_prefill_step(lm)
    _, initN = make_batch_decode_step(lm)
    full = rng.randint(0, 29, size=(9,))

    lp_full, c_full = prefillB(P, jnp.asarray(full[None]),
                               np.asarray([9], np.int32), initN(1))
    _, c_pre = prefillB(P, jnp.asarray(full[None, :5]),
                        np.asarray([5], np.int32), initN(1))
    sfx = np.zeros((1, 8), np.int32)          # padded suffix bucket
    sfx[0, :4] = full[5:]
    lp_cont, c_cont = prefillB(P, jnp.asarray(sfx),
                               np.asarray([4], np.int32), c_pre)
    assert int(np.asarray(c_cont["pos"])[0]) == 9
    assert_close(np.asarray(lp_cont)[0], np.asarray(lp_full)[0], atol=1e-5)
    for i in range(2):
        assert_close(np.asarray(c_cont[f"k{i}"])[0, :9],
                     np.asarray(c_full[f"k{i}"])[0, :9], atol=1e-5)


def test_batch_prefill_rejects_overflow_and_shape_mismatch():
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_batch_decode_step, make_batch_prefill_step, serving_params,
    )

    lm = _make_lm()
    P = serving_params(lm, None)
    prefillB = make_batch_prefill_step(lm)
    _, initN = make_batch_decode_step(lm)
    with pytest.raises(ValueError, match="lengths"):
        prefillB(P, jnp.zeros((2, 4), jnp.int32),
                 np.asarray([5, 1], np.int32), initN(2))   # length > L
    with pytest.raises(ValueError, match="max_len"):
        prefillB(P, jnp.zeros((1, 48), jnp.int32),
                 np.asarray([48], np.int32),
                 {**initN(1), "pos": jnp.ones((1,), jnp.int32)})
    with pytest.raises(ValueError, match="rows"):
        prefillB(P, jnp.zeros((2, 4), jnp.int32),
                 np.asarray([1, 1], np.int32), initN(3))   # B mismatch


# -- engine parity (THE acceptance contract) -------------------------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_batched_admission_parity_with_per_request(dtype_name, rng):
    """Ragged mixed-arrival trace (prompt lengths 1..12 including
    single-token prompts, fewer slots than requests so rows recycle
    mid-flight): batched admission must be token-for-token identical to
    PR 1's per-request admission AND to sequential generate()."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    dtype = None if dtype_name == "fp32" else jnp.bfloat16
    lm = _make_lm()
    reqs = [([int(rng.randint(1, 30))], 4)]        # a 1-token prompt
    for _ in range(8):
        plen = int(rng.randint(2, 13))
        reqs.append((rng.randint(1, 30, size=(plen,)).tolist(),
                     int(rng.randint(3, 10))))

    outs = {}
    for mode in ("batched", "per_request"):
        eng = ServingEngine(lm, n_slots=3, compute_dtype=dtype,
                            admission=mode)
        ids = [eng.submit(*r) for r in reqs[:3]]
        eng.step(); eng.step()                     # staggered arrivals
        ids += [eng.submit(*r) for r in reqs[3:]]
        res = eng.drain()
        outs[mode] = [res[rid] for rid in ids]
        assert eng.pool.free_slots == eng.pool.n_slots
    for j, (prompt, n_new) in enumerate(reqs):
        want = generate(lm, prompt, length=n_new, temperature=0.0,
                        compute_dtype=dtype)
        np.testing.assert_array_equal(
            outs["batched"][j], want,
            err_msg=f"req {j} prompt={prompt} dtype={dtype_name}")
        np.testing.assert_array_equal(outs["batched"][j],
                                      outs["per_request"][j])


def test_prefix_cache_engine_parity_and_hits(rng):
    """Shared-system-prompt traffic through a prefix-cached engine:
    outputs stay token-for-token equal to generate(), and repeat
    prefixes actually HIT (full, truncated, and suffix partial hits)."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2, admission="batched",
                        prefix_cache=True)
    sys_p = [5, 9, 3, 7, 2, 8]
    reqs = [(sys_p + rng.randint(1, 30, size=(3,)).tolist(), 5)
            for _ in range(4)]
    reqs.append((reqs[0][0], 5))                  # identical: full hit
    reqs.append((sys_p + [4], 5))                 # shorter: truncated hit
    ids = [eng.submit(*r) for r in reqs]
    outs = eng.drain()
    for rid, (p, n) in zip(ids, reqs):
        np.testing.assert_array_equal(
            outs[rid], generate(lm, p, length=n, temperature=0.0),
            err_msg=f"prompt={p}")
    st = eng.prefix_cache.stats()
    assert st["hits"] >= 3 and st["hit_tokens"] >= 3 * len(sys_p)
    assert eng.metrics.summary()["serving/prefix_hit_rate"] > 0


# -- the bounded-compile guarantee -----------------------------------------

def test_prefill_compile_count_bounded_by_buckets(rng):
    """Admitting prompts of MANY distinct lengths must trace a number of
    prefill programs bounded by the power-of-two bucket count — not by
    the number of distinct lengths (PR 1's per-request path compiled one
    program per novel length, mid-admission)."""
    from bigdl_tpu.serving import ServingEngine, bucket_len
    from tests.compile_guards import assert_compile_count

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=16, admission="batched")
    plens = list(range(2, 14))                    # prefill lens 1..12
    rng.shuffle(plens)
    ids = [eng.submit(rng.randint(1, 30, size=(n,)).tolist(),
                      max_new_tokens=3) for n in plens]
    eng.step()                                    # ONE admission round
    distinct = {n - 1 for n in plens}
    buckets = {bucket_len(n - 1, eng.max_len) for n in plens}
    traced = eng.admitter.traced_shapes
    assert len(traced) <= len(buckets) < len(distinct)
    # the jit cache agrees with our shape ledger
    assert_compile_count(eng._batch_prefill_fn, len(traced),
                         what="first admission wave")
    total_compiles, _ = eng.metrics.metrics.get(
        "serving/prefill_bucket_compiles")
    assert total_compiles == len(traced)
    eng.drain()
    # a second wave of the same lengths re-traces NOTHING
    for n in plens:
        eng.submit(rng.randint(1, 30, size=(n,)).tolist(), max_new_tokens=3)
    eng.drain()
    assert_compile_count(eng._batch_prefill_fn, len(traced),
                         what="repeat lengths, same engine")
    assert len(eng.admitter.traced_shapes) == len(traced)
    # a SECOND engine over the same warm model shares the jitted step:
    # same shapes routed, zero new compiles reported
    eng2 = ServingEngine(lm, n_slots=16, admission="batched")
    for n in plens:
        eng2.submit(rng.randint(1, 30, size=(n,)).tolist(),
                    max_new_tokens=3)
    eng2.drain()
    assert len(eng2.admitter.traced_shapes) == len(traced)
    compiles2, _ = eng2.metrics.metrics.get(
        "serving/prefill_bucket_compiles")
    assert compiles2 == 0
    assert_compile_count(eng2._batch_prefill_fn, len(traced),
                         what="second engine, warm model")


# -- PrefixCache unit invariants -------------------------------------------

def _fake_carry(n_tokens, tag=0.0):
    """A carry-shaped stand-in (the cache never inspects leaves beyond
    'pos', so plain numpy is fine for unit tests)."""
    import jax.numpy as jnp

    return {"pos": jnp.full((1,), n_tokens, jnp.int32),
            "k0": np.full((1, 4), tag, np.float32)}


def test_prefix_cache_lookup_hit_miss_and_truncation():
    from bigdl_tpu.serving import PrefixCache

    pc = PrefixCache(max_entries=8)
    assert pc.acquire([1, 2, 3]) == (None, 0, None)     # cold miss
    pc.insert([1, 2, 3, 4], _fake_carry(4, tag=1.0))
    # exact full hit
    carry, m, lease = pc.acquire([1, 2, 3, 4])
    assert m == 4 and carry["k0"][0, 0] == 1.0
    pc.release(lease)
    # longest-prefix (truncated) hit: cached 4 tokens serve a 2-token
    # prefix with pos clamped, same buffers
    carry, m, lease = pc.acquire([1, 2, 9, 9])
    assert m == 2 and int(np.asarray(carry["pos"])[0]) == 2
    assert carry["k0"][0, 0] == 1.0
    pc.release(lease)
    # divergence at the first token: miss
    assert pc.acquire([7, 1, 2]) == (None, 0, None)
    # deeper entries win over shallower ones
    pc.insert([1, 2], _fake_carry(2, tag=2.0))
    carry, m, lease = pc.acquire([1, 2, 3, 4, 5])
    assert m == 4 and carry["k0"][0, 0] == 1.0
    pc.release(lease)
    assert pc.entries == 2 and pc.hit_rate() > 0
    with pytest.raises(ValueError, match="empty"):
        pc.insert([], _fake_carry(0))


def test_prefix_cache_refcount_and_lru_eviction():
    """Invariants: leases pin entries against eviction, refcounts never
    go negative, eviction is LRU among refs==0 entries, and a
    fully-leased cache overflows rather than dropping live state."""
    from bigdl_tpu.serving import PrefixCache

    pc = PrefixCache(max_entries=2)
    pc.insert([1, 1], _fake_carry(2, tag=1.0))
    pc.insert([2, 2], _fake_carry(2, tag=2.0))
    _, _, lease1 = pc.acquire([1, 1])             # pin entry 1
    assert lease1.refs == 1
    pc.insert([3, 3], _fake_carry(2, tag=3.0))    # over capacity
    # entry 2 (LRU among refs==0) evicted; pinned entry 1 survives
    assert pc.entries == 2
    assert pc.acquire([2, 2]) == (None, 0, None)
    c, m, l3 = pc.acquire([3, 3])
    assert m == 2
    pc.release(l3)
    pc.release(lease1)
    with pytest.raises(ValueError, match="release"):
        pc.release(lease1)                        # refcount can't go < 0
    # everything leased → insert overflows instead of evicting live state
    _, _, la = pc.acquire([1, 1])
    _, _, lb = pc.acquire([3, 3])
    pc.insert([4, 4], _fake_carry(2, tag=4.0))
    assert pc.entries == 3                        # temporary overflow
    pc.release(la); pc.release(lb)
    pc.insert([5, 5], _fake_carry(2, tag=5.0))    # now eviction catches up
    assert pc.entries == 2
    with pytest.raises(ValueError, match="max_entries"):
        PrefixCache(0)


# -- bench scenario smoke (tier-1, small/CPU) ------------------------------

def test_admission_bench_smoke():
    """benchmarks/serving_bench.py --scenario admission on a small
    config: identical outputs, a compiled-prefill set bounded by the
    bucket count (vs one program per distinct length on the per-request
    path), reduced admission-phase wall time, and real prefix hits."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmarks"))
    try:
        import serving_bench
    finally:
        sys.path.pop(0)

    out = serving_bench.run_admission(model="tiny", n_requests=12,
                                      gen_tokens=3, n_slots=6,
                                      shared_frac=0.6, prefix_len=8)
    assert out["outputs_match"]
    # the bounded-compile acceptance: per-request compiles per DISTINCT
    # length; batched compiles per bucket (+ suffix-continuation shapes)
    assert out["per_request"]["prefill_programs"] \
        == out["distinct_prompt_lengths"]
    assert out["batched"]["prefill_programs"] \
        <= out["length_buckets"] + 2
    # admission-phase wall time must come DOWN (dominated by the compile
    # stalls the bucket scheme avoids; loose floor for a noisy CI box)
    assert out["admission_speedup"] > 1.05, out
    assert out["batched"]["prefix_hit_tokens"] > 0


def test_bucket_len():
    from bigdl_tpu.serving import bucket_len

    assert [bucket_len(n, 48) for n in (1, 2, 3, 5, 16, 17, 47, 300)] \
        == [1, 2, 4, 8, 16, 32, 48, 48]
    with pytest.raises(ValueError, match="positive"):
        bucket_len(0, 48)
