"""Example programs (SURVEY.md §2.8 example/* rows)."""

import numpy as np


def test_loadmodel_bigdl_roundtrip(tmp_path, rng):
    from bigdl_tpu.examples import loadmodel
    from bigdl_tpu.nn import Linear, Sequential, SoftMax

    m = Sequential().add(Linear(6, 3)).add(SoftMax())
    m._ensure_params()
    path = str(tmp_path / "m.bigdl")
    m.save_module(path)
    loaded = loadmodel.main(["--modelType", "bigdl", "--model", path,
                             "--inputShape", "6", "-b", "2"])
    assert type(loaded).__name__ == "Sequential"


def test_udfpredictor_end_to_end():
    from bigdl_tpu.examples import udfpredictor

    labels = udfpredictor.main([])
    assert labels == [1, 2], f"udf misclassified: {labels}"
