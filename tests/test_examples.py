"""Example programs (SURVEY.md §2.8 example/* rows)."""

import numpy as np
import pytest


def test_loadmodel_bigdl_roundtrip(tmp_path, rng):
    from bigdl_tpu.examples import loadmodel
    from bigdl_tpu.nn import Linear, Sequential, SoftMax

    m = Sequential().add(Linear(6, 3)).add(SoftMax())
    m._ensure_params()
    path = str(tmp_path / "m.bigdl")
    m.save_module(path)
    loaded = loadmodel.main(["--modelType", "bigdl", "--model", path,
                             "--inputShape", "6", "-b", "2"])
    assert type(loaded).__name__ == "Sequential"


def test_udfpredictor_end_to_end():
    from bigdl_tpu.examples import udfpredictor

    labels = udfpredictor.main([])
    assert labels == [1, 2], f"udf misclassified: {labels}"


def test_imageclassification_example(tmp_path, rng):
    from PIL import Image

    from bigdl_tpu.examples import imageclassification
    from bigdl_tpu.nn import Linear, Sequential, SoftMax
    from bigdl_tpu.nn.shape_ops import Reshape

    # tiny image folder: 2 classes x 3 images
    for cls in ("cats", "dogs"):
        d = tmp_path / "imgs" / cls
        d.mkdir(parents=True)
        for i in range(3):
            arr = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(str(d / f"{i}.png"))

    m = (Sequential().add(Reshape([8 * 8 * 3], batch_mode=True))
         .add(Linear(8 * 8 * 3, 2)).add(SoftMax()))
    m._ensure_params()
    mp = str(tmp_path / "m.bigdl")
    m.save_module(mp)

    preds = imageclassification.main([
        "--model", mp, "-f", str(tmp_path / "imgs"), "--imageSize", "8",
        "-b", "4",
    ])
    assert len(preds) == 6
    assert set(int(p) for p in preds) <= {1, 2}


def test_languagemodel_example_beam_generation(capsys):
    from bigdl_tpu.examples.languagemodel import main

    model = main(["--synthetic", "48", "--maxEpoch", "1", "--batchSize", "16",
                  "--vocab", "30", "--seqLen", "8", "--hidden", "16",
                  "--beam", "3", "--genLen", "5"])
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.startswith("beam ")]
    assert len(lines) == 3
    # generated ids must be in-vocabulary (1-based; eos id 0 unreachable)
    toks = [int(t) for t in lines[0].split()[4:]]
    assert len(toks) == 5 and all(1 <= t <= 30 for t in toks)


def test_mlpipeline_example_learns():
    from bigdl_tpu.examples.mlpipeline import main

    acc = main(["--samples", "256", "--maxEpoch", "5", "--batchSize", "64"])
    assert acc > 0.7  # separable blobs: must beat chance (1/3) by a margin


@pytest.mark.integration
def test_transformer_generation_example(capsys):
    from bigdl_tpu.examples.transformergeneration import main

    main(["--synthetic", "32", "--maxEpoch", "1", "--batchSize", "16",
          "--vocab", "20", "--seqLen", "12", "--hidden", "16",
          "--layers", "1", "--heads", "2",
          "--beam", "2", "--genLen", "4", "--topK", "4"])
    out = capsys.readouterr().out
    assert "greedy :" in out and "sampled:" in out
    beams = [l for l in out.splitlines() if l.startswith("beam ")]
    assert len(beams) == 2


def test_distributed_pod_example_smoke(tmp_path):
    """The pod-training example end to end in its single-process shape:
    partitioned DP, async checkpoints, preemption hook armed, validation.
    Blockstore mode with drop configured also runs."""
    from bigdl_tpu.examples import distributed_pod

    trained = distributed_pod.main([
        "-b", "32", "--maxIteration", "6", "--nSamples", "64",
        "--checkpoint", str(tmp_path / "ck"),
    ])
    assert trained is not None
    import os

    assert any(f.startswith("orbax")
               for f in os.listdir(str(tmp_path / "ck")))

    import os as _os

    _os.environ["BIGDL_BLOCKSTORE_DIR"] = str(tmp_path / "bs")
    try:
        trained = distributed_pod.main([
            "-b", "32", "--maxIteration", "4", "--nSamples", "64",
            "--parameterMode", "blockstore", "--dropPercentage", "0.1",
        ])
    finally:
        _os.environ.pop("BIGDL_BLOCKSTORE_DIR", None)
    assert trained is not None
