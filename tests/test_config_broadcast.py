"""Typed config (SURVEY.md §5.6) + ModelBroadcast (§2.4)."""

import os

import numpy as np


def test_config_env_overlay(monkeypatch):
    from bigdl_tpu.utils.config import BigDLConfig

    monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "9")
    monkeypatch.setenv("BIGDL_COMPUTE_DTYPE", "bf16")
    monkeypatch.setenv("BIGDL_LOCAL_MODE", "true")
    cfg = BigDLConfig.from_env()
    assert cfg.failure_retry_times == 9
    assert cfg.compute_dtype == "bf16"
    assert cfg.local_mode is True
    # explicit overrides beat env
    cfg2 = BigDLConfig.from_env(failure_retry_times=2)
    assert cfg2.failure_retry_times == 2


def test_config_applies_to_optimizer(rng):
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer
    from bigdl_tpu.utils.config import BigDLConfig

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(8)]
    opt = Optimizer(model=Sequential().add(Linear(4, 2)), dataset=samples,
                    criterion=MSECriterion(), batch_size=4)
    cfg = BigDLConfig(compute_dtype="bf16", loss_scale=8.0,
                      failure_retry_times=3)
    cfg.apply_optimizer(opt)
    assert opt.compute_dtype == "bf16"
    assert opt.loss_scale == 8.0
    assert opt.retry_times == 3


def test_model_broadcast_places_replicated(rng):
    import jax
    from jax.sharding import Mesh

    from bigdl_tpu.nn import Linear, Sequential
    from bigdl_tpu.parallel import ModelBroadcast

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    m = Sequential().add(Linear(4, 3))
    bc = ModelBroadcast().broadcast(mesh, m)
    params = bc.value()
    leaves = jax.tree_util.tree_leaves(params)
    assert leaves, "no parameters placed"
    for l in leaves:
        assert l.sharding.is_fully_replicated


def test_thread_pool_invoke_and_wait():
    import time

    from bigdl_tpu.utils.thread_pool import ThreadPool

    pool = ThreadPool(4)
    t0 = time.time()
    out = pool.invoke_and_wait([lambda i=i: (time.sleep(0.05), i * i)[1]
                                for i in range(8)])
    assert out == [i * i for i in range(8)]
    # parallel (2 waves of 4), comfortably under the 0.4s serial time
    assert time.time() - t0 < 0.4 * 0.9
    futs = pool.invoke([lambda: 42])
    assert futs[0].result() == 42
    pool.shutdown()
