"""Block-store parameter plane + gradient-drop straggler mitigation
(reference anchors, UNVERIFIED per SURVEY §0: AllReduceParameter.scala's
BlockManager exchange; DistriOptimizer.scala dropPercentage/
computeThresholdbatchSize/warmupIterationNum — SURVEY §5.3).

The exchange logic takes (pid, n_procs) explicitly, so the full
putGradients → aggregate-with-drop → publish/get weights dataflow is
driven here with THREADS over one FsBlockStore — the pod test
(test_multihost.py) re-runs it with real jax.distributed processes over
the coordination-service store."""

import threading
import time

import numpy as np
import pytest

from bigdl_tpu.parallel.block_store import (
    BlockStoreParameter, FsBlockStore, GradientDropPolicy, decode_array,
    encode_array,
)


# -- codec / store primitives ---------------------------------------------

@pytest.mark.parametrize("arr", [
    np.arange(7, dtype=np.float32),
    np.zeros((0,), np.float64),
    np.random.RandomState(0).rand(3, 4).astype(np.float16),
    np.array(3.5, np.float32),
    np.arange(6, dtype=np.int64).reshape(2, 3),
])
def test_array_codec_roundtrip(arr):
    out = decode_array(encode_array(arr))
    assert out.dtype == arr.dtype and out.shape == arr.shape
    np.testing.assert_array_equal(out, arr)


def test_fs_store_put_get_delete(tmp_path):
    st = FsBlockStore(str(tmp_path / "bs"))
    assert st.try_get("a/b") is None
    st.put("a/b", b"xyz")
    assert st.try_get("a/b") == b"xyz"
    st.put("a/b", b"overwritten")
    assert st.try_get("a/b") == b"overwritten"
    st.delete("a/b")
    st.delete("a/b")  # idempotent
    assert st.try_get("a/b") is None
    with pytest.raises(TimeoutError):
        st.get_blocking("missing", timeout_s=0.05)


# -- drop policy -----------------------------------------------------------

def test_drop_policy_warmup_and_threshold():
    p = GradientDropPolicy(0.3, compute_threshold_batch_size=10,
                           warmup_iteration=3, min_deadline_s=0.0)
    assert p.deadline(0) is None            # warmup
    for d in [0.1] * 7 + [1.0] * 3:
        p.record(d)
    assert p.deadline(2) is None            # still warmup
    dl = p.deadline(3)
    # 70th percentile of 7x0.1 + 3x1.0 sits in the fast cluster
    assert dl is not None and dl < 0.5
    assert p.min_arrivals(3) == 3  # ceil((1-0.3)*3) = ceil(2.1)
    assert GradientDropPolicy(0.5).min_arrivals(4) == 2
    assert GradientDropPolicy(0.9).min_arrivals(10) == 1


def test_drop_policy_floor_and_validation():
    p = GradientDropPolicy(0.5, warmup_iteration=0, min_deadline_s=0.25)
    p.record(0.001)
    assert p.deadline(1) == 0.25            # floored
    with pytest.raises(ValueError):
        GradientDropPolicy(1.0)
    with pytest.raises(ValueError):
        GradientDropPolicy(0.5, max_drop_percentage=0.2)


def test_calibration_epoch_is_publish_time_not_aggregation_start(tmp_path):
    """A contribution that lands BEFORE the owner starts aggregating must
    still record its true publish→arrival duration (the blob's embedded
    send marker), not ~0 s — otherwise an owner that is itself the slowest
    process collapses the calibration window to min_deadline_s and drops
    honest peers on the first jitter (round-4 ADVICE low)."""
    store = FsBlockStore(str(tmp_path / "bs"))
    policy = GradientDropPolicy(0.5, warmup_iteration=0,
                                min_deadline_s=0.05)
    owner = BlockStoreParameter(store, 2, 0, 8, drop_policy=policy,
                                timeout_s=5.0)
    peer = BlockStoreParameter(store, 2, 1, 8, timeout_s=5.0)
    g = np.ones(8, np.float32)

    peer.put_gradients(0, g)       # peer publishes early...
    time.sleep(0.2)                # ...owner is slow to reach aggregation
    owner.put_gradients(0, g)
    owner.aggregate_my_partition(0)
    assert len(policy._samples) == 1
    # the sample reflects the 0.2 s the blob sat in the store, not the
    # ~0 s the owner waited after starting aggregation
    assert policy._samples[0] >= 0.15, list(policy._samples)


def test_calibration_also_captures_compute_slow_peers(tmp_path):
    """The other side of the epoch fix: a peer whose COMPUTE lags (publish
    late, transfer instant) must still register its full lateness — the
    sample is max(wait-since-aggregation-start, transfer), so the deadline
    can adapt upward and a recovered straggler re-enters (the round-5
    review's heterogeneous-pod scenario)."""
    store = FsBlockStore(str(tmp_path / "bs"))
    policy = GradientDropPolicy(0.5, warmup_iteration=0,
                                min_deadline_s=0.05)
    owner = BlockStoreParameter(store, 2, 0, 8, drop_policy=policy,
                                timeout_s=5.0)
    peer = BlockStoreParameter(store, 2, 1, 8, timeout_s=5.0)
    g = np.ones(8, np.float32)

    owner.put_gradients(0, g)

    def late_publish():
        time.sleep(0.2)        # compute skew; the transfer itself is fast
        peer.put_gradients(0, g)

    th = threading.Thread(target=late_publish)
    th.start()
    owner.aggregate_my_partition(0)   # no deadline yet (first sample)
    th.join()
    assert len(policy._samples) == 1
    # the owner waited ~0.2 s; the sample must reflect that wait, not the
    # ~0 s publish→arrival transfer time
    assert policy._samples[0] >= 0.15, list(policy._samples)


def test_calibration_transfer_term_immune_to_clock_skew(tmp_path):
    """Positive wall-clock skew (owner clock AHEAD of the sender) must not
    inflate steady-state calibration samples: the per-peer min-offset
    baseline cancels a constant offset from the second marker on, so skew
    cannot permanently inflate the drop deadline and silently disable
    straggler drops (round-5 ADVICE). The first marker (no baseline yet)
    may carry the offset once — it ages out of the bounded window."""
    from unittest import mock

    import bigdl_tpu.parallel.block_store as bs

    store = FsBlockStore(str(tmp_path / "bs"))
    policy = GradientDropPolicy(0.5, warmup_iteration=0,
                                min_deadline_s=0.05)
    owner = BlockStoreParameter(store, 2, 0, 8, drop_policy=policy,
                                timeout_s=5.0)
    peer = BlockStoreParameter(store, 2, 1, 8, timeout_s=5.0)
    g = np.ones(8, np.float32)
    real_time = time.time
    for t in range(3):
        # the peer's send markers are stamped by a clock 5 s BEHIND the
        # owner's → every raw publish→arrival delta is ~+5 s
        with mock.patch.object(bs.time, "time",
                               lambda: real_time() - 5.0):
            peer.put_gradients(t, g)
        owner.put_gradients(t, g)
        owner.aggregate_my_partition(t)
    samples = list(policy._samples)
    assert len(samples) == 3
    assert samples[0] >= 4.0, samples       # first marker: raw (no baseline)
    # thereafter the constant offset cancels — samples are the genuine
    # excess transfer/queue delay (~0 here), not the 5 s skew
    assert max(samples[1:]) < 1.0, samples


def test_coord_store_self_check_raises_runtime_error():
    """The startup self-check must verify its probes with explicit raises
    (not bare ``assert``, which ``python -O`` strips — round-4 ADVICE
    low): a client whose deletes don't take must fail construction with
    the classification RuntimeError."""
    from bigdl_tpu.parallel.block_store import CoordServiceBlockStore

    class StickyClient:
        """key_value_delete silently no-ops, so the 'missing' probe sees a
        stale value — exactly the condition the bare assert guarded."""

        def __init__(self):
            self.kv = {"bigdl_bs/selfcheck/0": b"stale"}

        def key_value_set_bytes(self, k, v):
            if k in self.kv:
                raise RuntimeError(f"ALREADY_EXISTS: {k}")
            self.kv[k] = v

        def key_value_try_get_bytes(self, k):
            if k not in self.kv:
                raise RuntimeError(f"NOT_FOUND: {k}")
            return self.kv[k]

        def key_value_delete(self, k):
            pass  # broken: delete never lands

    store = CoordServiceBlockStore.__new__(CoordServiceBlockStore)
    store._client = StickyClient()
    store._prefix = "bigdl_bs"
    import unittest.mock as mock

    with mock.patch("jax.process_index", return_value=0):
        with pytest.raises(RuntimeError, match="self-check failed"):
            store._self_check()


# -- threaded exchange ----------------------------------------------------

def _run_exchange(store, n, total, grads_by_pid, w0, n_iters=1,
                  policies=None, put_delays=None, lr=0.1):
    """Drive n BlockStoreParameter instances with threads. Each iteration:
    everyone contributes its gradient, owners aggregate + SGD-update their
    slice, everyone assembles the new full vector. Returns (final weights
    per pid, bsp objects)."""
    results = [None] * n
    bsps = [None] * n
    errors = []

    def worker(pid):
        try:
            st = store
            if put_delays and put_delays.get(pid):
                spec = put_delays[pid]
                # scalar delay, or (delay, first_iter, last_iter) for a
                # straggler that heals mid-run
                st = _DelayedStore(store, *spec) \
                    if isinstance(spec, tuple) else _DelayedStore(store, spec)
            bsp = BlockStoreParameter(
                st, n, pid, total,
                drop_policy=policies[pid] if policies else None,
                timeout_s=30.0)
            bsps[pid] = bsp
            w = w0.copy()
            for t in range(n_iters):
                g = grads_by_pid[pid](t, w)
                bsp.put_gradients(t, g)
                gmy, _, _ = bsp.aggregate_my_partition(t)
                wpad = bsp._pad(w)
                lo = pid * bsp.shard_size
                new_w = wpad[lo:lo + bsp.shard_size] - lr * gmy
                bsp.publish_weights(t + 1, new_w)
                w = bsp.get_weights(t + 1)
            results[pid] = w
        except Exception as e:  # pragma: no cover - surfaced in assert
            errors.append((pid, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert not errors, errors
    return results, bsps


from tests.straggler import DelayedGradientPuts as _DelayedStore  # noqa: E402


def test_threaded_exchange_matches_numpy(tmp_path):
    """3 contributors, no stragglers: the assembled update must equal the
    plain numpy mean-gradient SGD step, and every pid must agree."""
    rs = np.random.RandomState(1)
    total, n = 103, 3  # deliberately not divisible by n (padding path)
    w0 = rs.rand(total).astype(np.float32)
    gs = [rs.rand(total).astype(np.float32) for _ in range(n)]
    store = FsBlockStore(str(tmp_path / "bs"))

    results, _ = _run_exchange(
        store, n, total, [lambda t, w, g=g: g for g in gs], w0, n_iters=2)

    # numpy oracle: two SGD steps on the mean gradient
    w = w0.copy()
    for _ in range(2):
        w = w - 0.1 * np.mean(gs, axis=0)
    for pid in range(n):
        np.testing.assert_allclose(results[pid], w, rtol=1e-6, atol=1e-6)


def test_threaded_exchange_drops_straggler(tmp_path):
    """pid 2's gradient puts are delayed past the calibrated deadline:
    owners 0 and 1 must aggregate without it (mean over 2 contributions),
    while pid 2's own partition still sees all 3. Weights stay identical
    across pids."""
    rs = np.random.RandomState(2)
    total, n = 60, 3
    w0 = np.zeros(total, np.float32)
    gs = [np.full(total, float(pid + 1), np.float32) for pid in range(n)]

    store = FsBlockStore(str(tmp_path / "bs"))
    policies = [GradientDropPolicy(0.34, warmup_iteration=1,
                                   min_deadline_s=0.15)
                for _ in range(n)]
    n_iters = 3  # iter 0 calibrates (no drop), iters 1-2 drop
    results, bsps = _run_exchange(
        store, n, total, [lambda t, w, g=g: g for g in gs], w0,
        n_iters=n_iters, policies=policies, put_delays={2: 1.2})

    # everyone assembled identical weights
    for pid in range(1, n):
        np.testing.assert_array_equal(results[0], results[pid])

    # owners 0 and 1 dropped pid 2's contribution in the post-warmup iters
    assert bsps[0].dropped_total == n_iters - 1
    assert bsps[1].dropped_total == n_iters - 1
    assert bsps[2].dropped_total == 0  # its own partition got fast blocks

    # slice-level oracle: partition 0/1 slices saw mean(g0,g1) after
    # warmup, mean(g0,g1,g2) during it; partition 2 always all three
    sh = bsps[0].shard_size
    mean01 = (1.0 + 2.0) / 2
    mean012 = (1.0 + 2.0 + 3.0) / 3
    exp = np.zeros(total, np.float32)
    exp[:sh] -= 0.1 * (mean012 + 2 * mean01)       # partition 0
    exp[sh:2 * sh] -= 0.1 * (mean012 + 2 * mean01)  # partition 1
    exp[2 * sh:] -= 0.1 * (3 * mean012)             # partition 2 (no drop)
    np.testing.assert_allclose(results[0], exp, rtol=1e-6, atol=1e-6)


def test_drop_deadline_recovers_after_straggler(tmp_path):
    """The calibration window must NOT ratchet down: a deadline-truncated
    wait is never recorded (it equals the deadline by construction), and a
    dropped contribution that lands late is probed on the next aggregation
    so its true (upper-bound) duration enters the window and the quantile
    can rise again once the straggler recovers."""
    store = FsBlockStore(str(tmp_path / "bs"))
    policy = GradientDropPolicy(0.5, warmup_iteration=0,
                                compute_threshold_batch_size=8,
                                min_deadline_s=0.05)
    owner = BlockStoreParameter(store, 2, 0, 8, drop_policy=policy,
                                timeout_s=5.0)
    peer = BlockStoreParameter(store, 2, 1, 8, timeout_s=5.0)
    g = np.ones(8, np.float32)

    # t=0: healthy iteration — one fast per-contribution sample
    peer.put_gradients(0, g)
    owner.put_gradients(0, g)
    owner.aggregate_my_partition(0)
    assert len(policy._samples) == 1

    # t=1: peer absent — the owner drops it at the 0.05s floor; the
    # truncated wait must NOT enter the window
    owner.put_gradients(1, g)
    _, arrived, dropped = owner.aggregate_my_partition(1)
    assert arrived == 1 and dropped == [1]
    assert owner.dropped_total == 1
    assert len(policy._samples) == 1          # no deadline-valued sample
    # and the (not-yet-arrived) late block was NOT pre-deleted
    peer.put_gradients(1, g * 2.0)            # lands AFTER the drop
    time.sleep(0.06)

    # t=2: the probe sees iteration 1's late arrival, records its true
    # (upper-bound) duration — which exceeds the floor — and reaps it
    peer.put_gradients(2, g)
    owner.put_gradients(2, g)
    owner.aggregate_my_partition(2)
    late = [s for s in policy._samples if s > 0.05]
    assert late, list(policy._samples)        # window can adapt upward
    assert store.try_get(owner._gkey(1, 0, 1)) is None
    assert not owner._late_probes


@pytest.mark.integration
def test_drop_policy_width8_targeting_and_recovery(tmp_path):
    """The drop policy at realistic width (round-5 verdict item #5):
    8 contributors, drop_percentage=0.15 (min_arrivals=ceil(0.85*8)=7),
    ONE persistent transfer-straggler that heals mid-run. Asserts
    (a) warmup holds (no drops while calibrating), (b) targeting — every
    drop across all 7 healthy owners names ONLY the straggler, (c) the
    straggler's own partition never drops, (d) after the heal the
    late-arrival probes let iterations proceed without drops, and
    (e) weights stay identical across all pids (weight partitions are
    never dropped)."""
    n, total = 8, 96
    straggler = n - 1
    warmup, heal_after = 2, 5      # straggle iters 2..5, healed from 6
    n_iters = 9
    rs = np.random.RandomState(4)
    gs = [np.full(total, float(pid + 1), np.float32) for pid in range(n)]
    w0 = np.zeros(total, np.float32)

    store = FsBlockStore(str(tmp_path / "bs"))
    policies = [GradientDropPolicy(0.15, warmup_iteration=warmup,
                                   min_deadline_s=0.12)
                for _ in range(n)]
    results, bsps = _run_exchange(
        store, n, total, [lambda t, w, g=g: g for g in gs], w0,
        n_iters=n_iters, policies=policies,
        put_delays={straggler: (0.9, warmup, heal_after)})

    # (e) identical weights everywhere
    for pid in range(1, n):
        np.testing.assert_array_equal(results[0], results[pid])

    healthy = [p for p in range(n) if p != straggler]
    total_drops = sum(bsps[p].dropped_total for p in healthy)
    assert total_drops > 0, "straggler was never dropped"
    for p in healthy:
        # (a) no drops inside the warmup window
        assert all(t >= warmup for t, _ in bsps[p].drop_log), \
            bsps[p].drop_log
        # (b) every drop names only the straggler
        assert set(bsps[p].dropped_by_src) <= {straggler}, (
            p, bsps[p].dropped_by_src)
        # (d) healed iterations (probe recovery margin of one iteration
        # after the last straggled put) proceed without drops
        assert all(t <= heal_after + 1 for t, _ in bsps[p].drop_log), \
            bsps[p].drop_log
    # (c) the straggler's own partition always aggregated cleanly
    assert bsps[straggler].dropped_total == 0
    # at 15% drop on 8 contributors min_arrivals is 7: at most ONE
    # contribution (the straggler's) may be missing per aggregation
    for p in healthy:
        assert all(len(srcs) == 1 for _, srcs in bsps[p].drop_log)


def test_late_blocks_garbage_collected(tmp_path):
    """A contribution landing after the owner's post-aggregation delete is
    reaped by the t+2 sweep — no leaked blocks."""
    store = FsBlockStore(str(tmp_path / "bs"))
    bsp = BlockStoreParameter(store, 2, 0, 10, timeout_s=5.0)
    peer = BlockStoreParameter(store, 2, 1, 10, timeout_s=5.0)

    g = np.ones(10, np.float32)
    for t in range(4):
        # peer contributes BEFORE owner aggregates at t=0..2
        peer.put_gradients(t, g * (t + 1))
        bsp.put_gradients(t, g)
        bsp.aggregate_my_partition(t)
        # late duplicate lands AFTER the delete (straggling retransmit)
        store.put(bsp._gkey(t, 0, 1), encode_array(g[:bsp.shard_size]))
    # the t=0 and t=1 late blocks were swept by the t=2/t=3 GC pass
    assert store.try_get(bsp._gkey(0, 0, 1)) is None
    assert store.try_get(bsp._gkey(1, 0, 1)) is None
    del peer


# -- single-process DistriOptimizer blockstore mode ------------------------

def test_blockstore_mode_trains_and_matches_local(tmp_path):
    """parameter_mode='blockstore' with one process must track the plain
    LocalOptimizer trajectory (mean over 1 process = full-batch gradient)
    and drive the loss down through the real store roundtrip."""
    import jax

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils.random_gen import RNG

    rs = np.random.RandomState(0)
    samples = [Sample(rs.rand(1, 28, 28).astype(np.float32),
                      np.float32(i % 10 + 1)) for i in range(64)]

    def train(mode):
        RNG.set_seed(7)
        model = LeNet5(10)
        kw = {}
        if mode == "blockstore":
            from bigdl_tpu.parallel.block_store import FsBlockStore

            kw = dict(distributed=True, parameter_mode="blockstore",
                      block_store=FsBlockStore(str(tmp_path / "bs")))
        opt = Optimizer(model=model, dataset=DataSet.array(samples),
                        criterion=ClassNLLCriterion(), batch_size=16,
                        end_trigger=Trigger.max_iteration(4), **kw)
        opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9))
        trained = opt.optimize()
        ws, _ = trained.parameters()
        return np.concatenate([np.asarray(w, np.float32).ravel()
                               for w in ws])

    w_local = train("local")
    w_bs = train("blockstore")
    assert w_local.shape == w_bs.shape
    np.testing.assert_allclose(w_bs, w_local, rtol=5e-4, atol=5e-5)


def test_drop_property_requires_blockstore_mode():
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

    opt = DistriOptimizer.__new__(DistriOptimizer)
    opt.parameter_mode = "partitioned"
    with pytest.raises(ValueError, match="blockstore"):
        DistriOptimizer.set_drop_module_property(opt, 0.1)


def test_codec_bfloat16():
    import ml_dtypes

    arr = np.arange(5, dtype=np.float32).astype(ml_dtypes.bfloat16)
    out = decode_array(encode_array(arr))
    assert out.dtype == arr.dtype
    np.testing.assert_array_equal(out.astype(np.float32),
                                  arr.astype(np.float32))


def test_threaded_exchange_bf16_compress(tmp_path):
    """compress='bf16' (the FP16CompressedTensor analog) must round-trip
    through the store: the aggregated mean equals the numpy oracle within
    bf16 quantization error."""
    rs = np.random.RandomState(3)
    total, n = 40, 2
    w0 = rs.rand(total).astype(np.float32)
    gs = [rs.rand(total).astype(np.float32) for _ in range(n)]
    store = FsBlockStore(str(tmp_path / "bs"))
    results = [None] * n
    errors = []

    def worker(pid):
        try:
            bsp = BlockStoreParameter(store, n, pid, total,
                                      compress="bf16", timeout_s=30.0)
            bsp.put_gradients(0, gs[pid])
            gmy, _, _ = bsp.aggregate_my_partition(0)
            wpad = bsp._pad(w0)
            lo = pid * bsp.shard_size
            bsp.publish_weights(1, wpad[lo:lo + bsp.shard_size] - 0.1 * gmy)
            results[pid] = bsp.get_weights(1)
        except Exception as e:  # pragma: no cover
            errors.append((pid, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert not errors, errors
    np.testing.assert_array_equal(results[0], results[1])
    # bf16 has ~3 decimal digits: remote halves quantized, own half exact
    np.testing.assert_allclose(results[0], w0 - 0.1 * np.mean(gs, axis=0),
                               rtol=2e-2, atol=2e-2)


def test_sweep_stale_reaps_previous_attempt(tmp_path):
    """Retry-from-checkpoint restarts the iteration counter at 0: blocks a
    previous attempt left behind must be reaped by sweep_stale so they
    can't alias the retried run's same-numbered iterations."""
    store = FsBlockStore(str(tmp_path / "bs"))
    bsp = BlockStoreParameter(store, 2, 0, 10, timeout_s=5.0)
    g = np.ones(10, np.float32)
    for t in range(5):  # "previous attempt" reaches iteration 4
        bsp.put_gradients(t, g)
        bsp._my_slice_cache = None
        bsp.publish_weights(t + 1, g[:bsp.shard_size])
        bsp.publish_aux(t, "loss", np.float32(1.0))
    assert store.try_get(bsp._gkey(4, 1, 0)) is not None

    fresh = BlockStoreParameter(store, 2, 0, 10, timeout_s=5.0)
    fresh.sweep_stale(aux_names=("loss",))
    for t in range(2, 6):
        assert store.try_get(fresh._gkey(t, 1, 0)) is None, t
        assert store.try_get(fresh._wkey(t, 0)) is None, t
    assert store.try_get(f"{fresh.ns}/pos/0") is None
    # sweeping with no marker is a no-op
    fresh.sweep_stale()


def test_blockstore_mode_applies_regularizer_gradient(tmp_path):
    """A layer-level L2 regularizer must actually move the weights in
    blockstore mode (a closed-over pytree in the loss would silently
    contribute zero gradient): with lr*wd shrinkage and zero data gradient,
    one step multiplies weights by (1 - lr*wd)."""
    import jax.numpy as jnp

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Identity, Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.optim.regularizer import L2Regularizer
    from bigdl_tpu.parallel.block_store import FsBlockStore
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(3)
    model = Linear(4, 4, with_bias=False,
                   w_regularizer=L2Regularizer(0.5))
    model._ensure_params()
    w_before = np.asarray(
        jax.tree_util.tree_leaves(model.params)[0]).copy()

    # zero input -> zero data gradient; only the regularizer acts
    samples = [Sample(np.zeros(4, np.float32), np.zeros(4, np.float32))
               for _ in range(8)]
    opt = Optimizer(model=model, dataset=DataSet.distributed(samples),
                    criterion=MSECriterion(), batch_size=8,
                    parameter_mode="blockstore",
                    block_store=FsBlockStore(str(tmp_path / "bs")),
                    end_trigger=Trigger.max_iteration(1))
    opt.set_optim_method(SGD(learning_rate=0.1))
    trained = opt.optimize()
    w_after = np.asarray(trained.parameters()[0][0])
    # d/dw (0.5*wd*||w||^2) = wd*w  ->  w' = w(1 - lr*wd) = 0.95*w
    np.testing.assert_allclose(w_after, w_before * (1 - 0.1 * 0.5),
                               rtol=1e-5, atol=1e-6)


import jax  # noqa: E402  (used by the regularizer test)
