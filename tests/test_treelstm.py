"""BinaryTreeLSTM vs a recursive python oracle + treeLSTMSentiment
end-to-end (SURVEY.md §2.8 treeLSTMSentiment row)."""

import numpy as np
import pytest

from tests.oracle import assert_close


def _tree_arrays():
    """((the cat) (sat down)) — 7 nodes, children before parents, 1-based
    child indices; node 7 is the root."""
    word = np.array([1, 2, 0, 3, 4, 0, 0], np.int32)   # leaves: the cat sat down
    left = np.array([0, 0, 1, 0, 0, 4, 3], np.int32)
    right = np.array([0, 0, 2, 0, 0, 5, 6], np.int32)
    return word, left, right


def _oracle(params, word, left, right, H):
    """Recursive reference implementation (pure numpy)."""
    import jax

    emb = np.asarray(params["embedding"])
    w_leaf, b_leaf = np.asarray(params["w_leaf"]), np.asarray(params["b_leaf"])
    w_comp, b_comp = np.asarray(params["w_comp"]), np.asarray(params["b_comp"])
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))

    memo = {}

    def node(i):  # 0-based
        if i in memo:
            return memo[i]
        if word[i] > 0:
            iou = emb[word[i] - 1] @ w_leaf + b_leaf
            i_g, o_g, u_g = sig(iou[:H]), sig(iou[H:2 * H]), np.tanh(iou[2 * H:])
            c = i_g * u_g
            h = o_g * np.tanh(c)
        else:
            hl, cl = node(left[i] - 1)
            hr, cr = node(right[i] - 1)
            g = np.concatenate([hl, hr]) @ w_comp + b_comp
            i_g, o_g = sig(g[:H]), sig(g[H:2 * H])
            u_g = np.tanh(g[2 * H:3 * H])
            f_l, f_r = sig(g[3 * H:4 * H]), sig(g[4 * H:])
            c = i_g * u_g + f_l * cl + f_r * cr
            h = o_g * np.tanh(c)
        memo[i] = (h, c)
        return memo[i]

    return np.stack([node(i)[0] for i in range(len(word))])


def test_treelstm_matches_recursive_oracle(rng):
    from bigdl_tpu.models.treelstm import BinaryTreeLSTM

    H = 6
    m = BinaryTreeLSTM(vocab_size=10, embedding_dim=5, hidden_size=H)
    m._ensure_params()
    word, left, right = _tree_arrays()
    out = np.asarray(m.forward([word[None], left[None], right[None]]))[0]
    want = _oracle(m.params, word, left, right, H)
    assert_close(out, want, atol=1e-5)


def test_treelstm_padding_nodes_zero(rng):
    from bigdl_tpu.models.treelstm import BinaryTreeLSTM

    m = BinaryTreeLSTM(vocab_size=10, embedding_dim=4, hidden_size=5)
    m._ensure_params()
    word, left, right = _tree_arrays()
    # pad to 10 nodes
    pad = lambda a: np.concatenate([a, np.zeros(3, np.int32)])
    out = np.asarray(m.forward([pad(word)[None], pad(left)[None],
                                pad(right)[None]]))[0]
    assert np.all(out[7:] == 0), "padding nodes must produce zero states"
    assert np.abs(out[:7]).sum() > 0


def test_treenn_accuracy():
    from bigdl_tpu.optim import TreeNNAccuracy

    # 2 trees, 3 nodes each; root = last labeled node
    out = np.zeros((2, 3, 4), np.float32)
    out[0, 2, 1] = 5.0   # tree0 root predicts class 2
    out[0, 0, 0] = 5.0   # tree0 leaf predicts class 1
    out[1, 1, 3] = 5.0   # tree1 root (node 1) predicts class 4
    target = np.array([[1, 0, 2],    # labels: leaf=1, pad, root=2
                       [3, 4, 0]], np.float32)  # leaf=3, root=4, pad
    root_acc, n = TreeNNAccuracy().apply(out, target).result()
    assert n == 2 and root_acc == 1.0
    all_acc, n_all = TreeNNAccuracy(all_nodes=True).apply(out, target).result()
    assert n_all == 4
    assert abs(all_acc - 3 / 4) < 1e-9  # tree1 leaf (class 3) mispredicted


@pytest.mark.integration
def test_treelstm_sentiment_trains(rng):
    """End-to-end: sentiment of tiny synthetic trees becomes learnable."""
    import jax

    from bigdl_tpu.models.treelstm import TreeLSTMSentiment
    from bigdl_tpu.nn import ClassNLLCriterion, TimeDistributedCriterion
    from bigdl_tpu.optim import LBFGS

    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)  # a well-conditioned init basin for this tiny problem
    V, N, B = 8, 7, 16
    model = TreeLSTMSentiment(V, embedding_dim=8, hidden_size=8, class_num=2)
    model._ensure_params()

    word, left, right = _tree_arrays()
    words = np.stack([word] * B)
    # class depends on the first leaf's token id
    rngs = np.random.RandomState(0)
    labels = np.zeros((B, N), np.float32)
    for b in range(B):
        tok = rngs.randint(1, V + 1)
        words[b, 0] = tok
        labels[b, :] = 0
        labels[b, 6] = 1 + (tok % 2)  # root label only
    lefts = np.stack([left] * B)
    rights = np.stack([right] * B)

    crit = TimeDistributedCriterion(ClassNLLCriterion())

    def feval(p):
        def loss_fn(pp):
            out, _ = model.apply(pp, [words, lefts, rights], model.state)
            # mask unlabeled nodes: select root column only
            root_logp = out[:, 6, :]
            root_t = labels[:, 6]
            return ClassNLLCriterion().apply(root_logp, root_t)

        return jax.value_and_grad(loss_fn)(p)

    new_params, losses = LBFGS(max_iter=25).optimize(feval, model.params)
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"

    model.params = new_params
    out = np.asarray(model.forward([words, lefts, rights]))
    from bigdl_tpu.optim import TreeNNAccuracy

    acc, n = TreeNNAccuracy().apply(out, labels).result()
    assert n == B and acc > 0.8, f"root accuracy {acc}"


def test_treenn_accuracy_shape_tolerance():
    import pytest

    from bigdl_tpu.optim import TreeNNAccuracy

    out = np.zeros((2, 3, 4), np.float32)
    out[:, :, 1] = 1.0
    t3 = np.full((2, 3, 1), 2.0, np.float32)  # trailing singleton dim
    acc, n = TreeNNAccuracy(all_nodes=True).apply(out, t3).result()
    assert n == 6 and acc == 1.0
    with pytest.raises(ValueError):
        TreeNNAccuracy().apply(out, np.zeros((2, 5), np.float32))
