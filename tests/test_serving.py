"""Continuous-batching serving engine (bigdl_tpu/serving/): output parity
with sequential generate(), eviction/readmission, KV-pool free-list
invariants, metrics plumbing, and the jitted-step cache."""

import numpy as np
import pytest

from tests.oracle import assert_close


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


# -- KV pool ---------------------------------------------------------------

def test_kv_pool_free_list_invariants():
    """No slot aliasing while allocated, None when saturated, double-free
    and foreign-slot writes raise, and a full drain leaks nothing."""
    from bigdl_tpu.models.transformer import make_batch_decode_step
    from bigdl_tpu.serving import KVPool

    lm = _make_lm()
    _, init_carry = make_batch_decode_step(lm)
    pool = KVPool(init_carry, 4)
    assert pool.free_slots == 4 and pool.used_slots == 0

    slots = [pool.alloc() for _ in range(4)]
    assert sorted(slots) == [0, 1, 2, 3]          # every slot handed out once
    assert len(set(slots)) == 4                   # no aliasing
    assert pool.alloc() is None                   # saturated → None, no raise
    assert pool.occupancy() == 1.0

    pool.free(slots[1])
    s = pool.alloc()
    assert s == slots[1]                          # freed slot is reusable
    with pytest.raises(ValueError, match="not allocated"):
        pool.free(99)
    pool.free(s)
    with pytest.raises(ValueError, match="not allocated"):
        pool.free(s)                              # double free
    with pytest.raises(ValueError, match="not allocated"):
        pool.set_pos(s, 0)                        # foreign-slot write
    for x in (slots[0], slots[2], slots[3]):
        pool.free(x)
    assert pool.free_slots == 4 and pool.used_slots == 0   # no leak
    assert np.asarray(pool.carry["pos"]).tolist() == [0, 0, 0, 0]

    with pytest.raises(ValueError, match="n_slots"):
        KVPool(init_carry, 0)


def test_kv_pool_write_prefill_row_scatter():
    """A B=1 prefilled carry row-scatters into exactly the target slot:
    K/V rows 0..P-1 land there, pos becomes P, other slots untouched."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_batch_decode_step, make_decode_step, make_prefill_step,
        serving_params,
    )
    from bigdl_tpu.serving import KVPool

    lm = _make_lm()
    _, init1 = make_decode_step(lm)
    _, initN = make_batch_decode_step(lm)
    prefill = make_prefill_step(lm)
    P = serving_params(lm, None)
    pool = KVPool(initN, 3)
    slot = pool.alloc()

    toks = np.array([[3, 7, 1, 4]], np.int32)
    _, pc = prefill(P, jnp.asarray(toks), init1(1))
    before = {k: np.asarray(v).copy() for k, v in pool.carry.items()}
    pool.write_prefill(slot, pc, 4)

    assert int(np.asarray(pool.carry["pos"])[slot]) == 4
    for i in range(pool.n_layers):
        got = np.asarray(pool.carry[f"k{i}"])
        assert_close(got[slot, :4], np.asarray(pc[f"k{i}"])[0, :4], atol=0)
        # other slots bitwise untouched
        others = [s for s in range(3) if s != slot]
        np.testing.assert_array_equal(got[others], before[f"k{i}"][others])
    with pytest.raises(ValueError, match="prompt_len"):
        pool.write_prefill(slot, pc, pool.max_len + 1)


# -- scheduler -------------------------------------------------------------

def test_scheduler_policies_and_lifecycle():
    from bigdl_tpu.serving.scheduler import Request, Scheduler

    with pytest.raises(ValueError, match="policy"):
        Scheduler("lifo")

    def req(i):
        return Request(req_id=i, prompt=[1, 2], max_new_tokens=4)

    cont = Scheduler("prefill_priority")
    cont.submit(req(0)); cont.submit(req(1)); cont.submit(req(2))
    assert cont.queue_depth == 3
    assert cont.admissible(free_slots=2) == 2
    a = cont.admit(0)
    assert a.req_id == 0 and a.state == "running"      # FIFO order
    # continuous batching: admission allowed while others run
    assert cont.admissible(free_slots=1) == 1

    fifo = Scheduler("fifo")
    fifo.submit(req(0)); fifo.submit(req(1))
    fifo.admit(0)
    # run-to-completion: no refill while the batch is non-empty
    assert fifo.admissible(free_slots=1) == 0
    r = fifo.running[0]
    fifo.finish(r, now=1.0)
    assert fifo.admissible(free_slots=2) == 1
    assert r.state == "finished" and r.finish_time == 1.0

    s = Scheduler()
    with pytest.raises(ValueError, match="non-empty"):
        s.submit(Request(req_id=9, prompt=[], max_new_tokens=4))
    with pytest.raises(ValueError, match="max_new_tokens"):
        s.submit(Request(req_id=9, prompt=[1], max_new_tokens=0))


# -- engine parity (THE serving contract) ----------------------------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_engine_parity_with_sequential_generate(dtype_name, rng):
    """For a mixed-arrival trace (varying prompt lengths and output
    budgets, staggered submits, fewer slots than requests so rows are
    evicted and reused mid-flight), every request's engine output must be
    token-for-token identical to per-request sequential
    generate(temperature=0) — plain and bf16-serving params."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    dtype = None if dtype_name == "fp32" else jnp.bfloat16
    lm = _make_lm()
    reqs = []
    for i in range(7):
        plen = int(rng.randint(1, 6))
        prompt = rng.randint(1, 30, size=(plen,)).tolist()
        reqs.append((prompt, int(rng.randint(3, 12))))

    eng = ServingEngine(lm, n_slots=3, compute_dtype=dtype)
    ids = [eng.submit(*reqs[0]), eng.submit(*reqs[1])]
    eng.step(); eng.step()                       # mid-flight...
    ids += [eng.submit(*r) for r in reqs[2:5]]   # ...staggered arrivals
    eng.step()
    ids += [eng.submit(*r) for r in reqs[5:]]
    outs = eng.drain()

    for rid, (prompt, n_new) in zip(ids, reqs):
        want = generate(lm, prompt, length=n_new, temperature=0.0,
                        compute_dtype=dtype)
        np.testing.assert_array_equal(
            outs[rid], want,
            err_msg=f"req {rid} prompt={prompt} dtype={dtype_name}")
    # free-list invariant after drain: nothing leaked
    assert eng.pool.free_slots == eng.pool.n_slots


def test_engine_eos_eviction_and_slot_readmission():
    """A row must be evicted the step its EOS appears (output truncated
    there) and its slot immediately reusable by a waiting request."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm(seed=13)
    prompt = [3, 7]
    free_run = generate(lm, prompt, length=8, temperature=0.0)
    eos = int(free_run[3])                 # a token greedy WILL emit
    cut = int(np.where(free_run == eos)[0][0])   # its FIRST occurrence

    eng = ServingEngine(lm, n_slots=1)     # single slot forces queueing
    a = eng.submit(prompt, max_new_tokens=8, eos_id=eos)
    b = eng.submit([5], max_new_tokens=4)  # waits for a's slot
    outs = eng.drain()
    np.testing.assert_array_equal(outs[a], free_run[:cut + 1])  # cut AT eos
    assert eng.request(a).done_reason == "eos"
    assert eng.request(b).done_reason == "length"
    np.testing.assert_array_equal(
        outs[b], generate(lm, [5], length=4, temperature=0.0))
    assert eng.pool.free_slots == 1


def test_engine_fifo_policy_runs_to_completion():
    """policy="fifo" (static batching baseline): same outputs, but no
    admission while the running batch is non-empty."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm(seed=17)
    eng = ServingEngine(lm, n_slots=2, policy="fifo")
    ids = [eng.submit([3, 4], max_new_tokens=3),
           eng.submit([5], max_new_tokens=5),
           eng.submit([7, 2], max_new_tokens=4)]
    eng.step()
    assert eng.active == 2 and eng.queue_depth == 1
    eng.step(); eng.step()                 # first request finishes at 3
    # run-to-completion: the freed slot is NOT refilled mid-batch
    assert eng.active == 1 and eng.queue_depth == 1
    outs = eng.drain()
    for rid, (p, n) in zip(ids, [([3, 4], 3), ([5], 5), ([7, 2], 4)]):
        np.testing.assert_array_equal(
            outs[rid], generate(lm, p, length=n, temperature=0.0))


def test_engine_rejects_bad_requests():
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit([1, 2, 3], max_new_tokens=100)       # would overflow
    with pytest.raises(ValueError, match="non-empty"):
        eng.submit([], max_new_tokens=4)
    eng.submit([1, 2, 3], max_new_tokens=4)             # at the edge: fine
    with pytest.raises(ValueError, match="admission"):
        ServingEngine(lm, admission="psychic")
    with pytest.raises(ValueError, match="batched"):
        ServingEngine(lm, admission="per_request", prefix_cache=True)


# -- lifecycle: cancel + bounded finished ledger ---------------------------

def test_cancel_waiting_and_running_requests():
    """A cancelled WAITING request is dequeued for good: it never takes
    a slot, emits nothing, and is reported state='cancelled'. A
    cancelled RUNNING request frees its slot immediately, keeps the
    tokens it had already emitted, and never emits another."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=1)       # single slot forces queueing
    a = eng.submit([3, 7], max_new_tokens=4)
    b = eng.submit([5, 2], max_new_tokens=4)
    c = eng.submit([9], max_new_tokens=3)
    eng.step()                               # a runs; b, c wait
    assert eng.cancel(b)
    assert not eng.cancel(b)                 # already cancelled: no-op
    assert eng.queue_depth == 1              # only c still waits
    # RUNNING cancel: a has emitted one token; its slot frees NOW and
    # its output freezes — c gets the slot on the next step
    assert eng.cancel(a)
    assert eng.request(a).state == "cancelled"
    out_a = list(eng.request(a).output)
    assert len(out_a) == 1
    assert eng.pool.free_slots == 1
    outs = eng.drain()
    assert a not in outs and b not in outs   # neither reached FINISHED
    assert list(eng.request(a).output) == out_a   # frozen at cancel
    assert eng.request(b).state == "cancelled"
    assert eng.request(b).done_reason is None
    assert eng.result(b) is not None and len(eng.result(b)) == 0
    np.testing.assert_array_equal(
        outs[c], generate(lm, [9], length=3, temperature=0.0))
    assert eng.pool.free_slots == 1
    total, n = eng.metrics.metrics.get("serving/cancelled")
    assert (total, n) == (2.0, 2)


def test_pop_result_and_keep_finished_bound_the_ledger():
    """pop_result() consumes an output; keep_finished=N evicts the
    oldest finished entries so a long-lived engine stays bounded."""
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2, keep_finished=2)
    rids = [eng.submit([3, i + 2], max_new_tokens=3) for i in range(5)]
    eng.drain()
    # only the 2 most recently finished survive
    assert len(eng._finished) == 2
    assert eng.result(rids[0]) is None       # evicted oldest-first
    kept = [r for r in rids if eng.result(r) is not None]
    assert len(kept) == 2
    out = eng.pop_result(kept[0])
    assert out is not None and len(out) == 3
    assert eng.result(kept[0]) is None       # consumed
    assert eng.pop_result(kept[0]) is None   # second pop: gone
    with pytest.raises(ValueError, match="keep_finished"):
        ServingEngine(lm, n_slots=2, keep_finished=-1)


# -- metrics ---------------------------------------------------------------

def test_engine_metrics_counters():
    """ServingMetrics rides the training plane's Metrics surface: queue
    depth / occupancy / TTFT / latency / tokens counters all populate and
    summary() derives throughput + TTFT percentiles."""
    from bigdl_tpu.optim.metrics import Metrics
    from bigdl_tpu.serving import ServingEngine, ServingMetrics

    backing = Metrics()
    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2,
                        metrics=ServingMetrics(backing))
    for p, n in [([3, 7], 4), ([5], 3), ([2, 9, 4], 5)]:
        eng.submit(p, max_new_tokens=n)
    eng.drain()

    s = eng.metrics.summary()
    assert s["serving/finished"] == 1.0                 # mean of ones
    total, n_req = backing.get("serving/finished")
    assert (total, n_req) == (3.0, 3)
    total_tok, _ = backing.get("serving/tokens_out")
    assert total_tok == 4 + 3 + 5
    assert s["serving/tokens_per_sec"] > 0
    assert 0 < s["serving/slot_occupancy"] <= 1.0
    assert s["serving/ttft_p50_s"] > 0
    assert s["serving/ttft_p50_s"] <= s["serving/ttft_p99_s"]
    _, n_ttft = backing.get("serving/ttft_s")
    assert n_ttft == 3                                  # one TTFT per request
    # the underlying Metrics is the standard observability object — a
    # TrainSummary-style consumer can read the same counters
    assert backing.mean("serving/queue_depth") >= 0.0
    # per-reason disposition counters: every request leaving the engine
    # lands in exactly one serving/finish_<reason> bucket (three cap out
    # on length; a fourth is cancelled while waiting — the buckets sum
    # to every submitted request's fate), and the vocabulary is CLOSED —
    # an unknown reason raises instead of minting an unaccounted counter
    # (the SRV205 contract's runtime half)
    assert s["serving/finish_length"] == 3.0
    assert "serving/finish_eos" not in s
    c = eng.submit([4, 8], max_new_tokens=2)
    assert eng.cancel(c)
    s = eng.metrics.summary()
    assert s["serving/finish_cancelled"] == 1.0
    total, _ = eng.metrics.metrics.get("serving/submitted")
    assert sum(v for k, v in s.items()
               if k.startswith("serving/finish_")) == total == 4
    with pytest.raises(ValueError, match="FINISH_REASONS"):
        eng.metrics.on_finish_reason("oom")   # analysis: ok: SRV205


# -- batch decode step (the model-layer factor the engine rides on) --------

def test_host_step_split_metric():
    """Every super-step records its host-vs-device split:
    serving/host_step_s samples land one per decode step, host +
    device account for (at most) the step wall, and summary() derives
    the p50/p99 the async refactor's acceptance will cite."""
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    eng = ServingEngine(lm, n_slots=2)
    eng.submit([3, 7], max_new_tokens=4)
    eng.submit([5, 2], max_new_tokens=4)
    eng.drain()

    total, n = eng.metrics.metrics.get("serving/host_step_s")
    _, n_dec = eng.metrics.metrics.get("serving/decode_step_s")
    assert n == n_dec and n >= 4          # one split sample per decode step
    assert total >= 0.0
    assert eng.metrics.device_seconds > 0.0
    s = eng.metrics.summary()
    assert s["serving/host_step_p50_s"] <= s["serving/host_step_p99_s"]
    pct = eng.metrics.host_step_percentiles()
    assert set(pct) == {"p50", "p90", "p99"}

    # the pairing survives fault recovery: a recovered step's discarded
    # outputs still cost host time, so the split sample lands for every
    # decode_step sample — the series stay comparable one for one
    from bigdl_tpu.serving import FaultInjector

    eng2 = ServingEngine(_make_lm(), n_slots=2,
                         faults=FaultInjector(seed=1, p_garbage=0.4))
    eng2.submit([3, 7], max_new_tokens=4)
    eng2.submit([5, 2], max_new_tokens=4)
    eng2.drain()
    _, n2 = eng2.metrics.metrics.get("serving/host_step_s")
    _, n2_dec = eng2.metrics.metrics.get("serving/decode_step_s")
    assert n2 == n2_dec and eng2.metrics.metrics.get(
        "serving/retries")[1] > 0

    # the split's windowed half: at dispatch_ahead>0 the residue's
    # device side is the BLOCKED time (fence_wait — decode_step
    # overlaps host work under a window and no longer feeds
    # device_seconds), fence_wait pairs one for one with decode_step,
    # and the pairing survives the drain teardown's out-of-step flush
    eng3 = ServingEngine(_make_lm(), n_slots=2, dispatch_ahead=2)
    eng3.submit([3, 7], max_new_tokens=4)
    eng3.submit([5, 2], max_new_tokens=4)
    eng3.drain()
    _, n3 = eng3.metrics.metrics.get("serving/host_step_s")
    _, n3_dec = eng3.metrics.metrics.get("serving/decode_step_s")
    _, n3_fence = eng3.metrics.metrics.get("serving/fence_wait_s")
    assert n3 == n3_dec == n3_fence
    assert "decode_step" not in eng3.metrics.DEVICE_PHASES
    assert "fence_wait" in eng3.metrics.DEVICE_PHASES


def test_batch_decode_step_matches_single_row(rng):
    """Per-row-position decode: a row stepped inside a shared pool (other
    rows active at different depths) matches the single-request decode
    step position-for-position; inactive rows stay bitwise untouched."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_batch_decode_step, make_decode_step, serving_params,
    )

    lm = _make_lm()
    step1, init1 = make_decode_step(lm)
    stepN, initN = make_batch_decode_step(lm)
    P = serving_params(lm, None)
    toks = rng.randint(0, 29, size=(6,))

    ref, c1 = [], init1(1)
    for t in toks:
        lp, c1 = step1(P, jnp.asarray([int(t)]), c1)
        ref.append(np.asarray(lp)[0])

    N = 3
    cN = initN(N)
    got2, got0 = [], []
    for i, t in enumerate(toks):
        tokens = np.zeros((N,), np.int32)
        active = np.zeros((N,), bool)
        tokens[2], active[2] = int(t), True
        if i >= 2:                      # row 0 joins two steps later
            tokens[0], active[0] = int(toks[i - 2]), True
        before_k0_row1 = np.asarray(cN["k0"])[1].copy()
        lp, cN = stepN(P, jnp.asarray(tokens), jnp.asarray(active), cN)
        # inactive row 1: cache and pos bitwise untouched
        np.testing.assert_array_equal(np.asarray(cN["k0"])[1],
                                      before_k0_row1)
        got2.append(np.asarray(lp)[2])
        if i >= 2:
            got0.append(np.asarray(lp)[0])
    assert int(np.asarray(cN["pos"])[1]) == 0
    for a, b in zip(ref, got2):
        assert_close(a, b, atol=1e-5)
    for a, b in zip(ref, got0):
        assert_close(a, b, atol=1e-5)


def test_prefill_rejects_partially_filled_carry():
    """The fresh-carry contract (ADVICE r5): prefill on a carry with
    pos != 0 must raise instead of silently overwriting rows 0..P-1."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        make_decode_step, make_prefill_step, serving_params,
    )

    lm = _make_lm()
    step, init_carry = make_decode_step(lm)
    prefill = make_prefill_step(lm)
    P = serving_params(lm, None)
    carry = init_carry(1)
    _, carry = step(P, jnp.asarray([3]), carry)         # pos is now 1
    with pytest.raises(ValueError, match="fresh carry"):
        prefill(P, jnp.asarray([[1, 2]], jnp.int32), carry)
    # a fresh carry still works
    _, c2 = prefill(P, jnp.asarray([[1, 2]], jnp.int32), init_carry(1))
    assert int(np.asarray(c2["pos"])[0]) == 2


def test_step_cache_reuses_jitted_steps():
    """get_*_step return the SAME objects per (model, dtype) — repeated
    generate()/engine construction stops paying XLA compiles (ADVICE r5);
    distinct dtypes and models still get distinct entries."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        get_batch_decode_step, get_decode_step, get_prefill_step,
    )

    lm = _make_lm()
    lm2 = _make_lm(seed=23)
    assert get_decode_step(lm) is get_decode_step(lm)
    assert get_prefill_step(lm) is get_prefill_step(lm)
    assert get_batch_decode_step(lm) is get_batch_decode_step(lm)
    assert get_decode_step(lm) is not get_decode_step(lm, jnp.bfloat16)
    assert get_decode_step(lm) is not get_decode_step(lm2)


# -- bench registration smoke (tier-1, small/CPU) --------------------------

def test_serving_bench_smoke():
    """benchmarks/serving_bench.py runs end-to-end on a tiny CPU config
    and the engine beats arrival-ordered sequential serving (the full-
    size acceptance run uses the defaults: ≥ 2x on 8+ requests)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmarks"))
    try:
        import serving_bench
    finally:
        sys.path.pop(0)

    # stagger 0: all requests arrive up front, so neither path sleeps on
    # wall-clock arrivals — the ratio is the pure batching win, stable
    # under CI load (wall-clock staggering made the assert flaky)
    out = serving_bench.run(model="tiny", n_requests=8, gen_tokens=24,
                            stagger_ms=0.0, n_slots=8)
    assert out["engine"]["tokens"] == out["sequential"]["tokens"] == 192
    assert out["engine"]["tokens_per_sec"] > 0
    assert set(out["engine"]["ttft"]) == {"p50_ms", "p90_ms", "p99_ms"}
    # loose floor for a noisy shared CPU box (this config measures ~2x;
    # the ≥2x acceptance number is the bench's own default run — see
    # docs/serving.md); near-1x would mean batching is broken outright
    assert out["speedup"] > 1.3, out
