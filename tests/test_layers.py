"""Per-layer forward/backward parity vs torch-cpu — the reference's Torch7
oracle pattern (SURVEY.md §4)."""

import numpy as np
import pytest

from tests.oracle import assert_close, torch_forward_backward


def _facade_grads(module):
    import jax

    return [np.asarray(g) for g in jax.tree_util.tree_leaves(module.grad_params)]


def test_linear_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import Linear

    layer = Linear(7, 5)
    layer._ensure_params()
    w = rng.randn(5, 7).astype(np.float32)
    b = rng.randn(5).astype(np.float32)
    layer.params = {"weight": w, "bias": b}
    layer.grad_params = None
    layer._ensure_params()

    tl = torch.nn.Linear(7, 5)
    with torch.no_grad():
        tl.weight.copy_(torch.from_numpy(w))
        tl.bias.copy_(torch.from_numpy(b))

    x = rng.randn(4, 7).astype(np.float32)
    g = rng.randn(4, 5).astype(np.float32)
    out = layer.forward(x)
    t_out, t_gin, t_grads = torch_forward_backward(tl, x, g)
    assert_close(out, t_out, atol=1e-5)

    gin = layer.backward(x, g)
    assert_close(gin, t_gin, atol=1e-5)
    assert_close(np.asarray(layer.grad_params["weight"]), t_grads["weight"], atol=1e-5)
    assert_close(np.asarray(layer.grad_params["bias"]), t_grads["bias"], atol=1e-5)


def test_grad_accumulation_semantics(rng):
    """backward() must ACCUMULATE grads until zero_grad_parameters()."""
    from bigdl_tpu.nn import Linear

    layer = Linear(3, 2)
    x = rng.randn(2, 3).astype(np.float32)
    g = rng.randn(2, 2).astype(np.float32)
    layer.forward(x)
    layer.backward(x, g)
    g1 = np.asarray(layer.grad_params["weight"]).copy()
    layer.backward(x, g)
    assert_close(np.asarray(layer.grad_params["weight"]), 2 * g1, atol=1e-5)
    layer.zero_grad_parameters()
    assert np.abs(np.asarray(layer.grad_params["weight"])).max() == 0.0


def test_spatial_convolution_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialConvolution

    # BigDL arg order: (nIn, nOut, kW, kH, dW, dH, padW, padH)
    layer = SpatialConvolution(3, 8, 5, 3, 2, 1, 2, 1)
    w = (rng.randn(8, 3, 3, 5) * 0.1).astype(np.float32)  # OIHW, kH=3 kW=5
    b = rng.randn(8).astype(np.float32)
    layer.params = {"weight": w, "bias": b}
    layer.grad_params = None
    layer._ensure_params()

    tl = torch.nn.Conv2d(3, 8, kernel_size=(3, 5), stride=(1, 2), padding=(1, 2))
    with torch.no_grad():
        tl.weight.copy_(torch.from_numpy(w))
        tl.bias.copy_(torch.from_numpy(b))

    x = rng.randn(2, 3, 9, 11).astype(np.float32)
    out = layer.forward(x)
    t_out, t_gin, t_grads = torch_forward_backward(tl, x, np.ones_like(np.asarray(out)))
    assert_close(out, t_out, atol=1e-4)

    gin = layer.backward(x, np.ones_like(np.asarray(out)))
    assert_close(gin, t_gin, atol=1e-4)
    assert_close(np.asarray(layer.grad_params["weight"]), t_grads["weight"], atol=1e-3)


def test_grouped_convolution_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialConvolution

    layer = SpatialConvolution(4, 6, 3, 3, 1, 1, 1, 1, n_group=2)
    w = (rng.randn(6, 2, 3, 3) * 0.1).astype(np.float32)
    layer.params = {"weight": w, "bias": np.zeros(6, np.float32)}
    layer.grad_params = None
    layer._ensure_params()

    tl = torch.nn.Conv2d(4, 6, 3, padding=1, groups=2)
    with torch.no_grad():
        tl.weight.copy_(torch.from_numpy(w))
        tl.bias.zero_()

    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    out = layer.forward(x)
    t_out, _, _ = torch_forward_backward(tl, x)
    assert_close(out, t_out, atol=1e-4)


def test_max_pooling_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialMaxPooling

    layer = SpatialMaxPooling(3, 3, 2, 2, 1, 1)
    tl = torch.nn.MaxPool2d(3, stride=2, padding=1)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    out = layer.forward(x)
    t_out, t_gin, _ = torch_forward_backward(tl, x, np.ones_like(np.asarray(out)))
    assert_close(out, t_out, atol=1e-5)
    gin = layer.backward(x, np.ones_like(np.asarray(out)))
    assert_close(gin, t_gin, atol=1e-5)


def test_max_pooling_ceil_mode(rng):
    import torch

    from bigdl_tpu.nn import SpatialMaxPooling

    layer = SpatialMaxPooling(3, 3, 2, 2).ceil()  # Inception-v1 pattern
    tl = torch.nn.MaxPool2d(3, stride=2, ceil_mode=True)
    x = rng.randn(2, 3, 10, 10).astype(np.float32)
    out = layer.forward(x)
    t_out, _, _ = torch_forward_backward(tl, x)
    assert_close(out, t_out, atol=1e-5)


def test_avg_pooling_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialAveragePooling

    layer = SpatialAveragePooling(7, 7, 1, 1)  # ResNet head
    tl = torch.nn.AvgPool2d(7, stride=1)
    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    out = layer.forward(x)
    t_out, _, _ = torch_forward_backward(tl, x)
    assert_close(out, t_out, atol=1e-5)


def test_batchnorm_train_and_eval_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialBatchNormalization

    layer = SpatialBatchNormalization(5, eps=1e-5, momentum=0.1)
    layer._ensure_params()
    tl = torch.nn.BatchNorm2d(5, eps=1e-5, momentum=0.1)

    x = rng.randn(4, 5, 6, 6).astype(np.float32)
    out = layer.forward(x)  # train mode
    tl.train()
    t_out, _, _ = torch_forward_backward(tl, x)
    assert_close(out, t_out, atol=1e-4)
    assert_close(
        np.asarray(layer.state["running_mean"]),
        tl.running_mean.detach().numpy(), atol=1e-5,
    )
    assert_close(
        np.asarray(layer.state["running_var"]),
        tl.running_var.detach().numpy(), atol=1e-4,
    )

    layer.evaluate()
    tl.eval()
    x2 = rng.randn(4, 5, 6, 6).astype(np.float32)
    out2 = layer.forward(x2)
    t_out2 = tl(torch.from_numpy(x2)).detach().numpy()
    assert_close(out2, t_out2, atol=1e-4)


def test_lrn_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialCrossMapLRN

    layer = SpatialCrossMapLRN(5, alpha=1e-4, beta=0.75, k=1.0)
    tl = torch.nn.LocalResponseNorm(5, alpha=1e-4, beta=0.75, k=1.0)
    x = (rng.randn(2, 8, 5, 5) * 2).astype(np.float32)
    out = layer.forward(x)
    t_out, _, _ = torch_forward_backward(tl, x)
    assert_close(out, t_out, atol=1e-5)


@pytest.mark.parametrize(
    "ours,theirs",
    [
        ("ReLU", "ReLU"),
        ("Tanh", "Tanh"),
        ("Sigmoid", "Sigmoid"),
        ("ELU", "ELU"),
        ("SoftPlus", "Softplus"),
        ("SoftSign", "Softsign"),
        ("LogSoftMax", "LogSoftmax"),
    ],
)
def test_activations_vs_torch(rng, ours, theirs):
    import torch

    import bigdl_tpu.nn as nn

    layer = getattr(nn, ours)()
    kwargs = {"dim": -1} if theirs == "LogSoftmax" else {}
    tl = getattr(torch.nn, theirs)(**kwargs)
    x = rng.randn(3, 6).astype(np.float32)
    g = rng.randn(3, 6).astype(np.float32)
    out = layer.forward(x)
    t_out, t_gin, _ = torch_forward_backward(tl, x, g)
    assert_close(out, t_out, atol=1e-5)
    gin = layer.backward(x, g)
    assert_close(gin, t_gin, atol=1e-5)


def test_dropout_semantics():
    import jax

    from bigdl_tpu.nn import Dropout

    layer = Dropout(0.5)
    x = np.ones((1000,), np.float32)
    out = np.asarray(layer.forward(x))
    # scaled: surviving entries are 2.0, dropped are 0
    assert set(np.round(np.unique(out), 5)) <= {0.0, 2.0}
    assert 0.3 < (out == 0).mean() < 0.7
    layer.evaluate()
    out_eval = np.asarray(layer.forward(x))
    assert_close(out_eval, x)


def test_lookup_table(rng):
    from bigdl_tpu.nn import LookupTable

    layer = LookupTable(10, 4)
    layer._ensure_params()
    w = np.asarray(layer.params["weight"])
    idx = np.array([[1, 5], [10, 3]], np.float32)  # 1-based
    out = np.asarray(layer.forward(idx))
    assert out.shape == (2, 2, 4)
    assert_close(out[0, 0], w[0])
    assert_close(out[1, 0], w[9])


def test_spatial_full_convolution_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialFullConvolution

    for g, s, p, adj in [(1, 1, 0, 0), (1, 2, 1, 1), (2, 2, 1, 0)]:
        layer = SpatialFullConvolution(4, 6, 3, 3, s, s, p, p,
                                       adj_w=adj, adj_h=adj, n_group=g)
        layer._ensure_params()
        tl = torch.nn.ConvTranspose2d(4, 6, 3, stride=s, padding=p,
                                      output_padding=adj, groups=g)
        with torch.no_grad():
            tl.weight.copy_(torch.from_numpy(np.asarray(layer.params["weight"]).copy()))
            tl.bias.copy_(torch.from_numpy(np.asarray(layer.params["bias"]).copy()))
        x = rng.randn(2, 4, 5, 5).astype(np.float32)
        out = layer.forward(x)
        t_out, t_gin, t_grads = torch_forward_backward(
            tl, x, np.ones_like(np.asarray(out)))
        assert_close(out, t_out, atol=1e-4, msg=f"g={g} s={s} p={p} adj={adj}")
        gin = layer.backward(x, np.ones_like(np.asarray(out)))
        assert_close(gin, t_gin, atol=1e-4)
        assert_close(np.asarray(layer.grad_params["weight"]), t_grads["weight"],
                     atol=1e-3)


def test_batchnorm_large_mean_fp32_accuracy(rng):
    """fp32 inputs with huge mean must not catastrophically cancel
    (regression: single-pass E[x2]-E[x]2 variance)."""
    import numpy as np

    from bigdl_tpu.nn import BatchNormalization

    bn = BatchNormalization(4)
    bn._ensure_params()
    bn.training()
    x = (rng.randn(64, 4) + 10000.0).astype(np.float32)
    out = np.asarray(bn.forward(x))
    assert abs(out.std() - 1.0) < 0.1, f"BN output std {out.std()}"
    # running_var blends init 1.0 with the true var 1.0; catastrophic
    # cancellation would instead blend toward 0
    rv = float(np.asarray(bn.state["running_var"]).mean())
    assert 0.95 < rv < 1.05, f"running_var {rv}"
