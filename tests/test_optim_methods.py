"""OptimMethod unit tests: convergence on a quadratic + parity vs torch SGD."""

import numpy as np

from tests.oracle import assert_close


def _quad_feval(target):
    def feval(x):
        g = x - target
        loss = 0.5 * float(np.sum(np.asarray(g) ** 2))
        return loss, g

    return feval


def test_sgd_converges_quadratic():
    import jax.numpy as jnp

    from bigdl_tpu.optim import SGD

    target = jnp.asarray(np.arange(4.0, dtype=np.float32))
    x = jnp.zeros(4)
    opt = SGD(learning_rate=0.5)
    feval = _quad_feval(target)
    for _ in range(50):
        x, losses = opt.optimize(feval, x)
    assert losses[0] < 1e-4


def test_sgd_momentum_matches_torch():
    import torch

    from bigdl_tpu.optim import SGD

    w0 = np.array([1.0, -2.0, 3.0], np.float32)
    grads = [np.array([0.5, 0.1, -0.3], np.float32),
             np.array([-0.2, 0.4, 0.6], np.float32),
             np.array([0.3, -0.5, 0.2], np.float32)]

    tw = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=0.01)
    for g in grads:
        topt.zero_grad()
        tw.grad = torch.from_numpy(g.copy())
        topt.step()

    import jax.numpy as jnp

    opt = SGD(learning_rate=0.1, momentum=0.9, weight_decay=0.01)
    x = jnp.asarray(w0)
    st = opt.init_state(x)
    for g in grads:
        x, st = opt.update(jnp.asarray(g), st, x)
    assert_close(np.asarray(x), tw.detach().numpy(), atol=1e-6)


def test_adam_matches_torch():
    import torch

    from bigdl_tpu.optim import Adam

    w0 = np.array([1.0, -2.0, 3.0], np.float32)
    grads = [np.array([0.5, 0.1, -0.3], np.float32)] * 5

    tw = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch.optim.Adam([tw], lr=0.01)
    for g in grads:
        topt.zero_grad()
        tw.grad = torch.from_numpy(g.copy())
        topt.step()

    import jax.numpy as jnp

    opt = Adam(learning_rate=0.01)
    x = jnp.asarray(w0)
    st = opt.init_state(x)
    for g in grads:
        x, st = opt.update(jnp.asarray(g), st, x)
    assert_close(np.asarray(x), tw.detach().numpy(), atol=1e-5)


def test_rmsprop_adagrad_adadelta_adamax_ftrl_run():
    import jax.numpy as jnp

    from bigdl_tpu.optim import Adadelta, Adagrad, Adamax, Ftrl, RMSprop

    target = jnp.asarray(np.arange(4.0, dtype=np.float32))
    # Adadelta's effective lr starts near sqrt(eps) so it needs far more steps
    for opt, iters in [(RMSprop(learning_rate=0.05), 100),
                       (Adagrad(learning_rate=0.5), 100),
                       (Adadelta(epsilon=1e-4), 3000),
                       (Adamax(learning_rate=0.1), 100),
                       (Ftrl(learning_rate=0.5), 100)]:
        x = jnp.zeros(4)
        st = opt.init_state(x)
        import jax

        @jax.jit
        def run_step(x, st):
            g = x - target
            return opt.update(g, st, x)

        for _ in range(iters):
            x, st = run_step(x, st)
        loss = float(jnp.sum((x - target) ** 2))
        assert loss < 1.0, f"{type(opt).__name__} did not converge: {loss}"


def test_lr_schedules():
    import jax.numpy as jnp

    from bigdl_tpu.optim import (
        Default, Exponential, MultiStep, Poly, SequentialSchedule, Step, Warmup,
    )

    s = jnp.asarray(10, jnp.int32)
    assert abs(float(Step(5, 0.1).lr(1.0, s)) - 0.01) < 1e-9
    assert abs(float(MultiStep([3, 8], 0.1).lr(1.0, s)) - 0.01) < 1e-9
    assert abs(float(Default(0.1).lr(1.0, s)) - 0.5) < 1e-9
    assert abs(float(Poly(2.0, 20).lr(1.0, s)) - 0.25) < 1e-6
    assert abs(float(Exponential(10, 0.5, stair_case=True).lr(1.0, s)) - 0.5) < 1e-9
    # warmup 0.1 + 0.09/step for 10 steps then constant 1.0
    seq = SequentialSchedule().add(Warmup(0.09), 10).add(Default(0.0), 1000)
    assert abs(float(seq.lr(0.1, jnp.asarray(0, jnp.int32))) - 0.1) < 1e-6
    assert abs(float(seq.lr(0.1, jnp.asarray(5, jnp.int32))) - 0.55) < 1e-6
    assert abs(float(seq.lr(0.1, jnp.asarray(15, jnp.int32))) - 0.1) < 1e-6


def test_plateau_host_schedule():
    from bigdl_tpu.optim import Plateau

    p = Plateau(factor=0.5, patience=2, mode="min")
    for score in [1.0, 0.9, 0.91, 0.92]:  # 2 non-improving -> reduce
        p.record_score(score)
    import jax.numpy as jnp

    assert abs(float(p.lr(1.0, jnp.asarray(0))) - 0.5) < 1e-9


def test_triggers():
    from bigdl_tpu.optim import Trigger

    st = {"epoch": 3, "neval": 21, "loss": 0.5, "score": 0.9, "epoch_finished": True}
    assert Trigger.max_epoch(2)(st)
    assert not Trigger.max_epoch(5)(st)
    assert Trigger.max_iteration(20)(st)
    assert Trigger.several_iteration(10)(st)
    assert Trigger.min_loss(0.6)(st)
    assert Trigger.max_score(0.8)(st)
    assert Trigger.max_epoch(2).and_(Trigger.min_loss(0.6))(st)
    ee = Trigger.every_epoch()
    assert ee(st)
    assert not ee(st)  # same epoch: fires once


def test_optim_method_save_load(tmp_path):
    from bigdl_tpu.optim import SGD, OptimMethod, Step

    opt = SGD(learning_rate=0.1, momentum=0.9,
              learning_rate_schedule=Step(10, 0.5))
    opt.state["neval"] = 42
    p = str(tmp_path / "optim.snapshot")
    opt.save(p)
    loaded = OptimMethod.load(p)
    assert isinstance(loaded, SGD)
    assert loaded.state["neval"] == 42
    assert loaded.momentum == 0.9


def test_lars_sgd_trains_and_scales(rng):
    """LARS: loss decreases; trust ratio rescales per-tensor steps."""
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.optim import LarsSGD

    W = rng.randn(6, 2).astype(np.float32)
    X = rng.randn(128, 6).astype(np.float32)
    Y = X @ W
    params = {"w": (rng.randn(6, 2) * 0.1).astype(np.float32),
              "b": np.zeros((2,), np.float32)}
    opt = LarsSGD(learning_rate=1.0, momentum=0.9, trust=0.01)
    state = opt.init_state(params)

    import jax

    def loss_fn(p):
        return jnp.mean((X @ p["w"] + p["b"] - Y) ** 2)

    losses = []
    for _ in range(60):
        l, g = jax.value_and_grad(loss_fn)(params)
        params, state = opt.update(g, state, params)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.1, f"{losses[0]} -> {losses[-1]}"


def test_validator_alias():
    from bigdl_tpu.optim import Validator
    from bigdl_tpu.optim.evaluator import Evaluator

    assert Validator is Evaluator


def test_rmsprop_adagrad_adadelta_trajectories_vs_torch(rng):
    """Multi-step trajectory parity vs torch.optim on a quadratic."""
    import jax.numpy as jnp
    import torch

    from bigdl_tpu.optim import Adadelta, Adagrad, RMSprop

    A = rng.randn(6, 6).astype(np.float32)
    A = (A @ A.T / 6 + np.eye(6)).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    x0 = rng.randn(6).astype(np.float32)

    def grad_np(x):
        return A @ x - b

    cases = [
        (RMSprop(learning_rate=0.01, decay_rate=0.9, epsilon=1e-8),
         lambda p: torch.optim.RMSprop([p], lr=0.01, alpha=0.9, eps=1e-8)),
        (Adagrad(learning_rate=0.05),
         lambda p: torch.optim.Adagrad([p], lr=0.05, eps=1e-10)),
        (Adadelta(decay_rate=0.9, epsilon=1e-6),
         lambda p: torch.optim.Adadelta([p], lr=1.0, rho=0.9, eps=1e-6)),
    ]
    for ours, theirs in cases:
        params = {"x": jnp.asarray(x0)}
        state = ours.init_state(params)
        pt = torch.from_numpy(x0.copy()).requires_grad_(True)
        topt = theirs(pt)
        for _ in range(12):
            g = {"x": jnp.asarray(grad_np(np.asarray(params["x"])))}
            params, state = ours.update(g, state, params)
            topt.zero_grad()
            pt.grad = torch.from_numpy(grad_np(pt.detach().numpy()))
            topt.step()
        np.testing.assert_allclose(
            np.asarray(params["x"]), pt.detach().numpy(), atol=2e-3,
            err_msg=type(ours).__name__)


def test_adam_bf16_state_tracks_fp32():
    """state_dtype='bf16' halves the slot-buffer footprint; the update
    math stays fp32 cast-in/cast-out, so trajectories track the fp32
    optimizer to bf16 slot precision on a quadratic."""
    import jax.numpy as jnp

    from bigdl_tpu.optim import Adam

    w32 = {"w": jnp.asarray([1.0, -2.0, 3.0], jnp.float32)}
    wbf = {"w": jnp.asarray([1.0, -2.0, 3.0], jnp.float32)}
    o32, obf = Adam(learning_rate=0.05), Adam(learning_rate=0.05,
                                              state_dtype="bf16")
    s32, sbf = o32.init_state(w32), obf.init_state(wbf)
    assert sbf["m"]["w"].dtype == jnp.bfloat16
    assert sbf["v"]["w"].dtype == jnp.bfloat16
    for _ in range(50):
        g32 = {"w": 2.0 * w32["w"]}
        gbf = {"w": 2.0 * wbf["w"]}
        w32, s32 = o32.update(g32, s32, w32)
        wbf, sbf = obf.update(gbf, sbf, wbf)
    assert wbf["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(wbf["w"]), np.asarray(w32["w"]),
                               atol=0.02)
    # both converged toward 0
    assert np.abs(np.asarray(wbf["w"])).max() < 1.0


def test_stochastic_round_unbiased_and_exact():
    """stochastic_round is exact on bf16-representable values and unbiased
    in expectation between them."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.optim.optim_method import stochastic_round

    # exact: a bf16-representable value never moves
    x = jnp.asarray([1.0, -0.5, 0.0, 2.0], jnp.float32)
    for seed in range(5):
        out = stochastic_round(x, jnp.bfloat16, jax.random.PRNGKey(seed))
        np.testing.assert_array_equal(np.asarray(out, np.float32),
                                      np.asarray(x))

    # unbiased: 1 + 0.3*ulp rounds up ~30% of the time
    import ml_dtypes

    one = np.float32(1.0)
    ulp = float(np.spacing(ml_dtypes.bfloat16(1.0)))
    val = jnp.full((4096,), one + 0.3 * ulp, jnp.float32)
    out = stochastic_round(val, jnp.bfloat16,
                           jax.random.PRNGKey(123)).astype(jnp.float32)
    frac_up = float((np.asarray(out) > 1.0).mean())
    assert 0.25 < frac_up < 0.35, frac_up
    # mean preserved to ~ulp/sqrt(N)
    np.testing.assert_allclose(float(np.asarray(out).mean()),
                               float(one + 0.3 * ulp), rtol=3e-4)

    # non-finite passthrough
    bad = jnp.asarray([np.inf, -np.inf, np.nan], jnp.float32)
    outb = np.asarray(stochastic_round(bad, jnp.bfloat16,
                                       jax.random.PRNGKey(0)),
                      np.float32)
    assert np.isinf(outb[0]) and np.isinf(outb[1]) and np.isnan(outb[2])


def test_adam_bf16_masters_with_sr_converges():
    """bf16 master weights + stochastic rounding keep making progress on
    updates far below the bf16 ulp — the regime where round-to-nearest
    stalls completely."""
    import jax.numpy as jnp

    from bigdl_tpu.optim import Adam

    # lr tuned so per-step updates are ~1e-4 relative to w=8.0 (bf16 ulp
    # at 8.0 is 0.0625 — each update is ~1/600 ulp)
    w_sr = {"w": jnp.full((512,), 8.0, jnp.bfloat16)}
    w_rn = {"w": jnp.full((512,), 8.0, jnp.bfloat16)}
    sr = Adam(learning_rate=1e-4, stochastic_rounding=True)
    rn = Adam(learning_rate=1e-4)
    s_sr, s_rn = sr.init_state(w_sr), rn.init_state(w_rn)
    for _ in range(200):
        g_sr = {"w": w_sr["w"].astype(jnp.float32)}
        g_rn = {"w": w_rn["w"].astype(jnp.float32)}
        w_sr, s_sr = sr.update(g_sr, s_sr, w_sr)
        w_rn, s_rn = rn.update(g_rn, s_rn, w_rn)
    assert w_sr["w"].dtype == jnp.bfloat16
    moved_sr = 8.0 - float(np.asarray(w_sr["w"], np.float32).mean())
    moved_rn = 8.0 - float(np.asarray(w_rn["w"], np.float32).mean())
    # Adam's unit-scale step is ~lr: 200 steps * 1e-4 = 0.02 expected
    assert 0.01 < moved_sr < 0.04, moved_sr
    # round-to-nearest cannot cross the 0.0625 ulp and stays pinned
    assert abs(moved_rn) < 1e-6, moved_rn
