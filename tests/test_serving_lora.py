"""Multi-tenant pooled LoRA serving (bigdl_tpu/serving/lora.py + the
engine's adapter plane): bank alloc/retain/free lifecycle, the
null-adapter token-identity contract against a no-bank engine (fp32 +
bf16), zero extra compiles for mixed base/adapted traffic, fixed-seed
replay through preemption and decode-pool failover, the speculative
draft pin, and sharded DP/TP parity."""

import numpy as np
import pytest


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


@pytest.fixture(scope="module")
def bank(lm):
    """One 4-slot bank for the module; slots 1-2 pre-allocated with
    visible-amplitude factors (rank-2, amp large enough that adapted
    logits actually diverge on this tiny model)."""
    from bigdl_tpu.serving import AdapterBank

    b = AdapterBank(lm, rank=2, n_slots=4)
    b.alloc(b.random_factors(seed=1, amp=1.0))      # id 1
    b.alloc(b.random_factors(seed=2, amp=1.0))      # id 2
    return b


def _trace(vocab=29, n=6, seed=3):
    from bigdl_tpu.serving import SamplingParams

    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        prompt = rng.randint(1, vocab + 1, size=([3, 7, 5][i % 3],)).tolist()
        sp = (SamplingParams(temperature=0.8, top_k=10, seed=100 + i)
              if i % 2 else None)
        out.append((prompt, 6, sp))
    return out


# -- bank lifecycle ---------------------------------------------------------

def test_bank_lifecycle_and_validation(lm):
    from bigdl_tpu.serving import AdapterBank

    b = AdapterBank(lm, rank=2, n_slots=3)
    assert b.n_free == 2                    # slot 0 is the null adapter
    factors = b.random_factors(seed=5)
    aid = b.alloc(factors)
    assert aid != 0 and b.is_live(aid) and b.live == {aid: 1}
    b.retain(aid)
    assert b.live[aid] == 2
    b.free(aid)                             # refcount 2 -> 1: still live
    assert b.is_live(aid)
    b.free(aid)                             # 1 -> 0: slot returns
    assert not b.is_live(aid) and b.n_free == 2
    # freed rows are ZEROED — a recycled slot must not leak the old
    # tenant's factors into the gather
    for k in b.arrays:
        assert not np.any(b.arrays[k][aid])
    # null adapter is permanent
    with pytest.raises(ValueError):
        b.free(0)
    b.retain(0)                             # no-op, never raises
    # unknown keys / wrong shapes rejected before any row is written
    with pytest.raises(KeyError):
        b.alloc({"nope_a": np.zeros((2, 2), np.float32)})
    bad = dict(factors)
    k0 = next(iter(bad))
    bad[k0] = np.zeros((1, 1), np.float32)
    with pytest.raises(ValueError):
        b.alloc(bad)
    # exhaustion is loud
    b.alloc(b.random_factors(seed=6))
    b.alloc(b.random_factors(seed=7))
    with pytest.raises(RuntimeError):
        b.alloc(b.random_factors(seed=8))
    # retain/free of a dead id is loud
    with pytest.raises(KeyError):
        b.retain(99)


def test_engine_submit_validation(lm, bank):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2, adapters=bank)
    with pytest.raises(ValueError, match="adapter"):
        eng.submit([3, 2], max_new_tokens=2, adapter_id=3)   # not live
    plain = ServingEngine(lm, n_slots=2)
    with pytest.raises(ValueError, match="adapter"):
        plain.submit([3, 2], max_new_tokens=2, adapter_id=1)  # no bank
    # per-request admission has no batch prefill plane for the bank
    with pytest.raises(ValueError, match="adapters require"):
        ServingEngine(lm, n_slots=2, adapters=bank,
                      admission="per_request")


# -- THE acceptance contract: null adapter == pre-PR engine -----------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_null_adapter_token_identical_to_plain_engine(dtype_name, lm, bank):
    """An adapter-enabled engine serving only null-adapter traffic is
    token-identical (and logprob-identical) to an engine built without
    a bank: the id-0 rows gather all-zero factors and the delta
    vanishes exactly, in both dtypes."""
    import jax.numpy as jnp

    from bigdl_tpu.serving import ServingEngine

    dt = None if dtype_name == "fp32" else jnp.bfloat16
    trace = _trace()

    plain = ServingEngine(lm, n_slots=3, seed=11, compute_dtype=dt)
    r0 = [plain.submit(p, max_new_tokens=n, sampling=sp)
          for p, n, sp in trace]
    o0 = plain.drain()

    eng = ServingEngine(lm, n_slots=3, seed=11, compute_dtype=dt,
                        adapters=bank)
    r1 = [eng.submit(p, max_new_tokens=n, sampling=sp)
          for p, n, sp in trace]
    o1 = eng.drain()
    for a, b in zip(r0, r1):
        np.testing.assert_array_equal(o0[a], o1[b])
        np.testing.assert_array_equal(plain.logprobs(a), eng.logprobs(b))


def test_adapted_rows_actually_diverge(lm, bank):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=3, seed=11, adapters=bank)
    r0 = eng.submit([3, 7, 2], max_new_tokens=8)
    r1 = eng.submit([3, 7, 2], max_new_tokens=8, adapter_id=1)
    r2 = eng.submit([3, 7, 2], max_new_tokens=8, adapter_id=2)
    o = eng.drain()
    assert list(o[r1]) != list(o[r0])        # adapter changes the stream
    assert list(o[r2]) != list(o[r1])        # ...per tenant


def test_finish_releases_refcount(lm):
    from bigdl_tpu.serving import AdapterBank, ServingEngine

    b = AdapterBank(lm, rank=2, n_slots=3)
    aid = b.alloc(b.random_factors(seed=5, amp=1.0))
    eng = ServingEngine(lm, n_slots=2, adapters=b)
    rid = eng.submit([3, 2], max_new_tokens=3, adapter_id=aid)
    assert b.live[aid] == 2                  # submit retained
    eng.drain()
    assert b.live[aid] == 1                  # finish released
    # cancellation releases too
    r2 = eng.submit([3, 2, 4], max_new_tokens=3, adapter_id=aid)
    assert b.live[aid] == 2
    eng.cancel(r2)
    assert b.live[aid] == 1
    b.free(aid)
    assert not b.is_live(aid)
    assert rid in eng._finished


# -- one program, mixed tenants --------------------------------------------

def test_mixed_traffic_zero_extra_compiles(lm, bank):
    """Base-only traffic, then mixed 3-tenant traffic, on the same
    adapter-enabled engine: the second wave compiles NOTHING new in
    decode or prefill — adapter ids are runtime rows of the one
    program."""
    from tests.compile_guards import assert_compile_count, compile_count

    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=3, seed=11, adapters=bank)
    for p, n, sp in _trace():
        eng.submit(p, max_new_tokens=n, sampling=sp)
    eng.drain()
    decode0 = compile_count(eng._step_fn)
    prefill0 = compile_count(eng._batch_prefill_fn)
    assert decode0 == 1

    for i, (p, n, sp) in enumerate(_trace(seed=7)):
        eng.submit(p, max_new_tokens=n, sampling=sp,
                   adapter_id=[0, 1, 2][i % 3])
    eng.drain()
    assert_compile_count(eng._step_fn, decode0, what="mixed decode")
    assert_compile_count(eng._batch_prefill_fn, prefill0,
                         what="mixed prefill")


# -- replay -----------------------------------------------------------------

def test_adapted_replay_through_preemption(lm, bank):
    """A fixed-seed adapted stream evicted mid-flight by a higher
    priority resumes draw-for-draw: the adapter id rides the preemption
    stash (row_state/restore_row) and the recycled slot re-gathers the
    same tenant's factors."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    sp = SamplingParams(temperature=0.9, top_k=10, seed=31)
    base = ServingEngine(lm, n_slots=2, adapters=bank)
    r0 = base.submit([3, 7, 2, 9, 4], max_new_tokens=10, sampling=sp,
                     adapter_id=1)
    want = base.drain()[r0]

    eng = ServingEngine(lm, n_slots=1, policy="priority", adapters=bank)
    r1 = eng.submit([3, 7, 2, 9, 4], max_new_tokens=10, sampling=sp,
                    adapter_id=1, priority=0)
    for _ in range(3):
        eng.step()
    eng.submit([5, 5], max_new_tokens=2, priority=5)   # forces eviction
    outs = eng.drain()
    assert eng.request(r1).preemptions >= 1
    np.testing.assert_array_equal(outs[r1], want)


def test_row_state_carries_adapter(lm, bank):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2, adapters=bank)
    rid = eng.submit([3, 7, 2], max_new_tokens=6, adapter_id=2)
    eng.step()
    slot = next(s for s, r in eng.scheduler.running.items()
                if r.req_id == rid)
    payload = eng.pool.row_state(slot)
    assert payload["adapter"] == 2
    # pre-adapter payloads (no key) restore as the null adapter
    del payload["adapter"]
    eng.pool.restore_row(slot, payload)
    assert eng.pool.adapter_ids[slot] == 0
    eng.drain()


@pytest.mark.disagg
def test_adapted_replay_through_pool_failover(lm, bank):
    """Mid-stream decode-pool kill: adapted + base rows all land
    token-identical to the monolithic engine — the adapter id crosses
    the wire in the row payload and in the replay handoff's request
    meta."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, SamplingParams, ServingEngine)

    sps = [SamplingParams(temperature=0.8, top_k=10, seed=40 + i)
           for i in range(4)]
    prompts = [[3, 7, 2], [5, 1, 8, 2], [9, 4], [6, 6, 6]]
    aids = [0, 1, 2, 1]

    mono = ServingEngine(lm, n_slots=4, seed=7, adapters=bank)
    mr = [mono.submit(p, max_new_tokens=8, sampling=sp, adapter_id=a)
          for p, sp, a in zip(prompts, sps, aids)]
    want = mono.drain()

    d = DisaggregatedEngine(lm, prefill_slots=4, decode_slots=2,
                            decode_pools=2, seed=7, adapters=bank)
    dr = [d.submit(p, max_new_tokens=8, sampling=sp, adapter_id=a)
          for p, sp, a in zip(prompts, sps, aids)]
    for _ in range(3):
        d.step()
    d.kill_pool(0)                           # strands mid-stream rows
    got = d.drain()
    for a, b in zip(mr, dr):
        np.testing.assert_array_equal(want[a], got[b])


def test_payload_wire_roundtrip_keeps_adapter(lm, bank):
    from bigdl_tpu.serving import ServingEngine
    from bigdl_tpu.serving.disagg import (
        pack_payload, request_from_meta, request_meta, unpack_payload)

    eng = ServingEngine(lm, n_slots=2, adapters=bank)
    rid = eng.submit([3, 7, 2], max_new_tokens=6, adapter_id=1)
    eng.step()
    req = next(r for r in eng.scheduler.running.values()
               if r.req_id == rid)
    payload = eng.pool.row_state(req.slot)
    blob = pack_payload(request_meta(req), payload)
    meta, back = unpack_payload(blob)
    assert back["adapter"] == 1
    assert request_from_meta(meta).adapter_id == 1
    eng.drain()


# -- speculative pin --------------------------------------------------------

def test_speculative_pins_drafts_to_null_adapter(lm, bank):
    """On a speculative engine an adapted submit must pin its draft
    budget to 0 (the draft model has no adapter plane); with the pin,
    the adapted stream matches the non-speculative adapter engine
    token for token, and null-adapter rows still draft."""
    from bigdl_tpu.serving import ServingEngine, SpeculativeConfig

    draft = _make_lm(hidden=16, heads=2, layers=1, seed=21)
    base = ServingEngine(lm, n_slots=3, seed=7, adapters=bank)
    b1 = base.submit([3, 7, 2], max_new_tokens=8, adapter_id=1)
    b2 = base.submit([5, 1, 8], max_new_tokens=8)
    want = base.drain()

    se = ServingEngine(lm, n_slots=3, seed=7, adapters=bank,
                       speculative=SpeculativeConfig(draft, k=3))
    with pytest.raises(ValueError, match="draft_tokens=0"):
        se.submit([3, 7, 2], max_new_tokens=8, adapter_id=1)
    s1 = se.submit([3, 7, 2], max_new_tokens=8, adapter_id=1,
                   draft_tokens=0)
    s2 = se.submit([5, 1, 8], max_new_tokens=8)
    got = se.drain()
    np.testing.assert_array_equal(want[b1], got[s1])
    np.testing.assert_array_equal(want[b2], got[s2])
    # the verify plane really ran (speculation stayed on for the mix)
    assert se.metrics.summary()["serving/spec_rows"] > 0


# -- sharded plane ----------------------------------------------------------

@pytest.mark.mesh
@pytest.mark.parametrize("parallelism", [{"data": 4},
                                         {"data": 2, "model": 2}])
def test_sharded_adapter_parity(bank, parallelism):
    """Slot-DP and DP x TP meshes serve the mixed-tenant trace
    token-identically to the unsharded adapter engine: the bank's slot
    axis is replicated, its model axes shard with the TP plane, and
    the row-parallel delta folds in before the psum."""
    from bigdl_tpu.serving import ServingEngine

    lm = _make_lm()
    trace = _trace()
    aids = [0, 1, 2, 0, 1, 2]

    def run(**kw):
        eng = ServingEngine(lm, n_slots=4, seed=11, adapters=bank, **kw)
        rids = [eng.submit(p, max_new_tokens=n, sampling=sp, adapter_id=a)
                for (p, n, sp), a in zip(trace, aids)]
        return eng, rids, eng.drain()

    e0, r0, o0 = run()
    e1, r1, o1 = run(parallelism=parallelism)
    for a, b in zip(r0, r1):
        np.testing.assert_array_equal(o0[a], o1[b])
        np.testing.assert_allclose(e0.logprobs(a), e1.logprobs(b),
                                   atol=2e-5)
