"""TF control-flow import: v1 Switch/Merge/Enter/Exit/NextIteration/LoopCond
frames and v2 functional While/If, lowered to lax.while_loop / lax.cond /
select (SURVEY.md §2.2 nn/ops control-flow row; round-1 verdict missing #3).
Differential-tested against live TF execution of the same GraphDef."""

import numpy as np
import pytest

from tests.oracle import assert_close

tf = pytest.importorskip("tensorflow")

tf1 = tf.compat.v1


def _v1_graph():
    g = tf1.Graph()
    ctx = g.as_default()
    ctx.__enter__()
    return g, ctx


def test_v1_while_loop_raw_frame():
    """tf.compat.v1 while_loop with control-flow v2 disabled emits the raw
    Enter/Merge/Switch/Exit/NextIteration/LoopCond nodes."""
    from bigdl_tpu.utils.tf_loader import load_tf

    g, ctx = _v1_graph()
    try:
        tf1.disable_control_flow_v2()
        x = tf1.placeholder(tf.float32, [3], name="x")

        def cond(i, acc):
            return i < 5

        def body(i, acc):
            return i + 1, acc * 1.5 + 1.0

        _, out = tf1.while_loop(cond, body, [tf.constant(0), x], name="loop")
        out = tf.identity(out, name="out")
        gd = g.as_graph_def()
        xv = np.array([0.5, -1.0, 2.0], np.float32)
        with tf1.Session(graph=g) as sess:
            want = sess.run(out, {x: xv})
    finally:
        tf1.enable_control_flow_v2()
        ctx.__exit__(None, None, None)

    ops = {n.op for n in gd.node}
    assert {"Enter", "Merge", "Switch", "Exit", "NextIteration",
            "LoopCond"} <= ops, f"not a raw v1 loop: {ops}"
    model = load_tf(gd, ["x"], ["out"])
    got = np.asarray(model.forward(xv))
    assert_close(got, want, atol=1e-5)


def test_v1_while_loop_dynamic_rnn_style():
    """Time-step recurrence h = tanh(x_t W + h U) as a raw v1 while loop —
    the dynamic-RNN shape the reference's TF importer handles."""
    from bigdl_tpu.utils.tf_loader import load_tf

    rs = np.random.RandomState(3)
    T, B, D, H = 4, 2, 3, 5
    Wv = rs.randn(D, H).astype(np.float32) * 0.4
    Uv = rs.randn(H, H).astype(np.float32) * 0.4
    xv = rs.randn(T, B, D).astype(np.float32)

    g, ctx = _v1_graph()
    try:
        tf1.disable_control_flow_v2()
        x = tf1.placeholder(tf.float32, [T, B, D], name="x")
        W = tf.constant(Wv)
        U = tf.constant(Uv)
        h0 = tf.zeros([B, H])

        def cond(t, h):
            return t < T

        def body(t, h):
            xt = tf.gather(x, t)
            return t + 1, tf.tanh(tf.matmul(xt, W) + tf.matmul(h, U))

        _, hT = tf1.while_loop(cond, body, [tf.constant(0), h0], name="rnn")
        out = tf.identity(hT, name="out")
        gd = g.as_graph_def()
        with tf1.Session(graph=g) as sess:
            want = sess.run(out, {x: xv})
    finally:
        tf1.enable_control_flow_v2()
        ctx.__exit__(None, None, None)

    assert any(n.op == "Enter" for n in gd.node)
    model = load_tf(gd, ["x"], ["out"])
    got = np.asarray(model.forward(xv))
    assert_close(got, want, atol=1e-5)


def test_v1_cond_switch_merge():
    """tf.compat.v1.cond emits Switch/Merge pairs; lowered to
    compute-both + select, so a data-dependent predicate must flip the
    result between calls of the SAME loaded graph."""
    from bigdl_tpu.utils.tf_loader import load_tf

    g, ctx = _v1_graph()
    try:
        tf1.disable_control_flow_v2()
        x = tf1.placeholder(tf.float32, [4], name="x")
        pred = tf.reduce_sum(x) > 0.0
        out = tf1.cond(pred, lambda: x * 2.0, lambda: x - 3.0)
        out = tf.identity(out, name="out")
        gd = g.as_graph_def()
        xs = [np.full((4,), 1.0, np.float32), np.full((4,), -1.0, np.float32)]
        with tf1.Session(graph=g) as sess:
            wants = [sess.run(out, {x: xv}) for xv in xs]
    finally:
        tf1.enable_control_flow_v2()
        ctx.__exit__(None, None, None)

    assert any(n.op == "Switch" for n in gd.node)
    assert any(n.op == "Merge" for n in gd.node)
    model = load_tf(gd, ["x"], ["out"])
    for xv, want in zip(xs, wants):
        assert_close(np.asarray(model.forward(xv)), want, atol=1e-6)


def _freeze(fn, spec):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    conc = tf.function(fn).get_concrete_function(spec)
    frozen = convert_variables_to_constants_v2(conc)
    return frozen.graph.as_graph_def(), frozen


def _while_fn(x):
    i = tf.constant(0)

    def cond(i, v):
        return i < 4

    def body(i, v):
        return i + 1, v * v - 0.5

    _, out = tf.while_loop(cond, body, [i, x])
    return tf.identity(out, name="out")


def test_v2_functional_stateless_while():
    """Unfrozen concrete-function graph keeps the functional
    While/StatelessWhile node + FunctionDef library."""
    from bigdl_tpu.utils.tf_loader import load_tf

    xv = np.array([[0.3, -0.7], [1.1, 0.0]], np.float32)
    conc = tf.function(_while_fn).get_concrete_function(
        tf.TensorSpec([2, 2], tf.float32))
    want = conc(tf.constant(xv)).numpy()
    gd = conc.graph.as_graph_def()
    assert any(n.op in ("While", "StatelessWhile") for n in gd.node), \
        sorted({n.op for n in gd.node})

    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = "out"
    model = load_tf(gd, [in_name], [out_name])
    assert_close(np.asarray(model.forward(xv)), want, atol=1e-5)


def test_v2_frozen_while_lowers_to_raw_frame():
    """TF's freezing pass lowers functional While back to the raw
    Enter/Merge/Switch/Exit form (with Func/NoOp control plumbing) — the
    frame extractor must digest that dialect too."""
    from bigdl_tpu.utils.tf_loader import load_tf

    xv = np.array([[0.3, -0.7], [1.1, 0.0]], np.float32)
    gd, frozen = _freeze(_while_fn, tf.TensorSpec([2, 2], tf.float32))
    want = frozen(tf.constant(xv))[0].numpy()
    assert any(n.op == "Enter" for n in gd.node)

    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = [n.name for n in gd.node if n.name == "Identity"
                or n.name.endswith("/Identity")][-1] if not any(
        n.name == "out" for n in gd.node) else "out"
    model = load_tf(gd, [in_name], [out_name])
    assert_close(np.asarray(model.forward(xv)), want, atol=1e-5)


def _cond_fn(x):
    out = tf.cond(tf.reduce_mean(x) > 0.0,
                  lambda: tf.nn.relu(x) + 1.0,
                  lambda: x * 0.5)
    return tf.identity(out, name="out")


def test_v2_functional_stateless_if():
    """Unfrozen concrete-function graph keeps the functional If node."""
    from bigdl_tpu.utils.tf_loader import load_tf

    conc = tf.function(_cond_fn).get_concrete_function(
        tf.TensorSpec([3], tf.float32))
    gd = conc.graph.as_graph_def()
    assert any(n.op in ("If", "StatelessIf") for n in gd.node), \
        sorted({n.op for n in gd.node})
    xs = [np.array([1.0, -2.0, 4.0], np.float32),
          np.array([-1.0, -2.0, 0.5], np.float32)]
    wants = [conc(tf.constant(xv)).numpy() for xv in xs]

    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    model = load_tf(gd, [in_name], ["out"])
    for xv, want in zip(xs, wants):
        assert_close(np.asarray(model.forward(xv)), want, atol=1e-6)


def test_v2_frozen_cond_lowers_to_switch_merge():
    """Frozen v2 cond arrives as raw Switch/Merge — the select lowering
    must flip with the predicate on the SAME loaded graph."""
    from bigdl_tpu.utils.tf_loader import load_tf

    gd, frozen = _freeze(_cond_fn, tf.TensorSpec([3], tf.float32))
    xs = [np.array([1.0, -2.0, 4.0], np.float32),
          np.array([-1.0, -2.0, 0.5], np.float32)]
    wants = [frozen(tf.constant(xv))[0].numpy() for xv in xs]
    assert any(n.op == "Switch" for n in gd.node), \
        sorted({n.op for n in gd.node})

    in_name = [n.name for n in gd.node if n.op == "Placeholder"][0]
    out_name = "out" if any(n.name == "out" for n in gd.node) else \
        [n.name for n in gd.node if n.name.endswith("Identity")][-1]
    model = load_tf(gd, [in_name], [out_name])
    for xv, want in zip(xs, wants):
        assert_close(np.asarray(model.forward(xv)), want, atol=1e-6)


def test_v1_nested_cond_picks_outer_predicate():
    """Nested v1 conds: the outer Merge must select on the OUTER predicate
    (a first-Switch-found trace would key on the inner one)."""
    from bigdl_tpu.utils.tf_loader import load_tf

    g, ctx = _v1_graph()
    try:
        tf1.disable_control_flow_v2()
        x = tf1.placeholder(tf.float32, [2], name="x")
        p1 = tf.reduce_sum(x) > 0.0          # outer predicate
        p2 = tf.reduce_max(x) > 2.0          # inner predicate

        def inner():
            return tf1.cond(p2, lambda: x * 10.0, lambda: x + 100.0)

        out = tf1.cond(p1, inner, lambda: x - 7.0)
        out = tf.identity(out, name="out")
        gd = g.as_graph_def()
        # p1>0,p2>2 / p1>0,p2<2 / p1<0 — all three paths
        xs = [np.array([3.0, 1.0], np.float32),
              np.array([1.0, 0.5], np.float32),
              np.array([-5.0, 1.0], np.float32)]
        with tf1.Session(graph=g) as sess:
            wants = [sess.run(out, {x: xv}) for xv in xs]
    finally:
        tf1.enable_control_flow_v2()
        ctx.__exit__(None, None, None)

    model = load_tf(gd, ["x"], ["out"])
    for xv, want in zip(xs, wants):
        assert_close(np.asarray(model.forward(xv)), want, atol=1e-6)
