"""2-process jax.distributed pod analog (round-1 verdict next-step #10;
reference anchor: Engine.init topology validation + SURVEY §5.8 multi-slice
note). Spawns two worker processes with 4 virtual CPU devices each, a
localhost coordinator, and one global 8-device data mesh; each runs the
full DistriOptimizer partitioned path on its OWN data shard and must end
with bit-identical parameters."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.integration

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_pod(tmp_path, mode, expect_rc=0, timeout=240, n=2):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port), str(tmp_path),
             mode, str(n)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in range(n)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc, (
            f"worker {pid} rc={p.returncode} (want {expect_rc}):\n"
            f"{out[-3000:]}")
    return outs


def test_two_process_distri_optimizer(tmp_path):
    outs = _run_pod(tmp_path, "orig")
    for pid, out in enumerate(outs):
        # pod validation merged globally: 2 x 50-sample shards -> count=100
        assert "count=100" in out, f"worker {pid} output:\n{out[-3000:]}"

    p0 = np.load(tmp_path / "params_0.npy")
    p1 = np.load(tmp_path / "params_1.npy")
    assert p0.shape == p1.shape and p0.size > 10_000
    np.testing.assert_array_equal(p0, p1)
    # and training actually moved the params (not a frozen no-op)
    assert float(np.abs(p0).sum()) > 0


def test_pod_checkpoint_kill_resume(tmp_path):
    """Pod durability (round-2 verdict item #4): checkpoint mid-run in
    partitioned mode, kill both workers hard (os._exit), restart fresh
    processes that resume from disk — the continued trajectory must land
    bit-identical to an uninterrupted 6-iteration run."""
    straight = tmp_path / "straight"
    straight.mkdir()
    _run_pod(straight, "straight")
    ref = np.load(straight / "params_0.npy")

    pod = tmp_path / "pod"
    pod.mkdir()
    _run_pod(pod, "crash", expect_rc=3)
    for pid in (0, 1):
        # on a pod the Optimizer suffixes the configured path per-rank
        # (proc_<rank>) so ranks sharing one durable path cannot race
        assert (pod / f"ckpt_{pid}" / f"proc_{pid}" / "model").exists(), (
            "no checkpoint written before the kill")
    _run_pod(pod, "resume")
    for pid in (0, 1):
        out = np.load(pod / f"params_{pid}.npy")
        np.testing.assert_array_equal(out, ref)


def test_pod_bounded_retry(tmp_path):
    """The §5.3 retry loop in its pod mode: BOTH workers hit an injected
    transient failure at iteration 4, reload their iteration-3 checkpoints
    in-process, and must still converge to the uninterrupted-run params."""
    straight = tmp_path / "straight"
    straight.mkdir()
    _run_pod(straight, "straight")
    ref = np.load(straight / "params_0.npy")

    pod = tmp_path / "pod"
    pod.mkdir()
    outs = _run_pod(pod, "retry")
    for pid, out in enumerate(outs):
        assert "retrying from checkpoint" in out, out[-2000:]
        arr = np.load(pod / f"params_{pid}.npy")
        np.testing.assert_array_equal(arr, ref)


def test_pod_blockstore_parameter_plane(tmp_path):
    """The BlockManager-analog DCN exchange (parallel/block_store.py) over
    the real coordination-service KV store: 2 processes, partitions owned
    by process, weights assembled from published partitions — both workers
    must finish with identical parameters that actually moved."""
    outs = _run_pod(tmp_path, "blockstore")
    for pid, out in enumerate(outs):
        assert "drops=0" in out, f"worker {pid}:\n{out[-3000:]}"
    p0 = np.load(tmp_path / "params_0.npy")
    p1 = np.load(tmp_path / "params_1.npy")
    np.testing.assert_array_equal(p0, p1)
    assert float(np.abs(p0).sum()) > 0


def test_pod_blockstore_drop_wide_targeting_and_recovery(tmp_path):
    """Round-5 verdict item #5: the drop policy at realistic width in a
    REAL 8-process pod (1 CPU device each), drop_percentage=0.15 —
    min_arrivals = ceil(0.85*8) = 7, so exactly one contribution may be
    dropped per aggregation (at n=6 the same p yields min_arrivals=6 and
    NOTHING can drop — width changes the policy's arithmetic, which is
    the point of this test). One persistent straggler (the last worker,
    delayed puts iters 2-5) HEALS from iteration 6. Verifies warmup,
    targeting (every drop across the healthy owners names only the
    straggler), probe recovery (no drops after the heal margin), and
    identical final weights."""
    import ast

    n = 8
    outs = _run_pod(tmp_path, "blockstore_drop_wide", n=n, timeout=600)
    straggler = n - 1
    info = {}
    for pid, out in enumerate(outs):
        d = {}
        for line in out.splitlines():
            if f"worker {pid}: drops=" in line:
                d["total"] = int(line.split("drops=")[1])
            elif f"worker {pid}: drops_by_src=" in line:
                d["by_src"] = dict(ast.literal_eval(
                    line.split("drops_by_src=")[1]))
            elif f"worker {pid}: drop_log=" in line:
                d["log"] = ast.literal_eval(line.split("drop_log=")[1])
        info[pid] = d
    healthy = [p for p in range(n) if p != straggler]
    assert sum(info[p]["total"] for p in healthy) > 0, info
    for p in healthy:
        assert set(info[p].get("by_src", {})) <= {straggler}, (p, info[p])
        # warmup held and the healed iterations (margin 1 for the probe)
        # proceeded without drops
        assert all(2 <= t <= 6 for t, _ in info[p].get("log", [])), info[p]
    assert info[straggler]["total"] == 0, info[straggler]
    arrs = [np.load(tmp_path / f"params_{pid}.npy") for pid in range(n)]
    for pid in range(1, n):
        np.testing.assert_array_equal(arrs[0], arrs[pid])
    assert float(np.abs(arrs[0]).sum()) > 0


def test_pod_blockstore_gradient_drop(tmp_path):
    """Reference dropPercentage semantics in a REAL 3-process pod: worker
    2's gradient puts straggle from iteration 2 on (after the warmup
    window calibrated thresholds); workers 0 and 1 must drop its
    contributions at the deadline and keep training, and all three still
    assemble identical weights (weight partitions are never dropped)."""
    outs = _run_pod(tmp_path, "blockstore_drop", n=3, timeout=420)
    drops = []
    for pid, out in enumerate(outs):
        for line in out.splitlines():
            if f"worker {pid}: drops=" in line:
                drops.append(int(line.split("drops=")[1]))
    assert len(drops) == 3, [o[-2000:] for o in outs]
    # owners 0 and 1 each dropped worker 2's contribution in the 4
    # post-warmup iterations; worker 2's own partition saw fast blocks
    assert drops[0] > 0 and drops[1] > 0, drops
    assert drops[2] == 0, drops
    arrs = [np.load(tmp_path / f"params_{pid}.npy") for pid in range(3)]
    np.testing.assert_array_equal(arrs[0], arrs[1])
    np.testing.assert_array_equal(arrs[0], arrs[2])
    assert float(np.abs(arrs[0]).sum()) > 0
