"""2-process jax.distributed pod analog (round-1 verdict next-step #10;
reference anchor: Engine.init topology validation + SURVEY §5.8 multi-slice
note). Spawns two worker processes with 4 virtual CPU devices each, a
localhost coordinator, and one global 8-device data mesh; each runs the
full DistriOptimizer partitioned path on its OWN data shard and must end
with bit-identical parameters."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.integration

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distri_optimizer(tmp_path):
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(pid), str(port), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        # pod validation merged globally: 2 x 50-sample shards -> count=100
        assert "count=100" in out, f"worker {pid} output:\n{out[-3000:]}"

    p0 = np.load(tmp_path / "params_0.npy")
    p1 = np.load(tmp_path / "params_1.npy")
    assert p0.shape == p1.shape and p0.size > 10_000
    np.testing.assert_array_equal(p0, p1)
    # and training actually moved the params (not a frozen no-op)
    assert float(np.abs(p0).sum()) > 0
