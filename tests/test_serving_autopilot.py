"""SLO autopilot suite (serving/autopilot.py): the closed control
loop's pinned contracts.

* Controller: the dead-band / sustain / cooldown hysteresis discipline
  is flap-free by construction — a boundary-riding signal can never
  fire, the dead band resets both runs, cooldown spaces actions, and
  ``hold_down`` vetoes only the low side. OccupancyAutoscaler IS this
  class now (the PR 14 discipline, generalized).
* ServingMetrics.window: bounded-recency mean/p50/p99, and the
  service-time estimate follows a traffic-phase shift instead of
  averaging it away.
* Degrade revert: a row degraded at a queue-depth spike gets its full
  budget back once pressure drops — static ``degrade_at`` path and the
  bus's per-class apply/restore both.
* Deadline-aware preemption: the evicted long-slack row's stream stays
  BYTE-IDENTICAL to an unpreempted run (loss-free: the loop reorders
  latency, never tokens), and the short-deadline waiter seats in time.
* Zero extra compiles: every actuation is host bookkeeping over
  runtime data — flipping knobs mid-run adds no programs.
* Interop: speculative draft cap, the disagg pool controller on the
  shared bus, and the seeded workload zoo (benchmarks/serving_bench).

Everything timed runs on a VirtualClock/SteppingClock — deterministic
virtual time, no sleeping — so every number here is a pure function of
the seed.
"""

import importlib

import numpy as np
import pytest

from tests.compile_guards import compile_count
from tests.test_serving import _make_lm


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


def _req(eng, rid):
    """The Request object wherever it lives — running, waiting, or
    finished (``engine.request`` only reads the finished ledger)."""
    for r in eng.scheduler.running.values():
        if r.req_id == rid:
            return r
    for e in eng.scheduler._waiting:
        if e[1].req_id == rid:
            return e[1]
    return eng.request(rid)


# -- Controller: the shared hysteresis discipline ---------------------------

def _controller(**kw):
    from bigdl_tpu.serving import Controller

    args = dict(high_water=0.8, low_water=0.2, sustain=3, cooldown=8)
    args.update(kw)
    return Controller(**args)


def test_controller_validation():
    from bigdl_tpu.serving import Controller

    with pytest.raises(ValueError, match="low_water < high_water"):
        Controller(high_water=0.2, low_water=0.8)
    with pytest.raises(ValueError, match="sustain"):
        _controller(sustain=0)
    with pytest.raises(ValueError, match="cooldown"):
        _controller(cooldown=-1)


def test_controller_sustain_demands_consecutive_evidence():
    c = _controller(sustain=3, cooldown=0)
    assert c.observe(0.9) is None
    assert c.observe(0.9) is None
    # a single dead-band sample resets the run — two more highs are
    # not enough, only the third CONSECUTIVE one fires
    assert c.observe(0.5) is None
    assert c.observe(0.9) is None
    assert c.observe(0.9) is None
    assert c.observe(0.9) == "up"


def test_controller_born_ready_and_cooldown_spacing():
    # born ready: the FIRST action needs no cooldown to expire
    c = _controller(sustain=1, cooldown=4)
    assert c.observe(0.9) == "up"
    # ... but the next one does: 4 samples of cooldown, then fire
    fired = [c.observe(0.9) for _ in range(5)]
    assert fired == [None, None, None, None, "up"]


def test_controller_low_side_and_hold_down_veto():
    c = _controller(sustain=2, cooldown=0)
    assert c.observe(0.1) is None
    assert c.observe(0.1) == "down"
    # hold_down vetoes ONLY the low side (the autoscaler's backlogged
    # lull): a vetoed low sample resets the run like a dead-band one
    c2 = _controller(sustain=2, cooldown=0)
    assert c2.observe(0.1, hold_down=True) is None
    assert c2.observe(0.1, hold_down=True) is None
    assert c2.observe(0.1) is None          # run restarted by the veto
    assert c2.observe(0.1) == "down"
    assert c2.observe(0.9, hold_down=True) is None
    assert c2.observe(0.9, hold_down=True) == "up"   # high side immune


def test_controller_can_gates_do_not_consume_the_run():
    c = _controller(sustain=2, cooldown=0)
    assert c.observe(0.9, can_up=False) is None
    assert c.observe(0.9, can_up=False) is None
    # the run is sustained; the moment the actuator CAN move, it does
    assert c.observe(0.9) == "up"


def test_controller_flap_free_on_boundary_riding_signal():
    """The flap-freedom argument, asserted: a signal that alternates
    across the dead band every sample NEVER fires (the band resets
    both runs), and a square wave riding the waterlines fires at most
    once per cooldown window."""
    c = _controller(sustain=3, cooldown=0)
    for _ in range(100):
        assert c.observe(0.9) is None
        assert c.observe(0.5) is None
    c2 = _controller(sustain=2, cooldown=10)
    acts = [c2.observe(s) for s in ([0.9] * 50 + [0.1] * 50)]
    fired = [i for i, a in enumerate(acts) if a is not None]
    assert all(b - a > 10 for a, b in zip(fired, fired[1:])), \
        f"actions closer than cooldown: {fired}"


def test_autoscaler_is_a_controller():
    from bigdl_tpu.serving import Controller
    from bigdl_tpu.serving.health import OccupancyAutoscaler

    a = OccupancyAutoscaler()
    assert isinstance(a, Controller)
    # the occupancy sample shape still works through the base
    cfg = a.config
    for _ in range(cfg.sustain):
        d = a.observe(cfg.high_water, backlog=0, can_up=True,
                      can_down=True)
    assert d == "up"


# -- AutopilotConfig / vocabulary -------------------------------------------

def test_autopilot_config_validation():
    from bigdl_tpu.serving import AutopilotConfig

    AutopilotConfig()                                   # defaults valid
    with pytest.raises(ValueError, match="gap_target_s"):
        AutopilotConfig(gap_target_s=0.0)
    with pytest.raises(ValueError, match="gap_low < gap_high"):
        AutopilotConfig(gap_low=2.0, gap_high=1.0)
    with pytest.raises(ValueError, match="queue_low < queue_high"):
        AutopilotConfig(queue_low=5.0, queue_high=5.0)
    with pytest.raises(ValueError, match="chunk_min"):
        AutopilotConfig(chunk_min=64, chunk_max=8)
    with pytest.raises(ValueError, match="window"):
        AutopilotConfig(window=0)
    with pytest.raises(ValueError, match="preempt_margin_s"):
        AutopilotConfig(preempt_margin_s=-0.1)


def test_actuation_sites_name_real_bus_methods():
    """The closed vocabulary is live: every ActuatorBus entry in
    ACTUATION_SITES is an actual method (a typo'd site would silently
    un-declare an actuator and SRV208 would start flagging it)."""
    from bigdl_tpu.serving import ACTUATION_SITES, ActuatorBus

    assert isinstance(ACTUATION_SITES, frozenset) and ACTUATION_SITES
    for site in ACTUATION_SITES:
        mod, cls, meth = site.split(".")
        if cls == "ActuatorBus":
            assert callable(getattr(ActuatorBus, meth)), site


# -- ServingMetrics.window / the service-time estimate ----------------------

def test_window_rolling_stats():
    from bigdl_tpu.serving import ServingMetrics

    m = ServingMetrics()
    assert m.window("decode_gap_s", 8) is None          # no samples yet
    for v in (0.1, 0.2, 0.3, 0.4):
        m.metrics.add("serving/decode_gap_s", v)
    w = m.window("decode_gap_s", 2)                     # last two only
    assert w["n"] == 2 and w["mean"] == pytest.approx(0.35)
    w = m.window("decode_gap_s", 100)                   # clamps to all
    assert w["n"] == 4 and w["p50"] == pytest.approx(0.25)
    assert w["p99"] <= 0.4
    with pytest.raises(ValueError, match="window size"):
        m.window("decode_gap_s", 0)


def test_service_estimate_follows_a_phase_shift():
    """The estimate is a WINDOWED median, not a lifetime one: after a
    traffic-phase shift (70 slow steps, then a window of fast ones)
    it reports the current phase — a whole-run median would still be
    poisoned by the lull and admit guaranteed misses."""
    from bigdl_tpu.serving import ServingMetrics

    m = ServingMetrics()
    assert m.service_time_estimate() is None            # never guesses
    for _ in range(70):
        m.add_phase("decode_step", 1.0)
    assert m.service_time_estimate() == pytest.approx(1.0)
    for _ in range(64):
        m.add_phase("decode_step", 0.01)
    assert m.service_time_estimate() == pytest.approx(0.01)


# -- degrade revert (static path + the bus) ---------------------------------

def test_static_degrade_reverts_when_pressure_drops(lm):
    """The PR 19 regression fix, pinned: a row degraded at a
    queue-depth spike, then preempted back to WAITING, gets its full
    budget back once the queue drains below ``degrade_at`` — before,
    the clamp was forever."""
    from bigdl_tpu.serving import Degrade, ServingEngine

    eng = ServingEngine(lm, n_slots=1, policy="priority", degrade_at=2)
    a = eng.submit([3, 7, 2, 9], max_new_tokens=8, priority=0,
                   degrade=Degrade(max_new_tokens=3))
    d1 = eng.submit([5, 5], max_new_tokens=1, priority=0)
    d2 = eng.submit([6, 6], max_new_tokens=1, priority=0)
    eng.step()                       # A seats with depth 2 -> degraded
    req = _req(eng, a)
    assert req.degraded and req.max_new_tokens == 3
    eng.cancel(d1), eng.cancel(d2)
    eng.submit([4, 4], max_new_tokens=2, priority=5)   # evicts A
    outs = eng.drain()
    req = eng.request(a)
    assert not req.degraded, "clamp survived the lull"
    assert len(outs[a]) == 8, f"restored row emitted {len(outs[a])}/8"
    s = eng.metrics.summary()
    assert s["serving/degraded"] == 1
    assert s["serving/degrade_restored"] == 1


def test_bus_degrade_is_per_class_and_revertible(lm):
    from bigdl_tpu.serving import Autopilot, Degrade, ServingEngine

    ap = Autopilot()
    eng = ServingEngine(lm, n_slots=1, policy="priority", autopilot=ap)
    eng.submit([3, 7], max_new_tokens=24, priority=0)   # slot hog
    eng.step()
    lo = eng.submit([2, 9], max_new_tokens=8, priority=0,
                    degrade=Degrade(max_new_tokens=2))
    hi = eng.submit([4, 8], max_new_tokens=8, priority=3,
                    degrade=Degrade(max_new_tokens=2))
    # per-class: only the batch tier (priority <= 0) sheds budget
    assert ap.bus.degrade_waiting(below_priority=0) == 1
    assert _req(eng, lo).degraded and _req(eng, lo).max_new_tokens == 2
    assert not _req(eng, hi).degraded
    assert ap.bus.restore_waiting() == 1
    assert not _req(eng, lo).degraded
    assert _req(eng, lo).max_new_tokens == 8
    # the audit log saw both actuations, tagged with counts
    assert [(a, v) for _, a, v in ap.bus.log] == [("degrade", 1),
                                                  ("restore", 1)]
    assert eng.metrics.summary()["serving/actuations"] == 2


def test_sample_drives_degrade_from_live_queue_depth(lm):
    """The degrade loop closed end-to-end through ``step()``: a queue
    spike past ``queue_high`` sustained degrades the waiting batch
    tier, and the drained lull restores it — no direct bus calls."""
    from bigdl_tpu.serving import (Autopilot, AutopilotConfig, Degrade,
                                   ServingEngine)

    ap = Autopilot(AutopilotConfig(queue_high=2.0, queue_low=1.0,
                                   sustain=1, cooldown=0))
    eng = ServingEngine(lm, n_slots=1, policy="priority", autopilot=ap)
    eng.submit([3, 7], max_new_tokens=30, priority=0)   # slot hog
    rows = [eng.submit([2 + i, 9], max_new_tokens=8, priority=0,
                       degrade=Degrade(max_new_tokens=2))
            for i in range(3)]
    eng.step()                       # sample sees depth 3 >= 2 -> degrade
    assert all(_req(eng, r).degraded for r in rows)
    for r in rows[1:]:
        eng.cancel(r)
    eng.step()                       # depth 1 <= queue_low -> restore
    assert not _req(eng, rows[0]).degraded
    acts = [a for _, a, _ in ap.bus.log]
    assert acts == ["degrade", "restore"]


# -- deadline-aware preemption ----------------------------------------------

def _run_deadline_trace(lm, autopilot):
    from bigdl_tpu.serving import ServingEngine, SteppingClock

    eng = ServingEngine(lm, n_slots=1, policy="priority",
                        clock=SteppingClock(0.002), autopilot=autopilot)
    long_row = eng.submit([3, 7, 2, 9, 4], max_new_tokens=20, priority=0)
    for _ in range(4):
        eng.step()                   # seat + seed the estimator
    short_row = eng.submit([5, 8], max_new_tokens=4, priority=0,
                           deadline_s=0.1)
    outs = eng.drain()
    return eng, outs, long_row, short_row


def test_deadline_preemption_is_loss_free_and_seats_the_waiter(lm):
    """The tentpole's preemption contract: a knife-edge waiter in the
    SAME priority class (class preemption would do nothing) evicts the
    long-slack row, makes its deadline, and the victim's stream is
    byte-identical to a run with the loop disabled — scheduling
    reorders latency, never tokens."""
    from bigdl_tpu.serving import Autopilot, AutopilotConfig

    on = Autopilot(AutopilotConfig(preempt_margin_s=0.12))
    off = Autopilot(AutopilotConfig(preempt=False))
    eng1, outs1, l1, s1 = _run_deadline_trace(lm, on)
    eng0, outs0, l0, s0 = _run_deadline_trace(lm, off)

    m1 = eng1.metrics.summary()
    assert m1.get("serving/preempted", 0) >= 1, \
        "deadline preemption never fired"
    assert eng0.metrics.summary().get("serving/preempted", 0) == 0
    # the waiter made its deadline only under the closed loop — the
    # static engine dropped it at expiry while it queued behind the
    # long row
    assert eng1.request(s1).finish_time <= eng1.request(s1).deadline_time
    assert eng1.request(s1).finish_reason in ("length", "stop")
    assert eng0.request(s0).finish_reason == "deadline"
    # loss-free: the VICTIM's stream is byte-identical across the two
    # runs (evict + replay reconstructed the exact cache state), and
    # the dropped waiter's partial stream is a prefix of the saved one
    assert np.array_equal(outs1[l1], outs0[l0])
    dropped = np.asarray(eng0.request(s0).output, np.int32)
    assert np.array_equal(dropped, outs1[s1][:len(dropped)])
    assert len(outs1[s1]) == 4
    assert len(outs1[l1]) == 20 and eng1.request(l1).preemptions >= 1


def test_infeasible_waiter_never_triggers_eviction(lm):
    """An already-doomed waiter is the shed path's problem: evicting a
    healthy row for it wastes a replay and saves nobody."""
    from bigdl_tpu.serving import (Autopilot, AutopilotConfig,
                                   ServingEngine, SteppingClock)

    ap = Autopilot(AutopilotConfig(preempt_margin_s=0.12))
    eng = ServingEngine(lm, n_slots=1, policy="priority",
                        clock=SteppingClock(0.002), autopilot=ap)
    eng.submit([3, 7, 2], max_new_tokens=16, priority=0)
    for _ in range(4):
        eng.step()
    # 30 tokens of work against a 1ms deadline: infeasible even seated
    eng.submit([5, 8], max_new_tokens=30, priority=0, deadline_s=0.001)
    eng.drain()
    assert eng.metrics.summary().get("serving/preempted", 0) == 0


# -- zero extra compiles under actuation ------------------------------------

def _programs(eng):
    return (compile_count(eng._step_fn)
            + compile_count(eng._batch_prefill_fn))


def test_actuations_compile_nothing(lm):
    """Every actuation is host bookkeeping over per-row runtime data:
    flipping the chunk budget and the degrade knobs mid-run adds ZERO
    programs beyond the warmed set."""
    from bigdl_tpu.serving import (Autopilot, AutopilotConfig, Degrade,
                                   ServingEngine)

    prompts = [list(range(3, 13)), list(range(4, 14))]
    for budget in (8, 16):                              # warm both paths
        warm = ServingEngine(lm, n_slots=2, admission="chunked",
                             chunk_budget=budget)
        for p in prompts:
            warm.submit(p, max_new_tokens=3)
        warm.drain()

    ap = Autopilot(AutopilotConfig(queue_high=2.0, queue_low=1.0,
                                   sustain=1, cooldown=0))
    eng = ServingEngine(lm, n_slots=2, admission="chunked",
                        chunk_budget=16, policy="priority", autopilot=ap)
    before = _programs(eng)
    eng.submit(prompts[0], max_new_tokens=4)
    eng.step()
    assert ap.bus.set_chunk_budget(8)                   # actuate mid-run
    eng.submit(prompts[1], max_new_tokens=4,
               degrade=Degrade(max_new_tokens=2))
    eng.drain()
    ap.bus.set_chunk_budget(16)
    eng.submit(prompts[0], max_new_tokens=3)
    eng.drain()
    assert ap.bus.log, "no actuation recorded"
    assert _programs(eng) == before, \
        "an actuation recompiled the engine"


# -- speculative interop: the draft cap -------------------------------------

def test_draft_cap_is_runtime_data_and_streams_identical(lm):
    from bigdl_tpu.serving import (Autopilot, ServingEngine,
                                   SpeculativeConfig)

    draft = _make_lm(seed=31, hidden=16, heads=2, layers=1)
    prompt, gen = [3, 7, 2, 9], 12
    base = ServingEngine(lm, n_slots=1)
    r = base.submit(prompt, max_new_tokens=gen)
    want = base.drain()[r]

    ap = Autopilot()
    eng = ServingEngine(lm, n_slots=1, autopilot=ap,
                        speculative=SpeculativeConfig(draft, k=3))
    r = eng.submit(prompt, max_new_tokens=gen)
    eng.step()
    drafted_before, _ = eng.metrics.metrics.get("serving/draft_tokens")
    assert drafted_before > 0, "no drafting before the cap"
    assert ap.bus.set_draft_cap(0)                      # kill drafting
    outs = eng.drain()
    drafted_after, _ = eng.metrics.metrics.get("serving/draft_tokens")
    assert drafted_after == drafted_before, \
        "draft dispatches continued past cap 0"
    assert np.array_equal(outs[r], want)                # exactness holds
    assert ("draft_cap", 0) in [(a, v) for _, a, v in ap.bus.log]


# -- attach discipline ------------------------------------------------------

def test_autopilot_binds_to_one_engine(lm):
    from bigdl_tpu.serving import Autopilot, ServingEngine

    ap = Autopilot()
    eng = ServingEngine(lm, n_slots=1, autopilot=ap)
    with pytest.raises(ValueError, match="one instance per engine"):
        ServingEngine(lm, n_slots=1, autopilot=ap)
    with pytest.raises(ValueError, match="not attached"):
        ap.sample(ServingEngine(lm, n_slots=1))
    # attach folded the measured estimate into the queue order
    assert eng.scheduler.service_estimate is not None


# -- disagg interop: pool scale on the shared bus ---------------------------

def test_disagg_registers_pool_controller_on_the_bus(lm):
    from bigdl_tpu.serving import (Autopilot, DisaggregatedEngine,
                                   ServingEngine)
    from bigdl_tpu.serving.health import OccupancyAutoscaler

    mono = ServingEngine(lm, n_slots=2)
    prompts = [[3, 7, 2], [9, 4, 5], [6, 1, 8]]
    for p in prompts:
        mono.submit(p, max_new_tokens=6)
    want = mono.drain()

    ap = Autopilot()
    d = DisaggregatedEngine(lm, prefill_slots=2, decode_slots=2,
                            decode_pools=2, standby_pools=1,
                            autoscaler=True, autopilot=ap)
    rids = [d.submit(p, max_new_tokens=6) for p in prompts]
    got = d.drain()
    assert all(np.array_equal(got[r], w)
               for r, w in zip(rids, want.values()))
    # the pool scaler rides the one controller registry + audit bus
    assert isinstance(ap.controllers["pool_scale"], OccupancyAutoscaler)
    assert ap.bus is not None and ap.bus.engine is d.prefill.engine


# -- the workload zoo -------------------------------------------------------

def test_zoo_trace_is_seed_deterministic():
    bench = importlib.import_module("benchmarks.serving_bench")
    cfg = {"vocab": 29}
    t1, t2 = bench.make_zoo_trace(cfg, 43), bench.make_zoo_trace(cfg, 43)
    assert t1 == t2, "same seed, different trace"
    assert bench.make_zoo_trace(cfg, 7) != t1
    arrivals = [r[0] for r in t1]
    assert arrivals == sorted(arrivals)
    for arrival, prompt, max_new, priority, deadline_s, degrade_to in t1:
        assert prompt and all(1 <= t <= 29 for t in prompt)
        assert max_new >= 1 and arrival >= 0.0
        assert deadline_s is None or deadline_s > 0
    # every tenant shape present: a hi-pri class, a degradable class
    assert any(r[3] > 0 for r in t1) and any(r[5] for r in t1)


def test_bench_autopilot_closed_loop_beats_static_sweep():
    """The headline claim, end to end: on the seeded zoo trace the
    closed loop strictly beats every static config on goodput-under-
    SLO, compiles nothing mid-run, and keeps clean streams identical
    (the bench asserts all of it internally — a green run IS the
    contract)."""
    bench = importlib.import_module("benchmarks.serving_bench")
    out = bench.run_autopilot()
    best_static = max(s["goodput"] for s in out["static"].values())
    assert out["closed"]["goodput"] > best_static
    assert out["closed"]["compiled_in_run"] == 0
    assert out["streams_identical"]
