"""Per-row sampled decoding (bigdl_tpu/serving/sampling.py): greedy
degradation parity, fixed-seed reproducibility across batching and
eviction/readmission, the zero-extra-compiles guarantee for mixed
sampling knobs, stop sets (per-request eos / stop tokens / stop
sequences / min-tokens ban), the logprobs surface, and the sampling
metrics + bench smoke."""

import numpy as np
import pytest


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    """One model for the whole module — every engine over it shares the
    cached jitted steps, so the file pays each (dtype, n_slots) compile
    once."""
    return _make_lm()


# -- params surface --------------------------------------------------------

def test_sampling_params_validation():
    from bigdl_tpu.serving.sampling import MAX_BAN_IDS, SamplingParams

    sp = SamplingParams()                        # default is greedy
    assert sp.is_greedy and sp.temperature == 0.0
    assert SamplingParams.greedy().is_greedy
    assert not SamplingParams(temperature=0.7).is_greedy
    # list inputs canonicalize to hashable tuples
    sp = SamplingParams(stop_token_ids=[3, 5], stop_sequences=[[1, 2]])
    assert sp.stop_token_ids == (3, 5)
    assert sp.stop_sequences == ((1, 2),)
    for bad in [dict(temperature=-0.1), dict(top_k=-1), dict(top_p=0.0),
                dict(top_p=1.5), dict(repetition_penalty=0.0),
                dict(min_tokens=-1), dict(max_tokens=0),
                dict(stop_token_ids=(0,)), dict(stop_sequences=((),)),
                dict(stop_sequences=((1, -2),)),
                dict(stop_token_ids=tuple(range(1, MAX_BAN_IDS + 1)))]:
        with pytest.raises(ValueError):
            SamplingParams(**bad)


# -- greedy degradation (THE acceptance contract) --------------------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_temperature_zero_matches_greedy_generate(dtype_name, lm, rng):
    """temperature=0 rows of the sampled step degrade EXACTLY to argmax:
    engine outputs (default params AND explicit greedy SamplingParams)
    are token-for-token identical to sequential generate(temperature=0)
    — fp32 and bf16 serving params."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    dtype = None if dtype_name == "fp32" else jnp.bfloat16
    reqs = []
    for _ in range(5):
        plen = int(rng.randint(1, 6))
        reqs.append((rng.randint(1, 30, size=(plen,)).tolist(),
                     int(rng.randint(3, 10))))
    eng = ServingEngine(lm, n_slots=3, compute_dtype=dtype)
    ids = []
    for i, (p, n) in enumerate(reqs):
        sp = SamplingParams.greedy() if i % 2 else None
        ids.append(eng.submit(p, max_new_tokens=n, sampling=sp))
    outs = eng.drain()
    for rid, (p, n) in zip(ids, reqs):
        want = generate(lm, p, length=n, temperature=0.0,
                        compute_dtype=dtype)
        np.testing.assert_array_equal(
            outs[rid], want, err_msg=f"prompt={p} dtype={dtype_name}")
    assert eng.pool.free_slots == eng.pool.n_slots


# -- fixed-seed reproducibility --------------------------------------------

def test_fixed_seed_reproducible_across_batching_and_readmission(lm):
    """One seeded request must produce ONE token stream: batched with
    arbitrary neighbors (any slot), sequentially via generate() (the
    same sample_rows + lane_key), and readmitted into a recycled slot
    after another request's eviction."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    sp = SamplingParams(temperature=0.9, top_k=8, top_p=0.95, seed=123)
    prompt = [3, 7, 2]

    want = generate(lm, prompt, length=8, sampling=sp)
    assert len(want) == 8

    # batched: greedy + differently-seeded sampled neighbors
    eng = ServingEngine(lm, n_slots=3)
    r = eng.submit(prompt, max_new_tokens=8, sampling=sp)
    eng.submit([4, 4], max_new_tokens=5,
               sampling=SamplingParams(temperature=1.3, seed=7))
    eng.submit([9], max_new_tokens=8)
    outs = eng.drain()
    np.testing.assert_array_equal(outs[r], want)

    # readmission: a single-slot engine recycles slot 0 from a previous
    # occupant — the lane is seeded from the REQUEST, not the slot
    eng1 = ServingEngine(lm, n_slots=1)
    eng1.submit([1, 2], max_new_tokens=3,
                sampling=SamplingParams(temperature=1.1, seed=55))
    eng1.drain()
    r2 = eng1.submit(prompt, max_new_tokens=8, sampling=sp)
    np.testing.assert_array_equal(eng1.drain()[r2], want)

    # same engine, same explicit seed, resubmitted → same stream again
    r3 = eng1.submit(prompt, max_new_tokens=8, sampling=sp)
    np.testing.assert_array_equal(eng1.drain()[r3], want)

    # seed=None draws a fresh engine-derived lane per request id (so a
    # resubmit is NOT forced to repeat — over several tries the free
    # lane must diverge somewhere for a 29-vocab softmax at temp 1.3)
    free_sp = SamplingParams(temperature=1.3)
    outs = []
    for _ in range(4):
        rid = eng1.submit(prompt, max_new_tokens=8, sampling=free_sp)
        outs.append(eng1.drain()[rid])
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])


# -- compile-count guard ---------------------------------------------------

def test_mixed_knobs_add_zero_decode_compiles(lm):
    """ONE compiled decode program serves every knob mix: a greedy-only
    engine and a mixed greedy/sampled engine (same n_slots) share the
    same single trace — changing per-request knobs is runtime data,
    never a recompile (the acceptance criterion)."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine
    from tests.compile_guards import assert_compile_count, compile_count

    eng_g = ServingEngine(lm, n_slots=3)
    for p in ([3, 7, 2], [5], [9, 1]):
        eng_g.submit(p, max_new_tokens=4)
    eng_g.drain()
    base = compile_count(eng_g._step_fn)
    assert base >= 1

    eng_m = ServingEngine(lm, n_slots=3)
    eng_m.submit([3, 7, 2], max_new_tokens=4)
    eng_m.submit([5], max_new_tokens=4, sampling=SamplingParams(
        temperature=0.8, top_k=5, seed=1))
    eng_m.submit([9, 1], max_new_tokens=4, sampling=SamplingParams(
        temperature=1.2, top_p=0.9, repetition_penalty=1.3,
        presence_penalty=0.5, frequency_penalty=0.2, min_tokens=2,
        seed=2))
    eng_m.drain()
    # second wave with yet other knob mixes — still the same program
    eng_m.submit([2, 2], max_new_tokens=3, sampling=SamplingParams(
        temperature=0.6, top_k=3, top_p=0.7, seed=9))
    eng_m.drain()
    assert_compile_count(eng_m._step_fn, base, what="mixed-knob engine")
    assert eng_m._step_fn is eng_g._step_fn        # the shared cached step


# -- stop sets -------------------------------------------------------------

def test_per_request_eos_stop_tokens_sequences_min_tokens(lm):
    """Per-request stop machinery: private eos per request, stop TOKEN
    ids evict like an extra eos set (reason 'stop'), stop SEQUENCES
    match on host against the output tail, and min_tokens bans
    eos/stop tokens on device until the floor is met."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    free = generate(lm, [3, 7], length=8, temperature=0.0)
    eos = int(free[3])                     # a token greedy WILL emit
    cut = int(np.where(free == eos)[0][0])

    eng = ServingEngine(lm, n_slots=2)
    # per-request eos: same prompt, one stops at its private eos, the
    # other (no eos) runs to length — eos is not engine-wide state
    a = eng.submit([3, 7], max_new_tokens=8, eos_id=eos)
    b = eng.submit([3, 7], max_new_tokens=8)
    outs = eng.drain()
    np.testing.assert_array_equal(outs[a], free[:cut + 1])
    np.testing.assert_array_equal(outs[b], free)
    assert eng.request(a).done_reason == "eos"
    assert eng.request(b).done_reason == "length"

    # stop token ids: an extra per-request eos set, reason 'stop'
    st = int(free[2])
    c = eng.submit([3, 7], max_new_tokens=8,
                   sampling=SamplingParams(stop_token_ids=(st,)))
    outs = eng.drain()
    assert len(outs[c]) == 3 and outs[c][-1] == st
    assert eng.request(c).done_reason == "stop"

    # stop sequences: host-side tail match, token run included
    seq = tuple(int(t) for t in free[1:3])
    d = eng.submit([3, 7], max_new_tokens=8,
                   sampling=SamplingParams(stop_sequences=(seq,)))
    outs = eng.drain()
    assert tuple(outs[d][-2:]) == seq and len(outs[d]) == 3
    assert eng.request(d).done_reason == "stop"

    # min_tokens: the eos that would fire at step 4 is BANNED on device
    # (greedy takes the runner-up) until >= 6 tokens exist
    e = eng.submit([3, 7], max_new_tokens=8, eos_id=eos,
                   sampling=SamplingParams(min_tokens=6))
    outs = eng.drain()
    assert len(outs[e]) >= 6
    assert not np.any(np.asarray(outs[e][:5]) == eos)

    # generate() honors the same stop machinery
    g = generate(lm, [3, 7], length=8,
                 sampling=SamplingParams(stop_sequences=(seq,)))
    np.testing.assert_array_equal(g, outs[d])


# -- logprobs --------------------------------------------------------------

def test_chosen_token_logprobs_surface(lm):
    """The fused epilogue reports the chosen token's RAW model log-prob
    per step: engine.logprobs() matches generate(return_logprobs=True)
    for the same greedy request (same tokens, float-round-off close),
    one finite value per output token."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=3)
    rid = eng.submit([3, 7, 2], max_new_tokens=6)
    outs = eng.drain()
    lp = eng.logprobs(rid)
    ids, glp = generate(lm, [3, 7, 2], length=6, temperature=0.0,
                        return_logprobs=True)
    np.testing.assert_array_equal(outs[rid], ids)
    assert lp.shape == (6,) and np.isfinite(lp).all()
    assert (lp <= 0).all()                     # log-probs
    np.testing.assert_allclose(lp, glp, atol=1e-5)
    assert eng.logprobs(12345) is None
    # the Request record carries them too
    assert len(eng.request(rid).logprobs) == 6


# -- metrics ---------------------------------------------------------------

def test_sampling_metrics_counters(lm):
    """serving/rows_sampled vs rows_greedy per step, derived
    sampled_row_frac, and per-request mean_logprob land in summary()."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    eng = ServingEngine(lm, n_slots=2)
    eng.submit([3, 7], max_new_tokens=4)
    eng.submit([5, 1], max_new_tokens=4,
               sampling=SamplingParams(temperature=1.0, seed=3))
    eng.drain()
    s = eng.metrics.summary()
    assert s["serving/sampled_row_frac"] == pytest.approx(0.5)
    total_s, _ = eng.metrics.metrics.get("serving/rows_sampled")
    total_g, _ = eng.metrics.metrics.get("serving/rows_greedy")
    assert total_s == 4 and total_g == 4
    assert np.isfinite(s["serving/mean_logprob"])
    _, n_fin = eng.metrics.metrics.get("serving/mean_logprob")
    assert n_fin == 2                          # one per finished request


# -- bench registration smoke (tier-1, small/CPU) --------------------------

def test_sampling_bench_smoke():
    """benchmarks/serving_bench.py --scenario sampling runs end-to-end
    on a tiny CPU config and pins the subsystem's two hard claims:
    zero extra decode compiles for mixed knobs, and greedy rows
    unperturbed by sampled neighbors."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmarks"))
    try:
        import serving_bench
    finally:
        sys.path.pop(0)

    out = serving_bench.run_sampling(model="tiny", n_requests=8,
                                     gen_tokens=12, n_slots=4)
    assert out["extra_decode_compiles"] == 0, out
    assert out["greedy_rows_match"] is True, out
    assert out["mixed"]["decode_programs"] == 1
    assert out["greedy"]["tokens_per_sec"] > 0
    assert out["mixed"]["tokens_per_sec"] > 0
    assert out["sampled_row_frac"] == pytest.approx(0.5)
