"""Extended layer set vs torch oracles / closed forms (SURVEY.md §2.2)."""

import numpy as np
import pytest

from tests.oracle import assert_close


def test_cosine_layer(rng):
    from bigdl_tpu.nn import Cosine

    m = Cosine(6, 4)
    m._ensure_params()
    x = rng.randn(3, 6).astype(np.float32)
    out = np.asarray(m.forward(x))
    w = np.asarray(m.params["weight"])
    want = (x / np.linalg.norm(x, axis=1, keepdims=True)) @ (
        w / np.linalg.norm(w, axis=1, keepdims=True)).T
    assert_close(out, want, atol=1e-5)


def test_euclidean_layer(rng):
    from bigdl_tpu.nn import Euclidean

    m = Euclidean(5, 3)
    m._ensure_params()
    x = rng.randn(4, 5).astype(np.float32)
    out = np.asarray(m.forward(x))
    w = np.asarray(m.params["weight"])
    want = np.linalg.norm(x[:, None, :] - w[None], axis=-1)
    assert_close(out, want, atol=1e-5)


def test_dot_pairwise_cosinedistance_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import CosineDistance, DotProduct, PairwiseDistance

    x = rng.randn(4, 7).astype(np.float32)
    y = rng.randn(4, 7).astype(np.float32)
    tx, ty = torch.from_numpy(x), torch.from_numpy(y)

    assert_close(np.asarray(DotProduct().forward([x, y])),
                 (x * y).sum(-1), atol=1e-5)
    assert_close(np.asarray(PairwiseDistance(2).forward([x, y])),
                 torch.nn.PairwiseDistance(p=2, eps=0)(tx, ty).numpy(),
                 atol=1e-4)
    assert_close(np.asarray(CosineDistance().forward([x, y])),
                 torch.nn.CosineSimilarity(dim=1)(tx, ty).numpy(), atol=1e-4)


def test_softmin_logsigmoid_threshold_rrelu_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import LogSigmoid, RReLU, SoftMin, Threshold

    x = rng.randn(3, 6).astype(np.float32)
    tx = torch.from_numpy(x)
    assert_close(np.asarray(SoftMin().forward(x)),
                 torch.nn.Softmin(dim=1)(tx).numpy(), atol=1e-5)
    assert_close(np.asarray(LogSigmoid().forward(x)),
                 torch.nn.LogSigmoid()(tx).numpy(), atol=1e-5)
    assert_close(np.asarray(Threshold(0.2, -1.0).forward(x)),
                 torch.nn.Threshold(0.2, -1.0)(tx).numpy(), atol=1e-6)
    r = RReLU().evaluate()
    assert_close(np.asarray(r.forward(x)),
                 torch.nn.RReLU(1 / 8, 1 / 3)(tx.requires_grad_(False)).numpy()
                 if False else np.where(x >= 0, x, (1 / 8 + 1 / 3) / 2 * x),
                 atol=1e-6)


def test_replicate_index_masking(rng):
    from bigdl_tpu.nn import Index, Masking, Replicate

    x = rng.randn(2, 3).astype(np.float32)
    out = np.asarray(Replicate(4, 1).forward(x))
    assert out.shape == (2, 4, 3)
    assert_close(out[:, 0], x)

    idx = np.array([2, 1], np.float32)
    got = np.asarray(Index(1).forward([x, idx]))
    assert_close(got, x[[1, 0]])

    xm = x.copy()
    xm[1] = 0.0
    seq = np.stack([xm, xm])  # (2, 2, 3) second row all-zero
    masked = np.asarray(Masking(0.0).forward(seq))
    assert np.all(masked[:, 1] == 0)
    assert_close(masked[:, 0], seq[:, 0])


def test_table_utilities(rng):
    from bigdl_tpu.nn import NarrowTable, SelectTable

    a, b, c = (rng.randn(2, 2).astype(np.float32) for _ in range(3))
    assert_close(np.asarray(SelectTable(2).forward([a, b, c])), b)
    assert_close(np.asarray(SelectTable(-1).forward([a, b, c])), c)
    out = NarrowTable(2, 2).forward([a, b, c])
    assert len(out) == 2
    assert_close(np.asarray(out[0]), b)


def test_spatial_zero_padding_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialZeroPadding

    x = rng.randn(2, 3, 5, 5).astype(np.float32)
    got = np.asarray(SpatialZeroPadding(1, 2, 1, 0).forward(x))
    want = torch.nn.ZeroPad2d((1, 2, 1, 0))(torch.from_numpy(x)).numpy()
    assert_close(got, want)
    # negative = crop
    got = np.asarray(SpatialZeroPadding(-1, -1, -1, -1).forward(x))
    assert_close(got, x[:, :, 1:-1, 1:-1])


def test_scale_layer(rng):
    from bigdl_tpu.nn import Scale

    m = Scale((3,))
    m._ensure_params()
    x = rng.randn(2, 3, 4, 4).astype(np.float32)
    m.params = {"weight": np.full((3,), 2.0, np.float32),
                "bias": np.full((3,), 1.0, np.float32)}
    out = np.asarray(m.forward(x))
    assert_close(out, x * 2.0 + 1.0)


def test_gradient_reversal_and_l1penalty(rng):
    from bigdl_tpu.nn import GradientReversal, L1Penalty

    x = rng.randn(3, 4).astype(np.float32)
    g = rng.randn(3, 4).astype(np.float32)

    m = GradientReversal(0.5)
    m._ensure_params()
    assert_close(np.asarray(m.forward(x)), x)
    gin = np.asarray(m.backward(x, g))
    assert_close(gin, -0.5 * g, atol=1e-6)

    p = L1Penalty(0.1)
    p._ensure_params()
    assert_close(np.asarray(p.forward(x)), x)
    gin = np.asarray(p.backward(x, g))
    assert_close(gin, g + 0.1 * np.sign(x), atol=1e-6)


def test_gaussian_sampler(rng):
    from bigdl_tpu.nn import GaussianSampler

    mean = rng.randn(2000, 2).astype(np.float32)
    log_var = np.full((2000, 2), np.log(0.25), np.float32)
    m = GaussianSampler()
    m._ensure_params()
    m.training()
    out = np.asarray(m.forward([mean, log_var]))
    resid = out - mean
    assert abs(resid.std() - 0.5) < 0.05  # std = exp(log_var/2) = 0.5


def test_temporal_convolution_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import TemporalConvolution

    m = TemporalConvolution(5, 8, 3, 2)
    m._ensure_params()
    x = rng.randn(2, 9, 5).astype(np.float32)
    out = np.asarray(m.forward(x))

    conv = torch.nn.Conv1d(5, 8, 3, stride=2)
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(np.asarray(m.params["weight"])))
        conv.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    want = conv(torch.from_numpy(x).permute(0, 2, 1)).permute(0, 2, 1)
    assert_close(out, want.detach().numpy(), atol=1e-4)


def test_volumetric_conv_pool_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import (
        VolumetricAveragePooling, VolumetricConvolution, VolumetricMaxPooling,
    )

    m = VolumetricConvolution(2, 4, 3, 3, 3, 1, 1, 1, 1, 1, 1)
    m._ensure_params()
    x = rng.randn(2, 2, 6, 6, 6).astype(np.float32)
    out = np.asarray(m.forward(x))
    conv = torch.nn.Conv3d(2, 4, 3, stride=1, padding=1)
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(np.asarray(m.params["weight"])))
        conv.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    assert_close(out, conv(torch.from_numpy(x)).detach().numpy(), atol=1e-4)

    mp = np.asarray(VolumetricMaxPooling(2, 2, 2).forward(x))
    assert_close(mp, torch.nn.MaxPool3d(2)(torch.from_numpy(x)).numpy(),
                 atol=1e-6)
    ap = np.asarray(VolumetricAveragePooling(2, 2, 2).forward(x))
    assert_close(ap, torch.nn.AvgPool3d(2)(torch.from_numpy(x)).numpy(),
                 atol=1e-6)


def test_dilated_conv_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialDilatedConvolution

    m = SpatialDilatedConvolution(3, 5, 3, 3, 1, 1, 2, 2, 2, 2)
    m._ensure_params()
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    out = np.asarray(m.forward(x))
    conv = torch.nn.Conv2d(3, 5, 3, stride=1, padding=2, dilation=2)
    with torch.no_grad():
        conv.weight.copy_(torch.from_numpy(np.asarray(m.params["weight"])))
        conv.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    assert_close(out, conv(torch.from_numpy(x)).detach().numpy(), atol=1e-4)


def test_upsampling_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SpatialUpSamplingBilinear, SpatialUpSamplingNearest

    x = rng.randn(2, 3, 4, 4).astype(np.float32)
    got = np.asarray(SpatialUpSamplingNearest(2).forward(x))
    want = torch.nn.Upsample(scale_factor=2, mode="nearest")(
        torch.from_numpy(x)).numpy()
    assert_close(got, want, atol=1e-6)

    got = np.asarray(SpatialUpSamplingBilinear(2).forward(x))
    want = torch.nn.Upsample(scale_factor=2, mode="bilinear",
                             align_corners=True)(torch.from_numpy(x)).numpy()
    assert_close(got, want, atol=1e-4)


def test_negative(rng):
    from bigdl_tpu.nn import Negative

    x = rng.randn(2, 3).astype(np.float32)
    assert_close(np.asarray(Negative().forward(x)), -x)


def test_shrink_family_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import HardShrink, HardSigmoid, SoftShrink, TanhShrink

    x = rng.randn(3, 7).astype(np.float32)
    tx = torch.from_numpy(x)
    assert_close(np.asarray(TanhShrink().forward(x)),
                 torch.nn.Tanhshrink()(tx).numpy(), atol=1e-6)
    assert_close(np.asarray(SoftShrink(0.3).forward(x)),
                 torch.nn.Softshrink(0.3)(tx).numpy(), atol=1e-6)
    assert_close(np.asarray(HardShrink(0.3).forward(x)),
                 torch.nn.Hardshrink(0.3)(tx).numpy(), atol=1e-6)
    # keras hard_sigmoid: clip(0.2x+0.5, 0, 1)
    assert_close(np.asarray(HardSigmoid().forward(x)),
                 np.clip(0.2 * x + 0.5, 0, 1), atol=1e-6)


def test_gaussian_noise_dropout(rng):
    from bigdl_tpu.nn import GaussianDropout, GaussianNoise
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(9)
    x = np.ones((2000, 4), np.float32)
    gn = GaussianNoise(0.5)
    gn._ensure_params()
    gn.training()
    out = np.asarray(gn.forward(x))
    assert abs((out - x).std() - 0.5) < 0.05
    gn.evaluate()
    assert_close(np.asarray(gn.forward(x)), x)

    gd = GaussianDropout(0.2)
    gd._ensure_params()
    gd.training()
    out = np.asarray(gd.forward(x))
    assert abs(out.mean() - 1.0) < 0.05
    gd.evaluate()
    assert_close(np.asarray(gd.forward(x)), x)


def test_bilinear_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import Bilinear

    m = Bilinear(5, 6, 3)
    m._ensure_params()
    x1 = rng.randn(4, 5).astype(np.float32)
    x2 = rng.randn(4, 6).astype(np.float32)
    out = np.asarray(m.forward([x1, x2]))

    tb = torch.nn.Bilinear(5, 6, 3)
    with torch.no_grad():
        tb.weight.copy_(torch.from_numpy(np.asarray(m.params["weight"])))
        tb.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    want = tb(torch.from_numpy(x1), torch.from_numpy(x2)).detach().numpy()
    assert_close(out, want, atol=1e-4)
