"""DistriOptimizer over an 8-virtual-device CPU mesh — the analog of the
reference's `new SparkContext("local[N]")` distributed tests (SURVEY.md §4)."""

import numpy as np
import pytest

from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import DistributedDataSet
from bigdl_tpu.dataset.mnist import TRAIN_MEAN, TRAIN_STD, load_samples
from bigdl_tpu.dataset.image import GreyImgNormalizer
from bigdl_tpu.models import LeNet5
from bigdl_tpu.nn import ClassNLLCriterion, Linear, MSECriterion, Sequential
from bigdl_tpu.optim import Adam, Optimizer, SGD, Top1Accuracy, Trigger
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from tests.oracle import assert_close

pytestmark = pytest.mark.integration


def _dist_mnist(n, batch):
    samples = load_samples("/nonexistent", "train", synthetic_count=n)
    ds = DistributedDataSet(samples)
    return (
        ds.transform(GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD))
        .transform(SampleToMiniBatch(batch))
    )


def test_factory_dispatches_distri():
    ds = _dist_mnist(64, 32)
    opt = Optimizer(model=LeNet5(10), dataset=ds, criterion=ClassNLLCriterion())
    assert isinstance(opt, DistriOptimizer)


@pytest.mark.parametrize("mode", ["allreduce", "partitioned"])
def test_distri_matches_local_one_step(mode):
    """One DP step over 8 shards must equal one local step on the full batch
    (same model, same global batch, SGD no momentum) — the parity contract
    of the partitioned-optimizer design (SURVEY.md §7)."""
    import jax

    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    y = rs.randn(16, 3).astype(np.float32)

    def fresh_model():
        from bigdl_tpu.utils.random_gen import RNG

        RNG.set_seed(5)
        m = Sequential().add(Linear(6, 12)).add(Linear(12, 3))
        m._ensure_params()
        return m

    # local reference step
    from bigdl_tpu.optim.train_step import make_train_step

    m1 = fresh_model()
    step = jax.jit(make_train_step(m1, MSECriterion(), SGD(learning_rate=0.1)))
    p1, _, _, loss1 = step(
        m1.params, SGD(learning_rate=0.1).init_state(m1.params), m1.state,
        jax.random.PRNGKey(0), x, y,
    )

    # distributed step via DistriOptimizer internals
    from bigdl_tpu.dataset.sample import MiniBatch, Sample

    samples = [Sample(x[i], y[i]) for i in range(16)]
    ds = DistributedDataSet(samples).transform(SampleToMiniBatch(16))
    m2 = fresh_model()
    dopt = DistriOptimizer(
        model=m2, dataset=ds, criterion=MSECriterion(), parameter_mode=mode
    )
    dopt.set_optim_method(SGD(learning_rate=0.1)).set_end_when(
        Trigger.max_iteration(1)
    )
    dopt.optimize()

    w1 = jax.tree_util.tree_leaves(p1)
    w2 = jax.tree_util.tree_leaves(m2.params)
    for a, b in zip(w1, w2):
        assert_close(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.parametrize("mode", ["partitioned", "allreduce"])
def test_distri_end_to_end_lenet(mode, tmp_path):
    ds = _dist_mnist(512, 64)
    model = LeNet5(10)
    opt = DistriOptimizer(
        model=model, dataset=ds, criterion=ClassNLLCriterion(),
        parameter_mode=mode,
    )
    opt.set_optim_method(Adam(1e-3)).set_end_when(Trigger.max_epoch(2))
    opt.set_checkpoint(str(tmp_path / "ck"), Trigger.every_epoch())
    trained = opt.optimize()

    val = load_samples("/nonexistent", "val", synthetic_count=256)
    correct = total = 0
    norm = GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD)
    batches = SampleToMiniBatch(64)(norm(iter(val)))
    for b in batches:
        out = trained.predict(b.get_input())
        r = Top1Accuracy().apply(out, b.get_target())
        correct += r.correct
        total += r.count
    assert correct / total > 0.4, f"acc {correct/total}"
    assert (tmp_path / "ck" / "model").exists()


def test_distri_bf16_compressed_gradients():
    """bf16 gradient exchange (FP16CompressedTensor analog) still trains."""
    ds = _dist_mnist(256, 32)
    model = LeNet5(10)
    opt = DistriOptimizer(
        model=model, dataset=ds, criterion=ClassNLLCriterion(),
        parameter_mode="partitioned", compress="bf16",
    )
    opt.set_optim_method(Adam(1e-3)).set_end_when(Trigger.max_iteration(5))
    trained = opt.optimize()
    assert trained is model


def test_batch_not_divisible_raises():
    ds = _dist_mnist(64, 12)  # 12 % 8 != 0
    opt = DistriOptimizer(model=LeNet5(10), dataset=ds,
                          criterion=ClassNLLCriterion())
    opt.set_end_when(Trigger.max_iteration(1))
    opt.retry_times = 1
    with pytest.raises(ValueError, match="divide"):
        opt.optimize()


def test_distri_mixed_precision_partitioned():
    """bf16 compute + partitioned-DP on the 8-device mesh: trains, fp32
    master shards preserved."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    rng = np.random.RandomState(1)
    samples = [Sample((rng.randn(6) * 0.3 + np.eye(3)[i % 3].repeat(2) * 2
                       ).astype(np.float32), np.int32(i % 3 + 1))
               for i in range(64)]
    m = (Sequential().add(Linear(6, 16)).add(ReLU())
         .add(Linear(16, 3)).add(LogSoftMax()))
    opt = Optimizer(model=m, dataset=DataSet.distributed(samples),
                    criterion=ClassNLLCriterion(), batch_size=32,
                    parameter_mode="partitioned", compress="bf16", mesh=mesh)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(Trigger.max_iteration(20))
    opt.set_compute_dtype("bf16")
    trained = opt.optimize()
    ws, _ = trained.parameters()
    assert all(np.asarray(w).dtype == np.float32 for w in ws)
    xs = np.stack([np.asarray(s.features[0]) for s in samples])
    ys = np.asarray([int(np.asarray(s.labels[0])) for s in samples])
    acc = (np.asarray(trained.evaluate().forward(xs)).argmax(-1) + 1 == ys).mean()
    assert acc > 0.8, f"distri bf16 training failed, acc={acc}"


@pytest.mark.parametrize("mode", ["partitioned", "allreduce"])
def test_validation_runs_sharded_on_mesh(mode):
    """In-training validation must execute SHARDED over the data axis —
    not gathered to one device (round-1 verdict weak #4; reference
    ``Evaluator.scala`` distributed eval, SURVEY §3.3). Asserts the eval
    output's device placement spans all 8 chips, and that validation
    still feeds scores/triggers correctly with a ragged final batch."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    ds = _dist_mnist(128, 32)
    model = LeNet5(10)
    opt = DistriOptimizer(
        model=model, dataset=ds, criterion=ClassNLLCriterion(),
        parameter_mode=mode,
    )
    # ragged validation set: 52 rows don't divide 8 -> exercises pad/trim
    val = load_samples("/nonexistent", "val", synthetic_count=52)
    from bigdl_tpu.dataset.dataset import DistributedDataSet as DDS

    vds = (DDS(val)
           .transform(GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD))
           .transform(SampleToMiniBatch(52)))
    opt.set_optim_method(Adam(1e-3)).set_end_when(Trigger.max_iteration(2))
    opt.set_validation(Trigger.several_iteration(1), vds, [Top1Accuracy()])
    opt.optimize()

    # the compiled eval step exists and places its output across the mesh
    assert hasattr(opt, "_dist_eval_step")
    x = np.zeros((8, 1, 28, 28), np.float32)
    params = opt._host_params_to_device(model.params) if mode == "partitioned" \
        else model.params
    out = opt._eval_forward(params, model.state, x)
    assert isinstance(out.sharding, NamedSharding)
    assert out.sharding.spec == P("data")
    assert len(out.sharding.device_set) == 8


@pytest.mark.parametrize("mode", ["partitioned", "allreduce"])
def test_sharded_validation_applies_device_preprocess(mode):
    """The sharded eval paths (spmd closure + make_sharded_eval_step) must
    run ``set_device_preprocess`` on the raw batch exactly like the train
    step does — a u8-NHWC pipeline that trains normalized must not
    validate on raw uint8 (round-4 ADVICE medium,
    ``distri_optimizer._eval_forward``)."""
    import jax

    rs = np.random.RandomState(3)
    raw_u8 = rs.randint(0, 256, size=(16, 1, 28, 28)).astype(np.uint8)

    def preprocess(x):
        return (x.astype(np.float32) / 255.0 - TRAIN_MEAN) / TRAIN_STD

    from bigdl_tpu.dataset.sample import Sample

    samples = [Sample(raw_u8[i], np.float32((i % 10) + 1))
               for i in range(16)]
    model = LeNet5(10)
    ds = DistributedDataSet(samples).transform(SampleToMiniBatch(16))
    opt = DistriOptimizer(
        model=model, dataset=ds, criterion=ClassNLLCriterion(),
        parameter_mode=mode,
    )
    opt.set_device_preprocess(preprocess)
    vds = DistributedDataSet(samples).transform(SampleToMiniBatch(16))
    opt.set_optim_method(SGD(learning_rate=1e-3)).set_end_when(
        Trigger.max_iteration(1))
    opt.set_validation(Trigger.several_iteration(1), vds, [Top1Accuracy()])
    opt.optimize()  # in-training validation itself exercises the path

    params = opt._host_params_to_device(model.params) \
        if mode == "partitioned" else model.params
    out = np.asarray(opt._eval_forward(params, model.state, raw_u8))
    ref, _ = model.apply(model.params, preprocess(raw_u8), model.state,
                         training=False, rng=None)
    assert_close(out, np.asarray(ref), atol=1e-5)


def test_pod_set_validation_pyspark_order():
    """Pod-mode set_validation must survive the pyspark positional order
    (batch_size, val_rdd, trigger, val_method) — round-2 review finding:
    the _result_cls pre-check ran before the int-first swap."""
    from unittest import mock

    from bigdl_tpu.dataset.sample import Sample

    rs = np.random.RandomState(0)
    samples = [Sample(rs.rand(1, 28, 28).astype(np.float32), np.float32(1))
               for _ in range(8)]

    with mock.patch("jax.process_count", return_value=2):
        opt = DistriOptimizer(model=LeNet5(10),
                              dataset=DistributedDataSet(samples),
                              criterion=ClassNLLCriterion(), batch_size=4)
        opt.set_validation(256, DistributedDataSet(samples),
                           Trigger.every_epoch(), [Top1Accuracy()])
        # global 256 / 2 processes -> local batches of 128
        probe = next(iter(opt.validation_dataset.data(train=False)))
        assert probe.size() <= 128

        with pytest.raises(ValueError, match="divide"):
            opt.set_validation(255, DistributedDataSet(samples),
                               Trigger.every_epoch(), [Top1Accuracy()])

        class NoCls(Top1Accuracy):
            _result_cls = None

        with pytest.raises(ValueError, match="_result_cls"):
            opt.set_validation(256, DistributedDataSet(samples),
                               Trigger.every_epoch(), [NoCls()])


def test_allreduce_construction_single_collective_on_wire():
    """The allreduce-mode spmd construction (mark params VARYING with
    pvary/pcast, then one explicit pmean — distri_optimizer.py:286-295)
    must compile to exactly ONE all-reduce carrying the gradient bytes.
    Without the varying mark, jax auto-psums the cotangent of the
    replicated input AND the user pmean reduces again — 2x wire traffic
    with sum-not-mean semantics. This pins the jax behavior the hot path
    depends on (verified by HLO extraction; also the cross-check inside
    benchmarks/pod_projection.py)."""
    import re

    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from bigdl_tpu.utils.compat import (
        device_varying_marker, shard_map, varying_marker_kind)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    if varying_marker_kind() == "identity":
        # NOTE: on such a jax the varying-mark construction (and the
        # distri_optimizer hot path that uses it) cannot be BUILT at all,
        # so there is no behavior to pin here — the skip loses coverage
        # only on toolchains where the feature itself is absent
        pytest.skip("this jax predates lax.pcast/lax.pvary — the "
                    "varying-mark construction under test cannot be built")
    mark = device_varying_marker("data")

    def make(marked):
        def f(x, w):
            wv = mark(w) if marked else w
            loss, g = jax.value_and_grad(
                lambda w_: jnp.mean(jnp.dot(x, w_) ** 2))(wv)
            return lax.pmean(g, "data"), lax.pmean(loss, "data")

        return jax.jit(shard_map(
            f, mesh=mesh, in_specs=(P("data"), P()), out_specs=(P(), P())))

    x = np.ones((8, 16), np.float32) * 0.25
    w = np.linspace(-1, 1, 64).astype(np.float32).reshape(16, 4)

    def allreduce_f32_bytes(fn):
        hlo = fn.lower(x, w).compile().as_text()
        total = 0
        for line in hlo.splitlines():
            if "all-reduce(" not in line or "=" not in line:
                continue
            sig = line.split("=", 1)[1].split("all-reduce(", 1)[0]
            for dt, dims in re.findall(r"(\w+)\[([0-9,]*)\]", sig):
                if dt == "f32":
                    k = 1
                    for d in dims.split(","):
                        if d:
                            k *= int(d)
                    total += 4 * k
        return total

    # RELATIONAL assertions, not exact byte pins: XLA formatting/combining
    # changes (tupled all-reduces, loss folded into the grad reduce) can
    # shift the textual accounting by a few bytes without any behavioral
    # regression. What the hot path depends on is only that the marked
    # construction reduces the gradient ONCE and the unmarked one pays
    # for it twice (auto-psum'd cotangent + explicit pmean).
    grad_bytes = 64 * 4
    marked = allreduce_f32_bytes(make(True))
    unmarked = allreduce_f32_bytes(make(False))
    assert marked < unmarked, (marked, unmarked)
    # marked: at least the gradient, and strictly less than two of them
    assert grad_bytes <= marked < 2 * grad_bytes, (marked, grad_bytes)
    # unmarked: the gradient goes over the wire (at least) twice
    assert unmarked >= 2 * grad_bytes, (unmarked, grad_bytes)
