"""TransformerLM family: shape/learning/remat/sequence-parallel behavior."""

import numpy as np
import pytest

from bigdl_tpu.utils.compat import shard_map
from tests.oracle import assert_close


def test_transformer_lm_shapes_and_causality(rng):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(1)
    m = TransformerLM(vocab_size=20, hidden_size=32, n_heads=4, n_layers=2,
                      max_len=16)
    m._ensure_params()
    m.evaluate()
    ids = (rng.randint(1, 21, size=(2, 10))).astype(np.float32)
    out = np.asarray(m.forward(ids))
    assert out.shape == (2, 10, 20)
    # causality: changing a future token must not change earlier outputs
    ids2 = ids.copy()
    ids2[:, -1] = 1 + (ids2[:, -1] % 20)
    out2 = np.asarray(m.forward(ids2))
    assert_close(out[:, :-1], out2[:, :-1], atol=1e-4)
    assert np.abs(out[:, -1] - out2[:, -1]).max() > 1e-6


@pytest.mark.integration
def test_transformer_remat_identical(rng):
    """Remat(block) computes EXACTLY what the bare block computes (forward
    and gradient) — verified by sharing one block's params across both."""
    import jax

    from bigdl_tpu.models.transformer import TransformerBlock
    from bigdl_tpu.nn import Remat
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(2)
    block = TransformerBlock(32, 4)
    block._ensure_params()
    x = rng.randn(2, 8, 32).astype(np.float32)
    a = np.asarray(block.forward(x))

    rem = Remat(block)
    rem.params = {rem._child_key(0): block.params}
    rem.state = {rem._child_key(0): {}}
    rem._ensure_params()
    rem.evaluate()
    block.evaluate()
    b = np.asarray(rem.forward(x))
    assert_close(a, b, atol=1e-6)

    ga = jax.grad(lambda p: (block.apply(p, x, {})[0] ** 2).sum())(block.params)
    gb = jax.grad(lambda p: (rem.apply(p, x, {})[0] ** 2).sum())(rem.params)
    for u, v in zip(jax.tree_util.tree_leaves(ga),
                    jax.tree_util.tree_leaves(gb)):
        assert_close(np.asarray(u), np.asarray(v), atol=1e-5)


def test_transformer_train_main():
    from bigdl_tpu.models import transformer

    model = transformer.train_main([
        "-b", "8", "--maxIteration", "12", "--synthetic", "64",
        "--seqLen", "12", "--vocab", "30", "--hidden", "32",
        "--layers", "1", "--heads", "2",
    ])
    ws, _ = model.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_transformer_ring_sequence_parallel(rng):
    """The same LM with ring SP over an 8-way mesh matches the local LM."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(3)
    local = TransformerLM(16, hidden_size=16, n_heads=2, n_layers=1,
                          max_len=16, causal=True)
    local._ensure_params()
    local.evaluate()
    RNG.set_seed(3)
    sp = TransformerLM(16, hidden_size=16, n_heads=2, n_layers=1,
                       max_len=16, causal=True,
                       sequence_parallel="ring", sp_axis="seq")
    sp._ensure_params()
    sp.evaluate()

    ids = (rng.randint(1, 17, size=(2, 16))).astype(np.float32)
    # share weights so the SP model is the SAME function as the local one;
    # child keys embed instance counters, so graft by tree structure
    # (index-prefixed keys sort identically in both models)
    sp.params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(sp.params),
        jax.tree_util.tree_leaves(local.params))
    want = np.asarray(local.forward(ids))

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("seq",))
    # sequence-sharded ids; PositionEmbedding(sp_axis="seq") offsets by
    # axis_index so positions stay global, matching ring causal offsets
    fn = jax.jit(shard_map(
        lambda p, x: sp.apply(p, x, sp.state, training=False)[0],
        mesh=mesh, in_specs=(P(), P(None, "seq")), out_specs=P(None, "seq"),
    ))
    out = np.asarray(fn(sp.params, ids))
    assert_close(out, want, atol=1e-3)


@pytest.mark.parametrize("layer_scan", [False, True])
def test_transformer_serialization_roundtrip(rng, tmp_path, layer_scan):
    """Unrolled AND ScanBlocks (stacked per-layer params) stacks survive
    the structured serializer — the Container protocol carries the
    stacked tree like any other child dict."""
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.nn.module import AbstractModule
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(4)
    m = TransformerLM(12, hidden_size=16, n_heads=2, n_layers=3, max_len=8,
                      layer_scan=layer_scan)
    m._ensure_params()
    m.evaluate()
    ids = (rng.randint(1, 13, size=(2, 8))).astype(np.float32)
    want = np.asarray(m.forward(ids))
    path = str(tmp_path / "lm.bigdl")
    m.save_module(path)
    m2 = AbstractModule.load_module(path)
    m2.evaluate()
    assert_close(np.asarray(m2.forward(ids)), want, atol=1e-6)


@pytest.mark.integration
def test_transformer_lm_remat_wiring(rng):
    """TransformerLM(remat=True): the Sequential/Remat key plumbing trains."""
    import jax

    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(6)
    m = TransformerLM(16, hidden_size=16, n_heads=2, n_layers=2, max_len=8,
                      remat=True)
    m._ensure_params()
    ids = (rng.randint(1, 17, size=(2, 8))).astype(np.float32)
    out = np.asarray(m.forward(ids))
    assert out.shape == (2, 8, 16) and np.all(np.isfinite(out))

    g = jax.grad(lambda p: (m.apply(p, ids, m.state, training=True,
                                    rng=jax.random.PRNGKey(0))[0] ** 2).sum())(
        m.params)
    leaves = jax.tree_util.tree_leaves(g)
    assert leaves and all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    assert any(np.abs(np.asarray(l)).sum() > 0 for l in leaves)


def test_kv_cached_decode_matches_full_forward(rng):
    """Cached single-token decoding must reproduce the full-forward
    log-probs at every position (exact KV-cache correctness)."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import TransformerLM, make_decode_step

    V, T = 23, 10
    model = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=2, max_len=T)
    model._ensure_params()
    model.evaluate()

    ids = rng.randint(1, V + 1, size=(1, T)).astype(np.float32)
    full = np.asarray(model.forward(ids))        # (1, T, V)

    step, init_carry = make_decode_step(model)
    carry = init_carry(1)
    for t in range(T):
        tok = jnp.asarray([int(ids[0, t]) - 1], jnp.int32)
        logp, carry = step(None, tok, carry)
        assert_close(np.asarray(logp)[0], full[0, t], atol=2e-4,
                     msg=f"position {t}")


@pytest.mark.integration
def test_kv_cached_decode_with_remat_blocks(rng):
    from bigdl_tpu.models.transformer import TransformerLM, make_decode_step

    V, T = 11, 6
    model = TransformerLM(V, hidden_size=16, n_heads=2, n_layers=2,
                          max_len=T, remat=True)
    model._ensure_params()
    model.evaluate()
    ids = rng.randint(1, V + 1, size=(1, T)).astype(np.float32)
    full = np.asarray(model.forward(ids))
    step, init_carry = make_decode_step(model)
    carry = init_carry(1)
    import jax.numpy as jnp
    for t in range(T):
        logp, carry = step(None, jnp.asarray([int(ids[0, t]) - 1]), carry)
    assert_close(np.asarray(logp)[0], full[0, -1], atol=2e-4)


def test_beam_generate_transformer(rng):
    from bigdl_tpu.models.transformer import TransformerLM, beam_generate

    V = 17
    model = TransformerLM(V, hidden_size=16, n_heads=2, n_layers=1,
                          max_len=24)
    model._ensure_params()
    model.evaluate()
    seqs, scores = beam_generate(model, [3, 7, 2], beam_size=3,
                                 decode_length=5)
    assert seqs.shape == (3, 5)
    assert ((seqs >= 1) & (seqs <= V)).all()
    assert np.isfinite(scores).all()
    # best-first ordering
    assert scores[0] >= scores[1] >= scores[2]


def test_generate_greedy_and_sampled(rng):
    from bigdl_tpu.models.transformer import TransformerLM, generate

    V = 13
    model = TransformerLM(V, hidden_size=16, n_heads=2, n_layers=1,
                          max_len=20)
    model._ensure_params()
    model.evaluate()
    g1 = generate(model, [2, 5], length=6, temperature=0.0)
    g2 = generate(model, [2, 5], length=6, temperature=0.0)
    assert (g1 == g2).all()                    # greedy is deterministic
    assert ((g1 >= 1) & (g1 <= V)).all()
    s1 = generate(model, [2, 5], length=6, temperature=1.0, top_k=4, seed=1)
    assert ((s1 >= 1) & (s1 <= V)).all()
    # greedy must follow the argmax of the cached log-probs step by step
    from bigdl_tpu.models.transformer import make_decode_step
    import jax.numpy as jnp
    step, init_carry = make_decode_step(model)
    carry = init_carry(1)
    _, carry = step(None, jnp.asarray([1]), carry)   # prompt token 2
    logp, _ = step(None, jnp.asarray([4]), carry)    # prompt token 5
    assert g1[0] == int(np.argmax(np.asarray(logp)[0])) + 1


def test_generate_rejects_overlong_decode(rng):
    """Regression: decoding past max_len must raise, not silently clamp."""
    from bigdl_tpu.models.transformer import (
        TransformerLM, beam_generate, generate,
    )

    model = TransformerLM(9, hidden_size=16, n_heads=2, n_layers=1, max_len=8)
    model._ensure_params()
    with pytest.raises(ValueError, match="max_len"):
        generate(model, [1, 2, 3], length=10)
    with pytest.raises(ValueError, match="max_len"):
        beam_generate(model, [1, 2], beam_size=2, decode_length=8)
    # exactly at the limit is fine
    out = generate(model, [1, 2, 3], length=6, temperature=0.0)
    assert out.shape == (6,)


def test_lookup_table_matmul_grad_matches_scatter(rng):
    """grad_via_matmul computes the embedding gradient as a one-hot MXU
    matmul — must match the scatter-add backward exactly (fp32)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.misc import LookupTable

    V, D = 13, 6
    ids = rng.randint(1, V + 1, size=(4, 5)).astype(np.float32)
    ids[0, 0] = 0.0   # padding id embeds to zero, must get zero grad
    w = rng.randn(V, D).astype(np.float32)

    def loss_for(flag):
        lt = LookupTable(V, D, grad_via_matmul=flag)

        def f(wv):
            out, _ = lt.apply({"weight": wv}, jnp.asarray(ids))
            return jnp.sum(out * out)

        return jax.grad(f)(jnp.asarray(w))

    g_scatter = np.asarray(loss_for(False))
    g_matmul = np.asarray(loss_for(True))
    np.testing.assert_allclose(g_matmul, g_scatter, rtol=1e-5, atol=1e-6)
    assert abs(g_matmul).sum() > 0


def test_transformer_lm_logits_output_trains_and_decodes(rng):
    """output="logits" + MaskedSoftmaxCECriterion is the fused LM-scale
    path: one train step moves the loss, and make_decode_step still
    resolves the head (no trailing LogSoftMax)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer import make_decode_step
    from bigdl_tpu.nn.criterion_more import MaskedSoftmaxCECriterion
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.train_step import make_train_step
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    V, T, B = 31, 8, 4
    lm = TransformerLM(V, hidden_size=16, n_heads=2, n_layers=2, max_len=T,
                       output="logits")
    crit = MaskedSoftmaxCECriterion(padding_value=0)
    optim = Adam(learning_rate=1e-2)
    lm._ensure_params()
    step = jax.jit(make_train_step(lm, crit, optim))
    x = jnp.asarray(rng.randint(1, V + 1, size=(B, T)).astype(np.int32))
    y = jnp.asarray(rng.randint(1, V + 1, size=(B, T)).astype(np.float32))
    params, ms = lm.params, lm.state
    opt_state = optim.init_state(params)
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(8):
        params, opt_state, ms, loss = step(params, opt_state, ms, key, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    lm.params = params
    dstep, init_carry = make_decode_step(lm)
    logp, carry = dstep(None, jnp.zeros((2,), jnp.int32), init_carry(2))
    assert logp.shape == (2, V)
    # decode head emits normalized log-probs even without the LM softmax
    np.testing.assert_allclose(np.exp(np.asarray(logp)).sum(-1), 1.0,
                               rtol=1e-4)


def test_layer_scan_matches_unrolled(rng):
    """layer_scan=True (ScanBlocks lax.scan over stacked params) computes
    EXACTLY the unrolled stack — verified by transplanting the unrolled
    model's block params into the stacked layout — and the KV-cached
    decode step resolves the scan model too."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer import ScanBlocks, make_decode_step
    from bigdl_tpu.utils.random_gen import RNG

    V, T, B, L = 23, 10, 2, 3
    RNG.set_seed(11)
    unrolled = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=L,
                             max_len=T)
    unrolled._ensure_params()
    RNG.set_seed(12)
    scan = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=L,
                         max_len=T, layer_scan=True)
    scan._ensure_params()
    sb = scan.modules[2]
    assert isinstance(sb, ScanBlocks)

    # transplant: unrolled blocks at Sequential indices 2..2+L; module
    # names carry a global counter so child keys must be remapped by
    # POSITION onto the scan template block's keys before stacking
    tmpl = sb.modules[0]

    def rekey(i):
        bp = unrolled.params[unrolled._child_key(2 + i)]
        blk = unrolled.modules[2 + i]
        return {tmpl._child_key(j): bp[blk._child_key(j)] for j in range(5)}

    per_layer = [rekey(i) for i in range(L)]
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_layer)
    new_p = dict(scan.params)
    new_p[scan._child_key(0)] = unrolled.params[unrolled._child_key(0)]
    new_p[scan._child_key(1)] = unrolled.params[unrolled._child_key(1)]
    new_p[scan._child_key(2)] = {sb._child_key(0): stacked}
    new_p[scan._child_key(3)] = unrolled.params[unrolled._child_key(2 + L)]
    new_p[scan._child_key(4)] = unrolled.params[unrolled._child_key(3 + L)]
    scan.params = new_p

    unrolled.evaluate()
    scan.evaluate()
    ids = rng.randint(1, V + 1, size=(B, T)).astype(np.float32)
    a, b = np.asarray(unrolled.forward(ids)), np.asarray(scan.forward(ids))
    assert_close(a, b, atol=1e-5)

    # gradients agree too (scan backward == unrolled backward)
    ga = jax.grad(lambda p: (unrolled.apply(p, ids, {})[0] ** 2).sum())(
        unrolled.params)
    gb = jax.grad(lambda p: (scan.apply(p, ids, {})[0] ** 2).sum())(
        scan.params)
    def rekey_grad(i):
        bp = ga[unrolled._child_key(2 + i)]
        blk = unrolled.modules[2 + i]
        return {tmpl._child_key(j): bp[blk._child_key(j)] for j in range(5)}

    ga_stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *[rekey_grad(i) for i in range(L)])
    for u, v in zip(
            jax.tree_util.tree_leaves(ga_stacked),
            jax.tree_util.tree_leaves(gb[scan._child_key(2)][sb._child_key(0)])):
        assert_close(np.asarray(u), np.asarray(v), atol=1e-4)

    # decode parity: the scan model's cached decode matches its forward
    dstep, init_carry = make_decode_step(scan)
    toks = rng.randint(1, V + 1, size=(1, 5)).astype(np.float32)
    full = np.asarray(scan.forward(toks))
    carry = init_carry(1)
    outs = []
    for t in range(5):
        logp, carry = dstep(None, jnp.asarray([int(toks[0, t]) - 1],
                                              jnp.int32), carry)
        outs.append(np.asarray(logp)[0])
    assert_close(np.stack(outs), full[0], atol=1e-4)


def test_layer_scan_with_remat(rng):
    """ScanBlocks composes with Remat (checkpoint-inside-scan — the
    long-context memory recipe): forward matches the bare scan model."""
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    V, T = 17, 8
    RNG.set_seed(21)
    plain = TransformerLM(V, hidden_size=16, n_heads=2, n_layers=2,
                          max_len=T, layer_scan=True)
    plain._ensure_params()
    RNG.set_seed(21)
    remat = TransformerLM(V, hidden_size=16, n_heads=2, n_layers=2,
                          max_len=T, layer_scan=True, remat=True)
    remat._ensure_params()
    ids = rng.randint(1, V + 1, size=(2, T)).astype(np.float32)
    plain.evaluate()
    remat.evaluate()
    a = np.asarray(plain.forward(ids))
    b = np.asarray(remat.forward(ids))
    # same seed, but the Remat wrapper adds a child-key level; compare
    # only shapes/finiteness here — exact parity is the unrolled test's job
    assert a.shape == b.shape and np.isfinite(b).all()


def test_flash_block_knob_validates_and_matches(rng):
    """flash_block must reject non-128-multiples and, when valid, compute
    the same attention as the dense path (interpret-mode Pallas on CPU)."""
    import pytest as _pytest

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.nn.attention import MultiHeadAttention
    from bigdl_tpu.utils.random_gen import RNG

    with _pytest.raises(ValueError, match="multiple of 128"):
        MultiHeadAttention(32, 4, flash_block=100)

    V, T = 19, 128
    RNG.set_seed(31)
    flash = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=1,
                          max_len=T, use_flash="always", flash_block=128)
    flash._ensure_params()
    RNG.set_seed(31)
    dense = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=1,
                          max_len=T, use_flash="never")
    dense._ensure_params()
    ids = rng.randint(1, V + 1, size=(1, T)).astype(np.float32)
    flash.evaluate()
    dense.evaluate()
    a = np.asarray(flash.forward(ids))
    b = np.asarray(dense.forward(ids))
    assert_close(a, b, atol=2e-3)


def test_decode_step_bf16_and_weight_only_int8(rng):
    """Serving paths of make_decode_step: compute_dtype=bf16 tracks the
    fp32 decode closely, and a weight_only-quantized LM decodes through
    the same step (int8 dequant projections) matching ITS full forward."""
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer import make_decode_step
    from bigdl_tpu.nn.quantized import Quantizer
    from bigdl_tpu.utils.random_gen import RNG

    V, T = 27, 12
    RNG.set_seed(41)
    lm = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=2, max_len=T)
    lm._ensure_params()
    lm.evaluate()
    toks = rng.randint(1, V + 1, size=(1, 6)).astype(np.float32)

    # bf16 serving dtype ~ fp32 decode
    d32, ic32 = make_decode_step(lm)
    dbf, icbf = make_decode_step(lm, compute_dtype=jnp.bfloat16)
    c32, cbf = ic32(1), icbf(1)
    assert cbf["k0"].dtype == jnp.bfloat16
    for t in range(6):
        tok = jnp.asarray([int(toks[0, t]) - 1], jnp.int32)
        l32, c32 = d32(None, tok, c32)
        lbf, cbf = dbf(None, tok, cbf)
    assert_close(np.asarray(l32), np.asarray(lbf), atol=0.15)
    # ranking preserved at bf16 for the top token
    assert np.asarray(l32).argmax() == np.asarray(lbf).argmax()

    # weight-only int8: decode matches the quantized model's own forward
    qlm = Quantizer.quantize(lm, scheme="weight_only")
    full = np.asarray(qlm.forward(toks))
    dq, icq = make_decode_step(qlm)
    cq = icq(1)
    outs = []
    for t in range(6):
        logp, cq = dq(None, jnp.asarray([int(toks[0, t]) - 1], jnp.int32),
                      cq)
        outs.append(np.asarray(logp)[0])
    # the quantized forward emits logprobs through LogSoftMax
    assert_close(np.stack(outs), full[0], atol=2e-3)


def test_decode_step_runtime_params_match_captured(rng):
    """step(params, ...) with the serving-params tree must equal
    step(None, ...) (captured constants) — the runtime-argument mode is
    how serving avoids baking weights into the compiled program."""
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer import make_decode_step, serving_params
    from bigdl_tpu.utils.random_gen import RNG

    V, T = 21, 10
    RNG.set_seed(51)
    lm = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=2, max_len=T)
    lm._ensure_params()
    step, init_carry = make_decode_step(lm, compute_dtype=jnp.bfloat16)
    P = serving_params(lm, jnp.bfloat16)
    c_none, c_p = init_carry(1), init_carry(1)
    toks = rng.randint(1, V + 1, size=(5,))
    for t in toks:
        tok = jnp.asarray([int(t) - 1], jnp.int32)
        l_none, c_none = step(None, tok, c_none)
        l_p, c_p = step(P, tok, c_p)
    np.testing.assert_array_equal(np.asarray(l_none), np.asarray(l_p))


def test_prefill_matches_sequential_decode(rng):
    """make_prefill_step must leave the carry EXACTLY where P sequential
    decode steps leave it (same K/V, same pos, same last-token logprobs)
    — for plain, bf16-serving, and weight-only-int8 models."""
    import jax.numpy as jnp

    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.models.transformer import (
        make_decode_step, make_prefill_step, serving_params,
    )
    from bigdl_tpu.nn.quantized import Quantizer
    from bigdl_tpu.utils.random_gen import RNG

    V, T, P, B = 19, 16, 7, 2
    RNG.set_seed(71)
    lm = TransformerLM(V, hidden_size=32, n_heads=4, n_layers=2, max_len=T)
    lm._ensure_params()
    lm.evaluate()
    cases = [(lm, None, 1e-5), (lm, jnp.bfloat16, 0.1),
             (Quantizer.quantize(lm, scheme="weight_only"), None, 1e-5)]
    toks = rng.randint(0, V, size=(B, P)).astype(np.int32)
    for model, dtype, atol in cases:
        step, init_carry = make_decode_step(model, compute_dtype=dtype)
        prefill = make_prefill_step(model, compute_dtype=dtype)
        Pp = serving_params(model, dtype)

        c_seq = init_carry(B)
        for t in range(P):
            l_seq, c_seq = step(Pp, jnp.asarray(toks[:, t]), c_seq)
        l_pre, c_pre = prefill(Pp, jnp.asarray(toks), init_carry(B))

        np.testing.assert_array_equal(np.asarray(c_pre["pos"]),
                                      np.asarray(c_seq["pos"]))
        for key in c_seq:
            if key == "pos":
                continue
            assert_close(np.asarray(c_pre[key], np.float32),
                         np.asarray(c_seq[key], np.float32), atol=atol,
                         msg=f"{key} dtype={dtype}")
        assert_close(np.asarray(l_pre), np.asarray(l_seq), atol=max(atol, 1e-4))
        # and decoding CONTINUES identically from the prefilled carry
        nxt = jnp.asarray(toks[:, 0])
        l1, _ = step(Pp, nxt, c_pre)
        l2, _ = step(Pp, nxt, c_seq)
        assert_close(np.asarray(l1), np.asarray(l2), atol=max(atol, 1e-4))
