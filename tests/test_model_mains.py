"""Model-zoo Train/Test CLI mains (SURVEY.md §2.8: builder + Train/Test
mains with option parsers) — smoke-trained on tiny synthetic data."""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.integration  # SURVEY §4 tag-split: heavy suite


def test_lenet_train_and_test_main(tmp_path):
    from bigdl_tpu.models import lenet

    model = lenet.train_main([
        "-b", "32", "--maxIteration", "3", "--synthetic", "64",
        "--cache", str(tmp_path / "ck"), "--overWrite",
    ])
    assert model is not None
    assert os.path.exists(str(tmp_path / "ck" / "model"))

    results = lenet.test_main([
        "--model", str(tmp_path / "ck" / "model"),
        "-b", "32", "--synthetic", "64",
    ])
    acc, total = results[0].result()
    assert total == 64


def test_vgg_train_main():
    from bigdl_tpu.models import vgg

    model = vgg.train_main(["-b", "16", "--maxIteration", "2",
                            "--synthetic", "32"])
    ws, _ = model.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_resnet_cifar_train_main():
    from bigdl_tpu.models import resnet

    model = resnet.train_main([
        "-b", "16", "--maxIteration", "2", "--synthetic", "32",
        "--dataset", "cifar10", "--depth", "20",
    ])
    ws, _ = model.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_textclassifier_train_main():
    from bigdl_tpu.models import textclassifier

    model = textclassifier.train_main([
        "-b", "16", "--maxIteration", "3", "--synthetic", "48",
        "--seqLen", "12", "--vocab", "60", "--classNum", "3",
        "--embeddingDim", "16",
    ])
    ws, _ = model.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_rnn_train_main():
    from bigdl_tpu.models import rnn

    model = rnn.train_main([
        "-b", "16", "--maxIteration", "3", "--synthetic", "48",
        "--seqLen", "10", "--vocab", "40", "--hidden", "32",
    ])
    ws, _ = model.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_rnn_real_corpus_main(tmp_path):
    """-f loads a real text corpus (tokenize → windows), not synthetic."""
    corpus = tmp_path / "train.txt"
    corpus.write_text(("the quick brown fox jumps over the lazy dog " * 40))
    from bigdl_tpu.models import rnn

    model = rnn.train_main([
        "-f", str(corpus), "-b", "8", "--maxIteration", "2",
        "--seqLen", "8", "--hidden", "16",
    ])
    ws, _ = model.parameters()
    # vocabulary derived from the corpus (8 words + OOV = 9), so the
    # LookupTable is (9, hidden) — proves the real path ran
    assert any(np.asarray(w).shape[0] == 9 for w in ws)


def test_textclassifier_real_folder_main(tmp_path):
    for ci, cls in enumerate(["alt.atheism", "sci.space"]):
        d = tmp_path / cls
        d.mkdir()
        for i in range(6):
            (d / f"{i}.txt").write_text(f"{cls.split('.')[-1]} topic words "
                                        f"document {i} " * 5)
    from bigdl_tpu.models import textclassifier

    model = textclassifier.train_main([
        "-f", str(tmp_path), "-b", "4", "--maxIteration", "2",
        "--seqLen", "12", "--embeddingDim", "8",
    ])
    ws, _ = model.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_resnet_imagenet_default_depth():
    """`--dataset imagenet` with no --depth must build ResNet-50, not crash."""
    from bigdl_tpu.models.resnet import ResNet

    # the main's depth resolution: args.depth or 50
    model = ResNet(1000, {"depth": None or 50, "shortcutType": "B"})
    assert model is not None


def test_seqfile_rejects_empty_process_shard(tmp_path):
    import pytest

    from bigdl_tpu.dataset.seqfile import SeqFileDataSet, encode_array, write_shards

    write_shards([(1, encode_array(np.zeros((2,), np.float32)))],
                 str(tmp_path), n_shards=1)
    with pytest.raises(ValueError, match="gets no shards"):
        SeqFileDataSet(str(tmp_path), shard_index=1, num_shards=2)


def test_alexnet_and_autoencoder_mains():
    from bigdl_tpu.models import alexnet, autoencoder

    m1 = alexnet.train_main(["-b", "8", "--maxIteration", "1",
                             "--synthetic", "16"])
    ws, _ = m1.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)

    m2 = autoencoder.train_main(["-b", "16", "--maxIteration", "2",
                                 "--synthetic", "32"])
    ws, _ = m2.parameters()
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)


def test_textclassifier_news20_glove_pipeline():
    """The reference's default pipeline: news20 texts embedded with GloVe on
    the host, BiRecurrent LSTM over pre-embedded input."""
    from bigdl_tpu.models import textclassifier

    model = textclassifier.train_main([
        "--news20", "--maxEpoch", "1", "--batchSize", "16",
        "--seqLen", "12", "--embeddingDim", "32", "--synthetic", "0",
    ])
    ws, _ = model.parameters()
    import numpy as np
    assert all(np.all(np.isfinite(np.asarray(w))) for w in ws)
