"""TensorflowSaver: jax2tf export round-trip (SURVEY.md §2.7 TF export)."""

import numpy as np
import pytest

from tests.oracle import assert_close

tf = pytest.importorskip("tensorflow")


def test_savedmodel_roundtrip(rng, tmp_path):
    from bigdl_tpu.nn import Linear, ReLU, Sequential, SoftMax
    from bigdl_tpu.utils.tf_saver import save_tf

    m = Sequential().add(Linear(8, 16)).add(ReLU()).add(Linear(16, 3)).add(SoftMax())
    m._ensure_params()
    x = rng.randn(4, 8).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))

    path = str(tmp_path / "sm")
    save_tf(m, [8], path)
    loaded = tf.saved_model.load(path)
    got = loaded.f(tf.constant(x)).numpy()
    assert_close(got, want, atol=1e-5)


def test_frozen_graph_roundtrip_via_loader(rng, tmp_path):
    """Export to frozen GraphDef, re-import with our own TensorflowLoader —
    full export→import cycle through the TF interchange format."""
    from bigdl_tpu.nn import Linear, Sequential, Tanh
    from bigdl_tpu.utils.tf_saver import save_tf

    m = Sequential().add(Linear(5, 7)).add(Tanh())
    m._ensure_params()
    x = rng.randn(3, 5).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))

    path = str(tmp_path / "frozen.pb")
    conc = save_tf(m, [5], path, frozen_graph=True, batch=3)

    gd = tf.compat.v1.GraphDef()
    with open(path, "rb") as f:
        gd.ParseFromString(f.read())
    # run it with TF as the oracle
    out_name = conc.outputs[0].name.split(":")[0]
    in_name = conc.inputs[0].name.split(":")[0]
    tf.compat.v1.reset_default_graph()
    with tf.compat.v1.Session() as sess:
        tf.import_graph_def(gd, name="")
        got = sess.run(out_name + ":0", {in_name + ":0": x})
    assert_close(got, want, atol=1e-5)
