"""Native C++ runtime: parity vs numpy, prefetch executor, pipeline.

Mirrors the reference's differential-testing pattern (SURVEY.md §4): the
C++ data path is checked op-for-op against the pure-numpy transformers.
"""

import numpy as np
import pytest

import bigdl_tpu.native as native
from bigdl_tpu.dataset.native_pipeline import NativeImagePipeline

pytestmark = pytest.mark.skipif(
    not native.is_available(),
    reason=f"native toolchain unavailable: {native.unavailable_reason()}")


def _imgs(n=8, h=40, w=40, c=3, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(n, h, w, c), dtype=np.uint8)


def test_augment_batch_matches_numpy():
    imgs = _imgs()
    rng = np.random.default_rng(1)
    oy = rng.integers(0, 9, 8).astype(np.int32)
    ox = rng.integers(0, 9, 8).astype(np.int32)
    fl = rng.integers(0, 2, 8).astype(np.uint8)
    mean = np.array([120.0, 115.0, 100.0], np.float32)
    std = np.array([60.0, 61.0, 62.0], np.float32)
    out = native.augment_batch(imgs, oy, ox, fl, 32, 32, mean, std)
    assert out.shape == (8, 3, 32, 32)
    for i in range(8):
        crop = imgs[i, oy[i]:oy[i] + 32, ox[i]:ox[i] + 32, :].astype(np.float32)
        if fl[i]:
            crop = crop[:, ::-1, :]
        ref = ((crop - mean) / std).transpose(2, 0, 1)
        np.testing.assert_allclose(out[i], ref, atol=1e-5)


def test_resize_bilinear_matches_numpy_halfpixel():
    imgs = _imgs(n=2, h=8, w=6, c=3, seed=2)
    out = native.resize_bilinear(imgs, 4, 3)
    # numpy oracle: half-pixel bilinear, clamped edges, round-half-up
    sh, sw, dh, dw = 8, 6, 4, 3
    fy = np.clip((np.arange(dh) + 0.5) * sh / dh - 0.5, 0, None)
    fx = np.clip((np.arange(dw) + 0.5) * sw / dw - 0.5, 0, None)
    y0 = fy.astype(int); y1 = np.minimum(y0 + 1, sh - 1); wy = fy - y0
    x0 = fx.astype(int); x1 = np.minimum(x0 + 1, sw - 1); wx = fx - x0
    src = imgs.astype(np.float32)
    top = src[:, y0][:, :, x0] * (1 - wx)[None, None, :, None] + \
        src[:, y0][:, :, x1] * wx[None, None, :, None]
    bot = src[:, y1][:, :, x0] * (1 - wx)[None, None, :, None] + \
        src[:, y1][:, :, x1] * wx[None, None, :, None]
    ref = top * (1 - wy)[None, :, None, None] + bot * wy[None, :, None, None]
    np.testing.assert_array_equal(out, (ref + 0.5).astype(np.uint8))


def test_decode_cifar_layout_and_labels():
    rng = np.random.default_rng(3)
    recs = rng.integers(0, 256, size=(5 * 3073,), dtype=np.uint8)
    imgs, labels = native.decode_cifar(recs)
    as_recs = recs.reshape(5, 3073)
    np.testing.assert_array_equal(labels, as_recs[:, 0].astype(np.int32) + 1)
    np.testing.assert_array_equal(
        imgs, as_recs[:, 1:].reshape(5, 3, 32, 32))


def test_native_loader_fifo_and_values():
    imgs = _imgs(n=4, h=36, w=36)
    mean = [100.0, 100.0, 100.0]
    std = [50.0, 50.0, 50.0]
    oy = np.array([0, 1, 2, 3], np.int32)
    ox = np.array([3, 2, 1, 0], np.int32)
    fl = np.array([0, 1, 0, 1], np.uint8)
    with native.NativeLoader(4, 36, 36, 3, 32, 32, mean, std,
                             queue_depth=3, n_workers=2) as L:
        for k in range(3):
            L.push(imgs, np.arange(4) + 10 * k, oy, ox, fl)
        for k in range(3):
            out, lab = L.pop()
            np.testing.assert_array_equal(lab, np.arange(4) + 10 * k)
            crop = imgs[1, 1:33, 2:34, ::].astype(np.float32)[:, ::-1, :]
            ref = ((crop - 100.0) / 50.0).transpose(2, 0, 1)
            np.testing.assert_allclose(out[1], ref, atol=1e-5)


def test_pipeline_native_matches_numpy_fallback():
    imgs = _imgs(n=32, h=32, w=32, seed=4)
    labels = np.arange(32) % 10 + 1
    kw = dict(batch_size=8, crop=(28, 28), mean=[125.3, 123.0, 113.9],
              std=[63.0, 62.1, 66.7], pad=2, seed=7)
    p1 = NativeImagePipeline(imgs, labels, **kw)
    p2 = NativeImagePipeline(imgs, labels, **kw)
    native_batches = list(p1._native_iter(train=False))
    numpy_batches = list(p2._numpy_iter(train=False))
    assert len(native_batches) == len(numpy_batches) == 4
    for a, b in zip(native_batches, numpy_batches):
        np.testing.assert_allclose(a.input, b.input, atol=1e-5)
        np.testing.assert_array_equal(a.target, b.target)


def test_pipeline_train_stream_deterministic_rng():
    imgs = _imgs(n=16, h=32, w=32, seed=5)
    labels = np.arange(16)
    kw = dict(batch_size=4, crop=(24, 24), mean=[0.0] * 3, std=[255.0] * 3,
              seed=11)
    it1 = NativeImagePipeline(imgs, labels, **kw)._native_iter(train=True)
    it2 = NativeImagePipeline(imgs, labels, **kw)._numpy_iter(train=True)
    for _ in range(6):  # crosses an epoch boundary (4 batches/epoch)
        a, b = next(it1), next(it2)
        np.testing.assert_allclose(a.input, b.input, atol=1e-5)
        np.testing.assert_array_equal(a.target, b.target)
    it1.close()


def test_recs_index_matches_python_reader(tmp_path):
    import numpy as np
    import pytest

    from bigdl_tpu import native
    from bigdl_tpu.dataset import seqfile

    if not native.is_available():
        pytest.skip(native.unavailable_reason())

    rng = np.random.default_rng(0)
    recs = [(int(rng.integers(0, 1000)),
             rng.integers(0, 256, size=int(rng.integers(1, 300)))
             .astype(np.uint8).tobytes())
            for _ in range(400)]
    paths = seqfile.write_shards(recs, str(tmp_path), n_shards=3)

    for p in paths:
        buf = np.fromfile(p, np.uint8)
        labels, offsets, lengths = native.recs_index(buf)
        # python reference reader (force the fallback branch)
        with open(p, "rb") as f:
            assert f.read(4) == seqfile.MAGIC
            want = []
            while True:
                lab = seqfile._read_varint(f)
                if lab is None:
                    break
                ln = seqfile._read_varint(f)
                want.append((lab, f.read(ln)))
        assert len(want) == len(labels)
        raw = buf.tobytes()
        for i, (lab, payload) in enumerate(want):
            assert labels[i] == lab
            assert raw[offsets[i]:offsets[i] + lengths[i]] == payload


def test_recs_index_rejects_malformed(tmp_path):
    import numpy as np
    import pytest

    from bigdl_tpu import native

    if not native.is_available():
        pytest.skip(native.unavailable_reason())

    with pytest.raises(ValueError):
        native.recs_index(np.frombuffer(b"NOPE" + b"\x00" * 10, np.uint8))
    # truncated payload: declares 100 bytes, provides 2
    bad = b"RECS" + bytes([5]) + bytes([100]) + b"\x01\x02"
    with pytest.raises(ValueError):
        native.recs_index(np.frombuffer(bad, np.uint8))


def test_u8_nhwc_output_and_device_normalizer(rng):
    """output="u8_nhwc" ships raw uint8 crops; DeviceImageNormalizer on
    the batch must equal the pipeline's own f32_nchw output for the same
    crop (flip/augment off for determinism)."""
    import jax

    from bigdl_tpu.dataset.native_pipeline import (
        DeviceImageNormalizer, NativeImagePipeline,
    )

    imgs = rng.randint(0, 256, size=(8, 10, 10, 3)).astype(np.uint8)
    labels = (np.arange(8) % 3 + 1).astype(np.int32)
    kw = dict(batch_size=4, crop=(8, 8), mean=(10.0, 20.0, 30.0),
              std=(50.0, 60.0, 70.0), hflip=False, seed=3)

    u8 = NativeImagePipeline(imgs, labels, output="u8_nhwc", **kw)
    f32 = NativeImagePipeline(imgs, labels, **kw)
    b_u8 = next(u8.data(train=False))
    b_f32 = next(f32.data(train=False))

    x = np.asarray(b_u8.get_input())
    assert x.dtype == np.uint8 and x.shape == (4, 8, 8, 3)
    norm = DeviceImageNormalizer((10.0, 20.0, 30.0), (50.0, 60.0, 70.0))
    got = np.asarray(jax.jit(norm)(x))
    want = np.asarray(b_f32.get_input())
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert u8.device_normalizer().mean.tolist() == [10.0, 20.0, 30.0]

    with pytest.raises(ValueError, match="output"):
        NativeImagePipeline(imgs, labels, output="f16_nhwc", **kw)


def test_u8_feed_through_distri_optimizer(rng):
    """set_device_preprocess must reach the DistriOptimizer spmd step
    builders too (a silently-dropped preprocess feeds raw uint8 NHWC into
    an NCHW conv)."""
    import jax
    from jax.sharding import Mesh

    from bigdl_tpu.dataset.native_pipeline import NativeImagePipeline
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils.random_gen import RNG

    imgs = rng.randint(0, 256, size=(64, 28, 28, 1)).astype(np.uint8)
    labels = (np.arange(64) % 10 + 1).astype(np.int32)
    pipe = NativeImagePipeline(imgs, labels, batch_size=16, crop=(28, 28),
                               mean=(33.3,), std=(78.6,), hflip=False,
                               output="u8_nhwc")
    for mode in ("allreduce", "partitioned"):
        RNG.set_seed(5)
        opt = Optimizer(model=LeNet5(10), dataset=pipe,
                        criterion=ClassNLLCriterion(), distributed=True,
                        parameter_mode=mode,
                        mesh=Mesh(np.asarray(jax.devices()).reshape(-1),
                                  ("data",)),
                        end_trigger=Trigger.max_iteration(2))
        opt.set_device_preprocess(pipe.device_normalizer())
        opt.set_optim_method(SGD(learning_rate=0.05))
        trained = opt.optimize()
        ws, _ = trained.parameters()
        assert sum(np.asarray(w).size for w in ws) > 1000


def test_u8_feed_validation_path(rng):
    """In-training validation must also run the device preprocess — a
    u8_nhwc validation set fed to a conv model crashes (or scores
    garbage) if _eval_forward skips the normalizer."""
    from bigdl_tpu.dataset.native_pipeline import NativeImagePipeline
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger
    from bigdl_tpu.utils.random_gen import RNG

    imgs = rng.randint(0, 256, size=(64, 28, 28, 1)).astype(np.uint8)
    labels = (np.arange(64) % 10 + 1).astype(np.int32)

    def pipe(n):
        return NativeImagePipeline(imgs[:n], labels[:n], batch_size=16,
                                   crop=(28, 28), mean=(33.3,),
                                   std=(78.6,), hflip=False,
                                   output="u8_nhwc")

    train = pipe(64)
    RNG.set_seed(9)
    opt = Optimizer(model=LeNet5(10), dataset=train,
                    criterion=ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(3))
    opt.set_device_preprocess(train.device_normalizer())
    opt.set_optim_method(SGD(learning_rate=0.05))
    opt.set_validation(Trigger.several_iteration(2), pipe(32),
                       [Top1Accuracy()], batch_size=16)
    trained = opt.optimize()   # would raise on conv dim mismatch if the
    ws, _ = trained.parameters()   # eval path skipped the preprocess
    assert sum(np.asarray(w).size for w in ws) > 1000
