import numpy as np

from tests.oracle import assert_close


def test_classnll_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import ClassNLLCriterion

    logp = torch.log_softmax(torch.from_numpy(rng.randn(4, 5).astype(np.float32)), 1)
    target = np.array([1, 3, 5, 2], np.float32)  # 1-based
    crit = ClassNLLCriterion()
    loss = crit.forward(logp.numpy(), target)
    t_loss = torch.nn.NLLLoss()(logp, torch.from_numpy(target).long() - 1)
    assert abs(loss - float(t_loss)) < 1e-5

    gin = crit.backward(logp.numpy(), target)
    lt = logp.clone().requires_grad_(True)
    torch.nn.NLLLoss()(lt, torch.from_numpy(target).long() - 1).backward()
    assert_close(np.asarray(gin), lt.grad.numpy(), atol=1e-5)


def test_crossentropy_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import CrossEntropyCriterion

    x = rng.randn(6, 4).astype(np.float32)
    target = np.array([1, 2, 3, 4, 1, 2], np.float32)
    crit = CrossEntropyCriterion()
    loss = crit.forward(x, target)
    t_loss = torch.nn.CrossEntropyLoss()(
        torch.from_numpy(x), torch.from_numpy(target).long() - 1
    )
    assert abs(loss - float(t_loss)) < 1e-5


def test_mse_abs_smoothl1_bce_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import AbsCriterion, BCECriterion, MSECriterion, SmoothL1Criterion

    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    assert abs(
        MSECriterion().forward(x, y)
        - float(torch.nn.MSELoss()(torch.from_numpy(x), torch.from_numpy(y)))
    ) < 1e-5
    assert abs(
        AbsCriterion().forward(x, y)
        - float(torch.nn.L1Loss()(torch.from_numpy(x), torch.from_numpy(y)))
    ) < 1e-5
    assert abs(
        SmoothL1Criterion().forward(x, y)
        - float(torch.nn.SmoothL1Loss()(torch.from_numpy(x), torch.from_numpy(y)))
    ) < 1e-5

    p = 1.0 / (1.0 + np.exp(-x))
    t = (rng.rand(3, 4) > 0.5).astype(np.float32)
    assert abs(
        BCECriterion().forward(p, t)
        - float(torch.nn.BCELoss()(torch.from_numpy(p), torch.from_numpy(t)))
    ) < 1e-4


def test_parallel_criterion(rng):
    from bigdl_tpu.nn import MSECriterion, ParallelCriterion

    pc = ParallelCriterion().add(MSECriterion(), 0.3).add(MSECriterion(), 0.7)
    x1, y1 = rng.randn(2, 3).astype(np.float32), rng.randn(2, 3).astype(np.float32)
    x2, y2 = rng.randn(2, 3).astype(np.float32), rng.randn(2, 3).astype(np.float32)
    loss = pc.forward([x1, x2], [y1, y2])
    expect = 0.3 * np.mean((x1 - y1) ** 2) + 0.7 * np.mean((x2 - y2) ** 2)
    assert abs(loss - expect) < 1e-5


def test_timedistributed_criterion(rng):
    import torch

    from bigdl_tpu.nn import CrossEntropyCriterion, TimeDistributedCriterion

    x = rng.randn(2, 3, 5).astype(np.float32)  # (N, T, C)
    t = np.array([[1, 2, 3], [4, 5, 1]], np.float32)
    crit = TimeDistributedCriterion(CrossEntropyCriterion(), size_average=True)
    loss = crit.forward(x, t)
    ref = np.mean([
        float(torch.nn.CrossEntropyLoss()(
            torch.from_numpy(x[:, i]), torch.from_numpy(t[:, i]).long() - 1))
        for i in range(3)
    ])
    assert abs(loss - ref) < 1e-5


def test_gradient_check_crossentropy(rng):
    """Finite-difference check (reference GradientChecker pattern)."""
    from bigdl_tpu.nn import CrossEntropyCriterion
    from tests.oracle import finite_diff_grad

    x = rng.randn(3, 4).astype(np.float64)
    t = np.array([1, 2, 3], np.float32)
    crit = CrossEntropyCriterion()
    g = np.asarray(crit.backward(x.astype(np.float32), t))
    g_fd = finite_diff_grad(lambda xx: float(crit.apply(xx.astype(np.float32), t)), x)
    assert_close(g, g_fd, atol=1e-3)


def test_time_distributed_criterion_vmap_matches_loop(rng):
    """The vmapped form must equal the per-step loop semantics exactly."""
    import jax.numpy as jnp

    from bigdl_tpu.nn import ClassNLLCriterion, MSECriterion, TimeDistributedCriterion

    logp = np.log(np.abs(rng.randn(3, 5, 4)).astype(np.float32) + 0.1)
    logp = logp - np.log(np.exp(logp).sum(-1, keepdims=True))
    tgt = rng.randint(1, 5, size=(3, 5)).astype(np.float32)

    c = TimeDistributedCriterion(ClassNLLCriterion(), size_average=True)
    got = c.forward(logp, tgt)
    want = np.mean([ClassNLLCriterion().forward(logp[:, t], tgt[:, t])
                    for t in range(5)])
    assert abs(got - want) < 1e-5

    # shared (time-less) target branch
    x = rng.randn(3, 4, 6).astype(np.float32)
    shared = rng.randn(3, 6).astype(np.float32)
    c2 = TimeDistributedCriterion(MSECriterion(), size_average=False)
    got = c2.forward(x, shared)
    want = sum(MSECriterion().forward(x[:, t], shared) for t in range(4))
    assert abs(got - want) < 1e-4
