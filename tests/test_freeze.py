"""Module.freeze/unfreeze (reference transfer-learning freeze) and the
pyspark get_weights/set_weights surface. Frozen layers must stay
BIT-identical through training — including under in-optimizer weight
decay, which zeroed gradients alone would not stop — in the local path
and both DistriOptimizer modes."""

import numpy as np
import pytest

from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn import Linear, MSECriterion, Sequential
from bigdl_tpu.optim import Optimizer, SGD, Trigger


def _model():
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(3)
    m = (Sequential()
         .add(Linear(6, 8).set_name("fc1"))
         .add(Linear(8, 4).set_name("fc2"))
         .add(Linear(4, 2).set_name("fc3")))
    m._ensure_params()
    return m


def _samples(n=32):
    rs = np.random.RandomState(0)
    return [Sample(rs.rand(6).astype(np.float32),
                   rs.rand(2).astype(np.float32)) for _ in range(n)]


def _leaf(model, name):
    ws = {}

    def walk(mods, params):
        for i, m in enumerate(mods):
            key = next(k for k in params if k.split(":")[0] == str(i))
            if m.sub_modules():
                walk(m.sub_modules(), params[key])
            else:
                ws[m.name] = {k: np.array(v) for k, v in params[key].items()}

    walk(model.sub_modules(), model.params)
    return ws[name]


@pytest.mark.parametrize("mode", ["local", "allreduce", "partitioned"])
def test_frozen_layers_stay_bit_identical(mode):
    from bigdl_tpu.dataset.dataset import DataSet, DistributedDataSet

    model = _model().freeze("fc1", "fc3")
    before = {n: _leaf(model, n) for n in ("fc1", "fc2", "fc3")}

    data = _samples()
    ds = (DistributedDataSet(data) if mode != "local"
          else DataSet.array(data))
    kw = {} if mode == "local" else {"parameter_mode": mode}
    opt = Optimizer(model=model, dataset=ds, criterion=MSECriterion(),
                    batch_size=8, end_trigger=Trigger.max_iteration(6), **kw)
    # weight decay would move frozen params if only the grads were zeroed
    opt.set_optim_method(SGD(learning_rate=0.1, momentum=0.9,
                             weight_decay=0.1))
    opt.optimize()

    after = {n: _leaf(model, n) for n in ("fc1", "fc2", "fc3")}
    for n in ("fc1", "fc3"):
        for k in before[n]:
            np.testing.assert_array_equal(before[n][k], after[n][k]), (n, k)
    assert any(np.abs(before["fc2"][k] - after["fc2"][k]).max() > 1e-6
               for k in before["fc2"]), "unfrozen layer did not train"


def test_unfreeze_and_whole_module_freeze():
    model = _model()
    model.freeze()                      # whole module
    assert model.is_frozen()
    model.unfreeze()
    assert not model.is_frozen()
    model.freeze("fc2")
    subs = model.sub_modules()
    assert subs[1].is_frozen() and not subs[0].is_frozen()
    model.unfreeze("fc2")
    assert not subs[1].is_frozen()
    with pytest.raises(ValueError, match="no sub-module"):
        model.freeze("nope")


def test_get_set_weights_roundtrip():
    m1, m2 = _model(), _model()
    rs = np.random.RandomState(9)
    ws = [rs.randn(*w.shape).astype(np.float32) for w in m1.get_weights()]
    m1.set_weights(ws)
    for got, want in zip(m1.get_weights(), ws):
        np.testing.assert_array_equal(got, want)
    # transfers between identical architectures
    m2.set_weights(m1.get_weights())
    x = rs.rand(3, 6).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m1.forward(x)),
                               np.asarray(m2.forward(x)), atol=1e-6)
    with pytest.raises(ValueError, match="shape mismatch"):
        m2.set_weights([w.T if w.ndim == 2 else w for w in ws])
    with pytest.raises(ValueError, match="arrays for"):
        m2.set_weights(ws[:-1])


def test_freeze_all_then_unfreeze_head():
    """The classic transfer-learning flow: freeze everything, then
    explicitly unfreeze the head — the head MUST train (round-2 review
    finding: inheritance must not override an explicit child flag)."""
    from bigdl_tpu.dataset.dataset import DataSet

    model = _model()
    model.freeze()
    model.unfreeze("fc3")
    before = {n: _leaf(model, n) for n in ("fc1", "fc2", "fc3")}

    opt = Optimizer(model=model, dataset=DataSet.array(_samples()),
                    criterion=MSECriterion(), batch_size=8,
                    end_trigger=Trigger.max_iteration(6))
    opt.set_optim_method(SGD(learning_rate=0.1, weight_decay=0.05))
    opt.optimize()

    after = {n: _leaf(model, n) for n in ("fc1", "fc2", "fc3")}
    for n in ("fc1", "fc2"):
        for k in before[n]:
            np.testing.assert_array_equal(before[n][k], after[n][k])
    assert any(np.abs(before["fc3"][k] - after["fc3"][k]).max() > 1e-6
               for k in before["fc3"]), "unfrozen head did not train"

    # bare unfreeze() clears EVERY flag, including named ones
    model2 = _model().freeze("fc1")
    model2.unfreeze()
    assert not model2.sub_modules()[0].is_frozen()
    from bigdl_tpu.optim.train_step import frozen_mask_tree
    assert frozen_mask_tree(model2, model2.params) is None
