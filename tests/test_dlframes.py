"""DLEstimator/DLClassifier pipeline plane (SURVEY.md §2.7 dlframes row)."""

import numpy as np


def _blobs(rng, n=90, d=6, c=3):
    xs, ys = [], []
    for i in range(n):
        k = i % c
        xs.append((rng.randn(d) * 0.3 + np.eye(c)[k].repeat(d // c) * 2
                   ).astype(np.float32))
        ys.append(k + 1)
    return np.stack(xs), np.asarray(ys)


def test_dlclassifier_fit_predict(rng):
    from bigdl_tpu.dlframes import DLClassifier, DLClassifierModel
    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential

    X, y = _blobs(rng)
    net = (Sequential().add(Linear(6, 16)).add(ReLU())
           .add(Linear(16, 3)).add(LogSoftMax()))
    est = (DLClassifier(net, ClassNLLCriterion(), [6])
           .set_batch_size(30).set_max_epoch(20).set_learning_rate(0.5))
    model = est.fit(X, y)
    assert isinstance(model, DLClassifierModel)
    pred = model.predict(X)
    assert pred.min() >= 1 and pred.max() <= 3
    assert (pred == y).mean() > 0.9

    proba = model.predict_proba(X)
    assert proba.shape == (len(X), 3)
    np.testing.assert_allclose(proba.sum(-1), 1.0, atol=1e-4)


def test_dlestimator_regression(rng):
    from bigdl_tpu.dlframes import DLEstimator
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Adam

    W = rng.randn(4, 2).astype(np.float32)
    X = rng.randn(200, 4).astype(np.float32)
    Y = X @ W
    est = (DLEstimator(Sequential().add(Linear(4, 2)), MSECriterion(),
                       [4], [2])
           .set_batch_size(50).set_max_epoch(60)
           .set_optim_method(Adam(learning_rate=0.05)))
    model = est.fit(X, Y)
    pred = model.transform(X)
    mse = ((pred - Y) ** 2).mean()
    assert mse < 0.05, f"regression failed to fit: mse={mse}"
