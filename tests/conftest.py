"""Test harness config.

Mirrors the reference's `new SparkContext("local[N]")` trick (SURVEY.md §4):
the full distributed path runs in one process by giving JAX 8 virtual CPU
devices. Must run before jax initializes a backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# This sandbox's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon already in the env, so the env vars above are too late
# for jax's config — override via jax.config before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")
# fp32 matmuls for oracle-parity tests
jax.config.update("jax_default_matmul_precision", "highest")

# persistent compilation cache: most fast-loop wall time is XLA recompiles
# of the same programs run-over-run (this box has ONE core) — warm runs
# skip them (round-1 verdict weak #6: iteration-speed tax)
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.expanduser("~"), ".cache",
                               "bigdl_tpu_test_jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(42)


# -- one-compiled-program guards -------------------------------------------
# Canonical home: tests/compile_guards.py (a plain, side-effect-free
# module — import THAT in test files; importing tests.conftest would
# load a second copy of this module next to pytest's own instance and
# re-run the jax/XLA session setup above).  Re-exported here so the
# guard is discoverable where fixtures live.
from tests.compile_guards import (  # noqa: E402,F401
    assert_compile_count, compile_count)
