"""Test harness config.

Mirrors the reference's `new SparkContext("local[N]")` trick (SURVEY.md §4):
the full distributed path runs in one process by giving JAX 8 virtual CPU
devices. Must run before jax initializes a backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# This sandbox's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon already in the env, so the env vars above are too late
# for jax's config — override via jax.config before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")
# fp32 matmuls for oracle-parity tests
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.RandomState(42)
