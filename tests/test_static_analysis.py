"""SPMD hygiene analyzer (bigdl_tpu/analysis): the tier-1 repo-wide
zero-findings gate, exact (line, code) parity against the EXPECT-marked
fixtures, the utils/compat.py no-false-positive guarantee, and the CLI
contract (exit codes, --select/--ignore, --json, baseline handling).

Pure AST — none of this traces or compiles anything, so the whole
module runs in milliseconds plus one subprocess for the `python -m`
entry point.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from bigdl_tpu.analysis import (
    DEFAULT_PATHS, analyze_paths, analyze_source, load_baseline, main,
    rule_codes, split_baselined,
)

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"
BASELINE = REPO / "analysis_baseline.txt"

BAD_FIXTURES = sorted(FIXTURES.glob("bad_*.py"))
ALL_CODES = ("ASY301", "ASY302", "ASY303", "ASY304", "ASY305",
             "ASY306", "ASY307", "ASY308", "ASY309", "ASY310",
             "MH401", "MH402", "MH403", "MH404", "MH405",
             "SPMD101", "SPMD102", "SPMD103", "SPMD104", "SPMD105",
             "SPMD106", "SRV201", "SRV202", "SRV203", "SRV204", "SRV205",
             "SRV206", "SRV207", "SRV208")
ASY_CODES = ["ASY301", "ASY302", "ASY303", "ASY304", "ASY305",
             "ASY306", "ASY307", "ASY308", "ASY309", "ASY310"]
MH_CODES = ["MH401", "MH402", "MH403", "MH404", "MH405"]


def _expected(path: Path):
    """(line, code) pairs from the fixture's `# EXPECT: CODE` comments."""
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = re.search(r"#\s*EXPECT:\s*([A-Z]+\d+)", line)
        if m:
            out.add((i, m.group(1)))
    return out


# -- the tier-1 acceptance gate --------------------------------------------

def test_repo_has_zero_non_baselined_findings(monkeypatch):
    """`python -m bigdl_tpu.analysis bigdl_tpu benchmarks tests` must be
    clean: every finding either fixed or explicitly grandfathered in the
    committed baseline.  Re-introducing the PR-4 spec drift or a direct
    jax.shard_map import anywhere in those trees fails THIS test with
    the rule code and file:line."""
    monkeypatch.chdir(REPO)
    # analyze_paths silently skips nonexistent paths — guard against a
    # renamed tree turning this gate into a zero-file false green (the
    # CLI exits 2 on this; the API caller must check itself)
    for p in DEFAULT_PATHS:
        assert (REPO / p).is_dir(), f"analyzed tree missing: {p}"
    findings = analyze_paths(DEFAULT_PATHS)
    new, _ = split_baselined(findings, load_baseline(str(BASELINE)))
    assert not new, (
        "SPMD hygiene violations (fix them, or baseline each with a "
        "justification comment in analysis_baseline.txt — see "
        "docs/analysis.md):\n"
        + "\n".join(f.format() for f in new))


def test_rule_registry_is_complete():
    assert tuple(sorted(rule_codes())) == ALL_CODES


# -- fixture parity ---------------------------------------------------------

@pytest.mark.parametrize("fixture", BAD_FIXTURES,
                         ids=[p.stem for p in BAD_FIXTURES])
def test_bad_fixture_exact_findings(fixture):
    """Exact (line, code) parity with the EXPECT comments — unmarked
    lines in the bad files double as false-positive checks (static
    shape branches, getattr of unrelated attrs, legit multi-axis tuple
    specs, carry rebinding...)."""
    expected = _expected(fixture)
    assert expected, f"{fixture} has no EXPECT annotations"
    got = {(f.line, f.code) for f in analyze_paths([str(fixture)])}
    assert got == expected, (
        f"missing: {sorted(expected - got)}; "
        f"spurious: {sorted(got - expected)}")


def test_good_fixture_is_clean():
    assert analyze_paths([str(FIXTURES / "good_clean.py")]) == []


def test_compat_module_itself_is_clean():
    """utils/compat.py is the one module allowed to spell the moved APIs
    directly — the analyzer must not flag its own shim."""
    compat = REPO / "bigdl_tpu" / "utils" / "compat.py"
    assert analyze_paths([str(compat)]) == []


def test_compat_rule_fires_on_compat_body_elsewhere(tmp_path):
    """The compat exemption is PATH-based, not content-based: the same
    probes outside utils/compat.py are flagged."""
    clone = tmp_path / "not_compat.py"
    clone.write_text((REPO / "bigdl_tpu" / "utils"
                      / "compat.py").read_text())
    assert any(f.code == "SPMD101" for f in analyze_paths([str(clone)]))


def test_fixture_dir_excluded_from_tree_scans():
    """Repo-wide scans must skip analysis_fixtures/ (deliberate
    violations) while explicit file paths still reach inside."""
    findings = analyze_paths([str(FIXTURES.parent)],
                             select=["SPMD102"])
    assert not any("analysis_fixtures" in f.path for f in findings)


# -- acceptance: re-introducing the historical bugs ------------------------

def test_reintroduced_pr4_spec_drift_is_caught(tmp_path):
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "ROWS = P(('data',))\n"
    )
    fs = analyze_source(src, "drifted.py")
    assert [(f.code, f.line) for f in fs] == [("SPMD102", 2)]


def test_duplicate_lines_get_distinct_fingerprints():
    """Baselining one occurrence of a drifted line must not silence a
    second paste of the identical line — fingerprints are occurrence-
    indexed."""
    src = (  # analysis: no-embed — deliberate violations under test
        "from jax.sharding import PartitionSpec as P\n"
        "SPECS = [\n"
        "    P(('data',)),\n"
        "    P(('data',)),\n"
        "]\n"
    )
    fs = analyze_source(src, "dup.py")
    assert [f.code for f in fs] == ["SPMD102", "SPMD102"]
    assert fs[0].source == fs[1].source
    assert fs[0].fingerprint() != fs[1].fingerprint()
    new, old = split_baselined(fs, {fs[0].baseline_key()})
    assert [f.line for f in old] == [3] and [f.line for f in new] == [4]


def test_reintroduced_direct_shard_map_import_is_caught():
    fs = analyze_source(
        "from jax.experimental.shard_map import shard_map\n", "bad.py")
    assert [(f.code, f.line) for f in fs] == [("SPMD101", 1)]


# -- CLI contract -----------------------------------------------------------

def test_cli_exit_codes_and_select(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    bad = str(FIXTURES / "bad_spec_spelling.py")

    assert main([bad, "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "SPMD102" in out and "bad_spec_spelling.py:19" in out

    # selecting a rule the file does not violate -> clean, exit 0
    assert main([bad, "--no-baseline", "--select", "SPMD104"]) == 0
    # ignoring the violated rule -> clean
    assert main([bad, "--no-baseline", "--ignore", "SPMD102"]) == 0
    capsys.readouterr()
    # unknown code -> usage error
    assert main([bad, "--select", "SPMD999"]) == 2
    # a typo'd / wrong-cwd path must be a usage error, never a false
    # green from scanning zero files
    assert main(["no_such_tree"]) == 2


def test_cli_json_report(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    rc = main([str(FIXTURES / "bad_donation.py"), "--no-baseline",
               "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["summary"]["new"] == 4
    assert {f["code"] for f in report["findings"]} == {"SPMD104"}
    assert all(f["fingerprint"] for f in report["findings"])

    rc = main([str(FIXTURES / "good_clean.py"), "--no-baseline", "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["summary"] == {
        "new": 0, "baselined": 0, "total": 0}


def test_cli_baseline_roundtrip(tmp_path, capsys, monkeypatch):
    """--write-baseline output, committed as the baseline, silences
    exactly the current findings (and ONLY those: the fingerprint is
    content-addressed, so editing the offending line re-flags it)."""
    monkeypatch.chdir(REPO)
    bad = str(FIXTURES / "bad_tracer_leak.py")
    assert main([bad, "--write-baseline"]) == 0
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(capsys.readouterr().out)

    assert main([bad, "--baseline", str(baseline)]) == 0
    assert "baselined" in capsys.readouterr().out

    # a NEW violation in the same file is not covered by the baseline
    drifted = tmp_path / "drifted_copy.py"
    drifted.write_text(Path(bad).read_text()
                       + "\n\nimport jax\nsm = jax.shard_map\n")
    assert main([str(drifted), "--baseline", str(baseline)]) == 1


def test_module_entrypoint_subprocess():
    """The `python -m bigdl_tpu.analysis` contract CI rides on: nonzero
    on findings, zero on clean, works from the repo root."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis",
         str(FIXTURES / "bad_compat_drift.py"), "--no-baseline",
         "--quiet"],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stderr
    assert "SPMD101" in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis", "--list-rules"],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for code in ALL_CODES:
        assert code in proc.stdout


# -- whole-program: SRV201 coverage of the REAL dispatch sites --------------

SERVING_DIR = REPO / "bigdl_tpu" / "serving"
_DISPATCH_RE = re.compile(
    r'(?:self|eng)\._dispatch\(\s*"[a-z_]+",\s*([\w.]+),')


def _serving_tree(tmp_path: Path) -> Path:
    """Copy bigdl_tpu/serving into a path that keeps the
    bigdl_tpu/serving/ scope marker (the SRV201 rule's path scoping)."""
    dst = tmp_path / "bigdl_tpu" / "serving"
    dst.mkdir(parents=True)
    for f in SERVING_DIR.glob("*.py"):
        (dst / f.name).write_text(f.read_text())
    return dst


def test_srv201_real_dispatch_sites_enumerated():
    """Every serving module that dispatches compiled steps routes them
    through _dispatch — and the routed sites exist where we think."""
    counts = {f.name: len(_DISPATCH_RE.findall(f.read_text()))
              for f in sorted(SERVING_DIR.glob("*.py"))}
    sites = {k: v for k, v in counts.items() if v}
    assert sites == {"admission.py": 2, "chunked.py": 1,
                     "engine.py": 2, "speculative.py": 3}, sites


@pytest.mark.parametrize("fname", ["engine.py", "speculative.py",
                                   "admission.py", "chunked.py"])
def test_srv201_catches_every_unrouted_dispatch_site(tmp_path, fname):
    """THE SRV201 acceptance proof: deleting the _dispatch routing on
    any one decode/verify/draft/prefill call site in serving/ makes the
    scan fail — demonstrated against a copy of the REAL serving tree
    with each site's routing stripped in turn (the call shape stays
    exactly the real one).  The unmutated copy must scan SRV201-clean,
    so the coverage is exact, not vacuous."""
    tree = _serving_tree(tmp_path)
    clean = analyze_paths([str(tmp_path)], select=["SRV201"])
    assert clean == [], [f.format() for f in clean]

    src = (tree / fname).read_text()
    matches = list(_DISPATCH_RE.finditer(src))
    assert matches, f"{fname} has no dispatch sites?"
    for i, _ in enumerate(matches):
        mutated = []
        for j, m in enumerate(matches):
            if j == i:
                mutated.append((m.start(), m.end(), f"{m.group(1)}("))
        start, end, repl = mutated[0]
        (tree / fname).write_text(src[:start] + repl + src[end:])
        found = analyze_paths([str(tmp_path)], select=["SRV201"])
        assert [f.code for f in found] == ["SRV201"], (
            f"stripping dispatch site {i} in {fname} must yield exactly "
            f"one SRV201, got: {[f.format() for f in found]}")
        assert found[0].path.endswith(fname)
    (tree / fname).write_text(src)


# -- whole-program: cross-module donation lifting ---------------------------

def test_cross_module_donation_reuse():
    """SRV204 resolves a donating helper THROUGH the import graph: the
    helper module is clean alone, the caller only fires when both files
    are in the project."""
    caller = FIXTURES / "xmod_donation_caller.py"
    helper = FIXTURES / "xmod_donation_helper.py"
    assert analyze_paths([str(helper)]) == []
    # caller alone: the import cannot resolve — documented degradation
    assert analyze_paths([str(caller)]) == []
    got = [(Path(f.path).name, f.line, f.code)
           for f in analyze_paths([str(caller), str(helper)])]
    assert got == [("xmod_donation_caller.py", 11, "SRV204")]


# -- whole-program: schema extraction beats the fallback --------------------

def test_srv205_vocabulary_extracted_from_project():
    """The finish-reason vocabulary comes from the PROJECT's
    ServingMetrics.FINISH_REASONS when visible — not the built-in
    fallback (proved by overriding it)."""
    src = (  # analysis: no-embed — deliberate violations under test
        "from bigdl_tpu.serving.metrics import whatever\n"
        "class ServingMetrics:\n"
        "    FINISH_REASONS = frozenset({'weird'})\n"
        "def f(engine, req):\n"
        "    engine._shed(req, 'weird')\n"
        "    engine._shed(req, 'eos')\n"
    )
    got = [(f.line, f.code) for f in analyze_source(src, "mini.py")]
    assert got == [(6, "SRV205")]


def test_srv206_real_tree_clean_and_mutation_caught(tmp_path):
    """SRV206 census over the REAL serving tree: the unmutated copy
    scans clean (every removal from a running/partial table wears a
    requeue/handoff/disposition or lives in the table-owning
    scheduler), and stripping the row_state capture from the one
    direct removal outside the scheduler (PrefillWorker._release —
    the handoff release) yields exactly one SRV206 at disagg.py: the
    no-stranded-rows invariant is enforced where the failover
    machinery actually lives, not just on fixtures."""
    tree = _serving_tree(tmp_path)
    clean = analyze_paths([str(tmp_path)], select=["SRV206"])
    assert clean == [], [f.format() for f in clean]
    src = (tree / "disagg.py").read_text()
    needle = "payload = self.engine.pool.row_state(slot)"
    assert needle in src, "_release moved — update the census"
    (tree / "disagg.py").write_text(
        src.replace(needle, "payload = None", 1))
    found = analyze_paths([str(tmp_path)], select=["SRV206"])
    assert [f.code for f in found] == ["SRV206"], \
        [f.format() for f in found]
    assert found[0].path.endswith("disagg.py")


def test_srv207_real_tree_clean_and_mutation_caught(tmp_path):
    """SRV207 census over the REAL serving tree: the unmutated copy
    scans clean (every block-store write of row state rides the
    pack_payload codec, and every spill site serializes BEFORE
    freeing), and stripping the codec call at THE row-spill site
    (TieredKVStore.put_row) yields exactly one SRV207 at kv_tier.py —
    the tier-codec discipline is enforced where the spill machinery
    actually lives, not just on fixtures."""
    tree = _serving_tree(tmp_path)
    clean = analyze_paths([str(tmp_path)], select=["SRV207"])
    assert clean == [], [f.format() for f in clean]
    src = (tree / "kv_tier.py").read_text()
    needle = "blob = pack_payload(request_meta(req), payload)"
    assert needle in src, "put_row moved — update the census"
    (tree / "kv_tier.py").write_text(
        src.replace(needle, "blob = payload", 1))
    found = analyze_paths([str(tmp_path)], select=["SRV207"])
    assert [f.code for f in found] == ["SRV207"], \
        [f.format() for f in found]
    assert found[0].path.endswith("kv_tier.py")


def test_srv208_real_tree_clean_and_mutation_caught(tmp_path):
    """SRV208 census over the REAL serving tree: the unmutated copy
    scans clean (every control-knob write lives in a constructor or a
    declared ACTUATION_SITES unit — the bus's setters, the engine's
    degrade pair, disagg's autoscale/kill paths), and adding a stray
    ``req.max_new_tokens`` write inside the admission replay helper
    yields exactly one SRV208 at engine.py — the declared-actuator
    discipline is enforced where the knobs actually live, not just on
    fixtures."""
    tree = _serving_tree(tmp_path)
    clean = analyze_paths([str(tmp_path)], select=["SRV208"])
    assert clean == [], [f.format() for f in clean]
    src = (tree / "engine.py").read_text()
    needle = "req.next_token = fed0[-1]"
    assert needle in src, "_admitted_prefill_tokens moved — update the census"
    (tree / "engine.py").write_text(
        src.replace(needle, needle + "\n        req.max_new_tokens = 1", 1))
    found = analyze_paths([str(tmp_path)], select=["SRV208"])
    assert [f.code for f in found] == ["SRV208"], \
        [f.format() for f in found]
    assert found[0].path.endswith("engine.py")


def test_srv208_reads_real_vocabulary():
    """The shipped autopilot.ACTUATION_SITES is what the repo gate
    checks against (extraction, not fallback, on the real tree) — and
    the fallback vocabulary stays in sync with it."""
    from bigdl_tpu.analysis.core import _parse_file, collect_file_facts
    from bigdl_tpu.analysis.rules import _DEFAULT_ACTUATION_SITES
    from bigdl_tpu.serving.autopilot import ACTUATION_SITES

    text = (REPO / "bigdl_tpu" / "serving" / "autopilot.py").read_text()
    ctx, err = _parse_file(text, "bigdl_tpu/serving/autopilot.py")
    assert err is None
    facts = collect_file_facts(ctx)
    assert set(facts["actuation_sites"]) == set(ACTUATION_SITES)
    assert _DEFAULT_ACTUATION_SITES == ACTUATION_SITES


def test_srv205_reads_real_vocabulary():
    """The shipped ServingMetrics.FINISH_REASONS is what the repo gate
    checks against (extraction, not fallback, on the real tree)."""
    from bigdl_tpu.analysis.core import (
        _parse_file, collect_file_facts, extract_embedded_units,
    )

    text = (REPO / "bigdl_tpu" / "serving" / "metrics.py").read_text()
    ctx, err = _parse_file(text, "bigdl_tpu/serving/metrics.py")
    assert err is None
    facts = collect_file_facts(ctx)
    assert set(facts.get("finish_reasons", [])) == {
        "eos", "stop", "length", "shed", "deadline", "infeasible",
        "error", "cancelled"}
    assert extract_embedded_units(ctx) == []


# -- embedded string programs (the PR-5 blind-spot closure) -----------------

def test_embedded_program_line_mapping(tmp_path):
    """Findings inside an assigned string program report HOST-file
    lines; format placeholders are unescaped first."""
    host = tmp_path / "host.py"
    host.write_text(
        'CHILD = r"""\n'
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "x = {repo!r}\n"
        '"""\n')
    got = [(f.code, f.line) for f in analyze_paths([str(host)])]
    assert got == [("SPMD101", 3)]


def test_docstrings_and_prose_are_not_embedded_units(tmp_path):
    host = tmp_path / "host.py"
    host.write_text(
        '"""Module docstring mentioning import jax and\n'
        "from jax.experimental.shard_map import shard_map\n"
        'across several lines of prose."""\n'
        "BANNER = (\n"
        "    'no import here'\n"
        ")\n")
    assert analyze_paths([str(host)]) == []


def test_pod_projection_child_scans_clean():
    """The historical blind spot itself: pod_projection's _CHILD is now
    parsed and scanned (it routes through compat, so it must be
    clean)."""
    target = REPO / "benchmarks" / "pod_projection.py"
    assert analyze_paths([str(target)]) == []


# -- baseline hygiene: stale warning + --prune-baseline ---------------------

def _staled_tree(tmp_path):
    """A tmp tree holding a copy of the spec-spelling fixture, plus a
    baseline with its LIVE entries, one STALE entry for a deleted file
    UNDER the tree, and one entry for a file OUTSIDE the tree."""
    from bigdl_tpu.analysis import format_baseline_entry

    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "bad_spec.py").write_text(
        (FIXTURES / "bad_spec_spelling.py").read_text())
    fs = analyze_paths([str(tree)])
    assert fs
    prefix = fs[0].path.rsplit("/", 1)[0]
    lines = ["# header comment"]
    for f in fs:
        lines.append(format_baseline_entry(f))
    lines += ["# stale justification",
              f"{prefix}/deleted_file.py:SPMD102:deadbeefdead",
              "# other-tree justification",
              "elsewhere/other.py:SPMD102:feedfacefeed"]
    baseline = tmp_path / "baseline.txt"
    baseline.write_text("\n".join(lines) + "\n")
    return tree, baseline


def test_stale_baseline_warning_preserves_exit_code(tmp_path, capsys,
                                                    monkeypatch):
    monkeypatch.chdir(REPO)
    tree, baseline = _staled_tree(tmp_path)
    assert main([str(tree), "--baseline", str(baseline)]) == 0
    err = capsys.readouterr().err
    assert "1 stale baseline entry" in err and "--prune-baseline" in err


def test_prune_baseline_drops_only_covered_stale_entries(
        tmp_path, capsys, monkeypatch):
    """Pruning removes the dead entry for the deleted file UNDER the
    scanned tree (justification comment included) but must NOT touch
    live entries or entries for files the scan never covered — a
    partial scan deleting other trees' grandfathered findings would
    un-baseline them on the next full run."""
    monkeypatch.chdir(REPO)
    tree, baseline = _staled_tree(tmp_path)
    assert main([str(tree), "--baseline", str(baseline),
                 "--prune-baseline"]) == 0
    out = capsys.readouterr()
    assert "pruned 1 stale baseline entry" in out.err
    text = baseline.read_text()
    assert "deadbeefdead" not in text
    assert "# stale justification" not in text     # justification went too
    assert "# header comment" in text
    assert "elsewhere/other.py" in text            # out of scope: kept
    # every live entry survived: the scan is still fully baselined,
    # and the out-of-scope entry is not warned about
    assert main([str(tree), "--baseline", str(baseline)]) == 0
    assert "stale" not in capsys.readouterr().err


def test_partial_scan_never_prunes_other_files(tmp_path, capsys,
                                               monkeypatch):
    """The review-found regression shape: scanning file B with a
    baseline full of file A's live entries must not warn about or
    prune A's entries."""
    monkeypatch.chdir(REPO)
    tree, baseline = _staled_tree(tmp_path)
    other = tmp_path / "clean.py"
    other.write_text("X = 1\n")
    before = baseline.read_text()
    assert main([str(other), "--baseline", str(baseline),
                 "--prune-baseline"]) == 0
    err = capsys.readouterr().err
    assert "pruned 0 stale" in err
    assert baseline.read_text() == before


# -- SARIF output -----------------------------------------------------------

def test_sarif_output(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    bad = str(FIXTURES / "bad_donation.py")
    rc = main([bad, "--no-baseline", "--format", "sarif"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["version"] == "2.1.0"
    run = report["runs"][0]
    assert run["tool"]["driver"]["name"] == "bigdl-tpu-analysis"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert set(ALL_CODES) <= rule_ids
    results = run["results"]
    assert results and all(r["ruleId"] == "SPMD104" for r in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("bad_donation.py")
    assert loc["region"]["startLine"] > 0
    assert results[0]["partialFingerprints"]["bigdlAnalysis/v1"]
    # clean input -> empty results, exit 0, same schema
    rc = main([str(FIXTURES / "good_clean.py"), "--no-baseline",
               "--format", "sarif"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["runs"][0]["results"] == []


# -- the cached/parallel scan driver ----------------------------------------

def test_scan_cache_and_parallel_parity(tmp_path, monkeypatch):
    """scan() with the findings cache (cold AND warm) returns
    byte-identical findings to analyze_paths — the cache can never
    change results, only skip work.  (The FORK workers are pinned by
    the subprocess tests below — in-process pytest has jax loaded,
    which rightly disables fork.)"""
    from bigdl_tpu.analysis import scan

    monkeypatch.chdir(REPO)
    paths = ["bigdl_tpu/analysis", "bigdl_tpu/serving"]
    plain = [f.to_dict() for f in analyze_paths(paths)]
    cache = tmp_path / "cache.json"
    cold = [f.to_dict() for f in scan(paths, cache_path=str(cache))]
    warm = [f.to_dict() for f in scan(paths, cache_path=str(cache))]
    assert cold == plain and warm == plain
    assert cache.exists()


# -- the inline suppression idiom -------------------------------------------

def test_inline_suppression_idiom():
    """`# analysis: ok[: CODES]` silences a line that is legitimate
    despite matching a rule — scoped to the listed codes; unrelated
    codes on the line still fire."""
    base = "from jax.sharding import PartitionSpec as P\n"
    assert analyze_source(base + "R = P(('data',))\n", "s.py")
    assert analyze_source(
        base + "R = P(('data',))  # analysis: ok\n", "s.py") == []
    assert analyze_source(
        base + "R = P(('data',))  # analysis: ok: SPMD102\n",
        "s.py") == []
    # listing a DIFFERENT code does not suppress
    fs = analyze_source(
        base + "R = P(('data',))  # analysis: ok: SRV205\n", "s.py")
    assert [f.code for f in fs] == ["SPMD102"]


def test_scan_cache_never_pollutes_facts_across_edits(tmp_path):
    """Regression: merge_facts must not mutate per-file fact dicts that
    live inside cache entries — a polluted entry would keep replaying a
    STALE cross-module fact (e.g. a deleted step binding) and make
    cached scans diverge from --no-cache after an edit."""
    from bigdl_tpu.analysis import scan

    proj = tmp_path / "bigdl_tpu" / "serving"
    proj.mkdir(parents=True)
    (proj / "other.py").write_text("X = 1\n")
    binder = (
        "from bigdl_tpu.models.transformer import get_prefill_step\n"
        "class A:\n"
        "    def __init__(self, m):\n"
        "        self._b_fn = get_prefill_step(m, None)\n")
    (proj / "f.py").write_text(binder)
    (proj / "g.py").write_text(
        "class B:\n"
        "    def run(self, x):\n"
        "        return self._b_fn(x)\n")
    cache = tmp_path / "cache.json"
    run1 = scan([str(tmp_path)], cache_path=str(cache))
    assert [f.code for f in run1] == ["SRV201"]
    # delete the binding: the bypass callsite is no longer a step call
    (proj / "f.py").write_text("def unrelated():\n    return 0\n")
    fresh = scan([str(tmp_path)])
    cached = scan([str(tmp_path)], cache_path=str(cache))
    assert fresh == [] and cached == [], (
        [f.format() for f in cached])


def test_cli_parallel_scan_matches_library(tmp_path):
    """The fork-worker path (CLI subprocess — in-process pytest has jax
    loaded, which rightly disables fork) returns the same findings as
    the serial library API, including cross-module facts split across
    workers."""
    lib = analyze_paths([str(FIXTURES / "bad_dispatch_bypass.py"),
                         str(FIXTURES / "bad_finish_reason.py")])
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis",
         str(FIXTURES / "bad_dispatch_bypass.py"),
         str(FIXTURES / "bad_finish_reason.py"),
         "--no-baseline", "--json", "--jobs", "2",
         "--no-cache"],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    got = {(Path(f["path"]).name, f["line"], f["code"])
           for f in report["findings"]}
    want = {(Path(f.path).name, f.line, f.code) for f in lib}
    assert got == want


def test_prune_baseline_conflicts_with_no_baseline(tmp_path, capsys,
                                                   monkeypatch):
    """--no-baseline makes every entry look stale — the combination
    must be a usage error, never an empty baseline file."""
    monkeypatch.chdir(REPO)
    tree, baseline = _staled_tree(tmp_path)
    before = baseline.read_text()
    rc = main([str(tree), "--baseline", str(baseline),
               "--no-baseline", "--prune-baseline"])
    assert rc == 2
    assert "conflicts" in capsys.readouterr().err
    assert baseline.read_text() == before


def test_subset_scan_keeps_whole_repo_cache(tmp_path):
    """A subset scan must MERGE into the cache, not evict the other
    trees' entries — alternating full and subset scans would otherwise
    pay the cold cost every time."""
    import json as _json

    from bigdl_tpu.analysis import scan

    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir(), b.mkdir()
    (a / "x.py").write_text("X = 1\n")
    (b / "y.py").write_text("Y = 2\n")
    cache = tmp_path / "cache.json"
    scan([str(a), str(b)], cache_path=str(cache))
    full = set(_json.loads(cache.read_text())["files"])
    assert len(full) == 2
    scan([str(a)], cache_path=str(cache))          # subset
    assert set(_json.loads(cache.read_text())["files"]) == full


def test_cli_parallel_workers_resolve_cross_file_facts(tmp_path):
    """Fork workers over a REAL multi-file serving tree: the SRV201
    binding lives in engine.py while the stripped call site is in
    admission.py — different worker slices, so the finding only
    survives if the phase-1 fact exchange merges across workers."""
    tree = _serving_tree(tmp_path)
    src = (tree / "admission.py").read_text()
    m = next(_DISPATCH_RE.finditer(src))
    (tree / "admission.py").write_text(
        src[:m.start()] + f"{m.group(1)}(" + src[m.end():])
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis", str(tmp_path),
         "--no-baseline", "--select", "SRV201", "--json",
         "--jobs", "2", "--no-cache"],
        cwd=str(REPO), capture_output=True, text=True, timeout=180)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    assert [(Path(f["path"]).name, f["code"])
            for f in report["findings"]] == [("admission.py", "SRV201")]


# -- the ASY3xx call graph: hot-path reachability ---------------------------

def test_hotpath_annotation_and_self_method_edges():
    """`# analysis: hotpath-root` marks a root; `self.` method edges
    carry hotness; an identical method NOT reachable from any root
    stays exempt — reachability, not path glob."""
    src = (
        "class Loop:\n"
        "    def run(self):  # analysis: hotpath-root\n"
        "        return self.helper()\n"
        "    def helper(self):\n"
        "        return float(self.carry['pos'][0])\n"
        "    def cold(self):\n"
        "        return float(self.carry['pos'][0])\n")
    got = [(f.line, f.code) for f in analyze_source(src, "mini.py")]
    assert got == [(5, "ASY301")]


@pytest.mark.parametrize("cls,meth", [
    ("ServingEngine", "step"), ("Speculator", "step"),
    ("ChunkedAdmissionController", "pump")])
def test_builtin_roots_cover_the_serving_surface(cls, meth):
    """Each built-in hot-path root is picked up by (class, method)
    name with no annotation; the same body on a non-root class stays
    cold."""
    body = "        return float(self.carry['pos'][0])\n"
    hot = f"class {cls}:\n    def {meth}(self):\n{body}"
    assert [f.code for f in analyze_source(hot, "m.py")] == ["ASY301"]
    cold = f"class Unrelated:\n    def {meth}(self):\n{body}"
    assert analyze_source(cold, "m.py") == []


def test_cross_module_call_edge_resolution(tmp_path):
    """A hot root in one file reaches a readback in ANOTHER file
    through an import-qualified call edge; the helper alone (no root
    in sight) scans clean."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "rootmod.py").write_text(
        "from helper import readback\n"
        "class ServingEngine:\n"
        "    def step(self):\n"
        "        return readback(self.carry)\n")
    helper = proj / "helper.py"
    helper.write_text(
        "def readback(carry):\n"
        "    return float(carry['pos'][0])\n")
    assert analyze_paths([str(helper)]) == []
    got = [(Path(f.path).name, f.line, f.code)
           for f in analyze_paths([str(proj)])]
    assert got == [("helper.py", 2, "ASY301")]


def test_scan_cache_invalidates_on_call_edge_change(tmp_path):
    """Editing ONLY the edge-defining file must re-judge the OTHER
    file: the call-graph facts feed the cache key, so a cached scan
    after the edit matches --no-cache exactly."""
    from bigdl_tpu.analysis import scan

    proj = tmp_path / "proj"
    proj.mkdir()
    root = proj / "rootmod.py"
    root.write_text(
        "from helper import readback\n"
        "class ServingEngine:\n"
        "    def step(self):\n"
        "        return readback(self.carry)\n")
    (proj / "helper.py").write_text(
        "def readback(carry):\n"
        "    return float(carry['pos'][0])\n")
    cache = tmp_path / "cache.json"
    run1 = scan([str(proj)], cache_path=str(cache))
    assert [f.code for f in run1] == ["ASY301"]
    # drop the edge: helper is no longer reachable from any root
    root.write_text(
        "class ServingEngine:\n"
        "    def step(self):\n"
        "        return 0\n")
    fresh = scan([str(proj)])
    cached = scan([str(proj)], cache_path=str(cache))
    assert fresh == [] and cached == [], [f.format() for f in cached]


def test_cli_parallel_workers_resolve_call_graph_facts(tmp_path):
    """Fork workers split the root file and the readback file across
    slices — the finding survives only if the phase-1 fact exchange
    merges call edges and roots across workers."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "rootmod.py").write_text(
        "from helper import readback\n"
        "class ServingEngine:\n"
        "    def step(self):\n"
        "        return readback(self.carry)\n")
    (proj / "helper.py").write_text(
        "def readback(carry):\n"
        "    return float(carry['pos'][0])\n")
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis", str(proj),
         "--no-baseline", "--select", "ASY301", "--json",
         "--jobs", "2", "--no-cache"],
        cwd=str(REPO), capture_output=True, text=True, timeout=180)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    assert [(Path(f["path"]).name, f["code"])
            for f in report["findings"]] == [("helper.py", "ASY301")]


# -- the ASY acceptance census over the REAL serving tree -------------------

_FENCE_SITE_RE = re.compile(r'\bfence(_wait)?\(\s*"')


def _fence_sites_in(tree: Path):
    """(file, regex match) for every declared fence call in a serving
    tree copy (the fence module itself excluded — it IS the idiom)."""
    out = []
    for f in sorted(tree.glob("*.py")):
        if f.name == "fences.py":
            continue
        for m in _FENCE_SITE_RE.finditer(f.read_text()):
            out.append((f, m))
    return out


def test_async_census_sites_enumerated():
    """The real serving plane's declared sync points exist where we
    think: one decode readback + one verify readback + the transfer
    readback + the draft completion fence.  The five prefill
    completion fences the PR 12 worksheet marked deletable are GONE
    (cashed in — prefill dispatches overlap the decode step and the
    step's decode/verify fence absorbs their completion; their phase
    timers went with them, docs/async_readiness.md)."""
    counts = {}
    for f, m in _fence_sites_in(SERVING_DIR):
        counts[f.name] = counts.get(f.name, 0) + 1
    assert counts == {"disagg.py": 1, "engine.py": 1,
                      "speculative.py": 2}, counts


def test_async_census_every_fence_site_individually_detected(tmp_path):
    """THE ASY acceptance census: strip each declared fence in the real
    serving tree back to its raw spelling (`fence(` -> `jax.device_get(`,
    `fence_wait(` -> `jax.block_until_ready(`) in turn — each mutation
    must yield exactly ONE ASY finding at the right file, and the
    unmutated copy scans ASY-clean, so the coverage is exact, not
    vacuous."""
    tree = _serving_tree(tmp_path)
    clean = analyze_paths([str(tmp_path)], select=ASY_CODES)
    assert clean == [], [f.format() for f in clean]
    by_file = {}
    for f, m in _fence_sites_in(tree):
        by_file.setdefault(f, []).append(m)
    assert sum(len(v) for v in by_file.values()) >= 4
    for fpath, matches in by_file.items():
        src = fpath.read_text()
        for m in matches:
            paren = src.index("(", m.start())
            repl = "jax.block_until_ready(" if m.group(1) \
                else "jax.device_get("
            mutated = src[:m.start()] + repl + src[paren + 1:]
            if "import jax" not in mutated:
                # the raw spelling must RESOLVE for the census to be a
                # fair counterfactual — a file whose only jax touch was
                # the fence idiom (disagg.py) never binds the name
                mutated = "import jax\n" + mutated
            fpath.write_text(mutated)
            found = analyze_paths([str(tmp_path)], select=ASY_CODES)
            want = "ASY302" if m.group(1) else "ASY301"
            assert [f.code for f in found] == [want], (
                f"stripping fence at {fpath.name}:{m.start()} must "
                f"yield exactly one {want}, got: "
                f"{[f.format() for f in found]}")
            assert found[0].path.endswith(fpath.name)
        fpath.write_text(src)


def test_async_census_deleting_a_fence_line_flags_the_timer(tmp_path):
    """Deleting a completion fence outright (not just un-routing it)
    surfaces as ASY305 on the now-lying timer read (the draft-chain
    fence — the remaining completion wait after the prefill fences
    were cashed in)."""
    tree = _serving_tree(tmp_path)
    spec = tree / "speculative.py"
    src = spec.read_text()
    line = '        fence_wait("draft", u)\n'
    assert line in src
    spec.write_text(src.replace(line, ""))
    found = analyze_paths([str(tmp_path)], select=ASY_CODES)
    assert [f.code for f in found] == ["ASY305"], (
        [f.format() for f in found])
    assert found[0].path.endswith("speculative.py")


# -- the sync-point inventory (--report sync-points) ------------------------

def test_sync_points_report_text_and_json(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    rc = main(["bigdl_tpu/serving", "--report", "sync-points"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fence:decode" in out and "ServingEngine.step" in out
    assert "0 un-fenced finding(s)" in out

    rc = main(["bigdl_tpu/serving", "--report", "sync-points",
               "--format", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["report"] == "sync-points"
    assert rep["summary"]["findings"] == 0
    assert rep["summary"]["declared"] == 4
    kinds = {e["kind"] for e in rep["entries"]}
    assert {"fence:decode", "fence:verify", "fence_wait:draft",
            "fence:transfer"} == kinds
    # every declared site carries its root chain back to a hot root
    for e in rep["entries"]:
        assert e["chain"], e
        assert e["chain"][0].rsplit(".", 2)[-2:] in (
            ["ServingEngine", "step"], ["Speculator", "step"],
            ["ChunkedAdmissionController", "pump"],
            ["ServingEngine", "_dispatch"]), e["chain"]


def test_sync_points_report_lists_unfenced_findings(tmp_path, capsys,
                                                    monkeypatch):
    """An un-fenced readback shows up IN the inventory (classification
    = the ASY code), not just in the failing scan."""
    monkeypatch.chdir(REPO)
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "mini.py").write_text(
        "class ServingEngine:\n"
        "    def step(self):\n"
        "        return float(self.carry['pos'][0])\n")
    rc = main([str(proj), "--report", "sync-points", "--format", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["summary"]["findings"] == 1
    assert rep["entries"][0]["kind"] == "ASY301"

    # an unknown-site fence is the ASY302 violation, not a declared
    # site — it must appear exactly once, never double-counted as both
    (proj / "mini.py").write_text(
        "from bigdl_tpu.serving.fences import fence_wait\n"
        "class ServingEngine:\n"
        "    def step(self):\n"
        "        return fence_wait('warmup', self.out)\n")
    rc = main([str(proj), "--report", "sync-points", "--format", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["summary"]["declared"] == 0
    assert [e["kind"] for e in rep["entries"]] == ["ASY302"]


# -- the MH4xx lockstep census over the REAL serving tree --------------------

def test_multihost_census_real_tree_clean_and_mutations_caught(tmp_path):
    """THE MH acceptance census: the unmutated serving tree scans
    MH-clean, and stripping each machine-encoded determinism
    discipline in turn yields exactly one finding at the right file —
    clock threading (an engine-clock read becomes a raw perf_counter),
    seed derivation (the request-keyed fold_in becomes a fresh
    PRNGKey), and the lockstep dispatch guard (a divergent branch
    around a dispatch)."""
    tree = _serving_tree(tmp_path)
    clean = analyze_paths([str(tmp_path)], select=MH_CODES)
    assert clean == [], [f.format() for f in clean]

    # 1. clock threading: ONE engine-clock read per file becomes a raw
    # wall-clock read -> exactly one MH403 at that file
    for fname, spelled in [("engine.py", "self._clock()"),
                           ("disagg.py", "self._clock()"),
                           ("health.py", "self._clock()")]:
        src = (tree / fname).read_text()
        assert spelled in src, f"{fname} lost its engine-clock reads?"
        (tree / fname).write_text(
            "import time\n" + src.replace(spelled,
                                          "time.perf_counter()", 1))
        found = analyze_paths([str(tmp_path)], select=MH_CODES)
        assert [f.code for f in found] == ["MH403"], (
            f"stripping clock threading in {fname} must yield exactly "
            f"one MH403, got: {[f.format() for f in found]}")
        assert found[0].path.endswith(fname)
        (tree / fname).write_text(src)

    # 2. seed derivation: the request-keyed lane (fold_in of
    # lane_key(engine seed)) becomes a fresh ambient PRNGKey ->
    # exactly one MH404 at engine.py
    eng = tree / "engine.py"
    src = eng.read_text()
    needle = "jax.random.fold_in(lane_key(self.seed), req.req_id)"
    assert needle in src, "_lane_key moved — update the census"
    eng.write_text(src.replace(needle, "jax.random.PRNGKey(0)", 1))
    found = analyze_paths([str(tmp_path)], select=MH_CODES)
    assert [f.code for f in found] == ["MH404"], \
        [f.format() for f in found]
    assert found[0].path.endswith("engine.py")
    eng.write_text(src)

    # 3. divergent-branch dispatch: rank-gating a compiled-step
    # dispatch -> exactly one MH401 at engine.py
    eng.write_text(src + (
        "\n\ndef _divergent_probe(eng, x):\n"
        "    import jax\n"
        "    if jax.process_index() == 0:\n"
        "        return eng._dispatch(\"decode\", eng._step_fn, x)\n"
        "    return x\n"))
    found = analyze_paths([str(tmp_path)], select=MH_CODES)
    assert [f.code for f in found] == ["MH401"], \
        [f.format() for f in found]
    assert found[0].path.endswith("engine.py")
    eng.write_text(src)


def test_clock_vocabulary_extracted_from_real_declaration():
    """MH403's vocabulary comes from serving/faults.py CLOCK_SITES by
    extraction (not the built-in fallback), and names exactly the two
    shipped raw-read units."""
    from bigdl_tpu.analysis.core import _parse_file, collect_file_facts

    text = (REPO / "bigdl_tpu" / "serving" / "faults.py").read_text()
    ctx, err = _parse_file(text, "bigdl_tpu/serving/faults.py")
    assert err is None
    facts = collect_file_facts(ctx)
    assert set(facts.get("clock_sites", [])) == {
        "faults.default_clock", "metrics.ServingMetrics.on_step"}
    assert facts.get("clock_modules") == ["bigdl_tpu.serving.faults"]


def test_clock_vocabulary_extraction_beats_fallback():
    """A project-local CLOCK_SITES declaration overrides the fallback:
    its site is exempt, a fallback site is not."""
    src = (  # analysis: no-embed — deliberate violations under test
        "import time\n"
        'CLOCK_SITES = frozenset({"mini.now"})\n'
        "def now():\n"
        "    return time.perf_counter()\n"
        "def default_clock():\n"
        "    return time.perf_counter()\n"
        "def _dispatch(site, fn):\n"
        "    return fn()\n"
    )
    got = [(f.line, f.code) for f in analyze_source(src, "mini.py")]
    assert got == [(6, "MH403")]


def test_divergence_taint_cross_module_reachability(tmp_path):
    """MH401 resolves the guarded collective THROUGH the import graph:
    the collective module is clean alone, the divergent caller fires
    only when both files are in the project."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "collmod.py").write_text(
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def shard_norm(g):\n"
        "    return lax.psum(jnp.sum(g * g), 'data')\n")
    (proj / "rootmod.py").write_text(
        "import jax\n"
        "from collmod import shard_norm\n"
        "def decide(g):\n"
        "    pid = jax.process_index()\n"
        "    if pid == 0:\n"
        "        return shard_norm(g)\n"
        "    return g\n")
    assert analyze_paths([str(proj / "collmod.py")]) == []
    # caller alone: the callee's collective is invisible — documented
    # degradation of single-file runs
    assert analyze_paths([str(proj / "rootmod.py")]) == []
    got = [(Path(f.path).name, f.line, f.code)
           for f in analyze_paths([str(proj)])]
    assert got == [("rootmod.py", 5, "MH401")]


def test_scan_cache_invalidates_on_collective_fact_change(tmp_path):
    """Editing ONLY the collective-defining file must re-judge the
    divergent caller: the lockstep facts feed the cache key, so a
    cached scan after the edit matches --no-cache exactly."""
    from bigdl_tpu.analysis import scan

    proj = tmp_path / "proj"
    proj.mkdir()
    coll = proj / "collmod.py"
    coll.write_text(
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def shard_norm(g):\n"
        "    return lax.psum(jnp.sum(g * g), 'data')\n")
    (proj / "rootmod.py").write_text(
        "import jax\n"
        "from collmod import shard_norm\n"
        "def decide(g):\n"
        "    pid = jax.process_index()\n"
        "    if pid == 0:\n"
        "        return shard_norm(g)\n"
        "    return g\n")
    cache = tmp_path / "cache.json"
    run1 = scan([str(proj)], cache_path=str(cache))
    assert [f.code for f in run1] == ["MH401"]
    # the helper stops being a collective: the branch is now pure host
    coll.write_text(
        "import jax.numpy as jnp\n"
        "def shard_norm(g):\n"
        "    return jnp.sum(g * g)\n")
    fresh = scan([str(proj)])
    cached = scan([str(proj)], cache_path=str(cache))
    assert fresh == [] and cached == [], [f.format() for f in cached]


def test_cli_parallel_workers_resolve_divergence_facts(tmp_path):
    """Fork workers split the collective module and the divergent
    caller across slices — the MH401 finding survives only if the
    phase-1 fact exchange merges collective_units and call edges
    across workers."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "collmod.py").write_text(
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def shard_norm(g):\n"
        "    return lax.psum(jnp.sum(g * g), 'data')\n")
    (proj / "rootmod.py").write_text(
        "import jax\n"
        "from collmod import shard_norm\n"
        "def decide(g):\n"
        "    pid = jax.process_index()\n"
        "    if pid == 0:\n"
        "        return shard_norm(g)\n"
        "    return g\n")
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis", str(proj),
         "--no-baseline", "--select", "MH401", "--json",
         "--jobs", "2", "--no-cache"],
        cwd=str(REPO), capture_output=True, text=True, timeout=180)
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    assert [(Path(f["path"]).name, f["code"])
            for f in report["findings"]] == [("rootmod.py", "MH401")]


# -- the lockstep inventory (--report lockstep) ------------------------------

def test_lockstep_report_text_and_json(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    rc = main(["bigdl_tpu/serving", "--report", "lockstep"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 MH finding(s)" in out
    assert "2 declared clock site(s)" in out

    rc = main(["bigdl_tpu/serving", "--report", "lockstep",
               "--format", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["report"] == "lockstep"
    assert rep["summary"]["findings"] == 0
    assert rep["summary"]["clock_sites"] == 2
    # every routed _dispatch call site is an agreement point the pod
    # must execute in lockstep
    assert rep["summary"]["agreement"] >= 8
    kinds = {e["kind"] for e in rep["entries"]}
    assert "agreement:dispatch" in kinds
    assert "clock:time.perf_counter" in kinds
    # the disaggregated transfer channel's per-peer read is a recorded
    # divergence root
    assert "divergence:peer-read" in kinds


def test_lockstep_report_lists_mh_findings(tmp_path, capsys, monkeypatch):
    """An un-fixed lockstep violation shows up IN the worksheet
    (classification = the MH code), not just in the failing scan."""
    monkeypatch.chdir(REPO)
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "mini.py").write_text(
        "import jax\n"
        "from jax import lax\n"
        "def decide(g):\n"
        "    if jax.process_index() == 0:\n"
        "        return lax.psum(g, 'data')\n"
        "    return g\n")
    rc = main([str(proj), "--report", "lockstep", "--format", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert rep["summary"]["findings"] == 1
    mh = [e for e in rep["entries"] if e["kind"] == "MH401"]
    assert len(mh) == 1 and mh[0]["suggestion"]
