"""SPMD hygiene analyzer (bigdl_tpu/analysis): the tier-1 repo-wide
zero-findings gate, exact (line, code) parity against the EXPECT-marked
fixtures, the utils/compat.py no-false-positive guarantee, and the CLI
contract (exit codes, --select/--ignore, --json, baseline handling).

Pure AST — none of this traces or compiles anything, so the whole
module runs in milliseconds plus one subprocess for the `python -m`
entry point.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

import pytest

from bigdl_tpu.analysis import (
    DEFAULT_PATHS, analyze_paths, analyze_source, load_baseline, main,
    rule_codes, split_baselined,
)

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"
BASELINE = REPO / "analysis_baseline.txt"

BAD_FIXTURES = sorted(FIXTURES.glob("bad_*.py"))
ALL_CODES = ("SPMD101", "SPMD102", "SPMD103", "SPMD104", "SPMD105",
             "SPMD106")


def _expected(path: Path):
    """(line, code) pairs from the fixture's `# EXPECT: CODE` comments."""
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), start=1):
        m = re.search(r"#\s*EXPECT:\s*(SPMD\d+)", line)
        if m:
            out.add((i, m.group(1)))
    return out


# -- the tier-1 acceptance gate --------------------------------------------

def test_repo_has_zero_non_baselined_findings(monkeypatch):
    """`python -m bigdl_tpu.analysis bigdl_tpu benchmarks tests` must be
    clean: every finding either fixed or explicitly grandfathered in the
    committed baseline.  Re-introducing the PR-4 spec drift or a direct
    jax.shard_map import anywhere in those trees fails THIS test with
    the rule code and file:line."""
    monkeypatch.chdir(REPO)
    # analyze_paths silently skips nonexistent paths — guard against a
    # renamed tree turning this gate into a zero-file false green (the
    # CLI exits 2 on this; the API caller must check itself)
    for p in DEFAULT_PATHS:
        assert (REPO / p).is_dir(), f"analyzed tree missing: {p}"
    findings = analyze_paths(DEFAULT_PATHS)
    new, _ = split_baselined(findings, load_baseline(str(BASELINE)))
    assert not new, (
        "SPMD hygiene violations (fix them, or baseline each with a "
        "justification comment in analysis_baseline.txt — see "
        "docs/analysis.md):\n"
        + "\n".join(f.format() for f in new))


def test_rule_registry_is_complete():
    assert tuple(sorted(rule_codes())) == ALL_CODES


# -- fixture parity ---------------------------------------------------------

@pytest.mark.parametrize("fixture", BAD_FIXTURES,
                         ids=[p.stem for p in BAD_FIXTURES])
def test_bad_fixture_exact_findings(fixture):
    """Exact (line, code) parity with the EXPECT comments — unmarked
    lines in the bad files double as false-positive checks (static
    shape branches, getattr of unrelated attrs, legit multi-axis tuple
    specs, carry rebinding...)."""
    expected = _expected(fixture)
    assert expected, f"{fixture} has no EXPECT annotations"
    got = {(f.line, f.code) for f in analyze_paths([str(fixture)])}
    assert got == expected, (
        f"missing: {sorted(expected - got)}; "
        f"spurious: {sorted(got - expected)}")


def test_good_fixture_is_clean():
    assert analyze_paths([str(FIXTURES / "good_clean.py")]) == []


def test_compat_module_itself_is_clean():
    """utils/compat.py is the one module allowed to spell the moved APIs
    directly — the analyzer must not flag its own shim."""
    compat = REPO / "bigdl_tpu" / "utils" / "compat.py"
    assert analyze_paths([str(compat)]) == []


def test_compat_rule_fires_on_compat_body_elsewhere(tmp_path):
    """The compat exemption is PATH-based, not content-based: the same
    probes outside utils/compat.py are flagged."""
    clone = tmp_path / "not_compat.py"
    clone.write_text((REPO / "bigdl_tpu" / "utils"
                      / "compat.py").read_text())
    assert any(f.code == "SPMD101" for f in analyze_paths([str(clone)]))


def test_fixture_dir_excluded_from_tree_scans():
    """Repo-wide scans must skip analysis_fixtures/ (deliberate
    violations) while explicit file paths still reach inside."""
    findings = analyze_paths([str(FIXTURES.parent)],
                             select=["SPMD102"])
    assert not any("analysis_fixtures" in f.path for f in findings)


# -- acceptance: re-introducing the historical bugs ------------------------

def test_reintroduced_pr4_spec_drift_is_caught(tmp_path):
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "ROWS = P(('data',))\n"
    )
    fs = analyze_source(src, "drifted.py")
    assert [(f.code, f.line) for f in fs] == [("SPMD102", 2)]


def test_duplicate_lines_get_distinct_fingerprints():
    """Baselining one occurrence of a drifted line must not silence a
    second paste of the identical line — fingerprints are occurrence-
    indexed."""
    src = (
        "from jax.sharding import PartitionSpec as P\n"
        "SPECS = [\n"
        "    P(('data',)),\n"
        "    P(('data',)),\n"
        "]\n"
    )
    fs = analyze_source(src, "dup.py")
    assert [f.code for f in fs] == ["SPMD102", "SPMD102"]
    assert fs[0].source == fs[1].source
    assert fs[0].fingerprint() != fs[1].fingerprint()
    new, old = split_baselined(fs, {fs[0].baseline_key()})
    assert [f.line for f in old] == [3] and [f.line for f in new] == [4]


def test_reintroduced_direct_shard_map_import_is_caught():
    fs = analyze_source(
        "from jax.experimental.shard_map import shard_map\n", "bad.py")
    assert [(f.code, f.line) for f in fs] == [("SPMD101", 1)]


# -- CLI contract -----------------------------------------------------------

def test_cli_exit_codes_and_select(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    bad = str(FIXTURES / "bad_spec_spelling.py")

    assert main([bad, "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "SPMD102" in out and "bad_spec_spelling.py:19" in out

    # selecting a rule the file does not violate -> clean, exit 0
    assert main([bad, "--no-baseline", "--select", "SPMD104"]) == 0
    # ignoring the violated rule -> clean
    assert main([bad, "--no-baseline", "--ignore", "SPMD102"]) == 0
    capsys.readouterr()
    # unknown code -> usage error
    assert main([bad, "--select", "SPMD999"]) == 2
    # a typo'd / wrong-cwd path must be a usage error, never a false
    # green from scanning zero files
    assert main(["no_such_tree"]) == 2


def test_cli_json_report(capsys, monkeypatch):
    monkeypatch.chdir(REPO)
    rc = main([str(FIXTURES / "bad_donation.py"), "--no-baseline",
               "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["summary"]["new"] == 4
    assert {f["code"] for f in report["findings"]} == {"SPMD104"}
    assert all(f["fingerprint"] for f in report["findings"])

    rc = main([str(FIXTURES / "good_clean.py"), "--no-baseline", "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["summary"] == {
        "new": 0, "baselined": 0, "total": 0}


def test_cli_baseline_roundtrip(tmp_path, capsys, monkeypatch):
    """--write-baseline output, committed as the baseline, silences
    exactly the current findings (and ONLY those: the fingerprint is
    content-addressed, so editing the offending line re-flags it)."""
    monkeypatch.chdir(REPO)
    bad = str(FIXTURES / "bad_tracer_leak.py")
    assert main([bad, "--write-baseline"]) == 0
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(capsys.readouterr().out)

    assert main([bad, "--baseline", str(baseline)]) == 0
    assert "baselined" in capsys.readouterr().out

    # a NEW violation in the same file is not covered by the baseline
    drifted = tmp_path / "drifted_copy.py"
    drifted.write_text(Path(bad).read_text()
                       + "\n\nimport jax\nsm = jax.shard_map\n")
    assert main([str(drifted), "--baseline", str(baseline)]) == 1


def test_module_entrypoint_subprocess():
    """The `python -m bigdl_tpu.analysis` contract CI rides on: nonzero
    on findings, zero on clean, works from the repo root."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis",
         str(FIXTURES / "bad_compat_drift.py"), "--no-baseline",
         "--quiet"],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stderr
    assert "SPMD101" in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis", "--list-rules"],
        cwd=str(REPO), capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    for code in ALL_CODES:
        assert code in proc.stdout
