"""Conv-BN-residual convergence parity — round-2 verdict item #2.

The LeNet parity harness (test_accuracy_parity.py) validates the training
loop but exercises none of the components the ResNet top-1 contract
stresses: BatchNormalization running/batch statistics, residual blocks,
MSRA init, zero-gamma, weight decay and a Step LR schedule. Here a
CIFAR-shape ResNet-8 (models/resnet.py ``_resnet_cifar``, shortcut type A
so every learnable layer maps 1:1 onto torch) trains multi-epoch through
the REAL pickle-batch reader with SGD + momentum + weight decay + Step LR,
must clear a fixed Top-1 bar, and an architecturally identical torch model
fed the same init and the same batch stream must land within a documented
tolerance — a convergence-level check that BN momentum/eps semantics,
schedule indexing and decay coupling all match.

Reference (UNVERIFIED, SURVEY.md §0): ``models/resnet/TrainCIFAR10.scala``,
``nn/SpatialBatchNormalization.scala``.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.integration

BATCH = 64
EPOCHS = 10
N_TRAIN = 1280
STEPS = EPOCHS * N_TRAIN // BATCH   # 200
LR = 0.1
MOMENTUM = 0.9
WEIGHT_DECAY = 5e-4
STEP_SIZE, GAMMA = 150, 0.2
ACCURACY_BAR = 0.90   # convergence contract
PARITY_TOL = 0.04     # |jax - torch| final Top-1 (noise=180 lands the jax
                      # side at ~0.97 — below the 1.0 saturation that would
                      # make cross-framework parity vacuous)


@pytest.fixture(scope="module")
def cifar_dir(tmp_path_factory):
    from bigdl_tpu.dataset.cifar import generate_batch_dataset

    d = tmp_path_factory.mktemp("cifar_batches")
    generate_batch_dataset(str(d), n_train=N_TRAIN, n_test=512, seed=5,
                           noise=180.0)
    return str(d)


def _batches(cifar_dir, n_batches, n_train=N_TRAIN, batch=BATCH):
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.cifar import TRAIN_MEAN, TRAIN_STD, load_samples
    from bigdl_tpu.dataset.image import BGRImgNormalizer

    samples = load_samples(cifar_dir, "train", synthetic_fallback=False)
    assert len(samples) == n_train
    ds = (DataSet.array(samples, seed=13)
          .transform(BGRImgNormalizer(TRAIN_MEAN, TRAIN_STD))
          .transform(SampleToMiniBatch(batch)))
    it = ds.data(train=True)
    return [next(it) for _ in range(n_batches)]


def _val_arrays(cifar_dir):
    from bigdl_tpu.dataset.cifar import TRAIN_MEAN, TRAIN_STD, load_samples
    from bigdl_tpu.dataset.image import BGRImgNormalizer

    samples = load_samples(cifar_dir, "test", synthetic_fallback=False)
    norm = BGRImgNormalizer(TRAIN_MEAN, TRAIN_STD)
    xs = np.stack([np.asarray(s.feature()) for s in norm(iter(samples))])
    ys = np.array([int(s.label()) for s in samples], np.int64)  # 1-based
    return xs.astype(np.float32), ys


def _weighted_in_topo_order(graph):
    """(module, params-dict) for every parameterized module, in graph topo
    order with Sequentials expanded — the deterministic order the torch
    mirror is built in."""
    from bigdl_tpu.nn.tpu_fusion import _expand, _tree_get

    pnodes, _, _ = _expand(graph)
    out = []
    seen = set()
    for p in pnodes:
        if not p.path:  # input placeholder: path () resolves to the root
            continue
        sub = _tree_get(graph.params, p.path)
        if isinstance(sub, dict) and sub and id(p.module) not in seen:
            seen.add(id(p.module))
            out.append((p.module, sub))
    return out


def _torch_resnet_cifar(n_blocks: int = 1):
    """torch mirror of ``_resnet_cifar(10, depth=6n+2, shortcut A,
    zero_gamma)`` — layer order matches graph topo order. ``n_blocks`` is
    the per-stage block count n (depth 8 -> 1, depth 20 -> 3)."""
    import torch
    import torch.nn as tnn
    import torch.nn.functional as F

    class PadShortcut(tnn.Module):
        def __init__(self, n_in, n_out, stride):
            super().__init__()
            self.stride, self.pad = stride, n_out - n_in

        def forward(self, x):
            x = x[:, :, ::self.stride, ::self.stride]
            return F.pad(x, (0, 0, 0, 0, 0, self.pad))

    class Block(tnn.Module):
        def __init__(self, n_in, planes, stride):
            super().__init__()
            self.conv1 = tnn.Conv2d(n_in, planes, 3, stride, 1, bias=False)
            self.bn1 = tnn.BatchNorm2d(planes)
            self.conv2 = tnn.Conv2d(planes, planes, 3, 1, 1, bias=False)
            self.bn2 = tnn.BatchNorm2d(planes)
            self.short = (PadShortcut(n_in, planes, stride)
                          if (stride != 1 or n_in != planes) else None)

        def forward(self, x):
            r = self.bn2(self.conv2(F.relu(self.bn1(self.conv1(x)))))
            s = x if self.short is None else self.short(x)
            return F.relu(r + s)

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.conv0 = tnn.Conv2d(3, 16, 3, 1, 1, bias=False)
            self.bn0 = tnn.BatchNorm2d(16)
            blocks = []
            n_in = 16
            for stage, planes in enumerate((16, 32, 64)):
                for i in range(n_blocks):
                    stride = 2 if (stage > 0 and i == 0) else 1
                    blocks.append(Block(n_in, planes, stride))
                    n_in = planes
            self.blocks = tnn.ModuleList(blocks)
            self.fc = tnn.Linear(64, 10)

        def forward(self, x):
            x = torch.relu(self.bn0(self.conv0(x)))
            for b in self.blocks:
                x = b(x)
            x = x.mean(dim=(2, 3))
            return torch.log_softmax(self.fc(x), dim=1)

        def weighted_modules(self):
            mods = [self.conv0, self.bn0]
            for b in self.blocks:
                mods += [b.conv1, b.bn1, b.conv2, b.bn2]
            mods.append(self.fc)
            return mods

    return Net()


def _torch_resnet8():
    return _torch_resnet_cifar(1)


def test_resnet_convergence_and_torch_parity(cifar_dir):
    import torch
    import torch.nn as tnn

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.evaluator import Evaluator
    from bigdl_tpu.optim.optim_method import Step
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(17)
    model = ResNet(10, {"depth": 8, "shortcutType": "A",
                        "dataSet": "cifar10"})
    model._ensure_params()
    weighted = _weighted_in_topo_order(model)
    kinds = [type(m).__name__ for m, _ in weighted]
    # stem conv+bn, 3 blocks of (conv,bn,conv,bn), final linear
    assert kinds == (["SpatialConvolution", "SpatialBatchNormalization"]
                     + ["SpatialConvolution", "SpatialBatchNormalization"] * 6
                     + ["Linear"]), kinds
    init_np = [{k: np.array(v) for k, v in sub.items()}
               for _, sub in weighted]

    batches = _batches(cifar_dir, STEPS)

    # --- bigdl_tpu: real Optimizer over the real reader stream ----------
    opt = Optimizer(model=model, dataset=DataSet.array(batches),
                    criterion=ClassNLLCriterion(),
                    end_trigger=Trigger.max_iteration(STEPS))
    opt.set_optim_method(SGD(learning_rate=LR, momentum=MOMENTUM,
                             weight_decay=WEIGHT_DECAY,
                             learning_rate_schedule=Step(STEP_SIZE, GAMMA)))
    trained = opt.optimize()

    xs, ys = _val_arrays(cifar_dir)
    res = Evaluator(trained).test(list(_as_minibatches(xs, ys)),
                                  [Top1Accuracy()], BATCH)[0]
    jax_acc, n_scored = res.result()
    assert n_scored == len(ys)
    assert jax_acc >= ACCURACY_BAR, f"Top-1 {jax_acc:.4f} < {ACCURACY_BAR}"

    # running stats actually moved (BN train-mode bookkeeping is live)
    rm = [np.array(v["running_mean"])
          for v in _iter_state_leaves(trained.state)]
    assert rm and any(np.abs(x).max() > 1e-3 for x in rm)

    # --- torch: identical arch/init/batches/schedule ---------------------
    tmodel = _torch_resnet8()
    tmods = tmodel.weighted_modules()
    with torch.no_grad():
        for tm, ours in zip(tmods, init_np):
            tm.weight.copy_(torch.from_numpy(ours["weight"]))
            if isinstance(tm, tnn.Linear) or isinstance(
                    tm, tnn.BatchNorm2d):
                tm.bias.copy_(torch.from_numpy(ours["bias"]))
    # zero-gamma check transferred: each block's bn2 starts at γ=0
    assert float(tmodel.blocks[0].bn2.weight.detach().abs().max()) == 0.0

    topt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=MOMENTUM,
                           weight_decay=WEIGHT_DECAY)
    lossf = tnn.NLLLoss()
    it_ds = DataSet.array(batches).data(train=True)
    tmodel.train()
    for it in range(STEPS):
        b = next(it_ds)
        for g in topt.param_groups:
            g["lr"] = LR * GAMMA ** (it // STEP_SIZE)
        x = torch.from_numpy(np.asarray(b.get_input()))
        y = torch.from_numpy(np.asarray(b.get_target()).astype(np.int64) - 1)
        topt.zero_grad()
        lossf(tmodel(x), y).backward()
        topt.step()

    tmodel.eval()
    with torch.no_grad():
        pred = tmodel(torch.from_numpy(xs)).argmax(1).numpy()
    torch_acc = float((pred == ys - 1).mean())
    assert torch_acc >= ACCURACY_BAR, f"torch Top-1 {torch_acc:.4f}"

    assert abs(jax_acc - torch_acc) <= PARITY_TOL, (
        f"final Top-1 parity broken: jax {jax_acc:.4f} vs "
        f"torch {torch_acc:.4f} (tol {PARITY_TOL})")


def _iter_state_leaves(state):
    if isinstance(state, dict):
        if "running_mean" in state:
            yield state
        else:
            for v in state.values():
                yield from _iter_state_leaves(v)


def _as_minibatches(xs, ys, batch=BATCH):
    from bigdl_tpu.dataset.sample import MiniBatch

    for i in range(0, len(xs), batch):
        yield MiniBatch(xs[i:i + batch], ys[i:i + batch].astype(np.float32))
