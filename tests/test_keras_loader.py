"""Keras-1.2 import (reference pyspark ``Model.load_keras`` +
``bigdl/keras`` converter; SURVEY §4 keras-compat harness). JSON configs
and HDF5 weight files are hand-written in the keras1 on-disk layout — no
keras/TF execution anywhere."""

import json

import numpy as np
import pytest

from bigdl_tpu.utils.keras_loader import load_keras, load_keras_json

torch = pytest.importorskip("torch")
h5py = pytest.importorskip("h5py")


def _write_h5(path, layers):
    """keras1 layout: root attr layer_names; per-layer group with
    weight_names."""
    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = [n.encode() for n, _ in layers]
        for name, arrays in layers:
            g = f.create_group(name)
            wnames = [f"{name}_p{i}".encode()
                      for i in range(len(arrays))]
            g.attrs["weight_names"] = wnames
            for wn, arr in zip(wnames, arrays):
                g[wn.decode()] = arr


def _seq_json(layer_entries):
    return json.dumps({"class_name": "Sequential",
                       "config": layer_entries})


def test_dense_mlp_weights_forward_parity(tmp_path):
    rs = np.random.RandomState(0)
    w1 = rs.randn(4, 3).astype(np.float32)   # keras kernel (in, out)
    b1 = rs.randn(3).astype(np.float32)
    w2 = rs.randn(3, 2).astype(np.float32)
    b2 = rs.randn(2).astype(np.float32)

    js = _seq_json([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 3,
                    "activation": "relu",
                    "batch_input_shape": [None, 4]}},
        {"class_name": "Dense",
         "config": {"name": "dense_2", "output_dim": 2,
                    "activation": "linear"}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("dense_1", [w1, b1]),
                                  ("dense_2", [w2, b2])])

    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))
    x = rs.randn(5, 4).astype(np.float32)
    got = np.asarray(model.forward(x))
    want = np.maximum(x @ w1 + b1, 0.0) @ w2 + b2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_conv_th_weights_vs_torch(tmp_path):
    rs = np.random.RandomState(1)
    k = rs.randn(2, 1, 3, 3).astype(np.float32)   # th kernel = OIHW
    b = rs.randn(2).astype(np.float32)
    js = _seq_json([
        {"class_name": "Convolution2D",
         "config": {"name": "conv_1", "nb_filter": 2, "nb_row": 3,
                    "nb_col": 3, "dim_ordering": "th",
                    "border_mode": "valid", "activation": "linear",
                    "batch_input_shape": [None, 1, 5, 5]}},
        {"class_name": "Flatten", "config": {"name": "flat_1"}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("conv_1", [k, b])])
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    x = rs.randn(2, 1, 5, 5).astype(np.float32)
    got = np.asarray(model.forward(x))
    want = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(k),
        torch.from_numpy(b)).numpy().reshape(2, -1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv_tf_ordering_transposed_to_chw(tmp_path):
    """A tf-dim_ordering config must produce the SAME model as its th
    twin: shapes go to CHW, kernels transpose (r,c,in,out)->OIHW."""
    rs = np.random.RandomState(2)
    k_oihw = rs.randn(2, 1, 3, 3).astype(np.float32)
    k_tf = np.transpose(k_oihw, (2, 3, 1, 0))     # (r, c, in, out)
    b = rs.randn(2).astype(np.float32)

    def build(ordering, kernel, shape):
        js = _seq_json([
            {"class_name": "Convolution2D",
             "config": {"name": "conv_1", "nb_filter": 2, "nb_row": 3,
                        "nb_col": 3, "dim_ordering": ordering,
                        "border_mode": "valid", "activation": "linear",
                        "batch_input_shape": shape}},
        ])
        p = tmp_path / f"{ordering}.json"
        p.write_text(js)
        _write_h5(tmp_path / f"{ordering}.h5", [("conv_1", [kernel, b])])
        return load_keras(str(p), str(tmp_path / f"{ordering}.h5"))

    th = build("th", k_oihw, [None, 1, 5, 5])
    tf_ = build("tf", k_tf, [None, 5, 5, 1])
    x = rs.randn(2, 1, 5, 5).astype(np.float32)   # both models eat CHW
    np.testing.assert_allclose(np.asarray(th.forward(x)),
                               np.asarray(tf_.forward(x)),
                               rtol=1e-5, atol=1e-5)


def test_tf_ordering_dense_after_flatten_permuted(tmp_path):
    """The th/tf conversion pitfall: a tf-ordered save's first
    post-Flatten Dense kernel has rows in HWC-flat order, but the built
    model (converted to CHW) flattens CHW — the importer must permute the
    kernel rows so the th and tf twins predict identically."""
    rs = np.random.RandomState(11)
    C, H, W, out = 2, 3, 3, 4              # conv output: (2, 3, 3)
    k_oihw = rs.randn(C, 1, 3, 3).astype(np.float32)
    k_tf = np.transpose(k_oihw, (2, 3, 1, 0))
    b = rs.randn(C).astype(np.float32)
    w_th = rs.randn(C * H * W, out).astype(np.float32)   # rows CHW-flat
    # the tf twin's kernel rows are the SAME weights in HWC-flat order
    w_tf = np.transpose(w_th.reshape(C, H, W, out),
                        (1, 2, 0, 3)).reshape(C * H * W, out)
    bd = rs.randn(out).astype(np.float32)

    def build(ordering, kernel, shape, dense_w):
        js = _seq_json([
            {"class_name": "Convolution2D",
             "config": {"name": "conv_1", "nb_filter": C, "nb_row": 3,
                        "nb_col": 3, "dim_ordering": ordering,
                        "border_mode": "valid", "activation": "relu",
                        "batch_input_shape": shape}},
            {"class_name": "Flatten", "config": {"name": "flat_1"}},
            {"class_name": "Dense",
             "config": {"name": "dense_1", "output_dim": out,
                        "activation": "linear"}},
        ])
        p = tmp_path / f"{ordering}d.json"
        p.write_text(js)
        _write_h5(tmp_path / f"{ordering}d.h5",
                  [("conv_1", [kernel, b]), ("dense_1", [dense_w, bd])])
        return load_keras(str(p), str(tmp_path / f"{ordering}d.h5"))

    th = build("th", k_oihw, [None, 1, 5, 5], w_th)
    tf_ = build("tf", k_tf, [None, 5, 5, 1], w_tf)
    x = rs.randn(2, 1, 5, 5).astype(np.float32)
    np.testing.assert_allclose(np.asarray(th.forward(x)),
                               np.asarray(tf_.forward(x)),
                               rtol=1e-5, atol=1e-5)


def test_batchnorm_weights_and_running_stats(tmp_path):
    rs = np.random.RandomState(3)
    gamma = rs.rand(4).astype(np.float32) + 0.5
    beta = rs.randn(4).astype(np.float32)
    mean = rs.randn(4).astype(np.float32)
    var = rs.rand(4).astype(np.float32) + 0.5    # keras1 "running_std"
    js = _seq_json([
        {"class_name": "BatchNormalization",
         "config": {"name": "bn_1", "epsilon": 1e-3, "mode": 0,
                    "batch_input_shape": [None, 4]}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("bn_1", [gamma, beta, mean, var])])
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))
    model.evaluate()

    x = rs.randn(6, 4).astype(np.float32)
    got = np.asarray(model.forward(x))
    want = (x - mean) / np.sqrt(var + 1e-3) * gamma + beta
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_embedding_weights(tmp_path):
    rs = np.random.RandomState(4)
    table = rs.randn(10, 3).astype(np.float32)
    js = _seq_json([
        {"class_name": "Embedding",
         "config": {"name": "emb_1", "input_dim": 10, "output_dim": 3,
                    "batch_input_shape": [None, 5]}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("emb_1", [table])])
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    ids = np.array([[0, 1, 2, 9, 3]], np.int32)   # keras ids are 0-based
    got = np.asarray(model.forward(ids))
    np.testing.assert_allclose(got[0], table[ids[0]], rtol=1e-6)


def test_functional_model_with_merge():
    js = json.dumps({
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1",
                            "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "dense_1",
                 "config": {"name": "dense_1", "output_dim": 3,
                            "activation": "relu"},
                 "inbound_nodes": [[["input_1", 0, 0]]]},
                {"class_name": "Dense", "name": "dense_2",
                 "config": {"name": "dense_2", "output_dim": 3,
                            "activation": "tanh"},
                 "inbound_nodes": [[["input_1", 0, 0]]]},
                {"class_name": "Merge", "name": "merge_1",
                 "config": {"name": "merge_1", "mode": "concat",
                            "concat_axis": -1},
                 "inbound_nodes": [[["dense_1", 0, 0],
                                    ["dense_2", 0, 0]]]},
            ],
            "input_layers": [["input_1", 0, 0]],
            "output_layers": [["merge_1", 0, 0]],
        },
    })
    model = load_keras_json(js)
    out = np.asarray(model.forward(np.ones((2, 4), np.float32)))
    assert out.shape == (2, 6)
    # relu half is >= 0; tanh half is in [-1, 1]
    assert out[:, :3].min() >= 0.0
    assert np.all(np.abs(out[:, 3:]) <= 1.0)


def test_unsupported_layer_and_weights_errors(tmp_path):
    with pytest.raises(ValueError, match="SomeExotic"):
        load_keras_json(_seq_json([
            {"class_name": "SomeExotic",
             "config": {"name": "x", "batch_input_shape": [None, 4]}}]))

    js = _seq_json([
        {"class_name": "LSTM",
         "config": {"name": "lstm_1", "output_dim": 3,
                    "return_sequences": False,
                    "batch_input_shape": [None, 7, 4]}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5",
              [("lstm_1", [np.zeros((4, 3), np.float32)] * 12)])
    # architecture alone builds fine
    m = load_keras_json(js)
    assert np.asarray(m.forward(np.zeros((1, 7, 4), np.float32))).shape \
        == (1, 3)
    with pytest.raises(NotImplementedError, match="LSTM"):
        load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))


def test_mismatched_json_h5_pair_raises(tmp_path):
    js = _seq_json([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 3,
                    "activation": "linear",
                    "batch_input_shape": [None, 4]}},
    ])
    (tmp_path / "m.json").write_text(js)
    # h5 written from a model with different auto-names
    _write_h5(tmp_path / "m.h5",
              [("dense_7", [np.zeros((4, 3), np.float32),
                            np.zeros(3, np.float32)])])
    with pytest.raises(ValueError, match="does not match"):
        load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))


def _keras1_lstm_h5(path, names, gate_list_order="icfo"):
    """keras1 LSTM group, weight list in keras1's own odd ordering."""
    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = [b"lstm_1"]
        g = f.create_group("lstm_1")
        wn = []
        for gate in gate_list_order:
            for kind in ("W", "U", "b"):
                n = f"lstm_1_{kind}_{gate}"
                wn.append(n.encode())
                g[n] = names[f"{kind}_{gate}"]
        g.attrs["weight_names"] = wn


def test_lstm_weight_import_vs_manual_keras1_math(tmp_path):
    """Gate identity comes from the weight NAMES (keras1 lists i,c,f,o —
    not our fused i,f,g,o layout); the imported model must reproduce the
    standard LSTM recurrence exactly."""
    rs = np.random.RandomState(7)
    I, H, T = 3, 4, 5
    names = {}
    for g in "ifco":
        names[f"W_{g}"] = rs.randn(I, H).astype(np.float32) * 0.3
        names[f"U_{g}"] = rs.randn(H, H).astype(np.float32) * 0.3
        names[f"b_{g}"] = rs.randn(H).astype(np.float32) * 0.1
    js = _seq_json([
        {"class_name": "LSTM",
         "config": {"name": "lstm_1", "output_dim": H,
                    "return_sequences": False,
                    "batch_input_shape": [None, T, I]}}])
    (tmp_path / "m.json").write_text(js)
    _keras1_lstm_h5(tmp_path / "m.h5", names)
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    x = rs.randn(2, T, I).astype(np.float32)
    got = np.asarray(model.forward(x))

    def sig(v):
        return 1 / (1 + np.exp(-v))

    h = np.zeros((2, H), np.float32)
    c = np.zeros((2, H), np.float32)
    for t in range(T):
        xt = x[:, t]
        i_ = sig(xt @ names["W_i"] + h @ names["U_i"] + names["b_i"])
        f_ = sig(xt @ names["W_f"] + h @ names["U_f"] + names["b_f"])
        g_ = np.tanh(xt @ names["W_c"] + h @ names["U_c"] + names["b_c"])
        o_ = sig(xt @ names["W_o"] + h @ names["U_o"] + names["b_o"])
        c = f_ * c + i_ * g_
        h = o_ * np.tanh(c)
    np.testing.assert_allclose(got, h, rtol=1e-4, atol=1e-5)


def test_simple_rnn_weight_import(tmp_path):
    rs = np.random.RandomState(8)
    I, H, T = 3, 4, 4
    W = rs.randn(I, H).astype(np.float32) * 0.4
    U = rs.randn(H, H).astype(np.float32) * 0.4
    b = rs.randn(H).astype(np.float32) * 0.1
    js = _seq_json([
        {"class_name": "SimpleRNN",
         "config": {"name": "rnn_1", "output_dim": H,
                    "return_sequences": False,
                    "batch_input_shape": [None, T, I]}}])
    (tmp_path / "m.json").write_text(js)
    with h5py.File(tmp_path / "m.h5", "w") as f:
        f.attrs["layer_names"] = [b"rnn_1"]
        g = f.create_group("rnn_1")
        g.attrs["weight_names"] = [b"rnn_1_W", b"rnn_1_U", b"rnn_1_b"]
        g["rnn_1_W"], g["rnn_1_U"], g["rnn_1_b"] = W, U, b
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    x = rs.randn(2, T, I).astype(np.float32)
    got = np.asarray(model.forward(x))
    h = np.zeros((2, H), np.float32)
    for t in range(T):
        h = np.tanh(x[:, t] @ W + h @ U + b)
    np.testing.assert_allclose(got, h, rtol=1e-4, atol=1e-5)


def test_gru_weight_import_vs_manual_keras1_math(tmp_path):
    """keras1 GRU applies the reset gate BEFORE the candidate's recurrent
    matmul (h~ = tanh(W_h x + U_h (r*h) + b_h)); the keras-compat GRU
    layer runs that exact cell (recurrent.GRU reset_after=False), so the
    named-gate weight import must reproduce the recurrence exactly."""
    rs = np.random.RandomState(9)
    I, H, T = 3, 4, 5
    names = {}
    for g in "zrh":
        names[f"W_{g}"] = rs.randn(I, H).astype(np.float32) * 0.3
        names[f"U_{g}"] = rs.randn(H, H).astype(np.float32) * 0.3
        names[f"b_{g}"] = rs.randn(H).astype(np.float32) * 0.1
    js = _seq_json([
        {"class_name": "GRU",
         "config": {"name": "gru_1", "output_dim": H,
                    "return_sequences": False,
                    "batch_input_shape": [None, T, I]}}])
    (tmp_path / "m.json").write_text(js)
    with h5py.File(tmp_path / "m.h5", "w") as f:
        f.attrs["layer_names"] = [b"gru_1"]
        g = f.create_group("gru_1")
        wn = []
        for gate in "zrh":                    # keras1's own list order
            for kind in ("W", "U", "b"):
                n = f"gru_1_{kind}_{gate}"
                wn.append(n.encode())
                g[n] = names[f"{kind}_{gate}"]
        g.attrs["weight_names"] = wn
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    x = rs.randn(2, T, I).astype(np.float32)
    got = np.asarray(model.forward(x))

    def sig(v):
        return 1 / (1 + np.exp(-v))

    h = np.zeros((2, H), np.float32)
    for t in range(T):
        xt = x[:, t]
        z_ = sig(xt @ names["W_z"] + h @ names["U_z"] + names["b_z"])
        r_ = sig(xt @ names["W_r"] + h @ names["U_r"] + names["b_r"])
        hh = np.tanh(xt @ names["W_h"] + (r_ * h) @ names["U_h"]
                     + names["b_h"])
        h = (1 - z_) * hh + z_ * h
    np.testing.assert_allclose(got, h, rtol=1e-4, atol=1e-5)


def test_gru_unrecognized_gate_names_rejected(tmp_path):
    """GRU arrays whose names don't carry the keras1 gate suffixes must
    refuse (gate identity cannot be guessed from list position)."""
    js = _seq_json([
        {"class_name": "GRU",
         "config": {"name": "gru_1", "output_dim": 3,
                    "return_sequences": False,
                    "batch_input_shape": [None, 4, 2]}}])
    (tmp_path / "m.json").write_text(js)
    with h5py.File(tmp_path / "m.h5", "w") as f:
        f.attrs["layer_names"] = [b"gru_1"]
        g = f.create_group("gru_1")
        g.attrs["weight_names"] = [b"gru_1_param_0"]
        g["gru_1_param_0"] = np.zeros((2, 3), np.float32)
    with pytest.raises(NotImplementedError, match="gates"):
        load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))


def test_orphan_weight_key_rejected(tmp_path):
    """An h5 bias for a bias-free json Dense must raise, not silently
    load a key the layer never reads."""
    js = _seq_json([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 3, "bias": False,
                    "activation": "linear",
                    "batch_input_shape": [None, 4]}}])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5",
              [("dense_1", [np.zeros((4, 3), np.float32),
                            np.full(3, 100.0, np.float32)])])
    with pytest.raises(ValueError, match="does not match"):
        load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))


def test_shared_functional_layer_rejected():
    js = json.dumps({
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1",
                            "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "InputLayer", "name": "input_2",
                 "config": {"name": "input_2",
                            "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "dense_1",
                 "config": {"name": "dense_1", "output_dim": 3,
                            "activation": "linear"},
                 "inbound_nodes": [[["input_1", 0, 0]],
                                   [["input_2", 0, 0]]]},
            ],
            "input_layers": [["input_1", 0, 0], ["input_2", 0, 0]],
            "output_layers": [["dense_1", 1, 0]],
        },
    })
    with pytest.raises(ValueError, match="shared keras layer"):
        load_keras_json(js)
