"""Keras-1.2 import (reference pyspark ``Model.load_keras`` +
``bigdl/keras`` converter; SURVEY §4 keras-compat harness). JSON configs
and HDF5 weight files are hand-written in the keras1 on-disk layout — no
keras/TF execution anywhere."""

import json

import numpy as np
import pytest

from bigdl_tpu.utils.keras_loader import load_keras, load_keras_json

torch = pytest.importorskip("torch")
h5py = pytest.importorskip("h5py")


def _write_h5(path, layers):
    """keras1 layout: root attr layer_names; per-layer group with
    weight_names."""
    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = [n.encode() for n, _ in layers]
        for name, arrays in layers:
            g = f.create_group(name)
            wnames = [f"{name}_p{i}".encode()
                      for i in range(len(arrays))]
            g.attrs["weight_names"] = wnames
            for wn, arr in zip(wnames, arrays):
                g[wn.decode()] = arr


def _seq_json(layer_entries):
    return json.dumps({"class_name": "Sequential",
                       "config": layer_entries})


def test_dense_mlp_weights_forward_parity(tmp_path):
    rs = np.random.RandomState(0)
    w1 = rs.randn(4, 3).astype(np.float32)   # keras kernel (in, out)
    b1 = rs.randn(3).astype(np.float32)
    w2 = rs.randn(3, 2).astype(np.float32)
    b2 = rs.randn(2).astype(np.float32)

    js = _seq_json([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 3,
                    "activation": "relu",
                    "batch_input_shape": [None, 4]}},
        {"class_name": "Dense",
         "config": {"name": "dense_2", "output_dim": 2,
                    "activation": "linear"}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("dense_1", [w1, b1]),
                                  ("dense_2", [w2, b2])])

    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))
    x = rs.randn(5, 4).astype(np.float32)
    got = np.asarray(model.forward(x))
    want = np.maximum(x @ w1 + b1, 0.0) @ w2 + b2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_conv_th_weights_vs_torch(tmp_path):
    rs = np.random.RandomState(1)
    k = rs.randn(2, 1, 3, 3).astype(np.float32)   # th kernel = OIHW
    b = rs.randn(2).astype(np.float32)
    js = _seq_json([
        {"class_name": "Convolution2D",
         "config": {"name": "conv_1", "nb_filter": 2, "nb_row": 3,
                    "nb_col": 3, "dim_ordering": "th",
                    "border_mode": "valid", "activation": "linear",
                    "batch_input_shape": [None, 1, 5, 5]}},
        {"class_name": "Flatten", "config": {"name": "flat_1"}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("conv_1", [k, b])])
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    x = rs.randn(2, 1, 5, 5).astype(np.float32)
    got = np.asarray(model.forward(x))
    want = torch.nn.functional.conv2d(
        torch.from_numpy(x), torch.from_numpy(k),
        torch.from_numpy(b)).numpy().reshape(2, -1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv_tf_ordering_transposed_to_chw(tmp_path):
    """A tf-dim_ordering config must produce the SAME model as its th
    twin: shapes go to CHW, kernels transpose (r,c,in,out)->OIHW."""
    rs = np.random.RandomState(2)
    k_oihw = rs.randn(2, 1, 3, 3).astype(np.float32)
    k_tf = np.transpose(k_oihw, (2, 3, 1, 0))     # (r, c, in, out)
    b = rs.randn(2).astype(np.float32)

    def build(ordering, kernel, shape):
        js = _seq_json([
            {"class_name": "Convolution2D",
             "config": {"name": "conv_1", "nb_filter": 2, "nb_row": 3,
                        "nb_col": 3, "dim_ordering": ordering,
                        "border_mode": "valid", "activation": "linear",
                        "batch_input_shape": shape}},
        ])
        p = tmp_path / f"{ordering}.json"
        p.write_text(js)
        _write_h5(tmp_path / f"{ordering}.h5", [("conv_1", [kernel, b])])
        return load_keras(str(p), str(tmp_path / f"{ordering}.h5"))

    th = build("th", k_oihw, [None, 1, 5, 5])
    tf_ = build("tf", k_tf, [None, 5, 5, 1])
    x = rs.randn(2, 1, 5, 5).astype(np.float32)   # both models eat CHW
    np.testing.assert_allclose(np.asarray(th.forward(x)),
                               np.asarray(tf_.forward(x)),
                               rtol=1e-5, atol=1e-5)


def test_batchnorm_weights_and_running_stats(tmp_path):
    rs = np.random.RandomState(3)
    gamma = rs.rand(4).astype(np.float32) + 0.5
    beta = rs.randn(4).astype(np.float32)
    mean = rs.randn(4).astype(np.float32)
    var = rs.rand(4).astype(np.float32) + 0.5    # keras1 "running_std"
    js = _seq_json([
        {"class_name": "BatchNormalization",
         "config": {"name": "bn_1", "epsilon": 1e-3, "mode": 0,
                    "batch_input_shape": [None, 4]}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("bn_1", [gamma, beta, mean, var])])
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))
    model.evaluate()

    x = rs.randn(6, 4).astype(np.float32)
    got = np.asarray(model.forward(x))
    want = (x - mean) / np.sqrt(var + 1e-3) * gamma + beta
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_embedding_weights(tmp_path):
    rs = np.random.RandomState(4)
    table = rs.randn(10, 3).astype(np.float32)
    js = _seq_json([
        {"class_name": "Embedding",
         "config": {"name": "emb_1", "input_dim": 10, "output_dim": 3,
                    "batch_input_shape": [None, 5]}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5", [("emb_1", [table])])
    model = load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))

    ids = np.array([[0, 1, 2, 9, 3]], np.int32)   # keras ids are 0-based
    got = np.asarray(model.forward(ids))
    np.testing.assert_allclose(got[0], table[ids[0]], rtol=1e-6)


def test_functional_model_with_merge():
    js = json.dumps({
        "class_name": "Model",
        "config": {
            "name": "m",
            "layers": [
                {"class_name": "InputLayer", "name": "input_1",
                 "config": {"name": "input_1",
                            "batch_input_shape": [None, 4]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "dense_1",
                 "config": {"name": "dense_1", "output_dim": 3,
                            "activation": "relu"},
                 "inbound_nodes": [[["input_1", 0, 0]]]},
                {"class_name": "Dense", "name": "dense_2",
                 "config": {"name": "dense_2", "output_dim": 3,
                            "activation": "tanh"},
                 "inbound_nodes": [[["input_1", 0, 0]]]},
                {"class_name": "Merge", "name": "merge_1",
                 "config": {"name": "merge_1", "mode": "concat",
                            "concat_axis": -1},
                 "inbound_nodes": [[["dense_1", 0, 0],
                                    ["dense_2", 0, 0]]]},
            ],
            "input_layers": [["input_1", 0, 0]],
            "output_layers": [["merge_1", 0, 0]],
        },
    })
    model = load_keras_json(js)
    out = np.asarray(model.forward(np.ones((2, 4), np.float32)))
    assert out.shape == (2, 6)
    # relu half is >= 0; tanh half is in [-1, 1]
    assert out[:, :3].min() >= 0.0
    assert np.all(np.abs(out[:, 3:]) <= 1.0)


def test_unsupported_layer_and_weights_errors(tmp_path):
    with pytest.raises(ValueError, match="SomeExotic"):
        load_keras_json(_seq_json([
            {"class_name": "SomeExotic",
             "config": {"name": "x", "batch_input_shape": [None, 4]}}]))

    js = _seq_json([
        {"class_name": "LSTM",
         "config": {"name": "lstm_1", "output_dim": 3,
                    "return_sequences": False,
                    "batch_input_shape": [None, 7, 4]}},
    ])
    (tmp_path / "m.json").write_text(js)
    _write_h5(tmp_path / "m.h5",
              [("lstm_1", [np.zeros((4, 3), np.float32)] * 12)])
    # architecture alone builds fine
    m = load_keras_json(js)
    assert np.asarray(m.forward(np.zeros((1, 7, 4), np.float32))).shape \
        == (1, 3)
    with pytest.raises(NotImplementedError, match="LSTM"):
        load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))


def test_mismatched_json_h5_pair_raises(tmp_path):
    js = _seq_json([
        {"class_name": "Dense",
         "config": {"name": "dense_1", "output_dim": 3,
                    "activation": "linear",
                    "batch_input_shape": [None, 4]}},
    ])
    (tmp_path / "m.json").write_text(js)
    # h5 written from a model with different auto-names
    _write_h5(tmp_path / "m.h5",
              [("dense_7", [np.zeros((4, 3), np.float32),
                            np.zeros(3, np.float32)])])
    with pytest.raises(ValueError, match="does not match"):
        load_keras(str(tmp_path / "m.json"), str(tmp_path / "m.h5"))
