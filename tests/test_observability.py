"""Observability: TB histograms + summary triggers + LoggerFilter + profiler
hook (SURVEY.md §5.1/§5.5)."""

import logging
import os

import numpy as np


def test_histogram_event_roundtrip(rng, tmp_path):
    """Histogram events parse back via tensorflow's event reader."""
    from bigdl_tpu.visualization.tensorboard import FileWriter

    w = FileWriter(str(tmp_path))
    vals = rng.randn(1000)
    w.add_histogram("Parameters/w", vals, 3)
    w.close()

    import tensorflow as tf

    events = list(tf.compat.v1.train.summary_iterator(w.path))
    histos = [e for e in events if e.summary.value
              and e.summary.value[0].HasField("histo")]
    assert len(histos) == 1
    h = histos[0].summary.value[0].histo
    assert histos[0].step == 3
    assert abs(h.num - 1000) < 1e-6
    assert abs(h.sum - vals.sum()) < 1e-3
    assert abs(h.min - vals.min()) < 1e-9


def test_parameter_histograms_during_training(rng, tmp_path):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.visualization import TrainSummary

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    summary = TrainSummary(str(tmp_path), "app")
    summary.set_summary_trigger("Parameters", Trigger.several_iteration(2))

    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(5))
    opt.set_train_summary(summary)
    opt.optimize()
    summary.close()

    import tensorflow as tf

    n_histo = 0
    for name in os.listdir(summary.log_dir):
        for e in tf.compat.v1.train.summary_iterator(
                os.path.join(summary.log_dir, name)):
            for v in e.summary.value:
                if v.HasField("histo"):
                    n_histo += 1
                    assert v.tag.startswith("Parameters/")
    assert n_histo >= 2  # weight+bias at least once


def test_logger_filter(tmp_path):
    from bigdl_tpu.utils.logger_filter import LoggerFilter

    path = LoggerFilter.redirect_spark_info_logs(str(tmp_path))
    logging.getLogger("jax").info("chatty compiler message")
    logging.getLogger("tensorflow").warning("tf noise")
    with open(path) as f:
        content = f.read()
    assert "chatty compiler message" in content
    assert "tf noise" in content


def test_profiler_hook_smoke(rng, tmp_path):
    """set_profile captures a trace directory without disturbing training."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(4))
    opt.set_profile(str(tmp_path / "trace"), start_iteration=2, n_iterations=1)
    opt.optimize()
    assert os.path.isdir(str(tmp_path / "trace"))


def test_orbax_checkpoint_and_resume(rng, tmp_path):
    """orbax backend: checkpoint written at trigger, resume restores state
    (SURVEY.md §5.4)."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    ckpt = str(tmp_path / "ckpt")
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(4))
    opt.set_checkpoint(ckpt, Trigger.several_iteration(2), backend="orbax")
    opt.optimize()
    assert any(f.startswith("orbax") for f in os.listdir(ckpt))

    snap = opt._latest_checkpoint()
    assert snap is not None
    mblob, oblob = snap
    assert "params" in mblob and oblob["neval"] >= 2
    w = np.asarray(next(iter(
        np.asarray(v) for v in _leaves(mblob["params"]))))
    assert np.all(np.isfinite(w))


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)
