"""Observability: TB histograms + summary triggers + LoggerFilter + profiler
hook (SURVEY.md §5.1/§5.5)."""

import logging
import os

import numpy as np
import pytest


def test_histogram_event_roundtrip(rng, tmp_path):
    """Histogram events parse back via tensorflow's event reader."""
    from bigdl_tpu.visualization.tensorboard import FileWriter

    w = FileWriter(str(tmp_path))
    vals = rng.randn(1000)
    w.add_histogram("Parameters/w", vals, 3)
    w.close()

    import tensorflow as tf

    events = list(tf.compat.v1.train.summary_iterator(w.path))
    histos = [e for e in events if e.summary.value
              and e.summary.value[0].HasField("histo")]
    assert len(histos) == 1
    h = histos[0].summary.value[0].histo
    assert histos[0].step == 3
    assert abs(h.num - 1000) < 1e-6
    assert abs(h.sum - vals.sum()) < 1e-3
    assert abs(h.min - vals.min()) < 1e-9


def test_parameter_histograms_during_training(rng, tmp_path):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.visualization import TrainSummary

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    summary = TrainSummary(str(tmp_path), "app")
    summary.set_summary_trigger("Parameters", Trigger.several_iteration(2))

    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(5))
    opt.set_train_summary(summary)
    opt.optimize()
    summary.close()

    import tensorflow as tf

    n_histo = 0
    for name in os.listdir(summary.log_dir):
        for e in tf.compat.v1.train.summary_iterator(
                os.path.join(summary.log_dir, name)):
            for v in e.summary.value:
                if v.HasField("histo"):
                    n_histo += 1
                    assert v.tag.startswith("Parameters/")
    assert n_histo >= 2  # weight+bias at least once


def test_logger_filter(tmp_path):
    from bigdl_tpu.utils.logger_filter import LoggerFilter

    path = LoggerFilter.redirect_spark_info_logs(str(tmp_path))
    logging.getLogger("jax").info("chatty compiler message")
    logging.getLogger("tensorflow").warning("tf noise")
    with open(path) as f:
        content = f.read()
    assert "chatty compiler message" in content
    assert "tf noise" in content


def test_profiler_hook_smoke(rng, tmp_path):
    """set_profile captures a trace directory without disturbing training."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(4))
    opt.set_profile(str(tmp_path / "trace"), start_iteration=2, n_iterations=1)
    opt.optimize()
    assert os.path.isdir(str(tmp_path / "trace"))


def test_orbax_checkpoint_and_resume(rng, tmp_path):
    """orbax backend: checkpoint written at trigger, resume restores state
    (SURVEY.md §5.4)."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    ckpt = str(tmp_path / "ckpt")
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(4))
    opt.set_checkpoint(ckpt, Trigger.several_iteration(2), backend="orbax")
    opt.optimize()
    assert any(f.startswith("orbax") for f in os.listdir(ckpt))

    snap = opt._latest_checkpoint()
    assert snap is not None
    mblob, oblob = snap
    assert "params" in mblob and oblob["neval"] >= 2
    w = np.asarray(next(iter(
        np.asarray(v) for v in _leaves(mblob["params"]))))
    assert np.all(np.isfinite(w))


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_orbax_async_checkpoint_and_resume(rng, tmp_path):
    """orbax_async backend: the save runs on a background thread (training
    is only gated by back-to-back saves); the written snapshot must be
    restorable and training must resume from it (SURVEY §5.4 + the
    TPU-ecosystem async-save extension)."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    ckpt = str(tmp_path / "ckpt")

    def make(n_iter):
        opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                        dataset=DataSet.array(samples),
                        criterion=MSECriterion(), batch_size=10)
        opt.set_optim_method(SGD(learning_rate=0.01))
        opt.set_end_when(Trigger.max_iteration(n_iter))
        opt.set_checkpoint(ckpt, Trigger.several_iteration(1),
                           backend="orbax_async")
        return opt

    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(11)
    make(3).optimize()
    snap_files = os.listdir(ckpt)
    assert any(f.startswith("orbax") for f in snap_files)

    RNG.set_seed(11)
    opt2 = make(6)
    trained = opt2.optimize(resume=True)
    assert opt2.optim_method.state["neval"] == 7  # continued 4..6
    w = np.concatenate([np.asarray(p).ravel()
                        for p in trained.parameters()[0]])
    assert np.all(np.isfinite(w))


def test_preemption_sigterm_checkpoints_and_resumes(tmp_path):
    """handle_preemption(): SIGTERM mid-training finishes the in-flight
    iteration, writes a checkpoint, and exits with TrainingPreempted
    instead of being retried; a fresh run resumes from that snapshot."""
    import subprocess
    import sys
    import time

    script = tmp_path / "preempt_worker.py"
    script.write_text(f"""
import os, sys, time
sys.path.insert(0, {repr(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))})
import numpy as np
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn import Linear, MSECriterion, Sequential
from bigdl_tpu.optim import Optimizer, SGD, Trigger, TrainingPreempted
from bigdl_tpu.utils.random_gen import RNG

RNG.set_seed(5)
rs = np.random.RandomState(0)
samples = [Sample(rs.randn(4).astype(np.float32),
                  rs.randn(2).astype(np.float32)) for _ in range(40)]
opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                dataset=DataSet.array(samples),
                criterion=MSECriterion(), batch_size=10)
opt.set_optim_method(SGD(learning_rate=0.01))
opt.set_end_when(Trigger.max_iteration(100000))
opt.set_checkpoint({repr(str(tmp_path / 'ckpt'))}, Trigger(lambda s: True, lambda s: False))
opt.handle_preemption()
print("READY", flush=True)

# slow the loop so the parent's SIGTERM lands mid-run; the ITER marker
# tells the parent the train loop (and the signal hook) is live
class SlowIter:
    def __init__(self, inner): self.inner = iter(inner)
    def __iter__(self): return self
    def __next__(self):
        print("ITER", flush=True)
        time.sleep(0.05)
        return next(self.inner)

_data = opt.dataset.data
opt.dataset.data = lambda train: SlowIter(_data(train=train))
try:
    opt.optimize()
    print("NOT_PREEMPTED", flush=True)
    sys.exit(1)
except TrainingPreempted as e:
    print("PREEMPTED_OK", e, flush=True)
    sys.exit(7)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # hermetic: no tunnel-compile window
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    # wait until the train loop is demonstrably iterating (two batch
    # fetches seen), then evict it — no timing guess
    deadline = time.time() + 120
    line, iters = "", 0
    while time.time() < deadline and iters < 2:
        line = proc.stdout.readline()
        if line == "" and proc.poll() is not None:
            break  # child died before the loop started — fail fast
        if "ITER" in line:
            iters += 1
    assert iters == 2, f"loop never started: {line}"
    proc.terminate()  # SIGTERM
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 7, f"rc={proc.returncode}\n{line}{out}"
    assert "PREEMPTED_OK" in out, out
    assert os.path.isdir(str(tmp_path / "ckpt"))
    assert any(f.startswith("model")
               for f in os.listdir(str(tmp_path / "ckpt")))

    # the evicted job's replacement resumes from the snapshot
    import numpy as np

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    rs = np.random.RandomState(0)
    samples = [Sample(rs.randn(4).astype(np.float32),
                      rs.randn(2).astype(np.float32)) for _ in range(40)]
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_checkpoint(str(tmp_path / "ckpt"),
                       Trigger.several_iteration(5))
    resumed_from = None
    snap = opt._latest_checkpoint()
    assert snap is not None
    resumed_from = snap[1]["neval"]
    assert resumed_from > 1  # at least one iteration ran pre-eviction
    opt.set_end_when(Trigger.max_iteration(resumed_from + 2))
    trained = opt.optimize(resume=True)
    assert opt.optim_method.state["neval"] == resumed_from + 3


def test_orbax_resume_preserves_mid_epoch_position(rng, tmp_path):
    """The orbax restore must carry the 'seen' counter so a mid-epoch
    snapshot resumes at the right stream position (not the epoch start)."""
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(40)]
    ckpt = str(tmp_path / "ckpt")
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(6))  # 1.5 epochs of 4 batches
    opt.set_checkpoint(ckpt, Trigger.several_iteration(1), backend="orbax")
    opt.optimize()
    snap = opt._latest_checkpoint()
    assert snap is not None
    # 6 iterations of 10 = epoch 2, 20 records into the 40-record epoch
    assert snap[1]["epoch"] == 2
    assert snap[1]["seen"] == 20


def test_adapt_restored_tree_natural_order():
    """Rebuilt auto-names with 2-digit counters must map by construction
    order, not lexicographic order (which scrambles L1,L10,L2,...)."""
    from bigdl_tpu.optim.optimizer import _adapt_restored_tree

    # checkpoint written by modules Linear1..Linear12, live model rebuilt
    # as Linear13..Linear24 — same architecture, same construction order
    restored = {f"Linear{i}": np.full((2,), float(i))
                for i in range(1, 13)}
    template = {f"Linear{i}": np.zeros((2,))
                for i in range(13, 25)}
    out = _adapt_restored_tree(template, restored, "params")
    for pos, i in enumerate(range(13, 25)):
        np.testing.assert_array_equal(out[f"Linear{i}"],
                                      np.full((2,), float(pos + 1)))

    # tuple->list container change (orbax) is tolerated
    out2 = _adapt_restored_tree((np.zeros(2), np.zeros(3)),
                                [np.ones(2), np.ones(3)], "opt_state")
    assert isinstance(out2, tuple)

    # real mismatches still raise
    with pytest.raises(ValueError, match="different architecture"):
        _adapt_restored_tree({"Linear1": np.zeros((2,))},
                             {"Conv1": np.zeros((2,))}, "params")
    with pytest.raises(ValueError, match="different architecture"):
        _adapt_restored_tree({"Linear1": np.zeros((3,))},
                             {"Linear2": np.zeros((2,))}, "params")


def test_handle_preemption_requires_checkpoint(rng):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    samples = [Sample(rng.randn(4).astype(np.float32),
                      rng.randn(2).astype(np.float32)) for _ in range(20)]
    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=DataSet.array(samples),
                    criterion=MSECriterion(), batch_size=10)
    opt.set_optim_method(SGD(learning_rate=0.01))
    opt.set_end_when(Trigger.max_iteration(1))
    opt.handle_preemption()
    with pytest.raises(ValueError, match="set_checkpoint"):
        opt.optimize()
