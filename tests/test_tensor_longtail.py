"""Round-2 tensor long tail vs the torch oracle (reference Tensor.scala's
wider ~400-method trait; round-1 verdict missing #4). Torch is the behavior
oracle wherever it has the same method; pure-shape/meta methods assert the
documented contract directly."""

import numpy as np
import pytest

from bigdl_tpu.tensor import Tensor
from tests.oracle import assert_close

torch = pytest.importorskip("torch")


def _pair(shape=(3, 4), seed=0):
    rs = np.random.RandomState(seed)
    a = rs.randn(*shape).astype(np.float32)
    return Tensor(a.copy()), torch.from_numpy(a.copy())


def test_storage_introspection():
    t = Tensor(np.arange(24.0, dtype=np.float32).reshape(2, 3, 4))
    tt = torch.arange(24.0).reshape(2, 3, 4)
    assert t.stride() == tt.stride()
    assert t.stride(1) == tt.stride(0)
    assert t.storage_offset() == tt.storage_offset() + 1  # 1-based
    assert t.is_contiguous()
    assert t.element_size() == 4
    assert t.n_dimension() == 3
    assert_close(t.storage(), np.arange(24.0, dtype=np.float32))


def test_dtype_casts():
    import jax

    t = Tensor(np.array([1.5, -2.5], np.float32))
    assert t.half().data.dtype == np.float16
    assert t.int().data.dtype == np.int32
    assert t.short().data.dtype == np.int16
    assert t.char().data.dtype == np.int8
    assert t.byte().data.dtype == np.uint8
    assert t.bool().data.dtype == np.bool_
    # 64-bit dtypes honor the x64 switch (JAX truncates them otherwise)
    jax.config.update("jax_enable_x64", True)
    try:
        assert t.double().data.dtype == np.float64
        assert t.long().data.dtype == np.int64
        assert t.type_as(
            Tensor(np.zeros(1), dtype=np.float64)).data.dtype == np.float64
    finally:
        jax.config.update("jax_enable_x64", False)


def test_apply_map():
    t = Tensor(np.array([1.0, 2.0, 3.0], np.float32))
    t.apply1(lambda x: x * x + 1)
    assert_close(t.to_numpy(), np.array([2.0, 5.0, 10.0], np.float32))
    o = Tensor(np.array([10.0, 20.0, 30.0], np.float32))
    t.map(o, lambda a, b: b - a)
    assert_close(t.to_numpy(), np.array([8.0, 15.0, 20.0], np.float32))


@pytest.mark.parametrize("name", [
    "frac", "trunc", "log2", "log10", "exp2", "neg", "lgamma", "digamma",
    "erfinv",
])
def test_elementwise_vs_torch(name):
    rs = np.random.RandomState(1)
    a = (rs.rand(3, 4).astype(np.float32) * 0.8 + 0.1)  # (0.1, 0.9)
    t = Tensor(a.copy())
    got = getattr(t, name)().to_numpy()
    want = getattr(torch.from_numpy(a.copy()), name)().numpy()
    assert_close(got, want, atol=2e-5)


def test_hypot_isnan_equal():
    t, tt = _pair()
    o, ot = _pair(seed=1)
    assert_close(t.clone().hypot(o).to_numpy(),
                 torch.hypot(tt, ot).numpy(), atol=1e-6)
    x = Tensor(np.array([1.0, np.nan, np.inf], np.float32))
    assert list(np.asarray(x.isnan().data)) == [False, True, False]
    assert list(np.asarray(x.isinf().data)) == [False, False, True]
    assert list(np.asarray(x.isfinite().data)) == [True, False, False]
    assert Tensor(np.ones((2, 2))).equal(Tensor(np.ones((2, 2))))
    assert not Tensor(np.ones((2, 2))).equal(Tensor(np.ones((2, 3))))


def test_shape_longtail():
    a = np.arange(12.0, dtype=np.float32).reshape(3, 4)
    t = Tensor(a.copy())
    tt = torch.from_numpy(a.copy())
    assert_close(t.flatten().to_numpy(), tt.flatten().numpy())
    assert_close(t.flip(1).to_numpy(), torch.flip(tt, [0]).numpy())
    assert_close(t.roll(1, 2).to_numpy(), torch.roll(tt, 1, 1).numpy())
    assert_close(t.rot90().to_numpy(), torch.rot90(tt).numpy())
    assert_close(t.tile(2, 1).to_numpy(), tt.repeat(2, 1).numpy())
    assert t.view_as(Tensor(np.zeros((4, 3)))).size() == (4, 3)


def test_take_put_scatter_add():
    a = np.arange(1, 13, dtype=np.float32).reshape(3, 4)
    t = Tensor(a.copy())
    tt = torch.from_numpy(a.copy())
    idx0 = np.array([0, 5, 11])
    assert_close(t.take(Tensor(idx0 + 1)).to_numpy(),
                 torch.take(tt, torch.from_numpy(idx0)).numpy())
    t2 = Tensor(a.copy())
    t2.put(Tensor(np.array([1, 12])), Tensor(np.array([-1.0, -2.0])))
    want = a.copy().reshape(-1)
    want[[0, 11]] = [-1.0, -2.0]
    assert_close(t2.to_numpy(), want.reshape(3, 4))

    base = np.zeros((3, 4), np.float32)
    src = np.ones((2, 4), np.float32) * 2
    index = np.array([[0, 1, 2, 0], [2, 0, 1, 1]])
    got = Tensor(base.copy()).scatter_add(1, Tensor(index + 1), Tensor(src))
    want = torch.zeros(3, 4).scatter_add(
        0, torch.from_numpy(index), torch.from_numpy(src)).numpy()
    assert_close(got.to_numpy(), want)


def test_arg_and_sort_family():
    t, tt = _pair(seed=3)
    assert int(np.asarray(t.argmax().data)) == int(tt.argmax()) + 1
    assert_close(np.asarray(t.argmax(2).data),
                 tt.argmax(dim=1).numpy() + 1)
    assert_close(np.asarray(t.argmin(1).data), tt.argmin(dim=0).numpy() + 1)
    assert_close(np.asarray(t.argsort(2).data),
                 tt.argsort(dim=1).numpy() + 1)
    assert_close(t.msort().to_numpy(), torch.msort(tt).numpy())
    h = Tensor(np.array([0.1, 0.4, 0.6, 0.9], np.float32))
    assert_close(h.histc(2, 0.0, 1.0).to_numpy(),
                 torch.histc(torch.tensor([0.1, 0.4, 0.6, 0.9]), 2, 0, 1).numpy())
    assert_close(Tensor(np.array([3.0, 1.0, 3.0])).unique().to_numpy(),
                 np.array([1.0, 3.0]))


def test_linalg_family():
    rs = np.random.RandomState(5)
    m = rs.randn(4, 4).astype(np.float32)
    spd = (m @ m.T + 4 * np.eye(4)).astype(np.float32)
    t = Tensor(spd.copy())
    tt = torch.from_numpy(spd.copy()).double()

    assert_close(t.inverse().to_numpy(), tt.inverse().numpy(), atol=1e-4)
    assert abs(t.det() - float(torch.det(tt))) < 1e-2 * abs(float(torch.det(tt)))
    u, s, v = t.svd()
    assert_close(np.asarray(s.data), torch.linalg.svdvals(tt).numpy(),
                 atol=1e-3)
    w, _ = t.symeig()
    assert_close(np.asarray(w.data),
                 torch.linalg.eigvalsh(tt).numpy(), atol=1e-3)
    q, r = t.qr()
    assert_close((q.data @ r.data), spd, atol=1e-3)
    u_chol = t.potrf(upper=True)
    assert_close(np.asarray(u_chol.data).T @ np.asarray(u_chol.data),
                 spd, atol=1e-3)
    b = rs.randn(4, 2).astype(np.float32)
    assert_close(t.gesv(b).to_numpy(), np.linalg.solve(spd, b), atol=1e-3)
    assert_close(u_chol.potrs(b, upper=True).to_numpy(),
                 np.linalg.solve(spd, b), atol=1e-3)
    l_chol = t.potrf(upper=False)
    assert_close(l_chol.potrs(b, upper=False).to_numpy(),
                 np.linalg.solve(spd, b), atol=1e-3)
    a_tall = rs.randn(6, 3).astype(np.float32)
    bb = rs.randn(6, 2).astype(np.float32)
    assert_close(Tensor(a_tall).gels(bb).to_numpy(),
                 np.linalg.lstsq(a_tall, bb, rcond=None)[0], atol=1e-3)
    x, y = _pair(seed=6)
    ox, oy = _pair(seed=7)
    assert abs(x.inner(ox) - float(torch.sum(y * oy))) < 1e-4
    assert_close(x.matmul(ox.t()).to_numpy(), (y @ oy.T).numpy(), atol=1e-5)
    assert_close(x.kron(ox).to_numpy(), torch.kron(y, oy).numpy(), atol=1e-5)


@pytest.mark.parametrize("mode", ["V", "F"])
def test_conv3_xcorr3_vs_torch(mode):
    rs = np.random.RandomState(8)
    x = rs.randn(6, 7, 8).astype(np.float32)
    k = rs.randn(3, 3, 2).astype(np.float32)

    got_conv = Tensor(x.copy()).conv3(Tensor(k.copy()), mode).to_numpy()
    got_xcorr = Tensor(x.copy()).xcorr3(Tensor(k.copy()), mode).to_numpy()

    xt = torch.from_numpy(x)[None, None]
    kt = torch.from_numpy(k)[None, None]
    pad = (k.shape[0] - 1, k.shape[1] - 1, k.shape[2] - 1) if mode == "F" \
        else (0, 0, 0)
    want_xcorr = torch.nn.functional.conv3d(xt, kt, padding=pad)[0, 0].numpy()
    kf = torch.from_numpy(k[::-1, ::-1, ::-1].copy())[None, None]
    want_conv = torch.nn.functional.conv3d(xt, kf, padding=pad)[0, 0].numpy()
    assert_close(got_xcorr, want_xcorr, atol=1e-4)
    assert_close(got_conv, want_conv, atol=1e-4)


def test_random_family_deterministic():
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(9)
    e = Tensor(3, 1000).exponential(2.0)
    assert abs(float(np.asarray(e.data).mean()) - 0.5) < 0.1  # mean 1/lam
    ln = Tensor(2000).log_normal(0.0, 0.25)
    assert abs(float(np.log(np.asarray(ln.data)).mean())) < 0.1
    g = Tensor(2000).geometric(0.5)
    vals = np.asarray(g.data)
    assert vals.min() >= 1 and abs(vals.mean() - 2.0) < 0.3
    c = Tensor(100).cauchy(0.0, 1.0)
    assert np.isfinite(np.asarray(c.data)).all()
    r = Tensor(1000).random(1, 6)
    vals = np.asarray(r.data)
    assert vals.min() >= 1 and vals.max() <= 6

    RNG.set_seed(10)
    p = Tensor.randperm(8)
    assert sorted(np.asarray(p.data).tolist()) == list(range(1, 9))
    m = Tensor(np.array([0.0, 0.0, 1.0])).multinomial(5, replacement=True)
    assert np.all(np.asarray(m.data) == 3)  # 1-based index of the only mass
    assert_close(Tensor.eye(3).to_numpy(), np.eye(3))


def test_method_count_bar():
    """The round-1 verdict asked for >=220 facade methods."""
    methods = [m for m in dir(Tensor)
               if not m.startswith("_") and callable(getattr(Tensor, m))]
    assert len(methods) >= 215, len(methods)


def test_outer_non_accumulating():
    a = Tensor(np.array([1.0, 2.0], np.float32))
    b = Tensor(np.array([3.0, 4.0, 5.0], np.float32))
    assert_close(a.outer(b).to_numpy(),
                 np.outer([1.0, 2.0], [3.0, 4.0, 5.0]))


def test_logical_mode_countnonzero():
    a = Tensor(np.array([1.0, 0.0, 2.0], np.float32))
    b = Tensor(np.array([0.0, 0.0, 3.0], np.float32))
    assert list(np.asarray(a.logical_and(b).data)) == [False, False, True]
    assert list(np.asarray(a.logical_or(b).data)) == [True, False, True]
    assert list(np.asarray(a.logical_xor(b).data)) == [True, False, False]
    assert list(np.asarray(a.logical_not().data)) == [False, True, False]
    assert a.count_nonzero() == 2
    m = Tensor(np.array([[1.0, 2.0, 2.0], [3.0, 3.0, 4.0]], np.float32))
    assert_close(m.mode(2).to_numpy(), np.array([2.0, 3.0], np.float32))
    assert_close(a.clip(0.5, 1.5).to_numpy(),
                 np.array([1.0, 0.5, 1.5], np.float32))
    t = Tensor(np.arange(2.0, dtype=np.float32))
    assert t.repeat(3).size() == (6,)


def test_round3_all_any_topk():
    t, tt = _pair((3, 5), seed=3)
    assert t.all() == bool(tt.bool().all())
    assert Tensor(np.ones((2, 2), np.float32)).all() is True
    assert Tensor(np.zeros((2, 2), np.float32)).any() is False
    assert Tensor(np.eye(2, dtype=np.float32)).any() is True
    v, i = t.topk(3, dim=2)
    tv, ti = tt.topk(3, dim=1)
    assert_close(v.data, tv.numpy())
    np.testing.assert_array_equal(np.asarray(i.data) - 1, ti.numpy())
    v, i = t.topk(2, dim=1, largest=False)
    tv, ti = tt.topk(2, dim=0, largest=False)
    assert_close(v.data, tv.numpy())
    np.testing.assert_array_equal(np.asarray(i.data) - 1, ti.numpy())


def test_round3_apply_and_index_family():
    t, tt = _pair((4, 3), seed=4)
    t.apply_(lambda x: x * 2.0 + 1.0)
    assert_close(t.data, (tt * 2.0 + 1.0).numpy())

    t, tt = _pair((4, 3), seed=5)
    idx1 = np.array([1, 3], np.int64)           # 1-based facade
    idx0 = torch.from_numpy(idx1 - 1)
    t.index_fill_(1, idx1, 7.0)
    tt.index_fill_(0, idx0, 7.0)
    assert_close(t.data, tt.numpy())

    t, tt = _pair((4, 3), seed=6)
    src = np.random.RandomState(9).randn(2, 3).astype(np.float32)
    t.index_copy_(1, idx1, src)
    tt.index_copy_(0, idx0, torch.from_numpy(src.copy()))
    assert_close(t.data, tt.numpy())

    t, tt = _pair((4, 3), seed=7)
    t.index_add_(1, idx1, src)
    tt.index_add_(0, idx0, torch.from_numpy(src.copy()))
    assert_close(t.data, tt.numpy())


def test_round3_underscore_aliases():
    t, tt = _pair((3, 3), seed=8)
    t.add_(1.0).mul_(2.0).abs_().sqrt().clamp_(0.5, 3.0)
    ref = ((tt + 1.0) * 2.0).abs().sqrt().clamp(0.5, 3.0)
    assert_close(t.data, ref.numpy())
    t.zero_()
    assert float(np.abs(np.asarray(t.data)).sum()) == 0.0
    t.fill_(4.0)
    assert_close(t.data, np.full((3, 3), 4.0, np.float32))


def test_round3_squeeze_inplace():
    t = Tensor(np.zeros((2, 1, 3), np.float32))
    r = t.squeeze_()
    assert r is t and t.data.shape == (2, 3)


# -- round-3b tranche: storage-set, axpy family, apply variants ------------

def test_cadd_csub_vs_torch():
    t, tt = _pair(seed=10)
    y, ty = _pair(seed=11)
    assert_close(t.clone().cadd(0.7, y).data,
                 tt.clone().add(ty, alpha=0.7).numpy())
    assert_close(t.clone().csub(0.7, y).data,
                 tt.clone().sub(ty, alpha=0.7).numpy())
    assert_close(t.clone().cadd(y).data, (tt + ty).numpy())
    assert_close(t.clone().csub(y).data, (tt - ty).numpy())


def test_tpow_vs_torch():
    t = Tensor(np.array([1.0, 2.0, 3.0], np.float32))
    tt = torch.tensor([1.0, 2.0, 3.0])
    assert_close(t.clone().tpow(2.0).data,
                 torch.pow(2.0, tt).numpy())


def test_sum_square():
    t, tt = _pair(seed=12)
    assert abs(t.sum_square() - float((tt ** 2).sum())) < 1e-4


def test_set_rebinds_value():
    t = Tensor(np.zeros((2, 2), np.float32))
    y = Tensor(np.array([1.0, 2.0], np.float32))
    assert t.set(y) is t
    assert_close(t.data, y.data)
    t.set()
    assert t.is_empty() and t.n_element() == 0


def test_singleton_dimension_roundtrip():
    t = Tensor(np.random.RandomState(0).rand(3, 4).astype(np.float32))
    t.add_singleton_dimension(1)
    assert tuple(t.data.shape) == (1, 3, 4)
    t.add_singleton_dimension(3)
    assert tuple(t.data.shape) == (1, 3, 1, 4)
    t.del_singleton_dimension(3).del_singleton_dimension(1)
    assert tuple(t.data.shape) == (3, 4)
    with pytest.raises(ValueError):
        t.del_singleton_dimension(1)  # size 3, not 1
    # negative dims count from the end
    t.add_singleton_dimension(-1)
    assert tuple(t.data.shape) == (3, 4, 1)
    t.del_singleton_dimension(-1)
    assert tuple(t.data.shape) == (3, 4)


def test_scalar_meta_predicates():
    assert Tensor(np.float32(2.0)).is_scalar()
    assert Tensor(np.array([2.0], np.float32)).is_scalar()
    assert not Tensor(np.zeros((2,), np.float32)).is_scalar()
    assert Tensor(np.zeros((0,), np.float32)).is_empty()
    assert Tensor(np.arange(3.0, dtype=np.float32)).get_type() == "float32"


def test_potri_vs_torch():
    rs = np.random.RandomState(3)
    m = rs.rand(4, 4).astype(np.float32)
    a = m @ m.T + 4 * np.eye(4, dtype=np.float32)  # SPD
    u = np.linalg.cholesky(a).T.astype(np.float32)
    want = torch.cholesky_inverse(
        torch.from_numpy(u), upper=True).numpy()
    assert_close(Tensor(u).potri("U").data, want, rtol=1e-3, atol=1e-4)
    l = np.linalg.cholesky(a).astype(np.float32)
    want_l = torch.cholesky_inverse(torch.from_numpy(l)).numpy()
    assert_close(Tensor(l).potri("L").data, want_l, rtol=1e-3, atol=1e-4)


def test_rand_and_new():
    r = Tensor.rand(100, seed=1)
    h = np.asarray(r.data)
    assert h.shape == (100,) and 0.0 <= h.min() and h.max() <= 1.0
    t = Tensor(np.ones((2,), np.float64))
    n = t.new(3, 2)
    assert n.data.shape == (3, 2) and n.data.dtype == t.data.dtype
    assert float(np.abs(np.asarray(n.data)).sum()) == 0.0


def test_apply2_apply3_zip_with():
    a = Tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    b = Tensor(np.array([[10.0, 20.0], [30.0, 40.0]], np.float32))
    out = a.clone().apply2(b, lambda x, y: x * y + 1)
    assert_close(out.data, np.array([[11.0, 41.0], [91.0, 161.0]]))
    z = Tensor(np.zeros((2, 2), np.float32))
    z.apply3(a, b, lambda x, y: y - x)
    assert_close(z.data, np.array([[9.0, 18.0], [27.0, 36.0]]))
    z2 = Tensor(np.zeros((2, 2), np.float32))
    z2.zip_with(a, b, lambda x, y: max(x, y / 20))
    assert_close(z2.data, np.maximum(np.asarray(a.data),
                                     np.asarray(b.data) / 20))


def test_bhistc_vs_torch_rows():
    rs = np.random.RandomState(4)
    m = rs.rand(3, 50).astype(np.float32)
    got = np.asarray(Tensor(m).bhistc(bins=8, min_v=0.0, max_v=1.0).data)
    for i in range(3):
        want = torch.histc(torch.from_numpy(m[i]), bins=8, min=0.0,
                           max=1.0).numpy()
        assert_close(got[i], want)
    with pytest.raises(ValueError):
        Tensor(m[0]).bhistc()


def test_round3b_inplace_aliases_vs_torch():
    """The new underscore spellings mutate self and match torch's."""
    t, tt = _pair(seed=13)
    t2 = t.clone().abs().add(0.5)      # positive domain
    tt2 = tt.abs().add(0.5)
    for name in ("sqrt", "rsqrt", "log", "log2", "log10", "log1p",
                 "reciprocal", "sign", "trunc", "frac", "neg"):
        x = t2.clone()
        ret = getattr(x, name + "_")()
        assert ret is x, name
        assert_close(x.data, getattr(tt2.clone(), name + "_")().numpy(),
                     rtol=1e-4, atol=1e-5, msg=name)
    for name in ("sin", "cos", "tan", "tanh", "sigmoid", "erf", "erfc"):
        x = t.clone()
        getattr(x, name + "_")()
        assert_close(x.data, getattr(tt.clone(), name + "_")().numpy(),
                     rtol=1e-4, atol=1e-5, msg=name)
    x = t.clone()
    x.fmod_(1.5)
    assert_close(x.data, tt.clone().fmod_(1.5).numpy())
    x = t.clone()
    x.remainder_(1.5)
    assert_close(x.data, tt.clone().remainder_(1.5).numpy())
    x = t.clone()
    x.lerp_(Tensor(np.zeros((3, 4), np.float32)), 0.25)
    assert_close(x.data,
                 tt.clone().lerp_(torch.zeros(3, 4), 0.25).numpy())


def test_round3b_view_rebinders():
    t = Tensor(np.arange(6.0, dtype=np.float32).reshape(2, 3))
    ref = np.asarray(t.data).copy()
    assert t.t_() is t
    assert_close(t.data, ref.T)
    t2 = Tensor(ref.copy())
    t2.transpose_(1, 2)
    assert_close(t2.data, ref.T)
    t3 = Tensor(ref.copy())
    t3.unsqueeze_(1)
    assert tuple(t3.data.shape) == (1, 2, 3)


# -- round-4 tranche 4 ------------------------------------------------------

def test_t4_amax_amin_aminmax_diff():
    t, tt = _pair((3, 4), 41)
    assert np.isclose(t.amax(), float(tt.amax()))
    assert np.isclose(t.amin(), float(tt.amin()))
    assert_close(t.amax(2).data, tt.amax(dim=1).numpy())
    assert_close(t.amin(1).data, tt.amin(dim=0).numpy())
    lo, hi = t.aminmax()
    assert np.isclose(lo, float(tt.amin())) and np.isclose(hi,
                                                           float(tt.amax()))
    assert_close(t.diff().data, torch.diff(tt).numpy())
    assert_close(t.diff(n=2, dim=1).data, torch.diff(tt, n=2, dim=0).numpy())


def test_t4_flips_movedim_broadcast():
    t, tt = _pair((3, 4), 42)
    assert_close(t.fliplr().data, torch.fliplr(tt).numpy())
    assert_close(t.flipud().data, torch.flipud(tt).numpy())
    t3, tt3 = _pair((2, 3, 4), 43)
    assert_close(t3.movedim(1, 3).data, torch.movedim(tt3, 0, 2).numpy())
    b = Tensor(np.ones((1, 4), np.float32)).broadcast_to(3, 4)
    assert b.shape == (3, 4)


def test_t4_take_along_repeat_interleave():
    t, tt = _pair((3, 4), 44)
    idx = np.array([[1, 2], [3, 1], [4, 4]], np.int64)   # 1-based
    got = t.take_along_dim(idx, 2)
    want = torch.take_along_dim(tt, torch.from_numpy(idx - 1), dim=1)
    assert_close(got.data, want.numpy())
    assert_close(t.repeat_interleave(3, dim=2).data,
                 torch.repeat_interleave(tt, 3, dim=1).numpy())
    assert_close(t.repeat_interleave(2).data,
                 torch.repeat_interleave(tt, 2).numpy())


def test_t4_binary_math_family():
    t, tt = _pair((3, 4), 45)
    o, ot = _pair((3, 4), 46)
    assert_close(t.logaddexp(o).data, torch.logaddexp(tt, ot).numpy())
    assert_close(t.logaddexp2(o).data, torch.logaddexp2(tt, ot).numpy())
    assert_close(t.copysign(o).data, torch.copysign(tt, ot).numpy())
    assert_close(t.nextafter(o).data, torch.nextafter(tt, ot).numpy())
    assert_close(t.heaviside(o.abs()).data,
                 torch.heaviside(tt, ot.abs()).numpy())
    p = Tensor(np.abs(np.asarray(t.data)) + 0.5)
    pt = torch.from_numpy(np.asarray(p.data).copy())
    assert_close(p.xlogy(o.abs()).data,
                 torch.special.xlogy(pt, ot.abs()).numpy(), atol=1e-5)
    assert_close(t.floor_divide(2.0).data,
                 torch.floor_divide(tt, 2.0).numpy())
    assert_close(t.true_divide(2.0).data,
                 torch.true_divide(tt, 2.0).numpy())
    assert_close(t.float_power(2.0).data,
                 torch.float_power(tt, 2.0).numpy(), atol=1e-5)


def test_t4_unary_family():
    t, tt = _pair((3, 4), 47)
    assert_close(t.deg2rad().data, torch.deg2rad(tt).numpy())
    assert_close(t.rad2deg().data, torch.rad2deg(tt).numpy())
    assert_close(t.sinc().data, torch.sinc(tt).numpy(), atol=1e-6)
    u = Tensor(np.clip(np.abs(np.asarray(t.data)) % 1.0, 0.01, 0.99))
    ut = torch.from_numpy(np.asarray(u.data).copy())
    assert_close(u.logit().data, torch.logit(ut).numpy(), atol=1e-5)
    w = Tensor(np.array([1.0, np.nan, np.inf, -np.inf], np.float32))
    assert_close(w.nan_to_num(nan=7.0, posinf=8.0, neginf=-8.0).data,
                 np.array([1.0, 7.0, 8.0, -8.0], np.float32))
    z = Tensor(np.array([1.0, np.inf, -np.inf], np.float32))
    assert list(np.asarray(z.isposinf().data)) == [False, True, False]
    assert list(np.asarray(z.isneginf().data)) == [False, False, True]


def test_t4_isclose_bincount_searchsorted():
    t, tt = _pair((3, 4), 48)
    o = Tensor(np.asarray(t.data) + 1e-7)
    assert bool(np.asarray(t.isclose(o).data).all())
    c = Tensor(np.array([0, 1, 1, 3, 2, 1], np.float32))
    assert_close(c.bincount().data,
                 torch.bincount(torch.tensor([0, 1, 1, 3, 2, 1])).numpy())
    w = np.array([0.5, 1.0, 1.0, 2.0, 0.25, 0.25], np.float32)
    assert_close(c.bincount(weights=w, minlength=6).data,
                 torch.bincount(torch.tensor([0, 1, 1, 3, 2, 1]),
                                torch.from_numpy(w), minlength=6).numpy())
    s = Tensor(np.array([1.0, 3.0, 5.0, 7.0], np.float32))
    got = s.searchsorted(np.array([0.0, 3.0, 8.0], np.float32))
    want = torch.searchsorted(torch.tensor([1.0, 3.0, 5.0, 7.0]),
                              torch.tensor([0.0, 3.0, 8.0])) + 1  # 1-based
    assert_close(got.data, want.numpy())
    got_r = s.searchsorted(np.array([3.0], np.float32), right=True)
    want_r = torch.searchsorted(torch.tensor([1.0, 3.0, 5.0, 7.0]),
                                torch.tensor([3.0]), right=True) + 1
    assert_close(got_r.data, want_r.numpy())


def test_t4_stacks_split_cast_cov():
    a, at = _pair((2, 3), 49)
    b, bt = _pair((2, 3), 50)
    assert_close(Tensor.hstack([a, b]).data,
                 torch.hstack([at, bt]).numpy())
    assert_close(Tensor.vstack([a, b]).data,
                 torch.vstack([at, bt]).numpy())
    assert_close(Tensor.dstack([a, b]).data,
                 torch.dstack([at, bt]).numpy())
    assert_close(Tensor.column_stack([a, b]).data,
                 torch.column_stack([at, bt]).numpy())

    t, tt = _pair((3, 8), 51)
    parts = t.tensor_split(3, dim=2)
    wparts = torch.tensor_split(tt, 3, dim=1)
    assert len(parts) == len(wparts)
    for p, w in zip(parts, wparts):
        assert_close(p.data, w.numpy())
    # indices form: 1-based split-before positions == torch 0-based + 1
    parts_i = t.tensor_split([3, 6], dim=2)
    wparts_i = torch.tensor_split(tt, [2, 5], dim=1)
    assert len(parts_i) == len(wparts_i)
    for p, w in zip(parts_i, wparts_i):
        assert_close(p.data, w.numpy())

    assert t.cast(np.int32).data.dtype == np.int32
    assert t.cast(Tensor(np.zeros(1, np.float16))).data.dtype == np.float16

    c, ct = _pair((3, 10), 52)
    assert_close(c.cov().data, torch.cov(ct).numpy(), atol=1e-5)
    assert_close(c.corrcoef().data, torch.corrcoef(ct).numpy(), atol=1e-5)


def test_t4_inplace_spellings_distinct():
    """The _ spellings rebind self where the pure forms return new
    tensors — both directions checked."""
    t, tt = _pair((3, 4), 53)
    pure = t.cumsum(2)
    assert pure is not t and not np.allclose(np.asarray(pure.data),
                                            np.asarray(t.data))
    r = t.cumsum_(2)
    assert r is t
    assert_close(t.data, torch.cumsum(tt, dim=1).numpy())

    t2, tt2 = _pair((4, 4), 54)
    t2.tril_()
    assert_close(t2.data, torch.tril(tt2).numpy())
    t2.triu_(-1)
    assert_close(t2.data, torch.triu(torch.tril(tt2), -1).numpy())

    t3, tt3 = _pair((3, 4), 55)
    t3.cumprod_(1)
    assert_close(t3.data, torch.cumprod(tt3, dim=0).numpy())

    t4, _ = _pair((3, 4), 56)
    snap = np.asarray(t4.data).copy()
    t4.ge_(0.0)
    assert_close(t4.data, (snap >= 0.0).astype(np.float32))
    for name, op in (("eq_", np.equal), ("ne_", np.not_equal),
                     ("lt_", np.less), ("gt_", np.greater),
                     ("le_", np.less_equal)):
        u, _ = _pair((3, 4), 57)
        snap = np.asarray(u.data).copy()
        getattr(u, name)(0.1)
        assert_close(u.data, op(snap, 0.1).astype(np.float32))

    s = Tensor(np.zeros((3, 4), np.float32))
    idx = np.ones((1, 4), np.int64)           # 1-based row 1
    src = np.arange(4, dtype=np.float32).reshape(1, 4) + 1
    r = s.scatter_(1, idx, src)
    assert r is s
    want = np.zeros((3, 4), np.float32)
    want[0] = src[0]
    assert_close(s.data, want)


# -- tranche 5 (final) ------------------------------------------------------

def test_t5_value_and_hyperbolic_inverses():
    assert Tensor(np.asarray([3.5], np.float32)).value() == 3.5
    with pytest.raises(ValueError):
        Tensor(np.zeros((2,), np.float32)).value()
    a = np.asarray([1.5, 2.0, 3.0], np.float32)
    assert_close(Tensor(a.copy()).acosh().to_numpy(),
                 torch.from_numpy(a).acosh().numpy(), atol=1e-6)
    assert_close(Tensor(a.copy()).asinh().to_numpy(),
                 torch.from_numpy(a).asinh().numpy(), atol=1e-6)
    b = np.asarray([-0.5, 0.0, 0.5], np.float32)
    assert_close(Tensor(b.copy()).atanh().to_numpy(),
                 torch.from_numpy(b).atanh().numpy(), atol=1e-6)
    # spelled-out aliases resolve and share in-place semantics
    t = Tensor(b.copy())
    t.arctanh()
    assert_close(t.to_numpy(), torch.from_numpy(b).atanh().numpy(),
                 atol=1e-6)


def test_t5_axis_movement_and_views():
    t, tt = _pair((2, 3, 4))
    assert_close(t.swapaxes(0, 2).to_numpy(),
                 tt.swapaxes(0, 2).numpy())
    assert_close(t.swapdims(1, 2).to_numpy(), tt.swapdims(1, 2).numpy())
    parts = t.unbind(2)
    tparts = tt.unbind(1)                    # 1-based vs 0-based dim
    assert len(parts) == len(tparts) == 3
    for p, tp in zip(parts, tparts):
        assert_close(p.to_numpy(), tp.numpy())
    assert_close(t.unflatten(3, (2, 2)).to_numpy(),
                 tt.unflatten(2, (2, 2)).numpy())
    assert_close(t.positive().to_numpy(), tt.positive().numpy())


def test_t5_diagonal_family():
    t, tt = _pair((3, 4))
    assert_close(t.diagonal(1).to_numpy(), tt.diagonal(1).numpy())
    v = Tensor(np.asarray([1.0, 2.0, 3.0], np.float32))
    tv = torch.tensor([1.0, 2.0, 3.0])
    assert_close(v.diagflat(1).to_numpy(), torch.diag_embed(
        tv, offset=1).numpy())
    assert_close(v.diag_embed().to_numpy(),
                 torch.diag_embed(tv).numpy())
    b = Tensor(np.arange(6.0, dtype=np.float32).reshape(2, 3))
    tb = torch.arange(6.0).reshape(2, 3)
    assert_close(b.diag_embed(1).to_numpy(),
                 torch.diag_embed(tb, offset=1).numpy())


def test_t5_cumulative_family():
    a = np.asarray([[3.0, 1.0, 4.0, 1.0], [-1.0, -5.0, 2.0, 0.0]],
                   np.float32)
    t, tt = Tensor(a.copy()), torch.from_numpy(a.copy())
    vals, idx = t.cummax(2)
    tv, ti = tt.cummax(1)
    assert_close(vals.to_numpy(), tv.numpy())
    np.testing.assert_array_equal(idx.to_numpy() - 1, ti.numpy())
    vals, idx = t.cummin(2)
    tv, ti = tt.cummin(1)
    assert_close(vals.to_numpy(), tv.numpy())
    np.testing.assert_array_equal(idx.to_numpy() - 1, ti.numpy())
    assert_close(t.logcumsumexp(2).to_numpy(),
                 tt.logcumsumexp(1).numpy(), atol=1e-5)
    assert_close(np.asarray(t.logsumexp()),
                 tt.logsumexp(dim=(0, 1)).numpy(), atol=1e-5)
    assert_close(t.logsumexp(1).to_numpy(), tt.logsumexp(0).numpy(),
                 atol=1e-5)


def test_t5_nan_reductions():
    a = np.asarray([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]], np.float32)
    t, tt = Tensor(a.copy()), torch.from_numpy(a.copy())
    assert_close(np.float32(t.nansum()), tt.nansum().numpy())
    assert_close(t.nansum(1).to_numpy(), tt.nansum(0).numpy())
    assert_close(np.float32(t.nanmean()), tt.nanmean().numpy())
    assert_close(t.nanmean(2).to_numpy(), tt.nanmean(1).numpy())
    clean = np.asarray([[1.0, 2.0, 3.0, 4.0]], np.float32)
    tc = Tensor(clean.copy())
    ttc = torch.from_numpy(clean.copy())
    assert_close(np.float32(tc.quantile(0.5)),
                 ttc.quantile(0.5).numpy())
    assert_close(tc.quantile(0.25, 2).to_numpy(),
                 ttc.quantile(0.25, dim=1).numpy(), atol=1e-6)
    assert tc.nanquantile(0.5) == 2.5
    # numpy convention: even-count median AVERAGES the middle two
    # ([1,3,5,6] -> 4.0); torch.nanmedian returns the lower (3.0)
    assert np.isclose(t.nanmedian(), 4.0)


def test_t5_std_var_mean_pairs():
    t, tt = _pair((3, 4), seed=3)
    s, m = t.std_mean(1)
    # facade heritage conventions: std drops the reduced dim, mean keeps it
    assert_close(s.to_numpy(), torch.std(tt, dim=0).numpy(), atol=1e-5)
    assert_close(m.to_numpy(),
                 torch.mean(tt, dim=0, keepdim=True).numpy(), atol=1e-6)
    v, m = t.var_mean()
    tv, tm = torch.var_mean(tt)
    assert_close(np.float32(v), tv.numpy(), atol=1e-5)
    assert_close(np.float32(m), tm.numpy(), atol=1e-6)


def test_t5_integer_and_float_decomp():
    a = np.asarray([12, 18, 7], np.int64)
    b = np.asarray([8, 12, 21], np.int64)
    np.testing.assert_array_equal(
        Tensor(a).gcd(b).to_numpy(),
        torch.from_numpy(a).gcd(torch.from_numpy(b)).numpy())
    np.testing.assert_array_equal(
        Tensor(a).lcm(b).to_numpy(),
        torch.from_numpy(a).lcm(torch.from_numpy(b)).numpy())
    # beyond-int32 results refuse loudly instead of truncating (the
    # facade's jnp storage is int32 under JAX's default x64-off config)
    with pytest.raises(OverflowError, match="int32"):
        Tensor(np.asarray([100000])).lcm(np.asarray([99999]))
    x = np.asarray([0.75, -3.5, 10.0], np.float32)
    assert_close(Tensor(x).ldexp(np.asarray([2, 1, 3])).to_numpy(),
                 torch.from_numpy(x).ldexp(torch.tensor([2, 1, 3])).numpy())
    m, e = Tensor(x).frexp()
    tm, te = torch.from_numpy(x).frexp()
    assert_close(m.to_numpy(), tm.numpy())
    np.testing.assert_array_equal(e.to_numpy(), te.numpy())


def test_t5_special_functions():
    x = np.asarray([0.5, 1.0, 2.5], np.float32)
    assert_close(Tensor(x).i0().to_numpy(),
                 torch.from_numpy(x).i0().numpy(), atol=1e-5)
    assert_close(Tensor(x).mvlgamma(3).to_numpy(),
                 torch.from_numpy(x.astype(np.float64)).mvlgamma(3)
                 .numpy().astype(np.float32), atol=1e-4)
    assert_close(Tensor(x).polygamma(1).to_numpy(),
                 torch.polygamma(1, torch.from_numpy(x)).numpy(),
                 atol=1e-5)
    y = np.asarray([[1.0, 2.0, 4.0, 7.0]], np.float32)
    assert_close(np.float32(Tensor(y[0]).trapz(dx=2.0)),
                 torch.trapz(torch.from_numpy(y[0]), dx=2.0).numpy())
    a, ta = _pair((6,), seed=5)
    b, tb = _pair((6,), seed=6)
    assert np.isclose(a.vdot(b.to_numpy()),
                      torch.dot(ta, tb).item(), atol=1e-5)
    h, edges = Tensor(x).histogram(bins=4)
    th, tedges = torch.histogram(torch.from_numpy(x), bins=4)
    assert_close(h.to_numpy(), th.numpy())
    assert_close(edges.to_numpy(), tedges.numpy(), atol=1e-6)
    sb = np.asarray([-1.0, 0.0, 2.0], np.float32)
    np.testing.assert_array_equal(
        Tensor(sb).signbit().to_numpy(),
        torch.from_numpy(sb).signbit().numpy())
    assert_close(Tensor(sb).rsub(10.0, alpha=2.0).to_numpy(),
                 torch.rsub(torch.from_numpy(sb), 10.0, alpha=2.0).numpy())


def test_t5_linalg_family():
    rs = np.random.RandomState(9)
    m = rs.randn(3, 3).astype(np.float32)
    spd = (m @ m.T + 3 * np.eye(3)).astype(np.float32)
    t, tt = Tensor(spd.copy()), torch.from_numpy(spd.copy())
    assert_close(t.matrix_power(3).to_numpy(),
                 torch.linalg.matrix_power(tt, 3).numpy(), atol=1e-2)
    assert_close(t.pinverse().to_numpy(),
                 torch.linalg.pinv(tt).numpy(), atol=1e-4)
    sign, logabs = t.slogdet()
    tsign, tlog = torch.linalg.slogdet(tt)
    assert sign == tsign.item()
    assert np.isclose(logabs, tlog.item(), atol=1e-4)
    assert_close(t.cholesky().to_numpy(),
                 torch.linalg.cholesky(tt).numpy(), atol=1e-4)
    b = rs.randn(3, 2).astype(np.float32)
    assert_close(t.lstsq(b).to_numpy(),
                 np.linalg.lstsq(spd, b, rcond=None)[0], atol=1e-4)


def test_t5_masked_and_indexed_writes():
    a = np.zeros((2, 3), np.float32)
    mask = np.asarray([[True, False, True], [False, True, False]])
    out = Tensor(a.copy()).masked_scatter(mask, np.asarray([1.0, 2.0, 3.0]))
    tout = torch.zeros(2, 3).masked_scatter_(
        torch.from_numpy(mask), torch.tensor([1.0, 2.0, 3.0]))
    assert_close(out.to_numpy(), tout.numpy())
    with pytest.raises(ValueError, match="masked_scatter"):
        Tensor(a.copy()).masked_scatter(mask, np.asarray([1.0]))
    # broadcastable mask: every expanded position consumes one source
    # element (torch semantics), and the guard counts them
    bmask = np.asarray([True, False])
    bout = Tensor(np.zeros((2, 2), np.float32)).masked_scatter(
        bmask, np.asarray([9.0, 8.0]))
    tbout = torch.zeros(2, 2).masked_scatter_(
        torch.from_numpy(bmask), torch.tensor([9.0, 8.0]))
    assert_close(bout.to_numpy(), tbout.numpy())
    # integer inputs keep FLOAT bin edges (no truncated duplicates)
    ih, iedges = Tensor(np.asarray([0, 1, 2, 3], np.int32)).histogram(bins=4)
    assert iedges.to_numpy().dtype == np.float32
    assert len(np.unique(iedges.to_numpy())) == 5

    t = Tensor(np.zeros((3, 3), np.float32))
    out = t.index_put((np.asarray([1, 3]), np.asarray([2, 1])),
                      np.asarray([5.0, 7.0]))
    exp = np.zeros((3, 3), np.float32)
    exp[0, 1], exp[2, 0] = 5.0, 7.0
    assert_close(out.to_numpy(), exp)

    n = Tensor(np.arange(12.0, dtype=np.float32).reshape(3, 4))
    nc = n.narrow_copy(2, 2, 2)
    assert_close(nc.to_numpy(),
                 np.arange(12.0, dtype=np.float32).reshape(3, 4)[:, 1:3])


def test_t5_aliases_resolve():
    t, tt = _pair((4,), seed=7)
    assert_close(t.clone().arccos is not None and
                 Tensor(np.asarray([0.3], np.float32)).arccos().to_numpy(),
                 torch.tensor([0.3]).arccos().numpy(), atol=1e-6)
    a = Tensor(np.asarray([1.0, -2.0], np.float32))
    assert_close(a.absolute().to_numpy(), np.asarray([1.0, 2.0]))
    b, tb = _pair((4,), seed=8)
    np.testing.assert_array_equal(
        t.greater(b.to_numpy()).to_numpy(),
        tt.greater(tb).numpy())
    assert_close(Tensor.concat([Tensor(np.ones((1, 2), np.float32)),
                                Tensor(np.zeros((1, 2), np.float32))],
                               1).to_numpy(),
                 np.concatenate([np.ones((1, 2)), np.zeros((1, 2))], 0))
