"""One-compiled-program guards (plain helper module, no side effects).

The serving plane's core perf invariant is that knob changes, mesh
shapes, and traffic mixes are runtime DATA, never a recompile.  The
runtime half of that guarantee is pinned here; the static half is
bigdl_tpu/analysis (SPMD102/SPMD103 — see docs/analysis.md).  Both
halves reference this one utility so the invariant has a single home.

Import from here (``from tests.compile_guards import ...``), not from
``tests.conftest`` — conftest re-exports these for discoverability, but
importing it as a module would load a SECOND copy next to pytest's
``conftest`` instance and re-run its jax/XLA session setup.
"""


def compile_count(step_fn):
    """Number of programs a cached jitted step has compiled.  Accepts
    either a caching wrapper exposing ``_cache_size()`` directly (the
    decode steps' ``eng._step_fn``) or one holding it on ``._jitted``
    (the prefill steps' ``eng._batch_prefill_fn``)."""
    if hasattr(step_fn, "_cache_size"):
        return step_fn._cache_size()
    jitted = getattr(step_fn, "_jitted", None)
    if jitted is not None and hasattr(jitted, "_cache_size"):
        return jitted._cache_size()
    raise TypeError(
        f"{step_fn!r} exposes neither _cache_size() nor _jitted — not a "
        "cached jitted step")


def assert_compile_count(step_fn, expected, what=""):
    """Assert a cached jitted step has compiled exactly ``expected``
    programs — the shared compile-count regression guard used by the
    serving suites (sharded / admission / sampling)."""
    got = compile_count(step_fn)
    label = f" [{what}]" if what else ""
    assert got == expected, (
        f"compile-count guard{label}: expected {expected} compiled "
        f"program(s), found {got} — something recompiled that should "
        f"have been runtime data")
