"""Evaluator/Predictor plane + TensorBoard summary round-trip tests."""

import os

import numpy as np
import pytest


def _toy_classifier(rng, n_feat=6, n_cls=3):
    from bigdl_tpu.nn import Linear, LogSoftMax, Sequential

    m = Sequential().add(Linear(n_feat, n_cls)).add(LogSoftMax())
    m._ensure_params()
    return m


def _toy_samples(rng, n=32, n_feat=6, n_cls=3):
    from bigdl_tpu.dataset.sample import Sample

    return [
        Sample(rng.randn(n_feat).astype(np.float32),
               np.float32(rng.randint(1, n_cls + 1)))
        for _ in range(n)
    ]


def test_evaluator_top1_counts(rng):
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    m = _toy_classifier(rng)
    samples = _toy_samples(rng)
    (res,) = Evaluator(m).test(samples, [Top1Accuracy()], batch_size=8)
    acc, total = res.result()
    assert total == 32
    # cross-check against a manual forward
    xs = np.stack([s.feature() for s in samples])
    ys = np.array([int(s.label()) for s in samples])
    pred = np.asarray(m.forward(xs)).argmax(-1) + 1
    assert acc == pytest.approx((pred == ys).mean())


def test_module_evaluate_overload_and_predict_class(rng):
    from bigdl_tpu.optim import Top1Accuracy

    m = _toy_classifier(rng)
    samples = _toy_samples(rng, n=16)
    (res,) = m.evaluate(samples, [Top1Accuracy()], batch_size=4)
    _, total = res.result()
    assert total == 16
    # predict/predict_class on raw arrays
    xs = np.stack([s.feature() for s in samples])
    probs = m.predict(xs, batch_size=4)
    assert probs.shape == (16, 3)
    cls = m.predict_class(xs, batch_size=4)
    assert cls.min() >= 1 and cls.max() <= 3
    np.testing.assert_array_equal(cls, probs.argmax(-1) + 1)


def test_predict_restores_training_mode(rng):
    m = _toy_classifier(rng)
    m.training()
    xs = rng.randn(4, 6).astype(np.float32)
    m.predict(xs)
    assert m.is_training() is True
    m.evaluate()
    m.predict(xs)
    assert m.is_training() is False


def test_evaluator_on_mesh(rng):
    """Distributed eval: batch sharded over the 8-device CPU mesh."""
    import jax
    from jax.sharding import Mesh

    from bigdl_tpu.optim import Evaluator, Loss, Top1Accuracy

    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("data",))
    m = _toy_classifier(rng)
    samples = _toy_samples(rng, n=64)
    res = Evaluator(m, mesh=mesh).test(samples, [Top1Accuracy()], batch_size=16)
    (acc_res,) = res
    _, total = acc_res.result()
    assert total == 64
    # ragged final batch (20 % 16 = 4 rows, not divisible by the 8-dev mesh)
    ragged = _toy_samples(rng, n=20)
    (r,) = Evaluator(m, mesh=mesh).test(ragged, [Top1Accuracy()], batch_size=16)
    _, total = r.result()
    assert total == 20


def test_evaluator_device_preprocess_local_and_mesh(rng):
    """Standalone Evaluator/Predictor must honor a device_preprocess the
    way the optimizer's validation path does (round-4 review): a model
    trained on normalized input scores raw batches through the same
    transform, on both the local and the sharded path."""
    import jax
    from jax.sharding import Mesh

    from bigdl_tpu.optim import Evaluator, Predictor, Top1Accuracy

    m = _toy_classifier(rng)
    raw = [s for s in _toy_samples(rng, n=16)]

    def pre(x):
        return x * 0.1 - 0.5

    for mesh in (None, Mesh(np.asarray(jax.devices()).reshape(8), ("data",))):
        (res,) = Evaluator(m, mesh=mesh, device_preprocess=pre).test(
            raw, [Top1Accuracy()], batch_size=8)
        acc, total = res.result()
        assert total == 16
        xs = np.stack([s.feature() for s in raw])
        ys = np.array([int(s.label()) for s in raw])
        want = (np.asarray(m.forward(pre(xs))).argmax(-1) + 1 == ys).mean()
        assert acc == pytest.approx(want)
        probs = Predictor(m, mesh=mesh, device_preprocess=pre).predict(
            xs, batch_size=8)
        np.testing.assert_allclose(
            probs, np.asarray(m.forward(pre(xs))), atol=1e-5)


def test_evaluator_accepts_dataset_and_respects_batch_size(rng):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.optim import Evaluator, Top1Accuracy

    m = _toy_classifier(rng)
    samples = _toy_samples(rng, n=24)
    (res,) = Evaluator(m).test(DataSet.array(samples), [Top1Accuracy()],
                               batch_size=8)
    _, total = res.result()
    assert total == 24


def test_biends_uses_backward_first_step(rng):
    """The backward half of the classifier feature must see the WHOLE
    sequence, not just the final (often padding) token."""
    from bigdl_tpu.models import TextClassifier

    m = TextClassifier(class_num=2, embedding_dim=4, hidden_size=4,
                       vocab_size=10, embedding_input=False)
    m._ensure_params()
    m.evaluate()
    x = rng.randint(1, 11, size=(2, 6)).astype(np.float32)
    base = np.asarray(m.forward(x))
    x2 = x.copy()
    x2[:, 0] = (x2[:, 0] % 10) + 1  # perturb FIRST token
    changed = np.asarray(m.forward(x2))
    assert not np.allclose(base, changed)


def test_tfevent_crc32c_known_vector():
    from bigdl_tpu.visualization.tensorboard import crc32c

    # known vectors: 32 zero bytes and "123456789"
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283


def test_summary_roundtrip(tmp_path):
    from bigdl_tpu.visualization import TrainSummary, ValidationSummary

    ts = TrainSummary(str(tmp_path), "app")
    for i in range(5):
        ts.add_scalar("Loss", 1.0 / (i + 1), i)
        ts.add_scalar("Throughput", 100.0 + i, i)
    ts.close()
    hist = TrainSummary(str(tmp_path), "app").read_scalar("Loss")
    got = {s: v for s, v in hist}
    for i in range(5):
        assert got[i] == pytest.approx(1.0 / (i + 1))

    vs = ValidationSummary(str(tmp_path), "app")
    vs.add_scalar("Top1Accuracy", 0.5, 10)
    vs.close()
    hist = ValidationSummary(str(tmp_path), "app").read_scalar("Top1Accuracy")
    assert (10, pytest.approx(0.5)) in [(s, v) for s, v in hist]


def test_optimizer_writes_summaries(tmp_path, rng):
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.nn.criterion import ClassNLLCriterion
    from bigdl_tpu.optim import Optimizer, SGD, Top1Accuracy, Trigger
    from bigdl_tpu.visualization import TrainSummary, ValidationSummary

    m = _toy_classifier(rng)
    samples = _toy_samples(rng)
    opt = Optimizer(model=m, dataset=DataSet.array(samples),
                    criterion=ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(SGD(learning_rate=0.1))
    opt.set_end_when(Trigger.max_epoch(2))
    ts = TrainSummary(str(tmp_path), "job")
    vs = ValidationSummary(str(tmp_path), "job")
    opt.set_train_summary(ts)
    opt.set_val_summary(vs)
    opt.set_validation(Trigger.every_epoch(), samples, [Top1Accuracy()],
                       batch_size=8)
    opt.optimize()
    assert len(ts.read_scalar("Loss")) == 8  # 4 iters/epoch × 2 epochs
    assert len(ts.read_scalar("LearningRate")) == 8
    assert len(vs.read_scalar("Top1Accuracy")) == 2


def test_predictor_ragged_batch_tail():
    """A batch-1 tail on a Reshape-headed model must not lose its batch
    axis (pre-existing Predictor bug found via predict_image)."""
    import numpy as np

    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(3)
    m = LeNet5(10)
    x = np.random.RandomState(0).rand(5, 1, 28, 28).astype(np.float32)
    out = np.asarray(m.predict(x, batch_size=2))
    assert out.shape == (5, 10)
    # per-row parity with the full-batch forward
    np.testing.assert_allclose(out, np.asarray(m.predict(x, batch_size=5)),
                               rtol=1e-5, atol=1e-6)
