"""Quantized KV serving (kv_dtype="int8": per-(slot, head)-scaled int8
pooled K/V + the pooled decode-attention read, ops/decode_attention.py):
greedy token parity vs the float-KV engine (fp32 + bf16 activations,
the weight_q parity pattern — pinned configs where top-2 argmax gaps
are real), fixed-seed sampled reproducibility across eviction and
readmission into recycled slots, the zero-extra-compiles guarantee,
scale lifecycle in the KVPool (scatter with rows, reset on free),
kv-format metrics/capacity accounting, prefix-cache interop, sharded-
mesh parity, and the kv_quant bench smoke."""

import numpy as np
import pytest


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    """One model for the module — engines over it share the cached
    jitted steps, so each (dtype, kv_quant, n_slots) compiles once."""
    return _make_lm()


def _reqs(n=8, vocab=29, seed=14):
    """More requests than any test engine has slots, so later requests
    are admitted into freed (recycled) slots — a stale dequant scale
    on a recycled slot would corrupt exactly these rows. The default
    seed is PINNED to a request set whose top-2 logit gaps clear the
    ~0.5% int8 cache-rounding noise on the untrained parity model
    (about half of all seeds put some rollout on a near-tie that any
    sub-fp32 cache format flips — chosen-logprob deltas stay ~0.01
    either way; see test_greedy_parity_int8_vs_float_kv)."""
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, vocab + 1,
                         size=(int(rng.randint(1, 8)),)).tolist(),
             int(rng.randint(4, 11))) for _ in range(n)]


def _run(lm, reqs, sampling=None, **kw):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, **kw)
    sampling = sampling or [None] * len(reqs)
    rids = [eng.submit(p, max_new_tokens=n, sampling=sp)
            for (p, n), sp in zip(reqs, sampling)]
    outs = eng.drain()
    return eng, rids, outs


# -- greedy parity (THE accuracy contract) ---------------------------------

@pytest.mark.parametrize("dtype_name", ["fp32", "bf16"])
def test_greedy_parity_int8_vs_float_kv(dtype_name, lm):
    """int8-KV greedy decode is token-identical to the float-KV engine
    on the pinned parity config, through eviction + readmission into
    recycled slots, and chosen-token logprobs agree to the quantization
    tolerance. (Parity is PINNED, not universal: per-(slot, head) int8
    carries ~0.5% cache-rounding error, so workloads whose top-2 logit
    gaps sit below that — e.g. long rollouts of a near-uniform
    untrained model over a large vocab — can flip near-tie argmaxes,
    exactly as a bf16 cache can vs fp32. This config's gaps are real
    (the _reqs seed is pinned for that); a divergence here means the
    quantized path broke, not that the model got unlucky.)"""
    import jax.numpy as jnp

    dtype = None if dtype_name == "fp32" else jnp.bfloat16
    reqs = _reqs()
    e_f, r_f, o_f = _run(lm, reqs, n_slots=3, compute_dtype=dtype)
    e_q, r_q, o_q = _run(lm, reqs, n_slots=3, compute_dtype=dtype,
                         kv_dtype="int8")
    assert e_q.kv_dtype == "int8" and e_q.pool.quantized
    for (p, n), a, b in zip(reqs, r_f, r_q):
        np.testing.assert_array_equal(
            o_f[a], o_q[b], err_msg=f"prompt={p} dtype={dtype_name}")
        np.testing.assert_allclose(e_f.logprobs(a), e_q.logprobs(b),
                                   atol=0.08)
    assert e_q.pool.free_slots == e_q.pool.n_slots     # clean drain


def test_greedy_parity_per_request_admission(lm):
    """The per_request (B=1 prefill) admission path writes the same
    quantized rows: parity vs the batched-admission int8 engine AND
    vs the float engine, including 1-token prompts (whose rows enter
    decode with a still-zero scale established on the first step)."""
    reqs = [([3], 6), ([7, 1, 4], 8), ([2, 9], 5), ([5] * 7, 6)]
    e_f, r_f, o_f = _run(lm, reqs, n_slots=2)
    e_b, r_b, o_b = _run(lm, reqs, n_slots=2, kv_dtype="int8")
    e_p, r_p, o_p = _run(lm, reqs, n_slots=2, kv_dtype="int8",
                         admission="per_request")
    for a, b, c in zip(r_f, r_b, r_p):
        np.testing.assert_array_equal(o_f[a], o_b[b])
        np.testing.assert_array_equal(o_b[b], o_p[c])


# -- fixed-seed sampled reproducibility ------------------------------------

def test_sampled_seed_reproducible_across_evict_readmit(lm):
    """A seeded sampled request under int8 KV produces ONE token
    stream regardless of neighbors, slot assignment, or readmission
    into a recycled slot (RNG lanes are request-keyed; the recycled
    slot's dequant scale was reset on free)."""
    from bigdl_tpu.serving import SamplingParams

    sp = SamplingParams(temperature=0.9, top_k=8, top_p=0.95, seed=123)
    probe = ([3, 7, 2], 8)

    # alone in the pool
    _, r_a, o_a = _run(lm, [probe], sampling=[sp], n_slots=3,
                       kv_dtype="int8")
    want = o_a[r_a[0]]
    # submitted LAST behind 6 greedy drains on a 2-slot pool: by the
    # time it admits, every slot has been used and freed at least once
    reqs = _reqs(6) + [probe]
    sps = [None] * 6 + [sp]
    _, r_b, o_b = _run(lm, reqs, sampling=sps, n_slots=2,
                       kv_dtype="int8")
    np.testing.assert_array_equal(o_b[r_b[-1]], want)
    # and the whole mixed trace replays identically run-over-run
    _, r_c, o_c = _run(lm, reqs, sampling=sps, n_slots=2,
                       kv_dtype="int8")
    for b, c in zip(r_b, r_c):
        np.testing.assert_array_equal(o_b[b], o_c[c])


# -- one compiled program --------------------------------------------------

def test_zero_extra_compiles_for_quantization():
    """Mixed greedy/sampled traffic through the int8 engine runs ONE
    compiled decode program — the same count as the float engine.
    Quantization is an engine-level storage format, never per-row
    runtime state, so it must not add programs for any traffic mix.
    (Fresh model: the jitted-step cache is per-model, and the
    module-scope lm's wrappers already hold other tests' n_slots
    shapes.)"""
    from tests.compile_guards import assert_compile_count

    from bigdl_tpu.serving import SamplingParams

    lm = _make_lm()
    reqs = _reqs(6)
    sps = [None if i % 2 else SamplingParams(temperature=0.8, top_k=5,
                                             seed=50 + i)
           for i in range(len(reqs))]
    e_f, _, _ = _run(lm, reqs, sampling=sps, n_slots=3)
    e_q, _, _ = _run(lm, reqs, sampling=sps, n_slots=3, kv_dtype="int8")
    assert_compile_count(e_f._step_fn, 1, "float-KV mixed traffic")
    assert_compile_count(e_q._step_fn, 1, "int8-KV mixed traffic")


# -- capacity accounting + metrics -----------------------------------------

def test_kv_bytes_per_slot_halved(lm):
    """The headline capacity math: int8 KV bytes per slot are ≤ ~half
    the bf16 cache's and ~a quarter of fp32's (per-(slot, head) fp32
    scales cost ~0.1%), and the serving metrics expose the format."""
    import jax.numpy as jnp

    e_32, _, _ = _run(lm, [([1], 2)], n_slots=2)
    e_16, _, _ = _run(lm, [([1], 2)], n_slots=2,
                      compute_dtype=jnp.bfloat16)
    e_q, _, _ = _run(lm, [([1], 2)], n_slots=2, kv_dtype="int8")
    assert e_32.pool.kv_bytes_per_slot / e_q.pool.kv_bytes_per_slot > 3.8
    assert e_16.pool.kv_bytes_per_slot / e_q.pool.kv_bytes_per_slot > 1.9
    s = e_q.metrics.summary()
    assert s["serving/kv_bits"] == 8.0
    assert s["serving/kv_bytes_per_slot"] == e_q.pool.kv_bytes_per_slot
    assert s["serving/kv_slots_per_gib"] == float(
        (1 << 30) // e_q.pool.kv_bytes_per_slot)
    assert s["serving/kv_slots_per_gib"] > 3.8 * (
        e_32.metrics.summary()["serving/kv_slots_per_gib"])
    assert "int8" in repr(e_q.pool)


def test_int8_draft_independence_exact(lm):
    """The accepted-only scale merge's contract, pinned at the BYTE
    level: under ``kv_dtype="int8"`` the verify step's carry update is
    a bitwise function of its ACCEPTED columns — a rejected draft can
    reach neither the (slot, head) dequant scales nor the stored int8
    bytes (the chunk attention reads float chunk K/V, and the grow-only
    merge + quantized scatter run after acceptance over emitted columns
    only). Pinned two ways: (a) end-to-end stream equality across a
    weight-tied and a garbage draft on a mixed greedy/sampled trace;
    (b) the unit contract — two verify calls on identical state whose
    REJECTED draft columns differ (same accepted outcome) must return
    bitwise-identical carries, scales and K/V bytes included. Before
    the restructure, (b) failed: the whole-chunk amax let the rejected
    columns grow a row's scale one step early."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import get_batch_verify_step
    from bigdl_tpu.serving import (
        SamplingParams, ServingEngine, SpeculativeConfig,
    )
    from bigdl_tpu.serving.sampling import lane_key, make_knob_rows

    good = _make_lm()                       # weight-tied: accepts a lot
    bad = _make_lm(seed=31)                 # garbage: rejects a lot
    reqs = [([3, 7, 2], 9, None),
            ([5, 1], 8, SamplingParams(temperature=0.9, top_k=8,
                                       seed=123)),
            ([9], 6, None)]

    # (a) stream equality across drafts on a mixed trace
    outs = []
    for draft in (good, bad):
        eng = ServingEngine(lm, n_slots=3, kv_dtype="int8",
                            speculative=SpeculativeConfig(draft, k=3))
        rids = [eng.submit(p, max_new_tokens=n, sampling=sp)
                for p, n, sp in reqs]
        drained = eng.drain()
        outs.append([list(drained[r]) for r in rids])
    assert outs[0] == outs[1]

    # (b) the unit contract on the verify step itself
    V = 29
    verify_fn, init = get_batch_verify_step(lm, None, width=4,
                                            kv_quant=True)
    knobs = {k: jnp.asarray(v) for k, v in make_knob_rows(2).items()}

    def fresh():
        c = init(2)
        c["rng"] = c["rng"].at[:].set(jnp.asarray(lane_key(3), jnp.uint32))
        return c

    import jax

    from bigdl_tpu.models.transformer import serving_params

    P = jax.device_put(serving_params(lm, None))

    def call(tokens):
        t, lp, ne, carry = verify_fn(
            P, jnp.asarray(tokens, jnp.int32),
            jnp.asarray([4, 0], jnp.int32), fresh(), knobs)
        return np.asarray(t), np.asarray(ne), carry

    # learn the greedy draws so we can build drafts with a CONTROLLED
    # accepted prefix: d0 = the draw after feeding token 2, d1 = the
    # draw after the accepted continuation (2, d0)
    probe, _, _ = call([[2, 0, 0, 0], [0] * 4])
    d0 = int(probe[0, 0])
    probe2, _, _ = call([[2, d0, 0, 0], [0] * 4])
    d1 = int(probe2[0, 1])

    def carry_bytes(c):
        return {k: np.asarray(v) for k, v in c.items()}

    # all-rejected: first draft mismatches in both calls, every later
    # column differs between them -> n_emit == 1, carries bitwise equal
    a = [[2, (d0 + 1) % V, (d0 + 3) % V, (d0 + 5) % V], [0] * 4]
    b = [[2, (d0 + 2) % V, (d0 + 7) % V, (d0 + 11) % V], [0] * 4]
    _, ne_a, ca = call(a)
    _, ne_b, cb = call(b)
    assert ne_a[0] == ne_b[0] == 1
    for k, va in carry_bytes(ca).items():
        np.testing.assert_array_equal(
            va, np.asarray(cb[k]),
            err_msg=f"rejected drafts leaked into carry[{k!r}]")

    # partial accept: first draft matches (d0), second mismatches with
    # DIFFERENT rejected tokens -> n_emit == 2, carries bitwise equal
    a = [[2, d0, (d1 + 1) % V, (d1 + 3) % V], [0] * 4]
    b = [[2, d0, (d1 + 2) % V, (d1 + 7) % V], [0] * 4]
    _, ne_a, ca = call(a)
    _, ne_b, cb = call(b)
    assert ne_a[0] == ne_b[0] == 2
    for k, va in carry_bytes(ca).items():
        np.testing.assert_array_equal(
            va, np.asarray(cb[k]),
            err_msg=f"rejected tail leaked into carry[{k!r}]")


def test_kv_dtype_validation(lm):
    """The knob is declarative and fails loudly: unknown formats,
    float spellings that contradict compute_dtype, and a KVPool whose
    carry disagrees with its claimed format all raise."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import make_batch_decode_step
    from bigdl_tpu.serving import KVPool, ServingEngine

    with pytest.raises(ValueError, match="unknown kv_dtype"):
        ServingEngine(lm, n_slots=2, kv_dtype="fp8")
    with pytest.raises(ValueError, match="conflicts with"):
        ServingEngine(lm, n_slots=2, kv_dtype="bf16")   # fp32 compute
    with pytest.raises(ValueError, match="conflicts with"):
        ServingEngine(lm, n_slots=2, compute_dtype=jnp.bfloat16,
                      kv_dtype="fp32")
    # matching spellings are accepted
    assert ServingEngine(lm, n_slots=2, kv_dtype="fp32").kv_dtype == "fp32"
    # uncanonical float compute dtypes keep constructing on the default
    # path (kv_dtype=None follows whatever the cache stores — an fp16
    # engine served fine before the knob existed and must keep doing so)
    assert ServingEngine(lm, n_slots=2, compute_dtype=jnp.float16
                         ).kv_dtype == "float16"
    _, init_f = make_batch_decode_step(lm, sampling=True)
    with pytest.raises(ValueError, match="carry stores"):
        KVPool(init_f, 2, kv_dtype="int8")


def test_pool_scale_lifecycle(lm):
    """Dequant scales ride the admission scatter with their rows and
    reset to zero on free — a recycled slot must not inherit its
    previous occupant's quantization range."""
    import jax.numpy as jnp

    from bigdl_tpu.models.transformer import (
        get_batch_decode_step, get_prefill_step, serving_params,
    )
    from bigdl_tpu.serving import KVPool

    _, init_q = get_batch_decode_step(lm, sampling=True, kv_quant=True)
    pool = KVPool(init_q, 3, kv_dtype="int8")
    assert pool.quantized and pool.kv_dtype == "int8"

    prefill = get_prefill_step(lm, kv_quant=True)
    P = serving_params(lm, None)
    _, pc = prefill(P, jnp.asarray([[3, 7, 1, 4]], jnp.int32), init_q(1))
    slot = pool.alloc()
    pool.write_prefill(slot, pc, 4)
    others = [s for s in range(3) if s != slot]
    for i in range(pool.n_layers):
        for kind in ("k", "v"):
            sc = np.asarray(pool.carry[f"{kind}{i}_scale"])
            assert (sc[slot] > 0).all()          # scales landed with rows
            assert (sc[others] == 0).all()       # neighbors untouched
    pool.free(slot)
    for i in range(pool.n_layers):
        for kind in ("k", "v"):
            sc = np.asarray(pool.carry[f"{kind}{i}_scale"])
            assert (sc == 0).all()               # reset on free


# -- prefix cache ----------------------------------------------------------

def test_prefix_cache_with_int8_kv(lm):
    """Shared-prefix traffic through the int8 engine with the prefix
    cache on: hits happen, outputs are deterministic run-over-run, and
    greedy tokens match the cache-off int8 engine (suffix continuation
    requantizes the cached prefix through the grow-only merge)."""
    rng = np.random.RandomState(11)
    base = [5, 9, 13, 2]
    reqs = [(base + rng.randint(1, 30, size=(2 + i % 3,)).tolist(), 6)
            for i in range(6)]
    e_off, r_off, o_off = _run(lm, reqs, n_slots=3, kv_dtype="int8")
    e_on, r_on, o_on = _run(lm, reqs, n_slots=3, kv_dtype="int8",
                            prefix_cache=True)
    assert e_on.metrics.summary().get("serving/prefix_hit_rate", 0) > 0
    for a, b in zip(r_off, r_on):
        np.testing.assert_array_equal(o_off[a], o_on[b])


# -- sharded plane ---------------------------------------------------------

@pytest.mark.mesh
@pytest.mark.parametrize("parallelism", [{"data": 4},
                                         {"data": 2, "model": 2}])
def test_sharded_int8_token_identical(parallelism):
    """int8 KV on the emulated 8-device mesh: slot-DP shards the int8
    payload rows and their scale rows together; TP shards both on the
    head axis (scales travel with the heads they dequantize). Outputs
    must match the unsharded int8 engine token for token, still ONE
    compiled decode program."""
    from tests.compile_guards import assert_compile_count

    lm = _make_lm(V=96, max_len=64, seed=17)
    lm2 = _make_lm(V=96, max_len=64, seed=17)     # private step cache:
    # the sharded engine's carry arrives with a NamedSharding, which is
    # legitimately its own program next to the unsharded engine's
    reqs = _reqs(8, vocab=96, seed=6)
    e0, r0, o0 = _run(lm, reqs, n_slots=4, kv_dtype="int8")
    e1, r1, o1 = _run(lm2, reqs, n_slots=4, kv_dtype="int8",
                      parallelism=parallelism)
    for a, b in zip(r0, r1):
        np.testing.assert_array_equal(o0[a], o1[b])
    assert_compile_count(e1._step_fn, 1, f"int8 {parallelism}")
    assert e1.pool.n_shards == parallelism.get("data", 1)
    assert e1.pool.quantized


# -- bench smoke -----------------------------------------------------------

def test_kv_quant_bench_smoke():
    """benchmarks/serving_bench.py --scenario kv_quant end-to-end on a
    tiny config: the capacity ratio clears the ~4x fp32 headline, the
    at-budget engine reproduces the equal-slot engine bitwise, and
    quantization adds zero decode compiles."""
    import sys

    sys.path.insert(0, "benchmarks")
    try:
        import serving_bench
    finally:
        sys.path.pop(0)
    out = serving_bench.run_kv_quant(model="tiny", n_requests=4,
                                     gen_tokens=6, budget_slots=2)
    assert out["extra_decode_compiles"] == 0
    assert out["outputs_match_at_budget"] is True
    assert out["kv_bytes_ratio"] > 3.8               # fp32 float KV
    assert out["slots_at_budget_ratio"] >= 1.9       # the acceptance bar
    assert out["int8_kv_at_budget"]["slots"] >= \
        2 * out["float_kv"]["slots"] - 1
