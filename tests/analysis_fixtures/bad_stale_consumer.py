"""ASY306 stale-consumer: a delayed-site fence readback fed back into
a LATER dispatch in the same hot unit — the consume-before-dispatch
ordering that re-serializes the dispatch-ahead window (the dispatch
must wait for the fence) and ships host state that is W steps stale.
Chaining on the previous dispatch's DEVICE handle and the unreachable
replay harness are the false-positive guards."""

import time
from collections import deque

import jax.numpy as jnp

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence


class _Entry:
    def __init__(self, tok, chosen):
        self.tok = tok
        self.chosen = chosen


class StaleWindowEngine:
    def __init__(self, model, dtype, clock=time.perf_counter):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None
        self._clock = clock
        self.dispatch_ahead = 2
        self._win = deque()
        self.phases = {}
        self.carry = None

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # steady state here consumes the DEFERRED readback and re-uploads
        # it as the next dispatch's tokens — every window step now blocks
        # on the fence before it can launch (W buys nothing), and the
        # re-uploaded tokens lag the in-flight dispatches by W steps
        if self._win:
            e = self._win.popleft()
            t_f = self._clock()
            nxt, lps = fence("decode", e.tok, e.chosen)
            self.phases["fence_wait"] = self._clock() - t_f
            toks = jnp.asarray(nxt)            # stale host state, re-uploaded
            tok, lp = self._dispatch(          # EXPECT: ASY306
                "decode", self._step_fn, params, toks, active, knobs)
        else:
            tok, lp = self._dispatch(
                "decode", self._step_fn, params, tokens, active, knobs)
        self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            e = self._win.popleft()
            t_f = self._clock()
            nxt, lps = fence("decode", e.tok, e.chosen)
            self.phases["fence_wait"] = self._clock() - t_f

    def steady_step(self, params, active, knobs):  # analysis: hotpath-root
        # the sanctioned spelling: chain on the previous dispatch's
        # DEVICE handle — no fence on the dispatch path, no staleness
        prev = self._win[-1]
        tok, lp = self._dispatch(
            "decode", self._step_fn, params, prev.tok, active, knobs)
        self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            e = self._win.popleft()
            t_f = self._clock()
            nxt, lps = fence("decode", e.tok, e.chosen)
            self.phases["fence_wait"] = self._clock() - t_f


def replay_consumer(engine, params, tokens, active, knobs):
    """Cold twin: a replay harness legitimately re-feeds fenced tokens
    through the step function — unreachable from a hot root, exempt."""
    nxt, lps = fence("decode", tokens, active)
    toks = jnp.asarray(nxt)
    return engine._dispatch(
        "decode", engine._step_fn, params, toks, active, knobs)
