"""SPMD103 fixtures: recompile hazards in Pallas BlockSpec index maps.

An index map is traced into the kernel's compiled program. Closing it
over an enclosing function's local — a per-request offset, a
data-derived start row — bakes that VALUE into the trace, so every
distinct value silently compiles a new pallas program (the kernel-side
cousin of the f-string-in-jit hazard). Index maps must be pure
functions of the grid indices; per-call data belongs in operands
(scalar prefetch) or the grid. Module-level constants are fine: they
cannot vary call to call.
"""

from jax.experimental import pallas as pl

N_HEADS = 4  # module-level constant: capturing this cannot recompile


def _kernel(q_ref, o_ref):
    o_ref[...] = q_ref[...] * 2.0


def sliced_attention(q, start, block):
    # `start` arrives per request — every distinct value is a new
    # compiled kernel keyed by the closure, not an argument
    qspec = pl.BlockSpec(
        (1, block), lambda i, j: (i, j + start))  # EXPECT: SPMD103
    # same hazard through the index_map keyword
    ospec = pl.BlockSpec((1, block),
                         index_map=lambda i, j: (start, j))  # EXPECT: SPMD103
    return pl.pallas_call(
        _kernel, grid=(4, 4), in_specs=[qspec], out_specs=ospec,
        out_shape=q)


def clean_shadow_in_nested_def(q, block):
    # a SIBLING nested function's local named like the module constant
    # must not make the index map's `N_HEADS` look like a per-call
    # capture — the lambda resolves the module-level name
    def helper():
        N_HEADS = 99  # noqa: F841 — different scope entirely
        return N_HEADS

    spec = pl.BlockSpec((1, block), lambda i, j: (i // N_HEADS, j))
    return pl.pallas_call(_kernel, grid=(4, 4), in_specs=[spec],
                          out_specs=spec, out_shape=q), helper


def clean_attention(q, block):
    # pure functions of the grid indices: nothing captured
    qspec = pl.BlockSpec((1, block), lambda i, j: (i, j))
    # a module-level constant is not per-call state
    ospec = pl.BlockSpec((1, block), lambda i, j: (i // N_HEADS, j))
    # data-derived locals in the BLOCK SHAPE are fine — shapes key the
    # compile legitimately (a new shape IS a new program)
    hspec = pl.BlockSpec((1, q.shape[-1]), lambda i, j: (i, 0))
    return pl.pallas_call(
        _kernel, grid=(4, 4), in_specs=[qspec, ospec], out_specs=hspec,
        out_shape=q)
