"""SPMD104/SPMD105 fixtures: the serving watchdog's retry-with-evict
loop (serving/faults.py + ServingEngine._recover_rows).

The engine's fault recovery re-dispatches a DONATED-carry decode step
after a failed attempt.  Two tempting spellings are wrong.  (a)
Retrying with the SAME carry the failed attempt was already handed:
donation means XLA reused that buffer's memory for the outputs, so the
retry reads garbage — the real engine instead re-points the pool at the
step's RETURNED carry (valid buffers) and replays the evicted rows from
host state (``prompt + output``).  (b) Putting the health check INSIDE
the compiled step as Python control flow on traced outputs: the check
must run on host, on the read-back arrays — on a tracer the `if`
raises, and "fixing" it by hoisting the value bakes one verdict into
the program.  The legal spellings — the rebind-the-carry retry loop and
the host-side verdict on concrete numpy arrays — are below and must not
be flagged.
"""

import jax
import jax.numpy as jnp
import numpy as np


def _decode(params, tokens, carry):
    logp = jnp.take(params, jnp.clip(tokens, 0, params.shape[0] - 1),
                    axis=0)
    return logp, {"pos": carry["pos"] + 1}


step = jax.jit(_decode, donate_argnums=(2,))


def retry_wrong(params, tokens, carry):
    # first attempt donates `carry`; its buffers now back the OUTPUTS
    logp, new_carry = step(params, tokens, carry)
    if not np.isfinite(np.asarray(logp)).all():     # host verdict — fine
        # WRONG retry: re-dispatching with the donated input reads a
        # dead buffer (the engine re-points at new_carry instead)
        logp, new_carry = step(params, tokens, carry)  # EXPECT: SPMD104
    return logp, new_carry


def retry_right(params, tokens, carry):
    # the legal retry loop: the carry name REBINDS to each attempt's
    # returned (valid) buffers, so no dead buffer is ever read
    for _ in range(3):
        logp, carry = step(params, tokens, carry)
        if np.isfinite(np.asarray(logp)).all():     # host verdict — fine
            break
    return logp, carry


def watchdog_step(params, tokens, carry):
    # WRONG: the health check spelled inside the traced step — Python
    # control flow needs a concrete bool, but every value here is a
    # tracer; the verdict belongs on host, after readback
    logp = jnp.take(params, jnp.clip(tokens, 0, params.shape[0] - 1),
                    axis=0)
    if params.max() > 1e30:  # EXPECT: SPMD105
        logp = jnp.zeros_like(logp)
    while tokens.sum() < 0:  # EXPECT: SPMD105
        tokens = tokens + 1
    return logp, {"pos": carry["pos"] + 1}


checked_step = jax.jit(watchdog_step)
