"""Caller half of the cross-module SRV204 demonstration (see
xmod_donation_helper.py).  The import resolves through the project
symbol table: ``ingest`` donates its first parameter two modules away,
so reading ``carry`` after the call is a use-after-donation."""

from xmod_donation_helper import ingest


def serve_broken(carry, upd):
    out = ingest(carry, upd)
    stale = carry["pos"]                          # EXPECT: SRV204
    return out, stale


def serve_rebound(carry, upd):
    carry = ingest(carry, upd)
    return carry["pos"]
