"""SPMD105 fixtures: the chunked-admission pump pattern.

Chunked streaming admission (``serving/chunked.py``) keeps per-row
chunk progress as HOST data (``KVPool.chunk_done``/``chunk_target``)
and drives the pump loop entirely outside any trace — that is what
lets the one compiled ``(1, L)`` chunk-prefill program serve every
progress state.  The tempting spelling is to move the loop INSIDE a
traced step and branch on (or iterate over) each row's traced
progress: on a tracer that raises TracerBoolConversionError, and the
"fix" of hoisting progress to the host bakes one progress pattern into
the program — a recompile per distinct chunk schedule, exactly the
admission stall the subsystem exists to remove.  Mask arithmetic
(``jnp.arange(L) < remaining[:, None]``) is the legal in-trace
spelling, and the host-side pump (progress as plain ints, never
traced) is the legal loop — neither may be flagged.
"""

import jax
import jax.numpy as jnp


def chunk_step(params, tokens, progress, carry):
    # legal spelling: per-row chunk validity is MASK arithmetic, so the
    # traced progress stays runtime data of the one program
    L = tokens.shape[1]                          # static shape — fine
    inb = jnp.arange(L)[None] < progress[:, None]
    x = jnp.where(inb, tokens, 0)
    if tokens.ndim != 2:                         # static fact — fine
        x = x[None]
    if progress.max() < L:  # EXPECT: SPMD105
        x = x + 1
    while progress.sum() > 0:  # EXPECT: SPMD105
        progress = progress - 1
    done = 1 if progress[0] else 0  # EXPECT: SPMD105
    pos = carry["pos"] + jnp.where(progress > 0, 1, 0) + done
    return x, pos


chunk_prefill = jax.jit(chunk_step)


def host_pump(pool, plans, budget):
    """The serving engine's ACTUAL spelling: chunk progress is host
    data (``pool.chunk_done`` is a numpy int array), so the pump may
    branch and loop freely — nothing here is ever traced."""
    spent = 0
    for slot, (req, pf) in plans.items():
        done = int(pool.chunk_done[slot])
        while done < len(pf) and spent < budget:
            n = min(budget - spent, len(pf) - done)
            done += n
            spent += n
    return spent
