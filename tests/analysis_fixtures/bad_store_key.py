"""MH405 store-key-namespace: block-store keys built from process-
divergent values (a per-process slot number, anything derived from
``jax.process_index()``) WITHOUT the process-id namespace — two
processes can construct the same key for different rows and one
silently wins (cross-process key collision).  Pid-namespaced keys (the
BlockStoreParameter ``.../{src}`` pattern) and pod-uniform keys are
the false-positive guards."""

import jax


class HandoffWriter:
    def __init__(self, store):
        self.store = store

    def publish(self, t, payload):
        slot = jax.process_index() * 4 + 1   # divergent, NOT the pid
        self.store.put(f"row/{t}/{slot}", payload)      # EXPECT: MH405
        key = f"stash/{slot}"
        self.store.put(key, payload)                    # EXPECT: MH405
        key2 = "g/" + str(t) + "/" + str(slot)
        self.store.put(key2, payload)                   # EXPECT: MH405
        pid = jax.process_index()
        # compliant: the pid component namespaces the divergent slot
        self.store.put(f"row/{t}/{pid}/{slot}", payload)
        # compliant: pod-uniform coordinates only
        self.store.put(f"w/{t}", payload)
        return slot
