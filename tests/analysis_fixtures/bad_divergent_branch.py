"""MH401 divergent-branch-collective: Python branches on process-
divergent values (``jax.process_index()`` or per-peer block-store
reads) whose bodies reach a cross-process agreement point — a
collective, a compiled-step dispatch, or a block-store barrier — by
call-graph reachability.  One process takes the branch, the others
don't, and the pod hangs at the next barrier (the classic
trace-divergence shape).  Rank-gated pure-host side effects and
branches on pod-uniform ``process_count`` are the false-positive
guards."""

import jax
import jax.numpy as jnp
from jax import lax


def shard_norm(g):
    """A helper that ISSUES a collective — branches guarding a call to
    it diverge the pod exactly like an inline psum."""
    return lax.psum(jnp.sum(g * g), "data")


class PodEngine:
    def __init__(self, store):
        self.store = store
        self.pid = jax.process_index()

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def step(self, step_fn, g):
        if self.pid == 0:                           # EXPECT: MH401
            norm = shard_norm(g)
        blob = self.store.try_get("peer/row")
        if blob is None:                            # EXPECT: MH401
            out = self._dispatch("decode", step_fn, g)
        n = jax.process_index()
        while n > 0:                                # EXPECT: MH401
            n = lax.psum(n, "data")
        return g

    def wait_rank0(self, g):
        # a divergent branch guarding a block-store BARRIER hangs the
        # same way: rank 0 waits, the rest never publish
        if jax.process_index() == 0:                # EXPECT: MH401
            return self.store.get_blocking("w/0", 1.0)
        return g

    def rank_gated_logging(self, g):
        # compliant: the collective runs on EVERY process; only the
        # pure-host side effect (logging) is rank-gated
        norm = shard_norm(g)
        if jax.process_index() == 0:
            print("norm", norm)
        return norm

    def uniform_branch(self, g):
        # compliant: process_count is pod-uniform — every process takes
        # the same side, so the collective stays in lockstep
        if jax.process_count() > 1:
            return shard_norm(g)
        return g
