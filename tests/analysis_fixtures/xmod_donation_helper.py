"""Cross-module half of the SRV204 demonstration: a module-level
helper that donates its parameter.  Clean on its own — the reuse bug
lives in the CALLER module (xmod_donation_caller.py); the pair must be
analyzed together (``analyze_paths([caller, helper])``) for the
project pass to lift the donation across the module boundary."""

import jax


def _scatter(carry, upd):
    return {k: v + upd for k, v in carry.items()}


scatter_jit = jax.jit(_scatter, donate_argnums=(0,))


def ingest(pool_carry, upd):
    return scatter_jit(pool_carry, upd)
