"""MH403 clock-discipline: raw wall-clock reads (``time.time`` /
``perf_counter`` / ``monotonic`` / ``sleep``) in serving-plane code
outside the declared CLOCK_SITES vocabulary.  Lockstep decisions must
run on the ONE injected engine clock so every pod peer and every
replay sees the same time source; the vocabulary below (extraction
beats the built-in fallback, the FENCE_SITES pattern) declares the one
sanctioned raw read.  The engine-clock spelling and the declared site
are the false-positive guards."""

import time

#: the declared vocabulary — the analyzer extracts this instead of the
#: serving/faults.py fallback when the file is in the project
CLOCK_SITES = frozenset({"bad_raw_clock.sanctioned_now"})


def sanctioned_now():
    # compliant: THE declared clock site — the one raw read everything
    # else is injected from
    return time.perf_counter()


class MiniEngine:
    def __init__(self, clock=sanctioned_now):
        self._clock = clock

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def step(self, step_fn, x):
        t0 = time.perf_counter()                    # EXPECT: MH403
        out = self._dispatch("decode", step_fn, x)
        time.sleep(0.001)                           # EXPECT: MH403
        deadline = time.monotonic() + 1.0           # EXPECT: MH403
        t1 = self._clock()       # compliant: the injected engine clock
        return out, t1 - t0, deadline
