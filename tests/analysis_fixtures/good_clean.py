"""The idiomatic spelling of every pattern the analyzer polices —
must produce ZERO findings (the false-positive guard for the whole
rule set)."""

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.utils.compat import (
    device_varying_marker, shard_map, varying_axes, varying_marker_kind)

# -- compat spellings (SPMD101) --------------------------------------------

mark = device_varying_marker("data")
kind = varying_marker_kind()


def vma_of(x):
    return varying_axes(x)


# -- spec spellings (SPMD102) ----------------------------------------------

REPLICATED = P()
ROWS = P("data")
TP = P("data", "model")
DOUBLE_SHARDED = P(("dcn", "data"), "model")


# -- jit bodies (SPMD103 / SPMD105) ----------------------------------------

def step(x, scale):
    # static facts about tracers are fine to branch and format on
    if x is None:
        return x
    if x.ndim > 1:
        x = x.sum(axis=0)
    shape_note = f"rank-{x.ndim} {x.shape}"
    y = jax.numpy.where(x > 0, x * scale, x)      # value branch via where
    return y, shape_note


jit_step = jax.jit(step)


@functools.partial(jax.jit, static_argnames=("bucket",))
def bucketed_prefill(tokens, bucket):
    # bucket is static — a bounded compile set by construction
    del bucket
    return tokens


# -- donation (SPMD104) ----------------------------------------------------

def scatter(buf, upd):
    return buf.at[0].set(upd)


donating = jax.jit(scatter, donate_argnums=(0,))


def carry_loop(cache, upds):
    for u in upds:
        cache = donating(cache, u)    # rebound every step — the idiom
    return cache


# -- mesh axes (SPMD106) ---------------------------------------------------

def sharded_apply(f):
    mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("data", "model"))
    fn = shard_map(f, mesh=mesh, in_specs=(P("data"), P("model")),
                   out_specs=P("data"))
    placement = NamedSharding(mesh, ROWS)
    return fn, placement


# -- serving contracts (SRV201/202/203/204/205) -----------------------------

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.kv_pool import KVPool
from bigdl_tpu.serving.metrics import ServingMetrics


class RoutedEngine:
    """Every compiled-step dispatch through _dispatch (SRV201), every
    finish reason in the vocabulary (SRV205)."""

    def __init__(self, model):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, None, sampling=True)
        self.metrics = ServingMetrics()

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def step(self, params, tokens, active, carry, knobs):
        tok, chosen, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        return tok, carry

    def finish(self, req):
        req.finish_reason = "length"
        self.metrics.on_finish_reason("length")


class MirroredPool(KVPool):
    """pos moves with the chunk mirrors in lockstep (SRV203); schema
    keys only (SRV202)."""

    def set_pos(self, slot: int, pos: int) -> None:
        self.carry["pos"] = self.carry["pos"].at[slot].set(int(pos))
        self.chunk_done[slot] = int(pos)


donating_scatter = jax.jit(lambda c, u: c, donate_argnums=(0,))


def ingest_row(row_carry, upd):
    return donating_scatter(row_carry, upd)


def serve_once(carry, upd):
    # the rebind idiom ACROSS a call boundary (SRV204's clean twin)
    carry = ingest_row(carry, upd)
    return carry["pos"]


# -- async-readiness spellings (ASY301-305) --------------------------------

from bigdl_tpu.serving.fences import fence, fence_wait


class AsyncReadyEngine:
    """The hot-loop spellings the ASY rules must never flag: ONE
    batched fence readback per step, host bookkeeping on the fenced
    arrays, device-handle accumulation, host-mirror branches, and
    fence-pinned timers."""

    def __init__(self, model, clock):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, None, sampling=True)
        self._clock = clock
        self.chunk_done = np.zeros((8,), np.int64)   # host mirror
        self.metrics = ServingMetrics()

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        t0 = self._clock()
        drafts = []
        for _ in range(3):
            tok, chosen, carry = self._dispatch(
                "decode", self._step_fn, params, tokens, active, carry,
                knobs)
            drafts.append(tok)                 # device handles are free
            if self.chunk_done[0] > 2:         # host mirror, no sync
                break
        # THE one declared sync: a batched readback through the fence
        nxt, lps = fence("decode", tok, chosen)
        self.metrics.add_phase("decode_step", self._clock() - t0)
        emitted = {}
        for slot in range(nxt.shape[0]):       # fenced host arrays
            tok1 = int(nxt[slot]) + 1
            if tok1 > 0:
                emitted[slot] = (tok1, float(lps[slot]))
        # completion-wait idiom for a tree that stays on device
        t1 = self._clock()
        carry = fence_wait("prefill", carry)
        self.metrics.add_phase("prefill", self._clock() - t1)
        return emitted, carry


# -- multi-host lockstep spellings (MH401-405) ------------------------------

import jax as _jax
from jax import lax as _lax


def pod_norm(g):
    return _lax.psum(jnp.sum(g * g), "data")


class LockstepEngine:
    """The pod-safe spellings the MH rules must never flag: every
    process runs the same collective/dispatch sequence, rank-gating
    covers only pure-host side effects, handoffs iterate canonical
    orders, keys carry the pid namespace, and all randomness is
    seed-derived."""

    def __init__(self, store, channel, clock, seed):
        self.store = store
        self.channel = channel
        self._clock = clock                   # the injected engine clock
        self.pid = _jax.process_index()
        self.rng = np.random.default_rng(int(seed))   # seeded source

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def pod_step(self, step_fn, g):
        # every process dispatches and collects — no divergent guard
        out = self._dispatch("decode", step_fn, g)
        norm = pod_norm(g)
        if _jax.process_index() == 0:
            print("norm", norm)               # rank-gated HOST effect only
        if _jax.process_count() > 1:          # pod-uniform: lockstep-safe
            norm = pod_norm(g)
        t0 = self._clock()                    # injected clock, not time.*
        for slot in sorted({1, 2, 3}):        # canonical handoff order
            self.channel.send(slot)
        self.store.put(f"row/{0}/{self.pid}", out)   # pid-namespaced key
        return out, norm, self._clock() - t0


# -- dispatch-ahead window spellings (ASY306-310) ---------------------------

from collections import deque


class _InFlightEntry:
    def __init__(self, tok, chosen, t0):
        self.tok = tok
        self.chosen = chosen
        self.t0 = t0


class DispatchAheadEngine:
    """The window spellings the ASY306-310 tier must never flag: depth
    bound by the declared knob, steady-state dispatch chained on the
    previous dispatch's DEVICE handle, exactly the declared delayed
    readback behind the window, clock-bracketed consumption (with the
    entry timestamp riding the append — ASY305's entry-stamp
    exemption), and donate-then-commit carry hygiene."""

    def __init__(self, model, clock):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, None, sampling=True)
        self._clock = clock
        self.dispatch_ahead = 2
        self._win = deque()
        self.carry = None
        self.metrics = ServingMetrics()

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        if self._win:
            toks = self._win[-1].tok       # device chain: no fence, no upload
        else:
            toks = tokens
        t0 = self._clock()
        tok, chosen, carry = self._dispatch(
            "decode", self._step_fn, params, toks, active,
            self.carry, knobs)
        self.carry = carry                 # committed before anything reads it
        self._win.append(_InFlightEntry(tok, chosen, t0))   # t0 rides the entry
        emitted = {}
        while len(self._win) > self.dispatch_ahead:   # the declared knob
            self._consume(emitted)
        return emitted

    def _consume(self, emitted):
        e = self._win.popleft()
        t_f = self._clock()
        # THE declared delayed readback, clock-bracketed: the wait and
        # the cross-step elapsed both land in the phase timers
        nxt, lps = fence("decode", e.tok, e.chosen)
        now = self._clock()
        self.metrics.add_phase("fence_wait", now - t_f)
        self.metrics.add_phase("decode_step", now - e.t0)
        for slot in range(nxt.shape[0]):
            emitted[slot] = (int(nxt[slot]) + 1, float(lps[slot]))

    def flush(self, emitted):  # analysis: hotpath-root
        while self._win:                   # truthiness drain only shrinks
            self._consume(emitted)
