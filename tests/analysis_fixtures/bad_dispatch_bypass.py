"""SRV201 dispatch-bypass: compiled steps from the ``get_*_step``
caches invoked DIRECTLY inside a serving engine instead of through
``_dispatch`` — silently defeating fault injection, the watchdog, and
retry accounting.  Mirrors the REAL call shapes of
``serving/engine.py`` (decode), ``serving/admission.py`` (bucketed
prefill), and ``serving/speculative.py`` (verify + draft prefill).
The routed spellings are the false-positive guards."""

import jax.numpy as jnp

from bigdl_tpu.models.transformer import (
    get_batch_decode_step, get_batch_prefill_step, get_batch_verify_step,
    get_prefill_step,
)


class MiniEngine:
    """The minimal engine shape: binds compiled steps and owns a
    ``_dispatch`` routing — exactly what makes direct invocation a
    bypass rather than test code."""

    def __init__(self, model, dtype):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._batch_prefill_fn = get_batch_prefill_step(model, dtype)
        self._prefill_fn = get_prefill_step(model, dtype)
        self.verify_fn, self.pool_init = get_batch_verify_step(
            model, dtype, width=5)
        self._faults = None

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, carry, knobs):
        # the routed spelling — never flagged
        tok, chosen, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        # the bypass: same dispatch, no routing
        tok2, chosen2, carry = self._step_fn(   # EXPECT: SRV201
            params, tokens, active, carry, knobs)
        return tok, tok2, carry

    def admit(self, params, toks, lengths, carry):
        _, out = self._dispatch("prefill", self._batch_prefill_fn,
                                params, toks, lengths, carry)
        _, out = self._batch_prefill_fn(        # EXPECT: SRV201
            params, toks, lengths, carry)
        _, pc = self._prefill_fn(params, toks, carry)   # EXPECT: SRV201
        return out, pc

    def verify(self, params, vtoks, lengths, carry, knobs):
        vt, vlp, n_emit, carry = self._dispatch(
            "verify", self.verify_fn, params, vtoks, lengths, carry, knobs)
        vt, vlp, n_emit, carry = self.verify_fn(        # EXPECT: SRV201
            params, vtoks, lengths, carry, knobs)
        return vt, carry

    def aliased(self, params, toks, lengths, carry):
        # a local alias is still the same compiled step
        fn = self._batch_prefill_fn
        _, out = fn(params, toks, lengths, carry)       # EXPECT: SRV201
        # ...but merely READING the attribute (compile-count probes,
        # `_note_shape`) is fine
        seen = getattr(self._batch_prefill_fn, "_traced_shapes", None)
        return out, seen

    def passthrough(self, params, x, carry):
        # handing the step to the router as an ARGUMENT is the idiom
        return self._dispatch("decode", self._step_fn, params, x, carry)
