"""SRV204 interprocedural donation-reuse: SPMD104 lifted through the
call graph.  ``ingest`` donates its parameter (it flows into a
``donate_argnums`` position), so a CALLER's buffer is invalid after
``ingest`` returns — even though no ``jax.jit`` appears at the call
site.  The rebind spelling and the non-donated helper are the
false-positive guards."""

import jax


def _scatter(carry, upd):
    return {k: v + upd for k, v in carry.items()}


scatter_jit = jax.jit(_scatter, donate_argnums=(0,))


def ingest(pool_carry, upd):
    """The helper hiding the donation behind a call boundary."""
    return scatter_jit(pool_carry, upd)


def inspect(pool_carry):
    """Reads only — donates nothing."""
    return pool_carry["pos"]


def serve_broken(carry, upd):
    out = ingest(carry, upd)
    stale = carry["pos"]                          # EXPECT: SRV204
    return out, stale


def serve_rebound(carry, upd):
    carry = ingest(carry, upd)        # the rebind idiom — fine
    return carry["pos"]


def serve_readonly(carry, upd):
    head = inspect(carry)             # non-donating helper — fine
    tail = carry["pos"]
    return head, tail


class PoolOwner:
    def write(self, row, upd):
        return scatter_jit(row, upd)  # method wrapper: donates row

    def serve(self, carry, upd):
        out = self.write(carry, upd)
        ghost = carry["pos"]                      # EXPECT: SRV204
        return out, ghost

    def serve_ok(self, carry, upd):
        carry = self.write(carry, upd)
        return carry["pos"]
