"""SPMD101 fixtures: version-moved jax APIs spelled directly.

Docstring mentions of jax.shard_map, jax.typeof, lax.pvary and
lax.pcast must NOT trigger — the rule is import-resolution based, which
is why the repo could not simply grep for these spellings.
"""

import jax
from jax import lax
from jax.experimental.shard_map import shard_map  # EXPECT: SPMD101


def direct_attribute_uses(x):
    sm = jax.shard_map  # EXPECT: SPMD101
    t = jax.typeof(x)  # EXPECT: SPMD101
    marked = lax.pvary(x, "data")  # EXPECT: SPMD101
    cast = jax.lax.pcast  # EXPECT: SPMD101
    return sm, t, marked, cast, shard_map


def getattr_probes():
    # the probe spelling is the same drift — compat.py owns these probes
    a = getattr(jax, "shard_map", None)  # EXPECT: SPMD101
    b = getattr(lax, "pvary", None)  # EXPECT: SPMD101
    # probing something unrelated is fine
    c = getattr(jax, "devices", None)
    return a, b, c


def unrelated_attributes_are_fine(engine):
    # `engine` is not an imported jax module — must not trigger even
    # though the attribute is literally named shard_map
    return engine.shard_map, engine.typeof
