"""MH404 ambient-randomness: draws that are NOT a pure function of
request seeds on serving replay paths — stdlib ``random.*``, the
module-level numpy global generator, an UNSEEDED ``default_rng()``,
and a fresh ``jax.random.PRNGKey`` outside the sampling module's seed
derivation.  Byte-identical failover/preemption replay dies on any of
them: the replacement draw differs per process and per run.  The
seeded injector generator and derived-key spellings are the
false-positive guards."""

import random

import jax
import numpy as np


class ReplayEngine:
    def __init__(self, seed):
        # compliant: the sanctioned SEEDED source (the FaultInjector
        # pattern) — a pure function of the constructor seed
        self.rng = np.random.default_rng(int(seed))
        self.base = int(seed)

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def lane(self, req_id):
        return jax.random.PRNGKey(req_id)           # EXPECT: MH404

    def route(self, pools):
        return random.choice(pools)                 # EXPECT: MH404

    def jitter(self):
        backoff = np.random.uniform(0.0, 1.0)       # EXPECT: MH404
        fresh = np.random.default_rng()             # EXPECT: MH404
        seeded = self.rng.random()  # compliant: the seeded generator
        return backoff, fresh, seeded

    def derived(self, key, n):
        # compliant: deriving from an EXISTING key is the lane
        # discipline (fold_in/split are pure functions of their input)
        sub = jax.random.fold_in(key, n)
        return jax.random.split(sub)[0]
