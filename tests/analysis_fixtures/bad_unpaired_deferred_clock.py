"""ASY310 unpaired-deferred-clock: a delayed consumer (the unit that
pops the window and fences the deferred readback) with NO engine-clock
read anywhere — the fence wait is invisible to the phase timers and
the watchdog, so a stalled in-flight dispatch hangs the engine with no
deadline to trip.  The clock-bracketed consumer and the cold drain are
the false-positive guards."""

import time
from collections import deque

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence


class _Entry:
    def __init__(self, tok, chosen):
        self.tok = tok
        self.chosen = chosen


class BlindConsumerEngine:
    def __init__(self, model, dtype, clock=time.perf_counter):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None
        self._clock = clock
        self.dispatch_ahead = 2
        self._win = deque()
        self.phases = {}
        self.emitted = {}

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        tok, lp = self._dispatch(
            "decode", self._step_fn, params, tokens, active, knobs)
        self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            self._consume()
        self._timed_consume()

    def _consume(self):
        # no clock read anywhere in this consumer: the deferred fence's
        # wait never reaches the timers or the watchdog
        e = self._win.popleft()
        nxt, lps = fence("decode", e.tok, e.chosen)  # EXPECT: ASY310
        self._account(nxt, lps)

    def _timed_consume(self):
        # the sanctioned spelling: the consumer brackets the deferred
        # fence with the engine clock, so the wait lands in the phase
        # timers and the watchdog's elapsed budget
        if not self._win:
            return
        e = self._win.popleft()
        t_f = self._clock()
        nxt, lps = fence("decode", e.tok, e.chosen)
        self.phases["fence_wait"] = self._clock() - t_f
        self._account(nxt, lps)

    def _account(self, nxt, lps):
        for slot in range(nxt.shape[0]):
            self.emitted[slot] = (int(nxt[slot]), float(lps[slot]))


def drain_all(engine):
    """Cold twin: a teardown drain needs no timers — unreachable from
    a hot root, exempt."""
    while engine._win:
        e = engine._win.popleft()
        nxt, lps = fence("decode", e.tok, e.chosen)
        engine._account(nxt, lps)
