"""ASY301 hot-readback: implicit device→host syncs on functions the
serving super-step can REACH — ``.item()``, ``float()/int()/bool()``
casts, ``np.asarray``/``np.array``, raw ``jax.device_get`` — flagged by
call-graph reachability from the hot-path roots, never by path glob.
The fenced spellings and the cold twin (same readbacks, unreachable)
are the false-positive guards."""

import jax
import numpy as np

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence


class MiniEngine:
    """The minimal hot-loop shape: a `_dispatch` routing and a compiled
    step binding (taint sources), plus an annotated root."""

    def __init__(self, model, dtype):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        tok, chosen, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        nxt = np.asarray(tok)                       # EXPECT: ASY301
        lp = float(chosen[0])                       # EXPECT: ASY301
        done = bool(tok[0])                         # EXPECT: ASY301
        raw = jax.device_get(chosen)                # EXPECT: ASY301
        scalar = tok.item()                         # EXPECT: ASY301
        pos = carry["pos"]
        n = int(pos[0])                             # EXPECT: ASY301
        # static accessors are trace/host metadata, never a sync
        width = tok.shape[0]
        nd = chosen.ndim
        ok = carry is None
        rows = len(tokens)
        return nxt, lp, done, raw, scalar, n, width, nd, ok, rows

    def fenced_step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        tok, chosen, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        # the routed spelling: ONE batched readback through the fence
        nxt, lps = fence("decode", tok, chosen)
        # fence results are HOST arrays — downstream casts never sync
        first = int(nxt[0])
        score = float(lps[0])
        return first, score, carry

    def helper(self, carry):
        # reachable FROM step via the self-method edge — still hot
        return float(carry["pos"][0])               # EXPECT: ASY301

    def wired(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        return self.helper(carry)


def bench_loop(engine, params, tokens, active, carry, knobs):
    """The cold twin: identical readback spellings, but NOT reachable
    from any hot-path root — exempt by reachability (unmarked lines =
    the false-positive guard the issue asks for)."""
    tok, chosen, carry = engine._dispatch(
        "decode", engine._step_fn, params, tokens, active, carry, knobs)
    nxt = np.asarray(tok)
    lp = float(chosen[0])
    raw = jax.device_get(chosen)
    return nxt, lp, raw, tok.item(), int(carry["pos"][0])
