"""ASY303 hot-branch-sync + ASY304 readback-accumulation: Python
control flow branching on an UN-fenced device value between dispatches
(every branch needs a concrete bool = a host sync on the whole pending
pipeline), and per-iteration readbacks accumulated inside the dispatch
loop (one sync per iteration — should batch through one fence).  The
fenced/host-mirror spellings and device-handle accumulation are the
false-positive guards."""

import numpy as np

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence


class MiniEngine:
    def __init__(self, model, dtype):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None
        self.chunk_done = np.zeros((8,), np.int64)   # host mirror

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        outs = []
        total = 0.0
        for _ in range(4):
            tok, lp, carry = self._dispatch(
                "decode", self._step_fn, params, tokens, active, carry,
                knobs)
            if tok[0] > 0:                          # EXPECT: ASY303
                break
            while lp.any():                         # EXPECT: ASY303
                break
            best = tok if carry["pos"][0] else lp   # EXPECT: ASY303
            assert lp[0] < 0.0                      # EXPECT: ASY303
            outs.append(int(tok[0]))                # EXPECT: ASY304
            total += float(lp[0])                   # EXPECT: ASY304
        return outs, total, best

    def fenced_step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        # the compliant spellings: accumulate DEVICE handles (free),
        # branch on host mirrors / fenced host arrays only
        drafts = []
        for _ in range(4):
            tok, lp, carry = self._dispatch(
                "decode", self._step_fn, params, tokens, active, carry,
                knobs)
            drafts.append(tok)                      # device handle: fine
            if self.chunk_done[0] > 2:              # host mirror: fine
                break
        nxt, lps = fence("decode", tok, lp)
        hist = []
        if nxt[0] > 0:                              # fenced host value: fine
            for t in nxt:
                hist.append(int(t))                 # host cast: fine
        # branching on trace-static facts never syncs either
        if carry is None or len(drafts) == 0:
            return hist, carry
        return hist, carry


def bench_loop(engine, params, tokens, active, carry, knobs):
    """Cold twin: the same branch/accumulation spellings, unreachable
    from any hot-path root — exempt by reachability."""
    outs = []
    for _ in range(4):
        tok, lp, carry = engine._dispatch(
            "decode", engine._step_fn, params, tokens, active, carry,
            knobs)
        if tok[0] > 0:
            break
        outs.append(int(tok[0]))
    return outs
