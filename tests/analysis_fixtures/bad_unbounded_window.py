"""ASY308 unbounded-window: the dispatch-ahead window's depth bound
spelled as a literal (or any non-knob expression) instead of the
declared engine knob — the analyzer can no longer tie the in-flight
depth to configuration, and a drive-by edit can silently deepen the
window past what the SLO math (and the watchdog budget) assumed.
Knob-bounded loops and the consumer's truthiness drain are the
false-positive guards."""

import time
from collections import deque

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence


class _Entry:
    def __init__(self, tok, chosen):
        self.tok = tok
        self.chosen = chosen


class UnboundedWindowEngine:
    def __init__(self, model, dtype, clock=time.perf_counter):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None
        self._clock = clock
        self.dispatch_ahead = 2
        self._win = deque()
        self.phases = {}

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # a literal depth bound: the window grows to 4 regardless of
        # what dispatch_ahead says
        while len(self._win) < 4:                  # EXPECT: ASY308
            tok, lp = self._dispatch(
                "decode", self._step_fn, params, tokens, active, knobs)
            self._win.append(_Entry(tok, lp))
        self._consume()

    def burst(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # a fixed-trip fill loop and a literal high-water check — both
        # detach the in-flight depth from the declared knob
        for _ in range(3):                          # EXPECT: ASY308
            tok, lp = self._dispatch(
                "decode", self._step_fn, params, tokens, active, knobs)
            self._win.append(_Entry(tok, lp))
        if len(self._win) > 6:                      # EXPECT: ASY308
            self._consume()

    def knob_step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # the sanctioned spellings: depth checks and fill loops that
        # reference the declared knob
        for _ in range(self.dispatch_ahead):
            tok, lp = self._dispatch(
                "decode", self._step_fn, params, tokens, active, knobs)
            self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            self._consume()

    def _consume(self):
        # the consumer's drain-everything spelling needs no knob — it
        # only shrinks the window
        while self._win:
            e = self._win.popleft()
            t_f = self._clock()
            nxt, lps = fence("decode", e.tok, e.chosen)
            self.phases["fence_wait"] = self._clock() - t_f


def fill_to_depth(engine, params, tokens, active, knobs, depth=4):
    """Cold twin: a bench harness fills to an arbitrary depth on
    purpose — unreachable from a hot root, exempt."""
    while len(engine._win) < depth:
        tok, lp = engine._dispatch(
            "decode", engine._step_fn, params, tokens, active, knobs)
        engine._win.append(_Entry(tok, lp))
