"""Embedded string-program scanning (the PR-5 blind-spot closure):
child programs inside ASSIGNED string constants — the
``pod_projection._CHILD`` shape, ``str.format`` placeholders included
— are parsed as nested units and scanned by EVERY rule, with finding
lines remapped into this host file.  The clean child below is the
false-positive guard."""

_BAD_CHILD = r"""
import sys
from jax.experimental.shard_map import shard_map   # EXPECT: SPMD101
from jax.sharding import PartitionSpec as P

sys.path.insert(0, {repo!r})

ROWS = P(("data",))                                # EXPECT: SPMD102
TABLE = {{"rows": ROWS}}
"""

_CLEAN_CHILD = r"""
import sys

from bigdl_tpu.utils.compat import shard_map
from jax.sharding import PartitionSpec as P

sys.path.insert(0, {repo!r})

ROWS = P("data")
"""
