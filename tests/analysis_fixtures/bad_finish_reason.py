"""SRV205 finish-reason accounting: every string a request can finish
with must be in ``ServingMetrics.FINISH_REASONS`` (each has a
``serving/finish_<reason>`` counter path).  A typo'd or novel reason
silently escapes goodput/shed accounting.  The vocabulary spellings
are the false-positive guards."""

from bigdl_tpu.serving.metrics import ServingMetrics


def shed_paths(engine, req):
    req.finish_reason = "shed"                    # vocabulary — fine
    engine._shed(req, "deadline")                 # vocabulary — fine
    req.finish_reason = "shedd"                   # EXPECT: SRV205
    engine._shed(req, "overload")                 # EXPECT: SRV205


def finish_paths(engine, req, now):
    engine._finish_row(req, "length", now)        # vocabulary — fine
    engine._finish_row(req, "lenght", now)        # EXPECT: SRV205
    reason = compute_reason(req)
    engine._finish_row(req, reason, now)          # dynamic — out of scope


def account(metrics: ServingMetrics):
    metrics.on_finish_reason("error")             # vocabulary — fine
    metrics.on_finish_reason("oom")               # EXPECT: SRV205


def compute_reason(req):
    return "eos" if req.output else "length"
