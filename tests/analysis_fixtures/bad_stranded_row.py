"""SRV206 stranded rows: any code path that removes a request from a
pool's ``running``/``partial`` scheduler tables must requeue/submit it,
serialize it for handoff (``row_state``/``pack_payload``), or land a
finish disposition — the static twin of the pool-failover invariant.
The compliant spellings and the table-owning scheduler class are the
false-positive guards."""

from bigdl_tpu.serving.scheduler import Request


class Router:
    def drop_row(self, slot):
        del self.engine.scheduler.running[slot]       # EXPECT: SRV206

    def pop_row(self, sched, slot):
        req = sched.running.pop(slot)                 # EXPECT: SRV206
        return req.req_id

    def wipe_partials(self, sched):
        sched.partial.clear()                         # EXPECT: SRV206

    def migrate_row(self, sched, pool, slot, target):
        payload = pool.row_state(slot)                # handed off — fine
        req = sched.running.pop(slot)
        target.submit(req, payload)

    def failover_row(self, sched, slot, survivor):
        req = sched.running.pop(slot)                 # requeued — fine
        survivor.scheduler.requeue(req)

    def finish_row(self, sched, req, now):
        del sched.running[req.slot]                   # disposition — fine
        self._ledger_finish(req, "length", now)

    def drop_waiting(self, sched, req_id):
        # the waiting heap is NOT a slot table: its drop surface
        # (pop_waiting) is the owning class's closed primitive
        sched._waiting.pop(0)
        return req_id


class MiniScheduler:
    """Owns the tables (the Scheduler shape): its methods ARE the
    sanctioned removal primitives — exempt."""

    def __init__(self):
        self.running = {}
        self.partial = {}

    def evict(self, slot):
        del self.running[slot]                        # primitive — fine

    def activate(self, slot):
        req = self.partial.pop(slot)                  # primitive — fine
        self.running[slot] = req
        return req


def lose_rows_at_module_scope_helper(sched, slot):
    req = sched.partial.pop(slot)                     # EXPECT: SRV206
    return req
