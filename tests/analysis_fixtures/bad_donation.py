"""SPMD104 fixtures: donated buffers read after the donating call.

``donate_argnums`` hands the argument's memory to XLA for the outputs —
the old array is INVALID afterwards (jax raises on some backends,
silently corrupts on others).  The carry idiom (rebind the name to the
result) is the fix, and is what serving's KV pool does.
"""

import jax


def scatter(buf, upd):
    return buf.at[0].set(upd)


donating = jax.jit(scatter, donate_argnums=(0,))


def good_carry_rebind(cache, upd):
    cache = donating(cache, upd)      # name rebound to the result — fine
    return cache + 1


def good_last_use(cache, upd):
    return donating(cache, upd)       # never touched again — fine


def bad_reuse(cache, upd):
    out = donating(cache, upd)
    return out + cache  # EXPECT: SPMD104


def bad_reuse_later(cache, upd):
    out = donating(cache, upd)
    other = out * 2
    norm = cache.sum()  # EXPECT: SPMD104
    return other, norm


def bad_same_line_rebind(cache, upd):
    out = donating(cache, upd)
    cache = cache + 1  # EXPECT: SPMD104
    return out, cache


def bad_augmented_rebind(cache, upd):
    out = donating(cache, upd)
    cache += 1  # EXPECT: SPMD104
    return out, cache
