"""SPMD103 fixtures: recompile hazards.

(a) string formatting on traced values inside jitted bodies — either a
    concretization error outright or, via ``.shape`` of data-dependent
    intermediates, a retrace per shape;
(b) containers built by comprehension flowing into a jitted callable —
    their pytree STRUCTURE varies with the data and structure is part of
    the compile key (the bug class serving's length-bucketed admission
    exists to prevent).
"""

import jax


def traced(x, y):
    bad_fstring = f"x is {x}"  # EXPECT: SPMD103
    ok_static = f"shape is {x.shape}, rank {x.ndim}"
    bad_format = "y = {}".format(y)  # EXPECT: SPMD103
    ok_const = "nothing traced {}".format(42)
    return x + y, bad_fstring, bad_format, ok_static, ok_const


step = jax.jit(traced)


def cfg_step(x, mode):
    # `mode` is static (static_argnames below) — formatting it is fine
    label = f"mode={mode}"
    return x, label


cfg = jax.jit(cfg_step, static_argnames=("mode",))


def admit(requests):
    # structure of the dict depends on the request batch -> one compile
    # per novel structure
    return step(1, {k: v for k, v in requests})  # EXPECT: SPMD103


def fine_calls(x):
    # plain dict literals / arrays are stable structures — fine
    return step(x, 2), cfg(x, mode="fast")
