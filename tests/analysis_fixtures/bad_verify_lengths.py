"""SPMD105 fixtures: the speculative VERIFY-step pattern.

``make_batch_verify_step`` takes per-row draft ``lengths`` as a runtime
array of ONE compiled (N, width) program — that is the whole
zero-compile contract of mixed speculative/normal traffic.  The
tempting spelling is to branch on (or iterate up to) each row's length
in Python: on a tracer that raises TracerBoolConversionError, and the
"fix" of hoisting lengths to the host bakes one traffic mix into the
program — a recompile per distinct draft-budget mix.  Mask arithmetic
(``jnp.arange(width) < lengths[:, None]``) is the legal spelling and
must not be flagged.
"""

import jax
import jax.numpy as jnp


def verify_chunk(params, tokens, lengths, carry):
    # legal spelling: per-row chunk validity is MASK arithmetic, so the
    # traced lengths stay runtime values of the one program
    width = tokens.shape[1]                      # static shape — fine
    inb = jnp.arange(width)[None] < lengths[:, None]
    x = jnp.where(inb, tokens, 0)
    if tokens.ndim != 2:                         # static fact — fine
        x = x[None]
    if lengths.max() > 0:  # EXPECT: SPMD105
        x = x + 1
    while lengths.sum() > 0:  # EXPECT: SPMD105
        lengths = lengths - 1
    bonus = 1 if lengths[0] else 0  # EXPECT: SPMD105
    n_emit = jnp.where(lengths > 0, 1, 0) + bonus
    return x, n_emit, carry


verify_step = jax.jit(verify_chunk)
