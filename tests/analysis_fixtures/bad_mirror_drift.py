"""SRV203 host-mirror lockstep: a KVPool-lineage method that moves the
device ``pos`` must keep the ``chunk_done``/``chunk_target`` host
mirrors in lockstep (the chunked-admission pump plans from the mirrors
WITHOUT a device readback — a drifted mirror stalls or double-feeds a
row).  The compliant overrides and the draft-carry methods (no
mirrors) are the false-positive guards."""

import jax.numpy as jnp

from bigdl_tpu.serving.kv_pool import KVPool


class DriftPool(KVPool):
    """Overrides set_pos but forgets the host mirror."""

    def set_pos(self, slot: int, pos: int) -> None:
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self.carry["pos"] = self.carry["pos"].at[slot].set(pos)  # EXPECT: SRV203


class ResetDriftPool(KVPool):
    """The reset-helper spelling of the same drift: the donated
    ``_free_reset`` moves pos as a side effect."""

    def recycle(self, slot: int) -> None:
        self.carry.update(self._free_reset(            # EXPECT: SRV203
            {"pos": self.carry["pos"]}, jnp.int32(slot)))


class LockstepPool(KVPool):
    """The compliant override — mirror written in the same method."""

    def set_pos(self, slot: int, pos: int) -> None:
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self.carry["pos"] = self.carry["pos"].at[slot].set(int(pos))
        self.chunk_done[slot] = int(pos)

    def free(self, slot: int) -> None:
        # delegating to super() keeps the whole contract
        super().free(slot)

    def set_draft_pos(self, slot: int, pos: int) -> None:
        # the DRAFT carry has no host mirrors — exempt by design
        self.draft_carry["pos"] = \
            self.draft_carry["pos"].at[slot].set(int(pos))
