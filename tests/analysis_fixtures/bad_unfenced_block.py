"""ASY302 unfenced-block: ``block_until_ready`` spelled raw on a
hot-path-reachable function instead of through the ``fence_wait``
idiom (serving/fences.py — the ONE designated home of the completion
wait), plus fence-site strings outside the closed FENCE_SITES
vocabulary.  The routed spelling and the cold twin are the
false-positive guards."""

import jax

from bigdl_tpu.models.transformer import get_batch_prefill_step
from bigdl_tpu.serving.fences import fence_wait


class MiniPump:
    def __init__(self, model, dtype):
        self._batch_prefill_fn = get_batch_prefill_step(model, dtype)
        self._faults = None

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def pump(self, params, toks, lengths, carry):  # analysis: hotpath-root
        _, out = self._dispatch("prefill", self._batch_prefill_fn,
                                params, toks, lengths, carry)
        jax.block_until_ready(out)                  # EXPECT: ASY302
        out.block_until_ready()                     # EXPECT: ASY302
        # the routed spelling — the designated completion wait
        out = fence_wait("prefill", out)
        # ...but only over the CLOSED site vocabulary
        out = fence_wait("warmup", out)             # EXPECT: ASY302
        return out


def bench_timing(engine, params, toks, lengths, carry):
    """Cold twin: block_until_ready is exactly how a bench SHOULD time
    device work — unreachable from the hot-path roots, so exempt."""
    _, out = engine._dispatch("prefill", engine._batch_prefill_fn,
                              params, toks, lengths, carry)
    jax.block_until_ready(out)
    return out
