"""SPMD105 fixtures: Python control flow on traced values.

``if``/``while`` run at TRACE time and need a concrete bool — on a
tracer that raises TracerBoolConversionError (or silently bakes one
branch into the program).  Branching on static facts (identity, shapes,
dtypes, ``len``) is fine and must not be flagged.
"""

import jax

xs = None


def body(x, lim):
    if x is None:                       # static identity check — fine
        return lim
    if x.ndim > 1:                      # shapes are static — fine
        x = x.sum(axis=0)
    if len(x.shape) == 1 and x.shape[0] > 4:    # still static — fine
        x = x[:4]
    if x > 0:  # EXPECT: SPMD105
        x = -x
    while lim > 0:  # EXPECT: SPMD105
        lim = lim - 1
    return x, lim


step = jax.jit(body)


def scan_body(carry, t):
    if carry:  # EXPECT: SPMD105
        carry = carry + t
    return carry, t


def run(init):
    return jax.lax.scan(scan_body, init, xs)


def untraced(x):
    # this function is never jitted — host-side branching is fine
    if x > 0:
        return x
    return -x
