"""SRV208 undeclared actuation: serving control knobs (the chunked
admitter's ``chunk_budget``, a request's degrade fields, the
speculative ``draft_cap``, pool activate/drain) mutated outside the
declared ACTUATION_SITES vocabulary.  Every knob the control plane
moves goes through the declared actuator API so the bus's audit log
sees it and hysteresis owns the cadence; the vocabulary below
(extraction beats the serving/autopilot.py fallback, the CLOCK_SITES
pattern) declares this file's sanctioned actuators.  The constructor
writes and the declared bus method are the false-positive guards."""

#: the declared vocabulary — the analyzer extracts this instead of the
#: serving/autopilot.py fallback when the file is in the project
ACTUATION_SITES = frozenset({"bad_knob_mutation.MiniBus.set_chunk_budget",
                             "bad_knob_mutation.MiniBus.degrade"})


class MiniAdmitter:
    def __init__(self, chunk_budget=32):
        # compliant: constructors set INITIAL values — configuration,
        # not actuation
        self.chunk_budget = int(chunk_budget)


class MiniBus:
    def __init__(self, engine):
        self.engine = engine

    def set_chunk_budget(self, n):
        # compliant: THE declared chunk-budget actuator
        self.engine.admitter.chunk_budget = int(n)

    def degrade(self, req):
        # compliant: THE declared degrade actuator
        req.max_new_tokens = 16
        req.degraded = True


class MiniEngine:
    def __init__(self, admitter):
        self.admitter = admitter
        self.draft_cap = None                       # compliant: __init__

    def _dispatch(self, site, fn, *args):
        return fn(*args)

    def step(self, req):
        self.admitter.chunk_budget = 8              # EXPECT: SRV208
        req.max_new_tokens = 4                      # EXPECT: SRV208
        self.draft_cap = 2                          # EXPECT: SRV208
        req.degraded = True                         # EXPECT: SRV208
        return self._dispatch("decode", lambda r: r, req)

    def rebalance(self, pools, i):
        pools.drain_pool(i)                         # EXPECT: SRV208
        pools._activate_pool(i + 1)                 # EXPECT: SRV208
