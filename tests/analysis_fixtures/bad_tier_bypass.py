"""SRV207 tier-codec bypass: a row leaves HBM for the host tier ONLY
as ``pack_payload(request_meta(req), pool.row_state(slot))`` bytes —
a raw ``row_state`` dict (or any name tainted by one) written into a
block store skips the length-prefixed wire codec and is unreadable by
every fetch path; and ``row_state`` AFTER ``pool.free`` serializes a
recycled slot. Wrapper detection is one level deep (a helper whose
parameter flows into a store ``.put()`` counts as a store write at its
call sites); ``pack_payload`` output is the sanitizer."""

from bigdl_tpu.serving.disagg import pack_payload, request_meta


class BadSpiller:
    def spill_param(self, store, key, payload):
        store.put(key, payload)                       # EXPECT: SRV207

    def spill_row_state(self, pool, slot, key):
        state = self.block_store.put(key, None)       # benign self-put key
        payload = pool.row_state(slot)
        self.block_store.put(key, payload)            # EXPECT: SRV207
        return state

    def spill_copy(self, pool, slot, key):
        payload = pool.row_state(slot)
        blob = payload                                # taint rides the copy
        self.store.put(key, blob)                     # EXPECT: SRV207

    def spill_through_helper(self, pool, slot, key, payload):
        self._write(key, payload)                     # EXPECT: SRV207

    def _write(self, key, blob):
        # one-level wrapper: parameter 1 flows into a store put, so
        # call sites are store writes (this body is the modeled
        # definition site — exempt itself)
        self.store.put(key, blob)

    def free_then_read(self, pool, sched, slot):
        req = sched.running.pop(slot)
        pool.free(slot)
        payload = pool.row_state(slot)                # EXPECT: SRV207
        return req, payload


class GoodSpiller:
    def spill_packed(self, pool, req, slot, key):
        payload = pool.row_state(slot)
        blob = pack_payload(request_meta(req), payload)   # the codec
        self.store.put(key, blob)                     # sanctioned — fine

    def pack_then_free(self, pool, req, slot):
        payload = pool.row_state(slot)                # serialize FIRST
        pool.free(slot)
        return pack_payload(request_meta(req), payload)


class MiniStore:
    """A block store's own put: the VALUE param is store internals,
    not a row payload — no taint, no finding."""

    def __init__(self):
        self._blobs = {}

    def put(self, key, value):
        self._blobs[key] = value
