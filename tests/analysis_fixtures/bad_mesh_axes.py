"""SPMD106 fixtures: in_specs/out_specs axes vs the Mesh's axis names.

A spec naming an axis the mesh does not define fails at trace time at
best, silently replicates at worst.  The rule only fires when it can
SEE the mesh construction (literal ``Mesh(...)`` axis names or the
fixed-axis ``make_mesh`` factory) — unknown provenance stays silent.
"""

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.utils.compat import shard_map


def known_mesh(f):
    mesh = Mesh(np.asarray(jax.devices()).reshape(4, 2), ("data", "model"))
    good = shard_map(f, mesh=mesh, in_specs=(P("data"),),
                     out_specs=P("data", "model"))
    bad = shard_map(
        f, mesh=mesh,
        in_specs=(P("batch"),),  # EXPECT: SPMD106
        out_specs=P("model"))
    return good, bad


def factory_mesh(f):
    from bigdl_tpu.serving.sharded import make_mesh

    mesh = make_mesh(data=4, model=2)
    return shard_map(
        f, mesh=mesh,
        in_specs=(P("data"),),
        out_specs=P("slots"))  # EXPECT: SPMD106


def unknown_mesh(f, mesh):
    # mesh arrives as a parameter — provenance unknown, stay silent
    return shard_map(f, mesh=mesh, in_specs=(P("whatever"),), out_specs=P())


def shadowed_mesh(f, build):
    mesh = Mesh(np.asarray(jax.devices()).reshape(8), ("data",))
    mesh = build()              # rebound to an unknown — stay silent
    return shard_map(f, mesh=mesh, in_specs=(P("rows"),), out_specs=P())
