"""ASY309 in-window-fence: a blocking sync (any ``fence``/
``fence_wait`` that is not the declared delayed-consumer readback)
inside a unit that OWNS the dispatch-ahead window — the sync lands
between the window's dispatches and re-serializes exactly the overlap
the window exists to buy.  The delayed consumer itself and hot units
that do not own the window are the false-positive guards."""

import time
from collections import deque

from bigdl_tpu.models.transformer import (
    get_batch_decode_step, get_prefill_step)
from bigdl_tpu.serving.fences import fence, fence_wait


class _Entry:
    def __init__(self, tok, chosen):
        self.tok = tok
        self.chosen = chosen


class InWindowFenceEngine:
    def __init__(self, model, dtype, clock=time.perf_counter):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._prefill_fn = get_prefill_step(model, dtype)
        self._faults = None
        self._clock = clock
        self.dispatch_ahead = 2
        self._win = deque()
        self.phases = {}

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        tok, lp = self._dispatch(
            "decode", self._step_fn, params, tokens, active, knobs)
        # blocks on THIS step's launch before the next dispatch can be
        # issued — the window never holds more than one step
        fence_wait("draft", tok)                    # EXPECT: ASY309
        self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            self._consume()

    def probe_step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        tok, lp = self._dispatch(
            "decode", self._step_fn, params, tokens, active, knobs)
        self._win.append(_Entry(tok, lp))
        # an eager metrics probe: a readback at a non-delayed site is
        # still a blocking sync inside the owning unit
        probe, = fence("verify", lp)                # EXPECT: ASY309
        while len(self._win) > self.dispatch_ahead:
            self._consume()

    def _consume(self):
        # the ONE declared delayed readback — exempt (this unit
        # consumes the window, it does not own it)
        e = self._win.popleft()
        t_f = self._clock()
        nxt, lps = fence("decode", e.tok, e.chosen)
        self.phases["fence_wait"] = self._clock() - t_f

    def admit(self, params, prompt, carry):  # analysis: hotpath-root
        # a hot unit that never touches the window may block freely —
        # admission waits on prefill before the slot enters the pool
        out, carry = self._dispatch(
            "decode", self._prefill_fn, params, prompt, carry)
        carry = fence_wait("prefill", carry)
        return out, carry


def drain_blocking(engine, params, tokens, active, knobs):
    """Cold twin: a shutdown path may sync mid-window on purpose —
    unreachable from a hot root, exempt."""
    tok, lp = engine._dispatch(
        "decode", engine._step_fn, params, tokens, active, knobs)
    fence_wait("draft", tok)
    engine._win.append(_Entry(tok, lp))
