"""SRV202 (payload half): every string key on a serialized row-payload
dict must be in ``serving/disagg.py``'s ``ROW_PAYLOAD_KEYS`` schema
(request, carry, draft, chunk_done, chunk_target).  A typo'd transfer
key silently drops a field on the wire — the receiving pool restores a
row missing its chunk mirrors or draft slice and the stream diverges
only under load.  The canonical spellings (and the inner carry-schema
reads, which stay governed by the carry half) are the false-positive
guards."""

from bigdl_tpu.serving.disagg import unpack_payload


def route_handoff(blob, pool, slot):
    meta, payload = unpack_payload(blob)
    done = payload["chunk_done"]                  # schema — fine
    target = payload.get("chunk_target", 0)       # schema — fine
    if "draft" in payload:                        # schema — fine
        draft = payload["draft"]                  # schema — fine
    carry = payload["carry"]                      # schema — fine
    pos = carry["pos"]                            # carry schema — fine
    stale = payload["chunk_doen"]                 # EXPECT: SRV202
    payload["cary"] = carry                       # EXPECT: SRV202
    extra = payload.get("draft_carry")            # EXPECT: SRV202
    return meta, done, target, pos, stale, extra


def repack(payload):
    payload["request"] = {"req_id": 0}            # schema — fine
    if "requset" in payload:                      # EXPECT: SRV202
        del payload["requset"]                    # EXPECT: SRV202
    return payload
