"""SRV202 carry-key schema: string keys on the pooled serving carry
checked against the layout ``_serving_init_carry`` declares.  A typo'd
key fails only at runtime — or worse, a typo'd WRITE silently creates
a new key the compiled step never reads.  Valid-key lines are the
false-positive guards."""

from bigdl_tpu.serving.kv_pool import KVPool


def read_row_state(pool: KVPool, slot: int):
    carry = pool.carry
    pos = carry["pos"]                            # schema key — fine
    k_scale = carry["k0_scale"]                   # int8 layout — fine
    lanes = carry["rng"]                          # sampling state — fine
    counts = carry["tok_counts"]                  # fine
    typo_scale = carry["k0_scal"]                 # EXPECT: SRV202
    typo_counts = carry["tok_count"]              # EXPECT: SRV202
    return pos, k_scale, lanes, counts, typo_scale, typo_counts


def write_row_state(pool: KVPool, slot: int, pos):
    dcarry = dict(pool.draft_carry)
    dcarry["pos"] = pos                           # draft shares the schema
    pool.carry["positions"] = pos                 # EXPECT: SRV202
    quantized = "k0_scale" in pool.carry          # membership test — fine
    stale = pool.carry.get("v3_scale")            # .get read — fine
    ghost = pool.carry.get("v3_scales")           # EXPECT: SRV202
    return dcarry, quantized, stale, ghost


def layer_loop(carry):
    # non-constant keys are out of scope (checked at the declaration)
    for i in range(4):
        _ = carry[f"k{i}"]
    return carry
