"""SPMD102 fixtures: the PR-4 PartitionSpec spelling drift.

``P(("data",))`` places identically to ``P("data")`` but hashes
differently — one drifted spelling made every sharded-engine step
compile twice before ``serving/sharded.py``'s ``named_sharding``
normalizer pinned it down.
"""

import jax.sharding
from jax.sharding import PartitionSpec as P

GOOD_BARE = P("data")
GOOD_MULTI_DIM = P("data", "model")
# a MULTI-axis tuple entry shards one dim over two mesh axes — legit
GOOD_MULTI_AXIS_ENTRY = P(("dcn", "data"), "model")
GOOD_EMPTY = P()
GOOD_NONE = P(None, "model")

BAD_ONE_TUPLE = P(("data",))  # EXPECT: SPMD102
BAD_MIXED = P("data", ("model",))  # EXPECT: SPMD102
BAD_FULL_NAME = jax.sharding.PartitionSpec(("model",))  # EXPECT: SPMD102
