"""ASY307 window-donation: a carry buffer donated to an in-flight
(not-yet-fenced) dispatch is donated AGAIN or read before being
rebound — use-after-donate lifted to the multi-step window, where the
live buffer is the LAST dispatch's return.  Same-statement rebinding,
commit-before-reuse, and the window-free twin are the false-positive
guards."""

import time
from collections import deque

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence


class _Entry:
    def __init__(self, tok, chosen):
        self.tok = tok
        self.chosen = chosen


class DonationWindowEngine:
    def __init__(self, model, dtype, clock=time.perf_counter):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None
        self._clock = clock
        self.dispatch_ahead = 2
        self._win = deque()
        self.phases = {}
        self.carry = None
        self.vcarry = None
        self.dcarry = None
        self.stash = None

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # the first dispatch donates self.carry; before its return is
        # committed, the SECOND dispatch donates the same (now freed)
        # buffer — with the window open the first is still in flight
        tok, lp, new_carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active,
            self.carry, knobs)
        tok2, lp2, newer = self._dispatch(     # EXPECT: ASY307
            "decode", self._step_fn, params, tokens, active,
            self.carry, knobs)
        self.carry = newer
        self._win.append(_Entry(tok2, lp2))
        while len(self._win) > self.dispatch_ahead:
            self._consume()

    def spill_step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # the donated buffer is READ (spilled) before the rebind — the
        # spill copies freed memory while the dispatch is in flight
        tok, lp, vcarry = self._dispatch(
            "decode", self._step_fn, params, tokens, active,
            self.vcarry, knobs)
        self.stash = self.vcarry               # EXPECT: ASY307
        self.vcarry = vcarry
        self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            self._consume()

    def clean_step(self, params, tokens, active, knobs):  # analysis: hotpath-root
        # commit-before-reuse: the returned carry is rebound before
        # anything else touches the spelling
        tok, lp, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active,
            self.carry, knobs)
        self.carry = carry
        self.stash = self.carry                # read AFTER the rebind: live
        # same-statement rebinding: `_, c = dispatch(..., c)` — the
        # donation is cleared the instant the call returns
        dcarry = self.dcarry
        lp2, dcarry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, dcarry)
        lp3, dcarry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, dcarry)
        self.dcarry = dcarry
        self._win.append(_Entry(tok, lp))
        while len(self._win) > self.dispatch_ahead:
            self._consume()

    def _consume(self):
        e = self._win.popleft()
        t_f = self._clock()
        nxt, lps = fence("decode", e.tok, e.chosen)
        self.phases["fence_wait"] = self._clock() - t_f


def replay_double_donate(engine, params, tokens, active, knobs):
    """Cold twin: a debugging harness may re-donate deliberately (e.g.
    bisecting a donation bug) — unreachable from a hot root, exempt."""
    engine._dispatch("decode", engine._step_fn, params, tokens, active,
                     engine.carry, knobs)
    return engine._dispatch("decode", engine._step_fn, params, tokens,
                            active, engine.carry, knobs)
