"""SRV202 (payload half, adapter field): the multi-tenant PR extended
``serving/disagg.py``'s ``ROW_PAYLOAD_KEYS`` with ``adapter`` — the
LoRA adapter slot id a restored row gathers its tenant's factors with.
A typo'd spelling silently drops the id on the wire and the receiving
pool restores the row under the NULL adapter: base-model logits for a
tenant's request, diverging only for adapted traffic.  The canonical
``adapter`` reads are the false-positive guards."""

from bigdl_tpu.serving.disagg import unpack_payload


def restore_tenant_row(blob, pool, slot):
    meta, payload = unpack_payload(blob)
    aid = payload["adapter"]                      # schema — fine
    fallback = payload.get("adapter", 0)          # schema — fine
    carry = payload["carry"]                      # schema — fine
    stale = payload["adpater"]                    # EXPECT: SRV202
    payload["adapter_slot"] = aid                 # EXPECT: SRV202
    other = payload.get("adapterid")              # EXPECT: SRV202
    return meta, aid, fallback, carry, stale, other


def repack_tenant(payload, aid):
    payload["adapter"] = int(aid)                 # schema — fine
    if "adaptor" in payload:                      # EXPECT: SRV202
        del payload["adaptor"]                    # EXPECT: SRV202
    return payload
