"""MH402 unordered-agreement-iteration: collectives or cross-process
handoffs issued from iteration over a ``set`` — set order depends on
hash seeding and insertion history, which differ per process, so two
pod peers issue their sends/collectives in different orders and the
receivers (or the collective schedule) disagree.  ``sorted(...)``
iteration and handoff-free set loops are the false-positive guards."""

import jax.numpy as jnp
from jax import lax


class Router:
    def __init__(self, channel, store):
        self.channel = channel
        self.store = store

    def flush(self, payloads):
        pending = {1, 2, 3}
        for slot in pending:                        # EXPECT: MH402
            self.channel.send(payloads[slot])
        for slot in sorted(pending):
            # compliant: a canonical order — every process sends the
            # same sequence
            self.channel.send(payloads[slot])
        live = set(payloads) - {0}
        for slot in live:                           # EXPECT: MH402
            self.store.put(f"row_{slot}", payloads[slot])
        total = 0
        for slot in live:
            # compliant: pure host bookkeeping — no agreement point in
            # the loop body, so per-process order is invisible
            total += payloads[slot]
        return total

    def reduce_axes(self, axes, g):
        for ax in set(axes):                        # EXPECT: MH402
            g = lax.psum(g, ax)
        for ax in sorted(set(axes)):
            # compliant: sorted() materializes a list in ONE order
            g = lax.pmean(g, ax)
        return jnp.sum(g)
