"""ASY305 clock-straddle: wall-clock read pairs timing a device
dispatch with NO fence between the dispatch and the second read —
under async dispatch the elapsed value measures launch latency, not
device work, so phase timers / decode-gap instrumentation / the
watchdog all lie.  Fence-pinned timers, dispatch-free pairs, and the
cold twin are the false-positive guards."""

import time

from bigdl_tpu.models.transformer import get_batch_decode_step
from bigdl_tpu.serving.fences import fence, fence_wait


class MiniEngine:
    def __init__(self, model, dtype, clock=time.perf_counter):
        self._step_fn, self._pool_init = get_batch_decode_step(
            model, dtype, sampling=True)
        self._faults = None
        self._clock = clock
        self.phases = {}

    def _dispatch(self, site, fn, *args):
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        # the injected engine clock everywhere (raw time.* spellings in
        # dispatch-scope code are MH403's business — bad_raw_clock.py);
        # ASY305 judges the PAIRING, not the clock source
        t0 = self._clock()
        tok, lp, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        self.phases["decode"] = self._clock() - t0  # EXPECT: ASY305
        t1 = self._clock()
        tok, lp, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        elapsed = self._clock() - t1                # EXPECT: ASY305
        return tok, carry, elapsed

    def fenced_step(self, params, tokens, active, carry, knobs):  # analysis: hotpath-root
        # timer pinned to the step's fence: measures the work
        t0 = self._clock()
        tok, lp, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        nxt, lps = fence("decode", tok, lp)
        self.phases["decode"] = self._clock() - t0      # fenced: fine
        # completion-wait spelling for trees that stay on device
        t1 = self._clock()
        tok, lp, carry = self._dispatch(
            "decode", self._step_fn, params, tokens, active, carry, knobs)
        carry = fence_wait("prefill", carry)
        self.phases["prefill"] = self._clock() - t1     # fenced: fine
        # a pair with NO dispatch between measures host work — fine
        t2 = self._clock()
        total = sum(int(x) for x in nxt)
        self.phases["host"] = self._clock() - t2
        return nxt, lps, carry, total


def bench_step_wall(engine, params, tokens, active, carry, knobs,
                    clock=time.perf_counter):
    """Cold twin: benches time un-synced dispatches deliberately (wall
    around the whole run) — unreachable, exempt (and the raw clock
    arrives injected, so MH403's dispatch-scope check stays quiet)."""
    t0 = clock()
    tok, lp, carry = engine._dispatch(
        "decode", engine._step_fn, params, tokens, active, carry, knobs)
    return clock() - t0, tok
