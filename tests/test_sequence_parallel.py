"""Ring/Ulysses sequence parallelism vs dense attention, on the 8-device
CPU mesh (the distributed-in-one-process pattern of SURVEY.md §4).

Uses ``utils.compat.shard_map`` (not ``jax.shard_map``) so the suite
runs on every jax generation this repo supports — 0.4.x spells it
``jax.experimental.shard_map`` and calls the replication check
``check_rep``; the shim resolves both."""

import numpy as np
import pytest

from bigdl_tpu.utils.compat import shard_map
from tests.oracle import assert_close


def _mesh(n=8, name="seq"):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]).reshape(n), (name,))


def _qkv(rng, B=2, T=32, H=4, D=8):
    mk = lambda: rng.randn(B, T, H, D).astype(np.float32)
    return mk(), mk(), mk()


def _reference_attention(q, k, v, causal):
    from bigdl_tpu.parallel.ring_attention import attention

    return np.asarray(attention(q, k, v, causal=causal))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(rng, causal):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import ring_attention

    q, k, v = _qkv(rng)
    mesh = _mesh()

    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    ))
    out = np.asarray(ring(q, k, v))
    want = _reference_attention(q, k, v, causal)
    assert_close(out, want, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(rng, causal):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import ulysses_attention

    q, k, v = _qkv(rng, H=8)
    mesh = _mesh()

    uly = jax.jit(shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
    ))
    out = np.asarray(uly(q, k, v))
    want = _reference_attention(q, k, v, causal)
    assert_close(out, want, atol=1e-4)


@pytest.mark.integration
def test_ring_attention_differentiable(rng):
    """The SP loss must differentiate cleanly (training path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import attention, ring_attention

    q, k, v = _qkv(rng, T=16)
    mesh = _mesh()

    def ring_loss(q, k, v):
        def inner(q, k, v):
            o = ring_attention(q, k, v, "seq", causal=True)
            return jax.lax.psum(jnp.sum(o ** 2), "seq")

        return shard_map(
            inner, mesh=mesh,
            in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
            out_specs=P(),
        )(q, k, v)

    def dense_loss(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss)(q, k, v)
    g_dense = jax.grad(dense_loss)(q, k, v)
    assert_close(np.asarray(g_ring), np.asarray(g_dense), atol=2e-3)


def test_mha_module_local_and_ring_agree(rng):
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.nn.attention import MultiHeadAttention

    B, T, Hid = 2, 32, 16
    local = MultiHeadAttention(Hid, 4, causal=True)
    local._ensure_params()
    x = rng.randn(B, T, Hid).astype(np.float32)
    want = np.asarray(local.forward(x))

    sp = MultiHeadAttention(Hid, 4, causal=True, sequence_parallel="ring")
    mesh = _mesh()
    out = jax.jit(shard_map(
        lambda p, x: sp.apply(p, x, {})[0],
        mesh=mesh, in_specs=(P(), P(None, "seq")), out_specs=P(None, "seq"),
    ))(local.params, x)
    assert_close(np.asarray(out), want, atol=1e-4)


def test_mha_trains(rng):
    """MHA composes with the standard layer stack and learns."""
    import jax

    from bigdl_tpu.nn import Linear, Select, Sequential
    from bigdl_tpu.nn.attention import MultiHeadAttention
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.train_step import make_train_step

    model = (Sequential()
             .add(MultiHeadAttention(8, 2))
             .add(Select(2, -1))
             .add(Linear(8, 3)))
    model._ensure_params()
    crit, optim = CrossEntropyCriterion(), Adam(learning_rate=1e-2)
    step = jax.jit(make_train_step(model, crit, optim))
    params, ms = model.params, model.state
    opt_state = optim.init_state(params)
    x = rng.randn(8, 5, 8).astype(np.float32)
    y = (rng.randint(0, 3, size=(8,)) + 1).astype(np.float32)
    k = jax.random.PRNGKey(0)
    losses = []
    for _ in range(40):
        params, opt_state, ms, loss = step(params, opt_state, ms, k, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


@pytest.mark.parametrize("grad", [False, True])
@pytest.mark.integration
def test_ring_attention_flash_matches_dense(rng, grad):
    """Flash-block ring (lse merge fwd, flash-block bwd) vs dense oracle."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import ring_attention

    q, k, v = _qkv(rng)
    mesh = _mesh()

    # check_vma=False: the Pallas INTERPRETER (used off-TPU) can't type
    # mixed-vma dynamic_slice operands (upstream JAX limitation). The ring
    # math itself is vma-correct (accumulators derive from q); compiled
    # multi-chip TPU runs are not exercisable in this single-chip sandbox.
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=False,
                                       use_flash=True),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    ))
    if not grad:
        out = np.asarray(ring(q, k, v))
        want = _reference_attention(q, k, v, causal=False)
        assert_close(out, want, atol=1e-4)
        return

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        from bigdl_tpu.parallel.ring_attention import attention

        return jnp.sum(attention(q, k, v, causal=False) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        assert_close(np.asarray(a), np.asarray(b), atol=1e-3)


@pytest.mark.integration
def test_causal_flash_ring_matches_dense(rng):
    """Striped-causal flash ring (causal diagonal kernel + LSE-nulled future
    blocks) vs single-device dense causal attention — forward AND gradients."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import attention, ring_attention

    n = 4
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("sp",))
    B, T, H, D = 2, 8 * n, 2, 16
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)

    # check_vma=False: Pallas INTERPRETER limitation with mixed-vma
    # dynamic_slice operands (same as the non-causal flash-ring test)
    ring = jax.jit(shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True,
                                       use_flash=True),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False))
    got = np.asarray(ring(q, k, v))
    want = np.asarray(attention(q, k, v, causal=True))
    assert_close(got, want, atol=2e-3)

    # gradient parity (flash fwd, einsum-recompute bwd)
    def ring_loss(q, k, v):
        inner = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=True,
                                           use_flash=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"), check_vma=False)
        return jnp.sum(inner(q, k, v) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        assert_close(np.asarray(a), np.asarray(b), atol=5e-3)


def test_causal_flash_ring_bwd_no_nan_with_large_logits(rng):
    """Regression: future-block p = exp(s − lse_global) can overflow to inf;
    the null must be a NaN-safe select, not multiply-by-zero."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import ring_attention

    n = 4
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("sp",))
    B, T, H, D = 1, 4 * n, 1, 16
    # large-magnitude activations: future-originated scores exceed the
    # global lse by far more than the exp overflow margin (~88)
    q = (rng.randn(B, T, H, D) * 10).astype(np.float32)
    k = (rng.randn(B, T, H, D) * 10).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)

    def loss(q, k, v):
        inner = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=True,
                                           use_flash=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"), check_vma=False)
        return jnp.sum(inner(q, k, v) ** 2)

    grads = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all(), "NaN/inf in ring grads"


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_matches_dense(rng, causal):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import ulysses_attention

    q, k, v = _qkv(rng, H=8)
    mesh = _mesh()
    uly = jax.jit(shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq", causal=causal,
                                          use_flash=True),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"), check_vma=False))
    out = np.asarray(uly(q, k, v))
    want = _reference_attention(q, k, v, causal)
    assert_close(out, want, atol=1e-3)

    # differentiable
    g = jax.grad(lambda q: jnp.sum(uly(q, k, v) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.integration
@pytest.mark.slow
def test_striped_ring_matches_dense_causal():
    """Striped causal ring (balanced schedule — no computed-then-nulled
    blocks) must equal dense causal attention on the unstriped global
    sequence, forward and backward. Slow-marked (out of the tier-1
    budget): ~90 s of 8-device fwd+bwd compile; the multichip dryrun
    re-proves this parity every round, and the full (non-tier-1) loop
    still runs it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from bigdl_tpu.parallel.ring_attention import (
        attention, stripe_sequence, striped_ring_attention,
        unstripe_sequence,
    )

    n = 8
    mesh = Mesh(np.asarray(jax.devices()[:n]).reshape(n), ("seq",))
    B, T, H, D = 2, 64, 2, 16
    rs = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rs.randn(B, T, H, D).astype(np.float32) * 0.5)
               for _ in range(3))

    def run(qs, ks, vs):
        # check_vma=False: Pallas INTERPRETER limitation with mixed-vma
        # operands (same workaround as the flash-ring tests above)
        inner = shard_map(
            lambda a, b, c: striped_ring_attention(a, b, c, "seq"),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"), check_vma=False)
        return inner(qs, ks, vs)

    qs, ks, vs = (stripe_sequence(x, n) for x in (q, k, v))
    got = unstripe_sequence(run(qs, ks, vs), n)
    want = attention(q, k, v, causal=True)
    assert_close(np.asarray(got), np.asarray(want), atol=2e-4)

    # gradients: d/dq,k,v of sum(out * w) must match the dense oracle
    w = jnp.asarray(rs.randn(B, T, H, D).astype(np.float32))

    def loss_striped(q, k, v):
        qs, ks, vs = (stripe_sequence(x, n) for x in (q, k, v))
        out = unstripe_sequence(run(qs, ks, vs), n)
        return jnp.sum(out * w)

    def loss_dense(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) * w)

    g_s = jax.grad(loss_striped, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_s, g_d):
        assert_close(np.asarray(a), np.asarray(b), atol=3e-4)


def test_stripe_roundtrip():
    from bigdl_tpu.parallel.ring_attention import (
        stripe_sequence, unstripe_sequence,
    )

    x = np.arange(2 * 12 * 3, dtype=np.float32).reshape(2, 12, 3)
    s = stripe_sequence(x, 4)
    # rank 0's shard (first T/n rows) must hold tokens 0, 4, 8
    np.testing.assert_array_equal(np.asarray(s)[:, :3], x[:, [0, 4, 8]])
    np.testing.assert_array_equal(np.asarray(unstripe_sequence(s, 4)), x)


def test_mha_module_striped_ring_agrees(rng):
    """MultiHeadAttention(sequence_parallel="striped_ring") on STRIPED
    input must equal the plain causal layer on the contiguous sequence."""
    import jax
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.nn.attention import MultiHeadAttention
    from bigdl_tpu.parallel.ring_attention import (
        stripe_sequence, unstripe_sequence,
    )

    B, T, Hid = 2, 32, 16
    local = MultiHeadAttention(Hid, 4, causal=True)
    local._ensure_params()
    x = rng.randn(B, T, Hid).astype(np.float32)
    want = np.asarray(local.forward(x))

    sp = MultiHeadAttention(Hid, 4, causal=True,
                            sequence_parallel="striped_ring")
    mesh = _mesh()
    n = mesh.devices.size
    xs = stripe_sequence(x, n)
    out = jax.jit(shard_map(
        lambda p, x: sp.apply(p, x, {})[0],
        mesh=mesh, in_specs=(P(), P(None, "seq")), out_specs=P(None, "seq"),
        check_vma=False,
    ))(local.params, xs)
    assert_close(np.asarray(unstripe_sequence(out, n)), want, atol=1e-4)

    with pytest.raises(ValueError, match="causal-only"):
        MultiHeadAttention(Hid, 4, causal=False,
                           sequence_parallel="striped_ring")
