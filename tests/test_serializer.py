"""Reflective serialization round-trip sweep.

Mirrors the reference's ``SerializerSpec.scala`` (SURVEY.md §4): enumerate
every exported module class, round-trip each through the structured
``save_module``/``load_module`` format, and diff forward outputs — so no
layer can silently miss serialization support.
"""

from __future__ import annotations

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import AbstractModule
from tests.oracle import assert_close

R = np.random.default_rng(7)


def x(*shape):
    return R.standard_normal(shape).astype(np.float32)


def _seq():
    return nn.Sequential().add(nn.Linear(4, 3)).add(nn.ReLU())


def _recurrent():
    return nn.Recurrent().add(nn.LSTM(3, 4))


def _quantized_linear():
    lin = nn.Linear(4, 3)
    lin._ensure_params()
    return nn.QuantizedLinear.from_linear(lin)


def _quantized_conv():
    conv = nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
    conv._ensure_params()
    return nn.QuantizedSpatialConvolution.from_conv(conv)


def _sparse_input():
    from bigdl_tpu.tensor import SparseTensor

    dense = x(2, 4) * (R.random((2, 4)) < 0.5)
    return SparseTensor.from_dense(dense, capacity=10)


def _sparse_ids():
    from bigdl_tpu.tensor import SparseTensor

    ids = np.array([[1, 3, 0], [2, 5, 0]], np.float32)
    return SparseTensor.from_dense(ids, capacity=8)


def _graph():
    inp = nn.Input()
    a = nn.Linear(4, 4).inputs(inp)
    b = nn.ReLU().inputs(a)
    c = nn.CAddTable().inputs(a, b)  # diamond: shared predecessor
    return nn.Graph(inp, c)


# name -> (factory, example_input); input None = layer takes a table/list
FACTORIES = {
    "Abs": (lambda: nn.Abs(), x(2, 3)),
    "Add": (lambda: nn.Add(4), x(2, 4)),
    "AddConstant": (lambda: nn.AddConstant(1.5), x(2, 3)),
    "BatchNormalization": (lambda: nn.BatchNormalization(4), x(3, 4)),
    "BiRecurrent": (lambda: nn.BiRecurrent().add(nn.GRU(3, 4)), x(2, 5, 3)),
    "Bottle": (lambda: nn.Bottle(nn.Linear(4, 3), 2, 2), x(2, 5, 4)),
    "CAdd": (lambda: nn.CAdd((3,)), x(2, 3)),
    "CAddTable": (lambda: nn.CAddTable(), [x(2, 3), x(2, 3)]),
    "CDivTable": (lambda: nn.CDivTable(), [x(2, 3), x(2, 3) + 3.0]),
    "CMaxTable": (lambda: nn.CMaxTable(), [x(2, 3), x(2, 3)]),
    "CAveTable": (lambda: nn.CAveTable(), [x(2, 3), x(2, 3)]),
    "CMinTable": (lambda: nn.CMinTable(), [x(2, 3), x(2, 3)]),
    "CMul": (lambda: nn.CMul((3,)), x(2, 3)),
    "CMulTable": (lambda: nn.CMulTable(), [x(2, 3), x(2, 3)]),
    "CSubTable": (lambda: nn.CSubTable(), [x(2, 3), x(2, 3)]),
    "Clamp": (lambda: nn.Clamp(-0.5, 0.5), x(2, 3)),
    "Concat": (lambda: nn.Concat(2).add(nn.Linear(4, 2)).add(nn.Linear(4, 3)), x(2, 4)),
    "ConcatTable": (lambda: nn.ConcatTable().add(nn.Linear(4, 2)).add(nn.Linear(4, 2)), x(2, 4)),
    "Contiguous": (lambda: nn.Contiguous(), x(2, 3)),
    "Dropout": (lambda: nn.Dropout(0.5), x(2, 3)),
    "ELU": (lambda: nn.ELU(), x(2, 3)),
    "Echo": (lambda: nn.Echo(), x(2, 3)),
    "Exp": (lambda: nn.Exp(), x(2, 3)),
    "FlattenTable": (lambda: nn.FlattenTable(), [x(2, 3), [x(2, 3), x(2, 3)]]),
    "GELU": (lambda: nn.GELU(), x(2, 3)),
    "GRU": (lambda: nn.GRU(3, 4), None),
    "Graph": (_graph, x(2, 4)),
    "HardTanh": (lambda: nn.HardTanh(), x(2, 3)),
    "Identity": (lambda: nn.Identity(), x(2, 3)),
    "JoinTable": (lambda: nn.JoinTable(1, 2), [x(2, 3), x(2, 3)]),
    "LSTM": (lambda: nn.LSTM(3, 4), None),
    "LSTMPeephole": (lambda: nn.LSTMPeephole(3, 4), None),
    "LeakyReLU": (lambda: nn.LeakyReLU(), x(2, 3)),
    "Linear": (lambda: nn.Linear(4, 3), x(2, 4)),
    "Log": (lambda: nn.Log(), np.abs(x(2, 3)) + 0.1),
    "LogSoftMax": (lambda: nn.LogSoftMax(), x(2, 3)),
    "LookupTable": (lambda: nn.LookupTable(10, 4), np.array([[1, 2], [3, 4]], np.int32)),
    "MM": (lambda: nn.MM(), [x(2, 3, 4), x(2, 4, 5)]),
    "MV": (lambda: nn.MV(), [x(2, 3, 4), x(2, 4)]),
    "MapTable": (lambda: nn.MapTable(nn.Linear(4, 3)), [x(2, 4), x(2, 4)]),
    "Max": (lambda: nn.Max(1), x(2, 3)),
    "Mean": (lambda: nn.Mean(1), x(2, 3)),
    "Min": (lambda: nn.Min(1), x(2, 3)),
    "Mul": (lambda: nn.Mul(), x(2, 3)),
    "MulConstant": (lambda: nn.MulConstant(2.0), x(2, 3)),
    "MultiHeadAttention": (lambda: nn.MultiHeadAttention(8, 2), x(2, 5, 8)),
    "Narrow": (lambda: nn.Narrow(1, 0, 2), x(2, 4)),
    "Normalize": (lambda: nn.Normalize(2.0), x(2, 3)),
    "PReLU": (lambda: nn.PReLU(), x(2, 3)),
    "Padding": (lambda: nn.Padding(1, 2, 2), x(2, 3)),
    "ParallelTable": (lambda: nn.ParallelTable().add(nn.Linear(4, 2)).add(nn.Linear(3, 2)),
                      [x(2, 4), x(2, 3)]),
    "Power": (lambda: nn.Power(2.0), np.abs(x(2, 3)) + 0.1),
    "ReLU": (lambda: nn.ReLU(), x(2, 3)),
    "ReLU6": (lambda: nn.ReLU6(), x(2, 3)),
    "Bilinear": (lambda: nn.Bilinear(4, 5, 3), [x(2, 4), x(2, 5)]),
    "GaussianDropout": (lambda: nn.GaussianDropout(0.3), x(2, 3)),
    "GaussianNoise": (lambda: nn.GaussianNoise(0.5), x(2, 3)),
    "HardShrink": (lambda: nn.HardShrink(), x(2, 3)),
    "HardSigmoid": (lambda: nn.HardSigmoid(), x(2, 3)),
    "SoftShrink": (lambda: nn.SoftShrink(), x(2, 3)),
    "TanhShrink": (lambda: nn.TanhShrink(), x(2, 3)),
    "Cosine": (lambda: nn.Cosine(4, 3), x(2, 4)),
    "CosineDistance": (lambda: nn.CosineDistance(), [x(2, 4), x(2, 4)]),
    "DotProduct": (lambda: nn.DotProduct(), [x(2, 4), x(2, 4)]),
    "Euclidean": (lambda: nn.Euclidean(4, 3), x(2, 4)),
    "GaussianSampler": (lambda: nn.GaussianSampler(), [x(2, 3), x(2, 3)]),
    "GradientReversal": (lambda: nn.GradientReversal(0.5), x(2, 3)),
    "Index": (lambda: nn.Index(1), [x(3, 4), np.array([2.0, 1.0])]),
    "L1Penalty": (lambda: nn.L1Penalty(0.1), x(2, 3)),
    "LogSigmoid": (lambda: nn.LogSigmoid(), x(2, 3)),
    "Masking": (lambda: nn.Masking(0.0), x(2, 4, 3)),
    "NarrowTable": (lambda: nn.NarrowTable(1, 2), None),
    "Negative": (lambda: nn.Negative(), x(2, 3)),
    "PairwiseDistance": (lambda: nn.PairwiseDistance(), [x(2, 4), x(2, 4)]),
    "RReLU": (lambda: nn.RReLU(), x(2, 3)),
    "Replicate": (lambda: nn.Replicate(3, 1), x(2, 4)),
    "Scale": (lambda: nn.Scale((3,)), x(2, 3, 4, 4)),
    "SelectTable": (lambda: nn.SelectTable(1), [x(2, 3), x(2, 3)]),
    "SoftMin": (lambda: nn.SoftMin(), x(2, 3)),
    "SpatialDilatedConvolution": (
        lambda: nn.SpatialDilatedConvolution(3, 4, 3, 3, 1, 1, 2, 2, 2, 2),
        x(2, 3, 8, 8)),
    "SpatialUpSamplingBilinear": (lambda: nn.SpatialUpSamplingBilinear(2),
                                  x(2, 3, 4, 4)),
    "SpatialUpSamplingNearest": (lambda: nn.SpatialUpSamplingNearest(2),
                                 x(2, 3, 4, 4)),
    "SpatialZeroPadding": (lambda: nn.SpatialZeroPadding(1), x(2, 3, 4, 4)),
    "TemporalConvolution": (lambda: nn.TemporalConvolution(4, 5, 3),
                            x(2, 7, 4)),
    "Threshold": (lambda: nn.Threshold(0.1, 0.0), x(2, 3)),
    "VolumetricAveragePooling": (lambda: nn.VolumetricAveragePooling(2, 2, 2),
                                 x(1, 2, 4, 4, 4)),
    "VolumetricConvolution": (
        lambda: nn.VolumetricConvolution(2, 3, 3, 3, 3, 1, 1, 1, 1, 1, 1),
        x(1, 2, 5, 5, 5)),
    "VolumetricMaxPooling": (lambda: nn.VolumetricMaxPooling(2, 2, 2),
                             x(1, 2, 4, 4, 4)),
    "QuantizedLinear": (_quantized_linear, x(2, 4)),
    "QuantizedSpatialConvolution": (_quantized_conv, x(2, 3, 5, 5)),
    "SparseLinear": (lambda: nn.SparseLinear(4, 3), _sparse_input()),
    "SparseJoinTable": (lambda: nn.SparseJoinTable(2), None),
    "Remat": (lambda: nn.Remat(nn.Linear(4, 3)), x(2, 4)),
    "Recurrent": (_recurrent, x(2, 5, 3)),
    "RecurrentDecoder": (lambda: nn.RecurrentDecoder(4).add(nn.RnnCell(3, 3)), x(2, 3)),
    "Reshape": (lambda: nn.Reshape([6]), x(2, 2, 3)),
    "RnnCell": (lambda: nn.RnnCell(3, 4), None),
    "Select": (lambda: nn.Select(1, 1), x(2, 4)),
    "Sequential": (_seq, x(2, 4)),
    "Sigmoid": (lambda: nn.Sigmoid(), x(2, 3)),
    "SoftMax": (lambda: nn.SoftMax(), x(2, 3)),
    "SoftPlus": (lambda: nn.SoftPlus(), x(2, 3)),
    "SoftSign": (lambda: nn.SoftSign(), x(2, 3)),
    "SpatialAveragePooling": (lambda: nn.SpatialAveragePooling(2, 2, 2, 2), x(2, 3, 4, 4)),
    "SpatialBatchNormalization": (lambda: nn.SpatialBatchNormalization(3), x(2, 3, 4, 4)),
    "SpatialConvolution": (lambda: nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1), x(2, 3, 5, 5)),
    "SpatialCrossMapLRN": (lambda: nn.SpatialCrossMapLRN(), x(2, 5, 4, 4)),
    "SpatialFullConvolution": (lambda: nn.SpatialFullConvolution(3, 4, 3, 3), x(2, 3, 5, 5)),
    "SpatialMaxPooling": (lambda: nn.SpatialMaxPooling(2, 2, 2, 2), x(2, 3, 4, 4)),
    "SplitTable": (lambda: nn.SplitTable(1, 2), x(2, 3)),
    "Sqrt": (lambda: nn.Sqrt(), np.abs(x(2, 3)) + 0.1),
    "Square": (lambda: nn.Square(), x(2, 3)),
    "Squeeze": (lambda: nn.Squeeze(2), x(2, 1, 3)),
    "Sum": (lambda: nn.Sum(1), x(2, 3)),
    "Tanh": (lambda: nn.Tanh(), x(2, 3)),
    "TimeDistributed": (lambda: nn.TimeDistributed(nn.Linear(3, 4)), x(2, 5, 3)),
    "Transpose": (lambda: nn.Transpose([(0, 1)]), x(2, 3)),
    "Unsqueeze": (lambda: nn.Unsqueeze(1), x(2, 3)),
    "View": (lambda: nn.View(6), x(2, 2, 3)),
    "Pack": (lambda: nn.Pack(1), [x(2, 3), x(2, 3)]),
    "Tile": (lambda: nn.Tile(2, 2), x(2, 3)),
    "Reverse": (lambda: nn.Reverse(1), x(2, 3)),
    "InferReshape": (lambda: nn.InferReshape([-1, 4]), x(2, 2, 4)),
    "BifurcateSplitTable": (lambda: nn.BifurcateSplitTable(2), None),
    "MixtureTable": (lambda: nn.MixtureTable(),
                     [np.abs(x(2, 2)), [x(2, 3), x(2, 3)]]),
    "MaskedSelect": (lambda: nn.MaskedSelect(),
                     [x(2, 3), np.ones((2, 3), np.float32)]),
    "DenseToSparse": (lambda: nn.DenseToSparse(capacity=8), None),
    "SReLU": (lambda: nn.SReLU((3,)), x(2, 3)),
    "Maxout": (lambda: nn.Maxout(4, 3, 2), x(2, 4)),
    "TemporalMaxPooling": (lambda: nn.TemporalMaxPooling(2), x(2, 6, 3)),
    "ConvLSTMPeephole": (
        lambda: nn.Recurrent().add(nn.ConvLSTMPeephole(2, 3)),
        x(1, 2, 2, 4, 4)),
    "TemporalAveragePooling": (lambda: nn.TemporalAveragePooling(2),
                               x(2, 6, 3)),
    "VolumetricZeroPadding": (lambda: nn.VolumetricZeroPadding(1, 1, 1),
                              x(1, 2, 2, 3, 3)),
    "UpSampling1D": (lambda: nn.UpSampling1D(2), x(2, 4, 3)),
    "UpSampling3D": (lambda: nn.UpSampling3D((2, 2, 2)), x(1, 2, 2, 3, 3)),
    "Cropping2D": (lambda: nn.Cropping2D((1, 1), (1, 1)), x(2, 3, 5, 5)),
    "Cropping3D": (lambda: nn.Cropping3D((1, 0), (0, 1), (1, 1)),
                   x(1, 2, 4, 4, 4)),
    "VolumetricFullConvolution": (
        lambda: nn.VolumetricFullConvolution(2, 3, 2, 2, 2), x(1, 2, 3, 3, 3)),
    "LocallyConnected1D": (lambda: nn.LocallyConnected1D(6, 4, 3, 3),
                           x(2, 6, 4)),
    "LocallyConnected2D": (
        lambda: nn.LocallyConnected2D(2, 5, 5, 3, 3, 3), x(2, 2, 5, 5)),
    "SpatialShareConvolution": (
        lambda: nn.SpatialShareConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        x(2, 3, 5, 5)),
    "SpatialSeparableConvolution": (
        lambda: nn.SpatialSeparableConvolution(3, 4, 2, 3, 3, p_w=1, p_h=1),
        x(2, 3, 5, 5)),
    "SpatialDropout1D": (lambda: nn.SpatialDropout1D(0.5), x(2, 4, 3)),
    "SpatialDropout2D": (lambda: nn.SpatialDropout2D(0.5), x(2, 3, 4, 4)),
    "SpatialDropout3D": (lambda: nn.SpatialDropout3D(0.5), x(1, 2, 3, 3, 3)),
    "SpatialWithinChannelLRN": (lambda: nn.SpatialWithinChannelLRN(3),
                                x(2, 3, 5, 5)),
    "SpatialSubtractiveNormalization": (
        lambda: nn.SpatialSubtractiveNormalization(3), x(2, 3, 6, 6)),
    "SpatialDivisiveNormalization": (
        lambda: nn.SpatialDivisiveNormalization(3), x(2, 3, 6, 6)),
    "SpatialContrastiveNormalization": (
        lambda: nn.SpatialContrastiveNormalization(3), x(2, 3, 6, 6)),
    "NegativeEntropyPenalty": (lambda: nn.NegativeEntropyPenalty(0.1),
                               np.abs(x(2, 3)) + 0.1),
    "MultiRNNCell": (lambda: nn.MultiRNNCell([nn.LSTM(3, 4), nn.GRU(4, 3)]),
                     None),
    "SpatialConvolutionMap": (
        lambda: nn.SpatialConvolutionMap(
            nn.SpatialConvolutionMap.full(2, 3), 3, 3, pad_w=1, pad_h=1),
        x(2, 2, 5, 5)),
    "LookupTableSparse": (lambda: nn.LookupTableSparse(6, 4), _sparse_ids()),
}

# abstract/base/helper classes with no standalone forward semantics,
# or classes exercised only through a wrapper factory above
EXEMPT = {
    "AbstractModule", "TensorModule", "Container", "Module",
    "Cell", "StaticGraph", "ModuleNode", "Input",
    # wraps a caller-supplied symbols_to_logits closure — inherently not
    # round-trippable (the reference serializes its transformer-bound
    # variant by reconstructing that closure from the bound model)
    "SequenceBeamSearch",
}


def _module_classes():
    out = {}
    for name in dir(nn):
        obj = getattr(nn, name)
        if isinstance(obj, type) and issubclass(obj, AbstractModule):
            out[name] = obj
    return out


def test_sweep_is_complete():
    """Every exported module class must have a round-trip factory."""
    classes = _module_classes()
    missing = set(classes) - set(FACTORIES) - EXEMPT
    assert not missing, f"layers missing serialization sweep coverage: {sorted(missing)}"


@pytest.mark.parametrize("name", sorted(FACTORIES))
def test_roundtrip(name, tmp_path):
    factory, inp = FACTORIES[name]
    m = factory()
    m.evaluate()  # deterministic forward for comparison
    path = str(tmp_path / f"{name}.bigdl")
    if inp is not None:
        before = np.asarray(m.forward(inp))
    m.save_module(path)
    m2 = AbstractModule.load_module(path)
    assert type(m2) is type(m)
    m2.evaluate()
    if inp is not None:
        after = np.asarray(m2.forward(inp))
        assert_close(before, after, atol=1e-6, rtol=1e-6,
                     msg=f"{name} forward changed across round-trip")
    else:  # bare cells: compare parameter pytrees
        w1, _ = m.parameters()
        w2, _ = m2.parameters()
        assert len(w1) == len(w2)
        for a, b in zip(w1, w2):
            assert_close(a, b, atol=0, rtol=0, msg=name)


def test_version_check(tmp_path):
    import json
    import zipfile

    from bigdl_tpu.utils.serializer import FORMAT_VERSION

    p = str(tmp_path / "m.bigdl")
    nn.Linear(2, 2).save_module(p)
    with zipfile.ZipFile(p) as z:
        spec = json.loads(z.read("spec.json"))
        arrays = z.read("arrays.npz")
    spec["version"] = FORMAT_VERSION + 1
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("spec.json", json.dumps(spec))
        z.writestr("arrays.npz", arrays)
    with pytest.raises(ValueError, match="newer"):
        AbstractModule.load_module(p)


def test_legacy_pickle_graph_roundtrip(tmp_path):
    """Module.save/load (legacy path) must survive Graph id-keyed caches."""
    m = _graph()
    m.evaluate()
    inp = x(2, 4)
    before = np.asarray(m.forward(inp))
    p = str(tmp_path / "g.bin")
    m.save(p)
    m2 = AbstractModule.load(p)
    after = np.asarray(m2.forward(inp))
    assert_close(before, after, atol=1e-6, rtol=1e-6)


@pytest.mark.integration
def test_resnet_roundtrip(tmp_path):
    """End-to-end: a real zoo Graph model round-trips bit-exact."""
    from bigdl_tpu.models.resnet import ResNet

    m = ResNet(class_num=10, opt={"depth": 20, "shortcutType": "A",
                                  "dataSet": "cifar10"})
    m.evaluate()
    inp = x(2, 3, 32, 32)
    before = np.asarray(m.forward(inp))
    p = str(tmp_path / "resnet.bigdl")
    m.save_module(p)
    m2 = AbstractModule.load_module(p)
    after = np.asarray(m2.forward(inp))
    assert_close(before, after, atol=1e-6, rtol=1e-6)


def test_aliased_arrays_keep_identity(tmp_path):
    """Tied weights (reference share() semantics): aliased arrays must come
    back as ONE array, stored once."""
    from bigdl_tpu.utils.serializer import save_module, load_module

    a = nn.Linear(4, 4)
    b = nn.Linear(4, 4)
    a._ensure_params()
    b._ensure_params()
    b.params["weight"] = a.params["weight"]  # tie
    m = nn.Sequential().add(a).add(b)
    p = str(tmp_path / "tied.bigdl")
    save_module(m, p)
    m2 = load_module(p)
    w1 = m2.modules[0].params["weight"]
    w2 = m2.modules[1].params["weight"]
    assert w1 is w2, "aliased parameter arrays were untied by a round-trip"


def test_save_module_creates_directories(tmp_path):
    from bigdl_tpu.utils.serializer import save_module, load_module

    m = nn.Linear(3, 2)
    p = str(tmp_path / "new" / "sub" / "m.bigdl")
    save_module(m, p)
    load_module(p)
