"""End-to-end: LeNet-5 on (synthetic) MNIST via LocalOptimizer — BASELINE
config #1, the reference's minimum end-to-end slice (SURVEY.md §7 stage 4)."""

import numpy as np

from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.mnist import TRAIN_MEAN, TRAIN_STD, load_samples
from bigdl_tpu.dataset.image import GreyImgNormalizer
from bigdl_tpu.models import LeNet5
from bigdl_tpu.nn import ClassNLLCriterion
from bigdl_tpu.optim import Adam, Optimizer, LocalOptimizer, Top1Accuracy, Trigger


def _mnist_ds(kind, n, batch):
    samples = load_samples("/nonexistent", kind, synthetic_count=n)
    return (
        DataSet.array(samples)
        .transform(GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD))
        .transform(SampleToMiniBatch(batch))
    )


def test_lenet_mnist_end_to_end(tmp_path):
    train_ds = _mnist_ds("train", 512, 64)
    val_ds = _mnist_ds("val", 256, 64)

    model = LeNet5(10)
    optimizer = Optimizer(
        model=model, dataset=train_ds, criterion=ClassNLLCriterion()
    )
    assert isinstance(optimizer, LocalOptimizer)
    (
        optimizer.set_optim_method(Adam(learning_rate=1e-3))
        .set_end_when(Trigger.max_epoch(3))
        .set_validation(Trigger.every_epoch(), val_ds, [Top1Accuracy()])
        .set_checkpoint(str(tmp_path / "ckpt"), Trigger.every_epoch())
    )
    trained = optimizer.optimize()

    # the synthetic digits are learnable: expect well above chance
    correct = total = 0
    for batch in val_ds.data(train=False):
        out = trained.predict(batch.get_input())
        r = Top1Accuracy().apply(out, batch.get_target())
        correct += r.correct
        total += r.count
    acc = correct / total
    assert acc > 0.5, f"accuracy {acc} not above chance"

    # checkpoint exists and resumes
    assert (tmp_path / "ckpt" / "model").exists()
    assert (tmp_path / "ckpt" / "optimMethod").exists()


def test_checkpoint_resume(tmp_path):
    """Kill mid-training, resume from snapshot (reference §5.3 retry loop)."""
    train_ds = _mnist_ds("train", 256, 64)
    model = LeNet5(10)
    opt = Optimizer(model=model, dataset=train_ds, criterion=ClassNLLCriterion())
    opt.set_optim_method(Adam(1e-3)).set_end_when(Trigger.max_iteration(6))
    opt.set_checkpoint(str(tmp_path / "ck"), Trigger.several_iteration(2))
    opt.optimize()

    snap = opt._latest_checkpoint()
    assert snap is not None
    _mblob, oblob = snap
    assert oblob["neval"] >= 5


def test_raw_sample_list_api():
    """pyspark-style: pass raw samples + batch_size straight to Optimizer."""
    samples = load_samples("/nonexistent", "train", synthetic_count=128)
    model = LeNet5(10)
    opt = Optimizer(
        model=model, dataset=samples, criterion=ClassNLLCriterion(), batch_size=32
    )
    opt.set_optim_method(Adam(1e-3)).set_end_when(Trigger.max_iteration(3))
    trained = opt.optimize()
    assert trained is model


def test_mixed_precision_trains(rng):
    """set_compute_dtype('bf16'): loss decreases, params stay fp32."""
    import numpy as np

    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    xs = [(rng.randn(6) * 0.3 + np.eye(3)[i % 3].repeat(2) * 2).astype(np.float32)
          for i in range(60)]
    ys = [np.int32(i % 3 + 1) for i in range(60)]
    m = (Sequential().add(Linear(6, 16)).add(ReLU())
         .add(Linear(16, 3)).add(LogSoftMax()))
    opt = Optimizer(model=m, dataset=DataSet.array(
        [Sample(x, y) for x, y in zip(xs, ys)]),
        criterion=ClassNLLCriterion(), batch_size=20)
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.set_end_when(Trigger.max_epoch(15))
    opt.set_compute_dtype("bf16")
    trained = opt.optimize()
    ws, _ = trained.parameters()
    assert all(np.asarray(w).dtype == np.float32 for w in ws)
    pred = np.asarray(trained.evaluate().forward(np.stack(xs))).argmax(-1) + 1
    assert (pred == np.asarray(ys)).mean() > 0.8


def test_optimizer_handles_finite_train_iterator():
    """Regression for input pipelining: a custom dataset whose train
    iterator is FINITE must finish cleanly (no StopIteration escape)."""
    import numpy as np

    from bigdl_tpu.dataset.dataset import AbstractDataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rng = np.random.default_rng(0)
    samples = [Sample(rng.standard_normal(4).astype(np.float32),
                      rng.standard_normal(2).astype(np.float32))
               for _ in range(32)]

    class FiniteDataSet(AbstractDataSet):
        def size(self):
            return len(samples)

        def data(self, train):
            # exactly TWO epochs worth, then exhausted — not infinite
            batcher = SampleToMiniBatch(8)
            return batcher(iter(samples * 2))

    opt = Optimizer(model=Sequential().add(Linear(4, 2)),
                    dataset=FiniteDataSet(),
                    criterion=MSECriterion(), batch_size=8,
                    end_trigger=Trigger.max_epoch(2))
    opt.set_optim_method(SGD(learning_rate=0.05))
    model = opt.optimize()   # must not raise StopIteration
    ws, _ = model.parameters()
    assert all(np.isfinite(np.asarray(w)).all() for w in ws)


def test_set_checkpoint_pyspark_keywords(tmp_path):
    """pyspark keyword dialect (advisor finding): set_checkpoint(
    checkpoint_trigger=..., checkpoint_path=...) must work like the
    positional forms."""
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.dataset.sample import Sample

    model = Sequential().add(Linear(4, 2))
    samples = [Sample(np.zeros(4, np.float32), np.zeros(2, np.float32))]
    opt = Optimizer(model=model, dataset=samples,
                    criterion=MSECriterion(), batch_size=1)
    opt.set_checkpoint(checkpoint_trigger=Trigger.every_epoch(),
                       checkpoint_path=str(tmp_path / "ck"))
    assert opt.checkpoint_path == str(tmp_path / "ck")
    assert opt.checkpoint_trigger is not None
    # Scala and pyspark positional dialects still accepted
    opt.set_checkpoint(str(tmp_path / "ck2"), Trigger.every_epoch())
    assert opt.checkpoint_path == str(tmp_path / "ck2")
    opt.set_checkpoint(Trigger.every_epoch(), str(tmp_path / "ck3"))
    assert opt.checkpoint_path == str(tmp_path / "ck3")
    import pytest as _pytest
    with _pytest.raises(ValueError, match="path and a trigger"):
        opt.set_checkpoint(str(tmp_path / "ck4"))


def test_end_when_every_epoch_stops(tmp_path):
    """Regression: the speculative prefetch peek must not consume
    every_epoch's one-shot latch — set_end_when(Trigger.every_epoch())
    stops after exactly one epoch on an infinite dataset."""
    from bigdl_tpu.nn import Linear, MSECriterion, Sequential
    from bigdl_tpu.optim import Optimizer, Trigger
    from bigdl_tpu.dataset.sample import Sample

    rs = np.random.RandomState(0)
    samples = [Sample(rs.rand(4).astype(np.float32),
                      rs.rand(2).astype(np.float32)) for _ in range(32)]
    model = Sequential().add(Linear(4, 2))
    opt = Optimizer(model=model, dataset=samples,
                    criterion=MSECriterion(), batch_size=8)
    opt.set_end_when(Trigger.every_epoch())
    opt.retry_times = 1
    opt.optimize()  # would loop forever if the latch were consumed
    assert opt.optim_method.state["epoch"] == 2  # stopped after epoch 1

    # mixed pyspark dialect keeps its positional trigger
    opt.set_checkpoint(Trigger.every_epoch(), checkpoint_path=str(tmp_path))
    assert opt.checkpoint_path == str(tmp_path)
    assert opt.checkpoint_trigger is not None


def test_pod_resume_consistency_helpers(tmp_path, monkeypatch):
    """On a multi-process pod every rank checkpoints under proc_<rank> of
    one shared path; resume must reconcile to the pod-wide COMMON
    iteration (min over LATEST sidecars) instead of silently restoring
    skewed per-rank snapshots."""
    import jax

    from bigdl_tpu.optim.optimizer import Optimizer

    opt = Optimizer.__new__(Optimizer)
    opt.checkpoint_path = str(tmp_path)

    # single process: the configured path is used verbatim
    assert opt._ckpt_dir() == str(tmp_path)
    assert opt._pod_common_neval(42) == 42

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    assert opt._ckpt_dir() == str(tmp_path / "proc_1")

    # sidecars present and skewed: the common iteration is the minimum
    for rank, neval in ((0, 100), (1, 105)):
        d = tmp_path / f"proc_{rank}"
        d.mkdir()
        opt._write_latest_marker(str(d), neval)
    assert (tmp_path / "proc_1" / "LATEST").read_text() == "105"
    assert opt._pod_common_neval(105) == 100

    # equal sidecars: own neval stands
    opt._write_latest_marker(str(tmp_path / "proc_0"), 105)
    assert opt._pod_common_neval(105) == 105

    # unreadable sibling sidecar is skipped, not fatal
    (tmp_path / "proc_0" / "LATEST").write_text("garbage")
    assert opt._pod_common_neval(105) == 105


def test_pod_fresh_start_with_peer_snapshots_raises(tmp_path, monkeypatch):
    """A pod rank with NOTHING restorable must refuse to silently start
    fresh while peers hold snapshots (the other door into iteration
    skew)."""
    import jax
    import pytest

    from bigdl_tpu.optim.optimizer import Optimizer

    opt = Optimizer.__new__(Optimizer)
    opt.checkpoint_path = str(tmp_path)
    opt.checkpoint_backend = "pickle"
    opt._async_ckptr = None
    opt._async_pending_marker = None

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    # no checkpoints anywhere: clean fresh start is fine
    assert opt._latest_checkpoint() is None
    # peer holds a snapshot: fresh start must refuse
    (tmp_path / "proc_0").mkdir()
    opt._write_latest_marker(str(tmp_path / "proc_0"), 7)
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        opt._latest_checkpoint()
