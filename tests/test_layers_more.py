"""Third layer/criterion breadth batch vs torch oracles / closed forms
(SURVEY.md §2.2 inventory; §4 oracle pattern)."""

import numpy as np
import pytest

from tests.oracle import assert_close


# ---------------------------------------------------------------------------
# table / shape utilities
# ---------------------------------------------------------------------------

def test_pack_tile_reverse(rng):
    from bigdl_tpu.nn import Pack, Reverse, Tile

    a = rng.randn(2, 3).astype(np.float32)
    b = rng.randn(2, 3).astype(np.float32)
    assert_close(np.asarray(Pack(1).forward([a, b])), np.stack([a, b], 0))
    assert_close(np.asarray(Pack(2).forward([a, b])), np.stack([a, b], 1))
    assert_close(np.asarray(Tile(2, 3).forward(a)),
                 np.concatenate([a, a, a], 1))
    assert_close(np.asarray(Reverse(1).forward(a)), a[::-1])


def test_infer_reshape():
    from bigdl_tpu.nn import InferReshape

    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    assert InferReshape([-1, 4]).forward(x).shape == (6, 4)
    assert InferReshape([0, -1]).forward(x).shape == (2, 12)
    assert InferReshape([-1], batch_mode=True).forward(x).shape == (2, 12)


def test_bifurcate_split_mixture(rng):
    from bigdl_tpu.nn import BifurcateSplitTable, MixtureTable

    x = rng.randn(3, 8).astype(np.float32)
    a, b = BifurcateSplitTable(2).forward(x)
    assert_close(np.asarray(a), x[:, :4])
    assert_close(np.asarray(b), x[:, 4:])

    gate = np.abs(rng.randn(3, 2)).astype(np.float32)
    e1 = rng.randn(3, 5).astype(np.float32)
    e2 = rng.randn(3, 5).astype(np.float32)
    want = gate[:, :1] * e1 + gate[:, 1:] * e2
    assert_close(np.asarray(MixtureTable().forward([gate, [e1, e2]])), want,
                 atol=1e-5)
    stacked = np.stack([e1, e2], axis=1)
    assert_close(np.asarray(MixtureTable().forward([gate, stacked])), want,
                 atol=1e-5)


def test_masked_select_dense_to_sparse(rng):
    from bigdl_tpu.nn import DenseToSparse, MaskedSelect

    x = rng.randn(3, 4).astype(np.float32)
    mask = (x > 0).astype(np.float32)
    out = np.asarray(MaskedSelect().forward([x, mask]))
    assert_close(out, x[x > 0])

    sp = DenseToSparse().forward(np.array([[0.0, 2.0], [3.0, 0.0]]))
    assert sp.shape == (2, 2)
    assert_close(np.asarray(sp.to_dense()), [[0.0, 2.0], [3.0, 0.0]])


# ---------------------------------------------------------------------------
# parameterized activations
# ---------------------------------------------------------------------------

def test_srelu_piecewise():
    from bigdl_tpu.nn import SReLU
    import jax.numpy as jnp

    m = SReLU(shape=(4,))
    m._ensure_params()
    m.params = {
        "t_left": jnp.full((4,), -1.0),
        "a_left": jnp.full((4,), 0.5),
        "t_right": jnp.full((4,), 1.0),
        "a_right": jnp.full((4,), 2.0),
    }
    x = np.array([[-3.0, -1.0, 0.0, 3.0]], np.float32)
    out = np.asarray(m.forward(np.broadcast_to(x, (1, 4))))
    # below: t_l + a_l(x - t_l) = -1 + .5(-3+1) = -2 ; mid: identity;
    # above: t_r + a_r(x - t_r) = 1 + 2(3-1) = 5
    assert_close(out, [[-2.0, -1.0, 0.0, 5.0]])


def test_srelu_shared_axes_shapes():
    from bigdl_tpu.nn import SReLU

    m = SReLU(shape=(3, 5, 5), shared_axes=(2, 3))
    m._ensure_params()
    assert m.params["t_left"].shape == (3, 1, 1)
    out = m.forward(np.random.randn(2, 3, 5, 5).astype(np.float32))
    assert out.shape == (2, 3, 5, 5)


def test_maxout(rng):
    from bigdl_tpu.nn import Maxout

    m = Maxout(6, 4, 3)
    m._ensure_params()
    x = rng.randn(2, 6).astype(np.float32)
    out = np.asarray(m.forward(x))
    w = np.asarray(m.params["weight"])
    b = np.asarray(m.params["bias"])
    want = (x @ w.T + b).reshape(2, 4, 3).max(-1)
    assert_close(out, want, atol=1e-5)


# ---------------------------------------------------------------------------
# temporal pooling / up-sampling / cropping
# ---------------------------------------------------------------------------

def test_temporal_max_pooling_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import TemporalMaxPooling

    x = rng.randn(2, 10, 5).astype(np.float32)
    out = np.asarray(TemporalMaxPooling(3, 2).forward(x))
    want = torch.nn.MaxPool1d(3, 2)(
        torch.from_numpy(x).transpose(1, 2)).transpose(1, 2).numpy()
    assert_close(out, want)
    # 2-D (no batch) path
    out2 = np.asarray(TemporalMaxPooling(3, 2).forward(x[0]))
    assert_close(out2, want[0])


def test_upsampling_1d_3d_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import UpSampling1D, UpSampling3D

    x = rng.randn(2, 4, 3).astype(np.float32)  # (B, T, F)
    out = np.asarray(UpSampling1D(2).forward(x))
    want = torch.nn.Upsample(scale_factor=2, mode="nearest")(
        torch.from_numpy(x).transpose(1, 2)).transpose(1, 2).numpy()
    assert_close(out, want)

    v = rng.randn(2, 3, 2, 3, 4).astype(np.float32)  # NCDHW
    out3 = np.asarray(UpSampling3D((2, 2, 2)).forward(v))
    want3 = torch.nn.Upsample(scale_factor=2, mode="nearest")(
        torch.from_numpy(v)).numpy()
    assert_close(out3, want3)


def test_cropping(rng):
    from bigdl_tpu.nn import Cropping2D, Cropping3D

    x = rng.randn(2, 3, 8, 9).astype(np.float32)
    out = np.asarray(Cropping2D((1, 2), (3, 1)).forward(x))
    assert_close(out, x[:, :, 1:6, 3:8])

    v = rng.randn(2, 3, 6, 7, 8).astype(np.float32)
    out3 = np.asarray(Cropping3D((1, 1), (2, 0), (0, 3)).forward(v))
    assert_close(out3, v[:, :, 1:5, 2:, :5])


# ---------------------------------------------------------------------------
# convolution variants
# ---------------------------------------------------------------------------

def test_volumetric_full_convolution_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import VolumetricFullConvolution

    m = VolumetricFullConvolution(3, 4, 2, 3, 3, d_t=2, d_w=1, d_h=2,
                                  pad_t=1, pad_w=1, pad_h=0)
    m._ensure_params()
    x = rng.randn(2, 3, 4, 5, 6).astype(np.float32)
    out = np.asarray(m.forward(x))

    ref = torch.nn.ConvTranspose3d(3, 4, (2, 3, 3), stride=(2, 2, 1),
                                   padding=(1, 0, 1))
    with torch.no_grad():
        ref.weight.copy_(torch.from_numpy(np.asarray(m.params["weight"])))
        ref.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    want = ref(torch.from_numpy(x)).detach().numpy()
    assert_close(out, want, atol=1e-4)


def test_locally_connected_2d_matches_unshared_conv(rng):
    from bigdl_tpu.nn import LocallyConnected2D

    m = LocallyConnected2D(2, 6, 5, 3, kernel_w=3, kernel_h=2,
                           stride_w=1, stride_h=1)
    m._ensure_params()
    x = rng.randn(1, 2, 5, 6).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (1, 3, m.out_h, m.out_w)

    w = np.asarray(m.params["weight"])   # (P, O, C*kh*kw)
    b = np.asarray(m.params["bias"])
    want = np.zeros_like(out)
    for oy in range(m.out_h):
        for ox in range(m.out_w):
            patch = x[0, :, oy:oy + 2, ox:ox + 3].reshape(-1)
            want[0, :, oy, ox] = w[oy * m.out_w + ox] @ patch + b[:, oy, ox]
    assert_close(out, want, atol=1e-4)


def test_locally_connected_1d(rng):
    from bigdl_tpu.nn import LocallyConnected1D

    m = LocallyConnected1D(7, 4, 3, kernel_w=3, stride_w=2)
    m._ensure_params()
    x = rng.randn(2, 7, 4).astype(np.float32)
    out = np.asarray(m.forward(x))
    assert out.shape == (2, m.out_t, 3)

    w = np.asarray(m.params["weight"])
    b = np.asarray(m.params["bias"])
    for p in range(m.out_t):
        # patch channels are feature-major: (F, k) flattened
        patch = x[:, p * 2:p * 2 + 3].transpose(0, 2, 1).reshape(2, -1)
        assert_close(out[:, p], patch @ w[p].T + b[p], atol=1e-4)


def test_separable_and_share_convolution_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import (
        SpatialSeparableConvolution, SpatialShareConvolution,
    )

    m = SpatialSeparableConvolution(3, 8, 2, 3, 3, p_w=1, p_h=1)
    m._ensure_params()
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    out = np.asarray(m.forward(x))

    depth = torch.nn.Conv2d(3, 6, 3, padding=1, groups=3, bias=False)
    point = torch.nn.Conv2d(6, 8, 1)
    with torch.no_grad():
        depth.weight.copy_(torch.from_numpy(np.asarray(m.params["depth_weight"])))
        point.weight.copy_(torch.from_numpy(np.asarray(m.params["point_weight"])))
        point.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    want = point(depth(torch.from_numpy(x))).detach().numpy()
    assert_close(out, want, atol=1e-4)

    s = SpatialShareConvolution(3, 5, 3, 3, 1, 1, 1, 1)
    s._ensure_params()
    ref = torch.nn.Conv2d(3, 5, 3, padding=1)
    with torch.no_grad():
        ref.weight.copy_(torch.from_numpy(np.asarray(s.params["weight"])))
        ref.bias.copy_(torch.from_numpy(np.asarray(s.params["bias"])))
    assert_close(np.asarray(s.forward(x)),
                 ref(torch.from_numpy(x)).detach().numpy(), atol=1e-4)


# ---------------------------------------------------------------------------
# channel-wise dropout
# ---------------------------------------------------------------------------

def test_spatial_dropout_masks_whole_channels():
    import jax

    from bigdl_tpu.nn import SpatialDropout1D, SpatialDropout2D, SpatialDropout3D

    rng_key = jax.random.PRNGKey(0)
    x2 = np.ones((2, 4, 5, 5), np.float32)
    m2 = SpatialDropout2D(0.5)
    out2, _ = m2.apply({}, x2, training=True, rng=rng_key)
    out2 = np.asarray(out2)
    # each (b, c) map is uniformly 0 or 2 (inverted-dropout scaling)
    per_map = out2.reshape(2, 4, -1)
    assert all(len(np.unique(m)) == 1 for b in per_map for m in b)
    assert set(np.unique(out2)) <= {0.0, 2.0}

    x1 = np.ones((2, 6, 3), np.float32)
    out1, _ = SpatialDropout1D(0.5).apply({}, x1, training=True, rng=rng_key)
    out1 = np.asarray(out1)
    assert all(len(np.unique(out1[b, :, c])) == 1
               for b in range(2) for c in range(3))

    x3 = np.ones((1, 4, 2, 3, 3), np.float32)
    out3, _ = SpatialDropout3D(0.5).apply({}, x3, training=True, rng=rng_key)
    out3 = np.asarray(out3)
    assert all(len(np.unique(out3[0, c])) == 1 for c in range(4))

    # eval mode: identity
    assert_close(np.asarray(SpatialDropout2D(0.5).evaluate().forward(x2)), x2)


# ---------------------------------------------------------------------------
# local normalization family
# ---------------------------------------------------------------------------

def test_within_channel_lrn():
    from bigdl_tpu.nn import SpatialWithinChannelLRN

    x = np.full((1, 2, 6, 6), 2.0, np.float32)
    out = np.asarray(SpatialWithinChannelLRN(3, alpha=1.0, beta=0.75).forward(x))
    # interior: sum over 3x3 window of x^2 = 36 -> 2 / (1 + 36/9)^0.75
    want = 2.0 / (1.0 + 36.0 / 9.0) ** 0.75
    assert_close(out[0, :, 2:4, 2:4], np.full((2, 2, 2), want), atol=1e-5)


def test_subtractive_normalization_constant_image_is_zero():
    from bigdl_tpu.nn import SpatialSubtractiveNormalization

    x = np.full((1, 3, 8, 8), 5.0, np.float32)
    out = np.asarray(SpatialSubtractiveNormalization(3).forward(x))
    # coverage correction makes the local mean exactly 5 everywhere,
    # including corners — so the output is identically 0
    assert_close(out, np.zeros_like(x), atol=1e-4)


def test_divisive_normalization_scale_invariance(rng):
    from bigdl_tpu.nn import SpatialDivisiveNormalization

    x = rng.randn(1, 1, 10, 10).astype(np.float32)
    m = SpatialDivisiveNormalization(1)
    a = np.asarray(m.forward(x))
    b = np.asarray(m.forward(x * 10.0))
    # dividing by the local std cancels a global scale
    assert_close(a, b, atol=1e-3)


def test_contrastive_normalization_runs(rng):
    from bigdl_tpu.nn import SpatialContrastiveNormalization

    x = rng.randn(2, 1, 9, 9).astype(np.float32)
    out = np.asarray(SpatialContrastiveNormalization(1).forward(x))
    assert out.shape == x.shape
    assert np.all(np.isfinite(out))


# ---------------------------------------------------------------------------
# penalty layers + MultiRNNCell
# ---------------------------------------------------------------------------

def test_negative_entropy_penalty_gradient():
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn import NegativeEntropyPenalty

    m = NegativeEntropyPenalty(beta=0.1)
    p = np.array([0.2, 0.3, 0.5], np.float32)

    def f(x):
        out, _ = m.apply({}, x)
        return jnp.sum(out * 2.0)

    g = np.asarray(jax.grad(f)(p))
    want = 2.0 + 0.1 * (np.log(p) + 1.0)
    assert_close(g, want, atol=1e-5)
    # forward is identity
    assert_close(np.asarray(m.forward(p)), p)


def test_multi_rnn_cell_matches_stacked_recurrents(rng):
    from bigdl_tpu.nn import GRU, LSTM, MultiRNNCell, Recurrent

    c1, c2 = LSTM(4, 6), GRU(6, 5)
    stack = MultiRNNCell([c1, c2])
    r = Recurrent().add(stack)
    r._ensure_params()
    x = rng.randn(3, 7, 4).astype(np.float32)
    out = np.asarray(r.forward(x))
    assert out.shape == (3, 7, 5)

    # equivalent two-layer unroll with the same params
    sp = r.params[r._key()]
    r1, r2 = Recurrent().add(c1), Recurrent().add(c2)
    r1.params = {r1._key(): sp[stack._key(0, c1)]}
    r2.params = {r2._key(): sp[stack._key(1, c2)]}
    mid = r1.forward(x)
    want = np.asarray(r2.forward(mid))
    assert_close(out, want, atol=1e-5)


# ---------------------------------------------------------------------------
# criterions
# ---------------------------------------------------------------------------

def test_poisson_criterion_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import PoissonCriterion

    pred = np.abs(rng.randn(4, 3)).astype(np.float32) + 0.1
    tgt = np.abs(rng.randn(4, 3)).astype(np.float32)
    loss = PoissonCriterion().forward(pred, tgt)
    want = torch.nn.PoissonNLLLoss(log_input=False, full=False)(
        torch.from_numpy(pred), torch.from_numpy(tgt)).item()
    assert abs(loss - want) < 1e-5


def test_l1_hinge_embedding_criterion(rng):
    from bigdl_tpu.nn import L1HingeEmbeddingCriterion

    x1 = rng.randn(5).astype(np.float32)
    x2 = rng.randn(5).astype(np.float32)
    d = np.abs(x1 - x2).sum()
    c = L1HingeEmbeddingCriterion(margin=2.0)
    assert abs(c.forward([x1, x2], np.float32(1)) - d) < 1e-5
    assert abs(c.forward([x1, x2], np.float32(-1)) - max(0.0, 2.0 - d)) < 1e-5


def test_keras_regression_criterions(rng):
    from bigdl_tpu.nn import (
        CategoricalCrossEntropy, KullbackLeiblerDivergenceCriterion,
        MeanAbsolutePercentageCriterion, MeanSquaredLogarithmicCriterion,
    )

    p = np.abs(rng.randn(4, 3)).astype(np.float32) + 0.1
    t = np.abs(rng.randn(4, 3)).astype(np.float32) + 0.1

    mape = MeanAbsolutePercentageCriterion().forward(p, t)
    assert abs(mape - 100 * np.mean(np.abs(t - p) / np.abs(t))) < 1e-2

    msle = MeanSquaredLogarithmicCriterion().forward(p, t)
    assert abs(msle - np.mean((np.log(t + 1) - np.log(p + 1)) ** 2)) < 1e-5

    probs = np.float32([[0.7, 0.2, 0.1], [0.3, 0.3, 0.4]])
    tgts = np.float32([[1, 0, 0], [0, 0, 1]])
    cce = CategoricalCrossEntropy().forward(probs, tgts)
    assert abs(cce - np.mean([-np.log(0.7), -np.log(0.4)])) < 1e-4

    kl = KullbackLeiblerDivergenceCriterion().forward(probs, probs)
    assert abs(kl) < 1e-6


def test_time_distributed_mask_criterion():
    from bigdl_tpu.nn import ClassNLLCriterion, TimeDistributedMaskCriterion

    logp = np.log(np.float32([
        [[0.7, 0.3], [0.6, 0.4], [0.5, 0.5]],
        [[0.9, 0.1], [0.2, 0.8], [0.5, 0.5]],
    ]))
    # last step of each row padded (class 0 = padding)
    tgt = np.float32([[1, 2, 0], [1, 2, 0]])
    c = TimeDistributedMaskCriterion(ClassNLLCriterion(), padding_value=0)
    loss = c.forward(logp, tgt)
    want = -np.mean([np.log(0.7), np.log(0.4), np.log(0.9), np.log(0.8)])
    assert abs(loss - want) < 1e-5


def test_multi_rnn_cell_subcell_dropout_applied(rng):
    """Code-review regression: sub-cell variational dropout must fire (two
    training forwards differ) and eval mode must be deterministic."""
    import jax

    from bigdl_tpu.nn import GRU, LSTM, MultiRNNCell, Recurrent

    stack = MultiRNNCell([LSTM(3, 4, p=0.5), GRU(4, 5, p=0.5)])
    r = Recurrent().add(stack)
    r._ensure_params()
    x = rng.randn(4, 6, 3).astype(np.float32)

    out1, _ = r.apply(r.params, x, training=True, rng=jax.random.PRNGKey(1))
    out2, _ = r.apply(r.params, x, training=True, rng=jax.random.PRNGKey(2))
    assert not np.allclose(np.asarray(out1), np.asarray(out2))

    ev1, _ = r.apply(r.params, x, training=False)
    ev2, _ = r.apply(r.params, x, training=False)
    assert_close(np.asarray(ev1), np.asarray(ev2))
    assert np.asarray(ev1).shape == (4, 6, 5)


def test_divisive_normalization_thresval_replaces_low_std():
    from bigdl_tpu.nn import SpatialDivisiveNormalization

    # tiny amplitude -> local sqrt(E[x²]) ≈ 1e-6 < threshold everywhere ->
    # every position's std is REPLACED by thresval=1.5 -> out = x / 1.5
    x = np.full((1, 1, 9, 9), 1e-6, np.float32)
    out = np.asarray(
        SpatialDivisiveNormalization(1, threshold=1e-4, thresval=1.5)
        .forward(x))
    assert_close(out, x / 1.5, atol=1e-9)


def test_multi_rnn_cell_interlayer_dropout(rng):
    """Sub-cell i>0's p must mask its INPUT leg too: with p=0 on cell 0 and
    p→1 on cell 1, cell 1 sees (almost surely) only zeros from cell 0."""
    import jax

    from bigdl_tpu.nn import MultiRNNCell, Recurrent, RnnCell

    c0, c1 = RnnCell(3, 4), RnnCell(4, 4)
    c1.p = 0.9999
    stack = MultiRNNCell([c0, c1])
    r = Recurrent().add(stack)
    r._ensure_params()
    x = rng.randn(2, 5, 3).astype(np.float32)
    out, _ = r.apply(r.params, x, training=True, rng=jax.random.PRNGKey(0))
    # with the input leg ~fully masked, cell 1 behaves like zero-input rnn:
    # output = tanh(b) rolled through its recurrence, identical across batch
    # rows even though x differs
    o = np.asarray(out)
    assert_close(o[0], o[1], atol=1e-5)


def test_spatial_convolution_map_vs_dense_conv(rng):
    """full() table must equal a plain SpatialConvolution with the same
    (rearranged) kernels; one_to_one() equals per-channel depthwise conv."""
    import torch

    from bigdl_tpu.nn import SpatialConvolutionMap

    table = SpatialConvolutionMap.full(3, 4)
    m = SpatialConvolutionMap(table, 3, 3, pad_w=1, pad_h=1)
    m._ensure_params()
    x = rng.randn(2, 3, 6, 6).astype(np.float32)
    out = np.asarray(m.forward(x))

    # dense conv with kernels scattered per the table
    w = np.zeros((4, 3, 3, 3), np.float32)
    for k, (i, o) in enumerate(np.asarray(table)):
        w[o - 1, i - 1] += np.asarray(m.params["weight"])[k]
    ref = torch.nn.Conv2d(3, 4, 3, padding=1)
    with torch.no_grad():
        ref.weight.copy_(torch.from_numpy(w))
        ref.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    assert_close(out, ref(torch.from_numpy(x)).detach().numpy(), atol=1e-4)

    one = SpatialConvolutionMap(SpatialConvolutionMap.one_to_one(3), 3, 3,
                                pad_w=1, pad_h=1)
    one._ensure_params()
    out1 = np.asarray(one.forward(x))
    dw = torch.nn.Conv2d(3, 3, 3, padding=1, groups=3)
    with torch.no_grad():
        dw.weight.copy_(torch.from_numpy(
            np.asarray(one.params["weight"])[:, None]))
        dw.bias.copy_(torch.from_numpy(np.asarray(one.params["bias"])))
    assert_close(out1, dw(torch.from_numpy(x)).detach().numpy(), atol=1e-4)

    rnd = SpatialConvolutionMap.random(6, 4, fan_in=2)
    assert rnd.shape == (8, 2) and rnd[:, 0].max() <= 6


def test_lookup_table_sparse_combiners(rng):
    import jax

    from bigdl_tpu.nn import LookupTableSparse
    from bigdl_tpu.tensor.sparse import SparseTensor

    # batch of 3 rows: ids (1-based), row 2 has one id, row 3 empty
    ids = np.array([[1, 3, 0], [2, 0, 0], [0, 0, 0]], np.float32)
    sp = SparseTensor.from_dense(ids, capacity=6)

    m = LookupTableSparse(5, 4, combiner="sum")
    m._ensure_params()
    emb = np.asarray(m.params["weight"])
    out = np.asarray(m.forward(sp))
    want = np.stack([emb[0] + emb[2], emb[1], np.zeros(4)])
    assert_close(out, want, atol=1e-5)

    mean = LookupTableSparse(5, 4, combiner="mean")
    mean.params = {"weight": m.params["weight"]}
    outm = np.asarray(mean.forward(sp))
    assert_close(outm[0], (emb[0] + emb[2]) / 2, atol=1e-5)
    assert_close(outm[1], emb[1], atol=1e-5)

    # weighted sqrtn: weights 2 and 1 on row 0
    w_dense = np.array([[2.0, 1.0, 0], [1.0, 0, 0], [0, 0, 0]], np.float32)
    wsp = SparseTensor.from_dense(w_dense, capacity=6)
    sq = LookupTableSparse(5, 4, combiner="sqrtn")
    sq.params = {"weight": m.params["weight"]}
    outs = np.asarray(sq.forward([sp, wsp]))
    assert_close(outs[0], (2 * emb[0] + emb[2]) / np.sqrt(5.0), atol=1e-5)
