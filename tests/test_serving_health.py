"""Pool-level fault tolerance for the disaggregated plane
(bigdl_tpu/serving/health.py + the failover/drain/autoscaler machinery
in serving/disagg.py): health classification from heartbeats and
transfer failures, pool-death chaos (byte-identical streams through a
mid-stream decode-pool kill at 3 fault seeds, in-process and
block_store-backed wire, plus a real 2-process death), graceful drain
migration, occupancy-autoscaler hysteresis, exponential transfer
backoff + send-timeout dedup, the cancel sweep of in-flight handoffs,
and closed finish-reason accounting through all of it."""

import numpy as np
import pytest

pytestmark = pytest.mark.disagg


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


def _trace(V=29, n=8, seed=3):
    rng = np.random.RandomState(seed)
    lens = rng.randint(1, 13, size=(n,))
    return [rng.randint(1, V + 1, size=(int(k),)).tolist() for k in lens]


def _samplings(n=8, seed=0):
    """Mixed greedy + seeded-sampled knobs (the chaos contract covers
    both stream kinds)."""
    from bigdl_tpu.serving import SamplingParams

    mixes = [None,
             SamplingParams(temperature=0.8, top_k=8, seed=11 + seed),
             None,
             SamplingParams(temperature=1.1, top_p=0.9),   # engine lane
             SamplingParams(temperature=0.7, repetition_penalty=1.3,
                            seed=5 + seed),
             None,
             SamplingParams(temperature=0.9, min_tokens=3, seed=7),
             None]
    return (mixes * ((n // len(mixes)) + 1))[:n]


def _mono_outputs(lm, dtype, prompts, sps, gen=8, slots=8):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=slots, compute_dtype=dtype)
    for p, sp in zip(prompts, sps):
        eng.submit(p, max_new_tokens=gen, sampling=sp)
    return eng.drain()


def _assert_same(want, got):
    assert set(want) == set(got)
    for rid in want:
        assert np.array_equal(want[rid], got[rid]), (
            f"request {rid}: {want[rid]} != {got[rid]}")


# -- health model units -----------------------------------------------------

def test_pool_health_classification():
    """Heartbeat silence and consecutive transfer failures walk a pool
    HEALTHY -> SUSPECT -> DEAD on the shared VirtualClock; a delivered
    send resets the failure run; force_dead is permanent."""
    from bigdl_tpu.serving import HealthConfig, PoolHealth, VirtualClock
    from bigdl_tpu.serving.health import DEAD, HEALTHY, SUSPECT

    clk = VirtualClock()
    h = PoolHealth(clk, HealthConfig(suspect_after_s=1.0, dead_after_s=3.0,
                                     suspect_after_failures=2,
                                     dead_after_failures=4))
    assert h.state() == HEALTHY
    clk.advance(1.5)
    assert h.state() == SUSPECT          # silent past suspect_after_s
    h.beat()
    assert h.state() == HEALTHY
    clk.advance(3.5)
    assert h.state() == DEAD             # silent past dead_after_s
    h.beat()
    assert h.state() == HEALTHY

    h.on_transfer_failure()
    assert h.state() == HEALTHY
    h.on_transfer_failure()
    assert h.state() == SUSPECT          # 2 consecutive failures
    h.on_transfer_ok()
    assert h.state() == HEALTHY          # a delivery resets the run
    for _ in range(4):
        h.on_transfer_failure()
    assert h.state() == DEAD

    h2 = PoolHealth(clk)
    h2.force_dead()
    h2.beat()
    assert h2.state() == DEAD            # beats never resurrect
    with pytest.raises(ValueError):
        h2.reset()


def test_health_and_retry_config_validation():
    from bigdl_tpu.serving import (
        AutoscalerConfig, HealthConfig, TransferRetryConfig,
    )

    with pytest.raises(ValueError):
        HealthConfig(suspect_after_s=5.0, dead_after_s=1.0)
    with pytest.raises(ValueError):
        HealthConfig(suspect_after_failures=0)
    with pytest.raises(ValueError):
        TransferRetryConfig(send_timeout_s=0.0)
    with pytest.raises(ValueError):
        AutoscalerConfig(low_water=0.9, high_water=0.5)
    with pytest.raises(ValueError):
        AutoscalerConfig(sustain=0)
    with pytest.raises(ValueError):
        AutoscalerConfig(min_pools=0)
    r = TransferRetryConfig(backoff_base_s=0.5, backoff_cap_s=3.0)
    assert [r.delay(n) for n in (1, 2, 3, 4)] == [0.5, 1.0, 2.0, 3.0]


def test_autoscaler_hysteresis_never_flaps():
    """The control loop demands SUSTAINED evidence, ignores the dead
    band, and refuses any action inside the cooldown window — a
    boundary-riding occupancy series triggers nothing."""
    from bigdl_tpu.serving import AutoscalerConfig, OccupancyAutoscaler

    cfg = AutoscalerConfig(high_water=0.8, low_water=0.3, sustain=3,
                           cooldown=5)
    sc = OccupancyAutoscaler(cfg)
    # two hot samples are not three: no action
    assert sc.observe(0.9, 0, True, True) is None
    assert sc.observe(0.9, 0, True, True) is None
    # a dead-band sample resets the run entirely
    assert sc.observe(0.5, 0, True, True) is None
    assert sc.observe(0.9, 0, True, True) is None
    assert sc.observe(0.9, 0, True, True) is None
    assert sc.observe(0.9, 0, True, True) == "up"
    # cooldown: even a fully-sustained cold run cannot reverse at once
    for _ in range(5):
        assert sc.observe(0.0, 0, True, True) is None
    assert sc.observe(0.0, 0, True, True) == "down"
    # backlogged lull is NOT cold: admission is catching up
    sc2 = OccupancyAutoscaler(cfg)
    for _ in range(10):
        assert sc2.observe(0.1, backlog=4, can_up=True,
                           can_down=True) is None
    # oscillation across the band, never sustained: flap-free forever
    sc3 = OccupancyAutoscaler(cfg)
    for i in range(40):
        assert sc3.observe(0.9 if i % 2 else 0.1, 0, True, True) is None


# -- pool-death chaos -------------------------------------------------------

@pytest.mark.parametrize("fault_seed,variant", [
    (0, "fp32"), (1, "fp32"), (2, "fp32"), (0, "bf16"), (2, "bf16")])
def test_pool_death_chaos_byte_identical(fault_seed, variant):
    """THE chaos contract: kill a decode pool mid-stream (the seed
    picks the victim, the kill step, and the sampling lanes) and every
    affected row's stream stays BYTE-IDENTICAL to the monolithic
    engine — greedy and fixed-seed sampled alike — with zero extra
    compiles on the surviving pool and the finish_* union still
    summing to every submitted request's fate."""
    import jax.numpy as jnp

    from tests.compile_guards import compile_count

    from bigdl_tpu.serving import DisaggregatedEngine

    lm = _make_lm()
    dtype = None if variant == "fp32" else jnp.bfloat16
    prompts = _trace(seed=3 + fault_seed)
    sps = _samplings(seed=fault_seed)
    # mono at the DECODE pools' slot geometry, so the one shared decode
    # program covers both engines and the compile guard is exact
    mono = _mono_outputs(lm, dtype, prompts, sps, slots=4)

    d = DisaggregatedEngine(lm, prefill_slots=8, decode_slots=4,
                            decode_pools=2, compute_dtype=dtype)
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=8, sampling=sp)
    for _ in range(1 + fault_seed):
        d.step()
    victim = fault_seed % 2
    survivor = d.decoders[1 - victim]
    programs_before = compile_count(survivor.engine._step_fn)
    assert programs_before == 1          # the one-program discipline
    d.kill_pool(victim)
    outs = d.drain()
    _assert_same(mono, outs)

    # the failover compiled NOTHING on the survivor
    assert compile_count(survivor.engine._step_fn) == programs_before
    s = d.summary()
    assert s["serving/pool_deaths"] == 1.0
    assert s["serving/failovers"] == 1.0
    assert s.get("serving/migrated_rows", 0.0) \
        + s.get("serving/replayed_rows", 0.0) >= 1.0
    # every submitted request landed in exactly one disposition bucket
    n_dispo = sum(v for k, v in s.items()
                  if k.startswith("serving/finish_"))
    assert n_dispo == len(prompts)
    assert d.pool_states()[victim] == "dead"


@pytest.mark.parametrize("fault_seed", [0, 1, 2])
def test_pool_death_blockstore_wire_reroute(fault_seed, tmp_path):
    """Block-store-backed channels (the cross-process wire format):
    kill a pool while handoffs sit UNCONSUMED in its store channel —
    failover re-routes the packed bytes to the survivor and streams
    stay identical (stratum 1 of the failover contract)."""
    from bigdl_tpu.parallel.block_store import FsBlockStore
    from bigdl_tpu.serving import BlockStoreTransfer, DisaggregatedEngine

    lm = _make_lm()
    prompts = _trace(n=6, seed=11 + fault_seed)
    sps = _samplings(6, seed=fault_seed)
    mono = _mono_outputs(lm, None, prompts, sps, gen=6)

    store = FsBlockStore(str(tmp_path / "bs"))
    d = DisaggregatedEngine(
        lm, prefill_slots=6, decode_slots=6, decode_pools=2,
        transfer_factory=lambda i: BlockStoreTransfer(store, f"d{i}"))
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=6, sampling=sp)
    # route everything WITHOUT stepping the decode pools: every row is
    # a wire payload in some pool's channel
    for req, payload in d.prefill.pump():
        d._handoff(req, payload)
    victim = fault_seed % 2
    assert d.decoders[victim].transfer.pending() > 0 or \
        d.decoders[1 - victim].transfer.pending() > 0
    d.kill_pool(victim)
    outs = d.drain()
    _assert_same(mono, outs)
    s = d.summary()
    assert s["serving/pool_deaths"] == 1.0


def test_heartbeat_detection_on_virtual_clock():
    """A pool that silently stops stepping (no out-of-band death
    signal) is discovered through missed heartbeats on the shared
    VirtualClock: SUSPECT after suspect_after_s, failover once past
    dead_after_s — no sleeps anywhere."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, HealthConfig, VirtualClock,
    )

    lm = _make_lm()
    prompts, sps = _trace(n=6), _samplings(6)
    mono = _mono_outputs(lm, None, prompts, sps)

    clk = VirtualClock()
    d = DisaggregatedEngine(
        lm, prefill_slots=6, decode_slots=6, decode_pools=2, clock=clk,
        health=HealthConfig(suspect_after_s=1.0, dead_after_s=3.0))
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=8, sampling=sp)
    for _ in range(2):
        d.step()
    d.kill_pool(1, immediate=False)       # crash nobody reported
    assert d.pool_health(1) == "healthy"  # not yet silent long enough
    clk.advance(1.5)
    d.step()
    assert d.pool_health(1) == "suspect"  # routing already avoids it
    assert d.pool_states()[1] == "active"
    clk.advance(2.0)
    d.step()                              # classification trips DEAD
    assert d.pool_states()[1] == "dead"
    outs = d.drain()
    _assert_same(mono, outs)
    assert d.summary()["serving/pool_deaths"] == 1.0


def test_transfer_failures_mark_pool_suspect_and_route_around():
    """Consecutive send failures to one pool mark it SUSPECT; the
    router stops handing it new rows (healthy pools first) and the
    trace still completes identically."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, HealthConfig, InProcessTransfer,
    )

    class DeafTransfer(InProcessTransfer):
        def __init__(self):
            super().__init__()
            self.attempts = 0

        def send(self, blob):
            self.attempts += 1
            raise OSError("link down")

    lm = _make_lm()
    prompts, sps = _trace(n=6), _samplings(6)
    mono = _mono_outputs(lm, None, prompts, sps)

    deaf = DeafTransfer()
    d = DisaggregatedEngine(
        lm, prefill_slots=6, decode_slots=6, decode_pools=2,
        health=HealthConfig(suspect_after_failures=2,
                            dead_after_failures=50),
        transfer_factory=lambda i: deaf if i == 0
        else InProcessTransfer())
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=8, sampling=sp)
    outs = d.drain()
    _assert_same(mono, outs)
    assert deaf.attempts >= 2
    assert d.pool_health(0) == "suspect"
    # once suspect, the healthy pool got every subsequent handoff
    assert d.decoders[1].engine.metrics.metrics.get(
        "serving/finished")[0] == len(prompts)


# -- graceful drain + autoscaler -------------------------------------------

def test_drain_pool_migrates_mid_stream_loss_free():
    """drain_pool on a LIVE pool mid-stream: rows migrate through the
    row_state wire handoff and resume byte-identically on the
    survivor; the retired pool ends empty and STANDBY; reactivation
    compiles nothing."""
    from tests.compile_guards import compile_count

    from bigdl_tpu.serving import DisaggregatedEngine

    lm = _make_lm()
    prompts, sps = _trace(), _samplings()
    mono = _mono_outputs(lm, None, prompts, sps)

    d = DisaggregatedEngine(lm, prefill_slots=8, decode_slots=4,
                            decode_pools=2)
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=8, sampling=sp)
    for _ in range(3):
        d.step()
    before = compile_count(d.decoders[0].engine._step_fn)
    n = d.drain_pool(0)
    assert n >= 1
    assert d.pool_states() == ["standby", "active"]
    assert d.decoders[0].engine.scheduler.idle()
    outs = d.drain()
    _assert_same(mono, outs)
    s = d.summary()
    assert s["serving/migrated_rows"] >= n
    assert s.get("serving/pool_deaths", 0.0) == 0.0   # graceful != death
    # reactivate and serve again: still zero new compiles (engine-
    # derived lanes fold in the request id, so only greedy and
    # explicit-seed rows replay across waves)
    d._activate_pool(0)
    rids2 = [d.submit(p, max_new_tokens=8, sampling=sp)
             for p, sp in zip(prompts, sps)]
    outs2 = d.drain()
    for rid_m, rid_d, sp in zip(sorted(mono), rids2, sps):
        if sp is None or sp.seed is not None:
            assert np.array_equal(mono[rid_m], outs2[rid_d])
    assert compile_count(d.decoders[0].engine._step_fn) == before


def test_drain_pool_validation():
    from bigdl_tpu.serving import DisaggregatedEngine

    lm = _make_lm()
    d = DisaggregatedEngine(lm, prefill_slots=2, decode_slots=2,
                            decode_pools=1, standby_pools=1)
    with pytest.raises(ValueError):
        d.drain_pool(0)               # last active pool
    with pytest.raises(ValueError):
        d.drain_pool(1)               # standby, not active
    with pytest.raises(ValueError):
        d.drain_pool(7)               # no such pool
    d.kill_pool(1)
    with pytest.raises(ValueError):
        d.kill_pool(1)                # already dead
    with pytest.raises(ValueError):
        DisaggregatedEngine(lm, decode_pools=1, standby_pools=-1)


def test_autoscaler_cycle_up_then_down():
    """End-to-end: sustained pressure activates the standby pool,
    the post-burst cold drains one back — and the streams match the
    monolithic engine throughout."""
    from bigdl_tpu.serving import AutoscalerConfig, DisaggregatedEngine

    lm = _make_lm()
    prompts, sps = _trace(), _samplings()
    mono = _mono_outputs(lm, None, prompts, sps, gen=12)

    d = DisaggregatedEngine(
        lm, prefill_slots=8, decode_slots=2, decode_pools=1,
        standby_pools=1,
        autoscaler=AutoscalerConfig(high_water=0.9, low_water=0.3,
                                    sustain=2, cooldown=3))
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=12, sampling=sp)
    outs = d.drain()
    _assert_same(mono, outs)
    s = d.summary()
    assert s["serving/autoscale_up"] == 1.0    # burst lit the standby
    for _ in range(12):                        # idle: cold → drain one
        d.step()
    s = d.summary()
    assert s["serving/autoscale_down"] == 1.0
    assert d.pool_states().count("active") == 1
    # hysteresis held: one action per direction, no flapping
    assert s["serving/autoscale_up"] + s["serving/autoscale_down"] == 2.0


# -- transfer hardening -----------------------------------------------------

def test_transfer_backoff_is_exponential_on_virtual_clock():
    """Failed sends retry with exponentially-spaced attempts on the
    engine clock — a down fabric is probed at a decaying rate, not
    hammered every pump."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, InProcessTransfer, TransferRetryConfig,
        VirtualClock, WatchdogConfig,
    )

    class Flaky(InProcessTransfer):
        def __init__(self, clk, fail_first):
            super().__init__()
            self.clk = clk
            self.fails_left = fail_first
            self.attempt_times = []

        def send(self, blob):
            self.attempt_times.append(self.clk())
            if self.fails_left > 0:
                self.fails_left -= 1
                raise OSError("fabric hiccup")
            super().send(blob)

    lm = _make_lm()
    clk = VirtualClock()
    tx = Flaky(clk, fail_first=3)
    d = DisaggregatedEngine(
        lm, prefill_slots=2, decode_slots=2, decode_pools=1, clock=clk,
        watchdog=WatchdogConfig(max_retries=5),
        transfer_retry=TransferRetryConfig(backoff_base_s=1.0,
                                           backoff_cap_s=8.0),
        transfer_factory=lambda i: tx)
    d.submit(_trace(n=1)[0], max_new_tokens=4)
    limit = 400
    while not d.idle() and limit:
        d.step()
        clk.advance(0.25)
        limit -= 1
    assert d.idle()
    t = tx.attempt_times
    assert len(t) == 4                   # 3 failures + the delivery
    gaps = [t[i + 1] - t[i] for i in range(len(t) - 1)]
    # attempt n defers by base * 2^(n-1): 1s, 2s, 4s (quantized by the
    # 0.25s step cadence, so compare with a half-step tolerance)
    for gap, want in zip(gaps, (1.0, 2.0, 4.0)):
        assert want - 1e-9 <= gap <= want + 0.5, (gaps,)
    req = d.request(0)
    assert req.finish_reason == "length" and len(req.output) == 4


def test_transfer_stall_fault_mode_is_bounded():
    """The injector's transfer_stall mode (hung fabric: the clock
    advances, nothing is delivered, the abandoned send raises) becomes
    a bounded retry instead of a wedge — streams stay identical."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, FaultInjector, TransferRetryConfig,
        VirtualClock, WatchdogConfig,
    )

    lm = _make_lm()
    prompts, sps = _trace(n=6), _samplings(6)
    mono = _mono_outputs(lm, None, prompts, sps)

    clk = VirtualClock()
    inj = FaultInjector(seed=2, p_transfer_stall=0.3, stall_s=1.0,
                        clock=clk, max_faults=4)
    d = DisaggregatedEngine(
        lm, prefill_slots=6, decode_slots=6, decode_pools=2, clock=clk,
        faults=inj, watchdog=WatchdogConfig(max_retries=10),
        transfer_retry=TransferRetryConfig(send_timeout_s=0.5,
                                           backoff_base_s=0.1))
    for p, sp in zip(prompts, sps):
        d.submit(p, max_new_tokens=8, sampling=sp)
    limit = 1000
    while not d.idle() and limit:
        d.step()
        clk.advance(0.05)
        limit -= 1
    assert d.idle()
    outs = {}
    for eng in d._engines():
        for rid, req in eng._finished.items():
            if req.state == "finished":
                outs[rid] = np.asarray(req.output, np.int32)
    _assert_same(mono, outs)
    assert inj.counts["transfer_stall"] >= 1     # faults actually fired
    assert d.prefill.engine.metrics.metrics.get("serving/retries")[0] \
        >= inj.counts["transfer_stall"]


@pytest.mark.parametrize("pools", [1, 2])
def test_send_timeout_resends_and_receiver_dedups(pools):
    """A send that RETURNS past send_timeout_s is treated as
    failed-unconfirmed and resent; since the slow original did land,
    the duplicate must be dropped by request id — including the
    CROSS-POOL case (the resend routes least-loaded, so with 2 pools
    the copy lands on a different pool than the original; the shared
    claims registry catches it). The row is served exactly once."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, InProcessTransfer, TransferRetryConfig,
        VirtualClock,
    )

    class SlowTransfer(InProcessTransfer):
        def __init__(self, clk, slow_first):
            super().__init__()
            self.clk = clk
            self.slow_left = slow_first

        def send(self, blob):
            if self.slow_left > 0:
                self.slow_left -= 1
                self.clk.advance(1.0)        # returns late — but lands
            super().send(blob)

    lm = _make_lm()
    prompts = _trace(n=3, seed=5)
    mono = _mono_outputs(lm, None, prompts, [None] * 3, gen=6)

    clk = VirtualClock()
    tx = SlowTransfer(clk, slow_first=1)     # pool 0 is the slow one
    d = DisaggregatedEngine(
        lm, prefill_slots=3, decode_slots=3, decode_pools=pools,
        clock=clk,
        transfer_retry=TransferRetryConfig(send_timeout_s=0.5,
                                           backoff_base_s=0.1),
        transfer_factory=lambda i: tx if i == 0
        else InProcessTransfer())
    for p in prompts:
        d.submit(p, max_new_tokens=6)
    limit = 400
    while not d.idle() and limit:
        d.step()
        clk.advance(0.05)
        limit -= 1
    assert d.idle()
    outs = {}
    owners = {}
    for eng in d._engines():
        for rid, req in eng._finished.items():
            if req.state == "finished":
                assert rid not in owners, (
                    f"request {rid} finished in TWO pools — the "
                    "timed-out resend was admitted twice")
                owners[rid] = eng
                outs[rid] = np.asarray(req.output, np.int32)
    _assert_same(mono, outs)
    s = d.summary()
    assert s["serving/transfer_timeouts"] >= 1.0
    # exactly one ledger entry per request — the duplicate was dropped
    assert s["serving/finish_length"] == len(prompts)


def test_idle_lull_does_not_kill_healthy_pools():
    """Heartbeat silence is measured against the front end's OWN
    stepping cadence: a long traffic lull between bursts (nobody
    calls step, the clock runs on) must not classify healthy pools
    DEAD at the next step — only a pool that misses beats while the
    plane is being DRIVEN dies."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, HealthConfig, VirtualClock,
    )

    lm = _make_lm()
    prompts = _trace(n=4, seed=17)
    mono = _mono_outputs(lm, None, prompts, [None] * 4, gen=6)

    clk = VirtualClock()
    d = DisaggregatedEngine(
        lm, prefill_slots=4, decode_slots=4, decode_pools=2, clock=clk,
        health=HealthConfig(suspect_after_s=1.0, dead_after_s=3.0))
    rids1 = [d.submit(p, max_new_tokens=6) for p in prompts]
    outs1 = d.drain()
    clk.advance(60.0)                        # a long idle lull
    rids2 = [d.submit(p, max_new_tokens=6) for p in prompts]
    outs2 = d.drain()
    assert d.pool_states() == ["active", "active"]
    assert d.summary().get("serving/pool_deaths", 0.0) == 0.0
    for rid_m, r1, r2 in zip(sorted(mono), rids1, rids2):
        assert np.array_equal(mono[rid_m], outs1[r1])
        assert np.array_equal(mono[rid_m], outs2[r2])


def test_cancel_reaches_backoff_parking_lot():
    """A request whose handoff failed and is waiting out its backoff
    window lives in NO scheduler and has no stash entry — cancel()
    must still find it (PrefillWorker.cancel_deferred), ledger it
    cancelled, and the resend must never happen."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, InProcessTransfer, TransferRetryConfig,
        VirtualClock,
    )

    class FailOnce(InProcessTransfer):
        def __init__(self):
            super().__init__()
            self.fails_left = 1

        def send(self, blob):
            if self.fails_left > 0:
                self.fails_left -= 1
                raise OSError("fabric hiccup")
            super().send(blob)

    lm = _make_lm()
    clk = VirtualClock()
    d = DisaggregatedEngine(
        lm, prefill_slots=2, decode_slots=2, decode_pools=1, clock=clk,
        transfer_retry=TransferRetryConfig(backoff_base_s=5.0),
        transfer_factory=lambda i: FailOnce())
    rid = d.submit(_trace(n=1, seed=19)[0], max_new_tokens=6)
    d.step()                                 # send fails -> deferred
    assert not d.prefill.idle()              # parked, not lost
    assert d.cancel(rid) is True
    clk.advance(10.0)                        # past the backoff window
    for _ in range(4):
        d.step()
    assert d.idle()
    req = d.request(rid)
    assert req is not None and req.state == "cancelled"
    assert req.output == [] and req.resume_carry is None
    s = d.summary()
    assert s["serving/finish_cancelled"] == 1.0
    assert s.get("serving/finish_length", 0.0) == 0.0   # never served


def test_cancel_sweeps_inflight_handoff():
    """A request cancelled while its payload sits packed in a transfer
    channel is SWEPT: the decode pool never restores it, the
    cancellation is ledgered at the front end, and the finish_* union
    still sums to every submitted fate."""
    from bigdl_tpu.serving import DisaggregatedEngine

    lm = _make_lm()
    prompts = _trace(n=4, seed=7)
    d = DisaggregatedEngine(lm, prefill_slots=4, decode_slots=4,
                            decode_pools=1)
    rids = [d.submit(p, max_new_tokens=6) for p in prompts]
    # pump + route by hand so the payloads sit on the wire un-ingested
    for req, payload in d.prefill.pump():
        d._handoff(req, payload)
    assert d.decoders[0].transfer.pending() == len(prompts)
    assert d.cancel(rids[1]) is True
    assert d.cancel(rids[1]) is False          # already ledgered
    outs = d.drain()
    assert rids[1] not in outs
    req = d.request(rids[1])
    assert req is not None and req.state == "cancelled"
    assert req.resume_carry is None            # no pinned KV slices
    s = d.summary()
    assert s["serving/finish_cancelled"] == 1.0
    n_dispo = sum(v for k, v in s.items()
                  if k.startswith("serving/finish_"))
    assert n_dispo == len(prompts)
    # the served rows match the monolithic streams
    mono = _mono_outputs(lm, None, prompts, [None] * 4, gen=6, slots=4)
    for i, rid in enumerate(rids):
        if rid != rids[1]:
            assert np.array_equal(outs[rid], mono[rid])


# -- accounting + latency observability ------------------------------------

def test_failover_latency_percentiles_reported():
    from bigdl_tpu.serving import DisaggregatedEngine

    lm = _make_lm()
    d = DisaggregatedEngine(lm, prefill_slots=4, decode_slots=2,
                            decode_pools=3)
    for p in _trace(n=6):
        d.submit(p, max_new_tokens=6)
    for _ in range(2):
        d.step()
    d.kill_pool(0)
    d.step()
    d.kill_pool(2)
    d.drain()
    s = d.summary()
    assert s["serving/pool_deaths"] == 2.0
    assert s["serving/failovers"] == 2.0
    assert s["serving/failover_p50_s"] >= 0.0
    assert s["serving/failover_p99_s"] >= s["serving/failover_p50_s"]
    assert d.metrics.failover_percentiles()["p90"] >= 0.0


def test_last_pool_death_with_standby_activates_it():
    """Killing the only active pool auto-activates a standby during
    failover; with no standby it raises (total outage is loud)."""
    from bigdl_tpu.serving import DisaggregatedEngine

    lm = _make_lm()
    prompts = _trace(n=4, seed=13)
    mono = _mono_outputs(lm, None, prompts, [None] * 4, gen=6)
    d = DisaggregatedEngine(lm, prefill_slots=4, decode_slots=4,
                            decode_pools=1, standby_pools=1)
    for p in prompts:
        d.submit(p, max_new_tokens=6)
    for _ in range(2):
        d.step()
    d.kill_pool(0)
    outs = d.drain()
    _assert_same(mono, outs)
    assert d.pool_states() == ["dead", "active"]

    d2 = DisaggregatedEngine(lm, prefill_slots=4, decode_slots=4,
                             decode_pools=1)
    d2.submit(prompts[0], max_new_tokens=6)
    for _ in range(2):
        d2.step()
    d2.kill_pool(0)
    with pytest.raises(RuntimeError):
        d2.drain()


# -- the real 2-process death ----------------------------------------------

_CHILD = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from bigdl_tpu.models.transformer import TransformerLM
from bigdl_tpu.utils.random_gen import RNG
from bigdl_tpu.parallel.block_store import FsBlockStore, encode_array
from bigdl_tpu.serving import BlockStoreTransfer, DecodeWorker

RNG.set_seed(9)
lm = TransformerLM(29, hidden_size=32, n_heads=4, n_layers=2, max_len=48)
lm._ensure_params(); lm.evaluate()
store = FsBlockStore({root!r})
w = DecodeWorker(lm, n_slots=4,
                 transfer=BlockStoreTransfer(store, "handoff"))
want = {n}
published = set()
deadline = time.time() + 300
while len(published) < want and time.time() < deadline:
    if not w.step():
        time.sleep(0.01)
    for rid, req in list(w.engine._finished.items()):
        if rid not in published and req.state == "finished":
            store.put(f"result_{{rid}}",
                      encode_array(np.asarray(req.output, np.int32)))
            published.add(rid)
sys.exit(0 if len(published) == want else 1)
"""


@pytest.mark.slow
def test_two_process_pool_death_reroutes_wire(tmp_path):
    """A REAL process death at 3 fault seeds: a child process serves
    wave A over an FsBlockStore channel and exits; wave B's handoffs
    land on the wire after it is gone (the crashed-pool shape — sent,
    never consumed). The parent fails the channel over to its local
    pool and every stream, both waves, matches the monolithic
    engine."""
    import pathlib
    import subprocess
    import sys

    from bigdl_tpu.parallel.block_store import FsBlockStore, decode_array
    from bigdl_tpu.serving import (
        BlockStoreTransfer, DecodeWorker, PrefillWorker, ServingEngine,
    )

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    lm = _make_lm()

    for fault_seed in range(3):
        prompts = _trace(n=6, seed=21 + fault_seed)
        sps = _samplings(6, seed=fault_seed)
        mono = ServingEngine(lm, n_slots=6)
        rids = [mono.submit(p, max_new_tokens=6, sampling=sp)
                for p, sp in zip(prompts, sps)]
        mono_out = mono.drain()
        n_a = 3                               # wave A: the child serves
        root = str(tmp_path / f"store{fault_seed}")
        store = FsBlockStore(root)
        child = subprocess.Popen(
            [sys.executable, "-c",
             _CHILD.format(repo=repo, root=root, n=n_a)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        try:
            pw = PrefillWorker(lm, n_slots=6)
            remote = BlockStoreTransfer(store, "handoff")
            local = DecodeWorker(lm, n_slots=6)
            for p, sp in zip(prompts[:n_a], sps[:n_a]):
                pw.submit(p, max_new_tokens=6, sampling=sp)
            while not pw.idle():
                for req, payload in pw.pump():
                    pw.send_handoff(remote, req, payload,
                                    pw.engine.metrics)
            wave_a = {rid: decode_array(
                store.get_blocking(f"result_{rid}", timeout_s=300))
                for rid in rids[:n_a]}
            child.wait(timeout=300)           # served A, exited
            assert child.returncode == 0, \
                child.stderr.read().decode()[-2000:]
            # wave B: handoffs to a DEAD pool — they sit on the wire
            for i, (p, sp) in enumerate(zip(prompts[n_a:], sps[n_a:])):
                pw.submit(p, max_new_tokens=6, sampling=sp)
            while not pw.idle():
                for req, payload in pw.pump():
                    pw.send_handoff(remote, req, payload,
                                    pw.engine.metrics)
            assert remote.pending() > 0
            # failover stratum 1: re-route the packed bytes untouched.
            # The wave-A results ARE the delivery acks — the receive
            # cursor resumes after the last acknowledged handoff
            # (their keys were consumed and deleted by the child)
            remote._received = n_a
            while True:
                blob = remote.recv()
                if blob is None:
                    break
                local.transfer.send(blob)
            while not local.idle():
                local.step()
            for j, rid in enumerate(rids[:n_a]):
                assert np.array_equal(wave_a[rid], mono_out[rid]), (
                    f"seed {fault_seed} wave-A request {rid} diverged")
            for rid in rids[n_a:]:
                got = np.asarray(local.engine._finished[rid].output,
                                 np.int32)
                assert np.array_equal(got, mono_out[rid]), (
                    f"seed {fault_seed} wave-B request {rid} diverged "
                    "across the process-death failover")
        finally:
            if child.poll() is None:
                child.kill()
                child.wait()


# -- bench smoke ------------------------------------------------------------

def test_serving_bench_failover_smoke():
    """The failover scenario's contracts hold at smoke scale (parity +
    survivor-compile + flap-free autoscaler are asserted inside
    run_failover)."""
    import importlib

    bench = importlib.import_module("benchmarks.serving_bench")
    out = bench.run_failover("tiny", "fp32", n_requests=6, gen_tokens=6,
                             n_slots=4, decode_pools=2, seeds=(0, 1))
    assert out["outputs_match"] is True
    assert out["pool_deaths"] == 2
    assert out["failover_ms"]["p99"] >= out["failover_ms"]["p50"] >= 0
    assert out["autoscaler"]["flap_free"] is True
