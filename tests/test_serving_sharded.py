"""Sharded serving plane (serving/sharded.py) on the 8-virtual-device
CPU mesh: token-identical parity of sharded vs unsharded engines
(slot-data-parallel, tensor-parallel, and combined meshes; fp32 + bf16;
mixed greedy/sampled traffic with evict/readmit), the one-compiled-
program-per-engine guard, seed reproducibility across mesh shapes,
balanced cross-shard allocation, and the new shard metrics."""

import numpy as np
import pytest

pytestmark = pytest.mark.mesh


def _build_lm(vocab=96, hidden=32, heads=4, layers=2, max_len=64, seed=17):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(vocab, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len, output="logits")
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    return _build_lm()


def _trace(n=10, vocab=96, seed=3):
    """Mixed greedy/sampled requests over a few prompt lengths; more
    requests than any test engine has slots, so later requests are
    admitted into evicted rows (the readmission path)."""
    from bigdl_tpu.serving import SamplingParams

    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = [3, 7, 12][i % 3]
        prompt = rng.randint(1, vocab + 1, size=(plen,)).tolist()
        sp = (SamplingParams(temperature=0.8, top_k=20, seed=100 + i)
              if i % 2 else None)
        out.append((prompt, 8, sp))
    return out


def _run(lm, trace, **kw):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, **kw)
    rids = [eng.submit(p, max_new_tokens=n, sampling=sp)
            for p, n, sp in trace]
    outs = eng.drain()
    return eng, rids, outs


def _assert_identical(eng_a, rids_a, outs_a, eng_b, rids_b, outs_b):
    for ra, rb in zip(rids_a, rids_b):
        np.testing.assert_array_equal(outs_a[ra], outs_b[rb])
        np.testing.assert_allclose(eng_a.logprobs(ra), eng_b.logprobs(rb),
                                   atol=2e-5)


@pytest.fixture(scope="module")
def baseline(lm):
    """Unsharded engine outputs for the standard trace — the oracle
    every mesh shape must reproduce token for token."""
    return _run(lm, _trace(), n_slots=4)


@pytest.mark.parametrize("parallelism", [{"data": 4},
                                         {"data": 2, "model": 2}])
def test_sharded_engine_token_identical(lm, baseline, parallelism):
    """Sharded engines (slot-DP and combined DP x TP meshes) serve the
    mixed greedy/sampled evict/readmit trace token-identically."""
    e0, r0, o0 = baseline
    e1, r1, o1 = _run(lm, _trace(), n_slots=4, parallelism=parallelism)
    _assert_identical(e0, r0, o0, e1, r1, o1)
    # sampled logprobs ride along: slot-DP is bitwise, TP to round-off
    assert e1.pool.n_shards == parallelism.get("data", 1)


def test_tensor_parallel_token_identical(lm, baseline):
    """Pure tensor parallelism (4-way heads/MLP sharding, two psums per
    block) reproduces the unsharded token stream."""
    e0, r0, o0 = baseline
    e1, r1, o1 = _run(lm, _trace(), n_slots=4, parallelism={"model": 4})
    _assert_identical(e0, r0, o0, e1, r1, o1)


def test_sharded_bf16_with_prefix_cache_token_identical(lm):
    """bf16 serving dtype + prefix cache on a 4-way slot-DP mesh vs the
    unsharded bf16 engine: identical tokens (shared-prefix clones land
    on the owning shard through the mesh-pinned scatter)."""
    import jax.numpy as jnp

    base = [5, 9, 13]                       # shared prefix
    rng = np.random.RandomState(11)
    trace = []
    for i in range(8):
        tail = rng.randint(1, 97, size=(3 + i % 4,)).tolist()
        trace.append((base + tail, 6, None))
    e0, r0, o0 = _run(lm, trace, n_slots=4, compute_dtype=jnp.bfloat16,
                      prefix_cache=True)
    e1, r1, o1 = _run(lm, trace, n_slots=4, compute_dtype=jnp.bfloat16,
                      prefix_cache=True, parallelism={"data": 4})
    _assert_identical(e0, r0, o0, e1, r1, o1)
    assert e1.metrics.summary().get("serving/prefix_hit_rate", 0) > 0


def test_tensor_parallel_bf16_token_identical(lm):
    """bf16 + tensor parallelism: the row-parallel projections must
    accumulate fp32 through the psum and round ONCE (regression for the
    per-chip-rounding drift that flipped greedy argmaxes on near-tied
    bf16 logits — caught by the user-style verify drive, not the fp32
    parity tests)."""
    import jax.numpy as jnp

    e0, r0, o0 = _run(lm, _trace(), n_slots=4, compute_dtype=jnp.bfloat16)
    e1, r1, o1 = _run(lm, _trace(), n_slots=4, compute_dtype=jnp.bfloat16,
                      parallelism={"data": 2, "model": 2})
    for ra, rb in zip(r0, r1):
        np.testing.assert_array_equal(o0[ra], o1[rb])


def test_per_request_admission_on_mesh(lm, baseline):
    """The per_request (B=1 prefill) admission path also routes into the
    sharded pool correctly."""
    e0, r0, o0 = baseline
    e1, r1, o1 = _run(lm, _trace(), n_slots=4, admission="per_request",
                      parallelism={"data": 2})
    _assert_identical(e0, r0, o0, e1, r1, o1)


def test_one_decode_program_regardless_of_mesh_size():
    """Compile-count regression guard: every engine — unsharded, 2-way,
    4-way slot-DP, 2-way TP — runs its whole trace through exactly ONE
    compiled decode program (fresh model per engine so each owns its
    step cache)."""
    from tests.compile_guards import assert_compile_count

    for kw in ({}, {"parallelism": {"data": 2}},
               {"parallelism": {"data": 4}},
               {"parallelism": {"model": 2}}):
        lm = _build_lm()
        eng, _, _ = _run(lm, _trace(6), n_slots=4, **kw)
        assert_compile_count(eng._step_fn, 1, what=repr(kw))


def test_seed_reproducible_across_mesh_shapes(lm):
    """A fixed-seed sampled request emits the same token stream on every
    mesh shape (lanes are request-keyed, never slot- or shard-keyed)."""
    from bigdl_tpu.serving import SamplingParams

    prompt = [4, 19, 33, 2]
    sp = SamplingParams(temperature=1.1, top_p=0.9, seed=1234)
    streams = []
    for kw in ({}, {"parallelism": {"data": 2}},
               {"parallelism": {"data": 4}},
               {"parallelism": {"model": 2}}):
        eng, rids, outs = _run(lm, [(prompt, 10, sp)], n_slots=4, **kw)
        streams.append(outs[rids[0]])
    for s in streams[1:]:
        np.testing.assert_array_equal(streams[0], s)


def test_balanced_allocation_and_slot_routing(lm):
    """ShardedKVPool invariants: contiguous slot→(shard, row) blocks,
    least-loaded allocation (one slot per shard before any second), and
    free/realloc keeping both free-list views consistent."""
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=8, parallelism={"data": 4})
    pool = eng.pool
    assert pool.n_shards == 4 and pool.rows_per_shard == 2
    assert pool.slot_shard(0) == (0, 0)
    assert pool.slot_shard(5) == (2, 1)
    with pytest.raises(ValueError):
        pool.slot_shard(8)
    slots = [pool.alloc() for _ in range(4)]
    assert sorted(pool.slot_shard(s)[0] for s in slots) == [0, 1, 2, 3]
    assert pool.used_per_shard() == [1, 1, 1, 1]
    pool.free(slots[1])
    assert pool.used_per_shard() == [1, 0, 1, 1]
    nxt = pool.alloc()                      # least-loaded shard refills
    assert pool.slot_shard(nxt)[0] == pool.slot_shard(slots[1])[0]
    for s in [slots[0], slots[2], slots[3], nxt]:
        pool.free(s)
    assert pool.free_slots == 8 and pool.used_per_shard() == [0] * 4
    assert "n_shards=4" in repr(pool)


def test_kvpool_repr_and_occupancy_guard():
    """Satellite: base-pool repr and the n_slots==0 occupancy guard."""
    import jax.numpy as jnp

    from bigdl_tpu.serving import KVPool

    def init(n):
        return {"pos": jnp.zeros((n,), jnp.int32),
                "k0": jnp.zeros((n, 4, 2, 2)), "v0": jnp.zeros((n, 4, 2, 2))}

    pool = KVPool(init, 2)
    r = repr(pool)
    assert "n_slots=2" in r and "n_shards" not in r
    pool.alloc()
    assert "used=1" in repr(pool) and pool.occupancy() == 0.5
    # the guard: a (hypothetical) zero-capacity pool reports 0.0, never
    # ZeroDivisionError mid-serving
    pool.n_slots = 0
    assert pool.occupancy() == 0.0
    with pytest.raises(ValueError):
        KVPool(init, 0)


def test_mesh_and_parallelism_validation(lm):
    from bigdl_tpu.serving import ServingEngine
    from bigdl_tpu.serving.sharded import make_mesh

    with pytest.raises(ValueError, match="not divisible"):
        ServingEngine(lm, n_slots=5, parallelism={"data": 4})
    with pytest.raises(ValueError, match="n_heads"):
        ServingEngine(lm, n_slots=8, parallelism={"model": 8})
    with pytest.raises(ValueError, match="unknown parallelism"):
        ServingEngine(lm, n_slots=8, parallelism={"tensor": 2})
    with pytest.raises(ValueError, match="1x1 mesh"):
        ServingEngine(lm, n_slots=8, parallelism={})
    with pytest.raises(ValueError, match="devices"):
        make_mesh(data=64)


def test_shard_metrics_surfaced(lm):
    """mesh_shape, per-shard occupancy, and admission imbalance ride
    through ServingMetrics; the balanced allocator keeps imbalance <= 1
    row under drain-style traffic."""
    eng, _, _ = _run(lm, _trace(8), n_slots=4, parallelism={"data": 4})
    s = eng.metrics.summary()
    assert s["serving/mesh_data_shards"] == 4.0
    assert s["serving/mesh_model_shards"] == 1.0
    assert "serving/shard_occupancy_min" in s
    assert "serving/shard_occupancy_max" in s
    vals = eng.metrics.metrics.values("serving/shard_imbalance")
    assert vals and max(vals) <= 1.0


def test_sharded_bench_smoke():
    """--scenario sharded runs end to end on a tiny config and reports
    an output-identity verdict."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmarks"))
    try:
        import serving_bench
    finally:
        sys.path.pop(0)
    res = serving_bench.run_sharded(model="tiny", n_requests=6,
                                    gen_tokens=4, n_slots=4,
                                    data_shards=4)
    assert res["outputs_match"] is True
    assert res["sharded"]["decode_programs"] == 1
    assert res["mesh"] == {"data": 4, "model": 1}
