"""Recurrent-family parity vs torch-cpu (the reference's Torch7 oracle
pattern, SURVEY.md §4) + scan-semantics tests."""

import numpy as np
import pytest

from tests.oracle import assert_close


def _set_lstm_weights(cell_params, t_lstm):
    import torch

    with torch.no_grad():
        t_lstm.weight_ih_l0.copy_(torch.from_numpy(np.asarray(cell_params["w_ih"])))
        t_lstm.weight_hh_l0.copy_(torch.from_numpy(np.asarray(cell_params["w_hh"])))
        t_lstm.bias_ih_l0.copy_(torch.from_numpy(np.asarray(cell_params["b_ih"])))
        t_lstm.bias_hh_l0.copy_(torch.from_numpy(np.asarray(cell_params["b_hh"])))


def test_lstm_recurrent_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import LSTM, Recurrent

    B, T, I, H = 3, 5, 4, 6
    rec = Recurrent().add(LSTM(I, H))
    rec._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = rec.forward(x)

    t_lstm = torch.nn.LSTM(I, H, batch_first=True)
    _set_lstm_weights(rec.params[rec._key()], t_lstm)
    t_out, _ = t_lstm(torch.from_numpy(x))
    assert_close(out, t_out.detach().numpy(), atol=1e-5)


def test_lstm_recurrent_backward_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import LSTM, Recurrent

    B, T, I, H = 2, 4, 3, 5
    rec = Recurrent().add(LSTM(I, H))
    rec._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    g = rng.randn(B, T, H).astype(np.float32)
    rec.forward(x)
    gin = rec.backward(x, g)

    t_lstm = torch.nn.LSTM(I, H, batch_first=True)
    _set_lstm_weights(rec.params[rec._key()], t_lstm)
    tx = torch.from_numpy(x).requires_grad_(True)
    t_out, _ = t_lstm(tx)
    t_out.backward(torch.from_numpy(g))
    assert_close(gin, tx.grad.numpy(), atol=1e-4)
    cp = rec.grad_params[rec._key()]
    assert_close(np.asarray(cp["w_ih"]), t_lstm.weight_ih_l0.grad.numpy(), atol=1e-4)
    assert_close(np.asarray(cp["w_hh"]), t_lstm.weight_hh_l0.grad.numpy(), atol=1e-4)
    assert_close(np.asarray(cp["b_ih"]), t_lstm.bias_ih_l0.grad.numpy(), atol=1e-4)


def test_gru_recurrent_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import GRU, Recurrent

    B, T, I, H = 3, 6, 4, 5
    rec = Recurrent().add(GRU(I, H))
    rec._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = rec.forward(x)

    t_gru = torch.nn.GRU(I, H, batch_first=True)
    cp = rec.params[rec._key()]
    with torch.no_grad():
        t_gru.weight_ih_l0.copy_(torch.from_numpy(np.asarray(cp["w_ih"])))
        t_gru.weight_hh_l0.copy_(torch.from_numpy(np.asarray(cp["w_hh"])))
        t_gru.bias_ih_l0.copy_(torch.from_numpy(np.asarray(cp["b_ih"])))
        t_gru.bias_hh_l0.copy_(torch.from_numpy(np.asarray(cp["b_hh"])))
    t_out, _ = t_gru(torch.from_numpy(x))
    assert_close(out, t_out.detach().numpy(), atol=1e-5)


def test_rnncell_recurrent_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import RnnCell, Recurrent

    B, T, I, H = 2, 5, 3, 4
    rec = Recurrent().add(RnnCell(I, H))
    rec._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = rec.forward(x)

    t_rnn = torch.nn.RNN(I, H, nonlinearity="tanh", batch_first=True)
    cp = rec.params[rec._key()]
    with torch.no_grad():
        t_rnn.weight_ih_l0.copy_(torch.from_numpy(np.asarray(cp["w_ih"])))
        t_rnn.weight_hh_l0.copy_(torch.from_numpy(np.asarray(cp["w_hh"])))
        t_rnn.bias_ih_l0.copy_(torch.from_numpy(np.asarray(cp["b_ih"])))
        t_rnn.bias_hh_l0.copy_(torch.from_numpy(np.asarray(cp["b_hh"])))
    t_out, _ = t_rnn(torch.from_numpy(x))
    assert_close(out, t_out.detach().numpy(), atol=1e-5)


def test_birecurrent_concat_matches_bidirectional_torch(rng):
    import torch

    from bigdl_tpu.nn import LSTM, BiRecurrent

    B, T, I, H = 2, 4, 3, 5
    bi = BiRecurrent(merge="concat").add(LSTM(I, H))
    bi._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = bi.forward(x)
    assert out.shape == (B, T, 2 * H)

    t_lstm = torch.nn.LSTM(I, H, batch_first=True, bidirectional=True)
    fwd_p = bi.params[f"0:{bi.fwd.name}"][bi.fwd._key()]
    bwd_p = bi.params[f"1:{bi.bwd.name}"][bi.bwd._key()]
    with torch.no_grad():
        t_lstm.weight_ih_l0.copy_(torch.from_numpy(np.asarray(fwd_p["w_ih"])))
        t_lstm.weight_hh_l0.copy_(torch.from_numpy(np.asarray(fwd_p["w_hh"])))
        t_lstm.bias_ih_l0.copy_(torch.from_numpy(np.asarray(fwd_p["b_ih"])))
        t_lstm.bias_hh_l0.copy_(torch.from_numpy(np.asarray(fwd_p["b_hh"])))
        t_lstm.weight_ih_l0_reverse.copy_(torch.from_numpy(np.asarray(bwd_p["w_ih"])))
        t_lstm.weight_hh_l0_reverse.copy_(torch.from_numpy(np.asarray(bwd_p["w_hh"])))
        t_lstm.bias_ih_l0_reverse.copy_(torch.from_numpy(np.asarray(bwd_p["b_ih"])))
        t_lstm.bias_hh_l0_reverse.copy_(torch.from_numpy(np.asarray(bwd_p["b_hh"])))
    t_out, _ = t_lstm(torch.from_numpy(x))
    assert_close(out, t_out.detach().numpy(), atol=1e-5)


def test_birecurrent_add_merge(rng):
    from bigdl_tpu.nn import GRU, BiRecurrent

    B, T, I, H = 2, 3, 4, 4
    bi = BiRecurrent().add(GRU(I, H))
    bi._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = bi.forward(x)
    assert out.shape == (B, T, H)
    # add-merge must equal fwd + reversed-bwd outputs (same param subtrees)
    fo, _ = bi.fwd.apply(bi.params[f"0:{bi.fwd.name}"], x, {})
    bo, _ = bi.bwd.apply(bi.params[f"1:{bi.bwd.name}"], x, {})
    assert_close(np.asarray(out), np.asarray(fo) + np.asarray(bo), atol=1e-6)


def test_lstm_peephole_shapes_and_finiteness(rng):
    from bigdl_tpu.nn import LSTMPeephole, Recurrent

    rec = Recurrent().add(LSTMPeephole(3, 4))
    rec._ensure_params()
    x = rng.randn(2, 5, 3).astype(np.float32)
    out = rec.forward(x)
    assert out.shape == (2, 5, 4)
    assert np.all(np.isfinite(np.asarray(out)))
    gin = rec.backward(x, np.ones((2, 5, 4), np.float32))
    assert np.all(np.isfinite(np.asarray(gin)))


def test_recurrent_decoder_feeds_output_back(rng):
    from bigdl_tpu.nn import RnnCell, RecurrentDecoder

    B, HI, T = 2, 4, 6
    dec = RecurrentDecoder(T).add(RnnCell(HI, HI))
    dec._ensure_params()
    x0 = rng.randn(B, HI).astype(np.float32)
    out = dec.forward(x0)
    assert out.shape == (B, T, HI)
    # step 0 must equal one manual cell step from zero carry
    cell = dec.cell
    o0, _ = cell.step(dec.params[dec._key()], x0, cell.init_carry(B))
    assert_close(np.asarray(out)[:, 0], np.asarray(o0), atol=1e-6)


def test_time_distributed_matches_per_step_linear(rng):
    from bigdl_tpu.nn import Linear, TimeDistributed

    B, T, I, O = 3, 4, 5, 2
    inner = Linear(I, O)
    td = TimeDistributed(inner)
    td._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = td.forward(x)
    assert out.shape == (B, T, O)
    p = td.params[td._key()]
    want = np.asarray(x) @ np.asarray(p["weight"]).T + np.asarray(p["bias"])
    assert_close(np.asarray(out), want, atol=1e-5)


def test_cell_regularizer_applied(rng):
    """w/u regularizers on a cell must contribute gradient terms
    (key sets w_ih / w_hh, not just 'weight')."""
    import jax

    from bigdl_tpu.nn import LSTM, Recurrent
    from bigdl_tpu.optim.regularizer import L2Regularizer
    from bigdl_tpu.optim.train_step import apply_module_regularizers

    rec = Recurrent().add(LSTM(3, 4, w_regularizer=L2Regularizer(0.5),
                               u_regularizer=L2Regularizer(0.25)))
    rec._ensure_params()
    zeros = jax.tree_util.tree_map(lambda p: np.zeros_like(np.asarray(p)),
                                   rec.params)
    out = apply_module_regularizers(rec, rec.params, zeros)
    cp, op = rec.params[rec._key()], out[rec._key()]
    assert_close(np.asarray(op["w_ih"]), 0.5 * np.asarray(cp["w_ih"]), atol=1e-6)
    assert_close(np.asarray(op["w_hh"]), 0.25 * np.asarray(cp["w_hh"]), atol=1e-6)
    assert_close(np.asarray(op["b_ih"]), np.zeros_like(np.asarray(cp["b_ih"])),
                 atol=0)


def test_cell_dropout_active_in_training_only(rng):
    import jax

    from bigdl_tpu.nn import LSTM, Recurrent

    rec = Recurrent().add(LSTM(4, 6, p=0.5))
    rec._ensure_params()
    x = rng.randn(3, 5, 4).astype(np.float32)
    k = jax.random.PRNGKey(0)
    train_a, _ = rec.apply(rec.params, x, {}, training=True, rng=k)
    train_b, _ = rec.apply(rec.params, x, {}, training=True,
                           rng=jax.random.PRNGKey(1))
    eval_a, _ = rec.apply(rec.params, x, {}, training=False, rng=None)
    eval_b, _ = rec.apply(rec.params, x, {}, training=False, rng=None)
    assert not np.allclose(np.asarray(train_a), np.asarray(train_b))
    assert_close(np.asarray(eval_a), np.asarray(eval_b), atol=0)
    assert not np.allclose(np.asarray(train_a), np.asarray(eval_a))


def test_recurrent_trains_under_jit(rng):
    """A Recurrent model must train end-to-end inside one jitted step."""
    import jax

    from bigdl_tpu.nn import LSTM, Linear, Recurrent, Select, Sequential
    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.train_step import make_train_step

    model = (Sequential()
             .add(Recurrent().add(LSTM(4, 8)))
             .add(Select(2, -1))
             .add(Linear(8, 3)))
    model._ensure_params()
    crit = CrossEntropyCriterion()
    optim = Adam(learning_rate=1e-2)
    step = jax.jit(make_train_step(model, crit, optim))

    params, ms = model.params, model.state
    opt_state = optim.init_state(params)
    x = rng.randn(8, 6, 4).astype(np.float32)
    y = (rng.randint(0, 3, size=(8,)) + 1).astype(np.float32)  # 1-based labels
    losses = []
    rngk = jax.random.PRNGKey(0)
    for i in range(30):
        params, opt_state, ms, loss = step(params, opt_state, ms, rngk, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_conv_lstm_peephole_matches_manual_scan(rng):
    """ConvLSTMPeephole driven by Recurrent vs a hand-rolled numpy/jnp
    recurrence (reference nn/ConvLSTMPeephole.scala semantics)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn import ConvLSTMPeephole, Recurrent
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    B, T, C, O, H, W = 2, 4, 3, 5, 6, 7
    cell = ConvLSTMPeephole(C, O, kernel_i=3, kernel_c=3)
    rec = Recurrent().add(cell)
    rec._ensure_params()
    x = rng.randn(B, T, C, H, W).astype(np.float32) * 0.5

    out = np.asarray(rec.forward(x))
    assert out.shape == (B, T, O, H, W)

    # manual recurrence with the same params
    p = rec.params[next(iter(rec.params))]
    h = jnp.zeros((B, O, H, W))
    c = jnp.zeros((B, O, H, W))
    for t in range(T):
        pre = cell._conv(jnp.asarray(x[:, t]), p["w_ih"], p["b_ih"])
        gates = pre + cell._conv(h, p["w_hh"])
        i, f, g, o = jnp.split(gates, 4, axis=1)
        i = jax.nn.sigmoid(i + p["w_pi"][None] * c)
        f = jax.nn.sigmoid(f + p["w_pf"][None] * c)
        g = jnp.tanh(g)
        c = f * c + i * g
        o = jax.nn.sigmoid(o + p["w_po"][None] * c)
        h = o * jnp.tanh(c)
        np.testing.assert_allclose(out[:, t], np.asarray(h), atol=2e-5)

    # trains end to end through the standard stack
    from bigdl_tpu.nn import MSECriterion, Sequential
    from bigdl_tpu.optim.optim_method import Adam
    from bigdl_tpu.optim.train_step import make_train_step

    model = Sequential().add(Recurrent().add(ConvLSTMPeephole(C, O)))
    model._ensure_params()
    tgt = rng.randn(B, T, O, H, W).astype(np.float32)
    step = jax.jit(make_train_step(model, MSECriterion(), Adam(1e-2)))
    params, opt = model.params, Adam(1e-2).init_state(model.params)
    losses = []
    rngk = jax.random.PRNGKey(0)
    ms = model.state
    for _ in range(8):
        params, opt, ms, loss = step(params, opt, ms, rngk, x, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_conv_lstm_no_peephole(rng):
    from bigdl_tpu.nn import ConvLSTMPeephole, Recurrent

    rec = Recurrent().add(ConvLSTMPeephole(2, 3, with_peephole=False))
    rec._ensure_params()
    out = rec.forward(rng.randn(1, 3, 2, 5, 5).astype(np.float32))
    assert np.asarray(out).shape == (1, 3, 3, 5, 5)
    assert np.isfinite(np.asarray(out)).all()


def test_stacked_conv_lstm_multirnncell(rng):
    """Stacked ConvLSTM through MultiRNNCell (the reference nowcasting
    architecture) and the single-step Cell facade."""
    from bigdl_tpu.nn import ConvLSTMPeephole, MultiRNNCell, Recurrent

    stack = MultiRNNCell([ConvLSTMPeephole(2, 3), ConvLSTMPeephole(3, 3)])
    rec = Recurrent().add(stack)
    rec._ensure_params()
    x = rng.randn(2, 4, 2, 5, 6).astype(np.float32)
    out = np.asarray(rec.forward(x))
    assert out.shape == (2, 4, 3, 5, 6)
    assert np.isfinite(out).all()

    # single-step table facade sizes the carry from the frame
    cell = ConvLSTMPeephole(2, 3)
    cell._ensure_params()
    frame = rng.randn(2, 2, 5, 6).astype(np.float32)
    res = cell.forward([frame])
    assert np.asarray(res[0]).shape == (2, 3, 5, 6)


def test_gru_reset_after_false_keras1_convention(rng):
    """reset_after=False applies the reset gate to the state BEFORE the
    candidate matmul (keras1 semantics) — numpy oracle, and a sanity
    check that the two conventions genuinely differ on the same
    weights."""
    import jax.numpy as jnp

    from bigdl_tpu.nn import GRU, Recurrent

    B, T, I, H = 2, 5, 3, 4
    rec = Recurrent().add(GRU(I, H, reset_after=False))
    rec._ensure_params()
    x = rng.randn(B, T, I).astype(np.float32)
    out = np.asarray(rec.forward(x))

    cp = rec.params[rec._key()]
    w_ih = np.asarray(cp["w_ih"]); w_hh = np.asarray(cp["w_hh"])
    b_ih = np.asarray(cp["b_ih"]); b_hh = np.asarray(cp["b_hh"])

    def sig(v):
        return 1 / (1 + np.exp(-v))

    h = np.zeros((B, H), np.float32)
    for t in range(T):
        pre = x[:, t] @ w_ih.T + b_ih
        xr, xz, xn = np.split(pre, 3, axis=-1)
        hp = h @ w_hh[:2 * H].T + b_hh[:2 * H]
        hr, hz = np.split(hp, 2, axis=-1)
        r, z = sig(xr + hr), sig(xz + hz)
        n = np.tanh(xn + (r * h) @ w_hh[2 * H:].T + b_hh[2 * H:])
        h = (1 - z) * n + z * h
    assert_close(out[:, -1], h, atol=1e-5)

    # same weights under torch semantics give a DIFFERENT trajectory
    rec2 = Recurrent().add(GRU(I, H))
    rec2._ensure_params()
    rec2.params = {rec2._key(): rec.params[rec._key()]}
    out2 = np.asarray(rec2.forward(x))
    assert float(np.abs(out - out2).max()) > 1e-4
