"""Shared straggler-injection helper for the block-store tests: wraps a
BlockStore so gradient-block puts from iteration ``first_iter`` on sleep
first — a process whose gradient transfers straggle (the reference's
BlockManager slow-fetch scenario) AFTER the warmup window calibrated
thresholds on healthy iterations, which is the reference's operating
assumption. Used by both the threaded unit tests (test_block_store.py)
and the real multi-process pod worker (multihost_worker.py)."""

import time


class DelayedGradientPuts:
    def __init__(self, inner, delay_s, first_iter=1):
        self._inner, self._delay, self._first = inner, delay_s, first_iter

    def put(self, key, value):
        parts = key.split("/")
        if len(parts) >= 3 and parts[1] == "g" and \
                int(parts[2]) >= self._first:
            time.sleep(self._delay)
        self._inner.put(key, value)

    def __getattr__(self, name):
        return getattr(self._inner, name)
