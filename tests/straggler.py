"""Shared straggler-injection helper for the block-store tests: wraps a
BlockStore so gradient-block puts from iteration ``first_iter`` on sleep
first — a process whose gradient transfers straggle (the reference's
BlockManager slow-fetch scenario) AFTER the warmup window calibrated
thresholds on healthy iterations, which is the reference's operating
assumption. Used by both the threaded unit tests (test_block_store.py)
and the real multi-process pod worker (multihost_worker.py)."""

import time


class DelayedGradientPuts:
    """``last_iter`` (inclusive) bounds the straggling window so tests can
    model a straggler that HEALS mid-run — the late-arrival-probe recovery
    path (BlockStoreParameter._probe_late_arrivals)."""

    def __init__(self, inner, delay_s, first_iter=1, last_iter=None):
        self._inner, self._delay, self._first = inner, delay_s, first_iter
        self._last = last_iter

    def put(self, key, value):
        parts = key.split("/")
        if len(parts) >= 3 and parts[1] == "g":
            t = int(parts[2])
            if t >= self._first and (self._last is None or t <= self._last):
                time.sleep(self._delay)
        self._inner.put(key, value)

    def __getattr__(self, name):
        return getattr(self._inner, name)
