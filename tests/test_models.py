"""Model-zoo tests: forward shapes, parameter counts vs published values,
and one optimizer step on the small variants (SURVEY.md §2.8 configs)."""

import numpy as np
import pytest

from bigdl_tpu.models import (
    AlexNet_OWT, Autoencoder, Inception_v1, LeNet5, ResNet, VggForCifar10,
    Vgg_16,
)

pytestmark = pytest.mark.integration  # SURVEY §4 tag-split: heavy suite


def _forward(model, shape, seed=0):
    import jax

    params = model.init_params(jax.random.PRNGKey(seed))
    state = model.init_state()
    x = np.random.RandomState(seed).rand(*shape).astype(np.float32)
    out, _ = model.apply(params, x, state, training=False)
    return params, out


def _n_params(params):
    import jax

    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params))


class TestShapes:
    def test_lenet(self):
        _, out = _forward(LeNet5(10), (2, 28, 28))
        assert out.shape == (2, 10)

    def test_vgg_cifar(self):
        params, out = _forward(VggForCifar10(10), (2, 3, 32, 32))
        assert out.shape == (2, 10)

    def test_resnet_cifar20(self):
        model = ResNet(10, {"depth": 20, "dataSet": "cifar10", "shortcutType": "A"})
        params, out = _forward(model, (2, 3, 32, 32))
        assert out.shape == (2, 10)
        # resnet-20 cifar ≈ 0.27M params (He et al. table 6)
        assert 0.25e6 < _n_params(params) < 0.30e6

    def test_resnet50_imagenet(self):
        model = ResNet(1000, {"depth": 50, "shortcutType": "B"})
        params, out = _forward(model, (1, 3, 224, 224))
        assert out.shape == (1, 1000)
        # canonical ResNet-50 param count ≈ 25.56M
        assert abs(_n_params(params) - 25.56e6) < 0.2e6

    def test_inception_v1(self):
        from bigdl_tpu.models import Inception_v1_NoAuxClassifier

        model = Inception_v1_NoAuxClassifier(1000)
        params, out = _forward(model, (1, 3, 224, 224))
        assert out.shape == (1, 1000)
        # GoogLeNet main tower ≈ 7.0M params (incl. classifier)
        assert 5.5e6 < _n_params(params) < 8.0e6

    def test_inception_v1_aux_classifiers(self):
        from bigdl_tpu.nn import ClassNLLCriterion, ParallelCriterion

        model = Inception_v1(1000)
        params, outs = _forward(model, (1, 3, 224, 224))
        # flat table: [main, aux@4d, aux@4a], each (1, 1000) log-probs
        assert isinstance(outs, list) and len(outs) == 3
        assert all(np.asarray(o).shape == (1, 1000) for o in outs)
        # aux towers add ~6M params over the 7M main tower
        assert 12e6 < _n_params(params) < 15e6
        crit = (ParallelCriterion(repeat_target=True)
                .add(ClassNLLCriterion(), 1.0)
                .add(ClassNLLCriterion(), 0.3)
                .add(ClassNLLCriterion(), 0.3))
        loss = crit.forward(outs, np.asarray([7.0]))
        assert np.isfinite(loss) and loss > 0

    def test_inception_v2_bn(self):
        from bigdl_tpu.models import Inception_v2

        model = Inception_v2(1000)
        params, out = _forward(model, (1, 3, 224, 224))
        assert out.shape == (1, 1000)
        # BN-GoogLeNet ≈ 11.3M params
        assert 10e6 < _n_params(params) < 13e6

    def test_alexnet_owt(self):
        model = AlexNet_OWT(1000)
        params, out = _forward(model, (1, 3, 224, 224))
        assert out.shape == (1, 1000)
        assert 55e6 < _n_params(params) < 65e6

    def test_autoencoder(self):
        model = Autoencoder(32)
        _, out = _forward(model, (2, 28, 28))
        assert out.shape == (2, 784)

    def test_vgg16_imagenet(self):
        model = Vgg_16(1000)
        import jax

        params = model.init_params(jax.random.PRNGKey(0))
        # canonical VGG-16 ≈ 138.36M
        assert abs(_n_params(params) - 138.36e6) < 1e6


class TestTraining:
    def test_resnet_cifar_step_decreases_loss(self):
        import jax

        from bigdl_tpu.nn import ClassNLLCriterion
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.optim.train_step import make_train_step

        model = ResNet(10, {"depth": 20, "dataSet": "cifar10"})
        crit = ClassNLLCriterion()
        sgd = SGD(learning_rate=0.1)
        params = model.init_params(jax.random.PRNGKey(0))
        state = model.init_state()
        opt_state = sgd.init_state(params)
        step = jax.jit(make_train_step(model, crit, sgd))

        rs = np.random.RandomState(0)
        x = rs.rand(8, 3, 32, 32).astype(np.float32)
        y = rs.randint(1, 11, size=(8,))  # 1-based labels (Torch convention)
        rng = jax.random.PRNGKey(1)
        losses = []
        for i in range(4):
            params, opt_state, state, loss = step(
                params, opt_state, state, jax.random.fold_in(rng, i), x, y
            )
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_zero_gamma_makes_blocks_identity_at_init(self):
        """With zeroGamma, each residual branch contributes 0 at init: a
        basic block's output must equal ReLU(shortcut) == its input for an
        identity shortcut with non-negative input."""
        import jax

        from bigdl_tpu.models.resnet import _basic_block
        from bigdl_tpu.nn import ReLU

        block, _ = _basic_block(8, 8, 1, True)
        block._ensure_params()
        block.evaluate()
        rs = np.random.RandomState(3)
        x = np.abs(rs.randn(2, 8, 5, 5)).astype(np.float32)  # >= 0
        res = np.asarray(block.forward(x))
        # residual branch is exactly zero at init → block + shortcut = x
        np.testing.assert_allclose(res, np.zeros_like(res), atol=0)

        model = ResNet(10, {"depth": 20, "dataSet": "cifar10", "zeroGamma": True})
        _, out = _forward(model, (2, 3, 32, 32))
        assert np.all(np.isfinite(np.asarray(out)))
