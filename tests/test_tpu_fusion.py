"""FusedGraph (NHWC fused lowering) parity vs the reference Graph executor.

The wrapped model must be indistinguishable from the original in params,
state, outputs and gradients — only faster on TPU. Runs on the CPU test
mesh (Pallas kernels in interpret mode), fp32, so parity is tight.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.models.resnet import _bn, _bottleneck_block, _conv, _residual
from bigdl_tpu.nn import (
    Graph, Input, Linear, ReLU, Reshape, SpatialAveragePooling,
)
from bigdl_tpu.nn.tpu_fusion import FusedGraph, maybe_fuse
from bigdl_tpu.utils.random_gen import RNG


def tiny_bottleneck(planes: int = 8):
    """conv-BN-ReLU stem + two bottleneck blocks (2nd strided, projection
    shortcuts) + global avgpool + Linear — every fused-edge pattern the
    ResNet zoo produces, at toy size."""
    inp = Input()
    x = _conv(3, 2 * planes, 3, 1, 1).inputs(inp)
    x = _bn(2 * planes).inputs(x)
    x = ReLU(True).inputs(x)
    n_in = 2 * planes
    x, n_in = _residual(x, n_in, planes, 1, _bottleneck_block, "B", True)
    x, n_in = _residual(x, n_in, 2 * planes, 2, _bottleneck_block, "B", True)
    x = SpatialAveragePooling(4, 4, 1, 1).inputs(x)
    x = Reshape([n_in], batch_mode=True).inputs(x)
    out = Linear(n_in, 10).inputs(x)
    return Graph(inp, out)


def _data(batch=2, hw=8, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((batch, 3, hw, hw)),
                       jnp.float32)


@pytest.mark.parametrize(
    "planes", [8, pytest.param(128, marks=pytest.mark.integration)])
def test_forward_parity_training(planes, monkeypatch):
    """planes=8 exercises the XLA-dot edge lowering; planes=128 forces the
    Pallas kernel path (interpret mode on CPU) via the env threshold."""
    monkeypatch.setenv("BIGDL_PALLAS_MIN_C", "128")
    RNG.set_seed(3)
    g = tiny_bottleneck(planes)
    g._ensure_params()
    fused = FusedGraph(g)
    assert len(fused._edges) == 4, f"expected 4 fused edges, got {len(fused._edges)}"
    x = _data()
    ref, ref_state = g.apply(g.params, x, g.state, training=True)
    out, out_state = fused.apply(g.params, x, g.state, training=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # running stats must update identically (same BN semantics)
    ref_leaves = jax.tree_util.tree_leaves(ref_state)
    out_leaves = jax.tree_util.tree_leaves(out_state)
    assert len(ref_leaves) == len(out_leaves)
    for a, b in zip(ref_leaves, out_leaves):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4)


def test_forward_parity_eval():
    RNG.set_seed(4)
    g = tiny_bottleneck(8)
    g._ensure_params()
    fused = FusedGraph(g)
    x = _data()
    # one training step first so the running stats are non-trivial
    _, state1 = g.apply(g.params, x, g.state, training=True)
    ref, _ = g.apply(g.params, x, state1, training=False)
    out, _ = fused.apply(g.params, x, state1, training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize(
    "planes", [8, pytest.param(128, marks=pytest.mark.integration)])
def test_grad_parity(planes, monkeypatch):
    monkeypatch.setenv("BIGDL_PALLAS_MIN_C", "128")
    RNG.set_seed(5)
    g = tiny_bottleneck(planes)
    g._ensure_params()
    fused = FusedGraph(g)
    x = _data()
    tgt = jnp.asarray(np.random.default_rng(1).standard_normal((2, 10)),
                      jnp.float32)

    def loss(params, model):
        out, _ = model.apply(params, x, g.state, training=True)
        return jnp.mean((out - tgt) ** 2)

    gref = jax.grad(loss)(g.params, g)
    gfus = jax.grad(loss)(g.params, fused)
    ref_l, tdef = jax.tree_util.tree_flatten(gref)
    fus_l, _ = jax.tree_util.tree_flatten(gfus)
    assert len(ref_l) == len(fus_l)
    for a, b in zip(ref_l, fus_l):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=5e-3, atol=1e-5,
            err_msg=str(tdef))


def test_maybe_fuse_passthrough():
    """Graphs with nothing to fuse come back unchanged."""
    from bigdl_tpu.models.resnet import ResNet

    cifar = ResNet(10, {"depth": 20, "shortcutType": "A",
                        "dataSet": "cifar10"})
    assert maybe_fuse(cifar) is cifar  # basic blocks: no 1×1 convs


def test_params_state_trees_identical():
    RNG.set_seed(6)
    g = tiny_bottleneck(8)
    fused = FusedGraph(g)
    k = jax.random.PRNGKey(0)
    pg = g.init_params(k)
    pf = fused.init_params(k)
    assert jax.tree_util.tree_structure(pg) == jax.tree_util.tree_structure(pf)
    assert jax.tree_util.tree_structure(g.init_state()) == \
        jax.tree_util.tree_structure(fused.init_state())


def test_rng_threads_to_dropout():
    """Dropout must actually drop under FusedGraph in training (rng=None
    would silently disable it — review finding r3)."""
    from bigdl_tpu.nn import Dropout, Graph, Input

    inp = Input()
    out = Dropout(0.5).inputs(inp)
    g = Graph(inp, out)
    g._ensure_params()
    fused = FusedGraph(g)
    x = jnp.ones((4, 64), jnp.float32)
    key = jax.random.PRNGKey(3)
    y, _ = fused.apply(g.params, x, g.state, training=True, rng=key)
    dropped = float(jnp.mean((jnp.asarray(y) == 0).astype(jnp.float32)))
    assert 0.2 < dropped < 0.8, f"dropout inactive under FusedGraph ({dropped})"
