"""Parametrized differential sweep vs torch (the §4 Torch7-oracle pattern,
widened): forward AND input-gradient parity for the activation family,
criterion family, and conv/pool gradients including groups."""

import numpy as np
import pytest

from tests.oracle import assert_close

torch = pytest.importorskip("torch")


def _fb(module, x, g):
    """bigdl_tpu facade forward + backward."""
    module._ensure_params()
    module.evaluate()
    out = np.asarray(module.forward(x))
    gin = np.asarray(module.backward(x, g))
    return out, gin


def _tfb(tmod, x, g):
    xt = torch.from_numpy(x).requires_grad_(True)
    out = tmod(xt)
    out.backward(torch.from_numpy(g))
    return out.detach().numpy(), xt.grad.numpy()


_ACTS = [
    ("ReLU", lambda nn: nn.ReLU(), lambda: torch.nn.ReLU()),
    ("ReLU6", lambda nn: nn.ReLU6(), lambda: torch.nn.ReLU6()),
    ("Tanh", lambda nn: nn.Tanh(), lambda: torch.nn.Tanh()),
    ("Sigmoid", lambda nn: nn.Sigmoid(), lambda: torch.nn.Sigmoid()),
    ("ELU", lambda nn: nn.ELU(), lambda: torch.nn.ELU()),
    ("LeakyReLU", lambda nn: nn.LeakyReLU(0.01),
     lambda: torch.nn.LeakyReLU(0.01)),
    ("SoftPlus", lambda nn: nn.SoftPlus(), lambda: torch.nn.Softplus()),
    ("SoftSign", lambda nn: nn.SoftSign(), lambda: torch.nn.Softsign()),
    ("HardTanh", lambda nn: nn.HardTanh(), lambda: torch.nn.Hardtanh()),
    ("SoftMax", lambda nn: nn.SoftMax(), lambda: torch.nn.Softmax(dim=-1)),
    ("LogSoftMax", lambda nn: nn.LogSoftMax(),
     lambda: torch.nn.LogSoftmax(dim=-1)),
    ("SoftMin", lambda nn: nn.SoftMin(), lambda: torch.nn.Softmin(dim=-1)),
    ("LogSigmoid", lambda nn: nn.LogSigmoid(), lambda: torch.nn.LogSigmoid()),
    ("GELU", lambda nn: nn.GELU(), lambda: torch.nn.GELU(approximate="tanh")),
    ("Threshold", lambda nn: nn.Threshold(0.3, -0.2),
     lambda: torch.nn.Threshold(0.3, -0.2)),
]


@pytest.mark.parametrize("name,ours,theirs", _ACTS,
                         ids=[a[0] for a in _ACTS])
def test_activation_forward_backward(rng, name, ours, theirs):
    import bigdl_tpu.nn as nn

    x = rng.randn(4, 7).astype(np.float32) * 2
    g = rng.randn(4, 7).astype(np.float32)
    out, gin = _fb(ours(nn), x, g)
    want, wgin = _tfb(theirs(), x, g)
    assert_close(out, want, atol=2e-4, msg=f"{name} fwd")
    assert_close(gin, wgin, atol=2e-4, msg=f"{name} bwd")


_CRITS = [
    ("MSE", lambda nn: nn.MSECriterion(), lambda: torch.nn.MSELoss(), "reg"),
    ("Abs", lambda nn: nn.AbsCriterion(), lambda: torch.nn.L1Loss(), "reg"),
    ("SmoothL1", lambda nn: nn.SmoothL1Criterion(),
     lambda: torch.nn.SmoothL1Loss(), "reg"),
    ("BCE", lambda nn: nn.BCECriterion(), lambda: torch.nn.BCELoss(), "prob"),
    ("ClassNLL", lambda nn: nn.ClassNLLCriterion(),
     lambda: torch.nn.NLLLoss(), "cls"),
    ("CrossEntropy", lambda nn: nn.CrossEntropyCriterion(),
     lambda: torch.nn.CrossEntropyLoss(), "cls"),
    ("DistKLDiv", lambda nn: nn.DistKLDivCriterion(),
     lambda: torch.nn.KLDivLoss(reduction="mean"), "kl"),  # element mean,
    # matching the reference's sizeAverage semantics
]


@pytest.mark.parametrize("name,ours,theirs,kind", _CRITS,
                         ids=[c[0] for c in _CRITS])
def test_criterion_gradients(rng, name, ours, theirs, kind):
    import bigdl_tpu.nn as nn

    N, C = 6, 5
    if kind == "reg":
        x = rng.randn(N, C).astype(np.float32)
        t = rng.randn(N, C).astype(np.float32)
        tt = torch.from_numpy(t)
    elif kind == "prob":
        x = rng.rand(N, C).astype(np.float32) * 0.9 + 0.05
        t = (rng.rand(N, C) > 0.5).astype(np.float32)
        tt = torch.from_numpy(t)
    elif kind == "kl":
        logits = rng.randn(N, C).astype(np.float32)
        x = np.asarray(torch.log_softmax(torch.from_numpy(logits), 1))
        t = np.asarray(torch.softmax(torch.from_numpy(
            rng.randn(N, C).astype(np.float32)), 1))
        tt = torch.from_numpy(t)
    else:  # cls
        logits = rng.randn(N, C).astype(np.float32)
        x = (np.asarray(torch.log_softmax(torch.from_numpy(logits), 1))
             if name == "ClassNLL" else logits)
        t = (rng.randint(1, C + 1, size=N)).astype(np.float32)
        tt = torch.from_numpy(t).long() - 1

    crit = ours(nn)
    loss = crit.forward(x, t)
    gin = np.asarray(crit.backward(x, t))

    xt = torch.from_numpy(x).requires_grad_(True)
    tl = theirs()(xt, tt)
    tl.backward()
    assert abs(loss - float(tl)) < 2e-4, f"{name} loss"
    assert_close(gin, xt.grad.numpy(), atol=2e-4, msg=f"{name} grad")


@pytest.mark.parametrize("groups", [1, 2])
def test_conv_gradients_with_groups(rng, groups):
    from bigdl_tpu.nn import SpatialConvolution

    conv = SpatialConvolution(4, 6, 3, 3, 2, 2, 1, 1, n_group=groups)
    conv._ensure_params()
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    out = np.asarray(conv.forward(x))
    g = rng.randn(*out.shape).astype(np.float32)
    gin = np.asarray(conv.backward(x, g))

    tconv = torch.nn.Conv2d(4, 6, 3, stride=2, padding=1, groups=groups)
    with torch.no_grad():
        tconv.weight.copy_(torch.from_numpy(np.asarray(conv.params["weight"])))
        tconv.bias.copy_(torch.from_numpy(np.asarray(conv.params["bias"])))
    xt = torch.from_numpy(x).requires_grad_(True)
    tout = tconv(xt)
    tout.backward(torch.from_numpy(g))
    assert_close(out, tout.detach().numpy(), atol=1e-4)
    assert_close(gin, xt.grad.numpy(), atol=1e-4)
    # weight gradient parity too (accGradParameters path)
    gw = [gg for gg in np.atleast_1d(conv.grad_params["weight"])]
    assert_close(np.asarray(conv.grad_params["weight"]),
                 tconv.weight.grad.numpy(), atol=1e-3)


@pytest.mark.parametrize("pool", ["max", "avg"])
def test_pooling_gradients(rng, pool):
    from bigdl_tpu.nn import SpatialAveragePooling, SpatialMaxPooling

    ours = (SpatialMaxPooling(3, 3, 2, 2, 1, 1) if pool == "max"
            else SpatialAveragePooling(3, 3, 2, 2, 1, 1))
    theirs = (torch.nn.MaxPool2d(3, 2, 1) if pool == "max"
              else torch.nn.AvgPool2d(3, 2, 1))
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    ours._ensure_params()
    out = np.asarray(ours.forward(x))
    g = rng.randn(*out.shape).astype(np.float32)
    gin = np.asarray(ours.backward(x, g))
    want, wgin = _tfb(theirs, x, g)
    assert_close(out, want, atol=1e-5)
    assert_close(gin, wgin, atol=1e-5)


def test_lstm_gru_gradient_parity(rng):
    """Recurrent backward parity vs torch over a short sequence."""
    import bigdl_tpu.nn as nn

    B, T, I, H = 2, 5, 3, 4
    x = rng.randn(B, T, I).astype(np.float32)

    rec = nn.Recurrent().add(nn.LSTM(I, H))
    rec._ensure_params()
    out = np.asarray(rec.forward(x))
    g = rng.randn(*out.shape).astype(np.float32)
    gin = np.asarray(rec.backward(x, g))

    tl = torch.nn.LSTM(I, H, batch_first=True)
    cell = rec.cell
    p = rec.params[rec._key()]
    with torch.no_grad():
        tl.weight_ih_l0.copy_(torch.from_numpy(np.asarray(p["w_ih"])))
        tl.weight_hh_l0.copy_(torch.from_numpy(np.asarray(p["w_hh"])))
        tl.bias_ih_l0.copy_(torch.from_numpy(np.asarray(p["b_ih"])))
        tl.bias_hh_l0.copy_(torch.from_numpy(np.asarray(p["b_hh"])))
    xt = torch.from_numpy(x).requires_grad_(True)
    tout, _ = tl(xt)
    tout.backward(torch.from_numpy(g))
    assert_close(out, tout.detach().numpy(), atol=1e-4)
    assert_close(gin, xt.grad.numpy(), atol=1e-4)


def test_separable_conv_gradients_vs_torch(rng):
    """Depthwise+pointwise gradients (input, both weights, bias) vs torch."""
    from bigdl_tpu.nn import SpatialSeparableConvolution

    m = SpatialSeparableConvolution(3, 5, 2, 3, 3, p_w=1, p_h=1)
    m._ensure_params()
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    out = np.asarray(m.forward(x))
    g = rng.randn(*out.shape).astype(np.float32)
    gin = np.asarray(m.backward(x, g))

    depth = torch.nn.Conv2d(3, 6, 3, padding=1, groups=3, bias=False)
    point = torch.nn.Conv2d(6, 5, 1)
    with torch.no_grad():
        depth.weight.copy_(torch.from_numpy(np.asarray(m.params["depth_weight"])))
        point.weight.copy_(torch.from_numpy(np.asarray(m.params["point_weight"])))
        point.bias.copy_(torch.from_numpy(np.asarray(m.params["bias"])))
    xt = torch.from_numpy(x).requires_grad_(True)
    tout = point(depth(xt))
    tout.backward(torch.from_numpy(g))
    assert_close(out, tout.detach().numpy(), atol=1e-4)
    assert_close(gin, xt.grad.numpy(), atol=1e-4)
    assert_close(np.asarray(m.grad_params["depth_weight"]),
                 depth.weight.grad.numpy(), atol=1e-3)
    assert_close(np.asarray(m.grad_params["point_weight"]),
                 point.weight.grad.numpy(), atol=1e-3)
    assert_close(np.asarray(m.grad_params["bias"]),
                 point.bias.grad.numpy(), atol=1e-3)


def test_maxout_srelu_convmap_finite_diff(rng):
    """Finite-difference gradient checks for layers without a torch twin
    (the nn/GradientChecker.scala pattern)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn import Maxout, SpatialConvolutionMap, SReLU
    from tests.oracle import finite_diff_grad

    cases = []
    mx = Maxout(4, 3, 2)
    mx._ensure_params()
    cases.append((mx, rng.randn(2, 4).astype(np.float32)))
    sr = SReLU((5,))
    sr._ensure_params()
    cases.append((sr, rng.randn(3, 5).astype(np.float32) * 2))
    cm = SpatialConvolutionMap(
        SpatialConvolutionMap.random(3, 2, fan_in=2, seed=1), 3, 3,
        pad_w=1, pad_h=1)
    cm._ensure_params()
    cases.append((cm, rng.randn(1, 3, 5, 5).astype(np.float32)))

    for m, x in cases:
        def loss(xx, m=m):
            out, _ = m.apply(m.params, jnp.asarray(xx, jnp.float32))
            return float(jnp.sum(out * out))

        g_analytic = np.asarray(jax.grad(
            lambda xx: jnp.sum(m.apply(m.params, xx)[0] ** 2))(
            jnp.asarray(x)))
        g_numeric = finite_diff_grad(loss, x.astype(np.float64), eps=1e-3)
        assert_close(g_analytic, g_numeric, atol=2e-2, rtol=2e-2,
                     msg=type(m).__name__)
