"""SparseTensor + sparse layers vs dense oracles (SURVEY.md §2.1 sparse row)."""

import numpy as np

from tests.oracle import assert_close


def _random_sparse(rng, B, D, density=0.2):
    dense = rng.randn(B, D).astype(np.float32)
    dense *= (rng.rand(B, D) < density)
    return dense


def test_from_dense_roundtrip(rng):
    from bigdl_tpu.tensor import SparseTensor

    dense = _random_sparse(rng, 5, 7)
    sp = SparseTensor.from_dense(dense)
    assert_close(np.asarray(sp.to_dense()), dense)
    # padded capacity roundtrips too
    sp2 = SparseTensor.from_dense(dense, capacity=60)
    assert_close(np.asarray(sp2.to_dense()), dense)


def test_sparse_dense_matmul(rng):
    from bigdl_tpu.tensor import SparseTensor, sparse_dense_matmul

    dense = _random_sparse(rng, 4, 10)
    w = rng.randn(10, 6).astype(np.float32)
    sp = SparseTensor.from_dense(dense, capacity=50)
    assert_close(np.asarray(sparse_dense_matmul(sp, w)), dense @ w, atol=1e-5)


def test_sparse_linear_matches_linear(rng):
    import jax

    from bigdl_tpu.nn import Linear, SparseLinear
    from bigdl_tpu.tensor import SparseTensor

    B, IN, OUT = 4, 12, 5
    dense = _random_sparse(rng, B, IN)
    sl = SparseLinear(IN, OUT)
    sl._ensure_params()
    out = sl.forward(SparseTensor.from_dense(dense, capacity=64))

    dl = Linear(IN, OUT)
    dl.params = sl.params
    dl.state = {}
    dl._ensure_params()
    want = dl.forward(dense)
    assert_close(np.asarray(out), np.asarray(want), atol=1e-5)

    # weight gradient flows through the segment-sum formulation
    def loss(p):
        o, _ = sl.apply(p, SparseTensor.from_dense(dense, capacity=64), {})
        return (o ** 2).sum()

    g = jax.grad(loss)(sl.params)
    assert np.all(np.isfinite(np.asarray(g["weight"])))
    assert float(np.abs(np.asarray(g["weight"])).sum()) > 0


def test_sparse_join_table(rng):
    from bigdl_tpu.nn import SparseJoinTable
    from bigdl_tpu.tensor import SparseTensor

    a = _random_sparse(rng, 3, 4)
    b = _random_sparse(rng, 3, 6)
    sj = SparseJoinTable(dimension=2)
    out = sj.forward([SparseTensor.from_dense(a, capacity=20),
                      SparseTensor.from_dense(b, capacity=20)])
    assert_close(np.asarray(out.to_dense()), np.concatenate([a, b], axis=1))


def test_sparse_tensor_is_pytree(rng):
    import jax

    from bigdl_tpu.tensor import SparseTensor, sparse_dense_matmul

    dense = _random_sparse(rng, 3, 8)
    sp = SparseTensor.from_dense(dense, capacity=30)
    w = rng.randn(8, 4).astype(np.float32)

    f = jax.jit(lambda sp, w: sparse_dense_matmul(sp, w))
    assert_close(np.asarray(f(sp, w)), dense @ w, atol=1e-5)


def test_sparse_math_breadth():
    """SparseTensorMath surface (round-2): transpose, scalar ops, sums,
    narrow, elementwise, mm/mv/addmm/addmv, dense x sparse — all against
    the dense oracle, all jit-safe."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.tensor.sparse import (
        SparseTensor, dense_sparse_matmul, sparse_addmm, sparse_addmv,
    )

    rs = np.random.RandomState(11)
    dense = rs.randn(5, 7).astype(np.float32)
    dense[rs.rand(5, 7) < 0.6] = 0.0
    sp = SparseTensor.from_dense(dense, capacity=32)

    # transpose / scalar / sums
    assert_close(np.asarray(sp.t().to_dense()), dense.T)
    assert_close(np.asarray(sp.mul(2.5).to_dense()), dense * 2.5)
    assert_close(np.asarray(sp.div(2.0).to_dense()), dense / 2.0)
    assert abs(float(sp.sum()) - dense.sum()) < 1e-5
    # same dim semantics as dense Tensor.sum: reduce OVER dim
    assert_close(np.asarray(sp.sum(1)), dense.sum(0), atol=1e-5)
    assert_close(np.asarray(sp.sum(2)), dense.sum(1), atol=1e-5)

    # narrow (1-based), jit-safe
    nar = jax.jit(lambda s: s.narrow(1, 2, 3))(sp)
    assert_close(np.asarray(nar.to_dense()), dense[1:4])
    nar2 = sp.narrow(2, 3, 4)
    assert_close(np.asarray(nar2.to_dense()), dense[:, 2:6])

    # elementwise / vdot
    other = rs.randn(5, 7).astype(np.float32)
    assert_close(np.asarray(sp.cmul_dense(other).to_dense()), dense * other,
                 atol=1e-5)
    assert abs(float(sp.vdot(other)) - (dense * other).sum()) < 1e-4

    # mm / mv / addmm / addmv / dense x sparse
    m = rs.randn(7, 3).astype(np.float32)
    v = rs.randn(7).astype(np.float32)
    c = rs.randn(5, 3).astype(np.float32)
    y = rs.randn(5).astype(np.float32)
    assert_close(np.asarray(sp.mm(m)), dense @ m, atol=1e-5)
    assert_close(np.asarray(sp.mv(v)), dense @ v, atol=1e-5)
    assert_close(np.asarray(sparse_addmm(0.5, c, 2.0, sp, m)),
                 0.5 * c + 2.0 * (dense @ m), atol=1e-5)
    assert_close(np.asarray(sparse_addmv(0.5, y, 2.0, sp, v)),
                 0.5 * y + 2.0 * (dense @ v), atol=1e-5)
    n = rs.randn(4, 5).astype(np.float32)
    assert_close(np.asarray(dense_sparse_matmul(n, sp)), n @ dense,
                 atol=1e-5)
    assert_close(np.asarray(sp.add_to_dense(jnp.asarray(other))),
                 other + dense, atol=1e-5)
