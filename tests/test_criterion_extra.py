"""Extended criterions vs torch oracles / closed forms (SURVEY.md §2.2)."""

import numpy as np

from tests.oracle import assert_close


def test_cosine_embedding_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import CosineEmbeddingCriterion

    x1 = rng.randn(5, 8).astype(np.float32)
    x2 = rng.randn(5, 8).astype(np.float32)
    y = np.array([1, -1, 1, -1, 1], np.float32)
    crit = CosineEmbeddingCriterion(margin=0.3)
    loss = crit.forward([x1, x2], y)
    t = torch.nn.CosineEmbeddingLoss(margin=0.3)(
        torch.from_numpy(x1), torch.from_numpy(x2), torch.from_numpy(y))
    assert abs(loss - float(t)) < 1e-5


def test_hinge_embedding_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import HingeEmbeddingCriterion

    x = np.abs(rng.randn(6).astype(np.float32))
    y = np.array([1, -1, 1, -1, -1, 1], np.float32)
    crit = HingeEmbeddingCriterion(margin=1.0)
    loss = crit.forward(x, y)
    t = torch.nn.HingeEmbeddingLoss(margin=1.0)(
        torch.from_numpy(x), torch.from_numpy(y))
    assert abs(loss - float(t)) < 1e-5


def test_margin_ranking_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import MarginRankingCriterion

    x1 = rng.randn(7).astype(np.float32)
    x2 = rng.randn(7).astype(np.float32)
    y = np.sign(rng.randn(7)).astype(np.float32)
    crit = MarginRankingCriterion(margin=0.2)
    loss = crit.forward([x1, x2], y)
    t = torch.nn.MarginRankingLoss(margin=0.2)(
        torch.from_numpy(x1), torch.from_numpy(x2), torch.from_numpy(y))
    assert abs(loss - float(t)) < 1e-5


def test_multi_margin_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import MultiMarginCriterion

    x = rng.randn(4, 6).astype(np.float32)
    y = np.array([1, 3, 6, 2], np.float32)  # 1-based
    for p in (1, 2):
        crit = MultiMarginCriterion(p=p)
        loss = crit.forward(x, y)
        t = torch.nn.MultiMarginLoss(p=p)(
            torch.from_numpy(x), torch.from_numpy(y).long() - 1)
        assert abs(loss - float(t)) < 1e-5, f"p={p}"

    # gradient parity
    crit = MultiMarginCriterion()
    gin = crit.backward(x, y)
    xt = torch.from_numpy(x).requires_grad_(True)
    torch.nn.MultiMarginLoss()(xt, torch.from_numpy(y).long() - 1).backward()
    assert_close(np.asarray(gin), xt.grad.numpy(), atol=1e-5)


def test_multilabel_margin_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import MultiLabelMarginCriterion

    x = rng.randn(3, 5).astype(np.float32)
    # 1-based targets, 0-terminated (torch uses 0-based with -1 terminator)
    t_ours = np.array([[2, 4, 0, 0, 0],
                       [1, 0, 0, 0, 0],
                       [3, 5, 1, 0, 0]], np.float32)
    t_torch = torch.from_numpy(
        np.array([[1, 3, -1, -1, -1],
                  [0, -1, -1, -1, -1],
                  [2, 4, 0, -1, -1]], np.int64))
    crit = MultiLabelMarginCriterion()
    loss = crit.forward(x, t_ours)
    t = torch.nn.MultiLabelMarginLoss()(torch.from_numpy(x), t_torch)
    assert abs(loss - float(t)) < 1e-5


def test_l1cost_and_softmaxwith(rng):
    import torch

    from bigdl_tpu.nn import L1Cost, SoftmaxWithCriterion

    x = rng.randn(4, 5).astype(np.float32)
    assert abs(L1Cost().forward(x, None) - np.abs(x).sum()) < 1e-4

    y = np.array([1, 2, 3, 4], np.float32)
    loss = SoftmaxWithCriterion().forward(x, y)
    t = torch.nn.CrossEntropyLoss()(
        torch.from_numpy(x), torch.from_numpy(y).long() - 1)
    assert abs(loss - float(t)) < 1e-5


def test_dice_closed_form(rng):
    from bigdl_tpu.nn import DiceCoefficientCriterion

    x = rng.rand(2, 10).astype(np.float32)
    t = (rng.rand(2, 10) > 0.5).astype(np.float32)
    eps = 1.0
    want = np.mean([
        1 - (2 * (x[i] * t[i]).sum() + eps) / (x[i].sum() + t[i].sum() + eps)
        for i in range(2)
    ])
    got = DiceCoefficientCriterion(epsilon=eps).forward(x, t)
    assert abs(got - want) < 1e-5


def test_multi_criterion(rng):
    from bigdl_tpu.nn import AbsCriterion, MSECriterion, MultiCriterion

    x = rng.randn(3, 4).astype(np.float32)
    t = rng.randn(3, 4).astype(np.float32)
    mc = MultiCriterion().add(MSECriterion(), 0.5).add(AbsCriterion(), 2.0)
    want = 0.5 * MSECriterion().forward(x, t) + 2.0 * AbsCriterion().forward(x, t)
    assert abs(mc.forward(x, t) - want) < 1e-5


def test_kld_gaussian_closed_form(rng):
    from bigdl_tpu.nn import GaussianCriterion, KLDCriterion

    mean = rng.randn(4, 3).astype(np.float32)
    log_var = rng.randn(4, 3).astype(np.float32) * 0.3
    t = rng.randn(4, 3).astype(np.float32)

    kl = KLDCriterion().forward([mean, log_var], None)
    want_kl = (-0.5 * (1 + log_var - mean ** 2 - np.exp(log_var)).sum()) / 4
    assert abs(kl - want_kl) < 1e-4

    nll = GaussianCriterion().forward([mean, log_var], t)
    want = 0.5 * (np.log(2 * np.pi) + log_var
                  + (t - mean) ** 2 / np.exp(log_var)).sum()
    assert abs(nll - want) < 1e-3


def test_cosine_distance_criterion(rng):
    from bigdl_tpu.nn import CosineDistanceCriterion

    x = rng.randn(5, 6).astype(np.float32)
    t = rng.randn(5, 6).astype(np.float32)
    cos = (x * t).sum(-1) / (np.linalg.norm(x, axis=-1)
                             * np.linalg.norm(t, axis=-1))
    want = (1 - cos).mean()
    assert abs(CosineDistanceCriterion().forward(x, t) - want) < 1e-5


def test_softmargin_vs_torch(rng):
    import torch

    from bigdl_tpu.nn import SoftMarginCriterion

    x = rng.randn(4, 6).astype(np.float32)
    y = np.sign(rng.randn(4, 6)).astype(np.float32)
    loss = SoftMarginCriterion().forward(x, y)
    t = torch.nn.SoftMarginLoss()(torch.from_numpy(x), torch.from_numpy(y))
    assert abs(loss - float(t)) < 1e-5


def test_cosine_proximity(rng):
    from bigdl_tpu.nn import CosineProximityCriterion

    x = rng.randn(5, 8).astype(np.float32)
    t = rng.randn(5, 8).astype(np.float32)
    cos = (x * t).sum(-1) / (np.linalg.norm(x, axis=-1)
                             * np.linalg.norm(t, axis=-1))
    assert abs(CosineProximityCriterion().forward(x, t) + cos.mean()) < 1e-5


def test_class_simplex_criterion(rng):
    from bigdl_tpu.nn import ClassSimplexCriterion

    C = 4
    crit = ClassSimplexCriterion(C)
    # vertices are unit-norm with equal pairwise dot products
    v = crit._simplex
    norms = np.linalg.norm(v, axis=1)
    np.testing.assert_allclose(norms, 1.0, atol=1e-6)
    dots = v @ v.T
    off = dots[~np.eye(C, dtype=bool)]
    assert np.allclose(off, off[0], atol=1e-6)
    # loss is zero exactly at the target vertex
    y = np.array([2.0])
    assert crit.forward(v[1][None], y) < 1e-10
    assert crit.forward(np.zeros((1, C), np.float32), y) > 0


def test_softmargin_stable_large_logits():
    from bigdl_tpu.nn import SoftMarginCriterion

    loss = SoftMarginCriterion().forward(
        np.array([[100.0]], np.float32), np.array([[-1.0]], np.float32))
    assert np.isfinite(loss) and abs(loss - 100.0) < 1e-3


def test_masked_softmax_ce_matches_unfused(rng):
    """MaskedSoftmaxCECriterion (fused, from logits) must equal
    TimeDistributedMaskCriterion(CrossEntropyCriterion) on the same
    logits, including padding masking — identical math, fused lowering."""
    import jax.numpy as jnp

    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.nn.criterion_more import (
        MaskedSoftmaxCECriterion, TimeDistributedMaskCriterion,
    )

    B, T, V = 3, 5, 11
    logits = rng.randn(B, T, V).astype(np.float32) * 2.0
    tg = rng.randint(1, V + 1, size=(B, T)).astype(np.float32)
    tg[0, 2] = 0.0  # padded step
    tg[2, 4] = 0.0

    fused = MaskedSoftmaxCECriterion(padding_value=0)
    unfused = TimeDistributedMaskCriterion(CrossEntropyCriterion(),
                                           padding_value=0)
    a = float(fused.apply(jnp.asarray(logits), jnp.asarray(tg)))
    b = float(unfused.apply(jnp.asarray(logits), jnp.asarray(tg)))
    assert abs(a - b) < 1e-5, (a, b)


def test_masked_softmax_ce_gradient_matches(rng):
    """Backward parity: d loss / d logits of the fused CE equals the
    unfused pipeline's (softmax - onehot scaled by mask/count)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.criterion import CrossEntropyCriterion
    from bigdl_tpu.nn.criterion_more import (
        MaskedSoftmaxCECriterion, TimeDistributedMaskCriterion,
    )

    B, T, V = 2, 4, 7
    logits = jnp.asarray(rng.randn(B, T, V).astype(np.float32))
    tg = jnp.asarray(rng.randint(1, V + 1, size=(B, T)).astype(np.float32)
                     * (rng.rand(B, T) > 0.2))

    fused = MaskedSoftmaxCECriterion(padding_value=0)
    unfused = TimeDistributedMaskCriterion(CrossEntropyCriterion(),
                                           padding_value=0)
    ga = jax.grad(lambda x: fused.apply(x, tg))(logits)
    gb = jax.grad(lambda x: unfused.apply(x, tg))(logits)
    np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                               rtol=1e-4, atol=1e-6)
