"""Pallas flash attention vs the dense jnp oracle (differential-testing
pattern, SURVEY.md §4). Runs in Pallas interpreter mode on the CPU backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.oracle import assert_close
from bigdl_tpu.ops import flash_attention
from bigdl_tpu.ops.flash_attention import (flash_attention_block_grads,
                                           flash_attention_with_lse)
from bigdl_tpu.parallel.ring_attention import attention


def _qkv(b=2, t=100, h=4, d=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    o_flash = flash_attention(q, k, v, causal=causal, block=32)
    o_dense = attention(q, k, v, causal=causal)
    np.testing.assert_allclose(o_flash, o_dense, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_dense(causal):
    q, k, v = _qkv(t=64)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    gf = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block=32)), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss(lambda q, k, v: attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


def test_unpadded_block_multiple():
    q, k, v = _qkv(t=64)
    np.testing.assert_allclose(
        flash_attention(q, k, v, block=32), attention(q, k, v),
        atol=2e-5, rtol=2e-5)


def test_mha_layer_flash_path_matches_dense():
    from bigdl_tpu.nn import MultiHeadAttention
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(5)
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 48, 64)),
                    jnp.float32)
    m1 = MultiHeadAttention(64, 4, causal=True, use_flash="always")
    m1._ensure_params()
    m2 = MultiHeadAttention(64, 4, causal=True, use_flash="never")
    m2.params, m2.state = m1.params, m1.state
    y1, _ = m1.apply(m1.params, x, m1.state)
    y2, _ = m2.apply(m2.params, x, m2.state)
    np.testing.assert_allclose(y1, y2, atol=2e-4, rtol=2e-4)


def test_flash_vmem_stays_blocked_at_long_seq():
    """Regression for the VMEM blow-up: at T=4096 (32 blocks of 128) the
    kernels must only keep O(block) tiles resident — verified by running the
    full fwd+bwd in interpret/compiled mode without materializing (T, T)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(3)
    B, T, H, D = 1, 4096, 2, 64
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

    loss, grads = jax.value_and_grad(
        lambda q: jnp.sum(flash_attention(q, k, v, causal=True) ** 2))(q), None
    assert np.isfinite(float(loss[0] if isinstance(loss, tuple) else loss))


def test_flash_cross_attention_different_kv_len():
    """q and kv lengths may differ (ring blocks); dk/dv shapes follow kv."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((2, 100, 2, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 260, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 260, 2, 32)), jnp.float32)
    out = flash_attention(q, k, v)
    assert out.shape == (2, 100, 2, 32)

    # parity vs dense
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(32)
    want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v)
    assert_close(np.asarray(out), np.asarray(want), atol=2e-3)

    grads = jax.grad(lambda k: jnp.sum(flash_attention(q, k, v) ** 2))(k)
    assert grads.shape == k.shape
    assert np.isfinite(np.asarray(grads)).all()


def test_causal_offset_without_causal_raises():
    """ADVICE r2: causal_offset with causal=False was silently ignored."""
    q = jnp.zeros((1, 8, 1, 4))
    with pytest.raises(ValueError, match="causal_offset requires"):
        flash_attention_with_lse(q, q, q, causal_offset=-1)
    lse = jnp.zeros((1, 1, 8))
    with pytest.raises(ValueError, match="causal_offset requires"):
        flash_attention_block_grads(q, q, q, q, lse, q, causal_offset=-1)
