"""Per-row token-mask constrained decoding (bigdl_tpu/serving/
constrain.py): TokenDFA/cursor semantics and validation, the wire meta
round trip, forced-template output, the permissive-mask identity
contract, fixed-seed replay through evict/readmit, zero extra compiles
for mixed constrained/unconstrained traffic, and parity on the
speculative and disaggregated planes."""

import numpy as np
import pytest


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


# -- automaton unit surface -------------------------------------------------

def test_dfa_validation():
    from bigdl_tpu.serving import TokenDFA, fixed_sequence, from_token_sets

    with pytest.raises(ValueError, match="at least one state"):
        TokenDFA([])
    with pytest.raises(ValueError, match="1-based"):
        TokenDFA([(frozenset({0}), {}, None)])
    with pytest.raises(ValueError, match="leaves the DFA"):
        TokenDFA([(None, {3: 7}, None)])
    with pytest.raises(ValueError, match="allow-set"):
        TokenDFA([(frozenset({2}), {3: 0}, None)])
    with pytest.raises(ValueError, match="out of range"):
        TokenDFA([(None, {}, 5)])
    with pytest.raises(ValueError, match="start"):
        TokenDFA([(None, {}, None)], start=2)
    with pytest.raises(ValueError):
        fixed_sequence([])
    with pytest.raises(ValueError):
        fixed_sequence([0, 3])
    with pytest.raises(ValueError):
        from_token_sets([])


def test_cursor_advance_and_mask():
    from bigdl_tpu.serving import (
        ConstraintError, fixed_sequence, from_token_sets)

    dfa = fixed_sequence([4, 9])
    cur = dfa.cursor()
    assert cur.allow == frozenset({4})
    row = cur.mask_row(6)
    assert row.tolist() == [False, False, False, True, False, False]
    cur.advance(4)
    assert cur.allow == frozenset({9})
    with pytest.raises(ConstraintError):
        cur.advance(5)                       # not allowed here
    cur.advance(9)
    assert cur.allow is None                 # exhausted: unconstrained
    assert cur.mask_row(6).all()
    # the replay rule: cursor(prefix) == advance token-by-token
    assert dfa.cursor([4, 9]).state == cur.state
    # in-place write into an engine knob row
    out = np.zeros((6,), bool)
    assert from_token_sets([[2, 5]]).cursor().mask_row(6, out=out) is out
    assert out.tolist() == [False, True, False, False, True, False]
    # ids beyond the vocab are simply absent from the mask
    assert fixed_sequence([99]).cursor().mask_row(6).sum() == 0


def test_dfa_meta_roundtrip():
    import json

    from bigdl_tpu.serving import TokenDFA, from_token_sets

    dfa = from_token_sets([[3, 1], None, [7]])
    meta = json.loads(json.dumps(dfa.to_meta()))     # real JSON round trip
    back = TokenDFA.from_meta(meta)
    assert back.states == dfa.states and back.start == dfa.start


def test_submit_validates_constraint(lm):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2)
    with pytest.raises(ValueError, match="constraint"):
        eng.submit([3, 2], max_new_tokens=2, constraint=object())


# -- engine contracts -------------------------------------------------------

def test_fixed_sequence_forces_output(lm):
    """The template constraint overrides whatever the model prefers —
    greedy and sampled rows both emit exactly the forced ids, then
    decode free."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine, \
        fixed_sequence

    eng = ServingEngine(lm, n_slots=2, seed=11)
    forced = [4, 9, 2]
    r0 = eng.submit([3, 7], max_new_tokens=5,
                    constraint=fixed_sequence(forced))
    r1 = eng.submit([3, 7], max_new_tokens=5,
                    sampling=SamplingParams(temperature=0.9, top_k=10,
                                            seed=42),
                    constraint=fixed_sequence(forced))
    outs = eng.drain()
    assert list(outs[r0])[:3] == forced
    assert list(outs[r1])[:3] == forced


def test_permissive_mask_is_identity(lm):
    """A constraint that allows the full vocabulary at every position
    leaves greedy AND fixed-seed sampled streams token-identical to the
    unconstrained engine — the mask path is exact, not approximate."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine, \
        from_token_sets

    V = 29
    sp = SamplingParams(temperature=0.8, top_k=10, seed=77)
    base = ServingEngine(lm, n_slots=2, seed=11)
    b0 = base.submit([3, 7, 2], max_new_tokens=8)
    b1 = base.submit([5, 1], max_new_tokens=8, sampling=sp)
    want = base.drain()

    eng = ServingEngine(lm, n_slots=2, seed=11)
    full = from_token_sets([list(range(1, V + 1))] * 8)
    c0 = eng.submit([3, 7, 2], max_new_tokens=8, constraint=full)
    c1 = eng.submit([5, 1], max_new_tokens=8, sampling=sp,
                    constraint=full)
    got = eng.drain()
    np.testing.assert_array_equal(want[b0], got[c0])
    np.testing.assert_array_equal(want[b1], got[c1])
    np.testing.assert_array_equal(base.logprobs(b1), eng.logprobs(c1))


def test_mixed_traffic_zero_extra_compiles(lm):
    """Unconstrained-only traffic, then mixed constrained traffic, on
    one engine: zero new decode or prefill programs — the mask is a
    runtime knob row."""
    from tests.compile_guards import assert_compile_count, compile_count

    from bigdl_tpu.serving import ServingEngine, fixed_sequence

    eng = ServingEngine(lm, n_slots=2, seed=11)
    eng.submit([3, 7, 2], max_new_tokens=4)
    eng.submit([5, 1], max_new_tokens=4)
    eng.drain()
    decode0 = compile_count(eng._step_fn)
    prefill0 = compile_count(eng._batch_prefill_fn)
    assert decode0 == 1

    eng.submit([3, 7, 2], max_new_tokens=4,
               constraint=fixed_sequence([4, 9]))
    eng.submit([5, 1], max_new_tokens=4)
    eng.drain()
    assert_compile_count(eng._step_fn, decode0, what="mixed decode")
    assert_compile_count(eng._batch_prefill_fn, prefill0,
                         what="mixed prefill")


def test_constrained_replay_through_preemption(lm):
    """A fixed-seed constrained stream evicted mid-template resumes
    draw-for-draw: the cursor is rebuilt from the emitted prefix at
    readmission (never checkpointed), so the mask at every step is
    identical to the uncontended run."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine, \
        from_token_sets

    cons = from_token_sets([[4, 9, 2], None, [1, 2, 3], None, [7, 8]])
    sp = SamplingParams(temperature=0.9, top_k=10, seed=31)

    base = ServingEngine(lm, n_slots=2)
    r0 = base.submit([3, 7, 2, 9, 4], max_new_tokens=10, sampling=sp,
                     constraint=cons)
    want = base.drain()[r0]

    eng = ServingEngine(lm, n_slots=1, policy="priority")
    r1 = eng.submit([3, 7, 2, 9, 4], max_new_tokens=10, sampling=sp,
                    constraint=cons, priority=0)
    for _ in range(3):
        eng.step()
    eng.submit([5, 5], max_new_tokens=2, priority=5)   # forces eviction
    outs = eng.drain()
    assert eng.request(r1).preemptions >= 1
    np.testing.assert_array_equal(outs[r1], want)


# -- composition: speculative + disagg --------------------------------------

def test_constrained_rows_on_speculative_engine(lm):
    """Constrained rows on a speculative engine emit one token per
    super-step (their draft budget is forced to 0 — the mask is
    per-position) and match the non-speculative engine token for
    token; unconstrained rows keep drafting."""
    from bigdl_tpu.serving import ServingEngine, SpeculativeConfig, \
        fixed_sequence

    draft = _make_lm(hidden=16, heads=2, layers=1, seed=21)
    cons = fixed_sequence([4, 9, 2])

    base = ServingEngine(lm, n_slots=2, seed=7)
    b0 = base.submit([3, 7], max_new_tokens=6, constraint=cons)
    b1 = base.submit([5, 1, 8], max_new_tokens=6)
    want = base.drain()

    se = ServingEngine(lm, n_slots=2, seed=7,
                       speculative=SpeculativeConfig(draft, k=3))
    s0 = se.submit([3, 7], max_new_tokens=6, constraint=cons)
    s1 = se.submit([5, 1, 8], max_new_tokens=6)
    got = se.drain()
    np.testing.assert_array_equal(want[b0], got[s0])
    np.testing.assert_array_equal(want[b1], got[s1])


@pytest.mark.disagg
def test_constraint_crosses_the_wire(lm):
    """Constrained requests through the disaggregated plane — prefill
    pool, KV handoff, decode pool, and a mid-stream pool kill — land
    token-identical to the monolithic engine: the automaton rides the
    wire as meta, the cursor is rebuilt from the emitted prefix."""
    from bigdl_tpu.serving import (
        DisaggregatedEngine, SamplingParams, ServingEngine,
        from_token_sets)
    from bigdl_tpu.serving.disagg import request_from_meta, request_meta
    from bigdl_tpu.serving.scheduler import Request

    cons = from_token_sets([[4, 9, 2], None, [1, 2, 3]])
    # wire meta round trip preserves the automaton
    req = Request(req_id=5, prompt=[3], max_new_tokens=4,
                  constraint=cons)
    back = request_from_meta(request_meta(req))
    assert back.constraint.states == cons.states

    sp = SamplingParams(temperature=0.8, top_k=10, seed=40)
    mono = ServingEngine(lm, n_slots=4, seed=7)
    m0 = mono.submit([3, 7, 2], max_new_tokens=8, sampling=sp,
                     constraint=cons)
    m1 = mono.submit([5, 1, 8], max_new_tokens=8)
    want = mono.drain()

    d = DisaggregatedEngine(lm, prefill_slots=2, decode_slots=2,
                            decode_pools=2, seed=7)
    d0 = d.submit([3, 7, 2], max_new_tokens=8, sampling=sp,
                  constraint=cons)
    d1 = d.submit([5, 1, 8], max_new_tokens=8)
    for _ in range(3):
        d.step()
    d.kill_pool(0)
    got = d.drain()
    np.testing.assert_array_equal(want[m0], got[d0])
    np.testing.assert_array_equal(want[m1], got[d1])
