"""Speculative decoding (bigdl_tpu/serving/speculative.py +
make_batch_verify_step): greedy token-identity with the baseline engine
and generate() (with GOOD and with GARBAGE drafts — the emitted stream
is draft-independent by construction), fixed-seed replay of rejection
sampling across speculative/normal engines, eviction/readmission, and
batched-vs-per_request admission, the one-verify-program compile guard
for mixed speculative/normal traffic, stop machinery through multi-token
chunks, KV-rollback/pool invariants, accept-rate metrics, the sharded
plane, and the bench smoke."""

import numpy as np
import pytest


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models.transformer import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                      n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    """The target model — one per module so every engine shares the
    cached jitted steps."""
    return _make_lm()


@pytest.fixture(scope="module")
def good_draft():
    """A weight-tied draft (same seed, same config): proposals track the
    target's greedy path, so acceptance is high — the 'trained draft'
    stand-in untrained bench models allow."""
    return _make_lm()


@pytest.fixture(scope="module")
def bad_draft():
    """An uncorrelated draft (different seed/width/depth): proposals are
    noise, acceptance ~0 — correctness must not care."""
    return _make_lm(seed=31, hidden=16, heads=2, layers=1)


def _spec(draft, k=3):
    from bigdl_tpu.serving import SpeculativeConfig

    return SpeculativeConfig(draft, k=k)


# -- config validation ------------------------------------------------------

def test_speculative_config_validation(lm, good_draft):
    from bigdl_tpu.serving import ServingEngine, SpeculativeConfig

    with pytest.raises(ValueError, match="k must be >= 1"):
        SpeculativeConfig(good_draft, k=0)
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(lm, n_slots=2,
                      speculative=_spec(_make_lm(V=17, seed=3)))
    with pytest.raises(ValueError, match="max_len"):
        ServingEngine(lm, n_slots=2,
                      speculative=_spec(_make_lm(max_len=24, seed=3)))
    eng = ServingEngine(lm, n_slots=2, speculative=_spec(good_draft))
    with pytest.raises(ValueError, match="draft_tokens"):
        eng.submit([3], max_new_tokens=4, draft_tokens=-1)


# -- greedy token identity (THE acceptance contract) ------------------------

@pytest.mark.parametrize("which", ["good", "bad"])
def test_greedy_spec_matches_generate(which, lm, good_draft, bad_draft,
                                      rng):
    """Greedy speculative output is token-identical to sequential
    generate(temperature=0) — with a high-acceptance draft AND with a
    garbage draft (a wrong draft costs steps, never tokens)."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    draft = good_draft if which == "good" else bad_draft
    eng = ServingEngine(lm, n_slots=3, speculative=_spec(draft))
    reqs = []
    for _ in range(5):
        plen = int(rng.randint(1, 6))
        reqs.append((rng.randint(1, 30, size=(plen,)).tolist(),
                     int(rng.randint(3, 10))))
    rids = [eng.submit(p, max_new_tokens=n) for p, n in reqs]
    outs = eng.drain()
    for rid, (p, n) in zip(rids, reqs):
        want = generate(lm, p, length=n, temperature=0.0)
        np.testing.assert_array_equal(outs[rid], want,
                                      err_msg=f"prompt={p} draft={which}")
    assert eng.pool.free_slots == eng.pool.n_slots


def test_greedy_spec_matches_baseline_engine_bf16(lm, good_draft):
    """bf16 serving dtype through the speculative engine equals the
    bf16 baseline engine token for token (greedy)."""
    import jax.numpy as jnp

    from bigdl_tpu.serving import ServingEngine

    reqs = [([3, 7, 2], 8), ([5], 6), ([9, 1, 4], 7)]
    base = ServingEngine(lm, n_slots=3, compute_dtype=jnp.bfloat16)
    rb = [base.submit(p, max_new_tokens=n) for p, n in reqs]
    outs_b = base.drain()
    spec = ServingEngine(lm, n_slots=3, compute_dtype=jnp.bfloat16,
                         speculative=_spec(good_draft))
    rs = [spec.submit(p, max_new_tokens=n) for p, n in reqs]
    outs_s = spec.drain()
    for a, b in zip(rb, rs):
        np.testing.assert_array_equal(outs_b[a], outs_s[b])


def test_greedy_spec_matches_baseline_engine_int8(lm, good_draft,
                                                  bad_draft):
    """int8-KV speculative vs the int8-KV baseline engine (greedy,
    pinned config), good AND garbage drafts. Since the accepted-only
    scale merge, draft-independence is EXACT on the int8 cache too:
    the verify step's chunk attention reads float chunk K/V and the
    quantized scatter + grow-only merge cover accepted columns only,
    so a rejected draft touches neither scales nor stored bytes (the
    byte-level pin lives in tests/test_serving_kv_quant.py::
    test_int8_draft_independence_exact); spec-vs-BASELINE parity
    remains a pinned-config contract (the chunked step sees its own
    K/V unrounded where plain decode reads the roundtripped write —
    a sub-quantum numerics difference near-tied argmaxes could
    notice)."""
    from bigdl_tpu.serving import ServingEngine

    rng = np.random.RandomState(11)
    reqs = [(rng.randint(1, 30,
                         size=(int(rng.randint(1, 7)),)).tolist(),
             int(rng.randint(4, 11))) for _ in range(6)]
    outs = {}
    for name, spec in (("base", None), ("good", _spec(good_draft)),
                       ("bad", _spec(bad_draft))):
        eng = ServingEngine(lm, n_slots=3, kv_dtype="int8",
                            speculative=spec)
        rids = [eng.submit(p, max_new_tokens=n) for p, n in reqs]
        drained = eng.drain()
        outs[name] = [list(drained[r]) for r in rids]
    assert outs["good"] == outs["base"]
    assert outs["bad"] == outs["base"]


# -- fixed-seed replay of rejection sampling --------------------------------

def test_seed_replay_across_spec_and_normal_engines(lm, good_draft,
                                                    bad_draft):
    """A fixed-seed sampled request emits ONE stream: through the plain
    engine, through a speculative engine (good or garbage draft, mixed
    with normal draft_tokens=0 neighbors), and after readmission into a
    recycled slot — the verify step's draws ride the same RNG lane
    splits the baseline sampler consumes, and the lane advances by
    exactly the emitted count."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    sp = SamplingParams(temperature=0.9, top_k=8, top_p=0.95, seed=123)
    prompt = [3, 7, 2]

    base = ServingEngine(lm, n_slots=3)
    rb = base.submit(prompt, max_new_tokens=8, sampling=sp)
    want = base.drain()[rb]

    for draft in (good_draft, bad_draft):
        eng = ServingEngine(lm, n_slots=3, speculative=_spec(draft))
        r = eng.submit(prompt, max_new_tokens=8, sampling=sp)
        eng.submit([4, 4], max_new_tokens=5, draft_tokens=0,
                   sampling=SamplingParams(temperature=1.3, seed=7))
        eng.submit([9], max_new_tokens=8)
        np.testing.assert_array_equal(eng.drain()[r], want)

    # eviction/readmission: a 1-slot engine recycles slot 0 from a
    # previous occupant — the replay survives because lanes are
    # request-keyed and the draft cache re-prefills from the prompt
    eng1 = ServingEngine(lm, n_slots=1, speculative=_spec(bad_draft, k=2))
    eng1.submit([1, 2], max_new_tokens=3,
                sampling=SamplingParams(temperature=1.1, seed=55))
    eng1.drain()
    r2 = eng1.submit(prompt, max_new_tokens=8, sampling=sp)
    np.testing.assert_array_equal(eng1.drain()[r2], want)


def test_seed_replay_across_admission_modes(lm, good_draft):
    """batched vs per_request admission feed the SAME speculative
    stream (the draft prefill rides slot configuration, not the
    admission pipeline)."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    sp = SamplingParams(temperature=0.8, top_k=6, seed=77)
    outs = []
    for admission in ("batched", "per_request"):
        eng = ServingEngine(lm, n_slots=2, admission=admission,
                            speculative=_spec(good_draft))
        r = eng.submit([5, 9, 2, 2], max_new_tokens=7, sampling=sp)
        eng.submit([1], max_new_tokens=4)
        outs.append(eng.drain()[r])
    np.testing.assert_array_equal(outs[0], outs[1])


# -- compile-count guard ----------------------------------------------------

def test_mixed_spec_traffic_one_verify_program(lm, good_draft):
    """Mixed speculative traffic — greedy speculative, sampled, normal
    draft_tokens=0, budget-capped tails, several admission waves — adds
    ZERO verify-program compiles: per-row draft length is runtime data
    of one fixed-width program, exactly as knob mixes are for the
    decode step. (The fresh-model 1-verify-vs-1-decode equality with a
    plain engine is pinned by test_speculative_bench_smoke, where each
    engine owns a private step cache.)"""
    from bigdl_tpu.serving import SamplingParams, ServingEngine
    from tests.compile_guards import assert_compile_count, compile_count

    eng = ServingEngine(lm, n_slots=3, speculative=_spec(good_draft))
    eng.submit([3, 7, 2], max_new_tokens=6)
    eng.drain()
    # the (n_slots, width) shape is traced now; everything after is mix
    base_v = compile_count(eng._spec.verify_fn)
    base_d = compile_count(eng._spec._draft_step_fn)
    eng.submit([3, 7, 2], max_new_tokens=6)
    eng.submit([5], max_new_tokens=4, draft_tokens=0)
    eng.submit([9, 1], max_new_tokens=5, sampling=SamplingParams(
        temperature=0.8, top_k=5, seed=1))
    eng.drain()
    # second wave with different mixes/budgets — still the same program
    eng.submit([2, 2], max_new_tokens=3, draft_tokens=1)
    eng.submit([8], max_new_tokens=9, sampling=SamplingParams(
        temperature=1.2, top_p=0.9, min_tokens=2, seed=2))
    eng.drain()
    assert_compile_count(eng._spec.verify_fn, base_v,
                         what="speculative verify")
    assert_compile_count(eng._spec._draft_step_fn, base_d,
                         what="draft decode")


# -- stop machinery through chunks ------------------------------------------

def test_stop_conditions_truncate_chunks(lm, good_draft):
    """eos / stop tokens / stop sequences / min-tokens behave exactly
    like the baseline even when they fire MID-CHUNK: the emission loop
    applies the per-token finish rule in order and discards the chunk
    tail the baseline would never have sampled."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    free = generate(lm, [3, 7], length=8, temperature=0.0)
    eos = int(free[3])
    cut = int(np.where(free == eos)[0][0])

    eng = ServingEngine(lm, n_slots=2, speculative=_spec(good_draft))
    a = eng.submit([3, 7], max_new_tokens=8, eos_id=eos)
    b = eng.submit([3, 7], max_new_tokens=8)
    outs = eng.drain()
    np.testing.assert_array_equal(outs[a], free[:cut + 1])
    np.testing.assert_array_equal(outs[b], free)
    assert eng.request(a).done_reason == "eos"
    assert eng.request(b).done_reason == "length"

    st = int(free[2])
    c = eng.submit([3, 7], max_new_tokens=8,
                   sampling=SamplingParams(stop_token_ids=(st,)))
    outs = eng.drain()
    assert len(outs[c]) == 3 and outs[c][-1] == st
    assert eng.request(c).done_reason == "stop"

    seq = tuple(int(t) for t in free[1:3])
    d = eng.submit([3, 7], max_new_tokens=8,
                   sampling=SamplingParams(stop_sequences=(seq,)))
    outs = eng.drain()
    assert tuple(outs[d][-2:]) == seq and len(outs[d]) == 3

    # min_tokens: the chunk budget drops to 0 while the ban is up, so
    # the banned-eos window is served step-exactly like the baseline
    e = eng.submit([3, 7], max_new_tokens=8, eos_id=eos,
                   sampling=SamplingParams(min_tokens=6))
    outs = eng.drain()
    assert len(outs[e]) >= 6
    assert not np.any(np.asarray(outs[e][:5]) == eos)


# -- rollback / pool invariants ---------------------------------------------

def test_rollback_and_draft_pool_lifecycle(lm, bad_draft):
    """The accepted-prefix rollback keeps both position counters
    consistent: after a drain the pool is empty, target and draft pos
    reset with their slots, and a LONG generation through a
    high-rejection draft (max rollback churn) still matches
    generate()."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2, speculative=_spec(bad_draft, k=3))
    r = eng.submit([3, 7, 2], max_new_tokens=20)
    outs = eng.drain()
    np.testing.assert_array_equal(
        outs[r], generate(lm, [3, 7, 2], length=20, temperature=0.0))
    assert eng.pool.free_slots == eng.pool.n_slots
    assert not np.asarray(eng.pool.carry["pos"]).any()
    assert not np.asarray(eng.pool.draft_carry["pos"]).any()
    # draft-pool misuse raises like the target pool's
    with pytest.raises(ValueError, match="not allocated"):
        eng.pool.set_draft_pos(0, 3)


def test_cancel_running_mid_chunk(lm, good_draft):
    """Cancelling a RUNNING row between super-steps frees BOTH its
    target and draft slots (one allocator, two caches), freezes its
    output (no post-cancel tokens, ever), and leaves the engine
    serving its neighbors unperturbed — including a neighbor admitted
    into the recycled slot afterwards."""
    from bigdl_tpu.models.transformer import generate
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2, speculative=_spec(good_draft, k=3))
    a = eng.submit([3, 7, 2], max_new_tokens=20)
    b = eng.submit([5, 1], max_new_tokens=12)
    for _ in range(2):
        eng.step()                     # both rows mid-stream, chunks landed
    assert eng.cancel(a)
    frozen = list(eng.request(a).output)
    assert frozen and len(frozen) < 20
    assert eng.request(a).state == "cancelled"
    assert eng.pool.free_slots == 1    # a's slot (target+draft) freed NOW
    assert not eng.cancel(a)           # already cancelled: no-op
    # a recycled-slot admission decodes correctly next to the survivor
    c = eng.submit([9], max_new_tokens=5)
    outs = eng.drain()
    assert a not in outs               # cancelled rows never FINISH
    assert list(eng.request(a).output) == frozen
    np.testing.assert_array_equal(
        outs[b], generate(lm, [5, 1], length=12, temperature=0.0))
    np.testing.assert_array_equal(
        outs[c], generate(lm, [9], length=5, temperature=0.0))
    assert eng.pool.free_slots == eng.pool.n_slots
    assert not np.asarray(eng.pool.carry["pos"]).any()
    assert not np.asarray(eng.pool.draft_carry["pos"]).any()


def test_attach_draft_guards(lm, good_draft):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2, speculative=_spec(good_draft))
    with pytest.raises(ValueError, match="already attached"):
        eng.pool.attach_draft(eng._spec._draft_init)
    plain = ServingEngine(lm, n_slots=2)
    with pytest.raises(ValueError, match="no draft carry"):
        plain.pool.set_draft_pos(0, 0)


# -- metrics ----------------------------------------------------------------

def test_spec_metrics_accounting(lm, good_draft):
    """draft_tokens/accepted_tokens/spec_rows land per super-step and
    summary() derives accept_rate and tokens_per_step; emitted tokens
    = accepted + row-steps exactly (every row emits one non-draft draw
    per step)."""
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=2, speculative=_spec(good_draft))
    r1 = eng.submit([3, 7], max_new_tokens=8)
    r2 = eng.submit([5, 1], max_new_tokens=8)
    outs = eng.drain()
    s = eng.metrics.summary()
    n_acc, _ = eng.metrics.metrics.get("serving/accepted_tokens")
    n_rows, _ = eng.metrics.metrics.get("serving/spec_rows")
    n_draft, _ = eng.metrics.metrics.get("serving/draft_tokens")
    assert n_acc + n_rows == len(outs[r1]) + len(outs[r2])
    assert 0.0 <= s["serving/accept_rate"] <= 1.0
    assert s["serving/accept_rate"] == pytest.approx(n_acc / n_draft)
    assert s["serving/tokens_per_step"] > 1.0   # weight-tied draft
    assert s["serving/tokens_per_step"] == pytest.approx(
        (n_acc + n_rows) / n_rows)


# -- sharded plane ----------------------------------------------------------

@pytest.mark.mesh
def test_sharded_speculative_parity(lm, good_draft):
    """Speculative serving on a 4-way slot-DP mesh and a DP2xTP2 mesh
    is token-identical to the unsharded speculative engine (draft
    weights replicated, draft carry rows sharded over data, verify
    lowered like the decode step)."""
    from bigdl_tpu.serving import SamplingParams, ServingEngine

    reqs = [([3, 7, 2], 8, SamplingParams(temperature=0.9, top_k=8,
                                          seed=5)),
            ([5], 6, None), ([9, 1, 4], 7, None), ([2, 2], 5, None)]

    def run(parallelism):
        eng = ServingEngine(lm, n_slots=4, parallelism=parallelism,
                            speculative=_spec(good_draft))
        rids = [eng.submit(p, max_new_tokens=n, sampling=sp)
                for p, n, sp in reqs]
        outs = eng.drain()
        assert eng.pool.free_slots == eng.pool.n_slots
        return [outs[r] for r in rids]

    base = run(None)
    for par in ({"data": 4}, {"data": 2, "model": 2}):
        got = run(par)
        for a, b in zip(base, got):
            np.testing.assert_array_equal(a, b, err_msg=str(par))


# -- bench registration smoke (tier-1, small/CPU) ---------------------------

def test_speculative_bench_smoke():
    """benchmarks/serving_bench.py --scenario speculative runs
    end-to-end on a tiny CPU config and pins the subsystem's hard
    claims: zero extra target-side compiles on the mixed trace,
    byte-identical greedy outputs, tokens-per-step > 1."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmarks"))
    try:
        import serving_bench
    finally:
        sys.path.pop(0)

    out = serving_bench.run_speculative(model="tiny", n_requests=8,
                                        gen_tokens=10, n_slots=4,
                                        draft_k=3)
    assert out["extra_target_compiles"] == 0, out
    assert out["greedy_outputs_match"] is True, out
    assert out["speculative"]["target_programs"] == 1
    assert out["draft_programs"] == 1
    assert out["tokens_per_step"] > 1.0, out
    assert 0.0 < out["accept_rate"] <= 1.0
