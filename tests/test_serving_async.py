"""Dispatch-ahead decode window (ServingEngine ``dispatch_ahead=W``):
the async-readiness ledger CASHED IN.  Byte-identity is the acceptance
bar everywhere — W in {0, 1, 2} must produce identical streams across
greedy + fixed-seed sampled traces, slot recycling, priority
preemption, chunked admission, the speculative plane (structurally
W=0), the disaggregated plane, and fault/stall replay mid-window —
with ZERO new compiles (the window re-dispatches the same program on
device handles) and the host_step/fence_wait accounting split intact.

The machine-checked half: the ASY306-310 census strips each window
invariant out of the REAL serving tree in turn (inline stale consume,
literal depth bound, in-window fence, clock-blind consumer) and each
mutation must yield exactly ONE finding of the right code, while the
unmutated tree scans clean — so the analyzer tier actually guards the
engine shape this suite exercises, not a fixture-only idiom.

Determinism discipline matches test_serving_faults: seeded fault
schedules, VirtualClock stalls (no sleeps), ``max_retries=None`` so
truncated error-finishes can't masquerade as passing streams.
"""

from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.asyncwin

REPO = Path(__file__).resolve().parent.parent
SERVING_DIR = REPO / "bigdl_tpu" / "serving"

WINDOW_CODES = ["ASY306", "ASY307", "ASY308", "ASY309", "ASY310"]


def _make_lm(V=29, hidden=32, heads=4, layers=2, max_len=48, seed=9):
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(seed)
    lm = TransformerLM(V, hidden_size=hidden, n_heads=heads,
                       n_layers=layers, max_len=max_len)
    lm._ensure_params()
    lm.evaluate()
    return lm


@pytest.fixture(scope="module")
def lm():
    return _make_lm()


def _trace():
    """Mixed acceptance trace: greedy rows, fixed-seed sampled rows
    (penalties included), and a 1-token prompt — 4 requests through 2
    slots, so rows recycle mid-flight (the readmission path)."""
    from bigdl_tpu.serving import SamplingParams

    return [
        ([3, 7, 2], 10, None),
        ([5, 1], 8, SamplingParams(temperature=0.9, top_k=8, seed=123)),
        ([9], 6, None),
        ([4, 4, 4, 4], 9, SamplingParams(temperature=1.1, seed=7,
                                         repetition_penalty=1.2,
                                         frequency_penalty=0.2)),
    ]


def _run(lm, n_slots=2, **kw):
    from bigdl_tpu.serving import ServingEngine

    eng = ServingEngine(lm, n_slots=n_slots, **kw)
    rids = [eng.submit(p, max_new_tokens=n, sampling=sp)
            for p, n, sp in _trace()]
    outs = eng.drain()
    return eng, [list(outs[r]) for r in rids]


@pytest.fixture(scope="module")
def baseline(lm):
    """The W=0 streams — dispatch-then-fence within one step, the
    pre-window engine byte for byte."""
    _, outs = _run(lm)
    return outs


# -- byte-identity across window depths (THE acceptance contract) ----------

@pytest.mark.parametrize("W", [1, 2])
def test_window_byte_identity(W, lm, baseline):
    """W in-flight dispatches chained on device token handles: every
    finished stream — greedy AND fixed-seed sampled, slots recycling
    across 4 requests / 2 slots — equals the W=0 run byte for byte,
    and the window drains to empty with the pool healed."""
    eng, outs = _run(lm, dispatch_ahead=W)
    assert outs == baseline
    assert not eng._window
    assert eng.pool.free_slots == eng.pool.n_slots


def test_window_zero_is_the_default_and_validated(lm, baseline):
    from bigdl_tpu.serving import ServingEngine

    eng, outs = _run(lm, dispatch_ahead=0)
    assert outs == baseline
    assert eng.dispatch_ahead == 0
    with pytest.raises(ValueError, match="dispatch_ahead"):
        ServingEngine(lm, n_slots=2, dispatch_ahead=-1)


def test_window_zero_new_compiles(lm):
    """The window replays the SAME compiled decode program on device
    handles — a W=2 drain after a W=0 drain adds zero programs."""
    from tests.compile_guards import compile_count

    eng0, _ = _run(lm, dispatch_ahead=0)
    n0 = compile_count(eng0._step_fn)
    eng2, _ = _run(lm, dispatch_ahead=2)
    assert compile_count(eng2._step_fn) == n0


def test_window_preemption_byte_identity(lm, baseline):
    """Priority preemption mid-window: eviction breaks the window's
    row snapshot, the open-check drains it, and the preempted +
    readmitted streams still match the fault-free W=0 run."""
    from bigdl_tpu.serving import ServingEngine

    trace = _trace()
    eng = ServingEngine(lm, n_slots=2, policy="priority",
                        dispatch_ahead=2)
    low = [eng.submit(p, max_new_tokens=n, sampling=sp)
           for p, n, sp in trace[:2]]
    for _ in range(3):
        eng.step()
    hi = [eng.submit(p, max_new_tokens=n, sampling=sp, priority=5)
          for p, n, sp in trace[2:]]
    drained = eng.drain()
    assert [list(drained[r]) for r in low + hi] == baseline
    assert eng.metrics.summary()["serving/preempted"] >= 1


def test_window_chunked_admission_byte_identity(lm):
    """Chunked-prefill admission under the window: staggered submits
    land mid-flight (window drains on each admission), and W=2 equals
    the W=0 chunked run token for token."""
    from bigdl_tpu.serving import ServingEngine

    def run(W):
        eng = ServingEngine(lm, n_slots=2, admission="chunked",
                            chunk_budget=5, dispatch_ahead=W)
        ids = [eng.submit(p, max_new_tokens=n, sampling=sp)
               for p, n, sp in _trace()[:2]]
        eng.step(); eng.step()
        ids += [eng.submit(p, max_new_tokens=n, sampling=sp)
                for p, n, sp in _trace()[2:]]
        outs = eng.drain()
        assert eng.pool.free_slots == eng.pool.n_slots
        return [list(outs[r]) for r in ids]

    assert run(2) == run(0)


def test_window_speculative_plane_byte_identity(lm, baseline):
    """The speculative plane is structurally W=0 (draft budgets are
    host decisions from the previous verify readback) — the knob must
    be inert there, not harmful."""
    from bigdl_tpu.serving import ServingEngine, SpeculativeConfig

    draft = _make_lm(seed=31)
    eng = ServingEngine(lm, n_slots=2,
                        speculative=SpeculativeConfig(draft, k=3),
                        dispatch_ahead=2)
    rids = [eng.submit(p, max_new_tokens=n, sampling=sp)
            for p, n, sp in _trace()]
    outs = eng.drain()
    assert [list(outs[r]) for r in rids] == baseline
    assert not eng._window


@pytest.mark.disagg
def test_window_disagg_byte_identity(lm, baseline):
    """The disaggregated plane threads dispatch_ahead to every decode
    worker; handoffs and cross-pool routing under the window stay
    byte-identical to the monolithic W=0 run."""
    from bigdl_tpu.serving import DisaggregatedEngine

    d = DisaggregatedEngine(lm, prefill_slots=4, decode_slots=2,
                            decode_pools=2, dispatch_ahead=2)
    rids = [d.submit(p, max_new_tokens=n, sampling=sp)
            for p, n, sp in _trace()]
    outs = d.drain()
    assert [list(outs[r]) for r in rids] == baseline
    for w in d.decoders:
        assert w.engine.dispatch_ahead == 2
        assert not w.engine._window


# -- faults mid-window ------------------------------------------------------

@pytest.mark.faults
@pytest.mark.parametrize("seed", [1, 3])
def test_faults_mid_window_byte_identity(seed, lm, baseline):
    """Dispatch failures and garbage readbacks with W=2 in flight: a
    failed dispatch flushes the (healthy) window first, an unhealthy
    consumed entry discards every newer entry chained through the
    poisoned carry — and replay restores the exact streams."""
    from bigdl_tpu.serving import FaultInjector, WatchdogConfig

    eng, outs = _run(lm, dispatch_ahead=2,
                     watchdog=WatchdogConfig(max_retries=None),
                     faults=FaultInjector(seed=seed, p_fail=0.25,
                                          p_garbage=0.15))
    assert eng._faults.total > 0
    assert outs == baseline
    assert eng.metrics.summary()["serving/recovered_rows"] > 0
    assert eng.pool.free_slots == eng.pool.n_slots


@pytest.mark.faults
def test_stall_watchdog_fires_through_deferred_fence(lm, baseline):
    """A stalled in-flight dispatch (VirtualClock advance, no sleeps)
    surfaces at the DELAYED consumer: elapsed spans dispatch →
    readback landed, so step_timeout_s still trips with the fence a
    full window behind the dispatch, and replay restores the exact
    streams."""
    from bigdl_tpu.serving import (
        FaultInjector, VirtualClock, WatchdogConfig,
    )

    clk = VirtualClock()
    eng, outs = _run(
        lm, dispatch_ahead=2, clock=clk,
        watchdog=WatchdogConfig(step_timeout_s=5.0, max_retries=None),
        faults=FaultInjector(seed=6, p_stall=0.35, stall_s=30.0,
                             clock=clk))
    assert eng._faults.counts["stall"] > 0
    assert outs == baseline


# -- the accounting split under the window ----------------------------------

def test_host_split_pairing_survives_window(lm):
    """The host_step/decode_step/fence_wait series stay paired one for
    one at W=2 (flush steps pad host_step with zero-residue samples),
    and the device phases are the BLOCKED phases: fence_wait counts
    once per consumed entry while decode_step — which OVERLAPS host
    work under a window — no longer feeds device_seconds."""
    from bigdl_tpu.serving.metrics import ServingMetrics

    assert "fence_wait" in ServingMetrics.DEVICE_PHASES
    assert "decode_step" not in ServingMetrics.DEVICE_PHASES

    eng, _ = _run(lm, dispatch_ahead=2)
    m = eng.metrics.metrics
    _, n_host = m.get("serving/host_step_s")
    _, n_dec = m.get("serving/decode_step_s")
    _, n_fence = m.get("serving/fence_wait_s")
    assert n_host == n_dec == n_fence >= 4
    assert eng.metrics.device_seconds >= 0.0
    s = eng.metrics.summary()
    assert s["serving/host_step_p50_s"] <= s["serving/host_step_p99_s"]


# -- the ASY306-310 census over the REAL engine ------------------------------

def _serving_tree(tmp_path):
    dst = tmp_path / "bigdl_tpu" / "serving"
    dst.mkdir(parents=True)
    for f in SERVING_DIR.glob("*.py"):
        (dst / f.name).write_text(f.read_text())
    return dst


def _scan(tmp_path):
    from bigdl_tpu.analysis import analyze_paths

    return analyze_paths([str(tmp_path)], select=WINDOW_CODES)


def _mutate(tree, needle, repl):
    eng = tree / "engine.py"
    src = eng.read_text()
    assert src.count(needle) == 1, f"census anchor drifted: {needle!r}"
    eng.write_text(src.replace(needle, repl))
    return src


def test_window_census_unmutated_engine_is_clean(tmp_path):
    tree = _serving_tree(tmp_path)
    assert tree.is_dir()
    clean = _scan(tmp_path)
    assert clean == [], [f.format() for f in clean]


def test_window_census_exactly_one_delayed_site(capsys, monkeypatch):
    """The sync-point inventory proves exactly ONE declared
    delayed-consumer site in the whole serving plane: the decode fence
    in ServingEngine._consume_window, depth-bound by dispatch_ahead;
    every other declared fence is an inline consumer."""
    import json

    from bigdl_tpu.analysis import main

    monkeypatch.chdir(REPO)
    rc = main(["bigdl_tpu/serving", "--report", "sync-points",
               "--format", "json"])
    rep = json.loads(capsys.readouterr().out)
    assert rc == 0
    delayed = [e for e in rep["entries"]
               if e.get("window", "").startswith("delayed")]
    assert len(delayed) == 1
    e = delayed[0]
    assert e["kind"] == "fence:decode"
    assert e["function"].endswith("ServingEngine._consume_window")
    assert "dispatch_ahead" in e["window"]
    inline = [e for e in rep["entries"] if e.get("window") == "inline"]
    assert len(inline) == len(rep["entries"]) - 1


def test_window_census_stale_consumer_detected(tmp_path):
    """Inline-consume-and-redispatch (the re-serializing shape the
    window exists to forbid) -> exactly one ASY306."""
    tree = _serving_tree(tmp_path)
    _mutate(
        tree,
        "                self._advance_constraint(slot, req)\n"
        "        return True\n",
        "                self._advance_constraint(slot, req)\n"
        "        self._dispatch(\"decode\", self._step_fn, self.params,\n"
        "                       jnp.asarray(nxt), entry.active_dev,\n"
        "                       self.pool.carry, self._knobs_device)\n"
        "        return True\n")
    found = _scan(tmp_path)
    assert [f.code for f in found] == ["ASY306"], (
        [f.format() for f in found])
    assert found[0].path.endswith("engine.py")


def test_window_census_literal_depth_detected(tmp_path):
    """The consume loop bound by a literal instead of the declared
    dispatch_ahead knob -> exactly one ASY308."""
    tree = _serving_tree(tmp_path)
    _mutate(
        tree,
        "        while len(self._window) > self.dispatch_ahead:\n"
        "            if not self._consume_window(emitted):\n"
        "                break\n",
        "        while len(self._window) > 2:\n"
        "            if not self._consume_window(emitted):\n"
        "                break\n")
    found = _scan(tmp_path)
    assert [f.code for f in found] == ["ASY308"], (
        [f.format() for f in found])
    assert found[0].path.endswith("engine.py")


def test_window_census_inwindow_fence_detected(tmp_path):
    """An eager readback inserted between dispatch and append (inside
    the owning unit) re-serializes the window -> exactly one ASY309."""
    tree = _serving_tree(tmp_path)
    _mutate(
        tree,
        "        self.pool.carry = carry\n",
        "        self.pool.carry = carry\n"
        "        nxt0, lps0 = fence(\"verify\", tok, chosen)\n")
    found = _scan(tmp_path)
    assert [f.code for f in found] == ["ASY309"], (
        [f.format() for f in found])
    assert found[0].path.endswith("engine.py")


def test_window_census_clock_blind_consumer_detected(tmp_path):
    """Stripping the consumer's clock bracket (constants instead of
    engine-clock reads) blinds the timers AND the watchdog -> exactly
    one ASY310 at the deferred fence."""
    tree = _serving_tree(tmp_path)
    _mutate(
        tree,
        "        entry = self._window.popleft()\n"
        "        t_f = self._clock()\n",
        "        entry = self._window.popleft()\n"
        "        t_f = 0.0\n")
    _mutate(
        tree,
        "        nxt, lps = fence(\"decode\", entry.tok, entry.chosen)\n"
        "        now = self._clock()\n",
        "        nxt, lps = fence(\"decode\", entry.tok, entry.chosen)\n"
        "        now = 0.0\n")
    found = _scan(tmp_path)
    assert [f.code for f in found] == ["ASY310"], (
        [f.format() for f in found])
    assert found[0].path.endswith("engine.py")
